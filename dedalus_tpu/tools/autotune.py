"""
Empirical per-backend plan autotuner: measure-once, cache-forever
fast-path selection (ROADMAP item 2; TurboFNO and the M2L-operators
paper in PAPERS.md are the precedents — fused-kernel and operator-form
wins are architecture-specific, so the right composition is *selected by
measurement per architecture*, not hard-coded).

The config exposes a genuine tuning space — `SOLVE_COMPOSITION` x
`SOLVE_DTYPE` x `REFINE_SWEEPS` x `SPIKE_CHUNKS` (plus the PALLAS
substitution kernel and the `FUSED_TRANSFORMS`/`TRANSPOSE_CHUNKS`
auto picks) — whose optimum is backend- and shape-dependent: the PR-15
CPU sweep measured sequential/f32+2-sweep at 1.166x while ascan ran
0.40x (a depth play priced for accelerators). This module replaces the
hand-coded `auto` heuristics with empirical selection:

  * at first solver build on a (backend, device_kind, problem-shape
    signature), `consult` microbenches the candidate plan cells at the
    OPS level — candidate BandedOps built over the solver's own
    assembled matrices, timed on repeated factor+solve probes with an
    accuracy guard against the sequential/native reference, so an
    inaccurate cell can never win;
  * the decision persists in the content-addressed assembly cache as a
    `tuning` payload (validate-on-install + corrupt-entry quarantine,
    like every other payload kind), keyed by the shape signature — the
    cache is cross-process, so one replica's tuning warms the whole
    serving fleet;
  * warm builds load the decision and perform ZERO microbench probes
    (`probe_count()` is the machine-checked witness, mirroring the
    PR-12 lazy-composite drive);
  * the chosen plan and its measured evidence ride
    `Solver.plan_provenance()` (`plan_source: tuned|config|default`),
    so every results.jsonl row names how its plan was chosen.

`python -m dedalus_tpu tune` runs the OFFLINE harness instead: the
per-cell sweep machinery extracted from benchmarks/fusion.py
`run_solve_sweep` (`measure_build`: warmup trajectory, scanned-block
medians, state-error + residual guards), measuring real end-to-end
steps/s per cell and warming the same cache.

Config discipline (DTL008): config is read ONLY in `resolve_autotune`
and the cell-pinning helpers, at solver-build/CLI time — never on the
step path, and the consulted decision is resolved ONCE per build before
`assembly_cache.solver_key` seals the plan into the cache/pool keys.
User-pinned knobs always win: any non-auto `SOLVE_COMPOSITION`/
`SOLVE_DTYPE`/`REFINE_SWEEPS`/`SPIKE_CHUNKS` disables the tuned path
for that build (`plan_source: config`).
"""

import hashlib
import logging
import time

import numpy as np

from .config import config

logger = logging.getLogger(__name__)

__all__ = ["AutotunePlan", "Decision", "resolve_autotune", "consult",
           "solver_signature", "candidate_cells", "measure_build",
           "probe_solve_residual", "set_solve_config", "pick_winner",
           "tune_offline", "run_tune", "store_decision", "load_decision",
           "seed_decision", "ops_decision", "probe_count", "clear_memo",
           "MODES", "ACCURACY_BAR"]

MODES = ("off", "cached", "force")

TUNING_VERSION = 1

# f64-class accuracy bar for a candidate cell vs the sequential/native
# reference (the PR-15 ladder bar): a fast-but-wrong cell can never win.
# Scaled up for low-precision native dtypes (f32 problems measure their
# candidates against an f32 reference).
ACCURACY_BAR = 1e-10

# backends where the Pallas substitution lowers natively; elsewhere the
# kernel only runs in interpret mode (a tested emulation, not a
# candidate worth a tuning budget) and the cell records as skipped
_PALLAS_BACKENDS = ("tpu", "axon")

# in-process decision memo: signature -> Decision (cross-process
# persistence rides the assembly cache)
_MEMO = {}

# coarse ops-level registry: (ops_kind, system_size) -> Decision, so
# bare BandedOps/DenseOps constructions (no solver threading a plan)
# resolve the SAME plan a tuned solver build picked for that shape
_OPS_DECISIONS = {}

# microbench probe counter: incremented once per measured cell, never on
# a warm (cached-decision) build — tests assert exact zeros against it
_PROBES = [0]

# reentrancy guard: candidate probes build ops/solvers themselves; a
# probe-in-progress must never consult the tuner again
_TUNING = [False]


def probe_count():
    """Total microbench probes performed by this process (one per
    measured candidate cell). A warm build must not move this."""
    return _PROBES[0]


def _count_probe():
    _PROBES[0] += 1


def clear_memo():
    """Drop the in-process decision memo + ops registry (tests)."""
    _MEMO.clear()
    _OPS_DECISIONS.clear()


# ------------------------------------------------------------- resolution

class AutotunePlan:
    """Resolved [autotune] budget knobs (immutable per build)."""

    __slots__ = ("mode", "tune_steps", "budget_sec")

    def __init__(self, mode="off", tune_steps=12, budget_sec=120.0):
        self.mode = mode
        self.tune_steps = int(tune_steps)
        self.budget_sec = float(budget_sec)

    def __repr__(self):
        return (f"AutotunePlan({self.mode}, steps={self.tune_steps}, "
                f"budget={self.budget_sec}s)")


def resolve_autotune():
    """Resolve the [autotune] section. Called once per solver build (and
    per tune CLI run); unknown values raise ValueError AT BUILD — the
    modes gate real measurement budgets and must not silently degrade."""
    section = config["autotune"] if config.has_section("autotune") else {}
    raw = (section.get("MODE", "off") or "off").strip().lower()
    if raw not in MODES:
        raise ValueError(
            f"[autotune] MODE = {raw!r} is not a recognized value "
            f"({'/'.join(MODES)})")
    mode = raw
    raw_steps = (section.get("TUNE_STEPS", "12") or "12").strip().lower()
    try:
        tune_steps = int(raw_steps)
    except ValueError:
        raise ValueError(
            f"[autotune] TUNE_STEPS = {raw_steps!r} is not a recognized "
            "value (an integer >= 1)")
    if tune_steps < 1:
        raise ValueError(
            f"[autotune] TUNE_STEPS = {tune_steps} must be >= 1")
    raw_budget = (section.get("TUNE_BUDGET_SEC", "120") or "120").strip()
    try:
        budget = float(raw_budget)
    except ValueError:
        raise ValueError(
            f"[autotune] TUNE_BUDGET_SEC = {raw_budget!r} is not a "
            "recognized value (a positive number of seconds)")
    if budget <= 0:
        raise ValueError(
            f"[autotune] TUNE_BUDGET_SEC = {budget} must be > 0")
    return AutotunePlan(mode=mode, tune_steps=tune_steps, budget_sec=budget)


# -------------------------------------------------------------- decisions

class Decision:
    """One persisted tuning decision: the chosen plan cell plus the
    measured evidence it was selected on."""

    __slots__ = ("signature", "cell", "evidence", "backend", "device_kind",
                 "evidence_kind", "wall_sec", "margin", "mode", "created",
                 "cache_verdict")

    def __init__(self, signature, cell, evidence=(), backend="?",
                 device_kind="?", evidence_kind="ops_probe", wall_sec=0.0,
                 margin=None, mode="cached", created=None,
                 cache_verdict="fresh"):
        self.signature = signature
        self.cell = dict(cell)
        self.evidence = [dict(c) for c in evidence]
        self.backend = backend
        self.device_kind = device_kind
        self.evidence_kind = evidence_kind
        self.wall_sec = float(wall_sec)
        self.margin = margin
        self.mode = mode
        self.created = float(created) if created is not None \
            else time.time()
        self.cache_verdict = cache_verdict

    def to_record(self):
        return {"tuning_version": TUNING_VERSION,
                "signature": self.signature,
                "cell": dict(self.cell),
                "cells": [dict(c) for c in self.evidence],
                "backend": self.backend,
                "device_kind": self.device_kind,
                "evidence_kind": self.evidence_kind,
                "wall_sec": round(self.wall_sec, 3),
                "margin": self.margin,
                "mode": self.mode,
                "created": self.created}

    @classmethod
    def from_record(cls, record, signature=None):
        """Validated Decision from a cache record, or None on any
        structural/semantic drift (the caller quarantines)."""
        from ..libraries.solvecomp import COMPOSITIONS, SOLVE_DTYPES
        if not isinstance(record, dict):
            return None
        if record.get("tuning_version") != TUNING_VERSION:
            return None
        sig = record.get("signature")
        if not isinstance(sig, str) or \
                (signature is not None and sig != signature):
            return None
        cell = record.get("cell")
        if not isinstance(cell, dict):
            return None
        if cell.get("composition") not in COMPOSITIONS:
            return None
        if cell.get("solve_dtype") not in SOLVE_DTYPES:
            return None
        sweeps = cell.get("refine_sweeps")
        if sweeps is not None and (not isinstance(sweeps, int)
                                   or isinstance(sweeps, bool)
                                   or sweeps < 0):
            return None
        chunks = cell.get("spike_chunks", 0)
        if not isinstance(chunks, int) or isinstance(chunks, bool) \
                or chunks < 0:
            return None
        if not isinstance(cell.get("pallas", False), bool):
            return None
        tchunks = cell.get("transpose_chunks")
        if tchunks is not None and (not isinstance(tchunks, int)
                                    or isinstance(tchunks, bool)
                                    or tchunks < 1):
            return None
        ftrans = cell.get("fused_transforms")
        if ftrans is not None and not isinstance(ftrans, bool):
            return None
        cells = record.get("cells")
        if not isinstance(cells, list):
            return None
        return cls(sig, cell, evidence=[c for c in cells
                                        if isinstance(c, dict)],
                   backend=str(record.get("backend", "?")),
                   device_kind=str(record.get("device_kind", "?")),
                   evidence_kind=str(record.get("evidence_kind", "?")),
                   wall_sec=record.get("wall_sec", 0.0) or 0.0,
                   margin=record.get("margin"),
                   mode=str(record.get("mode", "cached")),
                   created=record.get("created"))

    def provenance(self):
        """The `tuning` block of plan_provenance(): chosen cell plus the
        evidence summary, compact enough for every telemetry row."""
        return {"signature": str(self.signature)[:16],
                "mode": self.mode,
                "evidence_kind": self.evidence_kind,
                "wall_sec": round(self.wall_sec, 3),
                "cache": self.cache_verdict,
                "margin": self.margin,
                "chosen": dict(self.cell),
                "cells": [dict(c) for c in self.evidence]}

    def __repr__(self):
        c = self.cell
        tag = f"{c.get('composition')}/{c.get('solve_dtype')}"
        if c.get("pallas"):
            tag += "+pallas"
        return f"Decision({tag}, sig {str(self.signature)[:8]})"


def cell_label(cell):
    """Human-readable tag for one candidate/chosen cell."""
    tag = f"{cell.get('composition', '?')}/{cell.get('solve_dtype', '?')}"
    if cell.get("pallas"):
        tag += "+pallas"
    sweeps = cell.get("refine_sweeps")
    if sweeps:
        tag += f"+{sweeps}sw"
    return tag


# ------------------------------------------------------------- signatures

def _device_kind():
    try:
        import jax
        return getattr(jax.devices()[0], "device_kind", "?") or "?"
    except Exception:
        return "?"


def solver_signature(solver):
    """Content key of a tuning decision: everything shape- and
    architecture-relevant that is known BEFORE plan resolution (the
    decision must be consultable before `solver_key` seals the plan).
    None when the solver cannot be fingerprinted."""
    try:
        import jax
        G, S = solver.pencil_shape
        spec = solver.matsolver
        spec = spec if isinstance(spec, str) else getattr(
            spec, "__name__", type(spec).__name__)
        h = hashlib.blake2b(digest_size=20)
        for part in ("autotune-v%d" % TUNING_VERSION,
                     jax.default_backend(), _device_kind(),
                     len(jax.devices()), type(solver).__name__,
                     str(spec).lower(), int(G), int(S),
                     np.dtype(solver.pencil_dtype).str):
            h.update(repr(part).encode())
            h.update(b"\x00")
        return h.hexdigest()
    except Exception as exc:
        logger.debug(f"autotune: unfingerprintable solver ({exc!r})")
        return None


# ------------------------------------------------------- cache round-trip

def store_decision(cache, decision):
    """Persist one decision as a `tuning` assembly-cache payload."""
    from . import assembly_cache
    return assembly_cache.store_tuning(cache, decision.signature,
                                       decision.to_record())


def load_decision(cache, signature):
    """Load + validate a persisted decision; any corruption or semantic
    drift quarantines the entry and reports a miss (fresh tune next)."""
    from . import assembly_cache
    record = assembly_cache.load_tuning(cache, signature)
    if record is None:
        return None
    decision = Decision.from_record(record, signature=signature)
    if decision is None:
        logger.warning(
            f"autotune: tuning record {str(signature)[:12]} failed "
            "validation; quarantined, will re-tune")
        cache.discard(signature)
        return None
    return decision


def seed_decision(signature, cell, evidence=(), cache=None, mode="cached",
                  **kw):
    """Install a ready-made decision (tests, progcheck census, warm-cache
    priming): memo + ops registry, and optionally the persistent cache."""
    decision = Decision(signature, cell, evidence=evidence, mode=mode, **kw)
    _MEMO[signature] = decision
    if cache is not None:
        store_decision(cache, decision)
    return decision


def _register_ops(decision, sizes):
    """Expose a solver-level decision to bare-ops constructions of the
    same system size (libraries/pencilops.py fallback paths)."""
    for kind in ("banded", "dense"):
        for n in sizes:
            _OPS_DECISIONS[(kind, int(n))] = decision


def ops_decision(kind, n):
    """The registered decision for a bare-ops construction of `n`-sized
    systems, or None. In-process only: bare ops carry no problem
    fingerprint, so the registry is seeded by tuned SOLVER builds."""
    try:
        return _OPS_DECISIONS.get((kind, int(n)))
    except (TypeError, ValueError):
        return None


# ------------------------------------------------------------- candidates

def candidate_cells(backend=None):
    """The tuning grid: the PR-15 sweep cells (composition x ladder
    dtype) plus the Pallas substitution as a first-class candidate on
    backends that lower it natively. The sequential/native reference is
    ALWAYS first — every other cell's accuracy is measured against it."""
    if backend is None:
        import jax
        backend = jax.default_backend()
    cells = [
        {"composition": "sequential", "solve_dtype": "native",
         "pallas": False, "reference": True},
        {"composition": "sequential", "solve_dtype": "f32", "pallas": False},
        {"composition": "ascan", "solve_dtype": "native", "pallas": False},
        {"composition": "ascan", "solve_dtype": "f32", "pallas": False},
        {"composition": "spike", "solve_dtype": "native", "pallas": False},
        {"composition": "spike", "solve_dtype": "f32", "pallas": False},
    ]
    pallas = {"composition": "sequential", "solve_dtype": "native",
              "pallas": True}
    if backend not in _PALLAS_BACKENDS:
        pallas["skipped"] = (f"backend {backend!r} cannot lower the "
                             "pallas substitution natively "
                             "(interpret-only)")
    cells.append(pallas)
    return cells


def _accuracy_bar(native_dtype):
    """The per-problem accuracy bar: f64-class for f64 problems, scaled
    to the native precision otherwise (an f32 problem's reference is
    itself f32)."""
    real = np.finfo(np.dtype(native_dtype)).eps \
        if np.dtype(native_dtype).kind in "fc" else np.finfo(float).eps
    return max(ACCURACY_BAR, 1e4 * float(real))


def pick_winner(evidence, bar, rate_key):
    """(winner_cell, margin) from measured evidence: the fastest finite
    cell within the accuracy bar — an inaccurate cell can NEVER win, so
    the reference (rel_err 0) is always eligible. Margin is the
    winner's rate over the runner-up's (None with < 2 eligible)."""
    eligible = []
    for cell in evidence:
        if cell.get("skipped") or cell.get("error"):
            continue
        rate = cell.get(rate_key)
        if not isinstance(rate, (int, float)) or rate <= 0:
            continue
        if cell.get("finite") is False:
            continue
        err = cell.get("rel_err", cell.get("state_rel_err"))
        if err is None or not np.isfinite(err) or err > bar:
            continue
        eligible.append(cell)
    if not eligible:
        return None, None
    ordered = sorted(eligible, key=lambda c: c[rate_key], reverse=True)
    winner = ordered[0]
    margin = None
    if len(ordered) > 1 and ordered[1][rate_key] > 0:
        margin = round(winner[rate_key] / ordered[1][rate_key], 3)
    return winner, margin


def _decision_cell(measured, resolved_sweeps=None, spike_chunks=0):
    """The persisted plan cell for a winning measured cell."""
    return {"composition": measured["composition"],
            "solve_dtype": "native" if measured["solve_dtype"]
            in ("native", "f64") else measured["solve_dtype"],
            "refine_sweeps": resolved_sweeps,
            "spike_chunks": int(spike_chunks),
            "pallas": bool(measured.get("pallas")),
            "fused_transforms": None,
            "transpose_chunks": None}


# ----------------------------------------------------------- the consult

def consult(solver, plan=None, cache=None):
    """The build-time entry point (core/solvers._build_pencil_system):
    the tuned decision for this solver's shape signature, or None when
    the tuner is off, the knobs are user-pinned (`plan_source: config`),
    the problem is out of scope, or tuning is already in progress.

    Warm path (memo/disk hit): ZERO microbench probes. Cold path with
    MODE=cached|force: a bounded in-build ops-level tune, persisted for
    every later process/replica."""
    if plan is None:
        plan = resolve_autotune()
    if plan.mode == "off" or _TUNING[0]:
        return None
    from ..libraries import solvecomp
    if solvecomp.solve_knobs_pinned():
        return None         # explicit config wins: plan_source "config"
    names = tuple(getattr(solver, "matrices", ()) or ())
    if not {"M", "L"}.issubset(set(names)):
        return None         # the tuning space targets the IVP step loop
    sig = solver_signature(solver)
    if sig is None:
        return None
    if plan.mode != "force":
        hit = _MEMO.get(sig)
        if hit is not None:
            hit.cache_verdict = "memo"
            return hit
        if cache is None:
            from . import assembly_cache
            cache = assembly_cache.resolve()
        if cache is not None:
            hit = load_decision(cache, sig)
            if hit is not None:
                hit.cache_verdict = "hit"
                _MEMO[sig] = hit
                _register_ops(hit, solver.pencil_shape[1:])
                logger.info(
                    f"autotune: cached decision {hit!r} "
                    f"(sig {sig[:12]})")
                return hit
    else:
        if cache is None:
            from . import assembly_cache
            cache = assembly_cache.resolve()
    decision = _tune_in_build(solver, plan, sig)
    if decision is None:
        return None
    _MEMO[sig] = decision
    _register_ops(decision, solver.pencil_shape[1:])
    if cache is not None and store_decision(cache, decision):
        decision.cache_verdict = "stored"
    return decision


def _will_go_banded(solver, names):
    """Mirror of the main build's banded-vs-dense choice (the in-build
    probe must measure the representation the build will actually
    compile)."""
    spec = solver.matsolver if isinstance(solver.matsolver, str) else ""
    forced = spec.lower() if spec.lower() in ("banded", "dense") else None
    if forced == "banded":
        return True
    if forced == "dense" or not (isinstance(solver.matsolver, str)
                                 and spec.lower() == "auto"):
        return False
    G, S = solver.pencil_shape
    dense_bytes = G * S * S * np.dtype(solver.pencil_dtype).itemsize
    cutoff = int(config["linear algebra"].get(
        "BANDED_CUTOFF_BYTES", str(1 << 30)))
    return dense_bytes > cutoff


def _tune_in_build(solver, plan, sig):
    """Cold in-build tune: assemble the solver's own matrices (the
    assembly output is plan-independent), run the banded structural
    analysis, and microbench candidate BandedOps cells on repeated
    factor+solve probes. Returns a Decision or None (out of scope /
    probe failure — the build then proceeds untuned)."""
    names = list(solver.matrices)
    try:
        if not _will_go_banded(solver, names):
            return None     # dense path: compositions are inert there
    except Exception:
        return None
    import jax
    t0 = time.perf_counter()
    _TUNING[0] = True
    try:
        solver._assemble_batched(names)
        G, S = solver.pencil_shape
        result = solver._try_banded(names, S)
        if result is not True:
            return None
        structure = solver.structure
        stores = solver._matrices
        evidence = _probe_ops_cells(
            structure, stores, np.dtype(solver.pencil_dtype), plan, t0)
    except Exception as exc:
        logger.warning(f"autotune: in-build tune failed ({exc!r}); "
                       "build proceeds untuned")
        return None
    finally:
        _TUNING[0] = False
    bar = _accuracy_bar(solver.pencil_dtype)
    winner, margin = pick_winner(evidence, bar, "solves_per_sec")
    if winner is None:
        return None
    from ..libraries.solvecomp import _AUTO_SWEEPS
    cell = _decision_cell(winner,
                          resolved_sweeps=winner.get("refine_sweeps"),
                          spike_chunks=0)
    if cell["refine_sweeps"] is None:
        cell["refine_sweeps"] = _AUTO_SWEEPS.get(cell["solve_dtype"])
    wall = time.perf_counter() - t0
    decision = Decision(sig, cell, evidence=evidence,
                        backend=jax.default_backend(),
                        device_kind=_device_kind(),
                        evidence_kind="ops_probe", wall_sec=wall,
                        margin=margin, mode=plan.mode)
    logger.info(f"autotune: tuned {decision!r} in {wall:.1f}s "
                f"(margin {margin}, sig {sig[:12]})")
    return decision


def _probe_ops_cells(structure, stores, dtype, plan, t0):
    """Measure every candidate cell at the ops level: candidate
    BandedOps over the already-assembled band stores, timed on repeated
    jitted solves against one factored a*M + b*L (matsolve is the
    measured ~91% of the step, so solves/s ranks compositions the way
    steps/s does), each compared against the sequential/native
    reference solution. Budget-bounded: cells past TUNE_BUDGET_SEC
    record as skipped rather than silently vanishing."""
    import jax
    backend = jax.default_backend()
    evidence = []
    ref = None
    for cell in candidate_cells(backend):
        entry = {k: cell[k] for k in ("composition", "solve_dtype",
                                      "pallas")}
        if cell.get("skipped"):
            entry["skipped"] = cell["skipped"]
            evidence.append(entry)
            continue
        if ref is not None and \
                time.perf_counter() - t0 > plan.budget_sec:
            entry["skipped"] = (f"tuning budget "
                                f"({plan.budget_sec}s) exhausted")
            evidence.append(entry)
            continue
        try:
            probe = _probe_ops_cell(structure, stores, dtype, cell,
                                    plan.tune_steps,
                                    ref["x"] if ref else None)
        except Exception as exc:
            entry["error"] = repr(exc)
            evidence.append(entry)
            continue
        entry.update({k: probe[k] for k in ("solves_per_sec", "rel_err",
                                            "finite", "refine_sweeps")})
        if cell.get("reference"):
            entry["reference"] = True
            ref = probe
        evidence.append(entry)
    return evidence


def _probe_ops_cell(structure, stores, dtype, cell, tune_steps, ref_x):
    """One cell's microbench: build candidate ops, factor a*M + b*L
    once, then time `tune_steps` jitted solves (median of 3 passes).
    Returns solves/s + accuracy vs the reference solution. Counts one
    probe."""
    import jax
    import jax.numpy as jnp
    from ..core.fusedstep import FusionPlan
    from ..libraries import pencilops
    from ..libraries.solvecomp import SolvePlan, _AUTO_SWEEPS
    _count_probe()
    sdtype = "native" if cell["solve_dtype"] in ("native", "f64") \
        else cell["solve_dtype"]
    sweeps = _AUTO_SWEEPS.get(sdtype)
    splan = SolvePlan(composition=cell["composition"], spike_chunks=0,
                      dtype=sdtype, sweeps=sweeps)
    fplan = FusionPlan(solve=True, matvec=True, transforms=False,
                       donate=False, pallas=bool(cell.get("pallas")))
    ops = pencilops.BandedOps(structure, fusion=fplan, solve_plan=splan)
    M = ops.to_device(stores["M"], dtype)
    L = ops.to_device(stores["L"], dtype)
    G = int(np.asarray(stores["M"]["bands"]).shape[0])
    n = int(structure.S)
    rng = np.random.default_rng(8)
    if np.dtype(dtype).kind == "c":
        rhs_host = (rng.standard_normal((G, n))
                    + 1j * rng.standard_normal((G, n)))
    else:
        rhs_host = rng.standard_normal((G, n))
    rhs = jnp.asarray(rhs_host, dtype=dtype)
    aux = ops.factor_lincomb(1.0, M, 1e-3, L)

    def _solve_probe(a, r):
        return ops.solve(a, r, mats=(M, L))

    # one-shot probe program: built once per measured cell, timed, then
    # dropped — there is no retrace-per-call hazard to hoist away
    solve_jit = jax.jit(_solve_probe)  # dedalus-lint: disable=DTL003 (one-shot tuning probe)
    out = solve_jit(aux, rhs)
    x = np.asarray(out)             # deliberate host sync + accuracy copy
    times = []
    for _ in range(3):
        start = time.perf_counter()
        for _ in range(max(1, int(tune_steps))):
            out = solve_jit(aux, rhs)
        tail = np.asarray(out)      # deliberate host sync
        times.append(time.perf_counter() - start)
    del tail
    rate = max(1, int(tune_steps)) / float(np.median(times))
    finite = bool(np.isfinite(x).all())
    if ref_x is None:
        rel = 0.0
    else:
        scale = float(np.max(np.abs(ref_x))) or 1.0
        rel = float(np.max(np.abs(x - ref_x)) / scale)
    return {"solves_per_sec": round(float(rate), 3),
            "rel_err": rel, "finite": finite,
            "refine_sweeps": sweeps, "x": x}


# -------------------------------------------- offline (step-level) harness

def set_solve_config(composition="auto", solve_dtype="auto", sweeps="auto",
                     spike_chunks="auto", pallas=None):
    """Pin the solve composition + precision ladder for the next build
    (the [fusion]/[precision] knobs of the solve-composition sweep;
    extracted from benchmarks/fusion.py so the benchmark and the tuner
    pin cells identically). `pallas=None` leaves the flag untouched."""
    for section in ("fusion", "precision"):
        if not config.has_section(section):
            config.add_section(section)
    config["fusion"]["SOLVE_COMPOSITION"] = composition
    config["fusion"]["SPIKE_CHUNKS"] = spike_chunks
    config["precision"]["SOLVE_DTYPE"] = solve_dtype
    config["precision"]["REFINE_SWEEPS"] = sweeps
    if pallas is not None:
        config["fusion"]["PALLAS"] = pallas


class _cell_config:
    """Pin one candidate cell's config for a measured build, restored on
    exit. MODE is pinned off so the measured builds can never recurse
    into the tuner."""

    _KEYS = (("fusion", "SOLVE_COMPOSITION"), ("fusion", "SPIKE_CHUNKS"),
             ("fusion", "PALLAS"), ("fusion", "FUSED_SOLVE"),
             ("precision", "SOLVE_DTYPE"), ("precision", "REFINE_SWEEPS"),
             ("autotune", "MODE"))

    def __init__(self, cell):
        self.cell = cell

    def __enter__(self):
        for section in {s for s, _ in self._KEYS}:
            if not config.has_section(section):
                config.add_section(section)
        self.saved = {(s, k): config[s].get(k) for s, k in self._KEYS}
        cell = self.cell
        sdtype = cell.get("solve_dtype", "native")
        set_solve_config(
            composition=cell.get("composition", "auto"),
            solve_dtype="auto" if sdtype in ("native", "f64") else sdtype,
            sweeps="auto", spike_chunks="auto",
            pallas="on" if cell.get("pallas") else "off")
        config["fusion"]["FUSED_SOLVE"] = "on"
        config["autotune"]["MODE"] = "off"
        return self

    def __exit__(self, *exc):
        for (s, k), val in self.saved.items():
            if val is None:
                config[s].pop(k, None)
            else:
                config[s][k] = val


def measure_build(build, n_steps, block, blocks, solver_out=None):
    """Build, advance `n_steps` (trajectory warmup; single steps so only
    one scanned block size compiles below), then measure median steps/s
    over `blocks` scanned step_many blocks — the per-cell sweep
    machinery extracted from benchmarks/fusion.py run_solve_sweep.
    `solver_out` (a list) receives the live solver for post-measurement
    probes. Counts one microbench probe. Returns (result dict,
    post-warmup host state)."""
    _count_probe()
    solver, dt = build()
    if solver_out is not None:
        solver_out.append(solver)
    for _ in range(n_steps):
        solver.step(dt)
    x = solver.X
    state = np.asarray(x).copy()    # deliberate host sync + snapshot
    solver.step_many(block, dt)     # compile the block program
    x = solver.X
    np.asarray(x)                   # deliberate host sync
    rates = []
    for _ in range(blocks):
        start = time.perf_counter()
        solver.step_many(block, dt)
        x = solver.X
        tail = np.asarray(x)        # deliberate host sync (timed edge)
        rates.append(block / (time.perf_counter() - start))
    finite = bool(np.isfinite(tail).all())
    return {
        "steps_per_sec": round(float(np.median(rates)), 3),
        "steps_per_sec_iqr": round(float(np.percentile(rates, 75)
                                         - np.percentile(rates, 25)), 3),
        "finite": finite,
    }, state


def probe_solve_residual(solver):
    """Achieved relative residual of one probe solve against the live
    LHS factorization (the ladder accuracy record), or None."""
    import jax.numpy as jnp
    ts = getattr(solver, "timestepper", None)
    aux = getattr(ts, "_lhs_aux", None)
    if aux is None or not hasattr(solver.ops, "solve_report"):
        return None
    aux0 = aux[0] if isinstance(aux, list) else aux
    try:
        _, rel = solver.ops.solve_report(
            aux0, jnp.asarray(solver.X),
            mats=(solver.M_mat, solver.L_mat))
    except Exception:
        return None
    return None if rel is None else float(np.asarray(rel))


def tune_offline(build, plan=None, label="", n_steps=12, block=20,
                 blocks=5):
    """The offline (CLI / pre-tuning) harness: measure every candidate
    cell END TO END — real solver builds, real steps/s — under the
    state-error + residual guards, and return (Decision, evidence).
    Budget-bounded like the in-build probe; the decision's signature is
    taken from the reference build, so it warms exactly the builds
    `consult` will serve."""
    import jax
    if plan is None:
        plan = resolve_autotune()
    backend = jax.default_backend()
    t0 = time.perf_counter()
    evidence = []
    ref_state = None
    signature = None
    native_dtype = None
    for cell in candidate_cells(backend):
        entry = {k: cell[k] for k in ("composition", "solve_dtype",
                                      "pallas")}
        if cell.get("skipped"):
            entry["skipped"] = cell["skipped"]
            evidence.append(entry)
            continue
        if ref_state is not None and \
                time.perf_counter() - t0 > plan.budget_sec:
            entry["skipped"] = (f"tuning budget "
                                f"({plan.budget_sec}s) exhausted")
            evidence.append(entry)
            continue
        holder = []
        try:
            with _cell_config(cell):
                result, state = measure_build(
                    build, n_steps, block, blocks, solver_out=holder)
        except Exception as exc:
            entry["error"] = repr(exc)
            evidence.append(entry)
            continue
        solver = holder[0]
        splan = getattr(solver, "_solve_plan", None)
        entry.update(result)
        entry["refine_sweeps"] = None if splan is None else splan.sweeps
        entry["achieved_residual"] = probe_solve_residual(solver)
        if ref_state is None:
            entry["reference"] = True
            entry["rel_err"] = 0.0
            ref_state = state
            signature = solver_signature(solver)
            native_dtype = np.dtype(solver.pencil_dtype)
        else:
            scale = float(np.max(np.abs(ref_state))) or 1.0
            entry["rel_err"] = float(
                np.max(np.abs(state - ref_state)) / scale)
        evidence.append(entry)
    if signature is None:
        return None, evidence
    bar = _accuracy_bar(native_dtype)
    winner, margin = pick_winner(evidence, bar, "steps_per_sec")
    if winner is None:
        return None, evidence
    cell = _decision_cell(winner,
                          resolved_sweeps=winner.get("refine_sweeps"))
    decision = Decision(signature, cell, evidence=evidence,
                        backend=backend, device_kind=_device_kind(),
                        evidence_kind="step_sweep",
                        wall_sec=time.perf_counter() - t0,
                        margin=margin, mode=plan.mode)
    return decision, evidence


# ------------------------------------------------------------ the tune CLI

_PROBLEMS = ("rb256x64", "rb64x32", "diffusion64")


def _problem_build(name, dtype):
    from ..extras.bench_problems import (build_diffusion_solver,
                                         build_rb_solver)
    if name == "rb256x64":
        return lambda: (build_rb_solver(256, 64, dtype,
                                        matsolver="banded")[0], 0.01)
    if name == "rb64x32":
        return lambda: (build_rb_solver(64, 32, dtype,
                                        matsolver="banded")[0], 0.01)
    if name == "diffusion64":
        return lambda: (build_diffusion_solver(64, dtype), 1e-3)
    raise ValueError(f"unknown tune problem {name!r} "
                     f"(one of {', '.join(_PROBLEMS)})")


def run_tune(problem="rb256x64", force=False, quick=False, as_json=False,
             record=True, steps=None, budget=None, out=print):
    """`python -m dedalus_tpu tune`: pre-tune one benchmark problem
    offline, persist the decision (warming every later build/replica on
    this cache), and append a `kind: autotune` evidence row to
    benchmarks/results.jsonl. Returns a process exit code."""
    import json as json_mod
    import jax
    from . import assembly_cache
    try:
        plan = resolve_autotune()
    except ValueError as exc:
        out(f"tune: {exc}")
        return 2
    if steps is not None:
        plan.tune_steps = int(steps)
    if budget is not None:
        plan.budget_sec = float(budget)
    dtype = np.float64 if jax.default_backend() == "cpu" else np.float32
    try:
        build = _problem_build(problem, dtype)
    except ValueError as exc:
        out(f"tune: {exc}")
        return 2
    cache = assembly_cache.resolve()
    if not force and cache is not None:
        # a measured decision may already exist: probe it via one cheap
        # reference build signature
        pass
    n_steps, block, blocks = (4, 8, 2) if quick else (12, 20, 5)
    decision, evidence = tune_offline(build, plan=plan, label=problem,
                                      n_steps=n_steps, block=block,
                                      blocks=blocks)
    if decision is None:
        out(f"tune: {problem}: no accurate candidate cell survived "
            "(see cells below)")
        for cell in evidence:
            out(f"  {cell_label(cell)}: "
                f"{cell.get('skipped') or cell.get('error') or cell}")
        return 1
    decision.mode = "force" if force else plan.mode
    stored = False
    if cache is not None:
        stored = store_decision(cache, decision)
        decision.cache_verdict = "stored" if stored else "store-failed"
    else:
        decision.cache_verdict = "cache-disabled"
    _MEMO[decision.signature] = decision
    row = {
        "kind": "autotune",
        "config": problem,
        "backend": decision.backend,
        "device_kind": decision.device_kind,
        "signature": decision.signature,
        "evidence_kind": decision.evidence_kind,
        "mode": decision.mode,
        "forced": bool(force),
        "chosen": dict(decision.cell),
        "chosen_label": cell_label(decision.cell),
        "margin": decision.margin,
        "tuning_wall_sec": round(decision.wall_sec, 3),
        "cache": decision.cache_verdict,
        "cells": [dict(c) for c in evidence],
        "trajectory_steps": n_steps,
        "quick": bool(quick),
        "ts": round(time.time(), 1),
    }
    if record and not quick:
        try:
            from __graft_entry__ import _append_result
            _append_result(row)
        except Exception as exc:
            logger.warning(f"tune: could not record results row ({exc!r})")
    if as_json:
        out(json_mod.dumps(row, indent=2, default=str))
        return 0
    out(f"tune {problem} [{decision.backend}/{decision.device_kind}]: "
        f"chosen {cell_label(decision.cell)} "
        f"(margin {decision.margin or '?'}x over runner-up, "
        f"wall {decision.wall_sec:.1f}s, cache {decision.cache_verdict})")
    for cell in evidence:
        if cell.get("skipped"):
            out(f"  {cell_label(cell)}: skipped ({cell['skipped']})")
        elif cell.get("error"):
            out(f"  {cell_label(cell)}: ERROR {cell['error']}")
        else:
            tag = " (reference)" if cell.get("reference") else ""
            out(f"  {cell_label(cell)}: "
                f"{cell.get('steps_per_sec', '?')} steps/s, "
                f"err {cell.get('rel_err', '?'):.1e}{tag}")
    return 0
