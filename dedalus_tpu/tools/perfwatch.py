"""
perfwatch: the perf-trajectory regression sentinel.

benchmarks/results.jsonl accumulates one row per measurement, round
after round — steps/s headlines, serving throughput, `kind: ledger`
compiled-resource rows (tools/lint/progcheck.py), probe history. Nothing
watched those numbers over time: a silent 20% steps/s regression or a
doubling of compiled peak memory would ship undetected. This module
reads the FULL history, groups comparable measurements into series, and
flags the newest point when it moves outside the series' own noise band.

Series identity
---------------
A point joins a series only when everything that legitimately changes a
number matches: `(metric, identifier, backend, plan)` — the plan key is
a structural digest of the row's plan provenance (fusion flags, solve
composition/dtype, sweep/chunk counts; NOT the solver content key, which
re-keys on every assembly change). Rows without provenance are excluded
outright: no `ts`, an explicitly non-finite run (`finite: false`), or a
zero value never become evidence. Stale re-reports (rows carrying
`measured_ts`/`source`, bench's stale-headline guard) collapse to one
point per original measurement, stamped at the time it was MEASURED.

Noise bands
-----------
baseline = median(history), band = max(MAD_MULT x relative-MAD,
DRIFT_FLOOR). The floor defaults to 0.15 — the documented ±15% wall-
clock drift of the shared host (CHANGES.md, PR 16) must never
false-positive — and the MAD term widens the band further for series
that are intrinsically noisier (serving throughput). A series is
analyzed only once its history (excluding the newest point) has
MIN_HISTORY points; younger series report `insufficient-history` and
stay quiet. Direction matters: steps/s and requests/s regress DOWN;
memory, flops, bytes, HLO size, and scan depth regress UP.

Waivers
-------
benchmarks/perfwatch_waivers.json lists intentional trade-offs as
`{"series": <fnmatch pattern>, "reason": ...}` entries. A waived
regression is reported (counted, never hidden) but does not fail
`--check`. Plan changes generally should NOT need waivers: a plan
switch (including an autotune decision, tools/autotune.py) changes the
`plan_key` digest and therefore starts a NEW series — the PR-15 ascan
CPU waiver was retired on exactly that basis once `plan_source` landed
(the slow cell is a tuner-rejected candidate row, not a standing
regression against the sequential baseline).

Entry points: `python -m dedalus_tpu perfwatch [--check|--json]`,
`lint --perfwatch` (the standalone-CI tail), and `trend_lines()` (the
`report` CLI's trend table).
"""

import argparse
import fnmatch
import json
import pathlib
import sys

PACKAGE_DIR = pathlib.Path(__file__).resolve().parent.parent
DEFAULT_RESULTS = PACKAGE_DIR.parent / "benchmarks" / "results.jsonl"
DEFAULT_WAIVERS = PACKAGE_DIR.parent / "benchmarks" / "perfwatch_waivers.json"

# row kinds that are bookkeeping, not measurements (autotune rows carry
# per-cell microbench evidence, not trend-worthy throughput: a tuning
# probe's solves/s must never seed a regression baseline)
_NON_MEASUREMENT_KINDS = {"probe", "trace", "service_stats",
                          "router_stats", "health_postmortem",
                          "watchdog_postmortem", "autotune"}

# ledger fields watched for UPWARD drift (field -> metric name)
_LEDGER_METRICS = (("flops", "ledger_flops"),
                   ("bytes_accessed", "ledger_bytes"),
                   ("peak_bytes", "ledger_peak_bytes"),
                   ("hlo_instructions", "ledger_hlo_instructions"),
                   ("scan_max_length", "ledger_scan_depth"))

__all__ = ["load_rows", "extract_points", "build_series", "analyze_series",
           "analyze", "plan_key", "load_waivers", "trend_lines", "main",
           "DEFAULT_RESULTS", "DEFAULT_WAIVERS"]


def _cfg(key, fallback):
    try:
        from .config import cfg_get
        return float(cfg_get("perfwatch", key, str(fallback)))
    except Exception:
        return float(fallback)


def _drift_floor():
    return _cfg("DRIFT_FLOOR", 0.15)


def _min_history():
    return max(int(_cfg("MIN_HISTORY", 3)), 1)


def _mad_mult():
    return _cfg("MAD_MULT", 3.0)


# ----------------------------------------------------------- row ingestion

def load_rows(path=None):
    """Tolerant JSONL read: junk lines and non-dict rows are skipped (the
    trajectory file outlives every schema that wrote into it)."""
    path = pathlib.Path(path) if path else DEFAULT_RESULTS
    rows = []
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return rows
    for line in lines:
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if isinstance(row, dict):
            rows.append(row)
    return rows


def plan_key(plan):
    """Structural digest of a plan-provenance dict: everything that
    changes the PROGRAM (fusion flags, solve composition/dtype, sweeps,
    chunk counts) and nothing that merely re-keys the assembly cache.
    Rows without provenance digest to 'unversioned' — pre-provenance
    history stays comparable with itself, never with planned rows."""
    if not isinstance(plan, dict):
        return "unversioned"
    fusion = plan.get("fusion") or {}
    ftag = "".join(k[0] for k in ("solve", "matvec", "transforms",
                                  "donate", "pallas")
                   if fusion.get(k)) or "-"
    sweeps = plan.get("refine_sweeps")
    return ".".join([
        f"v{plan.get('plan_version', '?')}", ftag,
        str(plan.get("solve_composition") or "-"),
        str(plan.get("solve_dtype") or "-"),
        f"s{'-' if sweeps is None else sweeps}",
        f"k{plan.get('spike_chunks', '-')}",
        f"t{plan.get('transpose_chunks', '-')}",
    ])


def _num(value):
    return value if isinstance(value, (int, float)) \
        and not isinstance(value, bool) else None


def _point(metric, ident, value, direction, row, ts):
    return {"metric": metric, "ident": str(ident), "value": float(value),
            "direction": direction,              # 'down'|'up' = bad way
            "backend": row.get("backend") or "?",
            "plan": plan_key(row.get("plan")), "ts": float(ts)}


def extract_points(rows):
    """Measurement points from raw rows. Positive-matching per known row
    shape; everything unrecognized contributes nothing (a new row kind
    can never crash the sentinel)."""
    points = []
    seen_measured = set()
    for row in rows:
        if row.get("kind") in _NON_MEASUREMENT_KINDS:
            continue
        ts = _num(row.get("ts"))
        if ts is None:
            continue                    # no provenance, no evidence
        if row.get("kind") == "ledger":
            program = row.get("program") or "?"
            for field, metric in _LEDGER_METRICS:
                value = _num(row.get(field))
                if value is not None and value > 0:
                    points.append(_point(metric, program, value, "up",
                                         row, ts))
            continue
        if row.get("finite") is False:
            continue                    # a non-finite run measures nothing
        measured = _num(row.get("measured_ts"))
        if measured is not None or row.get("source") or row.get("stale"):
            # stale re-report: one point per ORIGINAL measurement
            key = (row.get("metric") or row.get("config"), measured)
            if key in seen_measured:
                continue
            seen_measured.add(key)
            ts = measured if measured is not None else ts
        # bench headline rows: metric/value/unit
        metric, value = row.get("metric"), _num(row.get("value"))
        if metric and value is not None and value > 0:
            unit = str(row.get("unit") or "")
            if "steps/sec" in unit or "requests/sec" in unit:
                points.append(_point(str(metric), row.get("config") or "",
                                     value, "down", row, ts))
        # per-config perf rows (bench shapes + step_metrics telemetry)
        sps = _num(row.get("steps_per_sec"))
        if sps is not None and sps > 0 and row.get("config"):
            ident = row["config"]
            if row.get("dtype"):
                ident = f"{ident}/{row['dtype']}"
            points.append(_point("steps_per_sec", ident, sps, "down",
                                 row, ts))
        mem = _num(row.get("device_mem_peak_bytes"))
        if mem is not None and mem > 0 and row.get("config"):
            points.append(_point("device_mem_peak_bytes", row["config"],
                                 mem, "up", row, ts))
        thr = _num(row.get("throughput_requests_per_sec"))
        if thr is not None and thr > 0:
            points.append(_point("requests_per_sec",
                                 row.get("config") or "", thr, "down",
                                 row, ts))
        bat = _num(row.get("batched_requests_per_sec"))
        if bat is not None and bat > 0:
            points.append(_point("batched_requests_per_sec",
                                 row.get("config") or "", bat, "down",
                                 row, ts))
        # solvecomp sweeps: one series per (config, composition, dtype)
        # cell — the grid the PR-15 ascan waiver addresses
        if row.get("benchmark") == "solvecomp":
            for cell in row.get("sweep") or []:
                if not isinstance(cell, dict):
                    continue
                csps = _num(cell.get("steps_per_sec"))
                if csps is None or csps <= 0:
                    continue
                ident = (f"{row.get('config', '?')}/"
                         f"{cell.get('composition', '?')}/"
                         f"{cell.get('solve_dtype', '?')}")
                points.append(_point("steps_per_sec", ident, csps,
                                     "down", row, ts))
    return points


def series_key(point):
    return (f"{point['metric']}:{point['ident']}:{point['backend']}:"
            f"{point['plan']}")


def build_series(rows):
    """{series key -> {'direction', 'values': [...chronological...]}}"""
    series = {}
    for point in sorted(extract_points(rows), key=lambda p: p["ts"]):
        entry = series.setdefault(series_key(point),
                                  {"direction": point["direction"],
                                   "values": []})
        entry["values"].append(point["value"])
    return series


# --------------------------------------------------------------- the bands

def _median(values):
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    return ordered[mid] if n % 2 else (ordered[mid - 1] + ordered[mid]) / 2


def analyze_series(values, direction, drift_floor=None, min_history=None,
                   mad_mult=None):
    """Verdict for one chronological series: the newest point against a
    noise band computed from the REST (median ± max(MAD_MULT x relative
    MAD, DRIFT_FLOOR)). Returns {n, newest, baseline, band, delta,
    verdict} with verdict one of ok | regression | insufficient-history.
    """
    drift_floor = _drift_floor() if drift_floor is None else drift_floor
    min_history = _min_history() if min_history is None else min_history
    mad_mult = _mad_mult() if mad_mult is None else mad_mult
    newest = values[-1]
    history = values[:-1]
    out = {"n": len(values), "newest": newest, "baseline": None,
           "band": None, "delta": None, "verdict": "insufficient-history"}
    if len(history) < min_history:
        return out
    baseline = _median(history)
    out["baseline"] = baseline
    if baseline == 0:
        out["verdict"] = "ok"
        return out
    rel_mad = _median([abs(v - baseline) for v in history]) / abs(baseline)
    band = max(mad_mult * rel_mad, drift_floor)
    delta = (newest - baseline) / abs(baseline)
    out["band"] = band
    out["delta"] = delta
    worse = delta > band if direction == "up" else delta < -band
    out["verdict"] = "regression" if worse else "ok"
    return out


# ----------------------------------------------------------------- waivers

def load_waivers(path=None):
    """[{series: pattern, reason: str}, ...]; a missing or malformed
    file waives nothing (and --check says so rather than crashing)."""
    path = pathlib.Path(path) if path else DEFAULT_WAIVERS
    try:
        data = json.loads(path.read_text())
    except (OSError, ValueError):
        return []
    entries = data.get("waivers") if isinstance(data, dict) else data
    if not isinstance(entries, list):
        return []
    return [e for e in entries
            if isinstance(e, dict) and isinstance(e.get("series"), str)]


def _waived_by(key, waivers):
    for entry in waivers:
        if fnmatch.fnmatch(key, entry["series"]):
            return entry
    return None


# ---------------------------------------------------------------- analysis

def analyze(rows, waivers=None, drift_floor=None, min_history=None,
            mad_mult=None):
    """Full-history analysis. Returns {series: [per-series dicts, sorted
    worst-delta first], regressions: [...unwaived...], waived: [...]}."""
    waivers = [] if waivers is None else waivers
    results = []
    for key, entry in sorted(build_series(rows).items()):
        verdict = analyze_series(entry["values"], entry["direction"],
                                 drift_floor=drift_floor,
                                 min_history=min_history,
                                 mad_mult=mad_mult)
        verdict.update(series=key, direction=entry["direction"])
        if verdict["verdict"] == "regression":
            waiver = _waived_by(key, waivers)
            if waiver is not None:
                verdict["verdict"] = "waived"
                verdict["waive_reason"] = waiver.get("reason", "")
        results.append(verdict)

    def badness(r):
        if r["delta"] is None:
            return 0.0
        return abs(r["delta"]) if (
            (r["direction"] == "up" and r["delta"] > 0)
            or (r["direction"] == "down" and r["delta"] < 0)) else 0.0

    results.sort(key=badness, reverse=True)
    return {
        "series": results,
        "regressions": [r for r in results if r["verdict"] == "regression"],
        "waived": [r for r in results if r["verdict"] == "waived"],
    }


def _fmt_value(value):
    if value is None:
        return "-"
    if abs(value) >= 1e6 or (value and abs(value) < 1e-3):
        return f"{value:.3g}"
    return f"{value:.3f}".rstrip("0").rstrip(".")


def _series_line(r):
    delta = f"{r['delta']:+.1%}" if r["delta"] is not None else "-"
    band = f"±{r['band']:.0%}" if r["band"] is not None else "-"
    return (f"{r['series']}  n={r['n']}  baseline={_fmt_value(r['baseline'])}"
            f"  newest={_fmt_value(r['newest'])}  delta={delta}  "
            f"band={band}  {r['verdict']}")


def trend_lines(rows, waivers=None, limit=20):
    """Trend-table lines for the `report` CLI: analyzed series only
    (insufficient-history series would drown a young file in noise),
    worst first, capped at `limit` with an elision note."""
    analyzed = [r for r in analyze(rows, waivers=waivers)["series"]
                if r["verdict"] != "insufficient-history"]
    lines = [_series_line(r) for r in analyzed[:limit]]
    if len(analyzed) > limit:
        lines.append(f"... {len(analyzed) - limit} more series "
                     "(python -m dedalus_tpu perfwatch for all)")
    return lines


# --------------------------------------------------------------------- CLI

def build_parser():
    parser = argparse.ArgumentParser(
        prog="python -m dedalus_tpu perfwatch",
        description="Perf-trajectory regression sentinel over "
                    "benchmarks/results.jsonl: per-series noise bands "
                    "from historical dispersion; flags the newest point "
                    "of any series that moved outside its band the bad "
                    "way. Exit codes: 0 quiet, 1 unwaived regression, "
                    "2 usage error.")
    parser.add_argument("jsonl", nargs="?", default=None,
                        help="results history to read (default: "
                             "benchmarks/results.jsonl)")
    parser.add_argument("--check", action="store_true",
                        help="CI mode: quiet on a clean trajectory, "
                             "named findings + exit 1 on an unwaived "
                             "regression")
    parser.add_argument("--json", action="store_true",
                        help="emit the full analysis as JSON")
    parser.add_argument("--waivers", default=None, metavar="FILE",
                        help="waiver file (default: "
                             "benchmarks/perfwatch_waivers.json)")
    parser.add_argument("--drift-floor", type=float, default=None,
                        metavar="FRAC",
                        help="minimum relative noise band (default: "
                             "[perfwatch] DRIFT_FLOOR, 0.15 — the "
                             "documented host drift)")
    parser.add_argument("--min-history", type=int, default=None,
                        metavar="N",
                        help="history points required before a series "
                             "is judged (default: [perfwatch] "
                             "MIN_HISTORY, 3)")
    return parser


def main(argv=None):
    """Entry point; returns the exit code (the __main__ shim sys.exits).
    """
    try:
        args = build_parser().parse_args(argv)
    except SystemExit as exc:
        return int(exc.code or 0)
    path = pathlib.Path(args.jsonl) if args.jsonl else DEFAULT_RESULTS
    rows = load_rows(path)
    if not rows and not path.exists():
        print(f"perfwatch: no history at {path}", file=sys.stderr)
        return 2
    waivers = load_waivers(args.waivers)
    report = analyze(rows, waivers=waivers, drift_floor=args.drift_floor,
                     min_history=args.min_history)
    regressions, waived = report["regressions"], report["waived"]

    if args.json:
        print(json.dumps(report, indent=1))
        return 1 if regressions else 0

    if args.check:
        for r in regressions:
            print(f"perfwatch regression: {r['series']} — newest "
                  f"{_fmt_value(r['newest'])} is {r['delta']:+.1%} vs "
                  f"baseline {_fmt_value(r['baseline'])} (noise band "
                  f"±{r['band']:.0%}, n={r['n']})")
        for r in waived:
            print(f"perfwatch waived: {r['series']} ({r['delta']:+.1%}) "
                  f"— {r.get('waive_reason', '')}")
        return 1 if regressions else 0

    analyzed = [r for r in report["series"]
                if r["verdict"] != "insufficient-history"]
    young = len(report["series"]) - len(analyzed)
    print(f"perfwatch: {len(report['series'])} series, {len(analyzed)} "
          f"analyzed, {len(regressions)} regression(s), "
          f"{len(waived)} waived, {young} insufficient-history")
    for r in analyzed:
        print("  " + _series_line(r))
    if young:
        print(f"  ({young} series below --min-history="
              f"{args.min_history or _min_history()} not judged)")
    return 1 if regressions else 0
