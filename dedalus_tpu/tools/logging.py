"""
Logging setup (reference: dedalus/tools/logging.py).

Process-aware root logger configuration from the [logging] config section:
stdout handler at `stdout_level` (non-initial processes use
`nonroot_level`), plus optional per-process file handlers at `file_level`
under `filename`_p{rank}.log (reference: tools/logging.py:24-47).
"""

import logging
import os
import pathlib
import sys

from .config import config

MPI_RANK = 0  # single-controller JAX; per-process files use jax process index


def _resolve_level(name):
    name = (name or "none").lower()
    if name == "none":
        return None
    return getattr(logging, name.upper())


def setup_logging(force=False):
    """Configure the dedalus_tpu root logger from config; idempotent."""
    root = logging.getLogger("dedalus_tpu")
    if root.handlers and not force:
        return root
    # Do NOT call jax.process_index() here: that initializes the backend at
    # import time (and hangs if the accelerator tunnel is down). Multi-host
    # launchers set this env var; single-controller runs are rank 0.
    rank = int(os.environ.get("JAX_PROCESS_INDEX", "0") or 0)
    section = config["logging"]
    stdout_level = _resolve_level(
        section.get("stdout_level", "info") if rank == 0
        else section.get("nonroot_level", "warning"))
    file_level = _resolve_level(section.get("file_level", "none"))
    formatter = logging.Formatter(
        "%(asctime)s %(name)s %(levelname)s :: %(message)s")
    root.setLevel(logging.DEBUG)
    if stdout_level is not None:
        handler = logging.StreamHandler(sys.stdout)
        handler.setLevel(stdout_level)
        handler.setFormatter(formatter)
        root.addHandler(handler)
    if file_level is not None:
        path = pathlib.Path(section.get("filename", "logs/dedalus_tpu"))
        os.makedirs(path.parent, exist_ok=True)
        handler = logging.FileHandler(f"{path}_p{rank}.log")
        handler.setLevel(file_level)
        handler.setFormatter(formatter)
        root.addHandler(handler)
    return root
