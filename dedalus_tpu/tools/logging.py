"""
Logging setup (reference: dedalus/tools/logging.py).

Process-aware root logger configuration from the [logging] config section:
stdout handler at `stdout_level` (non-initial processes use
`nonroot_level`), plus optional per-process file handlers at `file_level`
under `filename`_p{rank}.log (reference: tools/logging.py:24-47). File
handlers are flushed and closed at interpreter exit so per-process logs
survive abrupt ends of multi-host runs.
"""

import atexit
import logging
import os
import pathlib
import sys

from .config import config


def process_rank():
    """This process's rank for logging purposes. Reads JAX_PROCESS_INDEX
    (set by multi-host launchers) rather than calling jax.process_index():
    that would initialize the backend at import time (and hang if the
    accelerator tunnel is down). Single-controller runs are rank 0."""
    return int(os.environ.get("JAX_PROCESS_INDEX", "0") or 0)


def _resolve_level(name):
    name = (name or "none").lower()
    if name == "none":
        return None
    return getattr(logging, name.upper())


def _close_handlers(handlers):
    """Detach, flush, and close file handlers at interpreter exit. Mostly
    belt-and-braces over logging.shutdown (which flushes all live
    handlers), but detaching FIRST guarantees no later atexit callback
    logs into a closed stream, and the explicit close survives a
    `logging.raiseExceptions=False`-style global shutdown ordering."""
    root = logging.getLogger("dedalus_tpu")
    for handler in handlers:
        try:
            root.removeHandler(handler)
            handler.flush()
            handler.close()
        except Exception:
            pass


def setup_logging(force=False):
    """Configure the dedalus_tpu root logger from config; idempotent."""
    root = logging.getLogger("dedalus_tpu")
    if root.handlers and not force:
        return root
    rank = process_rank()
    section = config["logging"]
    stdout_level = _resolve_level(
        section.get("stdout_level", "info") if rank == 0
        else section.get("nonroot_level", "warning"))
    file_level = _resolve_level(section.get("file_level", "none"))
    formatter = logging.Formatter(
        "%(asctime)s %(name)s %(levelname)s :: %(message)s")
    root.setLevel(logging.DEBUG)
    added = []
    if stdout_level is not None:
        handler = logging.StreamHandler(sys.stdout)
        handler.setLevel(stdout_level)
        handler.setFormatter(formatter)
        root.addHandler(handler)
    if file_level is not None:
        path = pathlib.Path(section.get("filename", "logs/dedalus_tpu"))
        # parent must exist BEFORE FileHandler opens the stream
        path.parent.mkdir(parents=True, exist_ok=True)
        handler = logging.FileHandler(f"{path}_p{rank}.log")
        handler.setLevel(file_level)
        handler.setFormatter(formatter)
        root.addHandler(handler)
        added.append(handler)
    if added:
        atexit.register(_close_handlers, added)
    return root
