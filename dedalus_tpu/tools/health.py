"""
Numerical-health monitor + divergence flight recorder for the IVP loop.

PR 1 made wall time observable; this module makes the *numerics*
observable. A single jitted, cadence-gated probe (one fused reduction
over the gathered (G, S) pencil state) computes, per state field:

  * NaN and Inf entry counts,
  * max |coefficient| and the L2 norm,
  * the spectral tail-energy fraction per basis axis — energy carried by
    the top third of modes, the classic under-resolution tell (energy
    piling into the truncation edge instead of decaying).

Cadence gating reuses the [profiling] machinery (`metrics.CadenceGate`):
off-cadence iterations pay one Python attribute check and never touch the
device; on-cadence iterations dispatch the probe and pull back a handful
of scalars (the only host round-trip, riding the same sampled-sync budget
as the phase timers). When health is disabled the probe is never built or
compiled — the zero-overhead path.

Failure policy: NaN/Inf anywhere in the state, or max|coefficient| above
the configurable growth bound, is fatal. The solver halts *gracefully* —
`solver.proceed` flips False, a structured `SolverHealthError` becomes
available as `solver.health_error`, scheduled output handlers are skipped from the
detecting step onward (a detected-poisoned state is never written as a
"good" checkpoint; detection granularity is the probe cadence) —
and the monitor dumps a **flight recorder**: one post-mortem directory
holding the ring buffer of recent health records, the metrics flush, the
CFL/dt history of any attached `extras.flow_tools.CFL`, flow-property
snapshots of attached `GlobalFlowProperty` instances, and a
`load_state`-compatible state checkpoint, plus a
`benchmarks/results.jsonl`-compatible summary record. Tail energy above
the warn threshold logs an under-resolution warning (once per
field/axis) naming the offending field and basis axis.

Summarize a dump with `python -m dedalus_tpu postmortem <dir>`; the
`[health]` config section controls cadence, thresholds, ring size, and
the on/off default.
"""

import json
import logging
import os
import pathlib
import time
from collections import deque

import numpy as np

from .config import config
from .exceptions import SolverHealthError
from . import metrics as metrics_mod

logger = logging.getLogger(__name__)

__all__ = ["HealthMonitor", "SolverHealthError", "resolve",
           "read_postmortem", "format_postmortem"]

# Tail = top third of the resolved modes along an axis (by wavenumber
# magnitude for separable/Fourier axes, by polynomial degree for coupled
# axes). A well-resolved spectrum decays through the tail; a flat or
# rising one means the truncation is doing physics.
TAIL_FRACTION = 1.0 / 3.0
# Fields with less energy than this (L2) are spectrally meaningless noise:
# no tail warning (a zero-initialized velocity field would otherwise warn
# on its round-off content).
TAIL_ENERGY_FLOOR = 1e-10


def _jsonable(obj):
    """Recursively replace non-finite floats with their repr strings
    ('inf', '-inf', 'nan'): a diverged state produces exactly these values,
    and Python's json would emit non-strict NaN/Infinity literals that
    break downstream results.jsonl consumers."""
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, float) and not np.isfinite(obj):
        return repr(obj)
    return obj


def _fmt(value):
    """Format a maybe-sanitized numeric for the postmortem CLI."""
    if isinstance(value, (int, float)):
        return f"{value:#.4g}"
    return str(value)


def _tau_like(name):
    """Tau fields absorb boundary/gauge error and are spectrally broad by
    construction — their tail fraction is not an under-resolution signal,
    so they are exempt from tail WARNINGS (NaN/Inf and growth checks still
    apply, and their tail stats still land in every record). Uses the
    reference naming convention (tau_*) plus unnamed fields."""
    return name == "tau" or name.startswith("tau_") \
        or name.startswith("_anon_")


def _axis_label(basis, axis):
    """Coordinate name of one axis of a (possibly multi-dim) basis."""
    if getattr(basis, "dim", 1) == 1:
        return basis.coord.name
    sub = axis - basis.first_axis
    names = getattr(getattr(basis, "cs", None), "names", None)
    if names is not None and sub < len(names):
        return names[sub]
    return f"axis{axis}"


class HealthMonitor:
    """
    Per-solver numerical-health state: the jitted probe (built lazily, so
    disabled monitors never compile anything), the ring buffer of recent
    records, threshold bookkeeping, and the flight-recorder dump.
    """

    def __init__(self, enabled=True, cadence=200, ring_size=64,
                 max_abs_limit=1e12, tail_warn_frac=0.25,
                 postmortem_dir="postmortems"):
        self.enabled = bool(enabled)
        self.solver = None
        self.cadence = int(cadence)   # property: also (re)builds the gate
        self.ring = deque(maxlen=max(int(ring_size), 1))
        self.max_abs_limit = float(max_abs_limit)
        self.tail_warn_frac = float(tail_warn_frac)
        self.postmortem_dir = postmortem_dir
        self.checks = 0
        self.warnings = 0
        self.failed_reason = None
        self.postmortem_path = None
        self._probe = None
        self._specs = None
        self._warned = set()
        self._dt_dumped = False
        self._dt_sources = []     # CFL instances (dt/frequency history)
        self._flow_sources = []   # (GlobalFlowProperty, names) pairs

    # ------------------------------------------------------------ wiring

    @property
    def cadence(self):
        return self._cadence

    @cadence.setter
    def cadence(self, value):
        """Assigning a new cadence rebuilds the gate (re-anchored at the
        solver's current iteration when attached), so tuning
        `solver.health.cadence` mid-run takes effect instead of being a
        silent no-op against the already-armed gate."""
        self._cadence = int(value)
        self.gate = metrics_mod.CadenceGate(self._cadence)
        if self.solver is not None:
            self.gate.reset(int(self.solver.iteration))

    def attach(self, solver):
        self.solver = solver
        return self

    def reset_failure(self):
        """Clear the failure latch after a resilient rewind
        (tools/resilience.py): the solver's health_error is dropped,
        `proceed` can flip True again, and the probe gate re-anchors at
        the rewound iteration. Forensic state (ring, postmortem_path,
        check/warning counts) is preserved — the flight recordings of
        every attempt remain on disk and in the ring."""
        self.failed_reason = None
        self._dt_dumped = False
        if self.solver is not None:
            self.solver._health_error = None
            self.gate.reset(int(self.solver.iteration))

    def reset_run(self):
        """Fresh-run reset for a POOLED solver (service/pool.py), called
        between served requests: clears the failure latch AND the per-run
        forensic state — unlike `reset_failure`, which deliberately
        preserves the ring and counters across a resilient rewind within
        one run. The compiled probe survives (it is what makes the pool
        warm); postmortem dumps already written stay on disk."""
        self.ring.clear()
        self.checks = 0
        self.warnings = 0
        self.postmortem_path = None
        self._warned = set()
        self.reset_failure()

    def attach_dt_source(self, cfl):
        """Register a CFL controller whose dt/frequency history feeds the
        flight recorder (extras.flow_tools.CFL self-registers)."""
        if cfl not in self._dt_sources:
            self._dt_sources.append(cfl)

    def attach_flow(self, flow, names):
        """Register a GlobalFlowProperty whose `report(names)` snapshot is
        included in post-mortem dumps."""
        self._flow_sources.append((flow, list(names)))

    # ------------------------------------------------------------- probe

    def _build_specs(self):
        """Host-side probe plan: per state field, the (offset, size) slice
        of the gathered X and the tail masks per monitored basis axis.
        Masks factorize over the (G, slot) layout: a separable axis mask
        depends only on the group index (G-vector), a coupled axis mask
        only on the slot position (S_f-vector) — so the probe stays one
        fused reduction with no reshapes."""
        from ..core.subsystems import state_key
        solver = self.solver
        layout = solver.layout
        groups = None
        specs = []
        offset = 0
        for v in solver.variables:
            size = layout.slot_size(v.domain, v.tensorsig)
            slot_shape = layout.slot_shape(v.domain, v.tensorsig)
            axes = []
            for axis, basis in enumerate(v.domain.bases):
                if basis is None:
                    continue
                label = _axis_label(basis, axis)
                if axis in layout.sep_widths:
                    # separable axis: tail by |wavenumber| over groups
                    if (getattr(basis, "dim", 1) != 1
                            or not hasattr(basis, "group_wavenumber")):
                        continue
                    n_ax = layout.sep_n_groups[axis]
                    if n_ax < 4:
                        continue
                    k = np.abs(np.asarray(basis.group_wavenumber(
                        np.arange(n_ax)), dtype=float))
                    kmax = k.max()
                    if kmax <= 0:
                        continue
                    tail_ax = k > (1.0 - TAIL_FRACTION) * kmax
                    if groups is None:
                        groups = list(layout.groups())
                    mask = np.array([tail_ax[g[axis]] for g in groups],
                                    dtype=float)
                    axes.append((label, "group", mask))
                else:
                    # coupled axis: tail by mode position in the slot
                    n_ax = slot_shape[1 + axis]
                    if n_ax < 4:
                        continue
                    idx = np.indices(slot_shape)[1 + axis].reshape(-1)
                    cut = int(np.ceil((1.0 - TAIL_FRACTION) * n_ax))
                    mask = (idx >= cut).astype(float)
                    axes.append((label, "slot", mask))
            specs.append((state_key(v), offset, size, axes))
            offset += size
        return specs

    def _ensure_probe(self):
        """Compile the fused health reduction (once; only when enabled)."""
        if self._probe is not None:
            return self._probe
        import jax
        import jax.numpy as jnp
        self._specs = specs = self._build_specs()

        def probe(X):
            with metrics_mod.trace_scope("health", "probe"):
                out = {}
                for name, off, size, axes in specs:
                    Xf = X[:, off:off + size]
                    absXf = jnp.abs(Xf)
                    a2 = jnp.square(absXf)
                    total = jnp.sum(a2)
                    tails = {}
                    for label, kind, mask in axes:
                        m = jnp.asarray(mask, dtype=a2.dtype)
                        if kind == "group":
                            te = jnp.sum(a2 * m[:, None])
                        else:
                            te = jnp.sum(a2 * m[None, :])
                        tails[label] = jnp.where(total > 0.0, te / total, 0.0)
                    out[name] = {
                        "nan": jnp.sum(jnp.isnan(Xf).astype(jnp.int32)),
                        "inf": jnp.sum(jnp.isinf(Xf).astype(jnp.int32)),
                        "max_abs": jnp.max(absXf),
                        "l2": jnp.sqrt(total),
                        "tail_frac": tails,
                    }
                return out

        # noted(): the probe participates in the retrace sentinel like the
        # lifted_jit step programs (tools/retrace.py)
        from . import retrace as retrace_mod
        self._probe = jax.jit(retrace_mod.noted(probe, "health/probe"))
        return self._probe

    # ------------------------------------------------------------- ticks

    def warm(self, X):
        """Compile the probe and take a baseline record (called at warmup
        end, like the metrics phase probes, so probe compilation stays out
        of measured windows)."""
        if not self.enabled or self.solver is None:
            return
        try:
            self.check(X)
        except SolverHealthError:
            raise
        except Exception as exc:
            # telemetry firewall: a probe failure disables health
            # monitoring instead of killing the simulation
            logger.warning(f"health probe disabled: {exc}")
            self.enabled = False

    def tick(self, n=1):
        """Per-step hook: cadence-check the solver state. Off-cadence cost
        is one gate comparison; nothing device-side happens."""
        if not self.enabled or self.failed_reason is not None:
            return
        solver = self.solver
        if solver is None or not self.gate.due(solver.iteration):
            return
        try:
            self.check(solver.X)
        except SolverHealthError:
            raise
        except Exception as exc:
            logger.warning(f"health probe disabled: {exc}")
            self.enabled = False

    def check(self, X=None):
        """Run the probe now, record, and evaluate thresholds. Returns the
        health record. Fatal findings mark the solver (graceful halt);
        they do not raise from here."""
        solver = self.solver
        if X is None:
            X = solver.X
        import jax
        with metrics_mod.annotate("dedalus/health/check"):
            stats = jax.device_get(self._ensure_probe()(X))
        self.checks += 1
        fields = {}
        for name, s in stats.items():
            fields[name] = {
                "nan": int(s["nan"]),
                "inf": int(s["inf"]),
                "max_abs": float(s["max_abs"]),
                "l2": float(s["l2"]),
                "tail_frac": {lab: round(float(v), 6)
                              for lab, v in s["tail_frac"].items()},
            }
        record = {
            "kind": "health_sample",
            "ts": round(time.time(), 3),
            "iteration": int(solver.iteration),
            "sim_time": float(solver.sim_time),
            "dt": float(solver.dt) if solver.dt is not None else None,
            "fields": fields,
        }
        self.ring.append(record)
        self._evaluate(record)
        return record

    def _ensure_value_probe(self):
        """The fused non-finite count over a list of device leaves (one
        jitted reduction, scalar output) shared by `check_values` and
        `nonfinite_count`."""
        import jax
        import jax.numpy as jnp
        probe = getattr(self, "_value_probe", None)
        if probe is None:
            from . import retrace as retrace_mod

            def raw(leaves):
                with metrics_mod.trace_scope("health", "values"):
                    total = jnp.zeros((), dtype=jnp.int32)
                    for leaf in leaves:
                        total = total + jnp.sum(
                            (~jnp.isfinite(leaf)).astype(jnp.int32))
                    return total
            # memoized on self just above (one wrapper per monitor, so
            # the retrace sentinel counts real signature churn only)
            probe = self._value_probe = jax.jit(  # dedalus-lint: disable=DTL003
                retrace_mod.noted(raw, "health/values"))
        return probe

    def nonfinite_count(self, tree, phase="values"):
        """
        Fused device-side non-finite entry count over a pytree of device
        values: one jitted reduction, ONE scalar host pull, no verdict.
        This is the sync-light spelling of "is this state finite?" — the
        snapshot-validation paths (tools/resilience.Snapshot.is_finite,
        core/ensemble.FleetSnapshot) route through it instead of
        gathering the whole state to host (`np.asarray(X)` was a full
        device→host transfer per capture validation). Like
        `check_values` it is an explicit-call API: it works on a monitor
        built with enabled=False and never latches a failure.
        """
        import jax
        leaves = [leaf for leaf in jax.tree.leaves(tree)
                  if hasattr(leaf, "dtype")]
        if not leaves:
            return 0
        probe = self._ensure_value_probe()
        with metrics_mod.annotate(f"dedalus/health/{phase}"):
            return int(jax.device_get(probe(leaves)))

    def check_values(self, tree, phase="adjoint", context=None):
        """
        Explicit fused non-finite check over an arbitrary pytree of device
        values (the differentiable-solve path routes its loss + gradients
        through here, core/adjoint.py): one jitted reduction, one scalar
        host pull, and a structured `SolverHealthError` naming `phase`
        when anything is non-finite. Unlike the cadence-gated state probe
        this is an explicit-call API: it runs even on a monitor built
        with enabled=False (the zero-overhead contract covers the step
        loop's implicit ticks, not a caller asking for a verdict), it
        counts toward `checks`, and it does NOT latch the monitor failed
        — the solver state itself may be fine; only the requested
        computation is poisoned. Returns the non-finite entry count (0
        when healthy; the error is raised, not returned).
        """
        import jax
        leaves = [leaf for leaf in jax.tree.leaves(tree)
                  if hasattr(leaf, "dtype")]
        self.checks += 1
        if not leaves:
            return 0
        probe = self._ensure_value_probe()
        with metrics_mod.annotate(f"dedalus/health/{phase}"):
            bad = int(jax.device_get(probe(leaves)))
        if bad:
            solver = self.solver
            reason = (f"{phase}: non-finite values "
                      f"({bad} entries across the checked outputs)"
                      + (f" — {context}" if context else ""))
            raise SolverHealthError(
                reason,
                iteration=int(solver.iteration) if solver else None,
                sim_time=float(solver.sim_time) if solver else None)
        return 0

    def _evaluate(self, record):
        fatal = None
        for name, s in record["fields"].items():
            if s["nan"] or s["inf"]:
                fatal = (f"non-finite state: field '{name}' has "
                         f"{s['nan']} NaN / {s['inf']} Inf entries at "
                         f"iteration {record['iteration']}, "
                         f"sim_time {record['sim_time']:.6e}")
                break
            if np.isfinite(self.max_abs_limit) \
                    and s["max_abs"] > self.max_abs_limit:
                fatal = (f"growth bound exceeded: field '{name}' "
                         f"max|coeff| = {s['max_abs']:.3e} > "
                         f"{self.max_abs_limit:.3e} at iteration "
                         f"{record['iteration']}, "
                         f"sim_time {record['sim_time']:.6e}")
                break
            if s["l2"] > TAIL_ENERGY_FLOOR and not _tau_like(name):
                for label, frac in s["tail_frac"].items():
                    if frac > self.tail_warn_frac \
                            and (name, label) not in self._warned:
                        self._warned.add((name, label))
                        self.warnings += 1
                        logger.warning(
                            f"under-resolution: field '{name}' axis "
                            f"'{label}' holds {100 * frac:.1f}% of its "
                            f"energy in the top-third modes (warn "
                            f"threshold {100 * self.tail_warn_frac:.0f}%) "
                            f"at iteration {record['iteration']} — "
                            f"consider raising the resolution")
        if fatal:
            err = self._fail(fatal, record)
            self.solver._health_error = err
            logger.error(f"Numerical health failure, halting run: {fatal}"
                         + (f" (post-mortem: {err.postmortem_dir})"
                            if err.postmortem_dir else ""))

    # ----------------------------------------------------------- failure

    def invalid_dt(self, dt):
        """Structured error for a non-finite timestep (the CFL-blow-up
        path): dumps the flight recorder (when enabled, once per run) and
        returns the SolverHealthError for the caller to raise. Unlike a
        non-finite STATE this does not poison the solver — the state is
        still fine, so a legacy `except ValueError: retry with min_dt`
        guard keeps the run alive (as the SolverHealthError docstring
        promises); only the raise itself stops an unguarded loop."""
        solver = self.solver
        reason = (f"Invalid timestep: dt={dt!r} is non-finite at iteration "
                  f"{solver.iteration}, sim_time {solver.sim_time:.6e} "
                  f"(adaptive-CFL frequency blow-up upstream?)")
        pm = None
        if self.enabled and not self._dt_dumped:
            self._dt_dumped = True   # one forensic dump, not one per retry
            try:
                pm = self.dump_postmortem(reason)
            except Exception as exc:
                logger.warning(f"flight-recorder dump failed: {exc}")
        logger.error(f"Numerical health failure: {reason}"
                     + (f" (post-mortem: {pm})" if pm else ""))
        return SolverHealthError(
            reason, iteration=int(solver.iteration),
            sim_time=float(solver.sim_time),
            record=self.ring[-1] if self.ring else None,
            postmortem_dir=str(pm) if pm else None)

    def _fail(self, reason, record=None):
        """Mark failed, dump the flight recorder, build the error."""
        self.failed_reason = reason
        pm = None
        if self.enabled:
            try:
                pm = self.dump_postmortem(reason)
            except Exception as exc:
                logger.warning(f"flight-recorder dump failed: {exc}")
        self.postmortem_path = pm
        solver = self.solver
        if record is None and self.ring:
            record = self.ring[-1]
        return SolverHealthError(
            reason,
            iteration=int(solver.iteration) if solver else None,
            sim_time=float(solver.sim_time) if solver else None,
            record=record,
            postmortem_dir=str(pm) if pm else None)

    # --------------------------------------------------- flight recorder

    def dt_history(self):
        """Recent (iteration, dt, freq_max) entries from attached CFL
        controllers, oldest first."""
        out = []
        for src in self._dt_sources:
            out.extend(dict(e) for e in getattr(src, "history", ()))
        out.sort(key=lambda e: e.get("iteration", 0))
        return out

    def flow_report(self):
        """{name: stats} snapshots of attached GlobalFlowProperty sources
        (best-effort: a source whose tasks never evaluated is skipped)."""
        out = {}
        for flow, names in self._flow_sources:
            try:
                out.update(flow.report(names))
            except Exception as exc:
                logger.debug(f"flow report skipped: {exc}")
        return out

    def dump_postmortem(self, reason):
        """
        Write the post-mortem directory:
          postmortem.json       — the summary record (indented)
          record.jsonl          — the same record, one results.jsonl line
          health_ring.jsonl     — the ring buffer, one record per line
          state_at_failure.h5   — load_state-compatible checkpoint of the
                                  (possibly non-finite) state, clearly
                                  named as forensic evidence, never as a
                                  restartable "good" write
        Also appends the summary record to the metrics JSONL sink when one
        is configured. Returns the directory path.
        """
        solver = self.solver
        base = pathlib.Path(self.postmortem_dir)
        # collision-proof naming: iteration + wall-clock timestamp stem,
        # plus a counter for same-second repeats — a rewind-retry-fail
        # cycle rediverging at the SAME iteration must never overwrite an
        # earlier flight recording
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        stem = f"postmortem_i{int(solver.iteration):08d}_{stamp}"
        path = base / stem
        n = 0
        while path.exists():
            n += 1
            path = base / f"{stem}_{n}"
        path.mkdir(parents=True)
        # visible to summary() before the flush below, so the step_metrics
        # record emitted during the dump already carries the pointer
        self.postmortem_path = path
        with open(path / "health_ring.jsonl", "w") as f:
            for rec in self.ring:
                f.write(json.dumps(_jsonable(rec)) + "\n")
        metrics_rec = None
        try:
            metrics_rec = solver.flush_metrics()
        except Exception as exc:
            logger.warning(f"post-mortem metrics flush failed: {exc}")
        checkpoint = None
        try:
            checkpoint = self._write_checkpoint(path / "state_at_failure.h5")
        except Exception as exc:
            logger.warning(f"post-mortem checkpoint failed: {exc}")
        record = {
            "kind": "health_postmortem",
            "ts": round(time.time(), 3),
            "reason": reason,
            "iteration": int(solver.iteration),
            "sim_time": float(solver.sim_time),
            "dt": float(solver.dt) if solver.dt is not None else None,
            "checks": self.checks,
            "warnings": self.warnings,
            "ring_records": len(self.ring),
            "fields": self.ring[-1]["fields"] if self.ring else {},
            "dt_history": self.dt_history(),
            "flow": self.flow_report(),
            "metrics": metrics_rec,
            "checkpoint": checkpoint,
            "directory": str(path),
        }
        resilience = getattr(solver, "resilience", None)
        if resilience is not None:
            # retry lineage: which rewind/backoff attempts preceded this
            # dump (tools/resilience.py), so a chain of flight recordings
            # reads as one story
            record["resilience"] = resilience.summary()
        record.update({k: v for k, v in solver.metrics.meta.items()
                       if k not in record})
        record = _jsonable(record)
        with open(path / "postmortem.json", "w") as f:
            json.dump(record, f, indent=2)
        with open(path / "record.jsonl", "w") as f:
            f.write(json.dumps(record) + "\n")
        solver.metrics.emit(record)
        return path

    def _write_checkpoint(self, path):
        """One-write HDF5 state dump with the FileHandler/load_state schema
        (scales/sim_time|iteration|write_number|timestep, tasks/<name>)."""
        import h5py
        from ..core.subsystems import state_key
        solver = self.solver
        with h5py.File(path, "w") as f:
            scales = f.create_group("scales")
            dt = solver.dt if solver.dt is not None else np.nan
            for key, val in (("sim_time", solver.sim_time),
                             ("iteration", solver.iteration),
                             ("write_number", 1),
                             ("timestep", dt)):
                scales.create_dataset(
                    key, data=np.array([val], dtype=np.float64))
            tasks = f.create_group("tasks")
            for var in solver.state:
                var.change_scales(1)
                data = np.asarray(var["g"])
                tasks.create_dataset(state_key(var), data=data[None])
        return path.name

    # ----------------------------------------------------------- summary

    def summary(self):
        """Compact health summary attached to telemetry flushes and bench
        records (None when disabled)."""
        if not self.enabled and self.failed_reason is None:
            return None
        out = {"checks": self.checks, "warnings": self.warnings,
               "ok": self.failed_reason is None}
        if self.failed_reason is not None:
            out["reason"] = self.failed_reason
        if self.postmortem_path is not None:
            # set early in dump_postmortem, so even the metrics record
            # flushed DURING the dump carries the pointer; also covers
            # invalid-dt dumps (which do not mark the monitor failed)
            out["postmortem"] = str(self.postmortem_path)
        if self.ring:
            last = self.ring[-1]
            out["last_iteration"] = last["iteration"]
            out["max_abs"] = max(
                (s["max_abs"] for s in last["fields"].values()), default=0.0)
            out["max_tail_frac"] = max(
                (v for s in last["fields"].values()
                 for v in s["tail_frac"].values()), default=0.0)
        # diverged states put inf/nan here; keep the summary strict-JSON
        return _jsonable(out)


def resolve(spec=None, solver=None, cadence=None, ring_size=None,
            postmortem_dir=None):
    """
    Resolve a solver's `health` argument against the [health] config: a
    HealthMonitor passes through (attached to the solver); True/None build
    from config (None respects HEALTH_DEFAULT, True forces on); False
    builds a disabled monitor (still attached, so `solver.health` always
    exists and the invalid-dt path stays structured).
    """
    if isinstance(spec, HealthMonitor):
        return spec.attach(solver)
    section = config["health"] if config.has_section("health") else {}

    def get(key, fallback):
        try:
            return section.get(key, fallback) or fallback
        except AttributeError:
            return fallback

    if spec is None:
        default = str(get("HEALTH_DEFAULT", "True")).strip().lower()
        enabled = default in ("1", "true", "yes", "on")
    else:
        enabled = bool(spec)
    if cadence is None:
        cadence = int(get("CHECK_CADENCE", "200"))
    if ring_size is None:
        ring_size = int(get("RING_SIZE", "64"))
    if postmortem_dir is None:
        postmortem_dir = get("POSTMORTEM_DIR", "postmortems")
    monitor = HealthMonitor(
        enabled=enabled, cadence=cadence, ring_size=ring_size,
        max_abs_limit=float(get("MAX_ABS_LIMIT", "1e12")),
        tail_warn_frac=float(get("TAIL_WARN_FRAC", "0.25")),
        postmortem_dir=postmortem_dir)
    return monitor.attach(solver)


# ------------------------------------------------------- post-mortem CLI

def read_postmortem(path):
    """Load a post-mortem summary record from a directory (postmortem.json
    / record.jsonl) or a record file path. Returns (record, ring) where
    ring is the list of health records (empty when absent)."""
    path = pathlib.Path(path)
    if path.is_dir():
        for name in ("postmortem.json", "record.jsonl"):
            cand = path / name
            if cand.exists():
                rec_path = cand
                break
        else:
            raise FileNotFoundError(
                f"{path}: no postmortem.json or record.jsonl")
        ring_path = path / "health_ring.jsonl"
    else:
        rec_path = path
        ring_path = path.parent / "health_ring.jsonl"
    text = rec_path.read_text().strip()
    record = json.loads(text.splitlines()[0]) if rec_path.suffix == ".jsonl" \
        else json.loads(text)
    ring = []
    if ring_path.exists():
        for line in ring_path.read_text().splitlines():
            line = line.strip()
            if line:
                try:
                    ring.append(json.loads(line))
                except ValueError:
                    pass
    return record, ring


def format_postmortem(record, ring=()):
    """Render a post-mortem record as text lines (the `postmortem` CLI)."""
    lines = []
    lines.append(f"Post-mortem: {record.get('reason', '(no reason recorded)')}")
    it = record.get("iteration")
    st = record.get("sim_time")
    dt = record.get("dt")
    lines.append(f"  iteration={it}  sim_time={st}  dt={dt}")
    ident = " ".join(f"{k}={record[k]}"
                     for k in ("config", "backend", "dtype")
                     if record.get(k) is not None)
    if ident:
        lines.append(f"  {ident}")
    fields = record.get("fields") or {}
    if fields:
        lines.append(f"  fields at failure ({len(fields)}):")
        for name, s in fields.items():
            tails = s.get("tail_frac") or {}
            numeric = [v for v in tails.values()
                       if isinstance(v, (int, float))]
            strings = [v for v in tails.values() if isinstance(v, str)]
            worst = strings[0] if strings else max(numeric, default=0.0)
            lines.append(
                f"    {name:<12} nan={s.get('nan', 0):<6} "
                f"inf={s.get('inf', 0):<6} "
                f"max|c|={_fmt(s.get('max_abs', 0.0))}  "
                f"L2={_fmt(s.get('l2', 0.0))}  tail={_fmt(worst)}")
    hist = record.get("dt_history") or []
    if hist:
        last = hist[-1]
        lines.append(f"  dt history: {len(hist)} entries, last "
                     f"dt={last.get('dt')} freq_max={last.get('freq_max')} "
                     f"at iteration {last.get('iteration')}")
    flow = record.get("flow") or {}
    for name, s in flow.items():
        lines.append(f"  flow {name}: {s}")
    if ring:
        lines.append(f"  ring buffer: {len(ring)} records, iterations "
                     f"{ring[0].get('iteration')}..{ring[-1].get('iteration')}")
    metrics_rec = record.get("metrics")
    if metrics_rec:
        lines.append(f"  metrics: {metrics_rec.get('iterations', 0)} "
                     f"iterations, "
                     f"{metrics_rec.get('steps_per_sec', 0.0)} steps/s")
    if record.get("checkpoint"):
        lines.append(f"  checkpoint: {record['checkpoint']} "
                     f"(state at failure — forensic, may be non-finite)")
    resilience = record.get("resilience")
    if isinstance(resilience, dict):
        lines.append(
            f"  resilience: {resilience.get('rewinds', 0)} rewind(s), "
            f"{resilience.get('retries', 0)} retry(ies)"
            + (f", resumed from {resilience['resumed_from']}"
               if resilience.get("resumed_from") else ""))
        for attempt in resilience.get("lineage") or []:
            lines.append(
                f"    attempt {attempt.get('attempt', '?')}: failed at "
                f"iteration {attempt.get('failure_iteration', '?')} "
                f"({attempt.get('reason', '?')}) -> "
                f"{attempt.get('outcome', '?')}"
                + (f" @ iteration {attempt['rewind_iteration']}, "
                   f"dt capped {_fmt(attempt.get('dt_limit'))}"
                   if attempt.get("rewind_iteration") is not None else ""))
    lines.append(f"  checks={record.get('checks', 0)} "
                 f"warnings={record.get('warnings', 0)}")
    return lines
