"""
Version-compat shims for JAX API drift.

The sharding machinery targets the stable `jax.shard_map` entry point
(promoted out of `jax.experimental` in recent JAX), but deployed runtimes
span several majors: older installs only ship
`jax.experimental.shard_map.shard_map`. Every in-repo use routes through
`shard_map` exported here, so the parallel/collectives suite runs on
whichever spelling the installed JAX provides instead of failing on the
8-device virtual CPU mesh (ROADMAP item 4).

Resolution order (first hit wins):
  1. `jax.shard_map`                          — current public API
  2. `jax.experimental.shard_map.shard_map`   — the pre-promotion home

Both spellings share the keyword signature used here
(`mesh=`, `in_specs=`, `out_specs=`), so the shim is a plain re-export,
not an adapter.
"""

import jax

__all__ = ["shard_map"]


def _resolve_shard_map():
    # getattr (not hasattr+attribute) so jax's module-level deprecation
    # __getattr__ machinery is honored: an accelerated removal raises
    # AttributeError and falls through to the experimental spelling.
    try:
        sm = getattr(jax, "shard_map")
        if sm is not None:
            return sm
    except AttributeError:
        pass
    try:
        from jax.experimental.shard_map import shard_map as sm
        return sm
    except ImportError as exc:
        raise ImportError(
            "dedalus_tpu requires a JAX with shard_map (either "
            "jax.shard_map or jax.experimental.shard_map.shard_map); "
            f"neither is available in jax {jax.__version__}") from exc


shard_map = _resolve_shard_map()
