"""
Jit-hygiene static analysis (`python -m dedalus_tpu lint`).

The hot loop of this framework is only fast while three invariants hold:
no host round-trips inside the step path, no large host arrays inlined
into compiled program text (tools/jitlift.py exists precisely to lift
them to runtime arguments), and no post-warmup retraces. Benchmarks catch
violations hours later; this AST pass catches them at review time.

Components:
  framework.py — rule registry, findings, per-line `# dedalus-lint:
                 disable=RULE` suppressions, JSON baseline for
                 grandfathered findings, module context (import-alias
                 canonicalization + traced-function detection), parallel
                 per-file scanning.
  rules.py     — the DTL rule set (see each rule's docstring).
  progcheck.py — the SECOND tier: compiled-program contracts (DTP ids)
                 over a census of lowered step/grad/fleet programs —
                 collective placement, donation aliasing, forbidden
                 primitives, manual-region integrity
                 (`lint --programs`; baseline progcheck_baseline.json).
  threadcheck.py — the THIRD tier: thread-safety rules (DTC ids) over
                 the serving stack's threaded modules — a curated
                 guarded-by lock catalog, thread-aliased mutation, and
                 the lock-order graph — plus the opt-in runtime
                 lock-order sanitizer (`lint --threads`; baseline
                 threadcheck_baseline.json). The DTC rules register in
                 the shared rule set, so the default run covers them.
  cli.py       — `python -m dedalus_tpu lint [paths]`; exits nonzero on
                 findings not covered by the baseline.

The pass is self-enforcing: tests/test_lint.py runs the AST tier over
the package against the checked-in baseline (tools/lint/baseline.json)
and tests/test_progcheck.py runs the fast census subset against
progcheck_baseline.json, so tier-1 fails on any new un-baselined
violation in either source or compiled programs. The runtime complements
are the retrace sentinel (tools/retrace.py) and the opt-in `leak_check`
pytest marker (tests/conftest.py).
"""

from .framework import (DEFAULT_BASELINE, PACKAGE_DIR, Finding, LintResult,
                        Rule, all_rules, apply_baseline, baseline_rel,
                        load_baseline, make_baseline, register, run_lint)
from . import rules  # noqa: F401  (imports register the rule set)
from . import threadcheck  # noqa: F401  (registers the DTC rules)
from .threadcheck import THREADCHECK_BASELINE

__all__ = ["PACKAGE_DIR", "DEFAULT_BASELINE", "THREADCHECK_BASELINE",
           "Finding", "LintResult", "Rule", "all_rules",
           "apply_baseline", "baseline_rel", "check_baseline_fresh",
           "lint_package", "load_baseline", "make_baseline", "register",
           "run_lint"]


def lint_package(baseline_path=None):
    """Lint the installed package tree against a baseline (default: the
    checked-in one). Returns a plain-dict summary — the programmatic
    surface used by bench.py, `python -m dedalus_tpu test`, and tests:
    {"total", "new", "baselined", "suppressed", "stale", "findings"}
    where `findings` holds the NEW (un-baselined) findings as dicts and
    `stale` the baseline entries no longer matched by any finding."""
    import pathlib
    baseline_path = DEFAULT_BASELINE if baseline_path is None else baseline_path
    merge_threads = (pathlib.Path(baseline_path).resolve()
                     == DEFAULT_BASELINE.resolve())
    result = run_lint([PACKAGE_DIR])
    baseline = load_baseline(baseline_path)
    if merge_threads:
        # the default run includes the DTC thread-safety rules, whose
        # grandfathered entries live in their own per-tier baseline;
        # keys cannot collide (distinct rule-id prefixes)
        baseline = {**baseline, **load_baseline(THREADCHECK_BASELINE)}
    new, stale = apply_baseline(result.findings, baseline)
    return {
        "total": len(result.findings),
        "new": len(new),
        "baselined": len(result.findings) - len(new),
        "suppressed": len(result.suppressed),
        "stale": stale,
        "findings": [f.to_dict() for f in new],
    }


def check_baseline_fresh(baseline_path=None):
    """Fail-fast guard for `python -m dedalus_tpu test`: returns a list of
    problem strings when the lint baseline is missing or stale (a stale
    entry means a grandfathered finding was fixed but the baseline was not
    regenerated — run `python -m dedalus_tpu lint --update-baseline`).
    An empty list means the baseline exists and every entry still
    matches."""
    import pathlib
    baseline_path = pathlib.Path(
        DEFAULT_BASELINE if baseline_path is None else baseline_path)
    if not baseline_path.exists():
        return [f"lint baseline missing: {baseline_path} (run "
                "`python -m dedalus_tpu lint --update-baseline`)"]
    try:
        summary = lint_package(baseline_path)
    except ValueError as exc:
        return [f"lint baseline unreadable: {baseline_path}: {exc}"]
    return [f"lint baseline stale: {e['rule']} {e['path']} "
            f"({e['snippet']!r}) no longer found — run "
            "`python -m dedalus_tpu lint --update-baseline`"
            for e in summary["stale"]]
