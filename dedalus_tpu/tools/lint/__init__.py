"""
Jit-hygiene static analysis (`python -m dedalus_tpu lint`).

The hot loop of this framework is only fast while three invariants hold:
no host round-trips inside the step path, no large host arrays inlined
into compiled program text (tools/jitlift.py exists precisely to lift
them to runtime arguments), and no post-warmup retraces. Benchmarks catch
violations hours later; this AST pass catches them at review time.

Components:
  framework.py — rule registry, findings, per-line `# dedalus-lint:
                 disable=RULE` suppressions, JSON baseline for
                 grandfathered findings, module context (import-alias
                 canonicalization + traced-function detection), parallel
                 per-file scanning.
  rules.py     — the DTL rule set (see each rule's docstring).
  progcheck.py — the SECOND tier: compiled-program contracts (DTP ids)
                 over a census of lowered step/grad/fleet programs —
                 collective placement, donation aliasing, forbidden
                 primitives, manual-region integrity
                 (`lint --programs`; baseline progcheck_baseline.json).
  cli.py       — `python -m dedalus_tpu lint [paths]`; exits nonzero on
                 findings not covered by the baseline.

The pass is self-enforcing: tests/test_lint.py runs the AST tier over
the package against the checked-in baseline (tools/lint/baseline.json)
and tests/test_progcheck.py runs the fast census subset against
progcheck_baseline.json, so tier-1 fails on any new un-baselined
violation in either source or compiled programs. The runtime complements
are the retrace sentinel (tools/retrace.py) and the opt-in `leak_check`
pytest marker (tests/conftest.py).
"""

from .framework import (DEFAULT_BASELINE, PACKAGE_DIR, Finding, LintResult,
                        Rule, all_rules, apply_baseline, baseline_rel,
                        load_baseline, make_baseline, register, run_lint)
from . import rules  # noqa: F401  (imports register the rule set)

__all__ = ["PACKAGE_DIR", "DEFAULT_BASELINE", "Finding", "LintResult",
           "Rule", "all_rules", "apply_baseline", "baseline_rel",
           "check_baseline_fresh", "lint_package", "load_baseline",
           "make_baseline", "register", "run_lint"]


def lint_package(baseline_path=None):
    """Lint the installed package tree against a baseline (default: the
    checked-in one). Returns a plain-dict summary — the programmatic
    surface used by bench.py, `python -m dedalus_tpu test`, and tests:
    {"total", "new", "baselined", "suppressed", "stale", "findings"}
    where `findings` holds the NEW (un-baselined) findings as dicts and
    `stale` the baseline entries no longer matched by any finding."""
    baseline_path = DEFAULT_BASELINE if baseline_path is None else baseline_path
    result = run_lint([PACKAGE_DIR])
    baseline = load_baseline(baseline_path)
    new, stale = apply_baseline(result.findings, baseline)
    return {
        "total": len(result.findings),
        "new": len(new),
        "baselined": len(result.findings) - len(new),
        "suppressed": len(result.suppressed),
        "stale": stale,
        "findings": [f.to_dict() for f in new],
    }


def check_baseline_fresh(baseline_path=None):
    """Fail-fast guard for `python -m dedalus_tpu test`: returns a list of
    problem strings when the lint baseline is missing or stale (a stale
    entry means a grandfathered finding was fixed but the baseline was not
    regenerated — run `python -m dedalus_tpu lint --update-baseline`).
    An empty list means the baseline exists and every entry still
    matches."""
    import pathlib
    baseline_path = pathlib.Path(
        DEFAULT_BASELINE if baseline_path is None else baseline_path)
    if not baseline_path.exists():
        return [f"lint baseline missing: {baseline_path} (run "
                "`python -m dedalus_tpu lint --update-baseline`)"]
    try:
        summary = lint_package(baseline_path)
    except ValueError as exc:
        return [f"lint baseline unreadable: {baseline_path}: {exc}"]
    return [f"lint baseline stale: {e['rule']} {e['path']} "
            f"({e['snippet']!r}) no longer found — run "
            "`python -m dedalus_tpu lint --update-baseline`"
            for e in summary["stale"]]
