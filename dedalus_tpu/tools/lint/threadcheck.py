"""
Thread-safety tier: static lock-discipline analysis (DTC rules) plus the
opt-in runtime lock-order sanitizer (`lint --threads`).

The serving stack is genuinely concurrent — per-connection reader
threads, the single executor (replaced by the watchdog on a hang), the
watchdog poll thread, the async sharded-checkpoint writer, the metrics
signal hooks — and every shipped race so far was found by hand in
review. This tier encodes those bug classes the way the DTL/DTP tiers
encode the jit-hygiene and compiled-program ones:

  DTC001 guarded-field-access — a curated lock catalog (LOCK_CATALOG)
         declares which lock guards which fields per threaded class
         (plus the module-level metrics exit-flush table and the
         cross-object accesses batching makes into the server's
         counters); any read/write of a guarded field outside a
         `with <lock>:` scope is a finding. Encodes the PR-8
         admission-reservation drift class: an unguarded `+= 1` on a
         counter bumped from reader threads, the executor, the watchdog
         and the drain sweep loses counts.
  DTC002 thread-aliased-mutation — mutations reachable from
         `Thread(target=...)` / `executor.submit(...)` callables that
         subscript-assign into producer-held mutable state. A store
         whose index derives only from the callable's own parameters is
         the legitimate disjoint-slot pattern (tools/chaos.py storm
         drivers); anything else — and ANY store into a buffer bound by
         `asarray` (a zero-copy alias) — is the PR-11 host-mirror
         aliasing class generalized: the thread rewrites value operands
         of dispatches still queued on the async stream.
  DTC003 lock-order-cycle — nested `with lockA: ... with lockB:`
         acquisition pairs are extracted lexically per module, the
         acquisition-order digraph is built globally over the threaded
         modules (plus DECLARED_EDGES for orders established across
         function boundaries), and any cycle is a potential deadlock.
         Encodes the PR-8 buffered-writer-lock-vs-watchdog pair: the
         watchdog writing the error frame shared ctx.wfile's writer
         lock with the (possibly mid-send) wedged executor.

Honesty bounds, like every tier here: the guarded-by pass is
catalog-driven (fields the catalog does not name are not checked — the
catalog at the bottom of this docstring documents the intentional
EXCLUSIONS), dynamic getattr/setattr accesses (server._count) are
invisible to it, and the lexical lock-graph misses acquisition orders
established across function calls. The runtime sanitizer is precisely
the completeness check for that last gap: `[sanitize] LOCK_ORDER = on`
(or enable_lock_order()) makes named_lock() hand out instrumented locks
that record ACTUAL acquisition edges while the service/batching/chaos
suites run; an observed edge absent from the static graph fails the
cross-validation (verify_runtime_edges). When off, named_lock returns a
plain threading.Lock — zero overhead, empty dumps.

Documented catalog exclusions (single-writer / GIL-atomic by design —
the catalog must NOT flag them; see docs/static_analysis.md):
  server._avg_run_sec        executor-only EWMA; single-word float
                             reads from reader threads are GIL-atomic
  server._draining           write-once cross-thread flag
  RunContext.last_progress   single-word float stores (faults.py
                             docstring documents the contract)
  BatchContext.seats         executor-owned; the watchdog snapshot is
                             `list(ctx.seats.values())` (C-atomic)
  dcheckpoint written/submitted/stall_sec/errors
                             single writer + GIL list append; drain
                             returns `list(self.errors)`
  tracing._recorder          intentional double-checked lazy init
                             under _recorder_lock
  metrics flush paths        read `list(_exit_solvers)` lock-free BY
                             DESIGN (signal/atexit context must not
                             block); only WRITES are guarded
                             (writes_only in the catalog)

Findings ride the shared Finding/baseline machinery under
threadcheck_baseline.json (empty on a healthy tree); the rules register
in the shared registry, so the DEFAULT `lint` run, `--rules`, `--jobs`
parallel scanning and `# dedalus-lint: disable=DTC00x` suppressions all
cover this tier. `lint --threads` additionally runs the tier standalone
with per-rule timings, the global lock graph, and `--select` rule
filtering — the shape `--programs` established.
"""

import ast
import pathlib
import threading
import time

from .framework import (Finding, ModuleContext, Rule, RULES, register,
                        apply_baseline, collect_py_files, load_baseline,
                        module_matches, name_matches, run_lint,
                        PACKAGE_DIR)

__all__ = ["LOCK_CATALOG", "THREADED_MODULES", "THREADCHECK_BASELINE",
           "DECLARED_EDGES", "static_lock_graph", "find_cycles",
           "run_threads", "named_lock", "enable_lock_order",
           "disable_lock_order", "lock_order_enabled", "observed_edges",
           "reset_observed", "held_locks_dump", "verify_runtime_edges"]

# the threadcheck tier's own grandfather baseline (empty on a healthy
# tree; waivers are baseline entries with their reason documented in
# docs/static_analysis.md)
THREADCHECK_BASELINE = PACKAGE_DIR / "tools" / "lint" / \
    "threadcheck_baseline.json"

# the modules where threads actually meet (package-relative; fixtures
# opt in by mirroring a path suffix, exactly like the DTL scopes)
THREADED_MODULES = (
    "service/server.py",
    "service/batching.py",
    "service/faults.py",
    "service/pool.py",
    "service/router.py",
    "service/fleet.py",
    "tools/dcheckpoint.py",
    "tools/tracing.py",
    "tools/metrics.py",
    "tools/chaos.py",
)


class GuardSpec:
    """One lock -> guarded-fields declaration in the catalog.

    cls is None for module-level globals (the metrics exit-flush table);
    `aliases` are context-manager attributes that acquire the SAME lock
    (the checkpointer's Conditions constructed on _lock); `held_methods`
    are methods documented "caller holds the lock" (checked at their
    call sites' enclosing scopes, not inside); `writes_only` restricts
    the check to mutations (lock-free reads are part of the design —
    metrics flush paths must not block in signal context)."""

    __slots__ = ("module", "cls", "lock", "fields", "aliases",
                 "held_methods", "writes_only", "exempt")

    def __init__(self, module, cls, lock, fields, aliases=(),
                 held_methods=(), writes_only=False, exempt=()):
        self.module = module
        self.cls = cls
        self.lock = lock
        self.fields = frozenset(fields)
        self.aliases = frozenset(aliases)
        self.held_methods = frozenset(held_methods)
        self.writes_only = writes_only
        # methods where unguarded access is part of the contract
        # (constructors bind fields before any thread exists)
        self.exempt = frozenset(exempt) | {"__init__", "__del__"}

    def lock_id(self):
        owner = self.cls if self.cls else ""
        return f"{self.module}:{owner + '.' if owner else ''}{self.lock}"


LOCK_CATALOG = (
    # server: request accounting. Bumped from reader threads, the
    # executor, the watchdog and the drain sweep; server.py documents
    # the contract at the _counters_lock binding.
    GuardSpec("service/server.py", "SolverService", "_counters_lock",
              fields=("requests_served", "errors", "shed",
                      "deadline_exceeded", "watchdog_fires",
                      "client_drops", "mem_evictions", "error_codes",
                      "_queued_runs", "_request_seq", "hists")),
    # server: the active-run handoff between executor and watchdog
    GuardSpec("service/server.py", "SolverService", "_active_lock",
              fields=("_active_run",)),
    # batching: dispatcher stats vs executor mutation
    GuardSpec("service/batching.py", "BatchDispatcher", "_lock",
              fields=("batches", "members_seated", "late_joins",
                      "blocks", "detached", "peak_members",
                      "batch_events", "_batch_seq")),
    # faults: breaker key table (readers admit, the executor records)
    GuardSpec("service/faults.py", "CircuitBreaker", "_lock",
              fields=("_keys", "opens", "fastfails", "closes"),
              held_methods=("_entry",)),
    # faults: result-cache LRU (readers replay, the executor stores)
    GuardSpec("service/faults.py", "ResultCache", "_lock",
              fields=("_entries", "_bytes", "replays")),
    # router: relay accounting. Bumped from per-connection handler
    # threads, read by stats()/prom_text() from other handler threads;
    # router.py documents the tight-block contract at the _lock binding.
    GuardSpec("service/router.py", "RouterService", "_lock",
              fields=("forwarded", "failovers", "shed", "refusals",
                      "replica_faults", "client_drops",
                      "acks_suppressed", "error_codes", "hists")),
    # fleet: the replica table and supervision counters. Mutated by the
    # prober thread's verdict fold and the restart path, read by
    # routing (routable/endpoint) from every handler thread.
    GuardSpec("service/fleet.py", "ReplicaSupervisor", "_lock",
              fields=("_replicas", "restarts_total", "crashes_detected",
                      "wedges_detected", "watchdog_fires_total")),
    # pool: bookkeeping dicts read by stats() from reader threads
    GuardSpec("service/pool.py", "SolverPool", "_lock",
              fields=("_entries", "_aliases", "hits", "misses",
                      "evictions", "resets"),
              held_methods=("_evict", "_remove", "_pop_lru")),
    # checkpointer: the in-flight budget both Conditions wait on
    GuardSpec("tools/dcheckpoint.py", "ShardedCheckpointer", "_lock",
              fields=("_pending", "_closed"),
              aliases=("_not_full", "_drained")),
    # tracing: the process-wide span ring
    GuardSpec("tools/tracing.py", "TraceRecorder", "_lock",
              fields=("_spans", "_next_id")),
    # metrics: exit-flush registration table (module-level). WRITES
    # only: the flush paths read lock-free by design (signal context).
    GuardSpec("tools/metrics.py", None, "_exit_lock",
              fields=("_exit_solvers", "_signal_previous"),
              writes_only=True),
)

# cross-object accesses: batching reaches into the server's guarded
# counters as `svc.<field>`; the required lock is `svc.<lock>` (same
# base name). Keyed by field name — these names are unambiguous across
# the tiered modules.
FOREIGN_GUARDS = {
    "_queued_runs": ("_counters_lock", "SolverService"),
    "_request_seq": ("_counters_lock", "SolverService"),
    "error_codes": ("_counters_lock", "SolverService"),
    "_active_run": ("_active_lock", "SolverService"),
}

# acquisition orders established ACROSS function boundaries, which the
# lexical extractor cannot see. Curated, with the establishing call
# path as the reason; the runtime sanitizer's cross-validation is what
# keeps this list honest (an observed edge missing here AND from the
# lexical graph fails verify_runtime_edges). Empty on HEAD: every
# `with lock:` block in the tiered modules is tight — snapshots are
# taken under one lock and cross-object stats calls happen outside it
# (see SolverService.stats), so the service acquisition graph has no
# edges at all.
DECLARED_EDGES = ()

# method calls that mutate their receiver (the write-detection set for
# guarded container fields)
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "popitem", "clear", "update", "setdefault", "add",
    "discard", "move_to_end",
})

# with-item names recognized as lock acquisitions by DTC003 even
# without "lock" in the name (Conditions constructed on a lock)
_CONDITION_NAMES = frozenset({"_not_full", "_drained"})


def _threaded(ctx):
    return module_matches(ctx.rel, THREADED_MODULES)


def _module_key(ctx):
    """The THREADED_MODULES entry this file is (or mirrors — fixtures
    opt in by path suffix); its own rel path otherwise."""
    for mod in THREADED_MODULES:
        if module_matches(ctx.rel, (mod,)):
            return mod
    return ctx.rel


def _enclosing_class(ctx, node):
    cur = ctx.parent(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        cur = ctx.parent(cur)
    return None


def _is_writeish(ctx, node):
    """Whether an Attribute/Name access mutates the guarded object:
    direct (re)bind, subscript store/del, augmented assign, or a
    mutating method call on it."""
    if isinstance(getattr(node, "ctx", None), (ast.Store, ast.Del)):
        return True
    parent = ctx.parent(node)
    if isinstance(parent, ast.Subscript) \
            and isinstance(parent.ctx, (ast.Store, ast.Del)):
        return True
    if isinstance(parent, ast.Attribute) and parent.attr in _MUTATORS:
        grand = ctx.parent(parent)
        if isinstance(grand, ast.Call) and grand.func is parent:
            return True
    return False


def _guarded_by(ctx, node, lock_names, base):
    """Whether `node` sits inside a `with <base>.<lock>:` (attribute
    locks) or `with <lock>:` (module-level locks, base=None) for any
    name in `lock_names`."""
    cur = node
    while cur is not None:
        parent = ctx.parent(cur)
        if isinstance(parent, (ast.With, ast.AsyncWith)):
            for item in parent.items:
                expr = item.context_expr
                if base is None:
                    if isinstance(expr, ast.Name) and expr.id in lock_names:
                        return True
                elif isinstance(expr, ast.Attribute) \
                        and expr.attr in lock_names \
                        and isinstance(expr.value, ast.Name) \
                        and expr.value.id == base:
                    return True
        cur = parent
    return False


# ------------------------------------------------------------------ DTC001

@register
class GuardedFieldAccess(Rule):
    """Guarded-by checker: reads/writes of catalog-guarded fields
    outside their declaring `with <lock>:` scope. The lock catalog
    (LOCK_CATALOG) declares, per threaded class, which lock guards
    which fields — e.g. SolverService._counters_lock guards the
    per-error-code counters and the admission reservation, the batch
    dispatcher's _lock guards the seat-accounting tables, the sharded
    checkpointer's _lock guards the in-flight budget its Conditions
    wait on. Cross-object accesses (batching reading svc._queued_runs)
    check against FOREIGN_GUARDS with the same base name. Constructors
    and documented caller-holds-the-lock helpers are exempt; catalog
    entries marked writes_only check mutations only (metrics flush
    paths read lock-free in signal context by design). Dynamic
    getattr/setattr accesses (server._count) are invisible to this
    pass — they already take the lock inside."""

    id = "DTC001"
    severity = "error"
    title = "guarded-field-access"

    def check(self, ctx):
        if not _threaded(ctx):
            return
        specs = [s for s in LOCK_CATALOG
                 if module_matches(ctx.rel, (s.module,))]
        class_specs = {}
        for s in specs:
            if s.cls:
                class_specs.setdefault(s.cls, []).append(s)
        module_specs = [s for s in specs if s.cls is None]
        foreign_fields = frozenset(FOREIGN_GUARDS)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name):
                base = node.value.id
                if base in ("self", "cls"):
                    cls = _enclosing_class(ctx, node)
                    for spec in class_specs.get(cls.name, ()) if cls else ():
                        if node.attr in spec.fields:
                            f = self._check_access(ctx, node, spec, base)
                            if f is not None:
                                yield f
                elif node.attr in foreign_fields:
                    lock, owner = FOREIGN_GUARDS[node.attr]
                    fn = ctx.enclosing_function(node)
                    if fn is not None and fn.name in ("__init__",):
                        continue
                    if not _guarded_by(ctx, node, {lock}, base):
                        yield self.finding(
                            ctx, node,
                            f"guarded field `{base}.{node.attr}` "
                            f"accessed outside `with {base}.{lock}:` "
                            f"({owner} lock catalog; cross-object "
                            "access)")
            elif isinstance(node, ast.Name):
                for spec in module_specs:
                    if node.id in spec.fields:
                        f = self._check_access(ctx, node, spec, None)
                        if f is not None:
                            yield f

    def _check_access(self, ctx, node, spec, base):
        fn = ctx.enclosing_function(node)
        if fn is None:
            # module-scope / class-scope statements run before any
            # second thread exists (initial bindings)
            return None
        if fn.name in spec.exempt or fn.name in spec.held_methods:
            return None
        if spec.writes_only and not _is_writeish(ctx, node):
            return None
        locks = {spec.lock} | spec.aliases
        if _guarded_by(ctx, node, locks, base):
            return None
        name = node.attr if base else node.id
        hold = f"{base}.{spec.lock}" if base else spec.lock
        verb = "mutated" if _is_writeish(ctx, node) else "read"
        owner = spec.cls or pathlib.PurePosixPath(spec.module).name
        return self.finding(
            ctx, node,
            f"guarded field `{name}` {verb} outside `with {hold}:` "
            f"({owner} lock catalog)")


# ------------------------------------------------------------------ DTC002

@register
class ThreadAliasedMutation(Rule):
    """Thread-aliasing checker: a callable handed to
    `threading.Thread(target=...)` or `executor.submit(...)` that
    subscript-assigns into a variable it does not own (free in the
    callable — producer-held mutable state). The legitimate pattern is
    a disjoint-slot store whose index derives ONLY from the callable's
    own parameters (the chaos storm drivers' `results[i] = out`);
    stores with any other index provenance race their siblings, and
    stores into a buffer bound via `asarray` are the PR-11 host-mirror
    aliasing class regardless of index — the zero-copy alias rewrites
    value operands of dispatches still queued on the async stream."""

    id = "DTC002"
    severity = "error"
    title = "thread-aliased-mutation"

    def check(self, ctx):
        if not _threaded(ctx):
            return
        targets = self._thread_targets(ctx)
        if not targets:
            return
        aliased = self._asarray_bound(ctx)
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or fn.name not in targets:
                continue
            params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                      + fn.args.kwonlyargs)}
            if fn.args.vararg:
                params.add(fn.args.vararg.arg)
            if fn.args.kwarg:
                params.add(fn.args.kwarg.arg)
            owned = params | self._local_binds(fn)
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Subscript)
                        and isinstance(node.ctx, (ast.Store, ast.Del))
                        and isinstance(node.value, ast.Name)):
                    continue
                name = node.value.id
                if name in owned:
                    continue
                index_names = {n.id for n in ast.walk(node.slice)
                               if isinstance(n, ast.Name)}
                if name in aliased:
                    yield self.finding(
                        ctx, node,
                        f"thread callable `{fn.name}` mutates "
                        f"`{name}[...]`, which aliases device/host "
                        "state via asarray (zero-copy): the store can "
                        "rewrite value operands of dispatches still "
                        "queued on the async stream (PR-11 class); "
                        "bind by copy instead")
                elif not index_names or not index_names <= params:
                    yield self.finding(
                        ctx, node,
                        f"thread callable `{fn.name}` mutates "
                        f"producer-held `{name}[...]` without a "
                        "disjoint-index contract (index not derived "
                        "from the callable's own parameters): "
                        "concurrent workers race the slot")

    @staticmethod
    def _thread_targets(ctx):
        """Names of plain functions entered by Thread(target=...) or
        pool.submit(fn, ...). Bound methods (self._worker) resolve to
        class scope, which DTC001's catalog covers instead."""
        names = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            canon = ctx.canon(node.func)
            if canon is not None and name_matches(canon,
                                                  "threading.Thread",
                                                  "Thread"):
                for kw in node.keywords:
                    if kw.arg == "target" and isinstance(kw.value, ast.Name):
                        names.add(kw.value.id)
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "submit" and node.args \
                    and isinstance(node.args[0], ast.Name):
                names.add(node.args[0].id)
        return names

    @staticmethod
    def _local_binds(fn):
        """Names the callable itself binds (stores, for/with targets):
        mutations of its OWN state are not aliasing."""
        owned = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                owned.add(node.id)
        return owned

    @staticmethod
    def _asarray_bound(ctx):
        """Module variables bound to an `asarray(...)` result — zero-
        copy aliases of their operand."""
        aliased = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                canon = ctx.canon(node.value.func)
                if canon is not None and name_matches(canon, "asarray"):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            aliased.add(t.id)
        return aliased


# ------------------------------------------------------------------ DTC003

def _lockish(expr):
    """Whether a with-item expression acquires a lock: a Name/Attribute
    whose terminal name smells like a lock (or is a known Condition
    constructed on one). Calls (`with _socket_deadline(...)`) are
    context managers, not lock acquisitions."""
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    else:
        return False
    return "lock" in name.lower() or name in _CONDITION_NAMES


def _canon_lock(ctx, expr, modkey):
    """Canonical lock identity `module:Class.attr` (or `module:name`
    for module-level locks). `self.X` resolves Condition aliases
    through the catalog; a foreign `other.X` resolves to its owning
    catalog entry when the attr names exactly one cataloged lock
    (svc._counters_lock -> the server's)."""
    if isinstance(expr, ast.Name):
        return f"{modkey}:{expr.id}"
    attr = expr.attr
    if isinstance(expr.value, ast.Name) and expr.value.id in ("self", "cls"):
        cls = _enclosing_class(ctx, expr)
        cls_name = cls.name if cls else "?"
        for spec in LOCK_CATALOG:
            if spec.cls == cls_name and attr in spec.aliases \
                    and module_matches(ctx.rel, (spec.module,)):
                attr = spec.lock
                break
        return f"{modkey}:{cls_name}.{attr}"
    owners = [s for s in LOCK_CATALOG if s.cls and s.lock == attr]
    if len(owners) == 1:
        return owners[0].lock_id()
    base = expr.value.id if isinstance(expr.value, ast.Name) else "?"
    return f"{modkey}:{base}.{attr}"


def _module_edges(ctx):
    """Lexical acquisition-order edges in one module: for every lock
    acquired while another is (lexically) held — nested `with` blocks
    and multi-item `with A, B:` — yield (held, acquired, node)."""
    modkey = _module_key(ctx)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        held = []
        cur = ctx.parent(node)
        while cur is not None:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    if _lockish(item.context_expr):
                        held.append(_canon_lock(ctx, item.context_expr,
                                                modkey))
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                break   # lexical holding does not cross a def boundary
            cur = ctx.parent(cur)
        for item in node.items:
            if not _lockish(item.context_expr):
                continue
            acquired = _canon_lock(ctx, item.context_expr, modkey)
            for h in held:
                yield h, acquired, node
            held.append(acquired)   # `with A, B:` orders A before B


def find_cycles(edges):
    """Cycles in an acquisition-order digraph (edge iterable of (src,
    dst) pairs): Tarjan SCCs of size > 1, plus self-loops (a
    non-reentrant lock re-acquired under itself deadlocks outright).
    Returns a list of node lists."""
    graph = {}
    for src, dst in edges:
        graph.setdefault(src, set()).add(dst)
        graph.setdefault(dst, set())
    index = {}
    low = {}
    stack = []
    on_stack = set()
    cycles = []
    counter = [0]

    def strongconnect(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in sorted(graph[v]):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                cycles.append(sorted(comp))
            elif v in graph[v]:
                cycles.append([v])

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return cycles


@register
class LockOrderCycle(Rule):
    """Lock-order analysis: nested `with lockA: ... with lockB:`
    acquisition pairs are extracted lexically (including multi-item
    `with A, B:`), and any cycle in the module's acquisition-order
    digraph is a potential deadlock. Encodes the PR-8 buffered-writer-
    lock-vs-watchdog pair: the watchdog's error write shared ctx.wfile's
    writer lock with the wedged executor's mid-send — two threads
    acquiring the same two locks in opposite orders. The module-local
    pass runs per file; `lint --threads` additionally builds the GLOBAL
    graph across the tiered modules (plus DECLARED_EDGES for orders
    established through function calls) and the runtime sanitizer
    cross-validates it against acquisition edges observed live."""

    id = "DTC003"
    severity = "error"
    title = "lock-order-cycle"

    def check(self, ctx):
        if not _threaded(ctx):
            return
        edges = {}
        for src, dst, node in _module_edges(ctx):
            edges.setdefault((src, dst), node)
        for cycle in find_cycles(edges):
            involved = {(s, d): n for (s, d), n in edges.items()
                        if s in cycle and d in cycle}
            node = min(involved.values(), key=lambda n: n.lineno)
            path = " -> ".join(cycle + [cycle[0]])
            sites = ", ".join(
                f"{s}->{d} at line {n.lineno}"
                for (s, d), n in sorted(involved.items(),
                                        key=lambda kv: kv[1].lineno))
            yield self.finding(
                ctx, node,
                f"lock-order cycle (potential deadlock): {path}; "
                f"acquisition sites: {sites}")


DTC_RULE_IDS = ("DTC001", "DTC002", "DTC003")


# ------------------------------------------------------- the global graph

def static_lock_graph(paths=None):
    """The global acquisition-order digraph: lexical edges over the
    threaded modules (or explicit `paths`) plus DECLARED_EDGES.
    Returns {"edges": {(src, dst): [site, ...]}, "cycles": [...]}."""
    if paths is None:
        files = [PACKAGE_DIR / m for m in THREADED_MODULES
                 if (PACKAGE_DIR / m).exists()]
    else:
        files = collect_py_files(paths)
    edges = {}
    for path in files:
        try:
            ctx = ModuleContext(path, path.read_text())
        except (OSError, SyntaxError, ValueError):
            continue   # DTC runs through run_lint surface DTL000 there
        for src, dst, node in _module_edges(ctx):
            edges.setdefault((src, dst), []).append(
                f"{ctx.rel}:{node.lineno}")
    for src, dst, reason in DECLARED_EDGES:
        edges.setdefault((src, dst), []).append(f"declared: {reason}")
    return {"edges": edges, "cycles": find_cycles(edges)}


def run_threads(paths=None, rule_ids=None, baseline_path=None,
                no_baseline=False, jobs=None):
    """The --threads tier runner: the DTC rules over the threaded-module
    set (or explicit paths) with per-rule timings, plus the global
    lock-order graph. Report mirrors run_programs: {"modules", "graph",
    "findings" (new only), "summary", "timings"}."""
    for rid in rule_ids or ():
        if rid not in RULES or not rid.startswith("DTC"):
            raise KeyError(f"unknown DTC rule id {rid!r}; known: "
                           f"{list(DTC_RULE_IDS)}")
    rules = [RULES[r] for r in (rule_ids or DTC_RULE_IDS)]
    if paths is None:
        files = [PACKAGE_DIR / m for m in THREADED_MODULES
                 if (PACKAGE_DIR / m).exists()]
    else:
        files = collect_py_files(paths)
    findings, suppressed = [], []
    rule_timings = {}
    for rule in rules:
        t0 = time.perf_counter()
        result = run_lint(files, rules=[rule], jobs=jobs)
        rule_timings[rule.id] = round(time.perf_counter() - t0, 3)
        findings.extend(f for f in result.findings
                        if f.rule != "DTL000" or rule is rules[0])
        suppressed.extend(result.suppressed)
    t0 = time.perf_counter()
    graph = static_lock_graph(paths)
    # the per-module DTC003 pass already reported single-module cycles;
    # the global graph adds cross-module + declared-edge cycles
    global_findings = []
    for cycle in graph["cycles"]:
        modules = {n.split(":", 1)[0] for n in cycle}
        declared = any((s, d) in graph["edges"]
                       and any(site.startswith("declared:")
                               for site in graph["edges"][(s, d)])
                       for s in cycle for d in cycle)
        if len(modules) > 1 or declared:
            path = " -> ".join(cycle + [cycle[0]])
            global_findings.append(Finding(
                "DTC003", "error", "__locks__/graph", 1, 0,
                f"global lock-order cycle (potential deadlock): {path}",
                path))
    findings.extend(global_findings)
    rule_timings["lock-graph"] = round(time.perf_counter() - t0, 3)

    baseline_path = THREADCHECK_BASELINE if baseline_path is None \
        else pathlib.Path(baseline_path)
    baseline = {} if no_baseline else load_baseline(baseline_path)
    new, stale = apply_baseline(findings, baseline)
    # a subset run (rule filter or explicit paths) leaves out-of-scope
    # baseline entries unmatched by construction, not fixed
    if rule_ids or paths is not None:
        stale = []
    return {
        "modules": [str(f) for f in files],
        "graph": {
            "edges": [{"src": s, "dst": d, "sites": sites}
                      for (s, d), sites in sorted(graph["edges"].items())],
            "cycles": graph["cycles"],
        },
        "findings": [f.to_dict() for f in new],
        "summary": {
            "total": len(findings),
            "new": len(new),
            "baselined": len(findings) - len(new),
            "suppressed": len(suppressed),
            "stale": stale,
            "edges": len(graph["edges"]),
            "cycles": len(graph["cycles"]),
        },
        "timings": {"rules": rule_timings},
    }, findings


# ------------------------------------------------- runtime lock sanitizer
#
# Opt-in ([sanitize] LOCK_ORDER, or enable_lock_order() BEFORE the
# instrumented objects construct): named_lock() hands out wrapped locks
# that record actual acquisition edges + per-thread held/waiting state.
# Off (the default), named_lock returns a plain threading.Lock — the
# hot path pays nothing and the dumps are empty.

_san_lock = threading.Lock()    # guards the sanitizer's OWN tables
_observed = {}                  # (src, dst) -> acquisition count
_held = {}                      # thread ident -> [lock names]
_waiting = {}                   # thread ident -> lock name
_enabled_override = None


def lock_order_enabled():
    if _enabled_override is not None:
        return _enabled_override
    from ..config import cfg_get
    return str(cfg_get("sanitize", "LOCK_ORDER", "off")).strip().lower() \
        in ("1", "true", "yes", "on")


def enable_lock_order():
    """Turn the sanitizer on for locks constructed AFTER this call
    (tests enable it before building the service)."""
    global _enabled_override
    _enabled_override = True


def disable_lock_order():
    global _enabled_override
    _enabled_override = False


def named_lock(name):
    """A lock with a canonical identity (`module:Class.attr`, matching
    the static graph's node ids). Plain threading.Lock when the
    sanitizer is off — zero overhead; instrumented otherwise."""
    if lock_order_enabled():
        return _SanitizedLock(name)
    return threading.Lock()


class _SanitizedLock:
    """threading.Lock wrapper recording acquisition-order edges and
    per-thread held/waiting state. Condition-compatible: it exposes
    only acquire/release/__enter__/__exit__/locked, so
    threading.Condition(lock) falls back to its own default
    _release_save/_acquire_restore/_is_owned built on those."""

    __slots__ = ("name", "_lock")

    def __init__(self, name):
        self.name = str(name)
        self._lock = threading.Lock()

    def acquire(self, blocking=True, timeout=-1):
        ident = threading.get_ident()
        if blocking:
            with _san_lock:
                _waiting[ident] = self.name
        ok = self._lock.acquire(blocking, timeout)
        with _san_lock:
            _waiting.pop(ident, None)
            if ok:
                stack = _held.setdefault(ident, [])
                for h in stack:
                    if h != self.name:
                        _observed[(h, self.name)] = \
                            _observed.get((h, self.name), 0) + 1
                stack.append(self.name)
        return ok

    def release(self):
        self._lock.release()
        ident = threading.get_ident()
        with _san_lock:
            stack = _held.get(ident)
            if stack:
                for i in range(len(stack) - 1, -1, -1):
                    if stack[i] == self.name:
                        del stack[i]
                        break
                if not stack:
                    _held.pop(ident, None)

    def locked(self):
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return True

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        state = "locked" if self._lock.locked() else "unlocked"
        return f"<_SanitizedLock {self.name} {state}>"


def observed_edges():
    """The acquisition edges recorded since the last reset: a set of
    (held, acquired) canonical-name pairs."""
    with _san_lock:
        return set(_observed)


def reset_observed():
    with _san_lock:
        _observed.clear()


def held_locks_dump():
    """Per-thread held/waiting lock names, for the watchdog postmortem:
    {thread_name: {"held": [...], "waiting": name-or-None}}. Empty when
    the sanitizer is off (nothing was ever recorded)."""
    with _san_lock:
        held = {ident: list(stack) for ident, stack in _held.items()
                if stack}
        waiting = dict(_waiting)
    if not held and not waiting:
        return {}
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident in sorted(set(held) | set(waiting), key=str):
        out[str(names.get(ident, ident))] = {
            "held": held.get(ident, []),
            "waiting": waiting.get(ident),
        }
    return out


def verify_runtime_edges(observed=None, static=None):
    """Cross-validation: observed acquisition edges that the static
    graph (lexical + declared) does not contain — the analyzer's own
    completeness check. Returns the sorted list of missing (src, dst)
    pairs; empty means every live acquisition order was statically
    visible."""
    if observed is None:
        observed = observed_edges()
    if static is None:
        static = static_lock_graph()
    return sorted(set(observed) - set(static["edges"]))
