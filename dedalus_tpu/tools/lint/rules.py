"""
The DTL rule set. Every rule is grounded in a hazard this codebase has
actually hit (see docstrings); each documents its heuristic boundaries so
a quiet pass is never mistaken for a proof.

Scopes:
  HOT_PATH_MODULES     — the step-loop modules where a stray host sync
                         serializes the dispatch pipeline every iteration.
  TRACED_CONTEXT_MODULES — device math libraries whose functions run under
                         jit via the transform/solve call graph even though
                         no jit wrapper appears in-module (static tracing
                         detection cannot see through the call graph, so
                         these are declared).
  FUNNEL_MODULES       — the sanctioned precision/constant funnels; exempt
                         from DTL002 (they ARE the device_constant route).
"""

import ast

from .framework import Rule, register, name_matches, module_matches

HOT_PATH_MODULES = (
    "core/timesteppers.py",
    "core/ddstep.py",
    "libraries/pencilops.py",
    "parallel/transposes.py",
    # the resilient loop brackets every step: a stray sync here (the
    # shipped case: Snapshot.is_finite gathering the full state per
    # capture validation) stalls the same pipeline the step modules do
    "tools/resilience.py",
    # the continuous-batching dispatcher brackets every fleet block: a
    # stray host sync between boundaries serializes the whole batch's
    # dispatch pipeline (member IO belongs in core/ensemble seat APIs,
    # reply-phase IO after the boundary probe)
    "service/batching.py",
    # the fused-step module's grid_eval / pallas kernels compile into the
    # step program through the evaluator call graph (no in-module jit
    # wrapper for the structural pass to see) — a stray sync here lands
    # inside every fused step
    "core/fusedstep.py",
    # the restructured-substitution programs (associative-scan prefix,
    # SPIKE chunk solves, the precision-ladder refinement) trace into
    # every fused solve through BandedOps/DenseOps — same exposure as
    # pencilops itself
    "libraries/solvecomp.py",
    # request tracing brackets every step/request phase by contract as
    # HOST-ONLY bookkeeping (docs/observability.md): a device gather or
    # block_until_ready smuggled into a span helper would charge every
    # instrumented phase a sync and break the <1% overhead budget
    "tools/tracing.py",
    # chaos hooks wrap step/IO callables IN PLACE on the hot loop: a
    # fault injector that gathers state to decide whether to fire would
    # charge every un-faulted step the sync the suite exists to forbid
    "tools/chaos.py",
    # spec digesting + IC decoding run per request on the serving path;
    # result encoding is the one place device arrays legitimately land
    # on the host, but it must do so ONCE (explicitly), not via stray
    # per-field syncs smuggled into validation helpers
    "service/protocol.py",
    # the router relays every served frame and the supervisor probes
    # every replica each probe tick: both are pure host/socket plumbing
    # by contract — any device call here would charge every forwarded
    # request (or every health probe) a sync it has no business paying
    "service/router.py",
    "service/fleet.py",
    # the autotuner's consult runs inside every solver build and its
    # decision feeds the plan the step program compiles under: config
    # must be read at build/CLI time only (DTL008 — a tuned step that
    # re-read [autotune] per step would retrace), and the microbench
    # harness synchronizes via explicit np.asarray host gathers on
    # probe results, never via stray syncs a step path could inherit
    "tools/autotune.py",
)

# Device-state attribute names (the gathered pencil/fleet state and its
# companions). By codebase contract these attributes hold jax device
# arrays; `np.asarray` of one is a full device->host gather.
STATE_ARRAY_ATTRS = frozenset({
    "X", "dd_X", "T", "DT", "F_hist", "MX_hist", "LX_hist",
})

TRACED_CONTEXT_MODULES = (
    "core/transforms.py",
    "core/weighted_jacobi.py",
    "libraries/pencilops.py",
    "libraries/matsolvers.py",
    "libraries/solvecomp.py",
    "libraries/sphere.py",
    "libraries/zernike.py",
    "libraries/spin_intertwiners.py",
)

FUNNEL_MODULES = (
    "tools/array.py",
    "tools/jitlift.py",
)

STEP_BODY_MODULES = (
    "core/timesteppers.py",
    "core/ddstep.py",
)


def _contains_jax_call(ctx, node):
    """Whether the expression contains a call into jax/jax.numpy."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = ctx.canon(sub.func)
            if name is not None and (name.startswith("jax.")
                                     or name == "jax"):
                return True
    return False


@register
class HostSyncInHotPath(Rule):
    """DTL001: host synchronization in the step loop.

    JAX dispatch is asynchronous; `.item()`, `float()/int()` of a device
    value, `np.asarray()` of a tracer, and `block_until_ready` each force
    the host to wait on the device (or worse, bake a sync into every
    iteration), which serializes the dispatch pipeline the whole metrics
    subsystem was built to keep clean (tools/metrics.py module docstring).
    The only sanctioned blocking is the cadence-gated sampler in
    tools/metrics.py — which is outside this rule's scope by construction.

    Heuristics: fires in HOT_PATH_MODULES (whole file) and inside traced
    functions anywhere. `float()/int()` only flag when the argument
    contains a jax/jnp call (`float(dt)` on host scalars is fine);
    `np.asarray/np.array` only flag bare-Name arguments inside traced code
    (attribute chains like `scheme.A` are host tableau constants).
    Additionally, anywhere in HOT_PATH_MODULES, `np.asarray/np.array`
    of a STATE-array attribute (`.X`, `.F_hist`, ... — device arrays by
    codebase contract, see STATE_ARRAY_ATTRS) with no dtype= flags as a
    full device->host state gather: the shipped case was
    `np.all(np.isfinite(np.asarray(self.X)))` in the snapshot-capture
    validation (tools/resilience.py), fixed by routing through the
    HealthMonitor's fused device-side probe.
    """

    id = "DTL001"
    severity = "error"
    title = "host-sync-in-hot-path"

    def check(self, ctx):
        hot = module_matches(ctx.rel, HOT_PATH_MODULES)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            in_scope = hot or ctx.in_traced(node)
            if not in_scope:
                continue
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr == "item" \
                    and not node.args:
                yield self.finding(
                    ctx, node, ".item() forces a device->host sync in the "
                    "hot path; keep reductions on device or move the read "
                    "behind a metrics/health cadence gate")
                continue
            if (isinstance(func, ast.Attribute)
                    and func.attr == "block_until_ready") or (
                    (name := ctx.canon(func)) is not None
                    and name_matches(name, "jax.block_until_ready")):
                yield self.finding(
                    ctx, node, "block_until_ready in the hot path "
                    "serializes the dispatch pipeline; only the "
                    "cadence-gated sampler in tools/metrics.py may block")
                continue
            name = ctx.canon(func)
            if name in ("float", "int") and node.args \
                    and _contains_jax_call(ctx, node.args[0]):
                yield self.finding(
                    ctx, node, f"{name}() of a jax expression synchronously "
                    "pulls the value to host; keep the computation on "
                    "device or sample it behind a cadence gate")
                continue
            # exact match: suffix-tolerant matching would also catch
            # jax.numpy.asarray, which is the trace-safe spelling
            if name in ("numpy.asarray", "numpy.array") \
                    and node.args and isinstance(node.args[0], ast.Name) \
                    and ctx.in_traced(node):
                yield self.finding(
                    ctx, node, f"{name.split('.')[-1]}() on a local inside "
                    "traced code concretizes a tracer (host sync or trace "
                    "error); use jnp, or hoist host work out of the trace")
                continue
            # state-attribute gather: np.asarray(self.X) and friends in a
            # hot module is a full device->host transfer of the pencil/
            # fleet state (dtype= marks a deliberate host conversion of
            # host-side data and is exempt, matching DTL002's convention)
            if hot and name in ("numpy.asarray", "numpy.array") \
                    and node.args \
                    and isinstance(node.args[0], ast.Attribute) \
                    and node.args[0].attr in STATE_ARRAY_ATTRS \
                    and len(node.args) < 2 \
                    and not any(kw.arg == "dtype" for kw in node.keywords):
                yield self.finding(
                    ctx, node, f"{name.split('.')[-1]}() of the device "
                    f"state attribute .{node.args[0].attr} gathers the "
                    "full state to host; use the HealthMonitor fused "
                    "probe (nonfinite_count) or a jitted device-side "
                    "reduction with a scalar pull instead")


@register
class InlinedDeviceConstant(Rule):
    """DTL002: host array inlined into compiled program text.

    This JAX version inlines every non-splat array constant into the
    lowered MLIR — a 100 MB transform stack adds ~400 MB of program text,
    and spectral kernels are built from exactly such constants
    (tools/jitlift.py module docstring; the multi-GB programs that
    motivated lifted_jit). Host matrices entering traced code must route
    through tools.jitlift.device_constant (directly or via the
    tools.array.match_precision funnel) so they become runtime ARGUMENTS.

    Heuristic: flags `jnp.asarray(x)` / `jnp.array(x)` where x is a bare
    Name or attribute chain and no dtype= is given, inside traced
    functions anywhere plus anywhere in TRACED_CONTEXT_MODULES (device
    libraries reached under jit through the call graph). Calls that pass
    dtype= are the deliberate small-scalar/coefficient conversions the
    step path makes (e.g. `jnp.asarray(a, dtype=rd)`); the bare no-dtype
    form is the "just ship the matrix" pattern that inlines (the shipped
    case: core/weighted_jacobi.py's radial matmul before it was routed
    through the funnel).
    """

    id = "DTL002"
    severity = "error"
    title = "inlined-device-constant"

    def check(self, ctx):
        if module_matches(ctx.rel, FUNNEL_MODULES):
            return
        lib = module_matches(ctx.rel, TRACED_CONTEXT_MODULES)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.canon(node.func)
            if name is None or not name_matches(
                    name, "jax.numpy.asarray", "jax.numpy.array"):
                continue
            # a dtype argument (kwarg or positional) marks the deliberate
            # scalar/coefficient conversions of the step path
            if len(node.args) >= 2 \
                    or any(kw.arg == "dtype" for kw in node.keywords):
                continue
            if not node.args or not isinstance(node.args[0],
                                               (ast.Name, ast.Attribute)):
                continue
            if lib or ctx.in_traced(node):
                yield self.finding(
                    ctx, node, "host array converted in traced context is "
                    "inlined into program text; route it through "
                    "tools.jitlift.device_constant (or the "
                    "tools.array.match_precision funnel) so it becomes a "
                    "runtime argument")


@register
class JitInCallPath(Rule):
    """DTL003: jit wrapper constructed inside a call path.

    `jax.jit` / `lifted_jit` build a fresh trace cache per wrapper object:
    constructing one inside a function that runs per step (or per solve)
    retraces and recompiles on every call — the program-cache equivalent
    of a host sync, and it also defeats lifted_jit's constant interning.
    Wrappers belong at module scope, in `__init__`, or memoized.

    Heuristic: flags jit/lifted_jit calls (including
    functools.partial(jax.jit, ...) used as a decorator) lexically inside
    a function body, EXCEPT inside `__init__` and except when the result
    is stored to `self.<attr>` or into a subscripted cache (both memoized-
    once patterns used across this codebase). Hand-rolled `if cache is
    None` guards around a plain local are invisible to this pass — carry
    a suppression comment naming the cache.
    """

    id = "DTL003"
    severity = "error"
    title = "jit-in-call-path"

    def _exempt_assignment(self, ctx, node):
        """Whether the jit call's value lands in a memoized slot."""
        cur = node
        parent = ctx.parent(cur)
        while parent is not None and not isinstance(parent, ast.stmt):
            cur, parent = parent, ctx.parent(parent)
        if isinstance(parent, ast.Assign):
            return any(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in parent.targets)
        if isinstance(parent, (ast.AnnAssign, ast.AugAssign)):
            return isinstance(parent.target, (ast.Attribute, ast.Subscript))
        return False

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and ctx._jitish(node):
                name = ctx.canon(node.func)
                # only the jit constructors; tracing combinators like
                # lax.scan/vmap run inside traces by design
                if name is None or not (
                        name_matches(name, "jax.jit", "lifted_jit")
                        or (name_matches(name, "functools.partial")
                            and node.args
                            and (inner := ctx.canon(node.args[0])) is not None
                            and name_matches(inner, "jax.jit"))):
                    continue
                enclosing = ctx.enclosing_function(node)
                if enclosing is None or enclosing.name == "__init__":
                    continue
                if self._exempt_assignment(ctx, node):
                    continue
                yield self.finding(
                    ctx, node, "jit wrapper constructed inside a function "
                    "retraces per call; hoist to module scope/__init__, "
                    "memoize on self or in a cache, or suppress with the "
                    "cache named")


@register
class DtypeLiteralHygiene(Rule):
    """DTL004: hard-coded wide dtype on the device path.

    TPU has no complex128 and emulates float64; working precision is
    chosen once per problem and funneled through tools/array.py
    (match_precision) and the solver's pencil/real dtypes. A literal
    `jnp.float64` / `jnp.complex128` — or numpy's spelled as a jnp dtype=
    argument — silently promotes device arrays past the configured
    precision, costing memory and MXU throughput exactly where it is
    least visible.

    Heuristic: flags `jnp.float64` / `jnp.complex128` attributes anywhere,
    `np.float64` / `np.complex128` when passed as dtype= to a jnp call,
    and `.astype(np.float64/complex128)` inside traced code. Host-side
    numpy float64 (quadrature, matrix assembly) is the house precision
    and intentionally not flagged.
    """

    id = "DTL004"
    severity = "warning"
    title = "dtype-literal-hygiene"

    _WIDE_JNP = ("jax.numpy.float64", "jax.numpy.complex128")
    _WIDE_NP = ("numpy.float64", "numpy.complex128")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute):
                name = ctx.canon(node)
                if name is not None and name_matches(name, *self._WIDE_JNP):
                    yield self.finding(
                        ctx, node, f"hard-coded {name.split('.')[-1]} "
                        "bypasses the precision funnel (tools/array.py); "
                        "derive the dtype from the data or the solver's "
                        "configured precision")
            elif isinstance(node, ast.Call):
                fname = ctx.canon(node.func)
                if fname is not None and fname.startswith("jax.numpy."):
                    for kw in node.keywords:
                        if kw.arg != "dtype":
                            continue
                        dname = ctx.canon(kw.value)
                        if dname is not None and name_matches(
                                dname, *self._WIDE_NP):
                            yield self.finding(
                                ctx, node, f"dtype={dname.split('.')[-1]} "
                                "on a jnp call bypasses the precision "
                                "funnel (tools/array.py)")
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "astype" and node.args
                        and ctx.in_traced(node)):
                    dname = ctx.canon(node.args[0])
                    if dname is not None and name_matches(
                            dname, *self._WIDE_NP, *self._WIDE_JNP):
                        yield self.finding(
                            ctx, node, f".astype({dname.split('.')[-1]}) "
                            "inside traced code bypasses the precision "
                            "funnel (tools/array.py)")


@register
class PrivateJaxApi(Rule):
    """DTL005: dependency on jax._src internals.

    `jax._src` has no stability contract; imports from it are the part of
    this codebase that breaks on every JAX upgrade (the historical
    `_tracing_active` probe in tools/jitlift.py). Public equivalents or a
    guarded fallback (try public, degrade with one warning) are required;
    the single sanctioned fallback carries a suppression naming why.
    """

    id = "DTL005"
    severity = "warning"
    title = "private-jax-api"

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if node.level == 0 and (mod == "jax._src"
                                        or mod.startswith("jax._src.")):
                    yield self.finding(
                        ctx, node, f"import from {mod} (no stability "
                        "contract); prefer the public jax.* surface with "
                        "a guarded fallback")
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "jax._src" \
                            or alias.name.startswith("jax._src."):
                        yield self.finding(
                            ctx, node, f"import of {alias.name} (no "
                            "stability contract); prefer the public jax.* "
                            "surface with a guarded fallback")
            elif isinstance(node, ast.Attribute) and node.attr == "_src":
                name = ctx.canon(node)
                if name == "jax._src":
                    yield self.finding(
                        ctx, node, "jax._src attribute access (no "
                        "stability contract); prefer the public jax.* "
                        "surface with a guarded fallback")


@register
class NonDifferentiableOpInStepBody(Rule):
    """DTL006: gradient-breaking op in a raw step body.

    The raw step bodies (`MultistepIMEX.advance_body`,
    `RungeKuttaIMEX.step_body`) are the pure functions the differentiable
    subsystem scans and backpropagates through (core/adjoint.py), and the
    ensemble solver vmaps. Three op classes silently break that contract:

      * `jax.lax.stop_gradient` — zeroes the cotangent flow mid-loop, so
        adjoint gradients come back wrong with no error;
      * host callbacks (`io_callback`, `pure_callback`,
        `jax.debug.callback`, `host_callback.call`) — have no transpose
        rule, so `jax.grad` through the step raises (or, for debug
        callbacks, detaches silently);
      * `.at[...].set()` on a DONATED buffer — in-place aliasing of an
        input whose value the backward pass still needs to replay.

    Heuristics: fires only in STEP_BODY_MODULES. stop_gradient and the
    callbacks flag anywhere in those modules (the whole file compiles
    into step programs). The donated-buffer case flags `.at[...].set()`
    whose base is a PARAMETER of a function that some jit wrapper in the
    same module marks with donate_argnums (lexical detection only —
    donation via call sites in other modules is invisible to this pass;
    carry a suppression naming the owner if such a case is ever
    deliberate).
    """

    id = "DTL006"
    severity = "error"
    title = "non-differentiable-op-in-step-body"

    _CALLBACKS = ("jax.experimental.io_callback", "io_callback",
                  "jax.pure_callback", "jax.debug.callback",
                  "jax.experimental.host_callback.call")

    def _donated_functions(self, ctx):
        """Names of functions traced by a jit-ish call (or decorated)
        that passes donate_argnums in this module."""
        names = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            kwargs = {kw.arg for kw in node.keywords}
            if "donate_argnums" not in kwargs:
                continue
            name = ctx.canon(node.func)
            if name is None:
                continue
            jitish = name_matches(name, "jax.jit", "lifted_jit") or (
                name_matches(name, "functools.partial") and node.args
                and (inner := ctx.canon(node.args[0])) is not None
                and name_matches(inner, "jax.jit", "lifted_jit"))
            if not jitish:
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
            parent = ctx.parent(node)
            # decorator form: @functools.partial(jax.jit, donate_argnums=..)
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node in parent.decorator_list:
                names.add(parent.name)
        return names

    @staticmethod
    def _at_set_base(node):
        """For a call `X.at[...].set(...)`, the root expression X (None
        when the call is not an at-set chain)."""
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr == "set"):
            return None
        sub = func.value
        if not isinstance(sub, ast.Subscript):
            return None
        base = sub.value
        if not (isinstance(base, ast.Attribute) and base.attr == "at"):
            return None
        return base.value

    def check(self, ctx):
        if not module_matches(ctx.rel, STEP_BODY_MODULES):
            return
        donated = None
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.canon(node.func)
            if name is not None and name_matches(name,
                                                 "jax.lax.stop_gradient"):
                yield self.finding(
                    ctx, node, "stop_gradient inside a step body zeroes "
                    "the adjoint cotangent flow silently (core/adjoint.py "
                    "backpropagates through these bodies); compute the "
                    "detached value outside the step")
                continue
            if name is not None and name_matches(name, *self._CALLBACKS):
                yield self.finding(
                    ctx, node, "host callback inside a step body has no "
                    "transpose rule: jax.grad through the step loop "
                    "raises (or silently detaches); hoist the host work "
                    "out of the traced step")
                continue
            base = self._at_set_base(node)
            if base is None or not isinstance(base, ast.Name):
                continue
            enclosing = ctx.enclosing_function(node)
            if enclosing is None:
                continue
            if donated is None:
                donated = self._donated_functions(ctx)
            if enclosing.name not in donated:
                continue
            params = {a.arg for a in enclosing.args.args
                      + enclosing.args.posonlyargs
                      + enclosing.args.kwonlyargs}
            if base.id in params:
                yield self.finding(
                    ctx, node, f".at[].set on parameter '{base.id}' of a "
                    "donate_argnums-jitted step body aliases a donated "
                    "input the backward pass still needs; drop the "
                    "donation or write to a fresh buffer")


# Modules whose traced bodies run inside (or compose into) shard_map
# manual/partial-auto regions — the scope of DTL009. libraries/pencilops.py
# is deliberately NOT listed: its lax.map chunk dispatches route through
# BandedOps._shard_chunked manual shard_maps / static unrolls (the PR-13
# fixes), and its one surviving jnp.pad is mode="edge" factor-time padding
# that tools.array.zeropad cannot express — the compiled-program contract
# DTP105 (tools/lint/progcheck.py) still guards the lowered result.
MANUAL_REGION_MODULES = (
    "core/transforms.py",
    "core/subsystems.py",
    "core/field.py",
    "core/ensemble.py",
    "core/fusedstep.py",
    "core/timesteppers.py",
    "core/meshctx.py",
    "parallel/transposes.py",
)

# Function names that ARE the step/dispatch path in the hot modules: code
# here runs per step (or per fleet block), strictly after the solver key
# was sealed. Curated exact names, not substrings — build-time helpers
# like timesteppers._use_split_step legitimately read config.
STEP_PATH_FUNCTIONS = frozenset({
    "step", "step_many", "step_fleet", "advance", "advance_body",
    "step_body", "_step_split", "_dispatch", "_ms_single", "solve",
    "solve_transpose", "matvec", "matvec_pair", "evolve",
    "evolve_resilient",
})


def _dotted(node):
    """Dotted source name of a Name/Attribute chain ('self.active_host',
    'dts'); None when the base is not a plain name."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        return ".".join([node.id] + parts[::-1])
    return None


@register
class HostMirrorAliasing(Rule):
    """DTL007: zero-copy device placement of a mutated host mirror.

    `jnp.asarray` of an aligned numpy buffer is ZERO-COPY on CPU: the
    device array aliases the very memory later in-place writes mutate,
    which retroactively rewrites the value operand of every dispatch
    still queued on the async stream. The shipped case (PR 11): the
    ensemble host mirrors (`active_host[m] = False`, `sim_times += ...`)
    silently froze members for the tail of a served batch by rewriting
    queued fleet operands. The sanctioned spellings copy:
    `jnp.array(arr)` (copy=True by default — core/ensemble._put_host) or
    an explicit `.copy()` on the source.

    Heuristics: flags `jnp.asarray(x)` where x is
      * an attribute chain (`self.active_host`, `snap.X`) that is
        subscript-mutated (`x[...] = ...`, `x[...] += ...`) ANYWHERE in
        the module — mirrors live on objects and the placement and the
        mutation are typically in different methods; or
      * a bare local name subscript-mutated LATER in the same function —
        a buffer built in place and then placed (mutations before the
        placement) is the legitimate construction pattern and stays
        quiet.
    `jnp.array(...)` never flags (it copies). The dotted-name match is
    textual (no alias analysis): two objects sharing an attribute name in
    one module can false-positive — carry a suppression naming why the
    buffers are distinct.
    """

    id = "DTL007"
    severity = "error"
    title = "host-mirror-aliasing"

    @staticmethod
    def _mutations(ctx):
        """{dotted name: [mutation nodes]} for subscript stores."""
        out = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AugAssign):
                targets = [node.target]
            else:
                continue
            for target in targets:
                if isinstance(target, ast.Subscript):
                    name = _dotted(target.value)
                    if name:
                        out.setdefault(name, []).append(node)
        return out

    def check(self, ctx):
        mutated = None
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = ctx.canon(node.func)
            # only the zero-copy spelling; jnp.array copies by default
            if name is None or not name_matches(name, "jax.numpy.asarray"):
                continue
            arg = node.args[0]
            src = _dotted(arg)
            if src is None:
                continue
            if mutated is None:
                mutated = self._mutations(ctx)
            writes = mutated.get(src)
            if not writes:
                continue
            if isinstance(arg, ast.Name):
                fn = ctx.enclosing_function(node)
                later = [w for w in writes
                         if ctx.enclosing_function(w) is fn
                         and w.lineno > node.lineno]
                if fn is None or not later:
                    continue
            yield self.finding(
                ctx, node, f"jnp.asarray({src}) zero-copies a host "
                "buffer that is mutated in place elsewhere "
                f"(line {writes[0].lineno}): queued dispatches would see "
                "the rewritten value; place mirrors by copy "
                "(jnp.array, or .copy() the source)")


@register
class ConfigReadInStepPath(Rule):
    """DTL008: config read on the step/dispatch path after solver-key
    resolution.

    The load-bearing invariant of PRs 12-13: every config knob a compiled
    program depends on is resolved ONCE per solver build, stored on the
    solver (`solver._fusion_plan`, `solver._transpose_chunks`) BEFORE
    `assembly_cache.solver_key` seals it, and folded into the assembly
    and serving pool keys — so two configs can never alias one compiled
    program. A `cfg_get`/`config[...]` read inside the step path (or
    inside traced code, where it bakes into one program variant at trace
    time) reintroduces exactly the aliasing the keys exist to prevent:
    the value read at step N is invisible to every cache key.

    Heuristics: flags config reads (tools.config.cfg_get /
    config[...] subscripts) inside traced functions ANYWHERE, and — in
    the HOT_PATH_MODULES — inside functions named in STEP_PATH_FUNCTIONS
    (exact names; walk-up through nested functions). Build/factor-time
    reads (`__init__`, `_use_split_step`, `resolve_*`) are the sanctioned
    pattern and stay quiet; a step-path function that must consult config
    should take the resolved value as an argument instead.
    """

    id = "DTL008"
    severity = "error"
    title = "config-read-in-step-path"

    @staticmethod
    def _is_config_read(ctx, node):
        if isinstance(node, ast.Call):
            name = ctx.canon(node.func)
            return name is not None and name_matches(name, "cfg_get")
        if isinstance(node, ast.Subscript):
            name = ctx.canon(node.value)
            # exact forms only: the tools.config singleton (however
            # imported) or a bare `config` name — `self.config`/other
            # attributes named config are not the global read
            return name == "config" \
                or (name is not None
                    and name.endswith("tools.config.config"))
        return False

    def _in_step_path(self, ctx, node):
        cur = ctx.parent(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and cur.name in STEP_PATH_FUNCTIONS:
                return True
            cur = ctx.parent(cur)
        return False

    def check(self, ctx):
        hot = module_matches(ctx.rel, HOT_PATH_MODULES)
        for node in ast.walk(ctx.tree):
            if not self._is_config_read(ctx, node):
                continue
            # Subscript STORES (config["x"]["Y"] = ...) are test/setup
            # mutations, not reads
            if isinstance(node, ast.Subscript) \
                    and isinstance(getattr(node, "ctx", None),
                                   (ast.Store, ast.Del)):
                continue
            if ctx.in_traced(node):
                yield self.finding(
                    ctx, node, "config read inside traced code bakes the "
                    "value into one program variant invisibly to the "
                    "solver/pool keys; resolve it once per build and "
                    "pass the resolved value in")
            elif hot and self._in_step_path(ctx, node):
                yield self.finding(
                    ctx, node, "config read on the step/dispatch path "
                    "(after solver-key resolution): the value is "
                    "invisible to the assembly/pool keys, so two configs "
                    "could alias one compiled program; resolve once per "
                    "build (before solver_key) and store it on the "
                    "solver")


@register
class GspmdFragileOp(Rule):
    """DTL009: GSPMD-fragile op in a manual-region module.

    jaxlib 0.4.37's SPMD partitioner hard-crashes on `pad` ops inside the
    GSPMD-auto subregion of a partially-manual shard_map
    (hlo_sharding_util CHECK IsManualSubgroup), and miscompiles
    `lax.map`-style chunk scans under GSPMD (s64/s32
    dynamic_update_slice mismatch) — the three crash classes PR 13 fixed.
    The traced bodies of MANUAL_REGION_MODULES compose into exactly those
    regions (the 2-D batch x pencil fleet wraps them all), so zero
    padding there must lower through `tools.array.zeropad`
    (concatenation, bitwise identical) and chunk maps must route through
    an explicit manual shard_map or a static unroll
    (libraries/pencilops.BandedOps._shard_chunked is the model).

    Heuristic: flags any `jnp.pad` / `jax.lax.map` call in the scoped
    modules, whole-file — these modules' functions are reached under the
    fleet composition regardless of where in the file they sit. The
    compiled-program contract DTP105 (tools/lint/progcheck.py) is the
    backstop that checks the LOWERED programs, including modules outside
    this scope.
    """

    id = "DTL009"
    severity = "error"
    title = "gspmd-fragile-op"

    def check(self, ctx):
        if not module_matches(ctx.rel, MANUAL_REGION_MODULES):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.canon(node.func)
            if name is None:
                continue
            if name_matches(name, "jax.numpy.pad"):
                yield self.finding(
                    ctx, node, "jnp.pad in a manual-region module: the "
                    "SPMD partitioner crashes on pad inside partial-auto "
                    "shard_map regions; use tools.array.zeropad for zero "
                    "padding (non-zero modes need explicit manual "
                    "shard_map routing)")
            elif name_matches(name, "jax.lax.map"):
                yield self.finding(
                    ctx, node, "lax.map in a manual-region module "
                    "miscompiles under GSPMD; route the chunk map "
                    "through a manual shard_map or a static unroll "
                    "(see pencilops.BandedOps._shard_chunked)")
