"""
`python -m dedalus_tpu lint [paths]` — the static-analysis CLI.

Three tiers share the Finding/baseline machinery:

  * default: the AST rule set (DTL0xx, rules.py, plus the DTC
    thread-safety rules from threadcheck.py) over Python source — the
    DTL and DTC tiers keep separate baselines (baseline.json /
    threadcheck_baseline.json), merged for the default run and split
    again by rule-id prefix under --update-baseline;
  * `--programs`: the compiled-program contract checker (DTP1xx,
    progcheck.py) — lowers the census of representative step/grad/fleet
    programs on CPU and checks collective placement, donation aliasing,
    forbidden primitives and manual-region integrity;
  * `--threads`: the thread-safety tier standalone (DTC0xx,
    threadcheck.py) over the serving stack's threaded modules, with
    per-rule timings and the global lock-order acquisition graph.

Exit codes: 0 clean (every finding suppressed or baselined, baseline not
stale), 1 new findings or stale baseline entries, 2 usage error.
"""

import argparse
import json
import os
import pathlib
import sys

from .framework import (all_rules, apply_baseline, load_baseline,
                        make_baseline, run_lint, DEFAULT_BASELINE,
                        PACKAGE_DIR, RULES)


def build_parser():
    parser = argparse.ArgumentParser(
        prog="python -m dedalus_tpu lint",
        description="Static analysis: the DTL AST rule set, plus the "
                    "DTP compiled-program contract census under "
                    "--programs. Suppress single AST findings with a "
                    "same-line '# dedalus-lint: disable=RULE' comment; "
                    "grandfather existing ones into the baseline.")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint "
                             "(default: the dedalus_tpu package; "
                             "ignored under --programs)")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON of grandfathered findings "
                             "(default: the checked-in per-tier baseline)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline (report every finding)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="regenerate the baseline from the current "
                             "findings and exit 0")
    parser.add_argument("--format", choices=("text", "json"), default="text",
                        help="report format (default: text)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule + contract catalog and exit")
    parser.add_argument("--rules", default=None, metavar="IDS",
                        help="comma-separated AST rule ids to run "
                             "(e.g. DTL001,DTL007; default: all)")
    parser.add_argument("--jobs", type=int, default=None, metavar="N",
                        help="parallel per-file AST scanning processes "
                             "(0 = one per core; default: auto for "
                             "package-sized scans)")
    parser.add_argument("--programs", action="store_true",
                        help="run the compiled-program contract census "
                             "(tools/lint/progcheck.py) instead of the "
                             "AST scan; CPU-only, no chip needed")
    parser.add_argument("--threads", action="store_true",
                        help="run the thread-safety tier standalone "
                             "(tools/lint/threadcheck.py): DTC rules "
                             "over the threaded serving modules (or "
                             "explicit paths) with per-rule timings "
                             "and the global lock-order graph")
    parser.add_argument("--select", default=None, metavar="NAMES",
                        help="comma-separated census program names "
                             "(--programs mode) or DTC rule ids "
                             "(--threads mode; e.g. DTC001,DTC003)")
    parser.add_argument("--contracts", default=None, metavar="IDS",
                        help="comma-separated contract ids to check "
                             "(--programs mode; e.g. DTP101,DTP104)")
    parser.add_argument("--fast", action="store_true",
                        help="restrict the census to the fast subset "
                             "(the tier-1 gate's selection)")
    parser.add_argument("--ledger", nargs="?", const="", default=None,
                        metavar="PATH",
                        help="--programs mode: append one `kind: ledger` "
                             "resource-trajectory row per census program "
                             "(XLA cost/memory analysis + plan provenance "
                             "+ env fingerprint) to PATH (default: "
                             "benchmarks/results.jsonl)")
    parser.add_argument("--perfwatch", action="store_true",
                        help="after a clean run, also run the perf-"
                             "trajectory sentinel (`perfwatch --check`) "
                             "over results.jsonl; exit nonzero on a "
                             "confirmed, unwaived regression")
    return parser


def _render_stale(stale):
    """A stale entry means the grandfathered hazard was FIXED: print it
    with its fixed-occurrence count on every run (not only under
    --update-baseline) so the baseline visibly shrinks."""
    for entry in stale:
        n = entry.get("missing", 1)
        print(f"stale baseline entry: {entry['rule']} {entry['path']} "
              f"({entry['snippet']!r}) — {n} grandfathered "
              f"occurrence{'s' if n != 1 else ''} no longer found "
              "(fixed? run --update-baseline to drop it)")


def _summary_line(summary, stale):
    print(f"{summary['total']} finding(s): {summary['new']} new, "
          f"{summary['baselined']} baselined, "
          f"{summary['suppressed']} suppressed, "
          f"{len(stale)} stale baseline entr"
          f"{'y' if len(stale) == 1 else 'ies'}")


def _split_ids(text):
    return [t.strip() for t in text.split(",") if t.strip()]


def _run_programs(args):
    """The --programs tier. Imports (and thereby initializes) the solver
    stack lazily — the AST tier must stay import-light."""
    # the census needs a virtual device mesh; the flag only affects the
    # host (cpu) platform and must land before the backend initializes
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    from . import progcheck

    names = _split_ids(args.select) if args.select else None
    contracts = _split_ids(args.contracts) if args.contracts else None
    baseline_path = pathlib.Path(args.baseline) if args.baseline \
        else progcheck.PROGRAMS_BASELINE

    if args.update_baseline:
        if (names or contracts or args.fast) \
                and baseline_path.resolve() \
                == progcheck.PROGRAMS_BASELINE.resolve():
            print("lint: refusing to regenerate the programs baseline "
                  "from a census subset (it would drop entries outside "
                  "the selection); drop --select/--contracts/--fast, or "
                  "pass --baseline FILE for a scoped baseline",
                  file=sys.stderr)
            return 2
        from .progcheck import check_records, run_census
        records, _ = run_census(names, fast_only=args.fast)
        findings, _, _ = check_records(
            records, [progcheck.CONTRACTS[c] for c in contracts]
            if contracts else None)
        baseline_path.parent.mkdir(parents=True, exist_ok=True)
        baseline_path.write_text(
            json.dumps(make_baseline(findings), indent=1) + "\n")
        print(f"baseline: {len(findings)} finding(s) grandfathered "
              f"-> {baseline_path}")
        return 0

    ledger_path = None
    if args.ledger is not None:
        ledger_path = pathlib.Path(args.ledger) if args.ledger \
            else PACKAGE_DIR.parent / "benchmarks" / "results.jsonl"
    try:
        report = progcheck.run_programs(
            names=names, contracts=contracts, fast_only=args.fast,
            baseline_path=baseline_path, no_baseline=args.no_baseline,
            ledger_path=ledger_path)
    except KeyError as exc:
        print(f"lint: {exc.args[0]}", file=sys.stderr)
        return 2
    except ValueError as exc:
        print(f"lint: {exc}", file=sys.stderr)
        return 2

    summary = report["summary"]
    stale = summary["stale"]
    if args.format == "json":
        print(json.dumps(report, indent=1))
    else:
        for row in report["programs"]:
            if row.get("skipped"):
                print(f"program {row['program']}: SKIPPED "
                      f"({row['skipped']})")
                continue
            cols = [f"build {row['build_sec']}s"]
            coll = row.get("collectives") or {}
            cols.append(f"a2a {coll.get('all-to-all', 0)}")
            cols.append(f"gathers {coll.get('all-gather', 0)}")
            if row.get("donated") is not None:
                cols.append(f"donated {row.get('donated_aliases', 0)}"
                            f"/{row['donated']}")
            if row.get("pads_in_auto_regions") is not None:
                cols.append(f"auto-pads {row['pads_in_auto_regions']}")
            print(f"program {row['program']}: {', '.join(cols)}")
        for timing_kind in ("census", "contracts"):
            budget = report["timings"][timing_kind]
            total = round(sum(budget.values()), 3)
            print(f"{timing_kind} timings ({total}s total): "
                  + ", ".join(f"{k} {v}s" for k, v in budget.items()))
        for f in report["findings"]:
            print(f"{f['path']}:{f['line']}:{f['col']}: {f['rule']} "
                  f"[{f['severity']}] {f['message']}")
        if ledger_path is not None:
            print(f"ledger: {summary.get('ledger_rows', 0)} trajectory "
                  f"row(s) appended -> {ledger_path}")
        _render_stale(stale)
        _summary_line(summary, stale)
    rc = 1 if (summary["new"] or stale) else 0
    if args.perfwatch:
        # the standalone-CI tail: a structurally clean census can still
        # ship a perf regression — the sentinel reads the trajectory the
        # ledger rows just extended
        from ..perfwatch import main as perfwatch_main
        rc = max(rc, perfwatch_main(["--check"]))
    return rc


def _run_threads(args):
    """The --threads tier: DTC rules over the threaded-module set (or
    explicit paths — fixtures/tests scope the scan), per-rule timings,
    and the global lock-order acquisition graph."""
    from . import threadcheck

    ids = _split_ids(args.select) if args.select else None
    paths = args.paths or None
    for p in paths or ():
        path = pathlib.Path(p)
        if not (path.is_dir() or (path.is_file() and path.suffix == ".py")):
            print(f"lint: no such file or directory (or not .py): {p}",
                  file=sys.stderr)
            return 2
    baseline_path = pathlib.Path(args.baseline) if args.baseline \
        else threadcheck.THREADCHECK_BASELINE

    if args.update_baseline:
        if (ids or paths) and baseline_path.resolve() \
                == threadcheck.THREADCHECK_BASELINE.resolve():
            print("lint: refusing to regenerate the threadcheck baseline "
                  "from a subset of rules or paths (it would drop "
                  "entries outside the selection); drop --select/the "
                  "paths, or pass --baseline FILE for a scoped baseline",
                  file=sys.stderr)
            return 2
        try:
            _, findings = threadcheck.run_threads(
                paths=paths, rule_ids=ids, no_baseline=True)
        except KeyError as exc:
            print(f"lint: {exc.args[0]}", file=sys.stderr)
            return 2
        baseline_path.parent.mkdir(parents=True, exist_ok=True)
        baseline_path.write_text(
            json.dumps(make_baseline(findings), indent=1) + "\n")
        print(f"baseline: {len(findings)} finding(s) grandfathered "
              f"-> {baseline_path}")
        return 0

    try:
        report, _ = threadcheck.run_threads(
            paths=paths, rule_ids=ids, baseline_path=baseline_path,
            no_baseline=args.no_baseline, jobs=args.jobs)
    except KeyError as exc:
        print(f"lint: {exc.args[0]}", file=sys.stderr)
        return 2
    except ValueError as exc:
        print(f"lint: {exc}", file=sys.stderr)
        return 2

    summary = report["summary"]
    stale = summary["stale"]
    if args.format == "json":
        print(json.dumps(report, indent=1))
    else:
        print(f"threads: {len(report['modules'])} module(s) scanned, "
              f"{summary['edges']} lock-order edge(s), "
              f"{summary['cycles']} cycle(s)")
        for edge in report["graph"]["edges"]:
            print(f"lock edge: {edge['src']} -> {edge['dst']} "
                  f"({', '.join(edge['sites'])})")
        budget = report["timings"]["rules"]
        total = round(sum(budget.values()), 3)
        print(f"rule timings ({total}s total): "
              + ", ".join(f"{k} {v}s" for k, v in budget.items()))
        for f in report["findings"]:
            print(f"{f['path']}:{f['line']}:{f['col']}: {f['rule']} "
                  f"[{f['severity']}] {f['message']}")
        _render_stale(stale)
        _summary_line(summary, stale)
    return 1 if (summary["new"] or stale) else 0


def main(argv=None):
    """Entry point; returns the exit code (the __main__ shim sys.exits)."""
    try:
        args = build_parser().parse_args(argv)
    except SystemExit as exc:
        # argparse exits 2 on usage error, 0 on --help; keep its code
        return int(exc.code or 0)

    if args.list_rules:
        for rule in all_rules():
            doc = (rule.__doc__ or "").strip().splitlines()[0]
            print(f"{rule.id} [{rule.severity}] {rule.title}: {doc}")
        from .progcheck import all_contracts
        for contract in all_contracts():
            doc = (contract.__doc__ or "").strip().splitlines()[0]
            print(f"{contract.id} [{contract.severity}] {contract.title}: "
                  f"{doc} (--programs)")
        return 0

    if args.programs and args.threads:
        print("lint: --programs and --threads are separate tiers; run "
              "them as two invocations", file=sys.stderr)
        return 2

    if args.programs:
        if args.paths:
            print("lint: --programs checks the compiled census, not "
                  "source paths (drop the path arguments)",
                  file=sys.stderr)
            return 2
        return _run_programs(args)

    if args.threads:
        return _run_threads(args)

    rules = None
    if args.rules:
        ids = _split_ids(args.rules)
        unknown = [r for r in ids if r not in RULES]
        if unknown:
            print(f"lint: unknown rule id(s) {unknown}; known: "
                  f"{sorted(RULES)}", file=sys.stderr)
            return 2
        rules = [RULES[r] for r in ids]

    for p in args.paths:
        path = pathlib.Path(p)
        if not (path.is_dir() or (path.is_file() and path.suffix == ".py")):
            # a typo'd path must not report a clean lint
            print(f"lint: no such file or directory (or not .py): {p}",
                  file=sys.stderr)
            return 2
    paths = args.paths or [str(PACKAGE_DIR)]
    baseline_arg = args.baseline or str(DEFAULT_BASELINE)
    # staleness of the PACKAGE baseline is only meaningful when the scan
    # covers the package AND every rule ran: a subset scan (or rule
    # filter) leaves out-of-scope entries unmatched by construction, not
    # because their findings were fixed. A custom --baseline is assumed
    # scoped to the given paths.
    check_stale = (pathlib.Path(baseline_arg).resolve()
                   != DEFAULT_BASELINE.resolve()
                   or not args.paths
                   or any(pathlib.Path(p).resolve() == PACKAGE_DIR
                          for p in args.paths)) and rules is None
    jobs = args.jobs
    if jobs is None:
        # auto: fan out package-sized scans, stay serial for small ones
        files_guess = sum(1 for p in paths
                          for _ in pathlib.Path(p).rglob("*.py")) \
            if all(pathlib.Path(p).is_dir() for p in paths) else 0
        jobs = min(os.cpu_count() or 1, 8) if files_guess >= 16 else 1
    elif jobs == 0:
        jobs = os.cpu_count() or 1
    result = run_lint(paths, rules=rules, jobs=jobs)

    if args.update_baseline:
        baseline_path = pathlib.Path(baseline_arg)
        if (args.paths or rules is not None) \
                and baseline_path.resolve() == DEFAULT_BASELINE.resolve():
            # a subset scan would silently WIPE every grandfathered entry
            # outside the given paths (or outside the selected rules);
            # the package baseline regenerates only from the full scan
            print("lint: refusing to regenerate the package baseline from "
                  "a subset of paths or rules (it would drop entries "
                  "outside them); drop the paths/--rules, or pass "
                  "--baseline FILE for a scoped baseline", file=sys.stderr)
            return 2
        if baseline_path.resolve() == DEFAULT_BASELINE.resolve():
            # the default run covers both tiers but each keeps its own
            # checked-in baseline: split the findings back out by rule-id
            # prefix so neither file grandfathers the other tier's rules
            from .threadcheck import THREADCHECK_BASELINE
            dtc = [f for f in result.findings if f.rule.startswith("DTC")]
            dtl = [f for f in result.findings
                   if not f.rule.startswith("DTC")]
            for tier_findings, tier_path in ((dtl, baseline_path),
                                             (dtc, THREADCHECK_BASELINE)):
                tier_path.write_text(
                    json.dumps(make_baseline(tier_findings), indent=1)
                    + "\n")
                print(f"baseline: {len(tier_findings)} finding(s) "
                      f"grandfathered -> {tier_path}")
            return 0
        baseline_path.parent.mkdir(parents=True, exist_ok=True)
        baseline_path.write_text(
            json.dumps(make_baseline(result.findings), indent=1) + "\n")
        print(f"baseline: {len(result.findings)} finding(s) grandfathered "
              f"-> {baseline_path}")
        return 0

    if args.no_baseline:
        baseline = {}
    else:
        try:
            baseline = load_baseline(baseline_arg)
            if pathlib.Path(baseline_arg).resolve() \
                    == DEFAULT_BASELINE.resolve():
                # the default scan runs the DTC rules too; merge their
                # per-tier baseline (rule-id prefixes keep keys disjoint)
                from .threadcheck import THREADCHECK_BASELINE
                baseline = {**baseline,
                            **load_baseline(THREADCHECK_BASELINE)}
        except ValueError as exc:
            print(f"lint: {exc}", file=sys.stderr)
            return 2
    new, stale = apply_baseline(result.findings, baseline)
    if not check_stale:
        stale = []

    summary = {
        "total": len(result.findings),
        "new": len(new),
        "baselined": len(result.findings) - len(new),
        "suppressed": len(result.suppressed),
        "stale": stale,
    }
    if args.format == "json":
        print(json.dumps({"findings": [f.to_dict() for f in new],
                          "summary": summary}, indent=1))
    else:
        for f in new:
            print(f.format())
        _render_stale(stale)
        _summary_line(summary, stale)
    rc = 1 if (new or stale) else 0
    if args.perfwatch:
        from ..perfwatch import main as perfwatch_main
        rc = max(rc, perfwatch_main(["--check"]))
    return rc
