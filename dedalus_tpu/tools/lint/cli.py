"""
`python -m dedalus_tpu lint [paths]` — run the jit-hygiene analyzer.

Exit codes: 0 clean (every finding suppressed or baselined, baseline not
stale), 1 new findings or stale baseline entries, 2 usage error.
"""

import argparse
import json
import pathlib
import sys

from .framework import (all_rules, apply_baseline, load_baseline,
                        make_baseline, run_lint, DEFAULT_BASELINE,
                        PACKAGE_DIR)


def build_parser():
    parser = argparse.ArgumentParser(
        prog="python -m dedalus_tpu lint",
        description="Jit-hygiene static analysis (DTL rule set). "
                    "Suppress single findings with a same-line "
                    "'# dedalus-lint: disable=RULE' comment; grandfather "
                    "existing ones into the baseline.")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint "
                             "(default: the dedalus_tpu package)")
    parser.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                        help="baseline JSON of grandfathered findings "
                             "(default: %(default)s)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline (report every finding)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="regenerate the baseline from the current "
                             "findings and exit 0")
    parser.add_argument("--format", choices=("text", "json"), default="text",
                        help="report format (default: text)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    return parser


def main(argv=None):
    """Entry point; returns the exit code (the __main__ shim sys.exits)."""
    try:
        args = build_parser().parse_args(argv)
    except SystemExit as exc:
        # argparse exits 2 on usage error, 0 on --help; keep its code
        return int(exc.code or 0)

    if args.list_rules:
        for rule in all_rules():
            doc = (rule.__doc__ or "").strip().splitlines()[0]
            print(f"{rule.id} [{rule.severity}] {rule.title}: {doc}")
        return 0

    for p in args.paths:
        path = pathlib.Path(p)
        if not (path.is_dir() or (path.is_file() and path.suffix == ".py")):
            # a typo'd path must not report a clean lint
            print(f"lint: no such file or directory (or not .py): {p}",
                  file=sys.stderr)
            return 2
    paths = args.paths or [str(PACKAGE_DIR)]
    # staleness of the PACKAGE baseline is only meaningful when the scan
    # covers the package: a subset scan leaves out-of-scope entries
    # unmatched by construction, not because their findings were fixed.
    # A custom --baseline is assumed scoped to the given paths.
    check_stale = (pathlib.Path(args.baseline).resolve()
                   != DEFAULT_BASELINE.resolve()
                   or not args.paths
                   or any(pathlib.Path(p).resolve() == PACKAGE_DIR
                          for p in args.paths))
    result = run_lint(paths)

    if args.update_baseline:
        baseline_path = pathlib.Path(args.baseline)
        if args.paths \
                and baseline_path.resolve() == DEFAULT_BASELINE.resolve():
            # a subset scan would silently WIPE every grandfathered entry
            # outside the given paths; the package baseline regenerates
            # only from the full default scan
            print("lint: refusing to regenerate the package baseline from "
                  "a subset of paths (it would drop entries outside them); "
                  "drop the paths, or pass --baseline FILE for a scoped "
                  "baseline", file=sys.stderr)
            return 2
        baseline_path.parent.mkdir(parents=True, exist_ok=True)
        baseline_path.write_text(
            json.dumps(make_baseline(result.findings), indent=1) + "\n")
        print(f"baseline: {len(result.findings)} finding(s) grandfathered "
              f"-> {baseline_path}")
        return 0

    if args.no_baseline:
        baseline = {}
    else:
        try:
            baseline = load_baseline(args.baseline)
        except ValueError as exc:
            print(f"lint: {exc}", file=sys.stderr)
            return 2
    new, stale = apply_baseline(result.findings, baseline)
    if not check_stale:
        stale = []

    summary = {
        "total": len(result.findings),
        "new": len(new),
        "baselined": len(result.findings) - len(new),
        "suppressed": len(result.suppressed),
        "stale": stale,
    }
    if args.format == "json":
        print(json.dumps({"findings": [f.to_dict() for f in new],
                          "summary": summary}, indent=1))
    else:
        for f in new:
            print(f.format())
        for e in stale:
            print(f"stale baseline entry: {e['rule']} {e['path']} "
                  f"({e['snippet']!r}) — fixed? run --update-baseline")
        print(f"{summary['total']} finding(s): {summary['new']} new, "
              f"{summary['baselined']} baselined, "
              f"{summary['suppressed']} suppressed, "
              f"{len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'}")
    return 1 if (new or stale) else 0
