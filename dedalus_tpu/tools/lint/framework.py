"""
Lint framework: findings, rule registry, module context (import-alias
canonicalization + traced-function detection), suppressions, baseline.

Scope and honesty: this is a static pass over untyped Python, so rules
work from structural heuristics (documented per rule) rather than proofs.
Two shared analyses keep them precise enough to be useful:

  * Canonical names — import aliases are resolved per module, so
    `jnp.asarray`, `jax.numpy.asarray` and `from jax import numpy` all
    canonicalize to "jax.numpy.asarray" before rules match.
  * Traced-function detection — a function is considered TRACED when its
    name (or a lambda) is passed to lifted_jit / jax.jit / jax.eval_shape /
    jax.vmap / jax.lax.scan / shard_map, or it carries a jit-ish decorator
    (including functools.partial(jax.jit, ...)). Code inside a traced
    function becomes XLA program text, which changes what counts as a
    hazard. Transitive tracing through ordinary calls is NOT resolved;
    rules that depend on tracedness also take module-path scopes for the
    known device libraries.

Suppressions: `# dedalus-lint: disable=RULE[,RULE...]` on the finding's
line silences it (counted separately, never silently dropped);
`disable-file=RULE` anywhere in the file silences the whole module.

Baseline: grandfathered findings keyed on (rule, package-relative path,
stripped source line) with an occurrence count — stable across unrelated
line-number drift. A baseline entry matched by fewer findings than its
count is STALE (the hazard was fixed; regenerate with --update-baseline)
so the baseline can only shrink, never quietly pad.
"""

import ast
import json
import pathlib
import re

# dedalus_tpu package root (this file lives at tools/lint/framework.py)
PACKAGE_DIR = pathlib.Path(__file__).resolve().parents[2]

# the checked-in grandfather baseline (single source of truth; cli and the
# package API both import it from here)
DEFAULT_BASELINE = PACKAGE_DIR / "tools" / "lint" / "baseline.json"

_SUPPRESS_RE = re.compile(
    r"#\s*dedalus-lint:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")

# Wrappers whose function-valued arguments are traced into XLA programs.
_TRACE_WRAPPERS = ("jax.jit", "jax.eval_shape", "jax.vmap", "jax.lax.scan",
                   "jax.lax.while_loop", "jax.lax.fori_loop", "jax.grad",
                   "jax.experimental.shard_map.shard_map", "shard_map",
                   "lifted_jit")


def baseline_rel(path):
    """Baseline key path: package-relative posix when inside the package
    (stable across checkouts), absolute posix otherwise (test fixtures)."""
    p = pathlib.Path(path).resolve()
    try:
        return p.relative_to(PACKAGE_DIR).as_posix()
    except ValueError:
        return p.as_posix()


class Finding:
    """One rule violation at file:line."""

    __slots__ = ("rule", "severity", "path", "line", "col", "message",
                 "snippet")

    def __init__(self, rule, severity, path, line, col, message, snippet):
        self.rule = rule
        self.severity = severity
        self.path = pathlib.Path(path)
        self.line = int(line)
        self.col = int(col)
        self.message = message
        self.snippet = snippet

    def key(self):
        return (self.rule, baseline_rel(self.path), self.snippet)

    def to_dict(self):
        return {"rule": self.rule, "severity": self.severity,
                "path": baseline_rel(self.path), "line": self.line,
                "col": self.col, "message": self.message,
                "snippet": self.snippet}

    def format(self):
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.severity}] {self.message}")


class LintResult:
    """Active findings plus the suppressed ones (reported, never hidden)."""

    __slots__ = ("findings", "suppressed")

    def __init__(self, findings, suppressed):
        self.findings = findings
        self.suppressed = suppressed


RULES = {}


def register(cls):
    """Class decorator: add a Rule to the global registry by its id."""
    RULES[cls.id] = cls()
    return cls


def all_rules():
    return [RULES[rid] for rid in sorted(RULES)]


class Rule:
    """Base rule: subclasses set id/severity/title and implement
    check(ctx) yielding Findings."""

    id = None
    severity = "error"
    title = ""

    def check(self, ctx):
        raise NotImplementedError

    def finding(self, ctx, node, message):
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        snippet = ctx.lines[line - 1].strip() if line <= len(ctx.lines) else ""
        return Finding(self.id, self.severity, ctx.path, line, col,
                       message, snippet)


class ModuleContext:
    """Parsed module + the shared analyses rules draw on."""

    def __init__(self, path, source):
        self.path = pathlib.Path(path)
        self.rel = baseline_rel(path)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        self._parents = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
        self.aliases = self._collect_aliases()
        self.line_suppressions, self.file_suppressions = \
            self._collect_suppressions()
        self._traced = None

    # ------------------------------------------------------ canonical names

    def _collect_aliases(self):
        aliases = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        aliases[alias.asname] = alias.name
                    else:
                        root = alias.name.split(".")[0]
                        aliases[root] = root
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for alias in node.names:
                    full = f"{mod}.{alias.name}" if mod else alias.name
                    aliases[alias.asname or alias.name] = full
        return aliases

    def canon(self, node):
        """Dotted canonical name of a Name/Attribute chain, with the base
        resolved through this module's import aliases; None when the base
        is not a plain name (e.g. a call result)."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            base = self.aliases.get(node.id, node.id)
            return ".".join([base] + parts[::-1])
        return None

    # ----------------------------------------------------------- structure

    def parent(self, node):
        return self._parents.get(node)

    def enclosing_function(self, node):
        """Nearest enclosing FunctionDef/AsyncFunctionDef (not lambdas)."""
        cur = self.parent(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parent(cur)
        return None

    # ------------------------------------------------------------- tracing

    def _jitish(self, call):
        """Whether a Call node invokes a trace wrapper (directly or as
        functools.partial(jax.jit, ...))."""
        name = self.canon(call.func)
        if name is None:
            return False
        if name_matches(name, *_TRACE_WRAPPERS):
            return True
        if name_matches(name, "functools.partial") and call.args:
            inner = self.canon(call.args[0])
            return inner is not None and name_matches(inner, *_TRACE_WRAPPERS)
        return False

    def _decorator_jitish(self, dec):
        name = self.canon(dec)
        if name is not None and name_matches(name, *_TRACE_WRAPPERS):
            return True
        return isinstance(dec, ast.Call) and self._jitish(dec)

    def traced_nodes(self):
        """Set of FunctionDef/Lambda nodes treated as traced (see module
        docstring for the detection contract)."""
        if self._traced is not None:
            return self._traced
        traced_names = set()
        traced = set()
        for node in ast.walk(self.tree):
            is_wrap_call = isinstance(node, ast.Call) and (
                self._jitish(node)
                # curried form: functools.partial(jax.jit, ...)(fn)
                or (isinstance(node.func, ast.Call)
                    and self._jitish(node.func)))
            if is_wrap_call:
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        traced_names.add(arg.id)
                    elif isinstance(arg, ast.Lambda):
                        traced.add(arg)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(self._decorator_jitish(d) for d in node.decorator_list):
                    traced.add(node)
        for node in ast.walk(self.tree):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name in traced_names):
                traced.add(node)
        self._traced = traced
        return traced

    def in_traced(self, node):
        """Whether node sits lexically inside a traced function/lambda."""
        traced = self.traced_nodes()
        cur = node
        while cur is not None:
            if cur in traced:
                return True
            cur = self.parent(cur)
        return False

    # -------------------------------------------------------- suppressions

    def _collect_suppressions(self):
        """Scan COMMENT tokens only (via tokenize), so suppression syntax
        QUOTED in a docstring or string literal — e.g. documentation of
        the mechanism itself — never registers as a real suppression.
        Falls back to a raw line scan only if tokenization fails (the
        module already parsed, so that is not an expected path)."""
        per_line = {}
        per_file = set()
        try:
            import io
            import tokenize
            comments = [(tok.start[0], tok.string) for tok in
                        tokenize.generate_tokens(
                            io.StringIO(self.source).readline)
                        if tok.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = list(enumerate(self.lines, start=1))
        for lineno, text in comments:
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
            if m.group("file"):
                per_file |= rules
            else:
                per_line.setdefault(lineno, set()).update(rules)
        return per_line, per_file

    def suppressed(self, finding):
        if finding.rule in self.file_suppressions:
            return True
        return finding.rule in self.line_suppressions.get(finding.line, set())


def name_matches(canon, *patterns):
    """Suffix-tolerant canonical-name match: 'a.b.c' matches patterns
    'a.b.c', 'b.c' and 'c' only at dotted boundaries — so from-imports
    whose defining module the linter cannot resolve (relative imports)
    still match their known tails."""
    for pat in patterns:
        if canon == pat or canon.endswith("." + pat):
            return True
    return False


def module_matches(rel, module_paths):
    """Whether a file's baseline-relative path is one of the given
    package-relative module paths (suffix match, so test fixtures living
    under tmp dirs can opt into a scope by mirroring the path)."""
    rel = pathlib.PurePosixPath(rel).as_posix()
    for mod in module_paths:
        if rel == mod or rel.endswith("/" + mod):
            return True
    return False


# ------------------------------------------------------------------ runner

def collect_py_files(paths):
    files = []
    for path in paths:
        p = pathlib.Path(path)
        if p.is_dir():
            files.extend(sorted(f for f in p.rglob("*.py")
                                if "__pycache__" not in f.parts))
        elif p.suffix == ".py":
            files.append(p)
    # dedupe, preserving order
    seen = set()
    unique = []
    for f in files:
        r = f.resolve()
        if r not in seen:
            seen.add(r)
            unique.append(f)
    return unique


def _scan_file(path, rules):
    """Lint one file: (findings, suppressed). Unparsable files surface
    as DTL000 findings (a lint pass that skips broken files hides exactly
    the commit that needs review)."""
    findings = []
    suppressed = []
    try:
        source = path.read_text()
        ctx = ModuleContext(path, source)
    except (OSError, SyntaxError, ValueError) as exc:
        findings.append(Finding("DTL000", "error", path,
                                getattr(exc, "lineno", 1) or 1, 0,
                                f"unparsable module: {exc}", ""))
        return findings, suppressed
    for rule in rules:
        for finding in rule.check(ctx):
            if ctx.suppressed(finding):
                suppressed.append(finding)
            else:
                findings.append(finding)
    return findings, suppressed


def _scan_file_by_ids(args):
    """Process-pool worker: resolve rule ids against the registry in the
    child (the rules module import registers them) and scan one file.
    Finding objects pickle whole — plain slots of builtin types."""
    path_str, rule_ids = args
    from . import rules as _rules  # noqa: F401  (registers the rule set)
    from . import threadcheck as _tc  # noqa: F401  (registers DTC rules)
    return _scan_file(pathlib.Path(path_str), [RULES[r] for r in rule_ids])


def run_lint(paths, rules=None, jobs=None):
    """Run the rule set over .py files under `paths`; returns a
    LintResult. `jobs` > 1 fans the per-file AST scan over a fork-based
    process pool (the serial pass is the longest part of a package lint
    on this tree); results are identical and ordered as the serial scan.
    Parallel scanning requires registry rules (resolved by id in the
    children) and the fork start method — anything else silently runs
    serial, which is always correct.
    """
    rules = all_rules() if rules is None else rules
    files = collect_py_files(paths)
    if jobs and jobs > 1 and len(files) > 1 \
            and all(RULES.get(r.id) is r for r in rules):
        try:
            import multiprocessing
            import warnings
            from concurrent.futures import ProcessPoolExecutor
            mp_ctx = multiprocessing.get_context("fork")
            rule_ids = [r.id for r in rules]
            work = [(str(f), rule_ids) for f in files]
            with warnings.catch_warnings():
                # JAX warns that forking a multithreaded process risks
                # deadlock; the children do pure-AST parsing and never
                # enter the JAX runtime, and any pool failure falls back
                # to the serial scan below
                warnings.filterwarnings(
                    "ignore", message=".*os.fork.*", category=RuntimeWarning)
                with ProcessPoolExecutor(
                        max_workers=min(int(jobs), len(files)),
                        mp_context=mp_ctx) as pool:
                    results = list(pool.map(_scan_file_by_ids, work))
            findings, suppressed = [], []
            for file_findings, file_suppressed in results:
                findings.extend(file_findings)
                suppressed.extend(file_suppressed)
            return LintResult(findings, suppressed)
        except (ImportError, ValueError, OSError):
            pass  # no fork / restricted environment: serial fallback
    findings = []
    suppressed = []
    for path in files:
        file_findings, file_suppressed = _scan_file(path, rules)
        findings.extend(file_findings)
        suppressed.extend(file_suppressed)
    return LintResult(findings, suppressed)


# ---------------------------------------------------------------- baseline

def load_baseline(path):
    """Baseline file -> {key: count}. A missing file is an empty baseline
    (callers that require its presence check exists() themselves)."""
    p = pathlib.Path(path)
    if not p.exists():
        return {}
    try:
        data = json.loads(p.read_text())
        entries = data["entries"]
    except (OSError, ValueError, KeyError, TypeError) as exc:
        raise ValueError(f"unreadable baseline {p}: {exc}")
    baseline = {}
    for e in entries:
        key = (e["rule"], e["path"], e["snippet"])
        baseline[key] = baseline.get(key, 0) + int(e.get("count", 1))
    return baseline


def make_baseline(findings):
    """Grandfather the given findings: the JSON-able baseline structure."""
    counts = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    entries = [{"rule": rule, "path": rel, "snippet": snippet, "count": n}
               for (rule, rel, snippet), n in sorted(counts.items())]
    return {"version": 1, "entries": entries}


def apply_baseline(findings, baseline):
    """Split findings against a {key: count} baseline. Returns
    (new_findings, stale_entries): each baseline count absorbs that many
    matching findings; the excess is new, and under-matched entries are
    stale dicts {"rule", "path", "snippet", "missing"}."""
    remaining = dict(baseline)
    new = []
    for f in findings:
        k = f.key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
        else:
            new.append(f)
    stale = [{"rule": rule, "path": rel, "snippet": snippet, "missing": n}
             for (rule, rel, snippet), n in sorted(remaining.items()) if n > 0]
    return new, stale
