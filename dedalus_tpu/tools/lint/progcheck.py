"""
Compiled-program contract checker: the second static-analysis tier.

The AST rules (rules.py) catch hazards in Python source; the invariants
this framework's performance claims actually rest on live in COMPILED
program text — "zero full-state all-gathers in the sharded step", "no
triangular/pivot solves in the fused substitution scan", "the donated
history buffers really alias the outputs". Each was enforced by a one-off
regex buried in a single test, so any new program shape (a new scenario
builder, a new mesh composition, a pool-served fleet) shipped unchecked.
This module lowers a CENSUS of representative programs — the same
lifted_jit/jit wrappers the step loops dispatch, via the program handles
the owning modules expose (core/timesteppers.step_program_handle,
EnsembleSolver.step_program_handle, DifferentiableIVP.grad_program_handle)
— and checks each against a registry of declarative CONTRACTS over two
stable views of the program:

  * the COMPILED HLO text (`program.lower(*args).compile().as_text()`):
    collective placement (all-gather/all-to-all ops with their buffer
    sizes) and the `input_output_alias` donation header;
  * the JAXPR (`program.jaxpr(*args)` / `jax.make_jaxpr`): primitive-
    level structure — forbidden solve/callback primitives, and `pad`
    primitives inside partial-auto shard_map regions (the jaxlib-0.4.37
    SPMD-partitioner crash class PR 13 fixed by `tools.array.zeropad`).

Contracts (ids DTP1xx, disjoint from the AST DTL0xx ids):

  DTP101 no-full-state-gather   — size-aware: no all-gather whose result
                                  buffer reaches GATHER_FRACTION of the
                                  program's global state size. Small
                                  gathers (e.g. a tau line round-trip)
                                  pass; the full-state degradation the
                                  weak-scaling claim forbids fails.
  DTP102 no-forbidden-custom-call — no host-callback primitives/targets
                                  in any step/grad body; no triangular/
                                  pivot-LU solve primitives or LAPACK/
                                  cusolver custom calls in programs
                                  declared fused_solve (the 2.13x fusion
                                  win is precisely their absence).
  DTP103 collective-census      — at least the declared all-to-all count
                                  (one per chunk per transpose stage): a
                                  GSPMD fallback that silently replaces a
                                  chunked exchange with a gather is a
                                  lint failure, not a perf mystery.
  DTP104 donation-honored       — programs declaring donated buffers
                                  must compile with that many
                                  input_output_alias entries; a dropped
                                  donation is a silent 3x-state memory
                                  regression.
  DTP105 manual-region-integrity — no `pad` primitives inside shard_map
                                  regions with a nonempty `auto` set
                                  (pads in FULLY manual regions are
                                  explicitly partitioned and safe; pads
                                  in the GSPMD-auto subregion of a
                                  partially-manual shard_map are the
                                  hard-crash class).
  DTP107 tracing-inert          — programs declaring an untraced-build
                                  HLO hash (meta["untraced_sha256"])
                                  must compile byte-identically with
                                  request tracing enabled: the
                                  observability layer (tools/tracing.py)
                                  is host-side bookkeeping by contract,
                                  and a span helper leaking into the
                                  lowered computation is a lint failure,
                                  not a perf mystery.

Findings reuse the lint framework's Finding/baseline discipline
(framework.py): keys are (contract, "__programs__/<name>", detail), the
grandfather baseline lives in progcheck_baseline.json (empty on a healthy
tree), and per-program waivers declared in the census are counted as
suppressions, never silently dropped. The census runs CPU-only on the
virtual-device mesh (`--xla_force_host_platform_device_count`), so CI
needs no chip; builders that need more devices than the process has are
reported as skipped, not silently absent.

Entry points: `python -m dedalus_tpu lint --programs` (cli.py) and
`run_programs()` (tests/test_progcheck.py, the tier-1 gate).
"""

import hashlib
import pathlib
import re
import time

import numpy as np

from .framework import (Finding, PACKAGE_DIR, apply_baseline,
                        load_baseline)

# the checked-in grandfather baseline for PROGRAM findings (kept separate
# from the AST baseline: the two tiers regenerate independently)
PROGRAMS_BASELINE = PACKAGE_DIR / "tools" / "lint" / "progcheck_baseline.json"

# pseudo-path root for program findings: baseline keys come out as
# "__programs__/<census name>", stable across checkouts like the
# package-relative source paths of AST findings
_PSEUDO_ROOT = PACKAGE_DIR / "__programs__"

# an all-gather counts as "full-state" when one gathered buffer reaches
# this fraction of the program's global state size (tau-line round-trips
# and tiny bookkeeping gathers stay legal; gathering the pencil state
# does not)
GATHER_FRACTION = 0.5

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

__all__ = ["ProgramRecord", "Contract", "all_contracts", "collective_counts",
           "gather_buffers", "donated_alias_count", "jaxpr_primitives",
           "pads_in_auto_regions", "scan_lengths", "record_from_jit",
           "register_contract", "run_census", "check_records",
           "run_programs", "census_names", "PROGRAMS_BASELINE",
           "GATHER_FRACTION", "program_ledger", "hlo_instruction_count",
           "ledger_rows", "append_ledger_rows", "LEDGER_FIELDS"]


# ------------------------------------------------------- program analyses

def collective_counts(hlo_text):
    """Collective-op census of a compiled HLO module. The SHARED parser
    behind tests/test_collectives.py, tests/test_distributed.py and the
    DTP101/DTP103 contracts (each test used to carry its own regex)."""
    return {op: len(re.findall(rf"\s{op}(?:-start)?\(", hlo_text))
            for op in ("all-to-all", "all-gather", "all-reduce",
                       "reduce-scatter", "collective-permute")}


def _shape_bytes(dtype, dims):
    width = _DTYPE_BYTES.get(dtype)
    if width is None:
        return None
    n = 1
    for d in dims.split(",") if dims else []:
        if d:
            n *= int(d)
    return n * width


def gather_buffers(hlo_text):
    """[(dtype, shape, nbytes)] for every buffer produced by an
    all-gather op in the compiled module (tuple-shaped gathers yield one
    entry per element). Sizes are the gathered RESULT shapes — exactly
    what lands on every device."""
    out = []
    for line in hlo_text.splitlines():
        if " all-gather(" not in line and " all-gather-start(" not in line:
            continue
        head = line.split(" all-gather", 1)[0]
        if "=" not in head:
            continue
        head = head.split("=", 1)[1]
        for dtype, dims in re.findall(r"(\w+)\[([\d,]*)\]", head):
            nbytes = _shape_bytes(dtype, dims)
            if nbytes is not None:
                out.append((dtype, dims, nbytes))
    return out


def donated_alias_count(hlo_text):
    """Number of input/output alias pairs in the compiled module header —
    donation that XLA actually honored. A donate_argnums the compiler
    dropped (shape mismatch, aliasing conflict) simply does not appear
    here, which is exactly what DTP104 exists to catch."""
    header = hlo_text.split("\n", 1)[0]
    m = re.search(r"input_output_alias=\{(.*)", header)
    if not m:
        return 0
    return len(re.findall(r"\{[\d,\s]*\}:\s*\(\d+", m.group(1)))


def _walk_jaxprs(jaxpr, visit, in_auto=False):
    """Depth-first over a (Closed)Jaxpr and every sub-jaxpr reachable
    through eqn params (pjit bodies, scan/while bodies, cond branches,
    custom_vjp calls, shard_map regions). `visit(eqn, in_auto)` sees each
    equation with whether it sits inside a shard_map region whose `auto`
    set is nonempty (the partially-manual GSPMD region)."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        visit(eqn, in_auto)
        sub_auto = in_auto
        if eqn.primitive.name == "shard_map":
            sub_auto = bool(eqn.params.get("auto"))
        for val in eqn.params.values():
            items = val if isinstance(val, (list, tuple)) else [val]
            for item in items:
                if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                    _walk_jaxprs(item, visit, sub_auto)


def jaxpr_primitives(jaxpr):
    """{primitive name: count} over the whole program, sub-jaxprs
    included."""
    counts = {}

    def visit(eqn, _):
        counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1

    _walk_jaxprs(jaxpr, visit)
    return counts


def scan_lengths(jaxpr):
    """(lengths, whiles): the trip count of every `scan` equation in the
    program (sub-jaxprs included) and the number of `while` equations
    (whose trip counts are unprovable from the program text). The DTP106
    depth analysis: a restructured substitution's sequential depth IS
    the longest scan left in its lowered program."""
    lengths = []
    whiles = [0]

    def visit(eqn, _):
        if eqn.primitive.name == "scan":
            length = eqn.params.get("length")
            if length is not None:
                lengths.append(int(length))
        elif eqn.primitive.name == "while":
            whiles[0] += 1

    _walk_jaxprs(jaxpr, visit)
    return lengths, whiles[0]


def pads_in_auto_regions(jaxpr):
    """Count of `pad` primitives lexically inside shard_map regions with
    a nonempty `auto` set. Pads inside FULLY manual regions are already
    partitioned by hand and lower fine; pads the GSPMD partitioner must
    propagate shardings through inside a partial-auto region hard-crash
    jaxlib 0.4.37 (hlo_sharding_util CHECK IsManualSubgroup) — the class
    tools.array.zeropad exists to keep out of traced bodies."""
    hits = [0]

    def visit(eqn, in_auto):
        if in_auto and eqn.primitive.name == "pad":
            hits[0] += 1

    _walk_jaxprs(jaxpr, visit)
    return hits[0]


# ------------------------------------------------------ the resource ledger

# one HLO instruction per `name = type[...] op(...)` line (ROOT-prefixed
# or %-sigiled in older dumps); computation headers/braces don't match
_HLO_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s", re.M)

# every quantitative field a ledger can carry, all nullable: a backend
# lacking (or raising from) cost_analysis/memory_analysis degrades to
# partial rows, never a failed census
LEDGER_FIELDS = ("flops", "transcendentals", "bytes_accessed",
                 "argument_bytes", "output_bytes", "temp_bytes",
                 "generated_code_bytes", "peak_bytes", "hlo_instructions")


def hlo_instruction_count(hlo_text):
    """Instruction count of a compiled HLO module — the cheapest stable
    proxy for compiled-program size (tracks fusion regressions that flops
    alone cannot: an unfused program re-materializes as more
    instructions, not more arithmetic)."""
    return len(_HLO_INSTR_RE.findall(hlo_text or ""))


def _as_cost_dict(cost):
    """cost_analysis() returns a flat dict on current jax and a
    list-of-dicts (one per computation, main first) on older releases."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    return cost if isinstance(cost, dict) else None


def program_ledger(compiled, hlo_text=None):
    """Resource ledger of one compiled program: XLA ``cost_analysis()``
    (flops, transcendentals, bytes accessed) and ``memory_analysis()``
    (argument/output/temp/code bytes, with ``peak_bytes`` derived as
    their alias-corrected sum) plus the HLO instruction count. Every
    probe is guarded: a backend where an analysis is absent or raises
    yields nulls for its fields — the census stays green, the trajectory
    row records the absence explicitly."""
    ledger = {"ledger_version": 1}
    ledger.update({field: None for field in LEDGER_FIELDS})
    try:
        cost = _as_cost_dict(compiled.cost_analysis())
    except Exception:
        cost = None
    if cost:
        for field, key in (("flops", "flops"),
                           ("transcendentals", "transcendentals"),
                           ("bytes_accessed", "bytes accessed")):
            try:
                value = cost.get(key)
                if value is not None:
                    ledger[field] = int(value)
            except (TypeError, ValueError):
                pass
    try:
        mem = compiled.memory_analysis()
    except Exception:
        mem = None
    if mem is not None:
        for field, attr in (
                ("argument_bytes", "argument_size_in_bytes"),
                ("output_bytes", "output_size_in_bytes"),
                ("temp_bytes", "temp_size_in_bytes"),
                ("generated_code_bytes", "generated_code_size_in_bytes")):
            try:
                ledger[field] = int(getattr(mem, attr))
            except Exception:
                pass
        sized = [ledger[f] for f in
                 ("argument_bytes", "output_bytes", "temp_bytes")]
        if any(v is not None for v in sized):
            try:
                alias = int(getattr(mem, "alias_size_in_bytes"))
            except Exception:
                alias = 0
            ledger["peak_bytes"] = max(
                sum(v or 0 for v in sized) - alias, 0)
    if hlo_text is not None:
        ledger["hlo_instructions"] = hlo_instruction_count(hlo_text)
    return ledger


def _compile_views(lowered):
    """(hlo_text, ledger) off ONE compile of a lowered program — the
    census must never pay a second XLA compile just to read costs."""
    compiled = lowered.compile()
    text = compiled.as_text()
    return text, program_ledger(compiled, hlo_text=text)


def _plan_of(solver):
    """Guarded plan provenance: a handle without plan_provenance() (or
    one that raises during lowering-time introspection) yields None —
    rendered downstream as plan=unversioned, never faked."""
    try:
        return solver.plan_provenance()
    except Exception:
        return None


# ------------------------------------------------------------ the records

class ProgramRecord:
    """One lowered census program plus the metadata contracts key on.

    meta keys (all optional; a contract that needs one it lacks does not
    apply):
      sharded: bool            — collective contracts apply
      state_bytes: int         — global state size for the gather bound
      expected_a2a_min: int    — declared all-to-all floor (DTP103)
      donated: int             — declared donated-buffer count (DTP104)
      fused_solve: bool        — triangular/pivot solves forbidden
      manual_auto: bool        — program carries a partial-auto shard_map
                                 (informational; DTP105 walks every jaxpr)
      waive: set[str]          — contract ids waived for this program
                                 (counted as suppressed, never dropped)
    """

    __slots__ = ("name", "description", "compiled_text", "jaxpr", "meta",
                 "build_sec", "skipped", "ledger", "plan")

    def __init__(self, name, description="", compiled_text=None, jaxpr=None,
                 meta=None, build_sec=0.0, skipped=None, ledger=None,
                 plan=None):
        self.name = name
        self.description = description
        self.compiled_text = compiled_text
        self.jaxpr = jaxpr
        self.meta = dict(meta or {})
        self.build_sec = build_sec
        self.skipped = skipped
        self.ledger = ledger      # program_ledger() dict (None: not costed)
        self.plan = plan          # plan_provenance() dict (None: no plan)

    def pseudo_path(self):
        return _PSEUDO_ROOT / f"{self.name}.hlo"

    def stats(self):
        """Per-program census row for the JSON report."""
        row = {"program": self.name, "build_sec": round(self.build_sec, 3)}
        if self.skipped:
            row["skipped"] = self.skipped
            return row
        if self.compiled_text is not None:
            row["collectives"] = collective_counts(self.compiled_text)
            row["donated_aliases"] = donated_alias_count(self.compiled_text)
        if self.jaxpr is not None:
            row["pads_in_auto_regions"] = pads_in_auto_regions(self.jaxpr)
            if "max_scan_length" in self.meta:
                lengths, whiles = scan_lengths(self.jaxpr)
                row["scan_lengths"] = sorted(set(lengths), reverse=True)
                row["while_loops"] = whiles
        for key in ("state_bytes", "expected_a2a_min", "donated",
                    "fused_solve", "manual_auto", "max_scan_length",
                    "untraced_sha256"):
            if key in self.meta:
                row[key] = self.meta[key]
        if self.ledger is not None:
            row["ledger"] = self.ledger
        return row


def record_from_jit(name, fn, args, meta=None, donate_argnums=(),
                    description="", compile=True):
    """Build a ProgramRecord from a plain function: jit (with the given
    donation), compile, and capture the jaxpr. The fixture surface the
    seeded-regression tests drive contracts with — and the documented way
    to census a new program shape that has no package handle yet.
    `compile=False` captures the jaxpr only: the DTP105 crash class
    ABORTS the process inside the XLA partitioner (a CHECK failure, not
    an exception), so a program seeded with it can only be inspected at
    the jaxpr level — which is exactly the tier the contract runs at."""
    import jax
    t0 = time.perf_counter()
    compiled_text = ledger = None
    if compile:
        lowered = jax.jit(  # dedalus-lint: disable=DTL003 (one-shot fixture lowering, never dispatched)
            fn, donate_argnums=donate_argnums).lower(*args)
        compiled_text, ledger = _compile_views(lowered)
    jaxpr = jax.make_jaxpr(fn)(*args)
    return ProgramRecord(name, description=description,
                         compiled_text=compiled_text, jaxpr=jaxpr,
                         meta=meta, build_sec=time.perf_counter() - t0,
                         ledger=ledger)


# ---------------------------------------------------------- the contracts

CONTRACTS = {}


def register_contract(cls):
    CONTRACTS[cls.id] = cls()
    return cls


def all_contracts():
    return [CONTRACTS[cid] for cid in sorted(CONTRACTS)]


class Contract:
    """Base contract: subclasses set id/severity/title and implement
    check(record) yielding Findings (same Finding type as the AST rules,
    so the baseline/JSON machinery is shared)."""

    id = None
    severity = "error"
    title = ""

    def check(self, record):
        raise NotImplementedError

    def finding(self, record, detail, message):
        """`detail` is the stable baseline-key snippet (survives line
        drift by construction: program findings have no lines)."""
        return Finding(self.id, self.severity, record.pseudo_path(), 1, 0,
                       f"[{record.name}] {message}", detail)


@register_contract
class NoFullStateGather(Contract):
    """DTP101: no all-gather at global state size in sharded programs.

    The weak-scaling claim (benchmarks/scaling.py, docs/performance.md)
    rests on the sharded step moving pencils with all-to-all transposes;
    XLA's SPMD partitioner degrades unpartitionable ops (ffts, LU custom
    calls) to all-gather + replicated compute SILENTLY — correct numerics,
    destroyed memory/scaling. Size-aware: a gather is a violation when one
    gathered buffer reaches GATHER_FRACTION of meta["state_bytes"]; the
    tau-line round-trips of the 2-D fleet composition
    (meshctx.gathered_apply) stay legal because the lines are small.
    """

    id = "DTP101"
    severity = "error"
    title = "no-full-state-gather"

    def check(self, record):
        if not record.meta.get("sharded") or record.compiled_text is None:
            return
        state = int(record.meta.get("state_bytes", 0))
        if not state:
            return
        for dtype, dims, nbytes in gather_buffers(record.compiled_text):
            if nbytes >= GATHER_FRACTION * state:
                yield self.finding(
                    record, f"all-gather {dtype}[{dims}]",
                    f"full-state all-gather of {dtype}[{dims}] "
                    f"({nbytes} B >= {GATHER_FRACTION:.0%} of the "
                    f"{state} B global state): a shard_map/sharding-"
                    "constraint route has regressed to GSPMD replication")


@register_contract
class NoForbiddenCustomCall(Contract):
    """DTP102: forbidden primitives/custom calls in step and grad bodies.

    Host callbacks have no transpose rule and serialize dispatch — they
    must never compile into a step or grad program (the runtime telemetry
    reads device buffers on a cadence instead). Programs declared
    meta["fused_solve"] additionally forbid triangular/pivot solve
    primitives: the fused substitution (core/fusedstep.py) precomposes
    the panel factors into GEMMs precisely so no solve_triangular custom
    call (measured ~19x an equivalent matmul) survives in the scan.
    """

    id = "DTP102"
    severity = "error"
    title = "no-forbidden-custom-call"

    _CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback",
                      "callback", "outside_call")
    _SOLVE_PRIMS = ("triangular_solve", "lu", "lu_pivots_to_permutation",
                    "custom_linear_solve")
    _CALLBACK_TARGETS = re.compile(r"callback|CpuCallback|py_func",
                                   re.IGNORECASE)
    _SOLVE_TARGETS = re.compile(
        r"lapack_\w*(getrf|trsm|gesv)|cusolver|cublas_\w*trsm")

    def check(self, record):
        prims = jaxpr_primitives(record.jaxpr) if record.jaxpr is not None \
            else {}
        for prim in self._CALLBACK_PRIMS:
            if prims.get(prim):
                yield self.finding(
                    record, f"primitive {prim}",
                    f"host callback primitive '{prim}' ({prims[prim]}x) "
                    "compiled into the program body: no transpose rule, "
                    "serializes dispatch; hoist the host work out of the "
                    "traced body")
        if record.meta.get("fused_solve"):
            for prim in self._SOLVE_PRIMS:
                if prims.get(prim):
                    yield self.finding(
                        record, f"primitive {prim}",
                        f"'{prim}' ({prims[prim]}x) inside a fused-"
                        "substitution program: the precomposed GEMM path "
                        "(core/fusedstep.py FUSED_SOLVE) has regressed to "
                        "per-step triangular/pivot solves")
        if record.compiled_text is None:
            return
        targets = set(re.findall(r'custom_call_target="([^"]+)"',
                                 record.compiled_text))
        for target in sorted(targets):
            if self._CALLBACK_TARGETS.search(target):
                yield self.finding(
                    record, f"custom-call {target}",
                    f"host-callback custom call '{target}' in the "
                    "compiled program body")
            elif record.meta.get("fused_solve") \
                    and self._SOLVE_TARGETS.search(target):
                yield self.finding(
                    record, f"custom-call {target}",
                    f"solver custom call '{target}' inside a fused-"
                    "substitution program")


@register_contract
class CollectiveCensus(Contract):
    """DTP103: the declared all-to-all floor per program.

    Chunked transpose stages (parallel/transposes.py) compile one
    all_to_all per chunk; a GSPMD fallback that re-routes a stage through
    gather + replicated transform REMOVES all-to-alls (DTP101 catches the
    gather only when it is state-sized — a per-stage degradation on a
    small axis can hide below that bound, but never below this count).
    """

    id = "DTP103"
    severity = "error"
    title = "collective-census"

    def check(self, record):
        expected = record.meta.get("expected_a2a_min")
        if expected is None or record.compiled_text is None:
            return
        got = collective_counts(record.compiled_text)["all-to-all"]
        if got < int(expected):
            yield self.finding(
                record, f"all-to-all {got} < {int(expected)}",
                f"{got} all-to-all op(s) compiled where the census "
                f"declares >= {int(expected)} (one per chunk per "
                "transpose stage): a chunked exchange degraded to a "
                "gather/replicated path")


@register_contract
class DonationHonored(Contract):
    """DTP104: declared donations must appear as input_output_alias.

    The fused multistep programs donate the three history buffers
    (F/MX/LX) so XLA rolls them in place; XLA silently DROPS a donation
    it cannot honor (layout mismatch, an aliasing conflict introduced by
    a refactor), turning a zero-copy update into three fresh state-sized
    allocations per step. lifted_jit.lower carries donate_argnums through
    precisely so this header is checkable.
    """

    id = "DTP104"
    severity = "error"
    title = "donation-honored"

    def check(self, record):
        expected = record.meta.get("donated")
        if not expected or record.compiled_text is None:
            return
        got = donated_alias_count(record.compiled_text)
        if got < int(expected):
            yield self.finding(
                record, f"aliases {got} < {int(expected)}",
                f"{got} input_output_alias entr"
                f"{'y' if got == 1 else 'ies'} compiled where "
                f"{int(expected)} donated buffer(s) are declared: a "
                "donation was dropped (silent per-step memory "
                "regression; check donate_argnums wiring and buffer "
                "aliasing)")


@register_contract
class ManualRegionIntegrity(Contract):
    """DTP105: no pad primitives inside partial-auto shard_map regions.

    jaxlib 0.4.37's SPMD partitioner hard-crashes (hlo_sharding_util
    CHECK IsManualSubgroup) propagating shardings through `pad` inside
    the GSPMD-auto subregion of a partially-manual shard_map — the region
    every per-member op of the 2-D batch x pencil fleet lives in. PR 13
    replaced the traced zero-pads with tools.array.zeropad (concat with
    zeros, bitwise identical); this contract detects a restored pad
    instead of letting the crash be rediscovered at the next mesh
    composition. Fully-manual regions are exempt: their pads are already
    explicitly partitioned.
    """

    id = "DTP105"
    severity = "error"
    title = "manual-region-integrity"

    def check(self, record):
        if record.jaxpr is None:
            return
        pads = pads_in_auto_regions(record.jaxpr)
        if pads:
            yield self.finding(
                record, f"pad-in-auto-region x{pads}",
                f"{pads} pad primitive(s) inside a partial-auto "
                "shard_map region (the jaxlib SPMD-partitioner crash "
                "class): lower zero padding through tools.array.zeropad, "
                "or route the op through an explicit manual shard_map")


@register_contract
class ScanDepthBound(Contract):
    """DTP106: the substitution depth claim, machine-checkable.

    The restructured solve compositions (libraries/solvecomp.py) exist
    to cut the banded substitution's sequential depth: ascan leaves NO
    sequential scan over the block rows (ceil(log2(NB))+1 bounds the
    residual-refinement loop and any bookkeeping scan), spike leaves
    exactly the C-step reduced coupling scan. A refactor that silently
    reintroduces an O(NB) lax.scan (or hides depth in a while loop,
    whose trip count is unprovable from the program text) would keep
    the numerics and lose the entire point — this contract fails it.
    Programs declare their bound via meta["max_scan_length"].
    """

    id = "DTP106"
    severity = "error"
    title = "scan-depth-bound"

    def check(self, record):
        bound = record.meta.get("max_scan_length")
        if bound is None or record.jaxpr is None:
            return
        lengths, whiles = scan_lengths(record.jaxpr)
        worst = max(lengths, default=0)
        if worst > int(bound):
            yield self.finding(
                record, f"scan length {worst} > {int(bound)}",
                f"a lax.scan of length {worst} compiled where the "
                f"declared substitution depth bound is {int(bound)}: "
                "the restructured solve has regressed to a sequential "
                "sweep (check SOLVE_COMPOSITION wiring and the "
                "solvecomp chunk/prefix programs)")
        if whiles:
            yield self.finding(
                record, f"while-loop x{whiles}",
                f"{whiles} while loop(s) in a depth-bounded program: "
                "trip counts are unprovable from the program text; use "
                "fixed-length lax.scan/fori_loop so the depth contract "
                "stays checkable")


@register_contract
class TracingInert(Contract):
    """DTP107: request tracing must not change the compiled program.

    The observability layer (tools/tracing.py, docs/observability.md)
    promises "structurally free when off, host-side only when on": spans
    wrap dispatch sites, never traced computations, so enabling tracing
    must leave the lowered step program byte-identical. A span helper
    that slips inside a jit boundary (or gates lowering on
    tracing.enabled()) would silently fork the compiled artifact and
    invalidate every cross-run comparison. Programs declare the
    tracing-DISABLED build's HLO hash via meta["untraced_sha256"]; the
    record's compiled_text is the tracing-ENABLED build of the same
    program."""

    id = "DTP107"
    severity = "error"
    title = "tracing-inert"

    def check(self, record):
        want = record.meta.get("untraced_sha256")
        if want is None or record.compiled_text is None:
            return
        got = hashlib.sha256(record.compiled_text.encode()).hexdigest()
        if got != want:
            yield self.finding(
                record, "traced/untraced HLO divergence",
                "the compiled step program differs between tracing "
                f"enabled (sha256 {got[:12]}) and disabled (sha256 "
                f"{want[:12]}): instrumentation has leaked into the "
                "lowered computation — spans must stay host-side "
                "(docs/observability.md)")


# ------------------------------------------------------------- the census

CENSUS = {}


def census(name, fast=True):
    """Register a census builder. `fast=False` marks the expensive
    builders (banded RB factor+fuse builds) excluded from the tier-1
    subset (tests/test_progcheck.py) but included in the full
    `lint --programs` run."""
    def wrap(fn):
        CENSUS[name] = (fn, bool(fast))
        return fn
    return wrap


def census_names(fast_only=False):
    return [n for n, (_, fast) in CENSUS.items() if fast or not fast_only]


class _pinned_config:
    """Pin config keys for one build (restored on exit): census programs
    must not depend on ambient [fusion]/[distributed] mutations."""

    def __init__(self, section, **keys):
        self.section = section
        self.keys = keys

    def __enter__(self):
        from ...tools.config import config
        if not config.has_section(self.section):
            config.add_section(self.section)
        self.saved = {k: config[self.section].get(k) for k in self.keys}
        for k, v in self.keys.items():
            config[self.section][k] = v

    def __exit__(self, *exc):
        from ...tools.config import config
        for k, v in self.saved.items():
            if v is None:
                config[self.section].pop(k, None)
            else:
                config[self.section][k] = v


def _solver_record(name, solver, description, extra_meta=None, dt=1e-3):
    """ProgramRecord of a solver's compiled step program via the
    timesteppers handle; donation expectation derives from the wrapper's
    own donate_argnums unless the builder pins it explicitly."""
    from ...core.timesteppers import step_program_handle
    prog, args = step_program_handle(solver, dt=dt)
    meta = {"donated": len(getattr(prog, "donate_argnums", ()))}
    meta.update(extra_meta or {})
    compiled_text, ledger = _compile_views(prog.lower(*args))
    jaxpr = prog.jaxpr(*args)
    return ProgramRecord(name, description=description,
                         compiled_text=compiled_text, jaxpr=jaxpr,
                         meta=meta, ledger=ledger, plan=_plan_of(solver))


def _need_devices(n):
    import jax
    have = len(jax.devices())
    if have < n:
        return (f"needs >= {n} devices, have {have} (set "
                "--xla_force_host_platform_device_count in XLA_FLAGS "
                "before JAX initializes)")
    return None


@census("diffusion_step")
def _census_diffusion_step():
    """Dense multistep (SBDF2) step program with donation pinned ON: the
    donation-honored anchor — the declared 3 history buffers (F/MX/LX)
    must alias outputs."""
    from ...extras.bench_problems import build_diffusion_solver
    with _pinned_config("fusion", DONATE_STEP="on", PALLAS="off"):
        solver = build_diffusion_solver(48)
        solver.step(1e-3)
        rec = _solver_record(
            "diffusion_step", solver,
            "dense SBDF2 diffusion step (donating multistep program)",
            extra_meta={"donated": 3})
    return [rec]


@census("rb_step_fused", fast=False)
def _census_rb_fused():
    """Banded Rayleigh-Benard step with FUSED_SOLVE pinned on: the
    precomposed-GEMM substitution — triangular/pivot solves forbidden."""
    from ...extras.bench_problems import build_rb_solver
    with _pinned_config("fusion", FUSED_SOLVE="on", FUSED_MATVEC="auto",
                        FUSED_TRANSFORMS="off", DONATE_STEP="auto",
                        PALLAS="off"):
        solver, _ = build_rb_solver(16, 32, np.float64, matsolver="banded")
        solver.step(1e-3)
        rec = _solver_record(
            "rb_step_fused", solver,
            "banded RB RK222 step, fused substitution (no triangular/"
            "pivot solves)", extra_meta={"fused_solve": True})
    return [rec]


@census("rb_step_unfused", fast=False)
def _census_rb_unfused():
    """The same banded RB step with fusion off: breadth coverage (the
    unfused path legitimately carries triangular solves, so only the
    callback contract applies)."""
    from ...extras.bench_problems import build_rb_solver
    with _pinned_config("fusion", FUSED_SOLVE="off", FUSED_MATVEC="off",
                        FUSED_TRANSFORMS="off", DONATE_STEP="off",
                        PALLAS="off"):
        solver, _ = build_rb_solver(16, 32, np.float64, matsolver="banded")
        solver.step(1e-3)
        rec = _solver_record(
            "rb_step_unfused", solver,
            "banded RB RK222 step, fusion off (legacy substitution)")
    return [rec]


@census("tau_step_ascan")
def _census_tau_ascan():
    """Banded tau-IVP step with the associative-scan substitution
    (SOLVE_COMPOSITION=ascan): no triangular/pivot solves (DTP102) AND
    no sequential scan over the block rows — the depth claim of the
    log-depth composition, bounded at ceil(log2(NB))+1 (DTP106). The
    small banded problem keeps this in the fast tier-1 subset."""
    import math
    from ...extras.bench_problems import build_tau_ivp
    with _pinned_config("fusion", FUSED_SOLVE="on", SOLVE_COMPOSITION="ascan",
                        PALLAS="off"):
        solver, u, x, z = build_tau_ivp(8, 32, matsolver="banded")
        solver.step(1e-3)
        bound = math.ceil(math.log2(solver.ops.NB)) + 1
        rec = _solver_record(
            "tau_step_ascan", solver,
            "banded tau-IVP SBDF2 step, associative-scan substitution "
            f"(NB={solver.ops.NB}, depth bound {bound})",
            extra_meta={"fused_solve": True, "max_scan_length": bound})
    return [rec]


@census("rb_step_spike", fast=False)
def _census_rb_spike():
    """Banded Rayleigh-Benard step with the SPIKE-chunked substitution:
    the only sequential scan left is the C-step reduced coupling
    (DTP106 bound = C), and the chunk GEMM program still carries no
    triangular/pivot custom calls (DTP102)."""
    from ...extras.bench_problems import build_rb_solver
    from ...libraries import solvecomp
    with _pinned_config("fusion", FUSED_SOLVE="on", SOLVE_COMPOSITION="spike",
                        SPIKE_CHUNKS="auto", PALLAS="off"):
        solver, _ = build_rb_solver(16, 32, np.float64, matsolver="banded")
        solver.step(1e-3)
        chunks = solvecomp.spike_chunk_count(
            solver.ops.NB - 1, solver._solve_plan.spike_chunks)
        rec = _solver_record(
            "rb_step_spike", solver,
            f"banded RB RK222 step, SPIKE substitution (C={chunks})",
            extra_meta={"fused_solve": True, "max_scan_length": chunks})
    return [rec]


@census("rb_step_ladder", fast=False)
def _census_rb_ladder():
    """The precision-laddered banded RB step (SPIKE + f32 operators +
    f64 residual refinement): the fused-solve and depth contracts must
    survive the low-dtype factor store, and the fixed-trip refinement
    loop must stay inside the depth bound (no while loops)."""
    from ...extras.bench_problems import build_rb_solver
    from ...libraries import solvecomp
    with _pinned_config("fusion", FUSED_SOLVE="on", SOLVE_COMPOSITION="spike",
                        SPIKE_CHUNKS="auto", PALLAS="off"):
        with _pinned_config("precision", SOLVE_DTYPE="f32",
                            REFINE_SWEEPS="auto"):
            solver, _ = build_rb_solver(16, 32, np.float64,
                                        matsolver="banded")
            solver.step(1e-3)
            chunks = solvecomp.spike_chunk_count(
                solver.ops.NB - 1, solver._solve_plan.spike_chunks)
            sweeps = solver._solve_plan.sweeps or 0
            rec = _solver_record(
                "rb_step_ladder", solver,
                "banded RB RK222 step, f32 precision ladder over SPIKE "
                f"(C={chunks}, {sweeps} refinement sweeps)",
                extra_meta={"fused_solve": True,
                            "max_scan_length": max(chunks, sweeps)})
    return [rec]


@census("rb_step_tuned", fast=False)
def _census_rb_tuned():
    """The banded RB step built under an AUTOTUNED plan decision
    (tools/autotune.py): a seeded spike/f32 decision is consulted from
    the in-process memo at build time — zero microbench probes, the
    warm-path contract — and the resulting tuned step program must
    honor the same compiled contracts as the hand-picked plans: no
    full-state gather (DTP101), no triangular/pivot custom calls in the
    fused solve (DTP102), and the scan depth bounded by the decision's
    own chunk/sweep schedule (DTP106)."""
    from ...extras.bench_problems import build_rb_solver
    from ...libraries import solvecomp
    from ...tools import autotune
    with _pinned_config("fusion", FUSED_SOLVE="on",
                        SOLVE_COMPOSITION="auto", SPIKE_CHUNKS="auto",
                        PALLAS="off"):
        with _pinned_config("precision", SOLVE_DTYPE="auto",
                            REFINE_SWEEPS="auto"):
            with _pinned_config("autotune", MODE="off"):
                # plan-independent signature probe (matrices and shape
                # do not depend on the solve plan)
                ref, _ = build_rb_solver(16, 32, np.float64,
                                         matsolver="banded")
                sig = autotune.solver_signature(ref)
            autotune.seed_decision(sig, {
                "composition": "spike", "solve_dtype": "f32",
                "refine_sweeps": 2, "spike_chunks": 0, "pallas": False,
                "fused_transforms": None, "transpose_chunks": None},
                evidence_kind="seeded")
            try:
                with _pinned_config("autotune", MODE="cached"):
                    before = autotune.probe_count()
                    solver, _ = build_rb_solver(16, 32, np.float64,
                                                matsolver="banded")
                    probes = autotune.probe_count() - before
            finally:
                autotune.clear_memo()
    if probes:
        raise AssertionError(
            f"tuned build ran {probes} microbench probe(s); a cached "
            "decision must build probe-free")
    if getattr(solver, "_plan_source", None) != "tuned" \
            or solver._solve_plan.composition != "spike" \
            or solver._solve_plan.dtype != "f32":
        raise AssertionError(
            f"seeded decision not applied: source="
            f"{getattr(solver, '_plan_source', None)}, "
            f"plan={solver._solve_plan!r}")
    solver.step(1e-3)
    chunks = solvecomp.spike_chunk_count(
        solver.ops.NB - 1, solver._solve_plan.spike_chunks)
    sweeps = solver._solve_plan.sweeps or 0
    rec = _solver_record(
        "rb_step_tuned", solver,
        "banded RB RK222 step under a seeded autotune decision "
        f"(spike/f32 ladder, C={chunks}, {sweeps} sweeps, zero probes)",
        extra_meta={"fused_solve": True,
                    "max_scan_length": max(chunks, sweeps)})
    return [rec]


@census("traced_step")
def _census_traced_step():
    """The dense diffusion step lowered twice — request tracing disabled,
    then enabled — with the disabled build's HLO hash declared in meta so
    DTP107 can assert the enabled build is byte-identical: the
    observability layer's zero-overhead-when-off claim as a
    machine-checked structural fact, not a benchmark delta."""
    from ...tools import tracing
    from ...extras.bench_problems import build_diffusion_solver
    from ...core.timesteppers import step_program_handle

    def compiled_step():
        solver = build_diffusion_solver(32)
        solver.step(1e-3)
        prog, args = step_program_handle(solver, dt=1e-3)
        meta = {"donated": len(getattr(prog, "donate_argnums", ()))}
        text, ledger = _compile_views(prog.lower(*args))
        return text, prog.jaxpr(*args), meta, ledger, _plan_of(solver)

    was_on = tracing.enabled()
    with _pinned_config("fusion", DONATE_STEP="on", PALLAS="off"):
        try:
            tracing.disable()
            off_text, _, _, _, _ = compiled_step()
            tracing.enable()
            on_text, jaxpr, meta, ledger, plan = compiled_step()
        finally:
            if not was_on:
                tracing.disable()
    meta["untraced_sha256"] = hashlib.sha256(off_text.encode()).hexdigest()
    return [ProgramRecord(
        "traced_step",
        description="dense SBDF2 diffusion step lowered under tracing "
                    "(must match the untraced build byte-for-byte)",
        compiled_text=on_text, jaxpr=jaxpr, meta=meta, ledger=ledger,
        plan=plan)]


@census("sharded_step_1d")
def _census_sharded_step():
    """The tests/test_collectives.py program shape: a 4-device sharded
    step must move pencils with all-to-alls and zero full-state
    gathers."""
    skip = _need_devices(4)
    if skip:
        return [ProgramRecord("sharded_step_1d", skipped=skip)]
    import jax
    from jax.sharding import Mesh
    from ...extras.bench_problems import build_tau_ivp
    from ...parallel import distribute_solver
    solver, u, x, z = build_tau_ivp()
    distribute_solver(solver, Mesh(np.array(jax.devices()[:4]), ("x",)))
    solver.step(1e-3)
    rec = _solver_record(
        "sharded_step_1d", solver,
        "SBDF2 tau-IVP step sharded over a 1-D 4-device pencil mesh",
        extra_meta={"sharded": True, "state_bytes": int(solver.X.nbytes),
                    "expected_a2a_min": 2})
    return [rec]


@census("chunked_walk_1d")
def _census_chunked_walk():
    """Overlapped chunked transpose walks (chunks=2) on a 1-D mesh: one
    all_to_all per chunk per stage, zero gathers, both directions."""
    skip = _need_devices(4)
    if skip:
        return [ProgramRecord("chunked_walk_to_grid", skipped=skip),
                ProgramRecord("chunked_walk_to_coeff", skipped=skip)]
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from ...extras.bench_problems import build_tau_ivp
    from ...parallel import DistributedPencilPipeline
    solver, u, x, z = build_tau_ivp()
    mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
    pipe = DistributedPencilPipeline(u.domain, mesh, "x", chunks=2)
    cdata = np.asarray(u["c"])
    c_sh = jax.device_put(cdata, NamedSharding(mesh, P("x", None)))
    records = []
    # pipeline walks have no solver and thus no plan_provenance(); the
    # chunk count IS the plan-relevant knob, declared as a minimal plan
    walk_plan = {"plan_version": 1, "transpose_chunks": 2}
    prog_g = jax.jit(pipe.to_grid)  # dedalus-lint: disable=DTL003 (one-shot census lowering)
    g = prog_g(c_sh)
    text_g, ledger_g = _compile_views(prog_g.lower(c_sh))
    records.append(ProgramRecord(
        "chunked_walk_to_grid",
        description="chunked (C=2) coeff->grid walk, 1-D pencil mesh",
        compiled_text=text_g,
        jaxpr=jax.make_jaxpr(pipe.to_grid)(c_sh),
        meta={"sharded": True, "state_bytes": int(cdata.nbytes),
              "expected_a2a_min": 2},
        ledger=ledger_g, plan=dict(walk_plan)))
    prog_c = jax.jit(pipe.to_coeff)  # dedalus-lint: disable=DTL003 (one-shot census lowering)
    text_c, ledger_c = _compile_views(prog_c.lower(g))
    records.append(ProgramRecord(
        "chunked_walk_to_coeff",
        description="chunked (C=2) grid->coeff walk, 1-D pencil mesh",
        compiled_text=text_c,
        jaxpr=jax.make_jaxpr(pipe.to_coeff)(g),
        meta={"sharded": True, "state_bytes": int(cdata.nbytes),
              "expected_a2a_min": 2},
        ledger=ledger_c, plan=dict(walk_plan)))
    return records


@census("chunked_walk_2dmesh")
def _census_chunked_walk_2d():
    """R=2 chunked walk on a 2-D (2x4) pencil mesh over a 3-D domain:
    both mesh axes' stages chunk — the walk composition the 2048x1024
    north star runs."""
    skip = _need_devices(8)
    if skip:
        return [ProgramRecord("chunked_walk_2dmesh", skipped=skip)]
    import jax
    import dedalus_tpu.public as d3
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from ...parallel import DistributedPencilPipeline
    coords = d3.CartesianCoordinates("x", "y", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=8, bounds=(0, 2 * np.pi))
    yb = d3.RealFourier(coords["y"], size=8, bounds=(0, 2 * np.pi))
    # z=16 so BOTH stages' destination blocks tile their mesh axis into
    # 2 chunks (16/4=4, 8/2=4): the declared a2a floor is 2 per stage
    zb = d3.ChebyshevT(coords["z"], size=16, bounds=(0, 1))
    f = dist.Field(name="f", bases=(xb, yb, zb))
    x, y, z = dist.local_grids(xb, yb, zb)
    f["g"] = np.sin(2 * x) * np.cos(y) * z ** 2 + np.sin(y) + 1
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("px", "py"))
    pipe = DistributedPencilPipeline(f.domain, mesh, ("px", "py"), chunks=2)
    cdata = np.asarray(f["c"])
    c_sh = jax.device_put(cdata,
                          NamedSharding(mesh, P("px", "py", None)))
    prog = jax.jit(pipe.to_grid)  # dedalus-lint: disable=DTL003 (one-shot census lowering)
    text, ledger = _compile_views(prog.lower(c_sh))
    return [ProgramRecord(
        "chunked_walk_2dmesh",
        description="chunked (C=2) coeff->grid walk, 2-D (2x4) mesh, "
                    "3-D domain",
        compiled_text=text,
        jaxpr=jax.make_jaxpr(pipe.to_grid)(c_sh),
        meta={"sharded": True, "state_bytes": int(cdata.nbytes),
              "expected_a2a_min": 4},
        ledger=ledger, plan={"plan_version": 1, "transpose_chunks": 2})]


@census("fleet_2d")
def _census_fleet_2d():
    """The 2-D batch x pencil fleet step program (members vmapped over
    batch, pencils GSPMD-auto inside the manual member shard_map): zero
    full-state gathers — the assertion this program never had — plus the
    pad-free partial-auto region."""
    skip = _need_devices(8)
    if skip:
        return [ProgramRecord("fleet_2d", skipped=skip)]
    import jax
    from jax.sharding import Mesh
    from ...extras.bench_problems import build_tau_ivp
    solver, u, x, z = build_tau_ivp()
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("batch", "pencil"))
    fleet = solver.ensemble(2, mesh=mesh)

    def ics(i):
        u["g"] = np.sin(np.pi * z) * (1 + 0.1 * (i + 1)
                                      * np.cos(np.pi * x / 2))

    fleet.init_members(ics)
    fleet.step_many(4, 1e-3)
    prog, args = fleet.step_program_handle()
    text, ledger = _compile_views(prog.lower(*args))
    return [ProgramRecord(
        "fleet_2d",
        description="2-member fleet step on a 2-D (2 batch x 4 pencil) "
                    "mesh",
        compiled_text=text,
        jaxpr=jax.make_jaxpr(prog)(*args),
        meta={"sharded": True, "state_bytes": int(fleet.X.nbytes),
              "expected_a2a_min": 2, "manual_auto": True},
        ledger=ledger, plan=_plan_of(solver))]


@census("ensemble_fleet_1d")
def _census_fleet_1d():
    """The plain vmapped ensemble fleet step on a 1-D member mesh: the
    serving micro-batch program shape (service/batching.py anchors on
    exactly this fleet)."""
    skip = _need_devices(2)
    if skip:
        return [ProgramRecord("ensemble_fleet_1d", skipped=skip)]
    import jax
    from jax.sharding import Mesh
    from ...extras.bench_problems import build_tau_ivp
    solver, u, x, z = build_tau_ivp()
    fleet = solver.ensemble(2, mesh=Mesh(np.array(jax.devices()[:2]),
                                         ("batch",)))

    def ics(i):
        u["g"] = np.sin(np.pi * z) * (1 + 0.1 * (i + 1)
                                      * np.cos(np.pi * x / 2))

    fleet.init_members(ics)
    fleet.step_many(4, 1e-3)
    prog, args = fleet.step_program_handle()
    text, ledger = _compile_views(prog.lower(*args))
    return [ProgramRecord(
        "ensemble_fleet_1d",
        description="2-member vmapped fleet step, 1-D member mesh",
        compiled_text=text,
        jaxpr=jax.make_jaxpr(prog)(*args),
        meta={"sharded": True, "state_bytes": int(fleet.X.nbytes)},
        ledger=ledger, plan=_plan_of(solver))]


@census("adjoint_grad")
def _census_adjoint():
    """The compiled value_and_grad program (checkpointed-backprop scan +
    custom-VJP adjoint solves): host callbacks would break the transpose
    — forbidden."""
    import jax.numpy as jnp
    from ...extras.bench_problems import build_diffusion_solver
    solver = build_diffusion_solver(48)
    div = solver.differentiable(wrt=("initial_state",),
                                loss=lambda X: jnp.sum(X * X))
    prog, args = div.grad_program_handle(4, 1e-3)
    text, ledger = _compile_views(prog.lower(*args))
    return [ProgramRecord(
        "adjoint_grad",
        description="value_and_grad over 4 SBDF2 diffusion steps "
                    "(checkpointed adjoint)",
        compiled_text=text,
        jaxpr=prog.jaxpr(*args),
        ledger=ledger, plan=_plan_of(solver))]


@census("pool_step")
def _census_pool_step():
    """A warm-pool entry's compiled step program (the serving path):
    pooled programs carry the same donation/callback contracts as
    in-process solves — a pool-only regression must fail the census, not
    surface as a served memory blowup."""
    from ...service.pool import SolverPool
    with _pinned_config("fusion", DONATE_STEP="on", PALLAS="off"):
        pool = SolverPool(size=1)
        entry, verdict, _ = pool.acquire(
            {"problem": "diffusion", "params": {"size": 32}})
        solver = entry.solver
        solver.step(1e-3)
        rec = _solver_record(
            "pool_step", solver,
            f"warm-pool diffusion entry step program (verdict {verdict})",
            extra_meta={"donated": 3})
    return [rec]


# -------------------------------------------------------------- the runner

def run_census(names=None, fast_only=False):
    """Build the census. Returns (records, timings): every registered
    (or selected) program builds exactly once; a builder needing more
    devices than the process has yields skipped records (reported, never
    silently absent). Raises KeyError on an unknown selection — a typo'd
    program name must not report a clean census."""
    selected = census_names(fast_only) if names is None else list(names)
    unknown = [n for n in selected if n not in CENSUS]
    if unknown:
        raise KeyError(f"unknown census program(s) {unknown}; "
                       f"known: {sorted(CENSUS)}")
    records = []
    timings = {}
    for name in selected:
        builder, _ = CENSUS[name]
        t0 = time.perf_counter()
        built = builder()
        wall = time.perf_counter() - t0
        timings[name] = wall
        for rec in built:
            if not rec.build_sec:
                rec.build_sec = wall / max(len(built), 1)
            records.append(rec)
    return records, timings


def ledger_rows(records):
    """One `kind: ledger` trajectory row per costed census program, in
    the benchmarks/results.jsonl vocabulary: the program's resource
    ledger plus scan depth, plan provenance, and the host/environment
    fingerprint — the read-side input of tools/perfwatch.py. Skipped or
    un-costed records yield no row (absence stays explicit in the census
    report instead)."""
    from ..envinfo import env_fingerprint
    try:
        import jax
        backend = str(jax.default_backend())
    except Exception:
        backend = None
    env = env_fingerprint()
    rows = []
    for rec in records:
        if rec.skipped or rec.ledger is None:
            continue
        row = {"kind": "ledger", "config": "progcheck_census",
               "program": rec.name, "backend": backend}
        row.update(rec.ledger)
        if rec.jaxpr is not None:
            lengths, whiles = scan_lengths(rec.jaxpr)
            row["scan_max_length"] = max(lengths, default=0)
            row["while_loops"] = whiles
        row["plan"] = rec.plan
        row["env"] = env
        rows.append(row)
    return rows


def append_ledger_rows(records, path=None):
    """Persist ledger rows alongside the perf rows. Opt-in by design:
    the census itself never writes — tests and ad-hoc runs must not
    grow the checked-in trajectory. Returns the appended rows."""
    import json
    path = pathlib.Path(path) if path \
        else PACKAGE_DIR.parent / "benchmarks" / "results.jsonl"
    rows = ledger_rows(records)
    ts = round(time.time(), 1)
    with open(path, "a") as f:
        for row in rows:
            row.setdefault("ts", ts)
            f.write(json.dumps(row) + "\n")
    return rows


def check_records(records, contracts=None):
    """Run the contract registry over census records. Returns
    (findings, suppressed, contract_timings); per-record waivers land in
    `suppressed` (counted, never hidden), skipped records are not
    checked."""
    contracts = all_contracts() if contracts is None else contracts
    findings, suppressed = [], []
    timings = {}
    for contract in contracts:
        t0 = time.perf_counter()
        for rec in records:
            if rec.skipped:
                continue
            for finding in contract.check(rec):
                if contract.id in rec.meta.get("waive", ()):
                    suppressed.append(finding)
                else:
                    findings.append(finding)
        timings[contract.id] = timings.get(contract.id, 0.0) \
            + time.perf_counter() - t0
    return findings, suppressed, timings


def run_programs(names=None, contracts=None, fast_only=False,
                 baseline_path=None, no_baseline=False, ledger_path=None):
    """The programs-tier entry point (cli --programs and
    tests/test_progcheck.py): census + contracts + baseline. Returns the
    summary dict the CLI renders:
    {programs, findings (new, as dicts), summary{total,new,baselined,
    suppressed,stale}, timings{census,contracts}}.

    `ledger_path` (cli --ledger) additionally appends one `kind: ledger`
    trajectory row per costed program there; the default call appends
    nothing."""
    if contracts is not None:
        unknown = [c for c in contracts if c not in CONTRACTS]
        if unknown:
            raise KeyError(f"unknown contract(s) {unknown}; "
                           f"known: {sorted(CONTRACTS)}")
        contracts = [CONTRACTS[c] for c in contracts]
    records, census_timings = run_census(names, fast_only=fast_only)
    findings, suppressed, contract_timings = check_records(records,
                                                           contracts)
    baseline = {} if no_baseline \
        else load_baseline(baseline_path or PROGRAMS_BASELINE)
    new, stale = apply_baseline(findings, baseline)
    ledger_appended = None
    if ledger_path is not None:
        ledger_appended = len(append_ledger_rows(records, ledger_path))
    return {
        "programs": [rec.stats() for rec in records],
        "findings": [f.to_dict() for f in new],
        "summary": {
            "total": len(findings),
            "new": len(new),
            "baselined": len(findings) - len(new),
            "suppressed": len(suppressed),
            "stale": stale,
            "checked": sum(1 for r in records if not r.skipped),
            "skipped": [r.name for r in records if r.skipped],
            **({"ledger_rows": ledger_appended}
               if ledger_appended is not None else {}),
        },
        "timings": {
            "census": {k: round(v, 3) for k, v in census_timings.items()},
            "contracts": {k: round(v, 4)
                          for k, v in contract_timings.items()},
        },
    }
