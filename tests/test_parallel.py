"""
Distributed execution tests on a virtual 8-device CPU mesh
(reference: dedalus/tests_parallel/ — which requires real mpiexec; here the
sharding semantics are identical on virtual and real devices, so the
collective pencil machinery is exercised in CI).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import dedalus_tpu.public as d3
from dedalus_tpu.parallel import (all_to_all_transpose,
                                  DistributedPencilPipeline,
                                  distribute_solver, pencil_sharding)

N_DEV = len(jax.devices())
needs_devices = pytest.mark.skipif(N_DEV < 4, reason="needs >= 4 devices")


def make_mesh(n=None):
    n = n or min(N_DEV, 8)
    return Mesh(np.array(jax.devices()[:n]), ("x",))


@needs_devices
def test_all_to_all_transpose_roundtrip():
    mesh = make_mesh(4)
    rng = np.random.default_rng(0)
    data = rng.standard_normal((16, 8))
    sharded = jax.device_put(data, NamedSharding(mesh, P("x", None)))
    out = all_to_all_transpose(sharded, 0, 1, mesh, "x")
    # global values unchanged, sharding moved to axis 1
    assert np.allclose(np.asarray(out), data)
    assert out.sharding.spec == P(None, "x")
    back = all_to_all_transpose(out, 1, 0, mesh, "x")
    assert np.allclose(np.asarray(back), data)
    assert back.sharding.spec in (P("x"), P("x", None))


@needs_devices
def test_distributed_pencil_pipeline_matches_local():
    """The shard_map all_to_all pipeline reproduces the local transforms."""
    mesh = make_mesh(4)
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 2 * np.pi))
    zb = d3.ChebyshevT(coords["z"], size=8, bounds=(0, 1))
    f = dist.Field(name="f", bases=(xb, zb))
    x, z = dist.local_grids(xb, zb)
    f["g"] = np.sin(3 * x) * z ** 2 + np.cos(x) * z + 1
    cdata = np.asarray(f["c"])
    gdata = np.asarray(f["g"])
    pipeline = DistributedPencilPipeline(f.domain, mesh, "x")
    c_sharded = jax.device_put(cdata, NamedSharding(mesh, P("x", None)))
    g_out = jax.jit(pipeline.to_grid)(c_sharded)
    assert np.allclose(np.asarray(g_out), gdata, atol=1e-12)
    c_back = jax.jit(pipeline.to_coeff)(g_out)
    assert np.allclose(np.asarray(c_back), cdata, atol=1e-12)


@needs_devices
def test_sharded_ivp_step_matches_single_device():
    """A full sharded IMEX step (transforms + nonlinear RHS + batched solve
    under GSPMD) bit-matches the single-device step."""
    mesh = make_mesh(4)

    def build():
        coords = d3.CartesianCoordinates("x", "z")
        dist = d3.Distributor(coords, dtype=np.float64)
        xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 4.0), dealias=3 / 2)
        zb = d3.ChebyshevT(coords["z"], size=8, bounds=(0, 1.0), dealias=3 / 2)
        u = dist.Field(name="u", bases=(xb, zb))
        t1 = dist.Field(name="t1", bases=xb)
        t2 = dist.Field(name="t2", bases=xb)
        lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
        problem = d3.IVP([u, t1, t2], namespace=locals())
        problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = - u*u")
        problem.add_equation("u(z=0) = 0")
        problem.add_equation("u(z=1) = 0")
        solver = problem.build_solver(d3.SBDF2)
        x, z = dist.local_grids(xb, zb)
        u["g"] = np.sin(np.pi * z) * (1 + 0.3 * np.cos(np.pi * x / 2))
        return solver, u

    solver_ref, u_ref = build()
    for _ in range(5):
        solver_ref.step(1e-3)
    X_ref = np.asarray(solver_ref.X)

    solver_sh, u_sh = build()
    distribute_solver(solver_sh, mesh)
    for _ in range(5):
        solver_sh.step(1e-3)
    assert solver_sh.X.sharding.spec in (P("x"), P("x", None))
    assert np.allclose(np.asarray(solver_sh.X), X_ref, atol=1e-13)


@needs_devices
def test_distribute_solver_via_dist_mesh():
    """Passing mesh through the Distributor shards the solver state."""
    mesh = make_mesh(4)
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64, mesh=mesh)
    xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 4.0))
    zb = d3.ChebyshevT(coords["z"], size=8, bounds=(0, 1.0))
    u = dist.Field(name="u", bases=(xb, zb))
    t1 = dist.Field(name="t1", bases=xb)
    t2 = dist.Field(name="t2", bases=xb)
    lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
    problem = d3.IVP([u, t1, t2], namespace=locals())
    problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = 0")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("u(z=1) = 0")
    solver = problem.build_solver(d3.SBDF1)
    distribute_solver(solver)
    solver.step(1e-3)
    assert solver.X.sharding.spec in (P("x"), P("x", None))
    assert np.all(np.isfinite(np.asarray(solver.X)))


@needs_devices
def test_sharded_shell_step():
    """3D shell: (m, ell) pencil batch sharded over the mesh."""
    mesh = make_mesh(4)

    def build():
        cs = d3.SphericalCoordinates("phi", "theta", "r")
        dist = d3.Distributor(cs, dtype=np.float64)
        shell = d3.ShellBasis(cs, shape=(8, 8, 8), radii=(1.0, 2.0),
                              dealias=(3 / 2,) * 3, dtype=np.float64)
        phi, theta, r = dist.local_grids(shell)
        u = dist.Field(name="u", bases=shell)
        t1 = dist.Field(name="t1", bases=shell.S2_basis(2.0))
        t2 = dist.Field(name="t2", bases=shell.S2_basis(1.0))
        lift = lambda A, n: d3.Lift(A, shell.derivative_basis(2), n)
        problem = d3.IVP([u, t1, t2], namespace=locals())
        problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = - u*u")
        problem.add_equation("u(r=1.0) = 0")
        problem.add_equation("u(r=2.0) = 0")
        solver = problem.build_solver(d3.SBDF2)
        u["g"] = np.sin(np.pi * (np.asarray(r) - 1.0))
        return solver

    solver = build()
    for _ in range(3):
        solver.step(1e-3)
    X_ref = np.asarray(solver.X)

    solver2 = build()
    distribute_solver(solver2, mesh)
    for _ in range(3):
        solver2.step(1e-3)
    assert np.allclose(np.asarray(solver2.X), X_ref, atol=1e-13)
