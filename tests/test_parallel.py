"""
Distributed execution tests on a virtual 8-device CPU mesh
(reference: dedalus/tests_parallel/ — which requires real mpiexec; here the
sharding semantics are identical on virtual and real devices, so the
collective pencil machinery is exercised in CI).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import dedalus_tpu.public as d3
from dedalus_tpu.parallel import (all_to_all_transpose,
                                  DistributedPencilPipeline,
                                  distribute_solver, pencil_sharding)

N_DEV = len(jax.devices())
needs_devices = pytest.mark.skipif(N_DEV < 4, reason="needs >= 4 devices")


def make_mesh(n=None):
    n = n or min(N_DEV, 8)
    return Mesh(np.array(jax.devices()[:n]), ("x",))


@needs_devices
def test_all_to_all_transpose_roundtrip():
    mesh = make_mesh(4)
    rng = np.random.default_rng(0)
    data = rng.standard_normal((16, 8))
    sharded = jax.device_put(data, NamedSharding(mesh, P("x", None)))
    out = all_to_all_transpose(sharded, 0, 1, mesh, "x")
    # global values unchanged, sharding moved to axis 1
    assert np.allclose(np.asarray(out), data)
    assert out.sharding.spec == P(None, "x")
    back = all_to_all_transpose(out, 1, 0, mesh, "x")
    assert np.allclose(np.asarray(back), data)
    assert back.sharding.spec in (P("x"), P("x", None))


@needs_devices
def test_distributed_pencil_pipeline_matches_local():
    """The shard_map all_to_all pipeline reproduces the local transforms."""
    mesh = make_mesh(4)
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 2 * np.pi))
    zb = d3.ChebyshevT(coords["z"], size=8, bounds=(0, 1))
    f = dist.Field(name="f", bases=(xb, zb))
    x, z = dist.local_grids(xb, zb)
    f["g"] = np.sin(3 * x) * z ** 2 + np.cos(x) * z + 1
    cdata = np.asarray(f["c"])
    gdata = np.asarray(f["g"])
    pipeline = DistributedPencilPipeline(f.domain, mesh, "x")
    c_sharded = jax.device_put(cdata, NamedSharding(mesh, P("x", None)))
    g_out = jax.jit(pipeline.to_grid)(c_sharded)
    assert np.allclose(np.asarray(g_out), gdata, atol=1e-12)
    c_back = jax.jit(pipeline.to_coeff)(g_out)
    assert np.allclose(np.asarray(c_back), cdata, atol=1e-12)


@needs_devices
def test_sharded_ivp_step_matches_single_device():
    """A full sharded IMEX step (transforms + nonlinear RHS + batched solve
    under GSPMD) bit-matches the single-device step."""
    mesh = make_mesh(4)

    def build():
        coords = d3.CartesianCoordinates("x", "z")
        dist = d3.Distributor(coords, dtype=np.float64)
        xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 4.0), dealias=3 / 2)
        zb = d3.ChebyshevT(coords["z"], size=8, bounds=(0, 1.0), dealias=3 / 2)
        u = dist.Field(name="u", bases=(xb, zb))
        t1 = dist.Field(name="t1", bases=xb)
        t2 = dist.Field(name="t2", bases=xb)
        lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
        problem = d3.IVP([u, t1, t2], namespace=locals())
        problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = - u*u")
        problem.add_equation("u(z=0) = 0")
        problem.add_equation("u(z=1) = 0")
        solver = problem.build_solver(d3.SBDF2)
        x, z = dist.local_grids(xb, zb)
        u["g"] = np.sin(np.pi * z) * (1 + 0.3 * np.cos(np.pi * x / 2))
        return solver, u

    solver_ref, u_ref = build()
    for _ in range(5):
        solver_ref.step(1e-3)
    X_ref = np.asarray(solver_ref.X)

    solver_sh, u_sh = build()
    distribute_solver(solver_sh, mesh)
    for _ in range(5):
        solver_sh.step(1e-3)
    assert solver_sh.X.sharding.spec in (P("x"), P("x", None))
    assert np.allclose(np.asarray(solver_sh.X), X_ref, atol=1e-13)


@needs_devices
def test_distribute_solver_via_dist_mesh():
    """Passing mesh through the Distributor shards the solver state."""
    mesh = make_mesh(4)
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64, mesh=mesh)
    xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 4.0))
    zb = d3.ChebyshevT(coords["z"], size=8, bounds=(0, 1.0))
    u = dist.Field(name="u", bases=(xb, zb))
    t1 = dist.Field(name="t1", bases=xb)
    t2 = dist.Field(name="t2", bases=xb)
    lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
    problem = d3.IVP([u, t1, t2], namespace=locals())
    problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = 0")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("u(z=1) = 0")
    solver = problem.build_solver(d3.SBDF1)
    distribute_solver(solver)
    solver.step(1e-3)
    assert solver.X.sharding.spec in (P("x"), P("x", None))
    assert np.all(np.isfinite(np.asarray(solver.X)))


@needs_devices
def test_sharded_shell_step():
    """3D shell: (m, ell) pencil batch sharded over the mesh."""
    mesh = make_mesh(4)

    def build():
        cs = d3.SphericalCoordinates("phi", "theta", "r")
        dist = d3.Distributor(cs, dtype=np.float64)
        shell = d3.ShellBasis(cs, shape=(8, 8, 8), radii=(1.0, 2.0),
                              dealias=(3 / 2,) * 3, dtype=np.float64)
        phi, theta, r = dist.local_grids(shell)
        u = dist.Field(name="u", bases=shell)
        t1 = dist.Field(name="t1", bases=shell.S2_basis(2.0))
        t2 = dist.Field(name="t2", bases=shell.S2_basis(1.0))
        lift = lambda A, n: d3.Lift(A, shell.derivative_basis(2), n)
        problem = d3.IVP([u, t1, t2], namespace=locals())
        problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = - u*u")
        problem.add_equation("u(r=1.0) = 0")
        problem.add_equation("u(r=2.0) = 0")
        solver = problem.build_solver(d3.SBDF2)
        u["g"] = np.sin(np.pi * (np.asarray(r) - 1.0))
        return solver

    solver = build()
    for _ in range(3):
        solver.step(1e-3)
    X_ref = np.asarray(solver.X)

    solver2 = build()
    distribute_solver(solver2, mesh)
    for _ in range(3):
        solver2.step(1e-3)
    assert np.allclose(np.asarray(solver2.X), X_ref, atol=1e-13)


needs_8 = pytest.mark.skipif(N_DEV < 8, reason="needs >= 8 devices")


def make_mesh2(shape=(2, 4), names=("px", "py")):
    devs = np.array(jax.devices()[:shape[0] * shape[1]]).reshape(shape)
    return Mesh(devs, names)


@needs_8
def test_all_to_all_transpose_multiaxis_mesh():
    """One mesh axis moves while the other stays sharded (the reference's
    per-mesh-axis subcommunicator transposes, core/distributor.py:702)."""
    mesh = make_mesh2()
    rng = np.random.default_rng(1)
    data = rng.standard_normal((8, 8, 12))
    sharded = jax.device_put(data, NamedSharding(mesh, P("px", "py", None)))
    out = all_to_all_transpose(sharded, 1, 2, mesh, "py", layout={0: "px"})
    assert np.allclose(np.asarray(out), data)
    assert out.sharding.spec == P("px", None, "py")
    back = all_to_all_transpose(out, 2, 1, mesh, "py", layout={0: "px"})
    assert np.allclose(np.asarray(back), data)


@needs_8
def test_distributor_shardings_r2():
    mesh = make_mesh2()
    coords = d3.CartesianCoordinates("x", "y", "z")
    dist = d3.Distributor(coords, dtype=np.float64, mesh=mesh)
    cs = dist.coeff_sharding()
    gs = dist.grid_sharding()
    assert cs.spec == P("px", "py", None)
    assert gs.spec == P(None, "px", "py")
    vs = dist.coeff_sharding(tensorsig=(coords,))
    assert vs.spec == P(None, "px", "py", None)


@needs_8
def test_pipeline_3d_two_axis_mesh():
    """R=2 layout walk on a 3D Fourier x Fourier x Chebyshev domain matches
    the local transforms (reference: the R-dim layout chain,
    core/distributor.py:128-166)."""
    mesh = make_mesh2()
    coords = d3.CartesianCoordinates("x", "y", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=8, bounds=(0, 2 * np.pi))
    yb = d3.RealFourier(coords["y"], size=8, bounds=(0, 2 * np.pi))
    zb = d3.ChebyshevT(coords["z"], size=12, bounds=(0, 1))
    f = dist.Field(name="f", bases=(xb, yb, zb))
    x, y, z = dist.local_grids(xb, yb, zb)
    f["g"] = (np.sin(2 * x) * np.cos(y) * z ** 2 + np.cos(3 * x) * z
              + np.sin(y) + 1)
    cdata = np.asarray(f["c"])
    gdata = np.asarray(f["g"])
    pipeline = DistributedPencilPipeline(f.domain, mesh, ("px", "py"))
    c_sh = jax.device_put(cdata, NamedSharding(mesh, P("px", "py", None)))
    g_out = jax.jit(pipeline.to_grid)(c_sh)
    assert np.allclose(np.asarray(g_out), gdata, atol=1e-12)
    assert g_out.sharding.spec == P(None, "px", "py")
    c_back = jax.jit(pipeline.to_coeff)(g_out)
    assert np.allclose(np.asarray(c_back), cdata, atol=1e-12)
    assert c_back.sharding.spec in (P("px", "py"), P("px", "py", None))


@needs_8
def test_3d_rb_sharded_matches_single_device():
    """3D Rayleigh-Benard (Fourier^2 x Chebyshev) stepped on an 8-device
    mesh bit-matches the single-device run (VERDICT round-1 item 5)."""

    def build():
        coords = d3.CartesianCoordinates("x", "y", "z")
        dist = d3.Distributor(coords, dtype=np.float64)
        xb = d3.RealFourier(coords["x"], size=8, bounds=(0, 2.0), dealias=3 / 2)
        yb = d3.RealFourier(coords["y"], size=8, bounds=(0, 2.0), dealias=3 / 2)
        zb = d3.ChebyshevT(coords["z"], size=8, bounds=(0, 1.0), dealias=3 / 2)
        p = dist.Field(name="p", bases=(xb, yb, zb))
        b = dist.Field(name="b", bases=(xb, yb, zb))
        u = dist.VectorField(coords, name="u", bases=(xb, yb, zb))
        tau_p = dist.Field(name="tau_p")
        tau_b1 = dist.Field(name="tau_b1", bases=(xb, yb))
        tau_b2 = dist.Field(name="tau_b2", bases=(xb, yb))
        tau_u1 = dist.VectorField(coords, name="tau_u1", bases=(xb, yb))
        tau_u2 = dist.VectorField(coords, name="tau_u2", bases=(xb, yb))
        kappa = nu = 1e-2
        x, y, z = dist.local_grids(xb, yb, zb)
        ex, ey, ez = coords.unit_vector_fields(dist)
        lift_basis = zb.derivative_basis(1)
        lift = lambda A: d3.Lift(A, lift_basis, -1)
        grad_u = d3.grad(u) + ez * lift(tau_u1)
        grad_b = d3.grad(b) + ez * lift(tau_b1)
        problem = d3.IVP([p, b, u, tau_p, tau_b1, tau_b2, tau_u1, tau_u2],
                         namespace=locals())
        problem.add_equation("trace(grad_u) + tau_p = 0")
        problem.add_equation(
            "dt(b) - kappa*div(grad_b) + lift(tau_b2) = - u@grad(b)")
        problem.add_equation(
            "dt(u) - nu*div(grad_u) + grad(p) - b*ez + lift(tau_u2) = - u@grad(u)")
        problem.add_equation("b(z=0) = 1")
        problem.add_equation("u(z=0) = 0")
        problem.add_equation("b(z=1) = 0")
        problem.add_equation("u(z=1) = 0")
        problem.add_equation("integ(p) = 0")
        solver = problem.build_solver(d3.RK222)
        b.fill_random("g", seed=99, distribution="normal", scale=1e-3)
        b["g"] += (1 - z)
        return solver

    solver_ref = build()
    for _ in range(3):
        solver_ref.step(1e-3)
    X_ref = np.asarray(solver_ref.X)
    assert np.isfinite(X_ref).all()

    mesh = make_mesh(8)
    solver_sh = build()
    distribute_solver(solver_sh, mesh)
    for _ in range(3):
        solver_sh.step(1e-3)
    assert np.allclose(np.asarray(solver_sh.X), X_ref, atol=1e-13)


@needs_8
def test_sharded_banded_solver_matches():
    """The banded + pinned-Woodbury pencil path (BandedMatrix pytrees)
    shards over the mesh and matches the unsharded run."""
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from __graft_entry__ import _build_rb_solver
    from dedalus_tpu.tools.config import config
    old = config["linear algebra"].get("MATRIX_SOLVER", "auto")
    config["linear algebra"]["MATRIX_SOLVER"] = "banded"
    try:
        ref, _ = _build_rb_solver(16, 16, np.float64)
        assert type(ref.ops).__name__ == "BandedOps"
        for _ in range(3):
            ref.step(1e-3)
        X_ref = np.asarray(ref.X)
        sh, _ = _build_rb_solver(16, 16, np.float64)
        distribute_solver(sh, make_mesh(8))
        for _ in range(3):
            sh.step(1e-3)
        assert np.abs(np.asarray(sh.X) - X_ref).max() < 1e-10
    finally:
        config["linear algebra"]["MATRIX_SOLVER"] = old


@needs_devices
def test_cylinder_sharded_matches_single_device():
    """Cylinder (DirectProduct) solver sharded over the mesh bit-matches
    the single-device run: the disk's azimuth FFT, per-m radial stacks,
    and spin machinery all run under the constrained transform walk."""

    def build():
        cz = d3.Coordinate("z")
        cp = d3.PolarCoordinates("phi", "r")
        c = d3.DirectProduct(cz, cp)
        dist = d3.Distributor(c, dtype=np.float64)
        bz = d3.RealFourier(cz, size=8, bounds=(0, 2.0), dealias=3 / 2)
        bp = d3.DiskBasis(cp, (8, 12), dtype=np.float64, radius=1.5,
                          dealias=3 / 2)
        u = dist.Field(name="u", bases=(bz, bp))
        tau = dist.Field(name="tau", bases=(bz, bp.edge))
        lift = lambda A: d3.Lift(A, bp, -1)
        problem = d3.IVP([u, tau], namespace=locals())
        problem.add_equation("dt(u) - lap(u) + lift(tau) = - u*u")
        problem.add_equation("u(r=1.5) = 0")
        solver = problem.build_solver(d3.SBDF2)
        z, phi, r = dist.local_grids(bz, bp)
        u["g"] = ((1.5 ** 2 - r ** 2) * (1 + 0.3 * np.cos(np.pi * z))
                  * (1 + 0.1 * np.cos(phi)))
        return solver

    ref = build()
    for _ in range(4):
        ref.step(1e-3)
    X_ref = np.asarray(ref.X)
    assert np.isfinite(X_ref).all()

    sh = build()
    distribute_solver(sh, make_mesh(4))
    for _ in range(4):
        sh.step(1e-3)
    assert sh.X.sharding.spec in (P("x"), P("x", None))
    assert np.allclose(np.asarray(sh.X), X_ref, atol=1e-13)


@needs_devices
def test_coupled_ncc_sharded_matches_single_device():
    """An ell-COUPLED shell problem (theta-dependent conductivity NCC,
    per-m pencils on the flattened banded path) sharded over the mesh
    bit-matches the single-device run — the multichip story for
    rotating-convection-class problems."""
    from dedalus_tpu.libraries.pencilops import BandedOps

    def build():
        coords = d3.SphericalCoordinates("phi", "theta", "r")
        dist = d3.Distributor(coords, dtype=np.float64)
        shell = d3.ShellBasis(coords, shape=(16, 40, 16), radii=(0.5, 1.5),
                              dtype=np.float64)
        phi, theta, r = dist.local_grids(shell)
        T = dist.Field(name="T", bases=shell)
        tau1 = dist.Field(name="tau1", bases=shell.outer_surface)
        tau2 = dist.Field(name="tau2", bases=shell.outer_surface)
        kap = dist.Field(name="kap", bases=shell.meridional_basis)
        kap["g"] = 1.0 + 0.4 * np.cos(theta)
        lift = lambda A: d3.Lift(A, shell.derivative_basis(1), -1)
        rvec = dist.VectorField(coords, bases=shell.meridional_basis)
        rvec["g"][2] = np.broadcast_to(r, rvec["g"][2].shape)
        grad_T = d3.grad(T) + rvec * lift(tau1)
        problem = d3.IVP([T, tau1, tau2], namespace=locals())
        problem.add_equation("dt(T) - div(kap*grad_T) + lift(tau2) = 0")
        problem.add_equation("T(r=0.5) = 0")
        problem.add_equation("T(r=1.5) = 0")
        solver = problem.build_solver(d3.SBDF2, matsolver="banded")
        T["g"] = (np.sin(np.pi * (r - 0.5) / 1.0)
                  * (1 + 0.3 * np.cos(theta)
                     + 0.2 * np.sin(theta) * np.cos(phi)))
        return solver

    ref = build()
    assert isinstance(ref.ops, BandedOps), ref._banded_reason
    for _ in range(3):
        ref.step(2e-3)
    X_ref = np.asarray(ref.X)
    sh = build()
    distribute_solver(sh, make_mesh(8))
    for _ in range(3):
        sh.step(2e-3)
    assert np.abs(np.asarray(sh.X) - X_ref).max() < 1e-11
