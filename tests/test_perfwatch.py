"""
Perf-trajectory regression sentinel (tools/perfwatch.py): each seeded
regression class must FIRE (steps/s down, requests/s down, peak memory
up, ledger flops/HLO/scan-depth up), the documented ±15% host drift must
NOT, and the evidence rules that keep the sentinel quiet on real history
— no-ts exclusion, finite:false exclusion, stale-re-report dedupe,
waivers — each hold on a minimal fixture. No jax import anywhere: the
sentinel reads JSONL, and so do these tests.
"""

import json

import pytest

from dedalus_tpu.tools import perfwatch


def _write(path, rows):
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    return path


def _series(rows, key):
    return perfwatch.build_series(rows).get(key)


# ------------------------------------------------------- regression classes

def _steps_rows(values, config="rbX", backend="cpu"):
    return [{"config": config, "backend": backend, "steps_per_sec": v,
             "ts": float(i)} for i, v in enumerate(values)]


def test_steps_per_sec_drop_fires():
    rows = _steps_rows([10.0, 10.2, 9.9, 10.1, 6.0])
    report = perfwatch.analyze(rows)
    (reg,) = report["regressions"]
    assert reg["series"] == "steps_per_sec:rbX:cpu:unversioned"
    assert reg["delta"] < -0.15


def test_requests_per_sec_drop_fires():
    rows = [{"config": "srv", "backend": "cpu",
             "throughput_requests_per_sec": v, "ts": float(i)}
            for i, v in enumerate([20.0, 19.5, 20.5, 20.1, 11.0])]
    report = perfwatch.analyze(rows)
    (reg,) = report["regressions"]
    assert reg["series"].startswith("requests_per_sec:srv:")


def test_peak_memory_growth_fires():
    rows = [{"config": "rbX", "backend": "cpu",
             "device_mem_peak_bytes": v, "ts": float(i)}
            for i, v in enumerate([1e9, 1.02e9, 0.99e9, 1.01e9, 2.2e9])]
    report = perfwatch.analyze(rows)
    (reg,) = report["regressions"]
    assert reg["series"] == "device_mem_peak_bytes:rbX:cpu:unversioned"
    assert reg["direction"] == "up" and reg["delta"] > 0.15


@pytest.mark.parametrize("field,metric", [
    ("flops", "ledger_flops"),
    ("bytes_accessed", "ledger_bytes"),
    ("hlo_instructions", "ledger_hlo_instructions"),
    ("scan_max_length", "ledger_scan_depth"),
])
def test_ledger_growth_fires(field, metric):
    rows = [{"kind": "ledger", "program": "prog", "backend": "cpu",
             field: v, "ts": float(i)}
            for i, v in enumerate([100, 101, 99, 100, 180])]
    report = perfwatch.analyze(rows)
    (reg,) = report["regressions"]
    assert reg["series"] == f"{metric}:prog:cpu:unversioned"


def test_improvement_is_quiet():
    """The bands are one-sided: moving the GOOD way never fires."""
    faster = perfwatch.analyze(_steps_rows([10.0, 10.1, 9.9, 10.0, 30.0]))
    assert not faster["regressions"]
    leaner = perfwatch.analyze(
        [{"kind": "ledger", "program": "p", "backend": "cpu", "flops": v,
          "ts": float(i)} for i, v in enumerate([100, 101, 99, 100, 20])])
    assert not leaner["regressions"]


# ------------------------------------------------------------ noise bands

def test_host_drift_absorbed():
    """±15% scatter around a stable baseline — the documented host drift
    — stays inside the floor band even when the newest point lands at
    the bottom of the range."""
    rows = _steps_rows([100.0, 103.0, 97.0, 101.0, 99.0, 86.0])
    report = perfwatch.analyze(rows)
    assert not report["regressions"]
    (res,) = [r for r in report["series"] if r["verdict"] != "waived"]
    assert res["verdict"] == "ok"
    assert res["band"] >= 0.15


def test_noisy_series_widens_band():
    """Historical dispersion beyond the floor widens the band: a swing
    that would fire against a tight history is absorbed by a noisy one.
    """
    noisy_hist = [100.0, 140.0, 70.0, 125.0, 80.0]
    noisy = perfwatch.analyze(_steps_rows(noisy_hist + [60.0]))
    assert not noisy["regressions"]
    tight = perfwatch.analyze(
        _steps_rows([100.0, 101.0, 99.0, 100.5, 99.5] + [60.0]))
    assert len(tight["regressions"]) == 1


def test_insufficient_history_not_judged():
    report = perfwatch.analyze(_steps_rows([10.0, 4.0]))
    assert not report["regressions"]
    assert report["series"][0]["verdict"] == "insufficient-history"


def test_analyze_series_min_history_boundary():
    values = [10.0, 10.0, 10.0, 5.0]
    judged = perfwatch.analyze_series(values, "down", min_history=3)
    assert judged["verdict"] == "regression"
    young = perfwatch.analyze_series(values, "down", min_history=4)
    assert young["verdict"] == "insufficient-history"


# -------------------------------------------------------- evidence rules

def test_rows_without_ts_excluded():
    """No provenance, no evidence: undated rows never enter a series."""
    rows = _steps_rows([10.0, 10.1, 9.9, 10.0, 6.0])
    for row in rows[:3]:
        del row["ts"]
    assert not perfwatch.analyze(rows)["regressions"]
    series = _series(rows, "steps_per_sec:rbX:cpu:unversioned")
    assert len(series["values"]) == 2


def test_nonfinite_rows_excluded():
    rows = _steps_rows([10.0, 10.1, 9.9, 10.0])
    rows.append({"config": "rbX", "backend": "cpu", "finite": False,
                 "steps_per_sec": 52.0, "ts": 4.0})
    series = _series(rows, "steps_per_sec:rbX:cpu:unversioned")
    assert 52.0 not in series["values"]


def test_stale_rereports_deduped():
    """A measurement re-reported by later doc builds (measured_ts +
    source) counts ONCE, at its original time — re-reports must neither
    pad the history nor masquerade as fresh points."""
    rows = [{"config": "rbX", "backend": "cpu", "metric": "m",
             "value": 10.0, "unit": "steps/sec", "ts": float(i)}
            for i in range(4)]
    for i, ts in enumerate((10.0, 11.0, 12.0)):
        rows.append({"config": "rbX", "backend": "cpu", "metric": "m",
                     "value": 9.8, "unit": "steps/sec", "ts": ts,
                     "measured_ts": 5.0, "source": "docs", "stale": True})
    series = _series(rows, "m:rbX:cpu:unversioned")
    assert series["values"] == [10.0] * 4 + [9.8]


def test_non_measurement_kinds_skipped():
    rows = [{"kind": "probe", "config": "backend_probe", "ok": True,
             "ts": 1.0, "wall_sec": 800.0},
            {"kind": "service_stats", "requests_served": 3, "ts": 2.0},
            {"kind": "trace", "trace_id": "t1", "ts": 3.0}]
    assert perfwatch.extract_points(rows) == []


def test_plan_digest_separates_series():
    """A plan change re-keys the series: points before and after never
    share a baseline."""
    plan = {"plan_version": 1, "fusion": {"solve": True, "matvec": True},
            "solve_composition": "ascan", "solve_dtype": "f32",
            "refine_sweeps": 2, "spike_chunks": 0, "transpose_chunks": 2,
            "solver_key": "abc123"}
    assert perfwatch.plan_key(plan) == "v1.sm.ascan.f32.s2.k0.t2"
    assert perfwatch.plan_key(None) == "unversioned"
    rows = _steps_rows([10.0, 10.1, 9.9, 10.0])
    rows.append({"config": "rbX", "backend": "cpu", "steps_per_sec": 6.0,
                 "ts": 4.0, "plan": plan})
    assert not perfwatch.analyze(rows)["regressions"]
    assert len(perfwatch.build_series(rows)) == 2


def test_solver_key_does_not_rekey():
    """solver_key re-keys the assembly cache on ANY assembly change; the
    series digest must ignore it or every tweak would orphan history."""
    a = {"plan_version": 1, "solver_key": "aaa"}
    b = {"plan_version": 1, "solver_key": "bbb"}
    assert perfwatch.plan_key(a) == perfwatch.plan_key(b)


def test_solvecomp_sweep_cells_are_series():
    rows = [{"benchmark": "solvecomp", "config": "rb", "backend": "cpu",
             "ts": float(i),
             "sweep": [{"composition": "ascan", "solve_dtype": "f64",
                        "steps_per_sec": v}]}
            for i, v in enumerate([5.0, 5.1, 4.9, 5.0, 2.0])]
    report = perfwatch.analyze(rows)
    (reg,) = report["regressions"]
    assert reg["series"] == "steps_per_sec:rb/ascan/f64:cpu:unversioned"


def test_autotune_plan_switch_starts_new_series():
    """An autotune-induced plan switch (the tuner flips the resolved
    composition/dtype, stamping plan_source: tuned) starts a NEW
    plan_key series: the tuned points must not fire a false regression
    against the old plan's baseline even when the tuned cell is slower
    (the retired PR-15 ascan waiver's scenario), and the two plans'
    histories never share a baseline."""
    old_plan = {"plan_version": 1,
                "fusion": {"solve": True, "matvec": True},
                "solve_composition": "sequential", "solve_dtype": "native",
                "refine_sweeps": None, "spike_chunks": 0,
                "transpose_chunks": 2, "plan_source": "default"}
    tuned_plan = {"plan_version": 1,
                  "fusion": {"solve": True, "matvec": True},
                  "solve_composition": "ascan", "solve_dtype": "f32",
                  "refine_sweeps": 2, "spike_chunks": 0,
                  "transpose_chunks": 2, "plan_source": "tuned",
                  "tuning": {"evidence_kind": "ops_probe"}}
    rows = [{"config": "rbX", "backend": "cpu", "steps_per_sec": v,
             "ts": float(i), "plan": old_plan}
            for i, v in enumerate([10.0, 10.1, 9.9, 10.0])]
    # the switch point: a 60% drop that WOULD fire inside the old series
    rows.append({"config": "rbX", "backend": "cpu", "steps_per_sec": 4.0,
                 "ts": 4.0, "plan": tuned_plan})
    assert perfwatch.plan_key(old_plan) != perfwatch.plan_key(tuned_plan)
    report = perfwatch.analyze(rows)
    assert not report["regressions"]
    assert len(perfwatch.build_series(rows)) == 2
    # identical plan VALUES must still share one series regardless of
    # how they were chosen: plan_source alone is not a program change
    retuned = dict(old_plan, plan_source="tuned",
                   tuning={"evidence_kind": "step_sweep"})
    assert perfwatch.plan_key(old_plan) == perfwatch.plan_key(retuned)


def test_autotune_rows_are_not_measurements():
    """kind: autotune evidence rows (per-cell microbench numbers) never
    seed trend series."""
    rows = [{"kind": "autotune", "config": "rb256x64", "backend": "cpu",
             "ts": float(i), "steps_per_sec": 3.0,
             "cells": [{"composition": "ascan", "solve_dtype": "f32",
                        "steps_per_sec": 3.0}]}
            for i in range(5)]
    assert perfwatch.extract_points(rows) == []


# --------------------------------------------------------------- waivers

def test_waiver_matches_and_exits_zero(tmp_path):
    rows = _steps_rows([10.0, 10.1, 9.9, 10.0, 6.0])
    waivers = [{"series": "steps_per_sec:rbX:*", "reason": "by design"}]
    report = perfwatch.analyze(rows, waivers=waivers)
    assert not report["regressions"]
    (waived,) = report["waived"]
    assert waived["waive_reason"] == "by design"
    fixture = _write(tmp_path / "r.jsonl", rows)
    wfile = tmp_path / "w.json"
    wfile.write_text(json.dumps({"waivers": waivers}))
    assert perfwatch.main([str(fixture), "--check",
                           "--waivers", str(wfile)]) == 0


def test_repo_waiver_file_loads():
    """The checked-in waiver file must parse, every entry must carry a
    reason, and the PR-15 ascan waiver must stay RETIRED: with
    plan_source in provenance an autotune-rejected cell is evidence in
    the decision row, not a standing regression waiver (plan switches
    start new series instead — test below)."""
    waivers = perfwatch.load_waivers()
    assert not any("solvecomp/ascan" in w.get("series", "")
                   for w in waivers)
    assert all(w.get("reason") for w in waivers)


def test_malformed_waiver_file_waives_nothing(tmp_path):
    bad = tmp_path / "w.json"
    bad.write_text("{not json")
    assert perfwatch.load_waivers(bad) == []


# ------------------------------------------------------------------- CLI

def test_cli_quiet_on_stable_history(tmp_path, capsys):
    fixture = _write(tmp_path / "r.jsonl",
                     _steps_rows([10.0, 10.1, 9.9, 10.0, 10.05]))
    assert perfwatch.main([str(fixture), "--check"]) == 0
    assert capsys.readouterr().out == ""
    assert perfwatch.main([str(fixture)]) == 0
    out = capsys.readouterr().out
    assert "1 analyzed, 0 regression(s)" in out


def test_cli_fires_with_named_finding(tmp_path, capsys):
    rows = (_steps_rows([10.0, 10.1, 9.9, 10.0, 6.0])
            + [{"kind": "ledger", "program": "p", "backend": "cpu",
                "flops": v, "ts": float(i)}
               for i, v in enumerate([100, 101, 99, 100, 180])])
    fixture = _write(tmp_path / "r.jsonl", rows)
    assert perfwatch.main([str(fixture), "--check"]) == 1
    out = capsys.readouterr().out
    assert "perfwatch regression: steps_per_sec:rbX:cpu:unversioned" in out
    assert "perfwatch regression: ledger_flops:p:cpu:unversioned" in out
    assert "-40" in out         # the measured drop, human-readable


def test_cli_json_mode(tmp_path, capsys):
    fixture = _write(tmp_path / "r.jsonl",
                     _steps_rows([10.0, 10.1, 9.9, 10.0, 6.0]))
    assert perfwatch.main([str(fixture), "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["regressions"][0]["verdict"] == "regression"


def test_cli_missing_file(tmp_path, capsys):
    assert perfwatch.main([str(tmp_path / "absent.jsonl")]) == 2
    assert "no history" in capsys.readouterr().err


def test_cli_drift_floor_override(tmp_path):
    rows = _steps_rows([10.0, 10.1, 9.9, 10.0, 9.0])   # -10.5%
    fixture = _write(tmp_path / "r.jsonl", rows)
    assert perfwatch.main([str(fixture), "--check"]) == 0
    assert perfwatch.main([str(fixture), "--check",
                           "--drift-floor", "0.05"]) == 1


def test_trend_lines_analyzed_only():
    rows = _steps_rows([10.0, 10.1, 9.9, 10.0, 6.0])
    rows += _steps_rows([5.0, 5.0], config="young")
    lines = perfwatch.trend_lines(rows)
    assert len(lines) == 1
    assert "steps_per_sec:rbX:cpu:unversioned" in lines[0]
    assert "regression" in lines[0]
    assert perfwatch.trend_lines(_steps_rows([1.0])) == []


def test_load_rows_tolerates_junk(tmp_path):
    path = tmp_path / "r.jsonl"
    path.write_text('{"config": "a", "ts": 1.0}\nnot json\n[1,2]\n')
    rows = perfwatch.load_rows(path)
    assert rows == [{"config": "a", "ts": 1.0}]
