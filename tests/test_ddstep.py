"""
Emulated-f64 (double-double) IVP stepping oracles.

Each test runs the SAME problem twice: native f64 (the CPU reference
path, matching the reference framework's precision) and the DDIVPRunner
f32-pair path. The dd trajectory must track the f64 trajectory far below
the f32 error floor (~1e-7): agreement at ~1e-12 proves transforms,
matvecs, RHS nonlinearities, and the refined implicit solve all run at
emulated-f64 precision. (VERDICT round-4 item 3.)
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3
from dedalus_tpu.core.ddstep import DDIVPRunner, DDUnsupportedError
from dedalus_tpu.tools.config import config


@pytest.fixture(autouse=True)
def dense_path():
    old = config["linear algebra"].get("MATRIX_SOLVER", "auto")
    config["linear algebra"]["MATRIX_SOLVER"] = "dense"
    yield
    config["linear algebra"]["MATRIX_SOLVER"] = old


def build_heat(N, dtype):
    xcoord = d3.Coordinate("x")
    dist = d3.Distributor(xcoord, dtype=dtype)
    xbasis = d3.RealFourier(xcoord, size=N, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xbasis)
    kappa = 0.1
    dx = lambda A: d3.Differentiate(A, xcoord)
    problem = d3.IVP([u], namespace=locals())
    problem.add_equation("dt(u) - kappa*dx(dx(u)) = 0")
    x = dist.local_grids(xbasis)[0]
    return problem, u, x


def test_heat_dd_matches_f64():
    N, dt, n_steps = 64, 1e-3, 200
    problem, u, x = build_heat(N, np.float64)
    u["g"] = np.sin(3 * x) + 0.5 * np.cos(7 * x)
    solver = problem.build_solver(d3.SBDF2)
    runner = DDIVPRunner(solver)
    for _ in range(n_steps):
        solver.step(dt)
        runner.step(dt)
    X64 = np.asarray(solver.X, dtype=np.float64)
    Xdd = runner.state_f64()
    scale = np.abs(X64).max()
    assert np.abs(Xdd - X64).max() / scale < 1e-11
    # and both must match the exact decay
    runner.push_state()
    t = n_steps * dt
    exact = (np.exp(-0.1 * 9 * t) * np.sin(3 * x)
             + 0.5 * np.exp(-0.1 * 49 * t) * np.cos(7 * x))
    assert np.abs(u["g"] - exact).max() < 1e-5   # SBDF2 O(dt^2) time error


def build_kdv(N, dtype):
    xcoord = d3.Coordinate("x")
    dist = d3.Distributor(xcoord, dtype=dtype)
    xbasis = d3.RealFourier(xcoord, size=N, bounds=(0, 10), dealias=3 / 2)
    u = dist.Field(name="u", bases=xbasis)
    a, b = 1e-4, 2e-4
    dx = lambda A: d3.Differentiate(A, xcoord)
    problem = d3.IVP([u], namespace=locals())
    problem.add_equation("dt(u) - a*dx(dx(u)) - b*dx(dx(dx(u))) = - u*dx(u)")
    x = dist.local_grids(xbasis)[0]
    n = 20
    u["g"] = np.log(1 + np.cosh(n) ** 2 / np.cosh(n * (x - 3)) ** 2) / (2 * n)
    return problem, u


def test_kdv_dd_matches_f64():
    # nonlinear RHS: dd transforms + dealiased product + dd matvec chain
    N, dt, n_steps = 256, 5e-4, 100
    problem, u = build_kdv(N, np.float64)
    solver = problem.build_solver(d3.SBDF2)
    runner = DDIVPRunner(solver)
    for _ in range(n_steps):
        solver.step(dt)
        runner.step(dt)
    X64 = np.asarray(solver.X, dtype=np.float64)
    Xdd = runner.state_f64()
    scale = np.abs(X64).max()
    assert np.abs(Xdd - X64).max() / scale < 1e-10


def test_kdv_dd_mass_conservation():
    # f32 stepping drifts mass at ~1e-8 (BENCHMARKS.md); dd must hold
    # f64-grade drift. Mass = the mean (cos-0) Fourier coefficient.
    N, dt, n_steps = 256, 5e-4, 200
    problem, u = build_kdv(N, np.float64)
    solver = problem.build_solver(d3.SBDF2)
    runner = DDIVPRunner(solver)
    mass0 = float(np.mean(u["g"]))   # uniform-grid mean = integral / L
    for _ in range(n_steps):
        runner.step(dt)
    runner.push_state()
    mass1 = float(np.mean(u["g"]))
    assert abs(mass1 - mass0) / abs(mass0) < 1e-12


def test_rk222_dd_matches_f64():
    # Runge-Kutta IMEX path: dd tracks the native-f64 RK trajectory
    N, dt, n_steps = 64, 1e-3, 100
    problem, u, x = build_heat(N, np.float64)
    u["g"] = np.sin(3 * x) + 0.5 * np.cos(7 * x)
    solver = problem.build_solver(d3.RK222)
    runner = DDIVPRunner(solver)
    for _ in range(n_steps):
        solver.step(dt)
        runner.step(dt)
    X64 = np.asarray(solver.X, dtype=np.float64)
    Xdd = runner.state_f64()
    assert np.abs(Xdd - X64).max() / np.abs(X64).max() < 1e-11


def test_rk443_kdv_dd_matches_f64():
    # higher-order RK + nonlinear RHS through the dd interpreter
    N, dt, n_steps = 128, 1e-3, 50
    problem, u = build_kdv(N, np.float64)
    solver = problem.build_solver(d3.RK443)
    runner = DDIVPRunner(solver)
    for _ in range(n_steps):
        solver.step(dt)
        runner.step(dt)
    X64 = np.asarray(solver.X, dtype=np.float64)
    Xdd = runner.state_f64()
    assert np.abs(Xdd - X64).max() / np.abs(X64).max() < 1e-10


def test_forcing_update_mid_run():
    # non-variable RHS fields must be dynamic inputs: updating a forcing
    # between steps changes the trajectory (review finding — baking them
    # as trace-time constants silently froze the first step's forcing)
    xcoord = d3.Coordinate("x")
    dist = d3.Distributor(xcoord, dtype=np.float64)
    xb = d3.RealFourier(xcoord, size=32, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    F = dist.Field(name="F", bases=xb)
    dx = lambda A: d3.Differentiate(A, xcoord)
    problem = d3.IVP([u], namespace=locals())
    problem.add_equation("dt(u) - dx(dx(u)) = F")
    x = dist.local_grids(xb)[0]
    F["g"] = np.sin(x)
    solver = problem.build_solver(d3.SBDF2)
    runner = DDIVPRunner(solver)
    runner.step(1e-3)
    X1 = runner.state_f64().copy()
    F["g"] = 5 * np.cos(2 * x)
    runner.step(1e-3)
    X2 = runner.state_f64()
    # rerun with the forcing never updated: trajectories must differ
    solver2 = problem.build_solver(d3.SBDF2)
    F["g"] = np.sin(x)
    runner2 = DDIVPRunner(solver2)
    runner2.step(1e-3)
    assert np.abs(runner2.state_f64() - X1).max() < 1e-12
    runner2.step(1e-3)
    assert np.abs(runner2.state_f64() - X2).max() > 1e-6


def test_unsupported_rhs_detected_at_construction():
    # a dd-unsupported RHS node must raise at DDIVPRunner construction
    # (where the solver's auto-wiring can fall back to native f64)
    xcoord = d3.Coordinate("x")
    dist = d3.Distributor(xcoord, dtype=np.float64)
    xb = d3.RealFourier(xcoord, size=32, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    sin = np.sin
    problem = d3.IVP([u], namespace=locals())
    problem.add_equation("dt(u) = sin(u)")   # UnaryGridFunction: no dd
    solver = problem.build_solver(d3.SBDF2)
    with pytest.raises(DDUnsupportedError):
        DDIVPRunner(solver)


def test_rayleigh_benard_dd_matches_f64():
    """The flagship 2-D problem end-to-end in dd: vector fields, taus,
    LHS NCCs, Lift, DotProduct RHS, RK222 — tracks native f64."""
    from dedalus_tpu.extras.bench_problems import build_rb_solver
    solver, b = build_rb_solver(32, 8, np.float64)
    runner = DDIVPRunner(solver)
    dt = 1e-3
    for _ in range(10):
        solver.step(dt)
        runner.step(dt)
    X64 = np.asarray(solver.X, dtype=np.float64)
    Xdd = runner.state_f64()
    # tau/pin conditioning at this tiny resolution sets the IR floor at
    # ~1e-10 relative; still ~1000x below the f32 error floor
    assert np.abs(Xdd - X64).max() / np.abs(X64).max() < 1e-9
