"""
Scrapeable serving metrics (service/promexport.py): the Prometheus text
exposition renderer against the in-repo format validator, LogHistogram
-> native-histogram conversion with exact bucket bounds, and the two
transport paths off a live in-process daemon — the `stats` frame with
`prom: true` (ServiceClient.stats_prom) and GET /metrics on the
[service] METRICS_PORT listener. The acceptance bar: everything either
path serves parses under validate_exposition, histograms included.
"""

import io
import math
import threading
import time
import urllib.error
import urllib.request

import pytest

from dedalus_tpu.service import promexport
from dedalus_tpu.tools import tracing

pytestmark = pytest.mark.service


def _stats(**overrides):
    """A stats() dict shaped like SolverService.stats() emits."""
    stats = {
        "requests_served": 7, "errors": 2, "draining": None,
        "uptime_sec": 12.5,
        "pool": {"size": 4, "entries": [{"key": "a"}], "hits": 5,
                 "misses": 2, "evictions": 1, "resets": 3},
        "serving": {"batching": {"enabled": False}},
        "faults": {"queue_depth": 8, "queued": 1, "shed": 4,
                   "deadline_exceeded": 1, "watchdog_fires": 0,
                   "client_drops": 2, "mem_evictions": 0, "replays": 3,
                   "result_cache": 2,
                   "breaker": {"opens": 1, "closes": 1, "fastfails": 6,
                               "open": ["spec-a"]},
                   "error_codes": {"bad-spec": 1, "overloaded": 1}},
    }
    stats.update(overrides)
    return stats


# ------------------------------------------------------------- rendering

def test_render_counters_and_gauges():
    text = promexport.render_stats(_stats())
    families = promexport.validate_exposition(text)
    assert "dedalus_requests_served_total 7" in text
    assert "dedalus_errors_total 2" in text
    assert "dedalus_pool_hits_total 5" in text
    assert "dedalus_pool_entries 1" in text
    assert "dedalus_queued_runs 1" in text
    assert "dedalus_shed_total 4" in text
    assert "dedalus_replays_total 3" in text
    assert "dedalus_breaker_fastfails_total 6" in text
    assert "dedalus_breaker_open_circuits 1" in text
    assert "dedalus_draining 0" in text
    assert 'dedalus_errors_by_code_total{code="bad-spec"} 1' in text
    assert 'dedalus_errors_by_code_total{code="overloaded"} 1' in text
    assert families["dedalus_requests_served_total"]["type"] == "counter"
    assert families["dedalus_pool_entries"]["type"] == "gauge"


def test_render_draining_and_batching():
    batching = {"enabled": True, "batch_max": 4, "batches": 9,
                "members": 21, "late_joins": 2, "blocks": 30,
                "peak_members": 4,
                "detached": {"finished": 19, "deadline": 2}}
    text = promexport.render_stats(
        _stats(draining="SIGTERM",
               serving={"batching": batching}))
    promexport.validate_exposition(text)
    assert "dedalus_draining 1" in text
    assert "dedalus_batching_enabled 1" in text
    assert "dedalus_batches_total 9" in text
    assert "dedalus_batch_peak_members 4" in text
    assert 'dedalus_batch_detached_total{cause="finished"} 19' in text
    # disabled batching exports only the enabled gauge
    off = promexport.render_stats(_stats())
    assert "dedalus_batching_enabled 0" in off
    assert "dedalus_batches_total" not in off


def test_render_tolerates_sparse_stats():
    """Rows from older daemons (missing whole sub-dicts) render what
    they have instead of crashing — and still validate."""
    for stats in ({}, {"requests_served": 1}, {"pool": {}},
                  {"faults": {"breaker": {}}}):
        text = promexport.render_stats(stats)
        promexport.validate_exposition(text)
        assert "dedalus_up 1" in text


# ------------------------------------------------------------ histograms

def test_histogram_conversion_exact():
    hist = tracing.LogHistogram()
    for s in (0.1, 0.1, 0.2, 3.0):
        hist.add(s)
    text = promexport.render_stats(
        {}, {"run_seconds": (hist, "run wall")})
    families = promexport.validate_exposition(text)
    assert families["dedalus_run_seconds"]["type"] == "histogram"
    assert 'dedalus_run_seconds_bucket{le="+Inf"} 4' in text
    assert "dedalus_run_seconds_count 4" in text
    assert "dedalus_run_seconds_sum 3.4" in text
    # each le is the exact log-bucket upper bound, and every observation
    # sits at or below its bucket's bound
    for line in text.splitlines():
        if "_bucket" in line and "+Inf" not in line:
            le = float(line.split('le="')[1].split('"')[0])
            b = hist._bucket(le * 0.999999)
            assert math.isclose(le, tracing._LOG_FLOOR
                                * tracing._LOG_BASE ** b, rel_tol=1e-9)
    # cumulative counts non-decreasing is validator-enforced; check the
    # 0.1s pair shares a bucket (same le line carries >= 2)
    b01 = hist._bucket(0.1)
    le01 = tracing._LOG_FLOOR * tracing._LOG_BASE ** b01
    assert f'le="{le01!r}"' in text


def test_histogram_from_snapshot_dict():
    """The server snapshots hists under its counters lock and hands the
    renderer plain dicts; empty histograms still render completely."""
    snap = {"counts": {3: 2, 10: 1}, "total": 3, "sum": 0.5}
    text = promexport.render_stats({}, {"queue_seconds": (snap, "queue")})
    promexport.validate_exposition(text)
    assert 'dedalus_queue_seconds_bucket{le="+Inf"} 3' in text
    empty = promexport.render_stats(
        {}, {"queue_seconds": ({"counts": {}, "total": 0, "sum": 0.0},
                               "queue")})
    fams = promexport.validate_exposition(empty)
    assert fams["dedalus_queue_seconds"]["type"] == "histogram"
    assert "dedalus_queue_seconds_count 0" in empty


# ------------------------------------------------------------- validator

def test_validator_rejects_malformed():
    bad = [
        "dedalus_x{unclosed 1\n",                          # label syntax
        "dedalus_x 1\ndedalus_x 2\n",                      # duplicate
        "# TYPE dedalus_x wat\ndedalus_x 1\n",             # unknown type
        "dedalus_x notanumber\n",                          # value
        "# TYPE dedalus_h histogram\n"                     # no +Inf
        'dedalus_h_bucket{le="1"} 1\n'
        "dedalus_h_sum 1.0\ndedalus_h_count 1\n",
        "# TYPE dedalus_h histogram\n"                     # not cumulative
        'dedalus_h_bucket{le="1"} 3\n'
        'dedalus_h_bucket{le="2"} 2\n'
        'dedalus_h_bucket{le="+Inf"} 3\n'
        "dedalus_h_sum 1.0\ndedalus_h_count 3\n",
        "# TYPE dedalus_h histogram\n"                     # count mismatch
        'dedalus_h_bucket{le="+Inf"} 3\n'
        "dedalus_h_sum 1.0\ndedalus_h_count 2\n",
    ]
    for text in bad:
        with pytest.raises(ValueError):
            promexport.validate_exposition(text)


def test_validator_accepts_escapes_and_comments():
    ok = ('# random comment\n'
          '# HELP m help text with "quotes"\n'
          '# TYPE m counter\n'
          'm{path="C:\\\\dir\\"x\\""} 1\n'
          'm{path="other"} 2\n')
    families = promexport.validate_exposition(ok)
    assert families["m"]["samples"] == 2


# ------------------------------------------------- live daemon transports

@pytest.fixture()
def live_service():
    """In-process daemon with an ephemeral /metrics listener: exercises
    serve_forever's real bind/teardown without a subprocess."""
    from dedalus_tpu.service.server import SolverService
    svc = SolverService(port=0, metrics_port=0)
    ready = io.StringIO()
    thread = threading.Thread(target=svc.serve_forever,
                              kwargs={"ready_stream": ready}, daemon=True)
    thread.start()
    deadline = time.time() + 30
    while time.time() < deadline:
        if svc.started_ts and svc.port and svc._metrics_server is not None:
            break
        time.sleep(0.05)
    else:
        pytest.fail("daemon did not come up")
    yield svc
    svc.request_drain("test teardown")
    thread.join(timeout=30)


def test_stats_prom_frame_and_http(live_service):
    from dedalus_tpu.service.client import ServiceClient
    svc = live_service
    with svc._counters_lock:
        svc.hists["run_seconds"].add(0.25)
        svc.hists["queue_seconds"].add(0.002)
    text = ServiceClient(port=svc.port, retries=0).stats_prom()
    promexport.validate_exposition(text)
    assert "dedalus_up 1" in text
    assert 'dedalus_run_seconds_bucket{le="+Inf"} 1' in text
    # the HTTP listener serves the same surface
    url = f"http://127.0.0.1:{svc.metrics_port}/metrics"
    resp = urllib.request.urlopen(url, timeout=10)
    assert resp.status == 200
    assert resp.headers["Content-Type"].startswith(
        "text/plain; version=0.0.4")
    body = resp.read().decode("utf-8")
    promexport.validate_exposition(body)
    assert "dedalus_queue_seconds_count 1" in body
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(
            f"http://127.0.0.1:{svc.metrics_port}/other", timeout=10)
    # plain JSON stats still work on the same daemon
    stats = ServiceClient(port=svc.port, retries=0).stats()
    assert stats["kind"] == "stats"
    assert "pool" in stats


def test_metrics_listener_disabled_by_default():
    from dedalus_tpu.service.server import SolverService
    svc = SolverService(port=0)                # config METRICS_PORT = 0
    assert svc.metrics_port is None
    svc._start_metrics_server()                # must be a no-op
    assert svc._metrics_server is None
