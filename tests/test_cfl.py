"""
CFL and flow-tools tests (reference: dedalus/tests/test_cfl.py).
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3
from dedalus_tpu.extras.flow_tools import CFL, GlobalFlowProperty


def build_advection(vx=2.0, vz=0.5, Nx=32, Nz=16):
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=Nx, bounds=(0, 2 * np.pi))
    zb = d3.RealFourier(coords["z"], size=Nz, bounds=(0, 1))
    u = dist.VectorField(coords, name="u", bases=(xb, zb))
    s = dist.Field(name="s", bases=(xb, zb))
    problem = d3.IVP([s, u], namespace={})
    problem.add_equation((d3.dt(s), 0))
    problem.add_equation((d3.dt(u), 0))
    solver = problem.build_solver(d3.SBDF1)
    u["g"] = np.array([[[vx]], [[vz]]]) * np.ones((2, Nx, Nz))
    return solver, u, coords


def test_cfl_uniform_advection():
    """dt = safety / max(sum_i |u_i| / dx_i) for uniform velocity
    (reference: extras/flow_tools.py:191 compute_timestep)."""
    vx, vz, Nx, Nz = 2.0, 0.5, 32, 16
    solver, u, coords = build_advection(vx, vz, Nx, Nz)
    cfl = CFL(solver, initial_dt=1.0, safety=0.4, threshold=0.0)
    cfl.add_velocity(u)
    dt = cfl.compute_timestep()
    dx = 2 * np.pi / Nx   # bases built at dealias=1
    dz = 1.0 / Nz
    expected = 0.4 / (vx / dx + vz / dz)
    assert abs(dt - expected) / expected < 0.05


def test_cfl_cylinder_geometry():
    """Cylinder (DirectProduct) velocities combine the straight axis's
    interval spacing with the disk's (azimuth, radius) spacings."""
    from dedalus_tpu.extras.flow_tools import advective_cfl_frequency
    length, R = 2.0, 1.5
    Nz, Nphi, Nr = 8, 8, 16
    cz = d3.Coordinate("z")
    cp = d3.PolarCoordinates("phi", "r")
    c = d3.DirectProduct(cz, cp)
    dist = d3.Distributor(c, dtype=np.float64)
    bz = d3.RealFourier(cz, size=Nz, bounds=(0, length))
    bp = d3.DiskBasis(cp, (Nphi, Nr), dtype=np.float64, radius=R)
    u = dist.VectorField(c, name="u", bases=(bz, bp))
    vz, vphi, vr = 2.0, 0.7, 0.3
    ug = np.zeros((3, Nz, Nphi, Nr))
    ug[0], ug[1], ug[2] = vz, vphi, vr
    u["g"] = ug
    freq = np.asarray(advective_cfl_frequency(u, ug))
    # manual spacings: dz uniform; azimuth R/mmax (disk); dr from gradient
    dz = length / Nz
    mmax = Nphi // 2 - 1
    r = np.ravel(bp.global_grids((1, 1))[1])
    dr = np.gradient(r)
    expected = vz / dz + vphi / (R / mmax) + vr / dr[None, None, :]
    expected = np.broadcast_to(expected, freq.shape)
    assert np.allclose(freq, expected, rtol=1e-12)


def test_cfl_bounds_and_threshold():
    solver, u, coords = build_advection(2.0, 0.0)
    # max_dt bound binds for tiny velocity
    u["g"] *= 1e-8
    cfl = CFL(solver, initial_dt=1.0, safety=0.5, max_dt=0.25)
    cfl.add_velocity(u)
    assert cfl.compute_timestep() == 0.25
    # threshold suppresses small changes
    solver2, u2, _ = build_advection(2.0, 0.0)
    cfl2 = CFL(solver2, initial_dt=1.0, safety=0.5, threshold=0.5)
    cfl2.add_velocity(u2)
    dt1 = cfl2.compute_timestep()
    u2["g"] *= 1.2   # < 50% change in frequency
    u2.mark_modified()
    solver2.iteration += 1
    cfl2.cadence = 1
    dt2 = cfl2.compute_timestep()
    assert dt2 == dt1


def test_cfl_min_max_change():
    solver, u, coords = build_advection(2.0, 0.0)
    cfl = CFL(solver, initial_dt=1e-4, safety=0.5, max_change=1.5)
    cfl.add_velocity(u)
    dt = cfl.compute_timestep()
    assert abs(dt - 1.5e-4) < 1e-12


def test_global_flow_property():
    solver, u, coords = build_advection(3.0, 0.0)
    flow = GlobalFlowProperty(solver, cadence=1)
    flow.add_property(u @ u, name="u2")
    solver.step(1e-3)
    assert abs(flow.max("u2") - 9.0) < 1e-8
    assert abs(flow.min("u2") - 9.0) < 1e-8
    assert abs(flow.grid_average("u2") - 9.0) < 1e-8


def test_global_flow_property_report():
    """report(names) returns the health-sink-consumable dict: {name:
    {max, min, avg}} as plain floats; unevaluated names are skipped."""
    solver, u, coords = build_advection(2.0, 1.0)
    flow = GlobalFlowProperty(solver, cadence=1)
    flow.add_property(u @ u, name="u2")
    assert flow.report(["u2"]) == {}        # nothing evaluated yet
    solver.step(1e-3)
    out = flow.report(["u2", "missing"])
    assert set(out) == {"u2"}
    expected = 2.0 ** 2 + 1.0 ** 2
    for key in ("max", "min", "avg"):
        assert isinstance(out["u2"][key], float)
        assert abs(out["u2"][key] - expected) < 1e-8
    import json
    json.dumps(out)                         # sink-serializable as-is


def test_cfl_history_feeds_flight_recorder():
    """compute_timestep appends bounded (iteration, dt, freq_max) entries,
    and the CFL self-registers as a health dt source."""
    solver, u, coords = build_advection(2.0, 0.5)
    cfl = CFL(solver, initial_dt=1.0, safety=0.4, cadence=1, history_size=3)
    cfl.add_velocity(u)
    for i in range(5):
        cfl.compute_timestep()
        solver.iteration += 1
    assert len(cfl.history) == 3            # bounded ring
    last = cfl.history[-1]
    assert set(last) == {"iteration", "dt", "freq_max"}
    assert last["dt"] == cfl.current_dt
    assert last["freq_max"] > 0
    # the solver's health monitor sees the same entries
    assert cfl in solver.health._dt_sources
    hist = solver.health.dt_history()
    assert [e["iteration"] for e in hist] == sorted(
        e["iteration"] for e in hist)
    assert hist[-1]["dt"] == cfl.current_dt


def test_advective_cfl_operator_matches_flow_tool():
    """The AdvectiveCFL operator's grid frequencies agree with the CFL
    flow tool's host computation (reference: core/operators.py:4306)."""
    import dedalus_tpu.public as d3
    from dedalus_tpu.extras.flow_tools import advective_cfl_frequency
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 2.0), dealias=3 / 2)
    zb = d3.ChebyshevT(coords["z"], size=8, bounds=(0, 1.0), dealias=3 / 2)
    u = dist.VectorField(coords, name="u", bases=(xb, zb))
    u.fill_random("g", seed=7, distribution="normal")
    from dedalus_tpu.core.future import EvalContext
    op = d3.AdvectiveCFL(u)
    # compare in grid space (the op's natural layout): a coeff roundtrip
    # would project the non-smooth |u| frequencies
    freq_op = np.asarray(op.ev(EvalContext(), "g"))
    u.change_scales(u.domain.dealias)
    freq_host = advective_cfl_frequency(u, np.asarray(u["g"]))
    assert np.allclose(freq_op, freq_host, rtol=1e-10, atol=1e-12)
