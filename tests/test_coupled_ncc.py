"""
Colatitude-dependent (ell-coupled) NCCs on the shell
(reference: dedalus/core/arithmetic.py:359-406 theta-dependent Clenshaw
NCCs; dedalus/examples/evp_shell_rotating_convection).

The core check: the assembled pencil matrix of an LHS product with a
theta/radius-dependent NCC must act on coefficients exactly like the
grid-space pointwise product. Both are linear maps applied to the same
operand coefficients, so agreement on every azimuthal group is a
bit-level validation of the SWSH triple-product couplings, the
regularity intertwiner sandwich, and the slot bookkeeping.
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3
from dedalus_tpu.core.subsystems import PencilLayout, build_subproblems


def _shell(dtype, Nphi=8, Ntheta=8, Nr=6, radii=(0.6, 1.5)):
    coords = d3.SphericalCoordinates("phi", "theta", "r")
    dist = d3.Distributor(coords, dtype=dtype)
    shell = d3.ShellBasis(coords, shape=(Nphi, Ntheta, Nr), radii=radii,
                          dtype=dtype)
    return coords, dist, shell


def _ez(dist, coords, shell):
    phi, theta, r = dist.local_grids(shell)
    ez = dist.VectorField(coords, name="ez", bases=shell.meridional_basis)
    ez["g"][1] = -np.sin(theta)
    ez["g"][2] = np.cos(theta)
    return ez


def _check_expr(dist, expr, operand, groups=None):
    """Compare the assembled pencil matrix action against grid evaluation
    on every (or selected) azimuthal group."""
    eq = {"domain": expr.domain, "tensorsig": tuple(expr.tensorsig), "L": expr}
    layout = PencilLayout(dist, [operand], [eq])
    # the theta-dependent NCC must have forced the colatitude coupled
    colat = expr.domain.bases[-1].first_axis + 1
    assert colat not in layout.sep_widths
    sps = build_subproblems(layout)
    Xin = np.asarray(layout.gather(operand.coeff_data(), operand.domain,
                                   operand.tensorsig))
    out = expr.evaluate()
    Xout = np.asarray(layout.gather(out.coeff_data(), out.domain,
                                    out.tensorsig))
    scale = max(np.abs(Xout).max(), 1e-12)
    checked = 0
    for sp in sps:
        if groups is not None and sp.index not in groups:
            continue
        mats = expr.expression_matrices(sp, [operand])
        y = mats[operand] @ Xin[sp.index]
        valid = layout.valid_mask(expr.domain, tuple(expr.tensorsig),
                                  sp.group).ravel()
        err = np.abs(y - Xout[sp.index])[valid].max(initial=0.0) / scale
        assert err < 2e-10, (sp.group, err)
        # grid evaluation must not put significant data in invalid slots
        inv = np.abs(Xout[sp.index])[~valid].max(initial=0.0) / scale
        assert inv < 1e-8, (sp.group, inv)
        checked += 1
    assert checked


@pytest.mark.parametrize("dtype", [np.complex128, np.float64])
def test_scalar_ncc_theta_radial(dtype):
    """f(theta, r) * u for scalar u: pure ell-coupling, no spin mixing."""
    coords, dist, shell = _shell(dtype)
    phi, theta, r = dist.local_grids(shell)
    f = dist.Field(name="f", bases=shell.meridional_basis)
    f["g"] = 2.0 + np.cos(theta) * (1 + 0.3 * r) + 0.5 * np.cos(theta) ** 2
    u = dist.Field(name="u", bases=shell)
    u["g"] = np.sin(theta) ** 2 * np.cos(2 * phi) * (r - 1) + np.cos(theta)
    _check_expr(dist, (f * u), u)


@pytest.mark.parametrize("dtype", [np.complex128, np.float64])
def test_vector_ncc_times_scalar(dtype):
    """ez * u: spin-mixing vector NCC times scalar operand."""
    coords, dist, shell = _shell(dtype)
    phi, theta, r = dist.local_grids(shell)
    ez = _ez(dist, coords, shell)
    u = dist.Field(name="u", bases=shell)
    u["g"] = np.cos(theta) * r + np.sin(theta) * np.sin(phi) * (r - 1)
    _check_expr(dist, (ez * u), u)


@pytest.mark.parametrize("dtype", [np.complex128, np.float64])
def test_dot_ncc_vector(dtype):
    """dot(ez, v) for vector v: contraction through the spin metric."""
    coords, dist, shell = _shell(dtype)
    phi, theta, r = dist.local_grids(shell)
    ez = _ez(dist, coords, shell)
    v = dist.VectorField(coords, name="v", bases=shell)
    v["g"][0] = np.sin(theta) * np.cos(phi) * r
    v["g"][1] = np.sin(theta) * np.cos(theta) * (r - 1)
    v["g"][2] = np.cos(theta) ** 2 + 0.2 * r
    _check_expr(dist, d3.dot(ez, v), v)


@pytest.mark.parametrize("dtype", [np.complex128, np.float64])
def test_cross_ncc_vector(dtype):
    """cross(ez, v): the Coriolis coupling. Complex dtype carries the
    +-i spin couplings directly; real dtype carries them through the
    azimuthal (cos, sin) pair representation."""
    coords, dist, shell = _shell(dtype)
    phi, theta, r = dist.local_grids(shell)
    ez = _ez(dist, coords, shell)
    v = dist.VectorField(coords, name="v", bases=shell)
    v["g"][0] = np.sin(theta) * np.sin(phi) * r
    v["g"][1] = np.sin(theta) * np.cos(theta)
    v["g"][2] = np.cos(theta) + 0.1 * r
    _check_expr(dist, d3.cross(ez, v), v)


def test_radial_ncc_stays_separable():
    """An angularly-constant radial NCC must NOT couple ell (fast path)."""
    dtype = np.complex128
    coords, dist, shell = _shell(dtype)
    phi, theta, r = dist.local_grids(shell)
    rvec = dist.VectorField(coords, name="rvec", bases=shell.radial_basis)
    rvec["g"][2] = np.broadcast_to(r, rvec["g"][2].shape)
    u = dist.Field(name="u", bases=shell)
    expr = rvec * u
    eq = {"domain": expr.domain, "tensorsig": tuple(expr.tensorsig), "L": expr}
    layout = PencilLayout(dist, [u], [eq])
    colat = shell.first_axis + 1
    assert colat in layout.sep_widths


@pytest.mark.parametrize("dtype", [np.complex128, np.float64])
def test_lbvp_coupled_ncc_roundtrip(dtype):
    """Full-chain: solve (2 + cos(theta)(1+r)/2) * u = F for known u."""
    coords, dist, shell = _shell(dtype)
    phi, theta, r = dist.local_grids(shell)
    f = dist.Field(name="f", bases=shell.meridional_basis)
    f["g"] = 2.0 + 0.5 * np.cos(theta) * (1 + r)
    u = dist.Field(name="u", bases=shell)
    u_target = dist.Field(name="u_target", bases=shell)
    u_target["g"] = (np.cos(theta) * r
                     + np.sin(theta) * np.sin(phi) * (r - 1.0) ** 2)
    F = (f * u_target).evaluate()
    problem = d3.LBVP([u], namespace=locals())
    problem.add_equation("f*u = F")
    solver = problem.build_solver()
    solver.solve()
    err = np.abs(np.asarray(u["g"]) - np.asarray(u_target["g"])).max()
    assert err < 1e-9


def test_rotating_convection_evp_quick():
    """Rotating convection shell EVP (reference:
    examples/evp_shell_rotating_convection) at half resolution: the
    critical m=13 eigenvalue must land near the Marti et al. Table-1
    value 963.765 (converges to several digits at the reference's full
    64x64 resolution; here we assert the neighborhood)."""
    import pathlib
    import sys
    sys.argv = ["rotating_convection", "--quick"]
    src = (pathlib.Path(__file__).parent.parent / "examples"
           / "rotating_convection.py").read_text()
    ns = {}
    exec(src.split("if __name__")[0], ns)
    solver = ns["solver"]
    subproblem = solver.subproblems_by_group[(13, None, None)]
    solver.solve_sparse(subproblem, 5, 963.765)
    ev = solver.eigenvalues[0]
    assert abs(ev - 963.765) < 40.0


@pytest.mark.parametrize("dtype", [np.complex128, np.float64])
def test_lap_meridional_ncc_shell(dtype):
    """ncc(theta,r)*Lap(u) round-trip with ncc = z^2 = (r cos theta)^2 —
    jointly theta/radius-dependent (reference:
    tests/test_lbvp.py:515 test_lap_meridional_ncc_shell)."""
    coords, dist, shell = _shell(dtype, Nphi=16, Ntheta=8, Nr=16,
                                 radii=(0.5, 1.5))
    phi, theta, r = dist.local_grids(shell)
    x = r * np.sin(theta) * np.cos(phi)
    z = r * np.cos(theta)
    r0, r1 = 0.5, 1.5
    u = dist.Field(name="u", bases=shell)
    v = dist.Field(name="v", bases=shell)
    tau1 = dist.Field(name="tau1", bases=shell.S2_basis())
    tau2 = dist.Field(name="tau2", bases=shell.S2_basis())
    ncc = dist.Field(name="ncc", bases=shell.meridional_basis)
    v["g"] = x ** 2 + z ** 2
    ncc["g"] = z ** 2
    lift = lambda A, n: d3.Lift(A, shell.derivative_basis(2), n)
    F = (ncc * d3.lap(v)).evaluate()
    vr0 = v(r=r0).evaluate()
    vr1 = v(r=r1).evaluate()
    problem = d3.LBVP([u, tau1, tau2], namespace=locals())
    problem.add_equation("ncc*lap(u) + lift(tau1,-1) + lift(tau2,-2) = F")
    problem.add_equation("u(r=0.5) = vr0")
    problem.add_equation("u(r=1.5) = vr1")
    solver = problem.build_solver()
    solver.solve()
    assert np.allclose(np.asarray(u["g"]), np.asarray(v["g"]), atol=1e-8)


def test_lap_2dncc_vector_shell():
    """Meridional + radial NCCs against a VECTOR Laplacian — the case the
    reference marks xfail ("Radial NCCs don't work in meridional problems
    for vectors", tests/test_lbvp.py:573); the quadrature-built coupled
    assembly handles it."""
    dtype = np.complex128
    coords, dist, shell = _shell(dtype, Nphi=8, Ntheta=8, Nr=16,
                                 radii=(0.5, 1.5))
    phi, theta, r = dist.local_grids(shell)
    x = r * np.sin(theta) * np.cos(phi)
    z = r * np.cos(theta)
    u = dist.VectorField(coords, name="u", bases=shell)
    v = dist.VectorField(coords, name="v", bases=shell)
    tau1 = dist.VectorField(coords, name="tau1", bases=shell.S2_basis())
    tau2 = dist.VectorField(coords, name="tau2", bases=shell.S2_basis())
    ez = dist.VectorField(coords, name="ez", bases=shell.meridional_basis)
    ez["g"][1] = -np.sin(theta)
    ez["g"][2] = np.cos(theta)
    ncc_m = dist.Field(name="ncc_m", bases=shell.meridional_basis)
    ncc_r = dist.Field(name="ncc_r", bases=shell.radial_basis)
    v["g"] = (x ** 2 + z ** 2) * np.asarray(ez["g"])
    ncc_m["g"] = z ** 2
    ncc_r["g"] = r ** 2
    lift = lambda A, n: d3.Lift(A, shell.derivative_basis(2), n)
    F = (ncc_r * d3.lap(v) + ncc_m * d3.lap(v)).evaluate()
    vr0 = v(r=0.5).evaluate()
    vr1 = v(r=1.5).evaluate()
    problem = d3.LBVP([u, tau1, tau2], namespace=locals())
    problem.add_equation(
        "ncc_r*lap(u) + ncc_m*lap(u) + lift(tau1,-1) + lift(tau2,-2) = F")
    problem.add_equation("u(r=0.5) = vr0")
    problem.add_equation("u(r=1.5) = vr1")
    solver = problem.build_solver()
    solver.solve()
    assert np.allclose(np.asarray(u["g"]), np.asarray(v["g"]), atol=1e-8)


def _ball(dtype, Nphi=8, Ntheta=8, Nr=8):
    coords = d3.SphericalCoordinates("phi", "theta", "r")
    dist = d3.Distributor(coords, dtype=dtype)
    ball = d3.BallBasis(coords, shape=(Nphi, Ntheta, Nr), radius=1.0,
                        dtype=dtype)
    return coords, dist, ball


@pytest.mark.parametrize("dtype", [np.complex128, np.float64])
def test_ball_scalar_ncc_theta_radial(dtype):
    """f(theta, r)*u on the BALL (ell-coupled Zernike pair matrices)."""
    coords, dist, ball = _ball(dtype)
    phi, theta, r = dist.local_grids(ball)
    z = r * np.cos(theta)
    f = dist.Field(name="f", bases=ball.meridional_basis)
    f["g"] = 2.0 + z ** 2 + 0.3 * z
    u = dist.Field(name="u", bases=ball)
    x = r * np.sin(theta) * np.cos(phi)
    u["g"] = x ** 2 + 0.5 * z + 0.2 * z ** 2
    _check_expr(dist, (f * u), u)


@pytest.mark.parametrize("dtype", [np.complex128, np.float64])
def test_ball_vector_ncc_times_scalar(dtype):
    """ez * u on the ball: spin-mixing with per-(ell, ell') radial maps."""
    coords, dist, ball = _ball(dtype)
    phi, theta, r = dist.local_grids(ball)
    ez = dist.VectorField(coords, name="ez", bases=ball.meridional_basis)
    ez["g"][1] = -np.sin(theta)
    ez["g"][2] = np.cos(theta)
    u = dist.Field(name="u", bases=ball)
    z = r * np.cos(theta)
    u["g"] = z + 0.3 * (r * np.sin(theta)) ** 2 * np.cos(2 * phi)
    _check_expr(dist, (ez * u), u)


@pytest.mark.parametrize("dtype", [np.complex128, np.float64])
def test_ball_cross_ncc_vector(dtype):
    """cross(ez, v) on the ball (Coriolis term of rotating ball flows,
    e.g. the libration example class)."""
    coords, dist, ball = _ball(dtype)
    phi, theta, r = dist.local_grids(ball)
    ez = dist.VectorField(coords, name="ez", bases=ball.meridional_basis)
    ez["g"][1] = -np.sin(theta)
    ez["g"][2] = np.cos(theta)
    v = dist.VectorField(coords, name="v", bases=ball)
    z = r * np.cos(theta)
    v["g"][0] = r * np.sin(theta) * np.sin(phi)
    v["g"][1] = z * np.sin(theta)
    v["g"][2] = 0.4 * z + 0.1 * r ** 2
    _check_expr(dist, d3.cross(ez, v), v)


def _s2(dtype, Nphi=8, Ntheta=8):
    coords = d3.S2Coordinates("phi", "theta")
    dist = d3.Distributor(coords, dtype=dtype)
    # dealias 3/2: the grid-evaluation reference must be alias-free for
    # the top-ell rows to match the exact projection
    basis = d3.SphereBasis(coords, shape=(Nphi, Ntheta), dtype=dtype,
                           radius=1.0, dealias=(3 / 2, 3 / 2))
    return coords, dist, basis


def _check_s2_expr(dist, expr, operand):
    eq = {"domain": expr.domain, "tensorsig": tuple(expr.tensorsig),
          "L": expr}
    layout = PencilLayout(dist, [operand], [eq])
    sps = build_subproblems(layout)
    Xin = np.asarray(layout.gather(operand.coeff_data(), operand.domain,
                                   operand.tensorsig))
    out = expr.evaluate()
    Xout = np.asarray(layout.gather(out.coeff_data(), out.domain,
                                    out.tensorsig))
    scale = max(np.abs(Xout).max(), 1e-12)
    for sp in sps:
        mats = expr.expression_matrices(sp, [operand])
        y = mats[operand] @ Xin[sp.index]
        valid = layout.valid_mask(expr.domain, tuple(expr.tensorsig),
                                  sp.group).ravel()
        err = np.abs(y - Xout[sp.index])[valid].max(initial=0.0) / scale
        assert err < 2e-10, (sp.group, err)


@pytest.mark.parametrize("dtype", [np.complex128, np.float64])
def test_s2_scalar_ncc(dtype):
    """f(theta)*u on the standalone sphere (zonal background class,
    beyond the MulCosine special case)."""
    coords, dist, basis = _s2(dtype)
    phi, theta = dist.local_grids(basis)
    f = dist.Field(name="f", bases=basis)
    f["g"] = 2.0 + np.cos(theta) + 0.5 * np.sin(theta) ** 2 + 0 * phi
    u = dist.Field(name="u", bases=basis)
    u["g"] = np.cos(theta) + np.sin(theta) * np.cos(phi)
    _check_s2_expr(dist, (f * u), u)


@pytest.mark.parametrize("dtype", [np.complex128, np.float64])
def test_s2_dot_meridional_ncc(dtype):
    """dot(f(theta) etheta, v) on the sphere (real spin couplings)."""
    coords, dist, basis = _s2(dtype)
    phi, theta = dist.local_grids(basis)
    w = dist.VectorField(coords, name="w", bases=basis)
    w["g"][1] = np.sin(theta) * np.cos(theta) + 0 * phi
    v = dist.VectorField(coords, name="v", bases=basis)
    v["g"][0] = np.sin(theta) * np.sin(phi)
    v["g"][1] = np.sin(theta) * np.cos(theta)
    _check_s2_expr(dist, d3.dot(w, v), v)


@pytest.mark.parametrize("dtype", [np.complex128, np.float64])
def test_s2_zonal_flow_ncc(dtype):
    """U(theta) ephi * u: azimuthal NCC directions assemble complex spin
    couplings, carried by the pair representation for real dtype
    (linear stability analyses around zonal flows)."""
    coords, dist, basis = _s2(dtype)
    phi, theta = dist.local_grids(basis)
    U = dist.VectorField(coords, name="U", bases=basis)
    U["g"][0] = np.sin(theta) ** 2 + 0 * phi
    u = dist.Field(name="u", bases=basis)
    u["g"] = np.cos(theta) + np.sin(theta) * np.exp(1j * phi).real
    _check_s2_expr(dist, (U * u), u)


def test_rotating_convection_evp_full():
    """Full-resolution (64x64) rotating convection: the critical m=13
    eigenvalue matches Marti, Calkins & Julien (2016) Table 1 to ~1e-5
    relative (963.772 vs 963.765 stress-free; the reference docstring
    quotes 'several digits of precision' at this resolution)."""
    import pathlib
    import sys
    sys.argv = ["rotating_convection"]
    src = (pathlib.Path(__file__).parent.parent / "examples"
           / "rotating_convection.py").read_text()
    ns = {}
    exec(src.split("if __name__")[0], ns)
    solver = ns["solver"]
    subproblem = solver.subproblems_by_group[(13, None, None)]
    solver.solve_sparse(subproblem, 3, 963.765)
    ev = solver.eigenvalues[0]
    assert abs(ev.real - 963.765) < 0.05, ev
    assert abs(ev.imag) < 0.05, ev
