"""
Spherical-shell basis tests: transforms, regularity-component calculus vs
closed forms, NCC products, LBVPs, and a diffusion IVP
(reference patterns: dedalus/tests/test_transforms.py,
tests/test_spherical_calculus.py, tests/test_spherical_operators.py,
tests/test_lbvp.py).
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3

RI, RO = 1.0, 2.0


def make_shell(dtype, shape=(12, 8, 12), radii=(RI, RO), dealias=1):
    cs = d3.SphericalCoordinates("phi", "theta", "r")
    dist = d3.Distributor(cs, dtype=dtype)
    shell = d3.ShellBasis(cs, shape=shape, dtype=dtype, radii=radii,
                          dealias=dealias)
    return cs, dist, shell


def xyz(phi, theta, r):
    return (r * np.sin(theta) * np.cos(phi),
            r * np.sin(theta) * np.sin(phi),
            r * np.cos(theta))


def cartesian_vector_to_spherical(phi, theta, vx, vy, vz):
    """Coordinate components (phi, theta, r) of a Cartesian vector field."""
    v_phi = -np.sin(phi) * vx + np.cos(phi) * vy
    v_theta = (np.cos(theta) * np.cos(phi) * vx
               + np.cos(theta) * np.sin(phi) * vy - np.sin(theta) * vz)
    v_r = (np.sin(theta) * np.cos(phi) * vx
           + np.sin(theta) * np.sin(phi) * vy + np.cos(theta) * vz)
    return v_phi, v_theta, v_r


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("k", [0, 1])
def test_shell_scalar_roundtrip(dtype, k):
    cs, dist, shell = make_shell(dtype)
    shell = shell.clone_with(k=k)
    phi, theta, r = dist.local_grids(shell)
    x, y, z = xyz(phi, theta, r)
    f = dist.Field(name="f", bases=shell)
    f["g"] = x * y + z ** 2 + x + 3 / r
    g0 = np.array(f["g"])
    f["c"] = f["c"]
    assert np.abs(f["g"] - g0).max() < 1e-11


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_shell_vector_roundtrip(dtype):
    cs, dist, shell = make_shell(dtype)
    phi, theta, r = dist.local_grids(shell)
    x, y, z = xyz(phi, theta, r)
    vp, vt, vr = cartesian_vector_to_spherical(phi, theta, y + 1, x, 2 * z)
    u = dist.VectorField(cs, name="u", bases=shell)
    u["g"] = np.array([vp + 0 * r, vt + 0 * r, vr + 0 * r])
    g0 = np.array(u["g"])
    u["c"] = u["c"]
    assert np.abs(u["g"] - g0).max() < 1e-11


def test_shell_tensor_roundtrip():
    cs, dist, shell = make_shell(np.float64)
    phi, theta, r = dist.local_grids(shell)
    x, y, z = xyz(phi, theta, r)
    f = dist.Field(name="f", bases=shell)
    f["g"] = x * y * z + z ** 3
    T = d3.grad(d3.grad(f)).evaluate()
    g0 = np.array(T["g"])
    T["c"] = T["c"]
    assert np.abs(T["g"] - g0).max() < 1e-10


def test_shell_gradient():
    cs, dist, shell = make_shell(np.float64)
    phi, theta, r = dist.local_grids(shell)
    x, y, z = xyz(phi, theta, r)
    f = dist.Field(name="f", bases=shell)
    f["g"] = x * y + z ** 2 + x + 3
    vp, vt, vr = cartesian_vector_to_spherical(phi, theta, y + 1, x, 2 * z)
    g = d3.grad(f).evaluate()["g"]
    assert np.abs(g[0] - vp).max() < 1e-11
    assert np.abs(g[1] - vt).max() < 1e-11
    assert np.abs(g[2] - vr).max() < 1e-11


def test_shell_laplacian_divergence_curl():
    cs, dist, shell = make_shell(np.float64)
    phi, theta, r = dist.local_grids(shell)
    x, y, z = xyz(phi, theta, r)
    f = dist.Field(name="f", bases=shell)
    f["g"] = x * y + z ** 2 + x + 3
    assert np.abs(d3.lap(f).evaluate()["g"] - 2.0).max() < 1e-9
    assert np.abs(d3.div(d3.grad(f)).evaluate()["g"] - 2.0).max() < 1e-9
    assert np.abs(d3.curl(d3.grad(f)).evaluate()["g"]).max() < 1e-9


def test_shell_curl_of_rotation():
    """curl of the rigid rotation u = Omega x r is 2 Omega."""
    cs, dist, shell = make_shell(np.float64)
    phi, theta, r = dist.local_grids(shell)
    x, y, z = xyz(phi, theta, r)
    # u = z_hat x r = (-y, x, 0)
    vp, vt, vr = cartesian_vector_to_spherical(phi, theta, -y, x, 0 * z)
    u = dist.VectorField(cs, name="u", bases=shell)
    u["g"] = np.array([vp, vt, vr + 0 * x])
    c = d3.curl(u).evaluate()["g"]
    wp, wt, wr = cartesian_vector_to_spherical(phi, theta, 0 * x, 0 * x,
                                               2 + 0 * x)
    assert np.abs(c[0] - wp).max() < 1e-10
    assert np.abs(c[1] - wt).max() < 1e-10
    assert np.abs(c[2] - wr).max() < 1e-10


def test_shell_trace_vs_laplacian():
    cs, dist, shell = make_shell(np.float64)
    phi, theta, r = dist.local_grids(shell)
    x, y, z = xyz(phi, theta, r)
    f = dist.Field(name="f", bases=shell)
    f["g"] = x * y * z + z ** 3
    lap = d3.lap(f).evaluate()["g"]
    tr = d3.trace(d3.grad(d3.grad(f))).evaluate()["g"]
    assert np.abs(tr - lap).max() < 1e-9


def test_shell_trace_lhs_matrix():
    """The coefficient-space trace matrix (Q-intertwined spin metric) agrees
    with the laplacian identity trace(grad(grad(f))) == lap(f)."""
    cs, dist, shell = make_shell(np.float64, dealias=3 / 2)
    phi, theta, r = dist.local_grids(shell)
    x, y, z = xyz(phi, theta, r)
    f = dist.Field(name="f", bases=shell)
    f["g"] = x * z + np.asarray(r) ** 3
    f2 = dist.Field(name="f2", bases=shell)
    s = dist.Field(name="s", bases=shell)
    problem = d3.LBVP([f2, s], namespace=locals())
    problem.add_equation("s - trace(grad(grad(f2))) = 0")
    problem.add_equation("f2 = f")
    problem.build_solver().solve()
    lap = d3.lap(f).evaluate()["g"]
    assert np.abs(np.asarray(s["g"]) - np.asarray(lap)).max() < 1e-9


def test_shell_vector_ncc():
    """Radial vector NCCs (b*er, rvec*b) assemble exact LHS matrices."""
    cs, dist, shell = make_shell(np.float64, shape=(8, 6, 8), dealias=3 / 2)
    phi, theta, r = dist.local_grids(shell)
    x, y, z = xyz(phi, theta, r)
    er = dist.VectorField(cs, name="er", bases=shell)
    er["g"][2] = 1.0
    bvar = dist.Field(name="bvar", bases=shell)
    w = dist.VectorField(cs, name="w", bases=shell)
    f = dist.Field(name="f", bases=shell)
    f["g"] = x * z + np.asarray(r) ** 2
    problem = d3.LBVP([bvar, w], namespace=locals())
    problem.add_equation("w - bvar*er = 0")
    problem.add_equation("bvar = f")
    problem.build_solver().solve()
    expect = np.zeros_like(np.asarray(w["g"]))
    expect[2] = np.asarray(f["g"])
    assert np.abs(np.asarray(w["g"]) - expect).max() < 1e-12


def test_field_view_writeback():
    """u['g'][comp] = ... writes through to the field; derived arrays don't."""
    cs, dist, shell = make_shell(np.float64, shape=(4, 3, 4))
    u = dist.VectorField(cs, name="u", bases=shell)
    u["g"][2] = 1.0
    assert np.abs(np.asarray(u["g"])[2] - 1.0).max() < 1e-15
    assert np.abs(np.asarray(u["g"])[0]).max() < 1e-15
    t = dist.Field(name="t", bases=shell)
    t["g"] = 3.0
    w = t["g"] * 2
    w[0] = 99.0
    assert np.abs(np.asarray(t["g"]) - 3.0).max() < 1e-15
    t["g"] += 1.0
    assert np.abs(np.asarray(t["g"]) - 4.0).max() < 1e-15


def test_shell_interpolation_and_components():
    cs, dist, shell = make_shell(np.float64)
    phi, theta, r = dist.local_grids(shell)
    x, y, z = xyz(phi, theta, r)
    f = dist.Field(name="f", bases=shell)
    f["g"] = x * y + z ** 2 + x + 3
    phig, thetag = phi[:, :, 0], theta[:, :, 0]
    for r0 in (RI, RO):
        xo, yo, zo = xyz(phig, thetag, r0)
        fo = f(r=r0).evaluate()["g"]
        assert np.abs(fo[:, :, 0] - (xo * yo + zo ** 2 + xo + 3)).max() < 1e-11
    u = d3.grad(f)
    uo = u(r=RO).evaluate()
    xo, yo, zo = xyz(phig, thetag, RO)
    vp, vt, vr = cartesian_vector_to_spherical(phig, thetag, yo + 1, xo, 2 * zo)
    assert np.abs(d3.radial(uo).evaluate()["g"][:, :, 0] - vr).max() < 1e-10
    ang = d3.angular(uo).evaluate()["g"]
    assert np.abs(ang[0][:, :, 0] - vp).max() < 1e-10
    assert np.abs(ang[1][:, :, 0] - vt).max() < 1e-10


def test_shell_integration():
    cs, dist, shell = make_shell(np.float64)
    phi, theta, r = dist.local_grids(shell)
    x, y, z = xyz(phi, theta, r)
    f = dist.Field(name="f", bases=shell)
    f["g"] = z ** 2 + 3 + x  # odd x integrates to zero
    total = float(d3.integ(f).evaluate()["g"].ravel()[0])
    exact = 4 * np.pi / 3 * ((RO ** 5 - RI ** 5) / 5 + 3 * (RO ** 3 - RI ** 3))
    assert abs(total - exact) < 1e-11
    ave = float(d3.ave(f).evaluate()["g"].ravel()[0])
    assert abs(ave - exact / shell.volume) < 1e-12


def test_shell_ncc_lhs_vs_rhs():
    cs, dist, shell = make_shell(np.float64, shape=(8, 6, 10), dealias=3 / 2)
    phi, theta, r = dist.local_grids(shell)
    x, y, z = xyz(phi, theta, r)
    ncc = dist.Field(name="ncc", bases=shell)
    ncc["g"] = r ** 2 + 1 / r
    v = dist.Field(name="v", bases=shell)
    w = dist.Field(name="w", bases=shell)
    problem = d3.LBVP([v], namespace=locals())
    problem.add_equation("ncc*v = ncc*w")
    w["g"] = x * z + r
    problem.build_solver().solve()
    assert np.abs(v["g"] - w["g"]).max() < 1e-12


def test_shell_scalar_poisson_lbvp():
    cs, dist, shell = make_shell(np.float64)
    phi, theta, r = dist.local_grids(shell)
    u = dist.Field(name="u", bases=shell)
    t1 = dist.Field(name="t1", bases=shell.S2_basis(RO))
    t2 = dist.Field(name="t2", bases=shell.S2_basis(RI))
    six = dist.Field(name="six", bases=shell)
    six["g"] = 6.0
    lift_basis = shell.derivative_basis(2)
    lift = lambda A, n: d3.Lift(A, lift_basis, n)
    problem = d3.LBVP([u, t1, t2], namespace={**locals(), "RI": RI, "RO": RO})
    problem.add_equation("lap(u) + lift(t1, -1) + lift(t2, -2) = six")
    problem.add_equation("u(r=RI) = RI**2")
    problem.add_equation("u(r=RO) = RO**2")
    problem.build_solver().solve()
    assert np.abs(u["g"] - r ** 2).max() < 1e-12


def test_shell_vector_lbvp():
    """lap(u) = 0 for u = grad(xyz) with exact boundary data."""
    cs, dist, shell = make_shell(np.float64)
    phi, theta, r = dist.local_grids(shell)
    x, y, z = xyz(phi, theta, r)
    h = dist.Field(name="h", bases=shell)
    h["g"] = x * y * z
    u_exact = d3.grad(h).evaluate()
    u = dist.VectorField(cs, name="u", bases=shell)
    tu1 = dist.VectorField(cs, name="tu1", bases=shell.S2_basis(RO))
    tu2 = dist.VectorField(cs, name="tu2", bases=shell.S2_basis(RI))
    bco = u_exact(r=RO).evaluate()
    bci = u_exact(r=RI).evaluate()
    lift_basis = shell.derivative_basis(2)
    lift = lambda A, n: d3.Lift(A, lift_basis, n)
    problem = d3.LBVP([u, tu1, tu2], namespace={**locals(), "RI": RI, "RO": RO})
    problem.add_equation("lap(u) + lift(tu1, -1) + lift(tu2, -2) = 0")
    problem.add_equation("u(r=RI) = bci")
    problem.add_equation("u(r=RO) = bco")
    problem.build_solver().solve()
    assert np.abs(u["g"] - u_exact["g"]).max() < 1e-11


def test_shell_diffusion_ivp():
    cs, dist, shell = make_shell(np.float64, shape=(8, 6, 10), dealias=3 / 2)
    phi, theta, r = dist.local_grids(shell)
    x, y, z = xyz(phi, theta, r)
    u = dist.Field(name="u", bases=shell)
    t1 = dist.Field(name="t1", bases=shell.S2_basis(RO))
    t2 = dist.Field(name="t2", bases=shell.S2_basis(RI))
    lift_basis = shell.derivative_basis(2)
    lift = lambda A, n: d3.Lift(A, lift_basis, n)
    problem = d3.IVP([u, t1, t2], namespace={**locals(), "RI": RI, "RO": RO})
    problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = - u*u")
    problem.add_equation("u(r=RI) = 0")
    problem.add_equation("u(r=RO) = 0")
    solver = problem.build_solver(d3.RK222)
    u["g"] = np.sin(np.pi * (r - RI)) * (1 + 0.3 * x / r)
    E0 = float(d3.integ(u * u).evaluate()["g"].ravel()[0])
    for _ in range(40):
        solver.step(2e-3)
    E1 = float(d3.integ(u * u).evaluate()["g"].ravel()[0])
    assert np.isfinite(E1)
    assert E1 < E0
    assert np.abs(u(r=RI).evaluate()["g"]).max() < 1e-12
    assert np.abs(u(r=RO).evaluate()["g"]).max() < 1e-12


def test_spherical_ell_product_shell_lhs():
    """SphericalEllProduct on the shell, used on an LHS (per-(m, ell)
    pencil matrices): hyperdiffusion-style ell scaling."""
    coords = d3.SphericalCoordinates("phi", "theta", "r")
    dist = d3.Distributor(coords, dtype=np.float64)
    shell = d3.ShellBasis(coords, shape=(8, 8, 8), radii=(0.5, 1.5),
                          dtype=np.float64)
    phi, theta, r = dist.local_grids(shell)
    u = dist.Field(name="u", bases=shell)
    u_target = dist.Field(name="u_target", bases=shell)
    u_target["g"] = np.cos(theta) * r + np.sin(theta) * np.cos(phi)
    ellp = lambda A: d3.SphericalEllProduct(A, coords, lambda l: 1 + l * l)
    F = ellp(u_target).evaluate()
    problem = d3.LBVP([u], namespace=locals())
    problem.add_equation("ellp(u) = F")
    solver = problem.build_solver()
    solver.solve()
    err = np.abs(np.asarray(u["g"]) - np.asarray(u_target["g"])).max()
    assert err < 1e-12
