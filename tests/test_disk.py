"""
Disk basis tests: transforms, calculus operators vs closed forms, and LBVPs
vs manufactured solutions
(reference patterns: dedalus/tests/test_transforms.py:358 roundtrips,
tests/test_polar_calculus.py, tests/test_lbvp.py).
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3


def make_disk(dtype, shape=(24, 16), radius=1.5, names=("phi", "r")):
    cs = d3.PolarCoordinates(*names)
    dist = d3.Distributor(cs, dtype=dtype)
    disk = d3.DiskBasis(cs, shape=shape, dtype=dtype, radius=radius)
    return cs, dist, disk


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_disk_scalar_roundtrip(dtype):
    cs, dist, disk = make_disk(dtype)
    phi, r = dist.local_grids(disk)
    x, y = r * np.cos(phi), r * np.sin(phi)
    f = dist.Field(name="f", bases=disk)
    f["g"] = x ** 2 + 2 * x * y - y ** 2 + 3
    g0 = np.array(f["g"])
    f["c"] = f["c"]
    assert np.abs(f["g"] - g0).max() < 1e-12


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_disk_vector_roundtrip(dtype):
    cs, dist, disk = make_disk(dtype)
    phi, r = dist.local_grids(disk)
    x, y = r * np.cos(phi), r * np.sin(phi)
    ux = 2 * x * y
    uy = x ** 2 - y ** 2 + 1
    u = dist.VectorField(cs, name="u", bases=disk)
    u["g"] = np.array([-np.sin(phi) * ux + np.cos(phi) * uy,
                       np.cos(phi) * ux + np.sin(phi) * uy])
    g0 = np.array(u["g"])
    u["c"] = u["c"]
    assert np.abs(u["g"] - g0).max() < 1e-12


def test_disk_tensor_roundtrip():
    cs, dist, disk = make_disk(np.float64)
    phi, r = dist.local_grids(disk)
    x, y = r * np.cos(phi), r * np.sin(phi)
    T = dist.TensorField(cs, name="T", bases=disk)
    Tc = np.array([[x * y + 0 * r, x ** 2 + 0 * r],
                   [y ** 2 + 0 * r, x + y + 0 * r]])
    R = np.array([[-np.sin(phi) + 0 * r, np.cos(phi) + 0 * r],
                  [np.cos(phi) + 0 * r, np.sin(phi) + 0 * r]])
    T["g"] = np.einsum("ia...,ab...,jb...->ij...", R, Tc, R)
    g0 = np.array(T["g"])
    T["c"] = T["c"]
    assert np.abs(T["g"] - g0).max() < 1e-11


def test_disk_coeff_roundtrip_random():
    """Valid random coefficients survive a grid roundtrip."""
    cs, dist, disk = make_disk(np.float64, shape=(16, 12))
    f = dist.Field(name="f", bases=disk)
    rng = np.random.default_rng(0)
    c = rng.standard_normal(f["c"].shape)
    for g in range(8):
        c[2 * g:2 * g + 2, :g // 2] = 0
    c[1, :] = 0
    f["c"] = c
    f["g"] = f["g"]
    assert np.abs(f["c"] - c).max() < 1e-11


def test_disk_calculus():
    """grad/div/lap/skew vs closed forms on polynomials."""
    cs, dist, disk = make_disk(np.float64, radius=2.0)
    phi, r = dist.local_grids(disk)
    x, y = r * np.cos(phi), r * np.sin(phi)
    f = dist.Field(name="f", bases=disk)
    f["g"] = x ** 3 * y - y ** 2 + x
    dfx = 3 * x ** 2 * y + 1
    dfy = x ** 3 - 2 * y
    gphi = -np.sin(phi) * dfx + np.cos(phi) * dfy
    gr = np.cos(phi) * dfx + np.sin(phi) * dfy
    g = d3.grad(f).evaluate()["g"]
    assert np.abs(g[0] - gphi).max() < 1e-9
    assert np.abs(g[1] - gr).max() < 1e-9
    lap_analytic = 6 * x * y - 2
    assert np.abs(d3.lap(f).evaluate()["g"] - lap_analytic).max() < 1e-7
    assert np.abs(d3.div(d3.grad(f)).evaluate()["g"] - lap_analytic).max() < 1e-7
    u = d3.grad(f)
    sk = d3.skew(u).evaluate()["g"]
    assert np.abs(sk[0] - gr).max() < 1e-9
    assert np.abs(sk[1] + gphi).max() < 1e-9


def test_disk_vector_laplacian_commutes_with_gradient():
    cs, dist, disk = make_disk(np.float64)
    phi, r = dist.local_grids(disk)
    x, y = r * np.cos(phi), r * np.sin(phi)
    f = dist.Field(name="f", bases=disk)
    f["g"] = x ** 4 - 3 * x * y ** 2 + y
    lap_grad = d3.lap(d3.grad(f)).evaluate()["g"]
    grad_lap = d3.grad(d3.lap(f)).evaluate()["g"]
    assert np.abs(lap_grad - grad_lap).max() < 1e-6


def test_disk_interpolation_and_integration():
    cs, dist, disk = make_disk(np.float64, radius=2.0)
    phi, r = dist.local_grids(disk)
    x, y = r * np.cos(phi), r * np.sin(phi)
    f = dist.Field(name="f", bases=disk)
    f["g"] = x ** 2 * y - y + 2
    fR = f(r=2.0).evaluate()
    phig = phi[:, 0]
    xg, yg = 2 * np.cos(phig), 2 * np.sin(phig)
    assert np.abs(fR["g"][:, 0] - (xg ** 2 * yg - yg + 2)).max() < 1e-10
    total = float(d3.integ(f).evaluate()["g"].ravel()[0])
    # odd terms integrate to zero over the disk; constant integrates to 2*area
    assert abs(total - 2 * np.pi * 4) < 1e-10


def test_disk_edge_components():
    cs, dist, disk = make_disk(np.float64, radius=2.0)
    phi, r = dist.local_grids(disk)
    x, y = r * np.cos(phi), r * np.sin(phi)
    f = dist.Field(name="f", bases=disk)
    f["g"] = x ** 3 * y - y ** 2 + x
    u = d3.grad(f)
    uR = d3.Interpolate(u, cs.radius, 2.0)
    phig = phi[:, 0]
    dfx = 3 * (2 * np.cos(phig)) ** 2 * (2 * np.sin(phig)) + 1
    dfy = (2 * np.cos(phig)) ** 3 - 2 * (2 * np.sin(phig))
    expect_r = np.cos(phig) * dfx + np.sin(phig) * dfy
    expect_a = -np.sin(phig) * dfx + np.cos(phig) * dfy
    assert np.abs(d3.radial(uR).evaluate()["g"][:, 0] - expect_r).max() < 1e-9
    assert np.abs(d3.azimuthal(uR).evaluate()["g"][:, 0] - expect_a).max() < 1e-9


def test_disk_scalar_poisson_lbvp():
    cs, dist, disk = make_disk(np.float64, radius=1.5)
    R = 1.5
    phi, r = dist.local_grids(disk)
    x, y = r * np.cos(phi), r * np.sin(phi)
    u = dist.Field(name="u", bases=disk)
    tau = dist.Field(name="tau", bases=disk.edge)
    f = dist.Field(name="f", bases=disk)
    f["g"] = -12 * x * y  # lap of (R^2 - r^2) x y
    lift = lambda A: d3.Lift(A, disk.derivative_basis(2), -1)
    problem = d3.LBVP([u, tau], namespace=locals())
    problem.add_equation("lap(u) + lift(tau) = f")
    problem.add_equation("u(r=1.5) = 0")
    problem.build_solver().solve()
    assert np.abs(u["g"] - (R ** 2 - r ** 2) * x * y).max() < 1e-12


def test_disk_vector_poisson_lbvp():
    cs, dist, disk = make_disk(np.float64, radius=1.0)
    phi, r = dist.local_grids(disk)
    x, y = r * np.cos(phi), r * np.sin(phi)
    u = dist.VectorField(cs, name="u", bases=disk)
    tau_u = dist.VectorField(cs, name="tau_u", bases=disk.edge)
    F = dist.VectorField(cs, name="F", bases=disk)
    fx, fy = 32 * x, 32 * y  # lap(grad((1-r^2)^2))
    F["g"] = np.array([-np.sin(phi) * fx + np.cos(phi) * fy,
                       np.cos(phi) * fx + np.sin(phi) * fy])
    lift = lambda A: d3.Lift(A, disk.derivative_basis(2), -1)
    problem = d3.LBVP([u, tau_u], namespace=locals())
    problem.add_equation("lap(u) + lift(tau_u) = F")
    problem.add_equation("u(r=1) = 0")
    problem.build_solver().solve()
    ex, ey = -4 * x * (1 - r ** 2), -4 * y * (1 - r ** 2)
    expect = np.array([-np.sin(phi) * ex + np.cos(phi) * ey,
                       np.cos(phi) * ex + np.sin(phi) * ey])
    assert np.abs(u["g"] - expect).max() < 1e-12


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_disk_ncc_lhs(dtype):
    """Disk LHS NCCs (scalar, radial-vector, and contraction forms): the
    per-(m, spin) Zernike stack path (arithmetic._disk_ncc_matrix) must
    reproduce grid products exactly for band-limited data (the pipe-flow
    EVP relies on w0*dz(u) and u@grad(w0) terms of these forms)."""
    coords = d3.PolarCoordinates("phi", "r")
    dist = d3.Distributor(coords, dtype=dtype)
    disk = d3.DiskBasis(coords, shape=(16, 12), radius=1.0, dtype=dtype)
    phi, r = dist.local_grids(disk)
    w0 = dist.Field(name="w0", bases=disk)
    w0["g"] = np.broadcast_to(np.asarray(1 - r ** 2),
                              np.broadcast_shapes(phi.shape, r.shape))
    gv = dist.VectorField(coords, name="gv", bases=disk)
    gv["g"][1] = np.broadcast_to(np.asarray(r),
                                 np.broadcast_shapes(phi.shape, r.shape))
    bsrc = dist.Field(name="bsrc", bases=disk)
    bsrc["g"] = (r * np.cos(phi)) ** 2 + r * np.sin(phi)
    vsrc = dist.VectorField(coords, name="vsrc", bases=disk)
    vsrc["g"][0] = r * np.cos(phi)
    vsrc["g"][1] = r ** 2
    b2 = dist.Field(name="b2", bases=disk)
    u = dist.VectorField(coords, name="u", bases=disk)
    v2 = dist.VectorField(coords, name="v2", bases=disk)
    s2 = dist.Field(name="s2", bases=disk)
    w2 = dist.Field(name="w2", bases=disk)
    problem = d3.LBVP([b2, u, v2, s2, w2], namespace=locals())
    problem.add_equation("b2 = bsrc")
    problem.add_equation("v2 = vsrc")
    problem.add_equation("u + gv*b2 = 0")
    problem.add_equation("s2 - w0*b2 = 0")
    problem.add_equation("w2 + gv@v2 = 0")
    solver = problem.build_solver()
    solver.solve()
    e1 = np.abs(np.asarray(u["g"])
                + np.asarray(gv["g"]) * np.asarray(bsrc["g"])[None]).max()
    e2 = np.abs(np.asarray(s2["g"])
                - np.asarray(w0["g"]) * np.asarray(bsrc["g"])).max()
    e3 = np.abs(np.asarray(w2["g"])
                + (np.asarray(gv["g"]) * np.asarray(vsrc["g"])).sum(0)).max()
    assert max(e1, e2, e3) < 1e-11, (e1, e2, e3)
