"""
Collective-placement test: the compiled sharded step must move pencils
with all-to-all transposes, NOT full-state all-gathers (reference
counterpart: the MPI Alltoallv transposes ARE the hot communication path,
/root/reference/dedalus/core/transposes.pyx:246; an accidental gather
destroys memory and scaling silently at large sizes).

XLA's SPMD partitioner cannot partition fft ops — without the
meshctx.local_fft shard_map routing, every batched FFT in the step
lowered as all-gather + replicated full-size FFT (observed in round 3 on
the virtual 8-device mesh).
"""

import re

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import dedalus_tpu.public as d3
from dedalus_tpu.parallel import distribute_solver

N_DEV = len(jax.devices())
needs_devices = pytest.mark.skipif(N_DEV < 4, reason="needs >= 4 devices")


def build_sharded_step():
    mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 4.0), dealias=3 / 2)
    zb = d3.ChebyshevT(coords["z"], size=8, bounds=(0, 1.0), dealias=3 / 2)
    u = dist.Field(name="u", bases=(xb, zb))
    t1 = dist.Field(name="t1", bases=xb)
    t2 = dist.Field(name="t2", bases=xb)
    lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
    problem = d3.IVP([u, t1, t2], namespace=locals())
    problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = - u*u")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("u(z=1) = 0")
    solver = problem.build_solver(d3.SBDF2)
    x, z = dist.local_grids(xb, zb)
    u["g"] = np.sin(np.pi * z) * (1 + 0.3 * np.cos(np.pi * x / 2))
    distribute_solver(solver, mesh)
    return solver


def collective_counts(hlo_text):
    out = {}
    for op in ("all-to-all", "all-gather", "all-reduce", "reduce-scatter"):
        out[op] = len(re.findall(rf"\s{op}\(", hlo_text))
    return out


@needs_devices
def test_sharded_step_uses_all_to_all_not_gather():
    solver = build_sharded_step()
    solver.step(1e-3)  # builds factors; also catches runtime errors
    ts = solver.timestepper
    rd = solver.real_dtype
    s = ts.steps + 1
    a = b = jnp.zeros(s, dtype=rd)
    c = jnp.zeros(ts.steps, dtype=rd)
    args = (solver.M_mat, solver.L_mat, solver.X,
            jnp.asarray(0.0, dtype=rd), solver.rhs_extra(),
            ts.F_hist, ts.MX_hist, ts.LX_hist, a, b, c, ts._lhs_aux)
    txt = ts._advance.lower(*args).compile().as_text()
    counts = collective_counts(txt)
    assert counts["all-to-all"] >= 2, f"transform transposes missing: {counts}"
    assert counts["all-gather"] == 0, (
        f"full-state gathers in the sharded step: {counts} — the fft "
        "shard_map routing (core/meshctx.local_fft) has regressed")


@needs_devices
def test_sharded_checkpoint_write_is_per_shard_copies_only():
    """The zero-full-state-gather assertion, promoted to the durability
    path (ROADMAP item 4 leftover): capturing a fleet snapshot moves no
    bytes (device references), and writing a sharded checkpoint of an
    8-device fleet state host-copies ONE SHARD AT A TIME — the global
    array is never materialized on host. The spy wraps the module-level
    dcheckpoint._copy_out hook, which every shard copy funnels through."""
    import dedalus_tpu.public as d3_pub  # noqa: F401 (solver stack ready)
    from dedalus_tpu.tools import dcheckpoint as dc
    import tempfile

    mesh = Mesh(np.array(jax.devices()), ("batch",))
    from jax.sharding import NamedSharding, PartitionSpec
    n_dev = len(jax.devices())
    G, S = 16, 24
    fleet = jax.device_put(
        jnp.arange(n_dev * 2 * G * S, dtype=jnp.float64).reshape(
            n_dev * 2, G, S),
        NamedSharding(mesh, PartitionSpec("batch")))
    global_nbytes = fleet.nbytes
    copies = []
    original = dc._copy_out
    import threading
    writer_gate = threading.Event()   # holds the writer thread so the
    # submit-side assertion below cannot race its first copy

    def spy(block):
        writer_gate.wait(timeout=30)
        out = original(block)
        copies.append(out.nbytes)
        return out

    dc._copy_out = spy
    try:
        with tempfile.TemporaryDirectory() as tmp:
            # async submit: the capture itself must copy nothing
            ck = dc.ShardedCheckpointer(tmp, async_write=True, inflight=2)
            ck.save({"X": fleet}, {"iteration": 1})
            assert copies == [], \
                "async capture host-copied state at submit time"
            writer_gate.set()
            assert ck.drain() == []
            event = dc.restore_latest(tmp)
            assert np.array_equal(event["arrays"]["X"], np.asarray(fleet))
    finally:
        dc._copy_out = original
    # one copy per device shard, each exactly shard-sized — and nothing
    # anywhere near the global size (the all-gather signature)
    assert len(copies) == n_dev
    assert all(nb == global_nbytes // n_dev for nb in copies), copies
    assert max(copies) < global_nbytes


@needs_devices
def test_sharded_step_matches_unsharded_with_local_fft():
    """The shard_map fft routing must not change the numerics."""
    solver = build_sharded_step()
    for _ in range(5):
        solver.step(1e-3)
    X_sharded = np.asarray(solver.X)

    # rebuild unsharded
    mesh_backup = None
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 4.0), dealias=3 / 2)
    zb = d3.ChebyshevT(coords["z"], size=8, bounds=(0, 1.0), dealias=3 / 2)
    u = dist.Field(name="u", bases=(xb, zb))
    t1 = dist.Field(name="t1", bases=xb)
    t2 = dist.Field(name="t2", bases=xb)
    lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
    problem = d3.IVP([u, t1, t2], namespace=locals())
    problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = - u*u")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("u(z=1) = 0")
    ref = problem.build_solver(d3.SBDF2)
    x, z = dist.local_grids(xb, zb)
    u["g"] = np.sin(np.pi * z) * (1 + 0.3 * np.cos(np.pi * x / 2))
    for _ in range(5):
        ref.step(1e-3)
    assert np.allclose(X_sharded, np.asarray(ref.X), atol=1e-13)
