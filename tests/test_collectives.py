"""
Collective-placement test: the compiled sharded step must move pencils
with all-to-all transposes, NOT full-state all-gathers (reference
counterpart: the MPI Alltoallv transposes ARE the hot communication path,
/root/reference/dedalus/core/transposes.pyx:246; an accidental gather
destroys memory and scaling silently at large sizes).

XLA's SPMD partitioner cannot partition fft ops — without the
meshctx.local_fft shard_map routing, every batched FFT in the step
lowered as all-gather + replicated full-size FFT (observed in round 3 on
the virtual 8-device mesh).

The parsing machinery lives in the program contract checker
(tools/lint/progcheck.collective_counts — this file's ad-hoc regex,
promoted to shared, size-aware analysis), the program shape in
extras/bench_problems.build_tau_ivp, and the program handle in
core/timesteppers.step_program_handle: the assertions here are the SAME
checks `python -m dedalus_tpu lint --programs` runs over the whole
census, kept as tests so a regression names the exact program.
"""

import numpy as np
import pytest
import jax
from jax.sharding import Mesh

import dedalus_tpu.public as d3  # noqa: F401  (solver stack ready)
from dedalus_tpu.core.timesteppers import step_program_handle
from dedalus_tpu.extras.bench_problems import build_tau_ivp
from dedalus_tpu.parallel import distribute_solver
from dedalus_tpu.tools.lint.progcheck import collective_counts

N_DEV = len(jax.devices())
needs_devices = pytest.mark.skipif(N_DEV < 4, reason="needs >= 4 devices")
needs_8 = pytest.mark.skipif(N_DEV < 8, reason="needs >= 8 devices")


def build_sharded_step():
    solver, u, x, z = build_tau_ivp()
    distribute_solver(solver, Mesh(np.array(jax.devices()[:4]), ("x",)))
    return solver


def step_hlo(solver):
    solver_prog, args = step_program_handle(solver)
    return solver_prog.lower(*args).compile().as_text()


@needs_devices
def test_sharded_step_uses_all_to_all_not_gather():
    solver = build_sharded_step()
    solver.step(1e-3)  # builds factors; also catches runtime errors
    counts = collective_counts(step_hlo(solver))
    assert counts["all-to-all"] >= 2, f"transform transposes missing: {counts}"
    assert counts["all-gather"] == 0, (
        f"full-state gathers in the sharded step: {counts} — the fft "
        "shard_map routing (core/meshctx.local_fft) has regressed")


@needs_8
def test_fleet_2d_step_uses_no_gathers():
    """The zero-full-state-gather assertion PROMOTED to the 2-D
    batch x pencil fleet program (which previously had no gather
    assertion at all): members shard_map MANUAL over batch with pencils
    in GSPMD auto mode — exactly the regime where the partitioner
    degrades an unrouted op to a gather silently."""
    solver, u, x, z = build_tau_ivp()
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("batch", "pencil"))
    fleet = solver.ensemble(2, mesh=mesh)

    def ics(i):
        u["g"] = np.sin(np.pi * z) * (1 + 0.1 * (i + 1)
                                      * np.cos(np.pi * x / 2))

    fleet.init_members(ics)
    fleet.step_many(4, 1e-3)
    prog, args = fleet.step_program_handle()
    counts = collective_counts(prog.lower(*args).compile().as_text())
    assert counts["all-to-all"] >= 2, counts   # pencil transposes live
    assert counts["all-gather"] == 0, (
        f"full-state gathers in the 2-D fleet step: {counts} — the "
        "pencil routing of the batch x pencil composition has regressed")


@needs_devices
def test_sharded_checkpoint_write_is_per_shard_copies_only():
    """The zero-full-state-gather assertion, promoted to the durability
    path (ROADMAP item 4 leftover): capturing a fleet snapshot moves no
    bytes (device references), and writing a sharded checkpoint of an
    8-device fleet state host-copies ONE SHARD AT A TIME — the global
    array is never materialized on host. The spy wraps the module-level
    dcheckpoint._copy_out hook, which every shard copy funnels through."""
    from dedalus_tpu.tools import dcheckpoint as dc
    import jax.numpy as jnp
    import tempfile

    mesh = Mesh(np.array(jax.devices()), ("batch",))
    from jax.sharding import NamedSharding, PartitionSpec
    n_dev = len(jax.devices())
    G, S = 16, 24
    fleet = jax.device_put(
        jnp.arange(n_dev * 2 * G * S, dtype=jnp.float64).reshape(
            n_dev * 2, G, S),
        NamedSharding(mesh, PartitionSpec("batch")))
    global_nbytes = fleet.nbytes
    copies = []
    original = dc._copy_out
    import threading
    writer_gate = threading.Event()   # holds the writer thread so the
    # submit-side assertion below cannot race its first copy

    def spy(block):
        writer_gate.wait(timeout=30)
        out = original(block)
        copies.append(out.nbytes)
        return out

    dc._copy_out = spy
    try:
        with tempfile.TemporaryDirectory() as tmp:
            # async submit: the capture itself must copy nothing
            ck = dc.ShardedCheckpointer(tmp, async_write=True, inflight=2)
            ck.save({"X": fleet}, {"iteration": 1})
            assert copies == [], \
                "async capture host-copied state at submit time"
            writer_gate.set()
            assert ck.drain() == []
            event = dc.restore_latest(tmp)
            assert np.array_equal(event["arrays"]["X"], np.asarray(fleet))
    finally:
        dc._copy_out = original
    # one copy per device shard, each exactly shard-sized — and nothing
    # anywhere near the global size (the all-gather signature)
    assert len(copies) == n_dev
    assert all(nb == global_nbytes // n_dev for nb in copies), copies
    assert max(copies) < global_nbytes


@needs_devices
def test_sharded_step_matches_unsharded_with_local_fft():
    """The shard_map fft routing must not change the numerics."""
    solver = build_sharded_step()
    for _ in range(5):
        solver.step(1e-3)
    X_sharded = np.asarray(solver.X)

    # rebuild unsharded (same builder, no mesh)
    ref, u, x, z = build_tau_ivp()
    for _ in range(5):
        ref.step(1e-3)
    assert np.allclose(X_sharded, np.asarray(ref.X), atol=1e-13)
