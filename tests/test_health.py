"""
Numerical-health monitor + flight recorder (tools/health.py): divergence
halt semantics, post-mortem directory contents and CLI round-trip,
tail-energy under-resolution warnings, the structured invalid-dt path,
and the zero-overhead disabled path.
"""

import json
import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

import dedalus_tpu.public as d3
from dedalus_tpu.tools.exceptions import SolverHealthError

REPO = pathlib.Path(__file__).parent.parent


def build_blowup_solver(tmp_path, N=16, **solver_kw):
    """dt(s) = s*s with s0 = 2 and dt = 1: superexponential doubling that
    overflows float64 within ~10 steps — a deterministic, cheap divergent
    IVP (explicit quadratic term, unstable at any dt)."""
    coords = d3.CartesianCoordinates("x")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=N, bounds=(0, 2 * np.pi))
    s = dist.Field(name="s", bases=xb)
    problem = d3.IVP([s], namespace={})
    problem.add_equation((d3.dt(s), s * s))
    kw = dict(health_cadence=1, postmortem_dir=str(tmp_path / "pm"),
              warmup_iterations=2)
    kw.update(solver_kw)
    solver = problem.build_solver(d3.SBDF1, **kw)
    s["g"] = 2.0
    return solver, s


def build_2d_solver(Nx=16, Nz=24, **solver_kw):
    """Static 2D field (dt(s) = 0) on Fourier x Chebyshev: a probe target
    whose spectrum the test controls exactly."""
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=Nx, bounds=(0, 2 * np.pi))
    zb = d3.ChebyshevT(coords["z"], size=Nz, bounds=(0, 1))
    s = dist.Field(name="s", bases=(xb, zb))
    problem = d3.IVP([s], namespace={})
    problem.add_equation((d3.dt(s), 0))
    solver = problem.build_solver(d3.SBDF1, **solver_kw)
    return solver, s, dist, xb, zb


def test_divergent_ivp_halts_with_flight_recorder(tmp_path):
    """A divergent run halts gracefully within one health cadence of the
    first non-finite value: proceed flips False, a structured error is
    available, and the post-mortem directory holds the ring buffer, the
    summary record, and the forensic checkpoint."""
    solver, s = build_blowup_solver(tmp_path)
    solver.health.max_abs_limit = float("inf")   # ride through to NaN/Inf
    steps = 0
    while solver.proceed and steps < 60:
        solver.step(1.0)
        steps += 1
    assert steps < 60, "divergent run never halted"
    err = solver.health_error
    assert isinstance(err, SolverHealthError)
    assert isinstance(err, ValueError)           # legacy catch compatibility
    # cadence 1: the halt lands exactly on the iteration whose probe first
    # saw a non-finite value
    assert err.iteration == solver.iteration
    assert "non-finite state" in err.reason
    assert err.record["fields"]["s"]["nan"] + err.record["fields"]["s"]["inf"] > 0
    # flight-recorder directory contents
    pm = pathlib.Path(err.postmortem_dir)
    assert pm.is_dir()
    record = json.loads((pm / "postmortem.json").read_text())
    assert record["kind"] == "health_postmortem"
    assert record["reason"] == err.reason
    assert record["iteration"] == err.iteration
    ring = [json.loads(ln) for ln
            in (pm / "health_ring.jsonl").read_text().splitlines()]
    assert ring and ring[-1]["iteration"] == err.iteration
    assert all(r["kind"] == "health_sample" for r in ring)
    # one-line results.jsonl-compatible record matches the summary and is
    # STRICT JSON — a NaN-filled state must not leak NaN/Infinity literals
    def reject_constant(name):
        raise AssertionError(f"non-strict JSON literal {name} in record")
    line = (pm / "record.jsonl").read_text().strip()
    assert json.loads(line, parse_constant=reject_constant)["reason"] \
        == err.reason
    for ring_line in (pm / "health_ring.jsonl").read_text().splitlines():
        json.loads(ring_line, parse_constant=reject_constant)
    # forensic checkpoint present, clearly named (never a "good" write)
    assert (pm / "state_at_failure.h5").exists()
    # the summary rides on metric flushes
    rec = solver.flush_metrics()
    assert rec["health"]["ok"] is False
    assert rec["health"]["reason"] == err.reason


def test_postmortem_cli_roundtrip(tmp_path):
    """The dumped directory round-trips through
    `python -m dedalus_tpu postmortem <dir>`."""
    solver, s = build_blowup_solver(tmp_path)
    while solver.proceed and solver.iteration < 60:
        solver.step(1.0)
    err = solver.health_error
    assert err is not None and err.postmortem_dir
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "dedalus_tpu", "postmortem",
         err.postmortem_dir],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr
    assert "Post-mortem:" in proc.stdout
    assert f"iteration={err.iteration}" in proc.stdout
    assert "ring buffer" in proc.stdout


def test_growth_bound_halts_before_nan(tmp_path):
    """The configurable growth bound trips while the state is still
    finite, so the post-mortem evidence is inspectable numbers."""
    solver, s = build_blowup_solver(tmp_path)
    solver.health.max_abs_limit = 1e6
    while solver.proceed and solver.iteration < 60:
        solver.step(1.0)
    err = solver.health_error
    assert err is not None
    assert "growth bound exceeded" in err.reason
    stats = err.record["fields"]["s"]
    assert stats["nan"] == 0 and stats["inf"] == 0
    assert stats["max_abs"] > 1e6


def test_no_output_written_after_failure(tmp_path):
    """Scheduled file handlers are skipped on the poisoned step: the last
    checkpoint written predates the failure (no NaN write as 'good')."""
    import h5py
    solver, s = build_blowup_solver(tmp_path)
    solver.health.max_abs_limit = float("inf")
    snaps = solver.evaluator.add_file_handler(tmp_path / "snaps", iter=1)
    snaps.add_task(s, name="s")
    while solver.proceed and solver.iteration < 60:
        solver.step(1.0)
    err = solver.health_error
    assert err is not None
    files = sorted((tmp_path / "snaps").glob("*.h5"))
    assert files
    with h5py.File(files[-1], "r") as f:
        iters = np.asarray(f["scales/iteration"])
        data = np.asarray(f["tasks/s"])
    # every scheduled write happened strictly before the failing iteration
    assert iters.max() < err.iteration
    assert np.all(np.isfinite(data))


def test_rb_divergent_halts(tmp_path):
    """The flagship configuration diverged on purpose (explicitly unstable
    dt): the RB IVP halts within one cadence of the first non-finite
    state, with a post-mortem on disk."""
    from dedalus_tpu.extras.bench_problems import build_rb_solver
    solver, b = build_rb_solver(32, 16, np.float32)
    solver.warmup_iterations = 2
    solver.health.cadence = 1        # property: re-arms the gate
    solver.health.max_abs_limit = float("inf")
    solver.health.postmortem_dir = str(tmp_path / "pm")
    steps = 0
    while solver.proceed and steps < 300:
        solver.step(100.0)   # far above any stable explicit dt
        steps += 1
    err = solver.health_error
    assert err is not None, "unstable RB run never halted"
    assert err.iteration == solver.iteration   # within one cadence (=1)
    assert pathlib.Path(err.postmortem_dir).is_dir()
    bad = [name for name, st in err.record["fields"].items()
           if st["nan"] or st["inf"]]
    assert bad, "halt record carries no non-finite field"


def test_tail_energy_warning_and_quiet(caplog):
    """A flat (under-resolved) spectrum warns once per field/axis; a
    smooth resolved field stays quiet."""
    import logging
    solver, s, dist, xb, zb = build_2d_solver()
    s["c"] = np.ones_like(np.asarray(s["c"]))
    solver.X = solver.gather_fields()
    with caplog.at_level(logging.WARNING, logger="dedalus_tpu"):
        rec = solver.health.check()
    assert rec["fields"]["s"]["tail_frac"]["z"] > 0.25
    assert solver.health.warnings >= 2          # both x and z axes flat
    assert "under-resolution" in caplog.text
    assert "axis 'z'" in caplog.text
    warned = solver.health.warnings
    solver.health.check()                       # same state: no re-warn
    assert solver.health.warnings == warned
    # resolved field: compact spectrum -> no warning
    solver2, s2, dist2, xb2, zb2 = build_2d_solver()
    z = dist2.local_grids(xb2, zb2)[1]
    s2["g"] = np.exp(-((z - 0.5) ** 2) * 8.0) * np.ones((16, 1))
    solver2.X = solver2.gather_fields()
    rec2 = solver2.health.check()
    assert rec2["fields"]["s"]["tail_frac"]["z"] < 0.01
    assert solver2.health.warnings == 0


def test_tau_fields_exempt_from_tail_warning(tmp_path):
    """tau_* fields are spectrally broad by construction: no tail warning,
    but their stats still land in the record and NaN checks still apply."""
    import jax.numpy as jnp
    coords = d3.CartesianCoordinates("x")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 2 * np.pi))
    tau_s = dist.Field(name="tau_s", bases=xb)
    problem = d3.IVP([tau_s], namespace={})
    problem.add_equation((d3.dt(tau_s), 0))
    solver = problem.build_solver(d3.SBDF1,
                                  postmortem_dir=str(tmp_path / "pm"))
    tau_s["c"] = np.ones_like(np.asarray(tau_s["c"]))
    solver.X = solver.gather_fields()
    rec = solver.health.check()
    assert rec["fields"]["tau_s"]["tail_frac"]["x"] > 0.25
    assert solver.health.warnings == 0          # exempt from the warning
    X = np.asarray(solver.X).copy()
    X[0, 0] = np.nan
    solver.X = jnp.asarray(X)
    solver.health.check()
    assert solver.health_error is not None      # NaN check still applies


def test_zero_energy_field_never_warns():
    """Fields below the energy floor (e.g. a zero-initialized velocity)
    must not warn on round-off content."""
    solver, s, *_ = build_2d_solver()
    rec = solver.health.check()                 # s is all zeros
    assert rec["fields"]["s"]["l2"] == 0.0
    assert solver.health.warnings == 0


def test_probe_counts_nan_inf(tmp_path):
    """The fused probe reports exact NaN/Inf entry counts per field."""
    import jax.numpy as jnp
    solver, s, *_ = build_2d_solver(postmortem_dir=str(tmp_path / "pm"))
    X = np.asarray(solver.X).copy()
    X[0, 0] = np.nan
    X[0, 1] = np.inf
    X[1, 2] = -np.inf
    solver.X = jnp.asarray(X)
    rec = solver.health.check()
    assert rec["fields"]["s"]["nan"] == 1
    assert rec["fields"]["s"]["inf"] == 2
    assert solver.health_error is not None
    assert "non-finite state" in solver.health_error.reason


def test_ring_buffer_bounded(tmp_path):
    solver, s = build_blowup_solver(tmp_path)
    solver.health.ring = type(solver.health.ring)(maxlen=4)
    for _ in range(10):
        solver.health.check()
        if solver.health_error:
            break
    assert len(solver.health.ring) <= 4


def test_invalid_dt_routes_through_health(tmp_path):
    """A non-finite timestep (the CFL blow-up product) raises the same
    structured error and leaves a flight-recorder dump — but does NOT
    poison the solver: the state is still fine, so a legacy catch-and-
    retry guard keeps the run alive, and repeat offenses don't spray
    one dump per retry."""
    solver, s = build_blowup_solver(tmp_path)
    solver.step(0.01)
    with pytest.raises(SolverHealthError) as excinfo:
        solver.step(np.nan)
    err = excinfo.value
    assert "Invalid timestep" in str(err)
    assert f"iteration {solver.iteration}" in str(err)
    assert "sim_time" in str(err)
    assert err.postmortem_dir and pathlib.Path(err.postmortem_dir).is_dir()
    # catch-and-retry: the run continues (state untouched by the bad dt)
    assert solver.proceed
    assert solver.health_error is None
    solver.step(0.01)
    assert np.all(np.isfinite(np.asarray(solver.X)))
    # a second bad dt raises again but reuses the single forensic dump
    pm_parent = pathlib.Path(err.postmortem_dir).parent
    n_dumps = len(list(pm_parent.iterdir()))
    with pytest.raises(SolverHealthError):
        solver.step(np.nan)
    assert len(list(pm_parent.iterdir())) == n_dumps
    # legacy catch sites still work, even with health disabled
    with pytest.raises(ValueError):
        solver2, _ = build_blowup_solver(tmp_path, health=False)
        solver2.step_many(3, np.inf)


def test_cadence_setter_rearms_gate(tmp_path):
    """Assigning solver.health.cadence mid-run takes effect (the gate is
    rebuilt and re-anchored), instead of silently keeping the old one."""
    solver, s = build_blowup_solver(tmp_path, health_cadence=1000)
    for _ in range(3):
        solver.step(1e-3)
    checks0 = solver.health.checks
    solver.health.cadence = 2
    for _ in range(6):
        solver.step(1e-3)
    assert solver.health.checks >= checks0 + 2   # re-armed gate fired


def test_health_off_zero_overhead(tmp_path):
    """health=False: no probe is ever built or compiled, no records
    accumulate, and telemetry flushes carry no health key."""
    solver, s = build_blowup_solver(tmp_path, health=False)
    for _ in range(5):
        solver.step(0.01)
    monitor = solver.health
    assert monitor.enabled is False
    assert monitor._probe is None               # nothing compiled
    assert monitor.checks == 0
    assert len(monitor.ring) == 0
    assert monitor.summary() is None
    rec = solver.flush_metrics()
    assert rec is None or "health" not in rec


def test_checkpoint_restorable_after_growth_halt(tmp_path):
    """The forensic checkpoint of a growth-bound halt (finite state)
    loads back through solver.load_state."""
    solver, s = build_blowup_solver(tmp_path)
    solver.health.max_abs_limit = 1e6
    while solver.proceed and solver.iteration < 60:
        solver.step(1.0)
    err = solver.health_error
    ckpt = pathlib.Path(err.postmortem_dir) / "state_at_failure.h5"
    assert ckpt.exists()
    solver2, s2 = build_blowup_solver(tmp_path, health=False)
    write, dt = solver2.load_state(str(ckpt))
    assert solver2.iteration == err.iteration
    assert solver2.sim_time == pytest.approx(err.sim_time)
    assert np.all(np.isfinite(np.asarray(solver2.X)))


def test_health_summary_in_bench_style_flush(tmp_path):
    """Healthy runs flush ok=True summaries with check counts (the shape
    bench.py attaches to its official record)."""
    solver, s = build_blowup_solver(tmp_path)
    solver.stop_iteration = 4
    while solver.proceed:
        solver.step(1e-3)
    rec = solver.flush_metrics()
    health = rec["health"]
    assert health["ok"] is True
    assert health["checks"] >= 1
    assert "max_abs" in health
