"""
bench.py provenance-window plumbing: every results.jsonl probe
(`_recent_tpu_row`, `_recent_ensemble_row`, `_recent_serving_row`, and
the attach helpers behind them) shares ONE measurement window —
`[bench] STALE_WINDOW_SEC` through `_stale_window_sec()` and the single
`_recent_row` scan loop — so the staleness rules can never drift apart
helper by helper. Fast, pure-host tests (no JAX import, no benchmark
runs): bench.py is imported from the repo root and pointed at fixture
results files.
"""

import inspect
import json
import pathlib
import sys
import time

import pytest

REPO = pathlib.Path(__file__).parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402


@pytest.fixture
def results(tmp_path, monkeypatch):
    """Point bench.py's results.jsonl scan at a fixture file; returns a
    writer that appends rows."""
    (tmp_path / "benchmarks").mkdir()
    path = tmp_path / "benchmarks" / "results.jsonl"
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))

    def write(*rows):
        with open(path, "a") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
    return write


def test_stale_window_is_config_pinned():
    """The window comes from [bench] STALE_WINDOW_SEC — one knob, not a
    hardcoded constant per helper."""
    from dedalus_tpu.tools.config import config
    assert bench._stale_window_sec() == pytest.approx(
        float(config.get("bench", "STALE_WINDOW_SEC")))
    old = config.get("bench", "STALE_WINDOW_SEC")
    try:
        config.set("bench", "STALE_WINDOW_SEC", "60")
        assert bench._stale_window_sec() == 60.0
    finally:
        config.set("bench", "STALE_WINDOW_SEC", old)


def test_every_probe_defaults_to_the_shared_window():
    """Pinning: each probe helper takes max_age_sec=None (= the shared
    config window) — a helper growing its own hardcoded default breaks
    this."""
    for fn in (bench._recent_row, bench._recent_tpu_row,
               bench._recent_ensemble_row, bench._recent_serving_row):
        sig = inspect.signature(fn)
        assert "max_age_sec" in sig.parameters, fn.__name__
        assert sig.parameters["max_age_sec"].default is None, fn.__name__


def test_recent_row_window_semantics(results):
    now = time.time()
    fresh = {"config": "x", "ts": now - 10, "value": "fresh"}
    stale = {"config": "x", "ts": now - 30 * 86400.0, "value": "stale"}
    results(fresh, stale)
    pred = lambda row: row.get("config") == "x"  # noqa: E731
    # default window: the stale row (outside [bench] STALE_WINDOW_SEC)
    # is invisible even though it is the LATEST line in the file
    assert bench._recent_row(pred)["value"] == "fresh"
    # max_age_sec=0 disables the window (the stale-headline guard's
    # unfiltered probe): the latest matching line wins
    assert bench._recent_row(pred, max_age_sec=0)["value"] == "stale"
    # explicit narrow window drops both
    assert bench._recent_row(pred, max_age_sec=5) is None
    # rows without ts never match (no provenance, no reuse)
    results({"config": "y", "value": "no-ts"})
    assert bench._recent_row(lambda r: r.get("config") == "y",
                             max_age_sec=0) is None


def test_recent_row_missing_file_and_junk(results):
    assert bench._recent_row(lambda row: True) is None  # no file yet
    with open(pathlib.Path(bench.__file__).parent / "benchmarks"
              / "results.jsonl", "w") as f:
        f.write("not json\n")
    results({"config": "z", "ts": time.time()})
    assert bench._recent_row(
        lambda row: row.get("config") == "z") is not None


def test_probe_helpers_share_the_scan(results):
    """The typed probes route through _recent_row with their own
    predicates: in-window rows of the right shape are found, out-of-
    window twins are not."""
    now = time.time()
    results(
        {"config": "rb256x64", "backend": "tpu", "finite": True,
         "steps_per_sec": 5.0, "ts": now - 20},
        {"config": "diffusion64_ensemble", "sweep": [{"members": 64}],
         "speedup_n64": 30.0, "ts": now - 20},
        # a stale serving row: must be invisible under the default window
        {"config": "rb256x64_serving", "ttfs_speedup": 12.0,
         "bit_identical_cold_warm": True, "ts": now - 30 * 86400.0},
    )
    assert bench._recent_tpu_row()["steps_per_sec"] == 5.0
    assert bench._recent_ensemble_row(
        "diffusion64_ensemble")["speedup_n64"] == 30.0
    assert bench._recent_serving_row("rb256x64_serving") is None
    assert bench._recent_serving_row("rb256x64_serving",
                                     max_age_sec=0) is not None
