"""
bench.py provenance-window plumbing: every results.jsonl probe
(`_recent_tpu_row`, `_recent_ensemble_row`, `_recent_serving_row`, and
the attach helpers behind them) shares ONE measurement window —
`[bench] STALE_WINDOW_SEC` through `_stale_window_sec()` and the single
`_recent_row` scan loop — so the staleness rules can never drift apart
helper by helper. Fast, pure-host tests (no JAX import, no benchmark
runs): bench.py is imported from the repo root and pointed at fixture
results files.
"""

import inspect
import json
import pathlib
import sys
import time

import pytest

REPO = pathlib.Path(__file__).parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402


@pytest.fixture
def results(tmp_path, monkeypatch):
    """Point bench.py's results.jsonl scan at a fixture file; returns a
    writer that appends rows."""
    (tmp_path / "benchmarks").mkdir()
    path = tmp_path / "benchmarks" / "results.jsonl"
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))

    def write(*rows):
        with open(path, "a") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
    return write


def test_stale_window_is_config_pinned():
    """The window comes from [bench] STALE_WINDOW_SEC — one knob, not a
    hardcoded constant per helper."""
    from dedalus_tpu.tools.config import config
    assert bench._stale_window_sec() == pytest.approx(
        float(config.get("bench", "STALE_WINDOW_SEC")))
    old = config.get("bench", "STALE_WINDOW_SEC")
    try:
        config.set("bench", "STALE_WINDOW_SEC", "60")
        assert bench._stale_window_sec() == 60.0
    finally:
        config.set("bench", "STALE_WINDOW_SEC", old)


def test_every_probe_defaults_to_the_shared_window():
    """Pinning: each probe helper takes max_age_sec=None (= the shared
    config window) — a helper growing its own hardcoded default breaks
    this."""
    for fn in (bench._recent_row, bench._recent_tpu_row,
               bench._recent_ensemble_row, bench._recent_serving_row):
        sig = inspect.signature(fn)
        assert "max_age_sec" in sig.parameters, fn.__name__
        assert sig.parameters["max_age_sec"].default is None, fn.__name__


def test_recent_row_window_semantics(results):
    now = time.time()
    fresh = {"config": "x", "ts": now - 10, "value": "fresh"}
    stale = {"config": "x", "ts": now - 30 * 86400.0, "value": "stale"}
    results(fresh, stale)
    pred = lambda row: row.get("config") == "x"  # noqa: E731
    # default window: the stale row (outside [bench] STALE_WINDOW_SEC)
    # is invisible even though it is the LATEST line in the file
    assert bench._recent_row(pred)["value"] == "fresh"
    # max_age_sec=0 disables the window (the stale-headline guard's
    # unfiltered probe): the latest matching line wins
    assert bench._recent_row(pred, max_age_sec=0)["value"] == "stale"
    # explicit narrow window drops both
    assert bench._recent_row(pred, max_age_sec=5) is None
    # rows without ts never match (no provenance, no reuse)
    results({"config": "y", "value": "no-ts"})
    assert bench._recent_row(lambda r: r.get("config") == "y",
                             max_age_sec=0) is None


def test_recent_row_missing_file_and_junk(results):
    assert bench._recent_row(lambda row: True) is None  # no file yet
    with open(pathlib.Path(bench.__file__).parent / "benchmarks"
              / "results.jsonl", "w") as f:
        f.write("not json\n")
    results({"config": "z", "ts": time.time()})
    assert bench._recent_row(
        lambda row: row.get("config") == "z") is not None


def test_probe_helpers_share_the_scan(results):
    """The typed probes route through _recent_row with their own
    predicates: in-window rows of the right shape are found, out-of-
    window twins are not."""
    now = time.time()
    results(
        {"config": "rb256x64", "backend": "tpu", "finite": True,
         "steps_per_sec": 5.0, "ts": now - 20},
        {"config": "diffusion64_ensemble", "sweep": [{"members": 64}],
         "speedup_n64": 30.0, "ts": now - 20},
        # a stale serving row: must be invisible under the default window
        {"config": "rb256x64_serving", "ttfs_speedup": 12.0,
         "bit_identical_cold_warm": True, "ts": now - 30 * 86400.0},
    )
    assert bench._recent_tpu_row()["steps_per_sec"] == 5.0
    assert bench._recent_ensemble_row(
        "diffusion64_ensemble")["speedup_n64"] == 30.0
    assert bench._recent_serving_row("rb256x64_serving") is None
    assert bench._recent_serving_row("rb256x64_serving",
                                     max_age_sec=0) is not None


# ---------------------------------------------------- probe TTL cache

import __graft_entry__ as graft  # noqa: E402


@pytest.fixture
def probe_log(tmp_path):
    """A results.jsonl fixture path plus a writer; tests pass the path
    explicitly (results_path=...) so the real trajectory is untouched."""
    path = tmp_path / "results.jsonl"

    def write(*rows):
        with open(path, "a") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
    return path, write


@pytest.fixture
def no_live_probe(monkeypatch):
    """Fails the test if the cached path falls through to a live probe;
    the returned setter swaps in a canned live verdict instead."""
    def boom(env, timeouts=None, spacing=45):
        raise AssertionError("live probe ran despite a fresh cached row")
    monkeypatch.setattr(graft, "_probe_backend_retrying", boom)

    def allow(backend, info, platforms_after=None):
        def fake(env, timeouts=None, spacing=45):
            if platforms_after is not None:
                env["JAX_PLATFORMS"] = platforms_after
            return backend, info
        monkeypatch.setattr(graft, "_probe_backend_retrying", fake)
    return allow


def test_probe_cache_replays_ok_verdict(probe_log, no_live_probe):
    path, write = probe_log
    write({"kind": "probe", "config": "backend_probe", "ok": True,
           "backend": "tpu", "devices": 4, "info": None,
           "platforms": "tpu,cpu", "platforms_after": "tpu,cpu",
           "ts": time.time() - 60})
    env = {"JAX_PLATFORMS": "tpu,cpu"}
    backend, devices = graft._probe_backend_cached(env, results_path=path)
    assert (backend, devices) == ("tpu", 4)
    assert env["JAX_PLATFORMS"] == "tpu,cpu"
    # a cache replay appends nothing — only LIVE probes make history
    assert len(path.read_text().splitlines()) == 1


def test_probe_cache_replays_failure_and_platform_fallback(
        probe_log, no_live_probe):
    """A recorded failed probe that settled JAX_PLATFORMS onto the CPU
    fallback replays BOTH the verdict and the env mutation."""
    path, write = probe_log
    write({"kind": "probe", "config": "backend_probe", "ok": False,
           "backend": None, "devices": None,
           "info": "device probe timed out after 90s",
           "platforms": "tpu,cpu", "platforms_after": None,
           "ts": time.time() - 60})
    env = {"JAX_PLATFORMS": "tpu,cpu"}
    backend, info = graft._probe_backend_cached(env, results_path=path)
    assert backend is None
    assert "cached probe failure" in info and "timed out" in info
    assert "JAX_PLATFORMS" not in env        # replayed the fallback pop


def test_probe_cache_ttl_expiry_probes_live(probe_log, no_live_probe):
    path, write = probe_log
    write({"kind": "probe", "config": "backend_probe", "ok": True,
           "backend": "tpu", "devices": 4, "platforms": None,
           "platforms_after": None, "ts": time.time() - 3600})
    no_live_probe("cpu", 1)
    backend, devices = graft._probe_backend_cached(
        {}, cache_sec=900, results_path=path)
    assert (backend, devices) == ("cpu", 1)
    rows = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(rows) == 2                    # the live probe wrote history
    assert rows[-1]["ok"] is True and rows[-1]["backend"] == "cpu"
    assert rows[-1]["wall_sec"] >= 0
    assert "env" in rows[-1]                 # fingerprint-stamped


def test_probe_cache_platforms_mismatch_probes_live(
        probe_log, no_live_probe):
    """A verdict recorded for a different requested JAX_PLATFORMS never
    answers for this one."""
    path, write = probe_log
    write({"kind": "probe", "config": "backend_probe", "ok": True,
           "backend": "tpu", "devices": 4, "platforms": "tpu,cpu",
           "platforms_after": "tpu,cpu", "ts": time.time() - 10})
    no_live_probe("cpu", 1)
    backend, _ = graft._probe_backend_cached(
        {"JAX_PLATFORMS": "cpu"}, results_path=path)
    assert backend == "cpu"
    assert len(path.read_text().splitlines()) == 2


def test_probe_cache_zero_ttl_disables(probe_log, no_live_probe):
    path, write = probe_log
    write({"kind": "probe", "config": "backend_probe", "ok": True,
           "backend": "tpu", "devices": 4, "platforms": None,
           "platforms_after": None, "ts": time.time() - 1})
    no_live_probe("cpu", 1)
    backend, _ = graft._probe_backend_cached(
        {}, cache_sec=0, results_path=path)
    assert backend == "cpu"                  # fresh row ignored: TTL off


def test_probe_cache_ttl_is_config_pinned():
    from dedalus_tpu.tools.config import config
    assert graft._probe_cache_sec() == pytest.approx(
        float(config.get("bench", "PROBE_CACHE_SEC")))
    old = config.get("bench", "PROBE_CACHE_SEC")
    try:
        config.set("bench", "PROBE_CACHE_SEC", "60")
        assert graft._probe_cache_sec() == 60.0
    finally:
        config.set("bench", "PROBE_CACHE_SEC", old)


def test_append_result_stamps_env_fingerprint(tmp_path):
    """Every results.jsonl row grows the host/environment fingerprint —
    the provenance perfwatch needs to tell host drift from regressions."""
    path = tmp_path / "results.jsonl"
    graft._append_result({"config": "x", "value": 1.0}, path=path)
    row = json.loads(path.read_text().splitlines()[0])
    env = row["env"]
    assert env["env_version"] == 1
    assert env["python"] and env["host"]
    assert isinstance(env["cpu_count"], int)
    # an explicit env on the record is never overwritten
    graft._append_result({"config": "y", "env": {"canned": True}},
                         path=path)
    row2 = json.loads(path.read_text().splitlines()[1])
    assert row2["env"] == {"canned": True}
