"""
Plotting extras (reference: dedalus/extras/plot_tools.py and the
example plot scripts built on it): mesh construction, plane extraction,
the plot_bot family on Fields and HDF5 output files, and MultiFigure
layout arithmetic. Rendered against the Agg backend.
"""

import numpy as np
import pytest

matplotlib = pytest.importorskip("matplotlib")
matplotlib.use("Agg")

import dedalus_tpu.public as d3
from dedalus_tpu.extras import plot_tools as pt


def test_vertices_and_quad_mesh():
    g = np.array([0.0, 1.0, 3.0])
    v = pt.get_1d_vertices(g)
    assert np.allclose(v, [-0.5, 0.5, 2.0, 4.0])
    v = pt.get_1d_vertices(g, cut_edges=True)
    assert np.allclose(v, [0.0, 0.5, 2.0, 3.0])
    xm, ym = pt.quad_mesh(np.arange(3.0), np.arange(4.0))
    assert xm.shape == ym.shape == (5, 4)
    assert np.allclose(xm[0], [-0.5, 0.5, 1.5, 2.5])


def test_pad_limits():
    lims = pt.pad_limits(np.array([0.0, 1.0]), np.array([0.0, 2.0]),
                         xpad=0.1, ypad=0.0)
    assert np.allclose(lims, [-0.1, 1.1, 0.0, 2.0])


def _make_field():
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 2 * np.pi))
    zb = d3.ChebyshevT(coords["z"], size=12, bounds=(0, 1))
    u = dist.Field(name="u", bases=(xb, zb))
    x, z = dist.local_grids(xb, zb)
    u["g"] = np.sin(x) * z * (1 - z)
    return u


def test_field_wrapper_and_get_plane():
    u = _make_field()
    w = pt.FieldWrapper(u)
    assert w.shape == (16, 12)
    assert w.dims[0].label == "x"
    assert w.dims[1].label == "z"
    xm, ym, data = pt.get_plane(w, 0, 1, (slice(None), slice(None)))
    assert data.shape == (12, 16)   # arranged (y, x)
    assert xm.shape == (13, 17)


def test_plot_bot_2d_field(tmp_path):
    import matplotlib.pyplot as plt
    u = _make_field()
    paxes, caxes = pt.plot_bot_2d(u, even_scale=True, title="u")
    paxes.figure.savefig(tmp_path / "f.png", dpi=40)
    plt.close("all")


def test_plot_bot_3d_from_file(tmp_path):
    """End-to-end: file handler output -> plot_bot_3d renders a frame
    (the examples/plot_snapshots.py pipeline)."""
    import h5py
    import matplotlib.pyplot as plt
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 2 * np.pi))
    zb = d3.ChebyshevT(coords["z"], size=12, bounds=(0, 1))
    u = dist.Field(name="u", bases=(xb, zb))
    t1 = dist.Field(name="t1", bases=xb)
    t2 = dist.Field(name="t2", bases=xb)
    lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
    problem = d3.IVP([u, t1, t2], namespace=locals())
    problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = 0")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("u(z=1) = 0")
    solver = problem.build_solver(d3.SBDF2)
    x, z = dist.local_grids(xb, zb)
    u["g"] = np.sin(np.pi * z) * np.cos(x)
    snaps = solver.evaluator.add_file_handler(tmp_path / "snaps", iter=1)
    snaps.add_task(u, name="u")
    for _ in range(2):
        solver.step(1e-3)
    path = tmp_path / "snaps" / "snaps_s1.h5"
    with h5py.File(path, "r") as f:
        dset = f["tasks"]["u"]
        fig = plt.figure(figsize=(4, 3))
        axes = fig.add_subplot(1, 1, 1)
        pt.plot_bot_3d(dset, 0, 0, axes=axes, even_scale=True,
                       visible_axes=False)
        fig.savefig(tmp_path / "frame.png", dpi=40)
    plt.close("all")


def test_multifigure_layout(tmp_path):
    import matplotlib.pyplot as plt
    image = pt.Box(2.0, 2.0)
    pad = pt.Frame(0.2, 0.2, 0.2, 0.2)
    margin = pt.Frame(0.1, 0.1, 0.1, 0.1)
    mfig = pt.MultiFigure(2, 3, image, pad, margin, scale=1.0)
    ax = mfig.add_axes(0, 0, (0.1, 0.1, 0.8, 0.8))
    ax.plot([0, 1], [0, 1])
    ax2 = mfig.add_axes(1, 2, (0, 0, 1, 1))
    ax2.plot([0, 1], [1, 0])
    w, h = mfig.figure.get_size_inches()
    assert float(w).is_integer() and float(h).is_integer()
    mfig.figure.savefig(tmp_path / "mf.png", dpi=30)
    plt.close("all")
