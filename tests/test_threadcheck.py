"""
Thread-safety tier (tools/lint/threadcheck.py).

Two layers of proof, mirroring test_progcheck.py:

  * the REAL scan: the DTC rules over the actual threaded serving
    modules must report ZERO new findings against the checked-in
    threadcheck_baseline.json, and the static lock-order graph must be
    cycle-free — the tier-1 gate that keeps every future PR's lock
    discipline checked by default;
  * SEEDED regressions: each encoded bug class (an unguarded counter
    bump, a thread callable aliasing producer-held state through
    asarray, the PR-8 writer-lock-vs-watchdog opposite-order pair) is
    reproduced as a small fixture module and must produce its NAMED
    finding — a quiet scan is evidence the rules look, not that they
    cannot see.

The runtime lock-order sanitizer is covered both in isolation (edge
recording, held/waiting dumps, Condition compatibility, zero-overhead
off mode) and as the analyzer's own completeness check: a live
in-process service run with the sanitizer on must observe no
acquisition edge missing from the static graph (verify_runtime_edges).
"""

import json
import pathlib
import socket as socket_mod
import threading
import time

import numpy as np
import pytest

from dedalus_tpu.tools.lint import all_rules, run_lint
from dedalus_tpu.tools.lint.cli import main as lint_main
from dedalus_tpu.tools.lint.framework import RULES, make_baseline
from dedalus_tpu.tools.lint import threadcheck as tc
from dedalus_tpu.tools.lint.threadcheck import (
    DTC_RULE_IDS, LOCK_CATALOG, THREADCHECK_BASELINE, THREADED_MODULES,
    find_cycles, run_threads, static_lock_graph, verify_runtime_edges)

pytestmark = pytest.mark.threadcheck


def _fixture(tmp_path, relname, src):
    """Write a fixture module mirroring a threaded-module path (suffix
    match opts it into the DTC scopes, exactly like the DTL fixtures)
    and run the DTC rules over it."""
    path = tmp_path / relname
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(src)
    return run_lint([path], rules=[RULES[r] for r in DTC_RULE_IDS])


def _rules_fired(result):
    return sorted({f.rule for f in result.findings})


# --------------------------------------------------------- the real scan

def test_head_is_clean():
    """The acceptance gate: the threaded serving modules carry zero new
    lock-discipline findings, the checked-in baseline is empty (true
    positives get fixed, not grandfathered), and the static acquisition
    graph has no cycles."""
    report, findings = run_threads()
    summary = report["summary"]
    assert summary["new"] == 0, report["findings"]
    assert summary["stale"] == []
    assert summary["cycles"] == 0, report["graph"]["cycles"]
    assert len(report["modules"]) == len(THREADED_MODULES)
    data = json.loads(THREADCHECK_BASELINE.read_text())
    assert data["entries"] == []
    # per-rule timings cover every DTC rule plus the graph build
    assert set(report["timings"]["rules"]) \
        == set(DTC_RULE_IDS) | {"lock-graph"}


def test_static_graph_is_cycle_free_on_head():
    graph = static_lock_graph()
    assert graph["cycles"] == []
    # HEAD discipline: every `with lock:` block in the tiered modules is
    # tight (snapshots under one lock, cross-object stats outside it),
    # so the service acquisition graph has no edges at all — which is
    # what makes DECLARED_EDGES honest as the empty tuple
    assert graph["edges"] == {}
    assert tc.DECLARED_EDGES == ()


def test_rule_catalog_registers_dtc_rules():
    ids = [r.id for r in all_rules()]
    for rid in DTC_RULE_IDS:
        assert rid in ids
        rule = RULES[rid]
        assert rule.severity == "error"
        assert rule.title and rule.__doc__
    # the catalog itself is well-formed: unique lock ids, nonempty field
    # sets, every module inside the tier's scope
    lock_ids = [s.lock_id() for s in LOCK_CATALOG]
    assert len(lock_ids) == len(set(lock_ids))
    for spec in LOCK_CATALOG:
        assert spec.fields
        assert spec.module in THREADED_MODULES


# ----------------------------------------------------------------- DTC001

def test_dtc001_fires_on_unguarded_counter(tmp_path):
    """The admission-reservation drift class: a cataloged counter bumped
    outside its lock from a class the catalog names."""
    result = _fixture(tmp_path, "service/pool.py", """
import threading

class SolverPool:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0
        self._entries = {}

    def acquire(self, key):
        self.hits += 1          # unguarded: readers race this
        with self._lock:
            return self._entries.get(key)
""")
    assert _rules_fired(result) == ["DTC001"]
    (f,) = result.findings
    assert "guarded field `hits` mutated" in f.message
    assert "_lock" in f.message


def test_dtc001_clean_when_guarded_and_in_exempt_scopes(tmp_path):
    result = _fixture(tmp_path, "service/pool.py", """
import threading

class SolverPool:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0           # constructor binds before threads exist
        self._entries = {}

    def acquire(self, key):
        with self._lock:
            self.hits += 1
            return self._entries.get(key)

    def _pop_lru(self):
        self._entries.popitem()   # documented caller-holds-the-lock
""")
    assert result.findings == []


def test_dtc001_foreign_guard(tmp_path):
    """Cross-object accesses (batching reaching into the server's
    counters) check against FOREIGN_GUARDS."""
    bad = _fixture(tmp_path / "bad", "service/batching.py", """
def drive(svc):
    if svc._queued_runs == 0:
        return True
""")
    assert _rules_fired(bad) == ["DTC001"]
    assert "svc._counters_lock" in bad.findings[0].message
    good = _fixture(tmp_path / "good", "service/batching.py", """
def drive(svc):
    with svc._counters_lock:
        queued = svc._queued_runs
    return queued == 0
""")
    assert good.findings == []


def test_dtc001_writes_only_entries_allow_lockfree_reads(tmp_path):
    """metrics-style catalog entries guard WRITES only: the flush paths
    read lock-free by design (signal context must not block)."""
    src_read = """
import threading
_exit_solvers = []
_exit_lock = threading.Lock()

def flush_pending():
    for ref in list(_exit_solvers):    # lock-free read: by design
        ref()
"""
    assert _fixture(tmp_path / "r", "tools/metrics.py",
                    src_read).findings == []
    src_write = """
import threading
_exit_solvers = []
_exit_lock = threading.Lock()

def register_exit_flush(solver):
    _exit_solvers.append(solver)       # unguarded mutation
"""
    bad = _fixture(tmp_path / "w", "tools/metrics.py", src_write)
    assert _rules_fired(bad) == ["DTC001"]
    assert "guarded field `_exit_solvers` mutated" in bad.findings[0].message


def test_dtc001_condition_aliases_acquire_the_same_lock(tmp_path):
    """The checkpointer's Conditions are constructed on _lock, so
    `with self._not_full:` guards the _lock catalog fields."""
    good = _fixture(tmp_path / "g", "tools/dcheckpoint.py", """
import threading

class ShardedCheckpointer:
    def __init__(self):
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._pending = []

    def save(self, item):
        with self._not_full:
            self._pending.append(item)
""")
    assert good.findings == []
    bad = _fixture(tmp_path / "b", "tools/dcheckpoint.py", """
import threading

class ShardedCheckpointer:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = []

    def save(self, item):
        self._pending.append(item)
""")
    assert _rules_fired(bad) == ["DTC001"]


def test_dtc001_suppression_comment(tmp_path):
    result = _fixture(tmp_path, "service/pool.py", """
import threading

class SolverPool:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0

    def acquire(self):
        self.hits += 1  # dedalus-lint: disable=DTC001
""")
    assert result.findings == []
    assert len(result.suppressed) == 1


# ----------------------------------------------------------------- DTC002

def test_dtc002_flags_non_disjoint_index_store(tmp_path):
    result = _fixture(tmp_path, "tools/chaos.py", """
import threading

results = []
cursor = 0

def worker(i):
    results[cursor] = i      # index not derived from own parameters

threading.Thread(target=worker, args=(0,)).start()
""")
    assert _rules_fired(result) == ["DTC002"]
    assert "disjoint-index contract" in result.findings[0].message


def test_dtc002_disjoint_slot_pattern_is_clean(tmp_path):
    """The chaos storm-driver pattern: each worker stores only into the
    slot its own parameter names."""
    result = _fixture(tmp_path, "tools/chaos.py", """
import threading

results = [None] * 8

def worker(i):
    out = i * 2
    results[i] = out

threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
""")
    assert result.findings == []


def test_dtc002_flags_asarray_aliased_buffer(tmp_path):
    """The PR-11 host-mirror class: asarray is zero-copy, so a thread
    storing into the alias rewrites value operands of dispatches still
    queued on the async stream — flagged regardless of index shape."""
    result = _fixture(tmp_path, "tools/chaos.py", """
import threading
import numpy as np

state = [1.0, 2.0]
mirror = np.asarray(state)

def worker(i):
    mirror[i] = 0.0

threading.Thread(target=worker, args=(0,)).start()
""")
    assert _rules_fired(result) == ["DTC002"]
    assert "asarray" in result.findings[0].message


def test_dtc002_covers_submit_targets_and_owned_state(tmp_path):
    result = _fixture(tmp_path, "service/batching.py", """
table = {}
next_slot = 0

def job(key):
    local = {}
    local[key] = 1           # callable-owned: fine
    table[next_slot] = 1     # producer-held slot cursor: racy

def launch(pool):
    pool.submit(job, "a")
""")
    fired = result.findings
    assert len(fired) == 1 and fired[0].rule == "DTC002"
    assert "`table[...]`" in fired[0].message


# ----------------------------------------------------------------- DTC003

PR8_DEADLOCK_SRC = """
import threading

_writer_lock = threading.Lock()
_watchdog_lock = threading.Lock()

def send_result():
    with _writer_lock:          # executor: writer first, watchdog second
        with _watchdog_lock:
            pass

def watchdog_fire():
    with _watchdog_lock:        # watchdog: the opposite order
        with _writer_lock:
            pass
"""


def test_dtc003_fires_on_seeded_pr8_deadlock_pair(tmp_path):
    """The PR-8 buffered-writer-lock-vs-watchdog pair: two threads
    acquiring the same two locks in opposite orders."""
    result = _fixture(tmp_path, "service/server.py", PR8_DEADLOCK_SRC)
    assert _rules_fired(result) == ["DTC003"]
    (f,) = result.findings
    assert "lock-order cycle (potential deadlock)" in f.message
    assert "_writer_lock" in f.message and "_watchdog_lock" in f.message
    assert "acquisition sites" in f.message


FLEET_DEADLOCK_SRC = """
import threading

class ReplicaSupervisor:
    def __init__(self):
        self._lock = threading.Lock()
        self._prober_lock = threading.Lock()

    def restart(self):
        # restart path: replica table first, then the prober's verdict
        # state
        with self._lock:
            with self._prober_lock:
                pass

    def probe_tick(self):
        # prober: verdict state first, then reaching back into the table
        with self._prober_lock:
            with self._lock:
                pass
"""


def test_dtc003_fires_on_seeded_fleet_prober_pair(tmp_path):
    """The supervisor-lock-vs-health-prober ordering hazard the fleet's
    tight-block discipline exists to prevent: the prober folding
    verdicts while holding its own lock and reaching back into the
    replica table, against a restart path nesting the other way. On
    HEAD both paths snapshot under ONE lock and do IO outside it, so
    the real fleet.py contributes zero edges (see
    test_static_graph_is_cycle_free_on_head); this fixture pins that
    the analyzer would catch the regression."""
    result = _fixture(tmp_path, "service/fleet.py", FLEET_DEADLOCK_SRC)
    assert _rules_fired(result) == ["DTC003"]
    (f,) = result.findings
    assert "lock-order cycle (potential deadlock)" in f.message
    assert "_lock" in f.message and "_prober_lock" in f.message


def test_dtc003_consistent_order_is_clean(tmp_path):
    result = _fixture(tmp_path, "service/server.py", """
import threading

_writer_lock = threading.Lock()
_watchdog_lock = threading.Lock()

def send_result():
    with _writer_lock, _watchdog_lock:
        pass

def watchdog_fire():
    with _writer_lock:
        with _watchdog_lock:
            pass
""")
    assert result.findings == []


def test_find_cycles():
    assert find_cycles({("A", "B"), ("B", "C")}) == []
    assert find_cycles({("A", "B"), ("B", "A")}) == [["A", "B"]]
    assert find_cycles({("A", "A")}) == [["A"]]
    # two disjoint cycles both surface
    cycles = find_cycles({("A", "B"), ("B", "A"), ("C", "D"), ("D", "C")})
    assert sorted(map(tuple, cycles)) == [("A", "B"), ("C", "D")]


def test_static_graph_sees_fixture_edges_and_cycles(tmp_path):
    path = tmp_path / "service" / "server.py"
    path.parent.mkdir(parents=True)
    path.write_text(PR8_DEADLOCK_SRC)
    graph = static_lock_graph([tmp_path])
    assert len(graph["edges"]) == 2
    assert len(graph["cycles"]) == 1
    for sites in graph["edges"].values():
        assert all("server.py" in s for s in sites)


# ------------------------------------------------------- tier runner + CLI

def test_run_threads_rejects_unknown_rule():
    with pytest.raises(KeyError):
        run_threads(rule_ids=["DTC999"])
    with pytest.raises(KeyError):
        run_threads(rule_ids=["DTL001"])   # wrong tier


def test_run_threads_baseline_roundtrip(tmp_path):
    """Fixture findings grandfather into a scoped baseline and stop
    counting as new — the shared Finding/baseline machinery."""
    fixture_dir = tmp_path / "fix"
    path = fixture_dir / "service" / "server.py"
    path.parent.mkdir(parents=True)
    path.write_text(PR8_DEADLOCK_SRC)
    report, findings = run_threads(paths=[fixture_dir], no_baseline=True)
    assert report["summary"]["new"] == 1
    baseline_path = tmp_path / "scoped_baseline.json"
    baseline_path.write_text(
        json.dumps(make_baseline(findings), indent=1) + "\n")
    report2, _ = run_threads(paths=[fixture_dir],
                             baseline_path=baseline_path)
    assert report2["summary"]["new"] == 0
    assert report2["summary"]["baselined"] == 1


def test_serial_and_parallel_scans_agree():
    """--jobs covers the DTC tier: forked per-file workers resolve the
    registered DTC rules and return the same findings as the serial
    scan (compared pre-baseline, by key)."""
    from dedalus_tpu.tools.lint.framework import PACKAGE_DIR
    files = [PACKAGE_DIR / m for m in THREADED_MODULES]
    rules = [RULES[r] for r in DTC_RULE_IDS]
    serial = run_lint(files, rules=rules, jobs=1)
    parallel = run_lint(files, rules=rules, jobs=2)
    assert sorted(f.key() for f in serial.findings) \
        == sorted(f.key() for f in parallel.findings)


def test_cli_threads_clean_on_head(capsys):
    assert lint_main(["--threads"]) == 0
    out = capsys.readouterr().out
    assert "lock-order edge(s)" in out
    assert "rule timings" in out
    for rid in DTC_RULE_IDS:
        assert rid in out


def test_cli_threads_exits_nonzero_on_new_finding(tmp_path, capsys):
    path = tmp_path / "service" / "server.py"
    path.parent.mkdir(parents=True)
    path.write_text(PR8_DEADLOCK_SRC)
    assert lint_main(["--threads", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "DTC003" in out and "lock-order cycle" in out


def test_cli_threads_json_and_select(capsys):
    assert lint_main(["--threads", "--select", "DTC003",
                      "--format", "json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert set(report["timings"]["rules"]) == {"DTC003", "lock-graph"}
    assert report["summary"]["new"] == 0
    assert report["graph"]["cycles"] == []


def test_cli_threads_usage_errors(capsys, tmp_path):
    # unknown DTC rule id
    assert lint_main(["--threads", "--select", "DTC999"]) == 2
    # refuses to regenerate the package-tier baseline from a subset
    assert lint_main(["--threads", "--update-baseline",
                      "--select", "DTC001"]) == 2
    # the tiers do not combine
    assert lint_main(["--threads", "--programs"]) == 2
    # a typo'd path must not report a clean scan
    assert lint_main(["--threads", str(tmp_path / "nope")]) == 2
    err = capsys.readouterr().err
    assert "refusing to regenerate" in err


def test_cli_threads_scoped_baseline_update(tmp_path, capsys):
    """--update-baseline with an explicit --baseline FILE grandfathers a
    scoped scan; the follow-up scan against it is clean."""
    fixture_dir = tmp_path / "fix"
    path = fixture_dir / "service" / "server.py"
    path.parent.mkdir(parents=True)
    path.write_text(PR8_DEADLOCK_SRC)
    scoped = tmp_path / "scoped.json"
    assert lint_main(["--threads", str(fixture_dir),
                      "--update-baseline", "--baseline",
                      str(scoped)]) == 0
    assert scoped.exists()
    assert lint_main(["--threads", str(fixture_dir),
                      "--baseline", str(scoped)]) == 0
    capsys.readouterr()


# ------------------------------------------------------ runtime sanitizer

@pytest.fixture
def sanitizer():
    """Enable the lock-order sanitizer for the test and restore the
    off-by-default state (and empty tables) afterwards."""
    tc.reset_observed()
    tc.enable_lock_order()
    try:
        yield tc
    finally:
        tc.disable_lock_order()
        tc.reset_observed()


def test_named_lock_off_is_plain_lock():
    """Zero overhead off: a plain threading.Lock, nothing recorded,
    empty dumps."""
    assert not tc.lock_order_enabled()
    lock = tc.named_lock("test:off")
    assert isinstance(lock, type(threading.Lock()))
    with lock:
        assert tc.held_locks_dump() == {}


def test_sanitizer_records_edges_and_held_stack(sanitizer):
    a = tc.named_lock("test:A")
    b = tc.named_lock("test:B")
    with a:
        with b:
            dump = tc.held_locks_dump()
            me = threading.current_thread().name
            assert dump[me]["held"] == ["test:A", "test:B"]
            assert dump[me]["waiting"] is None
    assert ("test:A", "test:B") in tc.observed_edges()
    assert ("test:B", "test:A") not in tc.observed_edges()
    assert tc.held_locks_dump() == {}     # everything released
    tc.reset_observed()
    assert tc.observed_edges() == set()


def test_sanitizer_reports_waiting_thread(sanitizer):
    """A thread blocked on a held lock shows up as waiting — the
    watchdog-postmortem payload for a live deadlock."""
    lock = tc.named_lock("test:contended")
    entered = threading.Event()
    release = threading.Event()

    def holder():
        with lock:
            entered.set()
            release.wait(5.0)

    t = threading.Thread(target=holder, name="holder-thread")
    t.start()
    try:
        assert entered.wait(5.0)
        waiter_seen = []

        def waiter():
            got = lock.acquire(True, 2.0)
            if got:
                lock.release()

        w = threading.Thread(target=waiter, name="waiter-thread")
        w.start()
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            dump = tc.held_locks_dump()
            if dump.get("waiter-thread", {}).get("waiting") \
                    == "test:contended":
                waiter_seen.append(dump)
                break
            time.sleep(0.01)
        assert waiter_seen, "waiting state never surfaced in the dump"
        assert waiter_seen[0]["holder-thread"]["held"] \
            == ["test:contended"]
    finally:
        release.set()
        t.join(5.0)
        w.join(5.0)


def test_sanitized_lock_is_condition_compatible(sanitizer):
    """threading.Condition built on a sanitized lock works end to end
    (the checkpointer's _not_full/_drained pattern)."""
    cond = threading.Condition(tc.named_lock("test:cond"))
    ready = []

    def producer():
        with cond:
            ready.append(1)
            cond.notify()

    t = threading.Thread(target=producer)
    with cond:
        t.start()
        assert cond.wait_for(lambda: ready, timeout=5.0)
    t.join(5.0)
    # non-blocking acquire also round-trips (Condition uses it)
    lock = tc.named_lock("test:nb")
    assert lock.acquire(False)
    lock.release()


def test_verify_runtime_edges_flags_unknown_edge(sanitizer):
    static = {"edges": {("test:A", "test:B"): ["x.py:1"]}, "cycles": []}
    assert verify_runtime_edges({("test:A", "test:B")}, static) == []
    assert verify_runtime_edges({("test:B", "test:A")}, static) \
        == [("test:B", "test:A")]


# ------------------------------------- static-vs-runtime cross-validation

DIFF48 = {"problem": "diffusion", "params": {"size": 48}}


def test_live_service_observes_no_edge_missing_from_static_graph(
        sanitizer, tmp_path):
    """The analyzer's completeness check, live: a full in-process service
    run (request admission, pool build, executor solve, stats snapshots
    from a reader, the async checkpointer) with every service lock
    sanitized must observe no acquisition edge the static graph lacks —
    on HEAD, no nested acquisition at all."""
    from dedalus_tpu.service import protocol
    from dedalus_tpu.service.server import SolverService
    from dedalus_tpu.tools import dcheckpoint as dc

    svc = SolverService(port=0, pool_size=1)
    run_header = {"kind": "run", "spec": DIFF48, "dt": 1e-3,
                  "stop_iteration": 3}
    a, b = socket_mod.socketpair()
    with a:
        svc._queue.put({"conn": b, "wfile": b.makefile("wb"),
                        "header": run_header, "payload": None,
                        "t_accept": time.perf_counter(),
                        "deadline_mono": None, "probe": False})
        with svc._counters_lock:
            svc._queued_runs += 1
        svc._queue.put(None)               # stop sentinel
        svc._worker()                      # build + solve, in-process
        rfile = a.makefile("rb")
        header, _ = protocol.recv_frame(rfile)
        while header["kind"] not in ("result", "error"):
            header, _ = protocol.recv_frame(rfile)
    assert header["kind"] == "result", header
    # reader-thread surfaces: stats + retry-after math
    a2, b2 = socket_mod.socketpair()
    with a2:
        protocol.send_frame(a2.makefile("wb"), {"kind": "stats"})
        svc._receive(b2, time.perf_counter())
        stats_header, _ = protocol.recv_frame(a2.makefile("rb"))
    assert stats_header["kind"] == "stats"
    assert stats_header["pool"]["misses"] == 1
    # the async sharded-checkpoint writer (Conditions on the same lock)
    ck = dc.ShardedCheckpointer(tmp_path / "ck", async_write=True,
                                inflight=2)
    ck.save({"X": np.arange(8.0)}, {"iteration": 1})
    assert ck.drain() == []
    # the acceptance criterion: every observed acquisition order is
    # statically visible (lexical + DECLARED_EDGES)
    missing = verify_runtime_edges()
    assert missing == [], (
        f"live acquisition edges missing from the static lock graph: "
        f"{missing} — add the establishing call path to DECLARED_EDGES "
        "or restructure the nesting")
    # and the run genuinely went through sanitized locks (enable came
    # before construction), so the empty edge set means "no nesting",
    # not "nothing instrumented"
    assert isinstance(svc._counters_lock, tc._SanitizedLock)
    assert isinstance(svc.pool._lock, tc._SanitizedLock)
    assert isinstance(ck._lock, tc._SanitizedLock)
