"""
LBVP tests vs analytic solutions (reference: dedalus/tests/test_lbvp.py).
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3


def test_poisson_1d():
    """lap(u) = 6z, u(0)=0, u(1)=1 -> u = z^3."""
    zc = d3.Coordinate("z")
    dist = d3.Distributor(zc, dtype=np.float64)
    zb = d3.ChebyshevT(zc, size=16, bounds=(0, 1))
    z = dist.local_grid(zb)
    u = dist.Field(name="u", bases=zb)
    t1 = dist.Field(name="t1")
    t2 = dist.Field(name="t2")
    rhs = dist.Field(name="rhs", bases=zb)
    rhs["g"] = 6 * z.ravel()
    lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
    problem = d3.LBVP([u, t1, t2], namespace=locals())
    problem.add_equation("lap(u) + lift(t1,-1) + lift(t2,-2) = rhs")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("u(z=1) = 1")
    solver = problem.build_solver()
    solver.solve()
    assert np.allclose(u["g"], z.ravel() ** 3)


def test_poisson_2d():
    """2D Poisson with Fourier x Chebyshev and x-dependent RHS."""
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 2 * np.pi))
    zb = d3.ChebyshevT(coords["z"], size=16, bounds=(0, 1))
    x, z = dist.local_grids(xb, zb)
    u = dist.Field(name="u", bases=(xb, zb))
    t1 = dist.Field(name="t1", bases=xb)
    t2 = dist.Field(name="t2", bases=xb)
    rhs = dist.Field(name="rhs", bases=(xb, zb))
    # exact solution u = sin(x) sinh(z)/sinh(1): lap(u) = 0... use forced:
    # u = sin(x) z(1-z): lap u = -sin(x) z(1-z) - 2 sin(x)
    rhs["g"] = -np.sin(x) * z * (1 - z) - 2 * np.sin(x)
    lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
    problem = d3.LBVP([u, t1, t2], namespace=locals())
    problem.add_equation("lap(u) + lift(t1,-1) + lift(t2,-2) = rhs")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("u(z=1) = 0")
    solver = problem.build_solver()
    solver.solve()
    assert np.allclose(u["g"], np.sin(x) * z * (1 - z), atol=1e-12)


def test_ncc_variable_coefficient():
    """z*dz(u) + u = 3z^2, u(0)=0 -> u = z^2 (NCC on derivative operand)."""
    zc = d3.Coordinate("z")
    dist = d3.Distributor(zc, dtype=np.float64)
    zb = d3.ChebyshevT(zc, size=16, bounds=(0, 1))
    z = dist.local_grid(zb)
    u = dist.Field(name="u", bases=zb)
    tau = dist.Field(name="tau")
    zf = dist.Field(name="zf", bases=zb)
    zf["g"] = z.ravel()
    rhs = dist.Field(name="rhs", bases=zb)
    rhs["g"] = 3 * z.ravel() ** 2
    dz = lambda A: d3.Differentiate(A, zc)
    lift = lambda A: d3.Lift(A, zb.derivative_basis(1), -1)
    problem = d3.LBVP([u, tau], namespace=locals())
    problem.add_equation("zf*dz(u) + u + lift(tau) = rhs")
    problem.add_equation("u(z=0) = 0")
    solver = problem.build_solver()
    solver.solve()
    assert np.allclose(u["g"], z.ravel() ** 2)


def test_vector_lbvp():
    """Vector Poisson: lap(u_i) with Dirichlet BCs per component."""
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=8, bounds=(0, 2 * np.pi))
    zb = d3.ChebyshevT(coords["z"], size=16, bounds=(0, 1))
    x, z = dist.local_grids(xb, zb)
    u = dist.VectorField(coords, name="u", bases=(xb, zb))
    t1 = dist.VectorField(coords, name="t1", bases=xb)
    t2 = dist.VectorField(coords, name="t2", bases=xb)
    rhs = dist.VectorField(coords, name="rhs", bases=(xb, zb))
    rg = np.zeros((2, 8, 16))
    rg[0] = -np.sin(x) * z * (1 - z) - 2 * np.sin(x)
    rg[1] = 6 * z * np.ones_like(x)
    rhs["g"] = rg
    lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
    top = dist.VectorField(coords, name="top")
    top["g"] = np.array([0.0, 1.0]).reshape(2, 1, 1)
    problem = d3.LBVP([u, t1, t2], namespace=locals())
    problem.add_equation("lap(u) + lift(t1,-1) + lift(t2,-2) = rhs")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation((d3.Interpolate(u, coords["z"], 1.0), top))
    solver = problem.build_solver()
    solver.solve()
    exact0 = np.sin(x) * z * (1 - z)
    exact1 = z ** 3 * np.ones_like(x)
    ug = u["g"]
    assert np.allclose(ug[0], exact0, atol=1e-12)
    assert np.allclose(ug[1], exact1, atol=1e-12)


def test_per_group_equation_conditions():
    """Complementary conditioned BCs (reference: core/problems.py:67
    condition kwarg; core/subsystems.py:527-541): Dirichlet bottom at
    nx == 0, Neumann bottom elsewhere. Laplace solution is exactly 1 - z."""
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=8, bounds=(0, 2*np.pi))
    zb = d3.ChebyshevT(coords["z"], size=16, bounds=(0, 1))
    u = dist.Field(name="u", bases=(xb, zb))
    tau1 = dist.Field(name="tau1", bases=xb)
    tau2 = dist.Field(name="tau2", bases=xb)
    lift = lambda A, n: d3.Lift(A, zb.derivative_basis(1), n)
    dz = lambda A: d3.Differentiate(A, coords["z"])
    problem = d3.LBVP([u, tau1, tau2], namespace=locals())
    problem.add_equation("lap(u) + lift(tau1,-1) + lift(tau2,-2) = 0")
    problem.add_equation("u(z=1) = 0")
    problem.add_equation("u(z=0) = 1", condition="nx == 0")
    problem.add_equation("dz(u)(z=0) = 0", condition="nx != 0")
    solver = problem.build_solver()
    solver.solve()
    x, z = dist.local_grids(xb, zb)
    assert np.abs(np.asarray(u["g"]) - (1 - z)).max() < 1e-12


def test_independent_conditioned_pairs():
    """Two independent complementary conditioned BC pairs (one per
    boundary) must pack into separate row blocks."""
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=8, bounds=(0, 2*np.pi))
    zb = d3.ChebyshevT(coords["z"], size=16, bounds=(0, 1))
    u = dist.Field(name="u", bases=(xb, zb))
    tau1 = dist.Field(name="tau1", bases=xb)
    tau2 = dist.Field(name="tau2", bases=xb)
    lift = lambda A, n: d3.Lift(A, zb.derivative_basis(1), n)
    dz = lambda A: d3.Differentiate(A, coords["z"])
    problem = d3.LBVP([u, tau1, tau2], namespace=locals())
    problem.add_equation("lap(u) + lift(tau1,-1) + lift(tau2,-2) = 0")
    problem.add_equation("u(z=1) = 2", condition="nx == 0")
    problem.add_equation("dz(u)(z=1) = 0", condition="nx != 0")
    problem.add_equation("u(z=0) = 1", condition="nx == 0")
    problem.add_equation("dz(u)(z=0) = 0", condition="nx != 0")
    solver = problem.build_solver()
    solver.solve()
    x, z = dist.local_grids(xb, zb)
    assert np.abs(np.asarray(u["g"]) - (1 + z)).max() < 1e-12
