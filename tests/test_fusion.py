"""
Fused spectral step (core/fusedstep.py + libraries/pencilops fused
paths): fused-vs-unfused equivalence across schemes (SBDF2 + RK222),
problems (diffusion + Rayleigh-Benard) and pencil paths (dense +
banded); composition under EnsembleSolver vmap and DifferentiableIVP
adjoints; donation safety against the snapshot-rewind machinery; the
Pallas substitution kernel in interpret mode; assembly-cache fusion-key
invalidation; and the fused phase row in the metrics vocabulary.

Tolerance contract under test (documented in docs/performance.md and
the [fusion] config): FUSED_MATVEC and the dense-path fused layers are
BITWISE identical to the legacy step; the precomposed banded
substitution (FUSED_SOLVE) moves solutions at the eps*cond(block) level
and the refinement polish keeps trajectories within ~1e-12 relative of
the backward-stable sweeps (measured 7e-16 on the rb256x64 headline,
benchmarks/fusion.py rows).
"""

import pathlib
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import dedalus_tpu.public as d3
from dedalus_tpu.core import fusedstep
from dedalus_tpu.tools import retrace as retrace_mod
from dedalus_tpu.tools.config import config
from dedalus_tpu.tools.metrics import Metrics, SUM_PHASES, \
    format_phase_table

sys.path.insert(0, str(pathlib.Path(__file__).parent))
from test_banded import build_rb  # noqa: E402

pytestmark = pytest.mark.fusion

FUSION_KEYS = ("FUSED_SOLVE", "FUSED_MATVEC", "FUSED_TRANSFORMS",
               "DONATE_STEP", "PALLAS")


@pytest.fixture
def fusion_cfg():
    """Mutate the [fusion] section inside a test, restored afterwards."""
    if not config.has_section("fusion"):
        config.add_section("fusion")
    saved = {k: config["fusion"].get(k) for k in FUSION_KEYS}

    def set_flags(**kw):
        for key in FUSION_KEYS:
            config["fusion"][key] = kw.get(key.lower(), "auto"
                                           if key != "PALLAS" else "off")

    yield set_flags
    for key, val in saved.items():
        if val is None:
            config["fusion"].pop(key, None)
        else:
            config["fusion"][key] = val


def build_diffusion(scheme, size=64):
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=size, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    a = dist.Field(name="a", bases=xb)
    dx = lambda A: d3.Differentiate(A, xc)  # noqa: E731
    problem = d3.IVP([u], namespace={"u": u, "a": a, "lap": d3.lap,
                                     "dx": dx})
    problem.add_equation("dt(u) - lap(u) = a*u - u*dx(u)")
    x = dist.local_grid(xb)
    u["g"] = np.sin(3 * x) + 0.2 * np.cos(x)
    a["g"] = 0.1 * np.cos(x)
    return problem.build_solver(scheme, warmup_iterations=2,
                                enforce_real_cadence=0)


def rb_states(n, scheme, fusion_flags, set_flags, **build_kw):
    set_flags(**fusion_flags)
    solver = build_rb(8, 32, matsolver="banded", timestepper=scheme,
                      **build_kw)
    for _ in range(n):
        solver.step(0.01)
    return np.asarray(solver.X), solver


# ------------------------------------------------- fused vs unfused step

@pytest.mark.parametrize("scheme", [d3.RK222, d3.SBDF2])
def test_fused_vs_unfused_banded_rb(scheme, fusion_cfg):
    """Banded path (RB): the precomposed substitution + pair matvec +
    donation trajectory tracks the legacy step within the documented
    tolerance class (refinement-polished eps*cond; ~1e-15 observed)."""
    off = {k.lower(): "off" for k in FUSION_KEYS}
    x_off, _ = rb_states(10, scheme, off, fusion_cfg)
    x_on, solver = rb_states(10, scheme, {}, fusion_cfg)
    assert solver.ops._fused_solve
    aux = solver.timestepper._lhs_aux
    aux0 = aux[0] if isinstance(aux, list) else aux
    assert "fsub" in aux0 and "FwdOp" in aux0["fsub"]
    assert np.isfinite(x_on).all()
    scale = np.max(np.abs(x_off))
    assert np.max(np.abs(x_on - x_off)) <= 1e-12 * scale


@pytest.mark.parametrize("scheme", [d3.SBDF2, d3.RK222])
def test_fused_vs_unfused_dense_bitwise(scheme, fusion_cfg):
    """Dense path (diffusion): the fused layers that apply (pair matvec,
    donation) are BITWISE identical to the legacy step."""
    fusion_cfg(**{k.lower(): "off" for k in FUSION_KEYS})
    s_off = build_diffusion(scheme)
    for _ in range(12):
        s_off.step(1e-3)
    fusion_cfg()
    s_on = build_diffusion(scheme)
    assert s_on.timestepper._fusion.matvec
    for _ in range(12):
        s_on.step(1e-3)
    assert np.array_equal(np.asarray(s_off.X), np.asarray(s_on.X))


def test_matvec_pair_bitwise(fusion_cfg):
    """BandedOps.matvec_pair == separate matvecs, bit for bit (shared
    permute/pad only; per-matrix trimmed loops unchanged)."""
    fusion_cfg()
    solver = build_rb(8, 32, matsolver="banded")
    ops, M, L = solver.ops, solver.M_mat, solver.L_mat
    X = jnp.asarray(np.random.default_rng(3).normal(
        size=solver.pencil_shape))
    MX, LX = ops.matvec_pair(M, L, X)
    assert np.array_equal(np.asarray(MX), np.asarray(ops.matvec(M, X)))
    assert np.array_equal(np.asarray(LX), np.asarray(ops.matvec(L, X)))


# --------------------------------------------------- composite transforms

def test_fused_transforms_composites_match(fusion_cfg):
    """FUSED_TRANSFORMS folds the RB grad/div chains into composite
    GEMMs (plan registers nodes) and the trajectory tracks the generic
    transform path."""
    off = {k.lower(): "off" for k in FUSION_KEYS}
    x_off, _ = rb_states(8, d3.RK222, off, fusion_cfg)
    x_on, solver = rb_states(8, d3.RK222,
                             {"fused_transforms": "on"}, fusion_cfg)
    plan = solver._fused_eval_plan
    assert plan is not None and len(plan) > 0
    scale = np.max(np.abs(x_off))
    assert np.max(np.abs(x_on - x_off)) <= 1e-12 * scale


def test_fused_composites_cached_and_invalidated(fusion_cfg, tmp_path,
                                                 monkeypatch):
    """Precomposed composites are cached payloads: the entry lands on
    disk under a fusion-keyed name, a corrupt entry falls back to fresh
    folds, and a fusion-flag flip changes the key so stale composites
    can never be served."""
    monkeypatch.setenv("DEDALUS_TPU_ASSEMBLY_CACHE", str(tmp_path))
    fusion_cfg(fused_transforms="on")
    solver = build_rb(8, 32, matsolver="banded")
    plan = solver._fused_eval_plan
    key = plan.cache_key(solver)
    assert key is not None
    entry = tmp_path / f"asm-{key}.npb"
    assert entry.exists()
    # warm rebuild installs the cached composites (bit-identical arrays)
    solver2 = build_rb(8, 32, matsolver="banded")
    plan2 = solver2._fused_eval_plan
    assert plan2.cache_key(solver2) == key
    for n1, n2 in zip(plan._walk_order, plan2._walk_order):
        for (e1, e2) in zip(plan.nodes[id(n1)], plan2.nodes[id(n2)]):
            assert np.array_equal(e1[3], e2[3])
    # corruption falls back to fresh assembly (entry quarantined+restored)
    entry.write_bytes(b"garbage")
    solver3 = build_rb(8, 32, matsolver="banded")
    assert solver3._fused_eval_plan is not None
    # flag flip -> different resolved token -> different key: a stale
    # composite can never be served under another composition
    tok_on = fusedstep.cache_token()
    fusion_cfg(fused_transforms="on", fused_solve="off")
    assert fusedstep.cache_token() != tok_on


def test_assembly_key_carries_fusion_token(fusion_cfg):
    """The main assembly-cache content key includes the resolved fusion
    composition: a flag flip re-keys the solver payloads too."""
    from dedalus_tpu.tools import assembly_cache
    fusion_cfg()
    s1 = build_rb(8, 32, matsolver="banded")
    k1 = assembly_cache.solver_key(s1, s1.matrices)
    fusion_cfg(fused_solve="off")
    s2 = build_rb(8, 32, matsolver="banded")
    k2 = assembly_cache.solver_key(s2, s2.matrices)
    assert k1 is not None and k2 is not None and k1 != k2


# ------------------------------------------------------ adjoint + ensemble

def test_adjoint_fd_through_fused_banded(fusion_cfg):
    """DifferentiableIVP gradients FD-validate through the fused banded
    solve (the custom_vjp funnel transposes the precomposed-GEMM
    substitution exactly like the legacy sweeps)."""
    fusion_cfg()
    solver = build_rb(8, 32, matsolver="banded", timestepper=d3.RK222)
    assert solver.ops._fused_solve
    div = solver.differentiable(wrt=("initial_state",),
                                loss=lambda X: jnp.sum(X ** 2))
    n, dt = 12, 0.01
    X0 = np.asarray(solver.gather_fields()).copy()
    _, grads = div.value_and_grad(n, dt, initial_state=X0)
    g = np.asarray(grads["initial_state"])
    assert np.isfinite(g).all()
    v = np.random.default_rng(0).standard_normal(X0.shape)
    eps = 1e-6
    fd = (div.value(n, dt, initial_state=X0 + eps * v)
          - div.value(n, dt, initial_state=X0 - eps * v)) / (2 * eps)
    an = float(np.sum(g * v))
    assert abs(fd - an) <= 1e-5 * max(abs(fd), 1e-12)


def test_ensemble_vmap_composes_with_fused_solve(fusion_cfg):
    """EnsembleSolver vmaps the raw step bodies over the fused ops
    (including the vmapped precomposed-inverse factorization): fleet
    members bit-match their serial runs with fusion on."""
    fusion_cfg()
    seeds = [11, 12, 13]

    def build():
        return build_rb(8, 32, matsolver="banded", timestepper=d3.RK222)

    serial = []
    for seed in seeds:
        solver = build()
        solver.problem.variables[1].fill_random(
            "g", seed=seed, distribution="normal", scale=1e-3)
        solver.step_many(6, 0.01)
        serial.append(np.asarray(solver.X))
    solver = build()
    assert solver.ops._fused_solve
    ens = solver.ensemble(len(seeds), mesh=None)

    def member_init(i):
        solver.problem.variables[1].fill_random(
            "g", seed=seeds[i], distribution="normal", scale=1e-3)

    ens.init_members(member_init)
    ens.step_many(6, 0.01)
    for i in range(len(seeds)):
        err = np.max(np.abs(np.asarray(ens.X[i]) - serial[i]))
        assert err <= 1e-12, (i, err)


# ------------------------------------------------------- donation safety

def test_donation_snapshot_rewind_bitwise(fusion_cfg):
    """The donating multistep step program composes with the snapshot
    ring: capture -> step -> rewind -> re-step reproduces the original
    trajectory bitwise, twice from the SAME snapshot (the ring owns
    copies, so donation can never consume its slots)."""
    from dedalus_tpu.tools.resilience import (capture_snapshot,
                                              restore_snapshot)
    fusion_cfg()
    solver = build_diffusion(d3.SBDF2)
    assert solver.timestepper.donates_histories
    for _ in range(5):
        solver.step(1e-3)
    snap = capture_snapshot(solver)
    for _ in range(3):
        solver.step(1e-3)
    x_ref = np.asarray(solver.X).copy()
    for _ in range(2):
        restore_snapshot(solver, snap)
        for _ in range(3):
            solver.step(1e-3)
        assert np.array_equal(np.asarray(solver.X), x_ref)


# ------------------------------------------------------------ pallas path

def test_pallas_substitution_interpret_matches(fusion_cfg):
    """[fusion] PALLAS routes the banded substitution through the fused
    Pallas kernel (interpret mode on CPU) and matches the XLA scan path
    at the ulp level."""
    x_xla, solver = rb_states(3, d3.RK222, {}, fusion_cfg)
    assert solver.ops.NB > 1   # the kernel covers the multi-block sweep
    x_pal, solver_p = rb_states(3, d3.RK222, {"pallas": "on"}, fusion_cfg)
    assert solver_p.ops._pallas
    scale = np.max(np.abs(x_xla))
    assert np.max(np.abs(x_pal - x_xla)) <= 1e-12 * scale


def test_pallas_adjoint_falls_back_to_scan(fusion_cfg):
    """The Pallas kernel is not differentiable, so solve_transpose (the
    custom_vjp backward of every fused solve) transposes the XLA-scan
    fused path instead — the adjoint contract holds with PALLAS on, and
    the transpose bit-matches the pallas-off one (same precomposed
    operators, same program)."""
    fusion_cfg(pallas="on")
    solver = build_rb(8, 32, matsolver="banded", timestepper=d3.RK222)
    assert solver.ops._pallas
    ops = solver.ops
    # factor once through the step machinery (RK holds per-stage auxes),
    # then transpose-solve against the first stage factorization
    solver.step(0.01)
    aux = solver.timestepper._lhs_aux[0]
    rhs = jnp.asarray(np.random.default_rng(5).standard_normal(
        solver.pencil_shape))
    out_pal = np.asarray(ops.solve_transpose(aux, rhs))
    assert np.isfinite(out_pal).all()
    assert ops._pallas   # restored after the transpose trace
    fusion_cfg()
    solver2 = build_rb(8, 32, matsolver="banded", timestepper=d3.RK222)
    solver2.step(0.01)
    out_xla = np.asarray(solver2.ops.solve_transpose(
        solver2.timestepper._lhs_aux[0], rhs))
    assert np.array_equal(out_pal, out_xla)


# ----------------------------------------------- metrics + retrace hygiene

def test_fused_phase_row_and_zero_retraces(fusion_cfg):
    """The sampler records the fused whole-step row (excluded from the
    decomposition sum), format_phase_table renders it, and the fused
    step program compiles exactly once (zero post-warmup retraces)."""
    fusion_cfg()
    retrace_mod.sentinel.reset()
    metrics = Metrics(sample_cadence=2, sink=None, enabled=True,
                      sampling=True)
    solver = build_diffusion(d3.SBDF2)
    solver.metrics = metrics
    for _ in range(4):
        solver.step(1e-3)
    solver.step_many(8, 1e-3)
    solver.step_many(8, 1e-3)
    record = solver.flush_metrics()
    assert record["phase_samples"] > 0
    assert record["phase_mean_sec"]["fused"] > 0.0
    # the fused row overlaps the decomposition: excluded from the sum
    wall = record["loop_wall_sec"]
    decomp = sum(record["phase_total_sec"][p] for p in SUM_PHASES)
    assert record["phase_sum_frac"] == pytest.approx(
        decomp / wall, rel=1e-3)
    lines = "\n".join(format_phase_table(record))
    assert "fused" in lines and "excluded from sum" in lines
    assert retrace_mod.sentinel.post_arm_retraces == 0
    assert record["retraces_post_warmup"] == 0
