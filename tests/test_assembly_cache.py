"""
Persistent assembly-cache tests (tools/assembly_cache.py): hit/miss/
invalidation semantics of the content-addressed key, corruption fallback,
cross-process reuse, and the bit-identical cached-vs-fresh guarantee on
both a Cartesian (RB) and a curvilinear (annulus, m-coupled NCC) problem.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import dedalus_tpu.public as d3
from dedalus_tpu.tools import assembly_cache
from dedalus_tpu.tools.config import config


@pytest.fixture()
def cache_dir(tmp_path, monkeypatch):
    d = tmp_path / "assembly"
    monkeypatch.setenv("DEDALUS_TPU_ASSEMBLY_CACHE", str(d))
    return d


def build_rb(Nx=32, Nz=8, dtype=np.float64, kappa=1.0, matsolver=None):
    from dedalus_tpu.extras.bench_problems import build_rb_solver
    old = config["linear algebra"].get("MATRIX_SOLVER", "auto")
    if matsolver is not None:
        config["linear algebra"]["MATRIX_SOLVER"] = matsolver
    try:
        if kappa == 1.0:
            solver, b = build_rb_solver(Nx, Nz, dtype)
            return solver
        # variant problem: same SHAPE, different diffusivity scalar — the
        # equation STRING is identical, only the baked coefficient differs
        coords = d3.CartesianCoordinates("x", "z")
        dist = d3.Distributor(coords, dtype=dtype)
        xb = d3.RealFourier(coords["x"], size=Nx, bounds=(0, 4), dealias=3 / 2)
        zb = d3.ChebyshevT(coords["z"], size=Nz, bounds=(0, 1), dealias=3 / 2)
        u = dist.Field(name="u", bases=(xb, zb))
        problem = d3.IVP([u], namespace=locals())
        problem.add_equation("dt(u) - kappa*lap(u) = 0")
        return problem.build_solver(d3.RK222)
    finally:
        config["linear algebra"]["MATRIX_SOLVER"] = old


def mats_equal(a, b):
    if isinstance(a, dict):
        keys = set(a) | set(b)
        for k in keys - {"dsel"}:
            if not np.array_equal(np.asarray(a[k]), np.asarray(b[k])):
                return False
        return a.get("dsel") == b.get("dsel")
    return np.array_equal(a, b)


def test_miss_then_hit_bit_identical_rb(cache_dir):
    fresh = build_rb()
    assert fresh.build_phases.cache == "miss"
    assert list(cache_dir.glob("asm-*.npb"))
    cached = build_rb()
    assert cached.build_phases.cache == "hit"
    for name in ("M", "L"):
        assert mats_equal(fresh._matrices[name], cached._matrices[name])
    # the cached solver must actually run
    cached.step(1e-3)
    assert np.isfinite(np.asarray(cached.X)).all()


def test_banded_store_bit_identical(cache_dir):
    fresh = build_rb(64, 16, matsolver="banded")
    assert fresh.build_phases.cache == "miss"
    assert fresh.structure is not None
    cached = build_rb(64, 16, matsolver="banded")
    assert cached.build_phases.cache == "hit"
    assert cached.structure is not None
    for name in ("M", "L"):
        assert mats_equal(fresh._matrices[name], cached._matrices[name])
    for attr in ("row_perm", "col_perm", "pinned_positions"):
        assert np.array_equal(getattr(fresh.structure, attr),
                              getattr(cached.structure, attr))
    assert (fresh.structure.kl, fresh.structure.ku, fresh.structure.q) == \
        (cached.structure.kl, cached.structure.ku, cached.structure.q)
    cached.step(1e-3)
    assert np.isfinite(np.asarray(cached.X)).all()


def _annulus_lbvp(Nphi=8, Nr=6, eps=0.3):
    coords = d3.PolarCoordinates("phi", "r")
    dist = d3.Distributor(coords, dtype=np.float64)
    ann = d3.AnnulusBasis(coords, shape=(Nphi, Nr), dtype=np.float64,
                          radii=(0.7, 1.8), dealias=2)
    phi, r = dist.local_grids(ann)
    w = dist.Field(name="w", bases=ann)
    w["g"] = 1.0 + eps * np.cos(phi) * r
    u = dist.Field(name="u", bases=ann)
    tau1 = dist.Field(name="tau1", bases=ann.edge)
    tau2 = dist.Field(name="tau2", bases=ann.edge)
    lift_basis = ann.derivative_basis(2)
    lift = lambda A, n: d3.Lift(A, lift_basis, n)  # noqa: E731
    g = dist.Field(name="g", bases=ann)
    g["g"] = 1.0
    problem = d3.LBVP([u, tau1, tau2], namespace=locals())
    problem.add_equation("w*u - lap(u) + lift(tau1,-1) + lift(tau2,-2) = g")
    problem.add_equation("u(r=0.7) = 0")
    problem.add_equation("u(r=1.8) = 0")
    return problem.build_solver()


def test_curvilinear_hit_and_ncc_data_invalidation(cache_dir):
    fresh = _annulus_lbvp()
    assert fresh.build_phases.cache == "miss"
    cached = _annulus_lbvp()
    assert cached.build_phases.cache == "hit"
    assert mats_equal(fresh._matrices["L"], cached._matrices["L"])
    cached.solve()
    # identical equation TEXT but different NCC field data must MISS:
    # the data is baked into the matrices
    other = _annulus_lbvp(eps=0.4)
    assert other.build_phases.cache == "miss"
    assert not mats_equal(fresh._matrices["L"], other._matrices["L"])


def test_invalidation_axes(cache_dir):
    base = build_rb()
    assert base.build_phases.cache == "miss"
    # resolution
    assert build_rb(Nx=64).build_phases.cache == "miss"
    # dtype
    assert build_rb(dtype=np.float32).build_phases.cache == "miss"
    # equation coefficient (identical string, different baked scalar)
    assert build_rb(kappa=2.0).build_phases.cache == "miss"
    assert build_rb(kappa=2.0).build_phases.cache == "hit"
    # package version bump (scoped patch: monkeypatch.undo() would also
    # revert the cache_dir fixture's env var)
    import dedalus_tpu
    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(dedalus_tpu, "__version__", "999.0-test")
        assert build_rb().build_phases.cache == "miss"
    # original problem still hits afterwards
    assert build_rb().build_phases.cache == "hit"


def test_corrupted_entry_falls_back_to_fresh(cache_dir):
    fresh = build_rb()
    assert fresh.build_phases.cache == "miss"
    entries = list(cache_dir.glob("asm-*.npb"))
    assert entries
    # torn write: truncate the entry mid-file
    data = entries[0].read_bytes()
    entries[0].write_bytes(data[:len(data) // 3])
    rebuilt = build_rb()
    # corruption is a clean miss (quarantined + fresh assembly + restore)
    assert rebuilt.build_phases.cache == "miss"
    for name in ("M", "L"):
        assert mats_equal(fresh._matrices[name], rebuilt._matrices[name])
    # garbage entry (valid zip magic absent entirely)
    entries = list(cache_dir.glob("asm-*.npb"))
    entries[0].write_bytes(b"not a cache bundle at all")
    again = build_rb()
    assert again.build_phases.cache == "miss"
    assert build_rb().build_phases.cache == "hit"


def test_key_stability_and_resolve(cache_dir, monkeypatch):
    solver = build_rb()
    key1 = assembly_cache.solver_key(solver, ("M", "L"))
    key2 = assembly_cache.solver_key(solver, ("M", "L"))
    assert key1 == key2 and key1 is not None
    assert assembly_cache.solver_key(solver, ("L",)) != key1
    monkeypatch.setenv("DEDALUS_TPU_ASSEMBLY_CACHE", "")
    assert assembly_cache.resolve() is None


def test_cross_process_reuse(cache_dir):
    code = (
        "import numpy as np, json\n"
        "import dedalus_tpu.public\n"
        "from dedalus_tpu.extras.bench_problems import build_rb_solver\n"
        "solver, b = build_rb_solver(32, 8, np.float64)\n"
        "print(json.dumps(solver.build_phases.record()))\n"
    )
    env = dict(os.environ)
    env["DEDALUS_TPU_ASSEMBLY_CACHE"] = str(cache_dir)
    env.setdefault("JAX_PLATFORMS", "cpu")

    def run():
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             stdout=subprocess.PIPE, text=True, timeout=600)
        assert out.returncode == 0, out.stdout
        line = [ln for ln in out.stdout.splitlines()
                if ln.startswith("{")][-1]
        return json.loads(line)

    first = run()
    assert first["assembly_cache"] == "miss"
    second = run()
    assert second["assembly_cache"] == "hit"


def test_build_phases_in_telemetry(cache_dir):
    solver = build_rb()
    solver.step(1e-3)
    record = solver.flush_metrics()
    phases = record["build_phases"]
    for key in ("host_assembly_sec", "structure_sec", "factor_sec",
                "compile_sec"):
        assert key in phases
    assert phases["compile_sec"] > 0.0
    assert phases["assembly_cache"] in ("hit", "miss")
