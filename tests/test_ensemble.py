"""
EnsembleSolver (core/ensemble.py): vmapped + mesh-sharded fleet stepping.

The contract under test is the acceptance bar of the ensemble PR:
  * fleet results BIT-match a serial run of each member with identical
    parameters (same step bodies, same factorization — vmap only adds
    the member axis), on both the unsharded path and the 8-device
    virtual mesh;
  * a chaos-poisoned member drops out (or rewinds with a per-member dt
    backoff) WITHOUT stopping the batch, with zero post-warmup retraces
    from the PR-3 sentinel;
  * the telemetry record carries the `ensemble` block and `python -m
    dedalus_tpu report` renders it.

All CPU, deterministic, tier-1.
"""

import json
import pathlib
import subprocess
import sys

import numpy as np
import pytest

import dedalus_tpu.public as d3
from dedalus_tpu.tools import chaos as chaos_mod
from dedalus_tpu.tools import retrace as retrace_mod

REPO = pathlib.Path(__file__).parent.parent

# module-wide ensemble marker: tier-1 by default, and covered by the
# conftest hard watchdog (a hung reshard/collective must fail ITS test,
# not eat the tier-1 budget)
pytestmark = pytest.mark.ensemble

AMPS = [0.1, 0.5, 1.0, 2.0, 0.3, 0.7, 1.5, 0.05]
KS = [1, 2, 3, 4, 1, 2, 3, 4]


def build_heat_solver(scheme="RK222", **kw):
    """1-D forced heat IVP with a parameter field `a` riding as an RHS
    extra operand — so member batching covers parameters, not just ICs."""
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=32, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    a = dist.Field(name="a", bases=xb)
    problem = d3.IVP([u], namespace={"u": u, "a": a, "lap": d3.lap})
    problem.add_equation("dt(u) - lap(u) = a*u")
    solver = problem.build_solver(getattr(d3, scheme),
                                  warmup_iterations=2,
                                  enforce_real_cadence=10, **kw)
    x = dist.local_grid(xb)

    def member_init(i):
        u["g"] = np.sin(KS[i] * x)
        a["g"] = AMPS[i] * np.cos(x)

    return solver, member_init


def serial_states(scheme, n, dt, members=8, dts=None):
    """Reference: each member stepped on its own solver."""
    outs = []
    for i in range(members):
        solver, member_init = build_heat_solver(scheme)
        member_init(i)
        solver.step_many(n, dts[i] if dts is not None else dt)
        outs.append(np.asarray(solver.X))
    return outs


# ------------------------------------------------------------- bit-match

@pytest.mark.parametrize("scheme", ["SBDF2", "RK222"])
@pytest.mark.parametrize("mesh", [None, "auto"])
def test_fleet_bitmatches_serial(scheme, mesh):
    """Acceptance: fleet members == their serial runs (<= 1e-12 for f64;
    in practice identical), sharded and unsharded, both scheme families."""
    solver, member_init = build_heat_solver(scheme)
    ens = solver.ensemble(8, mesh=mesh)
    ens.init_members(member_init)
    ens.step_many(25, 1e-3)
    serial = serial_states(scheme, 25, 1e-3)
    for i in range(8):
        err = np.max(np.abs(np.asarray(ens.X[i]) - serial[i]))
        assert err <= 1e-12, (i, err)
    assert np.allclose(ens.sim_times[:8], 25e-3)


def test_heterogeneous_member_dts_bitmatch():
    """per_member_dt: members advance with genuinely different dts inside
    ONE compiled program (vmapped factorization) and still bit-match
    their own serial runs."""
    dts = np.array([1e-3, 5e-4, 2e-3, 1e-3, 7e-4, 1e-3, 1.5e-3, 9e-4])
    solver, member_init = build_heat_solver("RK222")
    ens = solver.ensemble(8, mesh="auto", per_member_dt=True)
    ens.init_members(member_init)
    ens.set_member_dts(dts)
    ens.step_many(20)
    serial = serial_states("RK222", 20, None, dts=dts)
    for i in range(8):
        err = np.max(np.abs(np.asarray(ens.X[i]) - serial[i]))
        assert err <= 1e-12, (i, err)
    assert np.allclose(ens.sim_times[:8], 20 * dts)


def test_member_io_roundtrip():
    """set_states/member_arrays/load_member move per-member state in and
    out of the fleet without loss."""
    solver, member_init = build_heat_solver("RK222")
    ens = solver.ensemble(3, mesh=None)
    G, S = solver.pencil_shape
    rng = np.random.default_rng(7)
    X = rng.normal(size=(3, G, S)).astype(solver.pencil_dtype)
    ens.set_states(X)
    assert np.array_equal(np.asarray(ens.X[:3]), X.astype(ens.X.dtype))
    arrays = ens.member_arrays(1)
    (key, arr), = arrays.items()
    state = ens.load_member(2)
    got = solver.gather_fields()
    assert np.array_equal(np.asarray(got), X[2].astype(ens.X.dtype))
    assert state is solver.state
    with pytest.raises(IndexError):
        ens.member_arrays(3)


# ------------------------------------------------------- construction API

def test_constructor_validation():
    solver, _ = build_heat_solver("SBDF2")
    with pytest.raises(ValueError, match="Runge-Kutta"):
        solver.ensemble(4, per_member_dt=True)
    with pytest.raises(ValueError, match="policy"):
        solver.ensemble(4, policy="explode")
    with pytest.raises(ValueError, match="per_member_dt"):
        solver.ensemble(4, policy="rewind")
    rk, _ = build_heat_solver("RK222")
    with pytest.raises(ValueError, match="per-member dt"):
        rk.ensemble(4, per_member_dt=False).set_member_dts([1e-3] * 4)


# --------------------------------------------------- chaos: drop + rewind

@pytest.mark.chaos
def test_chaos_member_poison_drops_without_stopping(tmp_path):
    """Acceptance: chaos NaN-poisons ONE member mid-run; the batch keeps
    going, the survivors finish bit-matching their serial runs, the
    dropped member is recorded (telemetry + report CLI), and the PR-3
    sentinel reports zero post-warmup retraces."""
    sink = tmp_path / "metrics.jsonl"
    solver, member_init = build_heat_solver("SBDF2")
    ens = solver.ensemble(8, mesh="auto", policy="drop", health_cadence=4,
                          snapshot_cadence=8,
                          metrics_file=str(sink))
    ens.init_members(member_init)
    injector = chaos_mod.ChaosInjector(nan_field="u", nan_iteration=20,
                                       nan_member=3)
    summary = ens.evolve(dt=1e-3, stop_iteration=60, block=4,
                         chaos=injector)
    assert ens.iteration == 60
    assert [f["kind"] for f in injector.fired] == ["nan"]
    # the poisoned member dropped; everyone else finished
    assert summary["dropped"] == 1
    assert summary["dropped_members"] == [3]
    assert summary["active"] == 7
    assert ens.dropped[0]["member"] == 3
    assert ens.dropped[0]["outcome"] == "dropped"
    # the dropped member froze at its newest finite snapshot
    assert np.all(np.isfinite(np.asarray(ens.X[3])))
    # survivors bit-match serial runs of the full 60 steps
    serial = serial_states("SBDF2", 60, 1e-3)
    for i in [0, 1, 2, 4, 5, 6, 7]:
        err = np.max(np.abs(np.asarray(ens.X[i]) - serial[i]))
        assert err <= 1e-12, (i, err)
    # zero post-warmup retraces: the drop was a value change, not a shape
    assert retrace_mod.sentinel.post_arm_retraces == 0
    # telemetry: ensemble block + counters in the flushed record
    record = ens.flush_metrics()
    assert record["ensemble"]["members"] == 8
    assert record["ensemble"]["active"] == 7
    assert record["ensemble"]["dropped"] == 1
    assert record["ensemble"]["dropped_members"] == [3]
    assert record["ensemble"]["ensemble_steps_per_sec"] > 0
    assert record["counters"]["ensemble/dropped"] == 1
    assert record["retraces_post_warmup"] == 0
    # report CLI round-trip: the ensemble columns render
    out = subprocess.run(
        [sys.executable, "-m", "dedalus_tpu", "report", str(sink)],
        cwd=REPO, capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "ensemble: 8 members, 7 active, 1 dropped" in out.stdout
    assert "member-steps/s" in out.stdout
    assert "dropped members: [3]" in out.stdout


@pytest.mark.chaos
def test_chaos_member_poison_rewinds_with_backoff():
    """policy='rewind': the poisoned member restores from its snapshot
    slot with its dt halved; the rest of the fleet never notices, and
    the member stays ACTIVE to completion."""
    solver, member_init = build_heat_solver("RK222")
    ens = solver.ensemble(8, mesh="auto", policy="rewind",
                          per_member_dt=True, health_cadence=4,
                          snapshot_cadence=8, dt_backoff=0.5,
                          max_member_retries=3)
    ens.init_members(member_init)
    injector = chaos_mod.ChaosInjector(nan_field="u", nan_iteration=20,
                                       nan_member=5)
    summary = ens.evolve(dt=1e-3, stop_iteration=60, block=4,
                         chaos=injector)
    assert ens.iteration == 60
    assert summary["dropped"] == 0
    assert summary["active"] == 8
    assert summary["rewinds"] >= 1
    event = ens.rewound[0]
    assert event["member"] == 5
    assert event["outcome"] == "rewound"
    assert event["rewind_iteration"] <= 20
    assert ens.dts[5] == pytest.approx(0.5e-3)
    assert np.all(np.isfinite(np.asarray(ens.X)))
    # the rewound member lost sim-time relative to the fleet (backed-off
    # dt from the snapshot onward)
    assert ens.sim_times[5] < ens.sim_times[0]
    assert retrace_mod.sentinel.post_arm_retraces == 0


@pytest.mark.chaos
def test_rewind_backoff_survives_scalar_dt_driving():
    """A per-step driving loop re-passes the same scalar dt every call;
    that must NOT undo a rewound member's backed-off dt (or rewind
    degenerates to drop-with-extra-work)."""
    solver, member_init = build_heat_solver("RK222")
    ens = solver.ensemble(8, mesh=None, policy="rewind",
                          per_member_dt=True, health_cadence=2,
                          snapshot_cadence=4)
    ens.init_members(member_init)
    ens.snapshot()
    injector = chaos_mod.ChaosInjector(nan_field="u", nan_iteration=6,
                                       nan_member=5)
    for _ in range(30):
        ens.step(1e-3)
        injector.after_step(ens)
    assert len(ens.rewound) == 1
    assert ens.dts[5] == pytest.approx(0.5e-3)
    assert ens.n_active == 8
    assert np.all(np.isfinite(np.asarray(ens.X)))


# ------------------------------------------------ chaos: device loss

@pytest.mark.chaos
def test_chaos_device_loss_reshards_onto_survivors(tmp_path):
    """Acceptance: chaos kills one of the 8 virtual mesh devices mid-run
    (its member block poisoned + loss notification). The fleet re-shards
    onto the 7 survivors before the next dispatch, the lost device's
    member restores from the snapshot ring, the run completes with every
    member ACTIVE — and the final states bit-match fault-free serial
    references (survivors: the full 60 steps; the restored member: its
    snapshot iteration 16 plus the remaining 40 = 56 steps). Zero
    post-warmup retraces: rebuilt programs are fresh wrappers, each
    tracing once."""
    sink = tmp_path / "metrics.jsonl"
    solver, member_init = build_heat_solver("SBDF2")
    ens = solver.ensemble(8, mesh="auto", snapshot_cadence=8,
                          health_cadence=4, metrics_file=str(sink))
    ens.init_members(member_init)
    retrace_mod.sentinel.reset()
    injector = chaos_mod.ChaosInjector(lose_device=2, lose_iteration=20)
    summary = ens.evolve(dt=1e-3, stop_iteration=60, block=4,
                         chaos=injector, log_cadence=0)
    assert [f["kind"] for f in injector.fired] == ["lose_device"]
    assert ens.iteration == 60
    assert summary["reshards"] == 1
    assert summary["devices"] == 7
    assert summary["active"] == 8 and summary["dropped"] == 0
    event = ens.reshard_events[0]
    assert event["lost_devices"] == [2]
    assert [r["source"] for r in event["restored"]] == ["ring"]
    affected = [r["member"] for r in event["restored"]]
    assert affected == injector.fired[0]["members"]
    restored_iter = event["restored"][0]["iteration"]
    assert restored_iter == 16          # newest pre-loss snapshot
    # bit-identity against fault-free references: the restored member
    # plus two survivors (one per side of the lost block) — each
    # reference is a full serial build+run, so spot-checking keeps this
    # inside the tier-1 budget without weakening the claim
    steps_for = lambda i: (restored_iter + (60 - 20)) if i in affected \
        else 60
    for i in sorted(set(affected) | {0, 7}):
        ref_solver, ref_init = build_heat_solver("SBDF2")
        ref_init(i)
        ref_solver.step_many(steps_for(i), 1e-3)
        err = np.max(np.abs(np.asarray(ens.X[i]) - np.asarray(ref_solver.X)))
        assert err <= 1e-12, (i, err)
    assert retrace_mod.sentinel.post_arm_retraces == 0
    # telemetry: reshard count in the flushed block and the report CLI
    record = ens.flush_metrics()
    assert record["ensemble"]["reshards"] == 1
    assert record["counters"]["ensemble/reshards"] == 1
    out = subprocess.run(
        [sys.executable, "-m", "dedalus_tpu", "report", str(sink)],
        cwd=REPO, capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "1 reshards" in out.stdout


@pytest.mark.chaos
def test_device_loss_restores_from_durable_checkpoint(tmp_path):
    """With the snapshot ring unusable (a real device loss destroys its
    slices too), the lost members restore from the last durable sharded
    checkpoint — and the post-fault run still bit-matches the fault-free
    reference (checkpoint iteration + remaining steps)."""
    solver, member_init = build_heat_solver("SBDF2")
    ens = solver.ensemble(8, mesh="auto", snapshot_cadence=1000,
                          health_cadence=4)
    ens.init_members(member_init)
    ens.init_checkpoints(tmp_path / "fleet")
    ens.snapshot()
    ens.step_many(16, 1e-3)
    ens.write_checkpoint()              # durable at iteration 16
    ens.step_many(4)
    # a REAL loss kills the ring slices with the device; model that
    ens.ring.clear()
    injector = chaos_mod.ChaosInjector(lose_device=2, lose_iteration=20)
    injector.after_step(ens)            # poison + notify at iteration 20
    ens.step_many(40)                   # reshard happens on entry
    assert ens.iteration == 60
    event = ens.reshard_events[0]
    assert [r["source"] for r in event["restored"]] == ["checkpoint"]
    assert event["restored"][0]["iteration"] == 16
    assert ens.n_active == 8
    affected = [r["member"] for r in event["restored"]]
    for i in sorted(set(affected) | {0, 7}):
        n = 16 + 40 if i in affected else 60
        ref_solver, ref_init = build_heat_solver("SBDF2")
        ref_init(i)
        ref_solver.step_many(n, 1e-3)
        err = np.max(np.abs(np.asarray(ens.X[i]) - np.asarray(ref_solver.X)))
        assert err <= 1e-12, (i, err)


@pytest.mark.chaos
def test_device_loss_without_any_source_drops_members(tmp_path):
    """No finite ring slot AND no durable checkpoint: the lost device's
    members drop (recorded, masked out) and the rest of the fleet
    completes untouched."""
    solver, member_init = build_heat_solver("SBDF2")
    ens = solver.ensemble(8, mesh="auto", snapshot_cadence=1000,
                          health_cadence=4)
    ens.init_members(member_init)
    ens.step_many(20, 1e-3)
    ens.ring.clear()
    injector = chaos_mod.ChaosInjector(lose_device=3, lose_iteration=20)
    injector.after_step(ens)
    ens.step_many(40)
    assert ens.iteration == 60
    event = ens.reshard_events[0]
    assert event["restored"] == []
    assert event["dropped"] == [3]
    assert ens.n_active == 7
    assert ens.dropped[0]["member"] == 3
    for i in (0, 4, 7):     # spot-check survivors (tier-1 budget)
        ref_solver, ref_init = build_heat_solver("SBDF2")
        ref_init(i)
        ref_solver.step_many(60, 1e-3)
        err = np.max(np.abs(np.asarray(ens.X[i]) - np.asarray(ref_solver.X)))
        assert err <= 1e-12, (i, err)


def test_notify_device_loss_without_mesh_raises():
    solver, member_init = build_heat_solver("SBDF2")
    ens = solver.ensemble(2, mesh=None)
    ens.init_members(member_init)
    ens.notify_device_loss(0)
    with pytest.raises(RuntimeError, match="without a device mesh"):
        ens.step_many(1, 1e-3)


@pytest.mark.chaos
def test_unrecoverable_member_drops_after_retries():
    """A member whose physics (not a transient) diverges exhausts its
    rewind retries and drops — the fleet still completes."""
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=16, bounds=(0, 2 * np.pi))
    s = dist.Field(name="s", bases=xb)
    problem = d3.IVP([s], namespace={})
    problem.add_equation((d3.dt(s), s * s))
    solver = problem.build_solver(d3.RK222, warmup_iterations=2,
                                  enforce_real_cadence=0)
    ens = solver.ensemble(4, mesh=None, policy="rewind",
                          per_member_dt=True, health_cadence=2,
                          snapshot_cadence=4, max_member_retries=2)

    def member_init(i):
        # member 2 blows up at any dt; the others decay harmlessly
        s["g"] = 40.0 if i == 2 else -0.5

    ens.init_members(member_init)
    ens.evolve(dt=0.2, stop_iteration=40, block=2, log_cadence=0)
    assert ens.iteration == 40
    assert [e["member"] for e in ens.dropped] == [2]
    assert ens.dropped[0]["outcome"] == "dropped"
    # it was retried (rewound) before giving up
    assert len([e for e in ens.rewound if e["member"] == 2]) == 2
    assert ens.n_active == 3
    finite = [np.all(np.isfinite(np.asarray(ens.X[i]))) for i in range(3)]
    assert all(finite)
