"""
CLI smoke tests: `get_config` and `report` run in fresh subprocesses so a
regression in the command-line surface fails tier-1 instead of only
surfacing on TPU watchers. Also covers the shared backend-probe platform
sanitization in __graft_entry__.
"""

import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).parent.parent


def _run_cli(args, timeout=120):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run([sys.executable, "-m", "dedalus_tpu", *args],
                          cwd=REPO, env=env, capture_output=True, text=True,
                          timeout=timeout)


def test_get_config_subprocess():
    proc = _run_cli(["get_config"])
    assert proc.returncode == 0, proc.stderr
    assert "[profiling]" in proc.stdout
    assert "SAMPLE_CADENCE" in proc.stdout
    assert "METRICS_DEFAULT" in proc.stdout


def test_report_subprocess(tmp_path):
    fixture = tmp_path / "metrics.jsonl"
    records = [
        {"kind": "step_metrics", "ts": 1.0, "config": "rb_fixture",
         "backend": "cpu", "dtype": "float32", "iterations": 20,
         "loop_wall_sec": 2.0, "steps_per_sec": 10.0, "sample_cadence": 5,
         "phase_samples": 4,
         "phase_mean_sec": {"transform": 0.03, "matsolve": 0.04,
                            "transpose": 0.0, "evaluator": 0.02},
         "phase_total_sec": {"transform": 0.6, "matsolve": 0.8,
                             "transpose": 0.0, "evaluator": 0.4},
         "phase_sum_frac": 0.9, "device_mem_peak_bytes": 123456789,
         "mem_source": "live_arrays", "counters": {"steps": 20}},
        # a bench-style row rides along in the same file
        {"config": "rb256x64_bench", "metric": "RB2D_steps_per_sec",
         "value": 12.3, "unit": "steps/sec", "ts": 2.0},
    ]
    fixture.write_text("".join(json.dumps(r) + "\n" for r in records))
    proc = _run_cli(["report", str(fixture)])
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "rb_fixture" in out
    for phase in ("transform", "matsolve", "transpose", "evaluator"):
        assert phase in out
    assert "1 metrics record(s), 1 other" in out
    assert "RB2D_steps_per_sec" in out


def test_report_heterogeneous_rows(tmp_path):
    """Pre-PR-2 records missing keys, postmortem rows, and non-object JSON
    lines must not crash the report; each lands in the right bucket."""
    fixture = tmp_path / "mixed.jsonl"
    rows = [
        '{"kind": "step_metrics"}',                        # bare, no keys
        '{"kind": "step_metrics", "iterations": 5, '
        '"health": {"ok": false, "reason": "boom", "checks": 2}}',
        '{"kind": "health_postmortem", "iteration": 7, '
        '"sim_time": 0.7, "reason": "non-finite state"}',
        '{"metric": "RB2D_steps_per_sec", "value": 1.0, "stale": true}',
        '[1, 2, 3]',                                       # not an object
        'not json at all',
    ]
    fixture.write_text("\n".join(rows) + "\n")
    proc = _run_cli(["report", str(fixture)])
    assert proc.returncode == 0, proc.stderr
    assert "2 metrics record(s), 1 other, 1 postmortem, 2 unparsable" \
        in proc.stdout
    assert "health: FAILED: boom" in proc.stdout
    assert "non-finite state" in proc.stdout
    assert "[stale]" in proc.stdout


def test_report_last_filter(tmp_path):
    fixture = tmp_path / "many.jsonl"
    rows = [{"kind": "step_metrics", "iterations": i} for i in range(5)]
    fixture.write_text("".join(json.dumps(r) + "\n" for r in rows))
    proc = _run_cli(["report", str(fixture), "--last", "2"])
    assert proc.returncode == 0, proc.stderr
    assert "2 metrics record(s)" in proc.stdout
    assert "3 iters" in proc.stdout and "4 iters" in proc.stdout
    assert "0 iters" not in proc.stdout
    proc = _run_cli(["report", str(fixture), "--last", "notanint"])
    assert proc.returncode == 2
    assert "--last" in proc.stderr


def test_postmortem_subprocess(tmp_path):
    """`postmortem <dir>` summarizes a flight-recorder dump; the record
    fields round-trip into the printed summary."""
    pm = tmp_path / "postmortem_i00000042"
    pm.mkdir()
    record = {
        "kind": "health_postmortem", "ts": 1.0,
        "reason": "non-finite state: field 'u' has 3 NaN / 0 Inf entries",
        "iteration": 42, "sim_time": 4.2, "dt": 0.1,
        "checks": 9, "warnings": 1,
        "fields": {"u": {"nan": 3, "inf": 0, "max_abs": 1.5, "l2": 2.5,
                         "tail_frac": {"z": 0.4}}},
        "dt_history": [{"iteration": 41, "dt": 0.1, "freq_max": 12.0}],
        "checkpoint": "state_at_failure.h5",
        "backend": "cpu", "dtype": "float32",
    }
    (pm / "postmortem.json").write_text(json.dumps(record))
    (pm / "health_ring.jsonl").write_text(
        json.dumps({"kind": "health_sample", "iteration": 41}) + "\n"
        + json.dumps({"kind": "health_sample", "iteration": 42}) + "\n")
    proc = _run_cli(["postmortem", str(pm)])
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "non-finite state: field 'u'" in out
    assert "iteration=42" in out
    assert "backend=cpu" in out
    assert "ring buffer: 2 records" in out
    assert "freq_max=12.0" in out
    assert "state_at_failure.h5" in out


def test_postmortem_missing_dir():
    proc = _run_cli(["postmortem", "/nonexistent/pm_dir"])
    assert proc.returncode == 1
    assert "cannot read" in proc.stderr


def test_postmortem_usage():
    proc = _run_cli(["postmortem"])
    assert proc.returncode == 2
    assert "usage" in proc.stderr


def test_report_missing_file():
    proc = _run_cli(["report", "/nonexistent/metrics.jsonl"])
    assert proc.returncode != 0
    assert "cannot read" in proc.stderr


def test_report_usage():
    proc = _run_cli(["report"])
    assert proc.returncode == 2
    assert "usage" in proc.stderr


def test_unknown_command():
    proc = _run_cli(["not_a_command"])
    assert proc.returncode == 2
    assert "report" in proc.stderr  # listed in usage


def _graft():
    sys.path.insert(0, str(REPO))
    import __graft_entry__
    return __graft_entry__


def test_sanitize_jax_platforms():
    graft = _graft()
    env = {"JAX_PLATFORMS": " tpu, ,cpu,, "}
    assert graft._sanitize_jax_platforms(env)["JAX_PLATFORMS"] == "tpu,cpu"
    env = {"JAX_PLATFORMS": " ,, "}
    assert "JAX_PLATFORMS" not in graft._sanitize_jax_platforms(env)
    env = {}
    assert "JAX_PLATFORMS" not in graft._sanitize_jax_platforms(env)


def test_probe_strips_unknown_platform():
    """A probe env naming an unregistered platform falls back cleanly: the
    bogus entry is stripped (mutating the caller's env, so bench children
    inherit the fix) and the probe succeeds on the remainder — bench
    records then never carry an 'Unable to initialize backend' error."""
    graft = _graft()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "definitely_not_a_backend,cpu"
    backend, n = graft._probe_devices(env, timeout=90)
    assert backend == "cpu", n
    assert env["JAX_PLATFORMS"] == "cpu"


def _trace_fixture(trace_id):
    spans = [
        {"trace_id": trace_id, "span_id": 1, "parent_id": None,
         "name": "request", "t0": 100.0, "dur_sec": 0.5, "tid": 1,
         "attrs": {"outcome": "ok", "plan": {"plan_version": 1}}},
        {"trace_id": trace_id, "span_id": 2, "parent_id": 1,
         "name": "queue", "t0": 100.05, "dur_sec": 0.01, "tid": 1},
        {"trace_id": trace_id, "span_id": 3, "parent_id": 1,
         "name": "run", "t0": 100.1, "dur_sec": 0.4, "tid": 2},
    ]
    return {"kind": "trace", "trace_id": trace_id, "ts": 1.0,
            "spans": spans}


def test_trace_subcommand(tmp_path):
    """`trace` renders span trees, filters by id prefix, summarizes, and
    exports valid Chrome trace-event JSON."""
    fixture = tmp_path / "served.jsonl"
    rows = [_trace_fixture("aaaa000011112222"),
            _trace_fixture("bbbb000011112222"),
            {"kind": "step_metrics", "iterations": 5}]   # ignored
    fixture.write_text("".join(json.dumps(r) + "\n" for r in rows))

    proc = _run_cli(["trace", str(fixture)])
    assert proc.returncode == 0, proc.stderr
    assert "trace aaaa000011112222" in proc.stdout
    assert "trace bbbb000011112222" in proc.stdout
    assert "request" in proc.stdout and "queue" in proc.stdout

    proc = _run_cli(["trace", str(fixture), "--trace-id", "bbbb",
                     "--summary"])
    assert proc.returncode == 0, proc.stderr
    assert "aaaa" not in proc.stdout
    assert "root request 500.000 ms, 3 spans" in proc.stdout

    out = tmp_path / "chrome.json"
    proc = _run_cli(["trace", str(fixture), "--last", "1", "--chrome",
                     str(out)])
    assert proc.returncode == 0, proc.stderr
    assert "wrote 1 trace(s), 3 span(s)" in proc.stdout
    doc = json.loads(out.read_text())
    assert doc["displayTimeUnit"] == "ms"
    assert len(doc["traceEvents"]) == 3
    for ev in doc["traceEvents"]:
        assert ev["ph"] == "X" and ev["ts"] > 0
    root = next(ev for ev in doc["traceEvents"] if ev["name"] == "request")
    assert root["args"]["trace_id"] == "bbbb000011112222"


def test_trace_subcommand_errors(tmp_path):
    proc = _run_cli(["trace", "/nonexistent/traces.jsonl"])
    assert proc.returncode == 1
    assert "cannot read" in proc.stderr
    fixture = tmp_path / "t.jsonl"
    fixture.write_text(json.dumps(_trace_fixture("aaaa")) + "\n")
    proc = _run_cli(["trace", str(fixture), "--trace-id", "zzzz"])
    assert proc.returncode == 1
    assert "no matching" in proc.stderr


def test_report_plan_provenance_and_backfill(tmp_path):
    """Report renders resolved plan provenance on stamped rows and the
    literal `plan=unversioned` on pre-provenance rows (the backfill
    guard: absence is explicit, never faked or crashed on)."""
    fixture = tmp_path / "mixed.jsonl"
    plan = {"plan_version": 1,
            "fusion": {"solve": True, "matvec": True, "transforms": False,
                       "donate": True, "pallas": False},
            "solve_composition": "sequential", "solve_dtype": "native",
            "spike_chunks": 0, "transpose_chunks": 2,
            "solver_key": "f760738c9e28c192"}
    rows = [
        {"kind": "step_metrics", "iterations": 5, "plan": plan},
        # a pre-PR-16 row: no plan block at all
        {"kind": "step_metrics", "iterations": 7},
        # bench-style row with provenance
        {"config": "rb256x64_tracing", "overhead_frac": 0.004,
         "plan": plan, "ts": 2.0},
    ]
    fixture.write_text("".join(json.dumps(r) + "\n" for r in rows))
    proc = _run_cli(["report", str(fixture)])
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert out.count("plan[v1]: fusion=solve+matvec+donate, "
                     "solve=sequential/native, spike=0, chunks=2, "
                     "key=f760738c9e28c192") == 2
    assert out.count("plan=unversioned") == 1


def test_report_service_stats_error_codes(tmp_path):
    """The service_stats faults block's per-error-code counters render as
    a census line; uptime rides the header line."""
    fixture = tmp_path / "stats.jsonl"
    record = {"kind": "service_stats", "requests_served": 9, "errors": 3,
              "uptime_sec": 42.5,
              "pool": {"hits": 5, "misses": 4, "evictions": 1,
                       "entries": []},
              "faults": {"shed": 2, "error_codes": {"overloaded": 2,
                                                    "bad-spec": 1}}}
    fixture.write_text(json.dumps(record) + "\n")
    proc = _run_cli(["report", str(fixture)])
    assert proc.returncode == 0, proc.stderr
    assert "uptime 42.5s" in proc.stdout
    assert "error codes: 1 bad-spec, 2 overloaded" in proc.stdout


def test_report_trace_record_line(tmp_path):
    """`kind: trace` records in a telemetry file get a one-line summary
    pointing at the `trace` subcommand."""
    fixture = tmp_path / "served.jsonl"
    fixture.write_text(json.dumps(_trace_fixture("cccc000011112222"))
                       + "\n")
    proc = _run_cli(["report", str(fixture)])
    assert proc.returncode == 0, proc.stderr
    assert "(trace) cccc000011112222: root request 500.0 ms, 3 spans" \
        in proc.stdout


def test_report_ledger_rows_with_deltas(tmp_path):
    """`kind: ledger` rows render one line per program with deltas vs
    the previous round of the same (program, backend) series; rows from
    before the cost tier versioned its fields render as the literal
    `ledger=unversioned` backfill instead of crashing."""
    fixture = tmp_path / "ledger.jsonl"
    plan = {"plan_version": 1, "solve_composition": "sequential"}
    rows = [
        {"kind": "ledger", "config": "progcheck_census",
         "program": "diffusion_step", "backend": "cpu",
         "ledger_version": 1, "flops": 1000000, "bytes_accessed": 5000000,
         "peak_bytes": 2000000, "hlo_instructions": 300,
         "scan_max_length": 6, "plan": plan, "ts": 1.0},
        {"kind": "ledger", "config": "progcheck_census",
         "program": "diffusion_step", "backend": "cpu",
         "ledger_version": 1, "flops": 1200000, "bytes_accessed": 5000000,
         "peak_bytes": 2500000, "hlo_instructions": 300,
         "scan_max_length": 6, "plan": plan, "ts": 2.0},
        # a pre-cost-tier row: no ledger_version, no fields
        {"kind": "ledger", "program": "old_prog", "ts": 3.0},
    ]
    fixture.write_text("".join(json.dumps(r) + "\n" for r in rows))
    proc = _run_cli(["report", str(fixture)])
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert out.count("(ledger) diffusion_step [cpu]:") == 2
    assert "flops=1,000,000" in out                  # first round, no delta
    assert "flops=1,200,000 (+20.0%)" in out         # second round delta
    assert "peak_mem=2,500,000 (+25.0%)" in out
    assert "scan_depth=6" in out
    assert "solve=sequential" in out                 # plan provenance line
    assert out.count("ledger=unversioned") == 1      # the backfill guard
    assert "3 other" in out


def test_report_perfwatch_trend_table(tmp_path):
    """With enough history in the file, report appends the perfwatch
    trend table before the summary line; a short fixture renders none
    (analyzed-series-only keeps young files quiet)."""
    fixture = tmp_path / "trend.jsonl"
    rows = [{"config": "trendcfg", "backend": "cpu", "steps_per_sec": v,
             "ts": float(i)}
            for i, v in enumerate([10.0, 10.1, 9.9, 10.0, 6.0])]
    fixture.write_text("".join(json.dumps(r) + "\n" for r in rows))
    proc = _run_cli(["report", str(fixture)])
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "perfwatch trends:" in out
    assert "steps_per_sec:trendcfg:cpu:unversioned" in out
    assert "regression" in out
    # the trend table precedes the summary line
    assert out.index("perfwatch trends:") < out.index("metrics record(s)")
    # a young file adds no table
    short = tmp_path / "short.jsonl"
    short.write_text(json.dumps(rows[0]) + "\n")
    proc = _run_cli(["report", str(short)])
    assert proc.returncode == 0, proc.stderr
    assert "perfwatch trends:" not in proc.stdout


def test_perfwatch_subprocess(tmp_path):
    """`python -m dedalus_tpu perfwatch` end to end: rc 0 + summary on a
    stable fixture, rc 1 + named finding under --check on a regressed
    one."""
    stable = tmp_path / "stable.jsonl"
    rows = [{"config": "c", "backend": "cpu", "steps_per_sec": v,
             "ts": float(i)}
            for i, v in enumerate([10.0, 10.1, 9.9, 10.0, 10.05])]
    stable.write_text("".join(json.dumps(r) + "\n" for r in rows))
    proc = _run_cli(["perfwatch", str(stable)])
    assert proc.returncode == 0, proc.stderr
    assert "1 analyzed, 0 regression(s)" in proc.stdout
    regressed = tmp_path / "regressed.jsonl"
    rows[-1]["steps_per_sec"] = 6.0
    regressed.write_text("".join(json.dumps(r) + "\n" for r in rows))
    proc = _run_cli(["perfwatch", str(regressed), "--check"])
    assert proc.returncode == 1
    assert "perfwatch regression: steps_per_sec:c:cpu:unversioned" \
        in proc.stdout
