"""
CLI smoke tests: `get_config` and `report` run in fresh subprocesses so a
regression in the command-line surface fails tier-1 instead of only
surfacing on TPU watchers. Also covers the shared backend-probe platform
sanitization in __graft_entry__.
"""

import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).parent.parent


def _run_cli(args, timeout=120):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run([sys.executable, "-m", "dedalus_tpu", *args],
                          cwd=REPO, env=env, capture_output=True, text=True,
                          timeout=timeout)


def test_get_config_subprocess():
    proc = _run_cli(["get_config"])
    assert proc.returncode == 0, proc.stderr
    assert "[profiling]" in proc.stdout
    assert "SAMPLE_CADENCE" in proc.stdout
    assert "METRICS_DEFAULT" in proc.stdout


def test_report_subprocess(tmp_path):
    fixture = tmp_path / "metrics.jsonl"
    records = [
        {"kind": "step_metrics", "ts": 1.0, "config": "rb_fixture",
         "backend": "cpu", "dtype": "float32", "iterations": 20,
         "loop_wall_sec": 2.0, "steps_per_sec": 10.0, "sample_cadence": 5,
         "phase_samples": 4,
         "phase_mean_sec": {"transform": 0.03, "matsolve": 0.04,
                            "transpose": 0.0, "evaluator": 0.02},
         "phase_total_sec": {"transform": 0.6, "matsolve": 0.8,
                             "transpose": 0.0, "evaluator": 0.4},
         "phase_sum_frac": 0.9, "device_mem_peak_bytes": 123456789,
         "mem_source": "live_arrays", "counters": {"steps": 20}},
        # a bench-style row rides along in the same file
        {"config": "rb256x64_bench", "metric": "RB2D_steps_per_sec",
         "value": 12.3, "unit": "steps/sec", "ts": 2.0},
    ]
    fixture.write_text("".join(json.dumps(r) + "\n" for r in records))
    proc = _run_cli(["report", str(fixture)])
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "rb_fixture" in out
    for phase in ("transform", "matsolve", "transpose", "evaluator"):
        assert phase in out
    assert "1 metrics record(s), 1 other" in out
    assert "RB2D_steps_per_sec" in out


def test_report_missing_file():
    proc = _run_cli(["report", "/nonexistent/metrics.jsonl"])
    assert proc.returncode != 0
    assert "cannot read" in proc.stderr


def test_report_usage():
    proc = _run_cli(["report"])
    assert proc.returncode == 2
    assert "usage" in proc.stderr


def test_unknown_command():
    proc = _run_cli(["not_a_command"])
    assert proc.returncode == 2
    assert "report" in proc.stderr  # listed in usage


def _graft():
    sys.path.insert(0, str(REPO))
    import __graft_entry__
    return __graft_entry__


def test_sanitize_jax_platforms():
    graft = _graft()
    env = {"JAX_PLATFORMS": " tpu, ,cpu,, "}
    assert graft._sanitize_jax_platforms(env)["JAX_PLATFORMS"] == "tpu,cpu"
    env = {"JAX_PLATFORMS": " ,, "}
    assert "JAX_PLATFORMS" not in graft._sanitize_jax_platforms(env)
    env = {}
    assert "JAX_PLATFORMS" not in graft._sanitize_jax_platforms(env)


def test_probe_strips_unknown_platform():
    """A probe env naming an unregistered platform falls back cleanly: the
    bogus entry is stripped (mutating the caller's env, so bench children
    inherit the fix) and the probe succeeds on the remainder — bench
    records then never carry an 'Unable to initialize backend' error."""
    graft = _graft()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "definitely_not_a_backend,cpu"
    backend, n = graft._probe_devices(env, timeout=90)
    assert backend == "cpu", n
    assert env["JAX_PLATFORMS"] == "cpu"
