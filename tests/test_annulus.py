"""
Annulus basis tests: transforms, calculus operators vs closed forms, NCC
products, and LBVPs vs manufactured solutions
(reference patterns: dedalus/tests/test_transforms.py roundtrips,
tests/test_polar_calculus.py annulus cases, tests/test_lbvp.py).
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3

RI, RO = 1.0, 3.0


def make_annulus(dtype, shape=(24, 16), radii=(RI, RO), k=0):
    cs = d3.PolarCoordinates("phi", "r")
    dist = d3.Distributor(cs, dtype=dtype)
    ann = d3.AnnulusBasis(cs, shape=shape, dtype=dtype, radii=radii, k=k)
    return cs, dist, ann


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("k", [0, 1])
def test_annulus_scalar_roundtrip(dtype, k):
    cs, dist, ann = make_annulus(dtype, k=k)
    phi, r = dist.local_grids(ann)
    x, y = r * np.cos(phi), r * np.sin(phi)
    f = dist.Field(name="f", bases=ann)
    f["g"] = x ** 2 + 2 * x * y - y ** 2 + 3 / r
    g0 = np.array(f["g"])
    f["c"] = f["c"]
    assert np.abs(f["g"] - g0).max() < 1e-10


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_annulus_vector_roundtrip(dtype):
    cs, dist, ann = make_annulus(dtype)
    phi, r = dist.local_grids(ann)
    x, y = r * np.cos(phi), r * np.sin(phi)
    ux = 2 * x * y
    uy = x ** 2 - y ** 2 + 1
    u = dist.VectorField(cs, name="u", bases=ann)
    u["g"] = np.array([-np.sin(phi) * ux + np.cos(phi) * uy,
                       np.cos(phi) * ux + np.sin(phi) * uy])
    g0 = np.array(u["g"])
    u["c"] = u["c"]
    assert np.abs(u["g"] - g0).max() < 1e-11


def test_annulus_coeff_roundtrip_random():
    cs, dist, ann = make_annulus(np.float64, shape=(16, 12))
    f = dist.Field(name="f", bases=ann)
    rng = np.random.default_rng(0)
    c = rng.standard_normal(f["c"].shape)
    c[1, :] = 0  # m=0 minus-sin slot invalid for scalars
    f["c"] = c
    f["g"] = f["g"]
    assert np.abs(f["c"] - c).max() < 1e-11


def test_annulus_calculus():
    """grad/div/lap/skew vs closed forms (incl. nonpolynomial 1/r terms)."""
    cs, dist, ann = make_annulus(np.float64, shape=(32, 24))
    phi, r = dist.local_grids(ann)
    x, y = r * np.cos(phi), r * np.sin(phi)
    f = dist.Field(name="f", bases=ann)
    f["g"] = x ** 3 * y - y ** 2 + x + np.log(r)
    dfx = 3 * x ** 2 * y + 1 + x / r ** 2
    dfy = x ** 3 - 2 * y + y / r ** 2
    gphi = -np.sin(phi) * dfx + np.cos(phi) * dfy
    gr = np.cos(phi) * dfx + np.sin(phi) * dfy
    g = d3.grad(f).evaluate()["g"]
    assert np.abs(g[0] - gphi).max() < 1e-8
    assert np.abs(g[1] - gr).max() < 1e-8
    lap_analytic = 6 * x * y - 2  # lap(log r) = 0 in 2D
    assert np.abs(d3.lap(f).evaluate()["g"] - lap_analytic).max() < 1e-7
    assert np.abs(d3.div(d3.grad(f)).evaluate()["g"] - lap_analytic).max() < 1e-7
    u = d3.grad(f)
    sk = d3.skew(u).evaluate()["g"]
    assert np.abs(sk[0] - gr).max() < 1e-8
    assert np.abs(sk[1] + gphi).max() < 1e-8


def test_annulus_interpolation_and_integration():
    cs, dist, ann = make_annulus(np.float64, shape=(24, 20))
    phi, r = dist.local_grids(ann)
    x, y = r * np.cos(phi), r * np.sin(phi)
    f = dist.Field(name="f", bases=ann)
    f["g"] = x ** 2 * y - y + 2
    for r0 in (RI, RO, 2.0):
        fR = f(r=r0).evaluate()
        phig = phi[:, 0]
        xg, yg = r0 * np.cos(phig), r0 * np.sin(phig)
        assert np.abs(fR["g"][:, 0] - (xg ** 2 * yg - yg + 2)).max() < 1e-10, r0
    total = float(d3.integ(f).evaluate()["g"].ravel()[0])
    # odd terms vanish; constant integrates to 2 * annulus area
    assert abs(total - 2 * np.pi * (RO ** 2 - RI ** 2)) < 1e-10


def test_annulus_k_interpolation():
    """Boundary evaluation from a differentiated (k=2) basis."""
    cs, dist, ann = make_annulus(np.float64, shape=(16, 16))
    phi, r = dist.local_grids(ann)
    f = dist.Field(name="f", bases=ann)
    f["g"] = r ** 3 - 2 * r
    lapf = d3.lap(f)  # lives at k=2
    expect = 9 * RO - 2 / RO  # lap(r^3 - 2r) = 9r - 2/r
    out = lapf(r=RO).evaluate()["g"]
    assert np.abs(out[:, 0] - expect).max() < 1e-8 * abs(expect)


def test_annulus_ncc_lhs_vs_rhs():
    """LHS NCC matrices match explicit grid-space multiplication."""
    cs, dist, ann = make_annulus(np.float64, shape=(16, 16))
    phi, r = dist.local_grids(ann)
    x, y = r * np.cos(phi), r * np.sin(phi)
    ncc = dist.Field(name="ncc", bases=ann)
    ncc["g"] = r ** 2 + 1 / r
    u = dist.Field(name="u", bases=ann)
    v = dist.Field(name="v", bases=ann)
    problem = d3.LBVP([u], namespace=locals())
    problem.add_equation("ncc*u = ncc*v")
    v["g"] = x * y + 3 * y + r
    problem.build_solver().solve()
    assert np.abs(u["g"] - v["g"]).max() < 1e-9


def test_annulus_scalar_poisson_lbvp():
    cs, dist, ann = make_annulus(np.float64, shape=(24, 24))
    phi, r = dist.local_grids(ann)
    x, y = r * np.cos(phi), r * np.sin(phi)
    u = dist.Field(name="u", bases=ann)
    tau1 = dist.Field(name="tau1", bases=ann.edge)
    tau2 = dist.Field(name="tau2", bases=ann.edge)
    f = dist.Field(name="f", bases=ann)
    # u_exact = (r^2 - RI^2)(RO^2 - r^2): lap = -16 r^2 + 4(RI^2 + RO^2)
    f["g"] = -16 * r ** 2 + 4 * (RI ** 2 + RO ** 2)
    lift_basis = ann.derivative_basis(2)
    lift = lambda A, n: d3.Lift(A, lift_basis, n)
    problem = d3.LBVP([u, tau1, tau2], namespace={**locals(), 'RI': RI, 'RO': RO})
    problem.add_equation("lap(u) + lift(tau1, -1) + lift(tau2, -2) = f")
    problem.add_equation("u(r=RI) = 0")
    problem.add_equation("u(r=RO) = 0")
    problem.build_solver().solve()
    expect = (r ** 2 - RI ** 2) * (RO ** 2 - r ** 2)
    assert np.abs(u["g"] - expect).max() < 1e-10


def test_annulus_vector_lbvp():
    """Vector Poisson with zero BCs: u_exact = grad(h), h chosen so grad(h)
    vanishes at both boundaries; F = lap(u_exact) evaluated spectrally."""
    cs, dist, ann = make_annulus(np.float64, shape=(24, 28))
    phi, r = dist.local_grids(ann)
    x, y = r * np.cos(phi), r * np.sin(phi)
    h = dist.Field(name="h", bases=ann)
    g = (r ** 2 - RI ** 2) * (RO ** 2 - r ** 2)
    h["g"] = g ** 2 * (1 + 0.1 * x)
    u_exact = d3.grad(h).evaluate()
    F_k3 = d3.lap(d3.grad(h)).evaluate()  # lives at k=3
    F = dist.VectorField(cs, name="F", bases=ann)
    F["g"] = np.asarray(F_k3["g"])  # re-represent at base level
    u = dist.VectorField(cs, name="u", bases=ann)
    tau1 = dist.VectorField(cs, name="tau1", bases=ann.edge)
    tau2 = dist.VectorField(cs, name="tau2", bases=ann.edge)
    lift_basis = ann.derivative_basis(2)
    lift = lambda A, n: d3.Lift(A, lift_basis, n)
    problem = d3.LBVP([u, tau1, tau2], namespace={**locals(), 'RI': RI, 'RO': RO})
    problem.add_equation("lap(u) + lift(tau1, -1) + lift(tau2, -2) = F")
    problem.add_equation("u(r=RI) = 0")
    problem.add_equation("u(r=RO) = 0")
    problem.build_solver().solve()
    err = np.abs(u["g"] - u_exact["g"]).max()
    scale = np.abs(u_exact["g"]).max()
    assert err < 1e-8 * max(scale, 1.0)


def test_annulus_diffusion_ivp():
    """Azimuthal-mode diffusion decay rates vs analytic Bessel combination.

    Evolve dt(u) = lap(u) with u(RI)=u(RO)=0 from a smooth initial condition
    and compare against a high-resolution reference run.
    """
    cs, dist, ann = make_annulus(np.float64, shape=(8, 24))
    phi, r = dist.local_grids(ann)
    u = dist.Field(name="u", bases=ann)
    tau1 = dist.Field(name="tau1", bases=ann.edge)
    tau2 = dist.Field(name="tau2", bases=ann.edge)
    lift_basis = ann.derivative_basis(2)
    lift = lambda A, n: d3.Lift(A, lift_basis, n)
    problem = d3.IVP([u, tau1, tau2], namespace={**locals(), 'RI': RI, 'RO': RO})
    problem.add_equation("dt(u) - lap(u) + lift(tau1, -1) + lift(tau2, -2) = 0")
    problem.add_equation("u(r=RI) = 0")
    problem.add_equation("u(r=RO) = 0")
    solver = problem.build_solver(d3.SBDF2)
    u["g"] = np.sin(np.pi * (r - RI) / (RO - RI)) * (1 + 0.3 * np.cos(phi))
    # analytic lowest decay rate approx (pi/dR)^2 modified by cylindrical
    # geometry; instead check self-consistency: energy decays monotonically
    # and solution stays smooth.
    E0 = float(d3.integ(u * u).evaluate()["g"].ravel()[0])
    for _ in range(200):
        solver.step(1e-3)
    E1 = float(d3.integ(u * u).evaluate()["g"].ravel()[0])
    assert np.isfinite(E1)
    assert E1 < E0
    # decay rate of the m=0 component comparable to Dirichlet Laplacian
    # lowest eigenvalue lambda ~ (pi/dR)^2 = 2.47; loose bounds
    rate = -np.log(E1 / E0) / (2 * 200e-3)
    assert 1.5 < rate < 4.0


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_annulus_vector_ncc_lhs(dtype):
    """Tensor-valued (radial-vector) LHS NCC on the annulus: the
    intertwiner-sandwich matrix path (arithmetic._polar_tensor_ncc_matrix)
    must reproduce the grid product exactly for band-limited data (the
    reference example ivp_annulus_centrifugal_convection relies on
    rvec-lift and b*g terms of this form)."""
    RI, RO = 1.0, 3.0
    coords = d3.PolarCoordinates("phi", "r")
    dist = d3.Distributor(coords, dtype=dtype)
    ann = d3.AnnulusBasis(coords, shape=(16, 12), radii=(RI, RO), dtype=dtype)
    phi, r = dist.local_grids(ann)
    gv = dist.VectorField(coords, name="gv", bases=ann)
    gv["g"][1] = np.broadcast_to(np.asarray(0.5 + r ** 2),
                                 np.broadcast_shapes(phi.shape, r.shape))
    bsrc = dist.Field(name="bsrc", bases=ann)
    bsrc["g"] = np.cos(2 * phi) * (r - 2) ** 2 + np.sin(phi) * r
    b2 = dist.Field(name="b2", bases=ann)
    u = dist.VectorField(coords, name="u", bases=ann)
    problem = d3.LBVP([b2, u], namespace=locals())
    problem.add_equation("b2 = bsrc")
    problem.add_equation("u + gv*b2 = 0")
    solver = problem.build_solver()
    solver.solve()
    expect = -np.asarray(gv["g"]) * np.asarray(bsrc["g"])[None]
    assert np.abs(np.asarray(u["g"]) - expect).max() < 1e-11
