"""
Double-double (emulated f64) arithmetic: exactness and precision oracles.

Every check compares the f32-pair result against numpy float64 reference
arithmetic; tolerances reflect dd's ~49-bit significand (eps ~ 2^-49 ~
1.8e-15) vs f64's 53 bits. Reference parity target: the reference
framework runs float64 end-to-end (SURVEY.md §7 hard part 7); this is the
TPU-native equivalent compute path.
"""

import numpy as np
import pytest

from dedalus_tpu.libraries import doubledouble as dd


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def rel_err(approx, exact):
    scale = np.max(np.abs(exact)) + 1e-300
    return np.max(np.abs(approx - exact)) / scale


def test_roundtrip_precision(rng):
    # a dd pair carries ~49 significand bits (24 + 24 + implicit overlap
    # headroom) vs f64's 53: roundtrip is accurate to ~2^-49 relative,
    # not exact
    x = rng.standard_normal(1000) * 10.0 ** rng.integers(-8, 8, 1000)
    a = dd.dd_from_f64(x)
    err = np.abs(dd.dd_to_f64(a) - x) / np.abs(x)
    assert err.max() < 2.0 ** -48


def test_two_sum_exact(rng):
    a = np.float32(1.0)
    b = np.float32(1e-8)
    s, e = dd.two_sum(a, b)
    assert float(s) + float(e) == pytest.approx(1.0 + 1e-8, abs=0)
    # exactness: s + e == a + b in f64
    assert np.float64(s) + np.float64(e) == np.float64(a) + np.float64(b)


def test_two_prod_exact(rng):
    a = rng.standard_normal(200).astype(np.float32)
    b = rng.standard_normal(200).astype(np.float32)
    p, e = dd.two_prod(np.asarray(a), np.asarray(b))
    exact = a.astype(np.float64) * b.astype(np.float64)
    got = np.asarray(p, dtype=np.float64) + np.asarray(e, dtype=np.float64)
    assert np.array_equal(got, exact)


def test_add_mul_div_precision(rng):
    x = rng.standard_normal(500)
    y = rng.standard_normal(500) * 3.7
    ax, ay = dd.dd_from_f64(x), dd.dd_from_f64(y)
    assert rel_err(dd.dd_to_f64(dd.dd_add(ax, ay)), x + y) < 2e-14
    assert rel_err(dd.dd_to_f64(dd.dd_mul(ax, ay)), x * y) < 2e-14
    assert rel_err(dd.dd_to_f64(dd.dd_div(ax, ay)), x / y) < 2e-14
    assert rel_err(dd.dd_to_f64(dd.dd_mul_f32(ax, np.float32(1.5))),
                   x * 1.5) < 2e-14


def test_accumulated_sum_precision(rng):
    # f32 would drift at ~1e-7 over 10^4 additions; dd must hold ~1e-14
    x = rng.standard_normal(10000)
    a = dd.dd_zeros(())
    for chunk in x.reshape(100, 100):
        c = dd.dd_from_f64(chunk)
        # tree-reduce the chunk then accumulate
        s = dd.DD(c.hi.sum(), c.lo.sum())  # f32 partial: deliberately crude
        a = dd.dd_add(a, s)
    crude = float(dd.dd_to_f64(a))
    exact = x.sum()
    # even with crude f32 chunk sums the dd accumulator stays ~1e-11;
    # this guards the accumulator itself, not the chunk reduction
    assert abs(crude - exact) < 1e-4
    # full-precision path: element-wise dd accumulate of one chunk
    c = dd.dd_from_f64(x[:100])
    tot = dd.dd_zeros(())
    for i in range(100):
        tot = dd.dd_add(tot, c[i])
    assert abs(float(dd.dd_to_f64(tot)) - x[:100].sum()) < 1e-13


def test_matmul_precision(rng):
    A = rng.standard_normal((100, 80))
    B = rng.standard_normal((80, 60))
    C = dd.dd_matmul(dd.dd_from_f64(A), dd.dd_from_f64(B))
    exact = A @ B
    assert rel_err(dd.dd_to_f64(C), exact) < 1e-13


def test_matmul_batched(rng):
    A = rng.standard_normal((5, 32, 48))
    B = rng.standard_normal((5, 48, 16))
    C = dd.dd_matmul(dd.dd_from_f64(A), dd.dd_from_f64(B))
    exact = A @ B
    assert rel_err(dd.dd_to_f64(C), exact) < 1e-13


def test_matmul_presliced(rng):
    # static-operand fast path: the transform-matrix use case
    M = rng.standard_normal((64, 64))
    X = rng.standard_normal((64, 24))
    planes, inv = dd.dd_slices_from_f64(M, axis=-1)
    import jax.numpy as jnp
    pl = (jnp.asarray(planes), jnp.asarray(inv))
    C = dd.dd_matmul(None, dd.dd_from_f64(X), a_planes=pl)
    assert rel_err(dd.dd_to_f64(C), M @ X) < 1e-13


def test_matmul_wild_scales(rng):
    # rows/cols spanning ~24 orders of magnitude: per-line exponent
    # normalization must keep relative precision. (Range is bounded by
    # f32's exponent field — dd(f32) covers ~1e+/-38 magnitudes, so
    # products stay below ~1e30 here; beyond that is a documented
    # limitation of f32-pair emulation, not a precision loss.)
    A = rng.standard_normal((40, 50)) * 10.0 ** rng.integers(-12, 12, (40, 1))
    B = rng.standard_normal((50, 30)) * 10.0 ** rng.integers(-12, 12, (1, 30))
    C = dd.dd_matmul(dd.dd_from_f64(A), dd.dd_from_f64(B))
    exact = A @ B
    # compare per-element relative to the row/col scale product
    scale = np.abs(A).max(axis=1)[:, None] * np.abs(B).max(axis=0)[None, :]
    err = np.abs(dd.dd_to_f64(C) - exact) / (scale * A.shape[1])
    assert err.max() < 1e-13


def test_matmul_under_jit(rng):
    import jax
    A = rng.standard_normal((32, 32))
    B = rng.standard_normal((32, 32))
    f = jax.jit(lambda a, b: dd.dd_matmul(a, b))
    C = f(dd.dd_from_f64(A), dd.dd_from_f64(B))
    assert rel_err(dd.dd_to_f64(C), A @ B) < 1e-13


def test_mass_conservation_grade(rng):
    # the KdV oracle scale: sum of ~1000 coefficients must be stable to
    # ~1e-14 relative over repeated add/sub cycles
    x = rng.standard_normal(1024)
    a = dd.dd_from_f64(x)
    b = a
    for _ in range(50):
        b = dd.dd_add(b, a)
        b = dd.dd_sub(b, a)
    assert rel_err(dd.dd_to_f64(b), x) < 1e-13
