"""
Unit tests for the curvilinear special-function libraries
(reference test pattern: dedalus/tests/test_transforms.py — fast-vs-matrix
oracles; here: quadrature-assembled operators vs analytic identities).
"""

import numpy as np
import pytest
import scipy.integrate

from dedalus_tpu.libraries import sphere, zernike, spin_intertwiners


# ---------------------------------------------------------------- SWSH

@pytest.mark.parametrize("m,s", [(0, 0), (3, 0), (2, 1), (-2, 1), (1, -2), (5, 2)])
def test_swsh_orthonormality(m, s):
    Lmax = 15
    z, w = sphere.quadrature(Lmax + 2)
    Y = sphere.harmonics(Lmax, m, s, z)
    G = (Y * w) @ Y.T
    assert np.allclose(G, np.eye(len(Y)), atol=1e-12)


@pytest.mark.parametrize("m,s", [(0, 0), (3, 0), (2, 1), (-4, 0), (1, -1)])
def test_swsh_laplacian_eigenvalues(m, s):
    """D+D- + D-D+ is diagonal with eigenvalues -(l(l+1) - s^2)."""
    Lmax = 15
    Dp = sphere.ladder_matrix(Lmax, m, s, +1)
    Dm = sphere.ladder_matrix(Lmax, m, s, -1)
    lap = (sphere.ladder_matrix(Lmax, m, s + 1, -1) @ Dp
           + sphere.ladder_matrix(Lmax, m, s - 1, +1) @ Dm)
    ells = sphere.ell_range(Lmax, m, s)
    expect = -(ells * (ells + 1) - s ** 2).astype(float)
    d = np.diag(lap)
    assert np.abs(lap - np.diag(d)).max() < 1e-10
    # the top mode can lose content to truncation when lmin shifts
    assert np.allclose(d[:-1], expect[:-1], atol=1e-9)


def test_swsh_ladder_structure():
    """D+ is diagonal in l with |entries| sqrt((l-s)(l+s+1)/2)."""
    Lmax, m, s = 15, 2, 0
    Dp = sphere.ladder_matrix(Lmax, m, s, +1)
    in_ells = sphere.ell_range(Lmax, m, s)
    out_ells = sphere.ell_range(Lmax, m, s + 1)
    for i, lo in enumerate(out_ells):
        for j, li in enumerate(in_ells):
            v = Dp[i, j]
            if lo == li:
                assert abs(abs(v) - np.sqrt((li - s) * (li + s + 1) / 2)) < 1e-10
            else:
                assert abs(v) < 1e-10


def test_swsh_cos_matrix():
    """cos(theta) multiplication reproduces grid-space multiplication."""
    Lmax, m, s = 12, 1, 0
    z, w = sphere.quadrature(Lmax + 2)
    Y = sphere.harmonics(Lmax, m, s, z)
    rng = np.random.default_rng(0)
    c = rng.standard_normal(len(Y))
    f = c @ Y
    C = sphere.cos_matrix(Lmax, m, s)
    cf = (Y * w) @ (z * f)
    assert np.allclose((C @ c)[:-1], cf[:-1], atol=1e-11)


@pytest.mark.parametrize("m", [0, 1, 3])
@pytest.mark.parametrize("s_in,s_out", [(0, 1), (0, -1), (1, 0), (-1, 0),
                                        (1, 2), (-1, -2)])
def test_swsh_sin_matrix(m, s_in, s_out):
    """sin(theta) spin-mixing multiplication reproduces grid-space
    multiplication (the meridional ez-coupling half), with the |dl| <= 1
    band structure."""
    Lmax = 12
    z, w = sphere.quadrature(Lmax + 3)
    Yin = sphere.harmonics(Lmax, m, s_in, z)
    Yout = sphere.harmonics(Lmax, m, s_out, z)
    if not len(Yin) or not len(Yout):
        pytest.skip("empty spin space at this (m, s)")
    rng = np.random.default_rng(1)
    c = rng.standard_normal(len(Yin))
    f = c @ Yin
    M = sphere.sin_matrix(Lmax, m, s_out, s_in)
    proj = (Yout * w) @ (np.sqrt(1 - z * z) * f)
    # the top degree couples past the truncation; compare below it
    assert np.allclose((M @ c)[:-1], proj[:-1], atol=1e-11)
    # band structure: |l_out - l_in| <= 1
    l_out = np.arange(sphere.lmin(m, s_out), Lmax + 1)
    l_in = np.arange(sphere.lmin(m, s_in), Lmax + 1)
    outside = np.abs(l_out[:, None] - l_in[None, :]) > 1
    assert np.abs(M[outside]).max() < 1e-13


def test_sphere_sin_stack_alignment():
    """SphereBasis.sin_stack aligns per-m blocks at each spin's l_min."""
    import dedalus_tpu.public as d3
    cs = d3.S2Coordinates("phi", "theta")
    basis = d3.SphereBasis(cs, shape=(8, 8), dtype=np.float64)
    stack = basis.sin_stack(1, 0)
    ms = basis.group_m()
    for g, m in enumerate(ms):
        M = sphere.sin_matrix(basis.Lmax, int(m), 1, 0)
        r0 = basis._lmin(int(m), 1)
        c0 = basis._lmin(int(m), 0)
        block = stack[g, r0:r0 + M.shape[0], c0:c0 + M.shape[1]]
        assert np.allclose(block, M)
        # nothing outside the aligned block
        total = np.abs(stack[g]).sum()
        assert np.isclose(total, np.abs(M).sum())


def test_swsh_transform_roundtrip():
    Lmax, m, s = 20, 3, 1
    F = sphere.forward_matrix(Lmax, m, s)
    B = sphere.backward_matrix(Lmax, m, s)
    assert np.allclose(F @ B, np.eye(F.shape[0]), atol=1e-11)


# ---------------------------------------------------------------- Zernike

@pytest.mark.parametrize("dim", [2, 3])
@pytest.mark.parametrize("k,l", [(0, 0), (0, 1), (0, 3), (1, 2), (2, 5)])
def test_zernike_orthonormality(dim, k, l):
    N = 12
    z, w = zernike.quadrature(dim, N + 4, k)
    Q = zernike.polynomials(dim, N, k, l, z)
    G = (Q * w) @ Q.T
    assert np.allclose(G, np.eye(N), atol=1e-11)


@pytest.mark.parametrize("dim", [2, 3])
def test_zernike_ladders_on_explicit_function(dim):
    """D+- of f = r^2(1-r^2) (an l=2 function) vs analytic results."""
    N, mu = 10, 2
    z0, w0 = zernike.quadrature(dim, N + 6, 0)
    r0 = np.sqrt((1 + z0) / 2)
    c = (zernike.polynomials(dim, N, 0, 2, z0) * w0) @ (r0**2 * (1 - r0**2))
    z1, w1 = zernike.quadrature(dim, N + 6, 1)
    r1 = np.sqrt((1 + z1) / 2)
    df = 2 * r1 - 4 * r1 ** 3
    f_over_r = r1 - r1 ** 3
    Dp = zernike.ladder_matrix(dim, N, 0, 2, 3, mu, +1)
    cg = (zernike.polynomials(dim, N, 1, 3, z1) * w1) @ ((df - mu * f_over_r) / np.sqrt(2))
    assert np.allclose(Dp @ c, cg, atol=1e-11)
    Dm = zernike.ladder_matrix(dim, N, 0, 2, 1, mu, -1)
    ch = (zernike.polynomials(dim, N, 1, 1, z1) * w1) @ ((df + mu * f_over_r) / np.sqrt(2))
    assert np.allclose(Dm @ c, ch, atol=1e-11)


@pytest.mark.parametrize("dim", [2, 3])
def test_zernike_conversion_and_integration(dim):
    N = 10
    z0, w0 = zernike.quadrature(dim, N + 6, 0)
    r0 = np.sqrt((1 + z0) / 2)
    f = r0 ** 2 * (1 - r0 ** 2)
    c = (zernike.polynomials(dim, N, 0, 2, z0) * w0) @ f
    # conversion k: 0 -> 1
    z1, w1 = zernike.quadrature(dim, N + 6, 1)
    r1 = np.sqrt((1 + z1) / 2)
    c1 = (zernike.polynomials(dim, N, 1, 2, z1) * w1) @ (r1**2 * (1 - r1**2))
    C = zernike.conversion_matrix(dim, N, 0, 2)
    assert np.allclose(C @ c, c1, atol=1e-11)
    # integration against r^{dim-1} dr
    I = zernike.integration_row(dim, N, 0, 2)
    val = scipy.integrate.quad(lambda r: r**2 * (1 - r**2) * r**(dim - 1), 0, 1)[0]
    assert np.allclose(I @ c, val, atol=1e-12)


def test_zernike_odd_l_integration_exact():
    N = 8
    zb, wb = zernike.quadrature(3, 20, 1)
    rb = np.sqrt((1 + zb) / 2)
    cb = (zernike.polynomials(3, N, 1, 3, zb) * wb) @ (rb**3 * (1 - rb**2))
    I = zernike.integration_row(3, N, 1, 3)
    val = scipy.integrate.quad(lambda r: r**3 * (1 - r**2) * r**2, 0, 1)[0]
    assert np.allclose(I @ cb, val, atol=1e-12)


# ---------------------------------------------------------------- intertwiners

@pytest.mark.parametrize("rank", [1, 2])
def test_intertwiner_orthogonality(rank):
    for ell in range(rank, 6):
        Q = spin_intertwiners.regularity_to_spin(ell, rank)
        assert np.allclose(Q @ Q.T, np.eye(3 ** rank), atol=1e-12)


@pytest.mark.parametrize("rank", [1, 2])
def test_intertwiner_low_ell_restriction(rank):
    for ell in range(rank):
        Q = spin_intertwiners.regularity_to_spin(ell, rank)
        v = spin_intertwiners.valid_regularities(ell, rank)
        assert np.allclose(Q.T @ Q, np.diag(v.astype(float)), atol=1e-12)
