"""
Continuous batching (service/batching.py): concurrent same-spec run
requests coalesced into one vmapped ensemble micro-batch, with
member-level fault isolation proven BITWISE — every surviving member's
served result must equal a direct in-process solve of the same request,
under every injected fault:

  * the batched-vs-solo bit-identity matrix (SBDF2 + RK222, diffusion +
    Rayleigh-Benard), batch sizes > 1, with zero post-warmup retraces;
  * late join at a block boundary (deterministic: the joiner is
    submitted only after the anchor's first progress frame proves the
    batch is in flight);
  * per-member deadline skew: one member deadline-stops at a boundary
    with a durable validated checkpoint while its batchmate completes;
  * a NaN-poisoned member (the request's own chaos block) detaching
    with a structured `health` error, blast radius zero;
  * a mid-batch vanished client detaching under ON_CLIENT_DROP=abort;
  * a wedged batch (hang chaos) abandoned by the watchdog with its
    surviving members REQUEUED and re-served by the replacement
    executor — the rolling-batch replay;
  * occupancy telemetry: per-batch member/join/detach accounting in the
    `serving` stats block, and the `report` CLI rendering of it.

Each fault is followed by a healthy request asserted bit-identical to a
direct solve (the daemon survives). In-process daemons throughout (no
subprocess JAX import tax); covered by the conftest hard watchdog via
the `batching` marker.
"""

import contextlib
import io
import json
import pathlib
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from dedalus_tpu.service import protocol
from dedalus_tpu.service.client import ServiceClient
from dedalus_tpu.service.server import SolverService
from dedalus_tpu.service.protocol import ServiceError
from dedalus_tpu.tools import chaos as chaos_mod
from dedalus_tpu.tools import resilience as res_mod
from dedalus_tpu.tools import retrace as retrace_mod

REPO = pathlib.Path(__file__).parent.parent

pytestmark = [pytest.mark.batching, pytest.mark.service, pytest.mark.chaos]

SIZE = 32
DT = 1e-3
STEPS = 40
DIFF = {"problem": "diffusion", "params": {"size": SIZE}}
DIFF_RK = {"problem": "diffusion", "params": {"size": SIZE,
                                              "scheme": "RK222"}}
RB = {"problem": "rayleigh_benard", "params": {"Nx": 32, "Nz": 8}}

_x = np.linspace(0, 2 * np.pi, SIZE, endpoint=False)


def diff_ics(k=3, amp=0.2):
    return {"u": ("g", np.sin(k * _x)), "a": ("g", amp * np.cos(_x))}


def rb_ics(seed=1):
    rng = np.random.default_rng(seed)
    return {"b": ("g", 1e-3 * rng.standard_normal((32, 8)))}


_references = {}


def direct_reference(spec, ics, dt, steps):
    """The direct in-process solve a served member must bit-match:
    builder + IC install + `steps` x solver.step — exactly the solo
    served execution (test_service.py established served == direct)."""
    key = json.dumps([spec, sorted(ics), dt, steps], sort_keys=True,
                     default=str)
    ics_key = (key, tuple(np.asarray(v[1]).tobytes() for v in
                          ics.values()))
    if ics_key not in _references:
        solver = protocol.resolve_builder(spec)()
        SolverService._install_ics(solver, ics)
        for _ in range(steps):
            solver.step(dt)
        _references[ics_key] = {
            v.name: np.asarray(v.coeff_data()).copy()
            for v in solver.state}
    return _references[ics_key]


@contextlib.contextmanager
def batch_service(**kw):
    """In-process batching daemon: serve_forever on a thread with real
    sockets, reader threads, the batching executor, and the watchdog."""
    kw.setdefault("batching_enabled", True)
    kw.setdefault("batch_max", 4)
    kw.setdefault("batch_window", 0.1)
    kw.setdefault("chaos_enabled", True)
    # the retrace sentinel is process-global and accumulates across the
    # whole pytest run; the zero-retraces-across-join/detach assertions
    # below are about THIS daemon's fleet programs (same reset
    # discipline as tests/test_ensemble.py)
    retrace_mod.sentinel.reset()
    svc = SolverService(port=0, **kw)
    thread = threading.Thread(target=svc.serve_forever,
                              kwargs={"ready_stream": io.StringIO()},
                              daemon=True)
    thread.start()
    deadline = time.monotonic() + 30
    while svc.started_ts is None:
        if time.monotonic() > deadline:
            raise RuntimeError("in-process batch daemon did not come up")
        time.sleep(0.01)
    try:
        yield svc
    finally:
        svc.request_drain("test teardown")
        thread.join(timeout=60)
        assert not thread.is_alive(), "batch daemon failed to drain"


@pytest.fixture(scope="module")
def daemon(tmp_path_factory):
    """The shared batching daemon most tests aim at (sequential faults
    against one long-lived process IS the survival claim)."""
    sink = str(tmp_path_factory.mktemp("batching") / "served.jsonl")
    with batch_service(sink=sink) as svc:
        svc.sink_path = sink
        yield svc


def concurrent_runs(svc, requests, stagger=0.0):
    """Fire len(requests) client runs concurrently (optionally
    staggered); returns results/errors in submission order. Each request
    is a kwargs dict for ServiceClient.run."""
    out = [None] * len(requests)

    def one(i, kw):
        client = ServiceClient(port=svc.port, timeout=300)
        try:
            out[i] = client.run(**kw)
        except (ServiceError, OSError) as exc:
            out[i] = exc

    threads = []
    for i, kw in enumerate(requests):
        thread = threading.Thread(target=one, args=(i, kw), daemon=True)
        threads.append(thread)
        thread.start()
        if stagger and i + 1 < len(requests):
            time.sleep(stagger)
    for thread in threads:
        thread.join(timeout=300)
    assert all(r is not None for r in out), "a storm client hung"
    return out


def assert_healthy(svc, tag):
    """Post-fault acceptance bar: a fresh request served bit-identically
    to the direct in-process solve."""
    client = ServiceClient(port=svc.port, timeout=300)
    result = client.run(DIFF, ics=diff_ics(), dt=DT, stop_iteration=STEPS)
    ref = direct_reference(DIFF, diff_ics(), DT, STEPS)
    assert result.result["stopped_by"] == "completed"
    assert np.array_equal(result.fields["u"][1], ref["u"]), \
        f"post-{tag} served result differs from the direct solve"


# -------------------------------------------------- bit-identity matrix

@pytest.mark.parametrize("spec,make_ics,dt,steps,direct_exact", [
    (DIFF, lambda i: diff_ics(k=2 + i, amp=0.1 * (i + 1)), DT, STEPS,
     True),
    (DIFF_RK, lambda i: diff_ics(k=2 + i, amp=0.1 * (i + 1)), DT, STEPS,
     True),
    # the 2-D flagship: the vmapped fleet program and the solo step
    # program are DIFFERENT XLA executables whose FMA contraction can
    # differ at the ulp level, so batched-vs-direct is tolerance-checked;
    # batched-vs-solo-SERVED (same daemon, same compiled fleet program,
    # batch of one) is still exact below
    (RB, lambda i: rb_ics(seed=i + 1), 1e-3, 12, False),
], ids=["diffusion-SBDF2", "diffusion-RK222", "rb-RK222"])
def test_batched_vs_solo_bit_identity(daemon, spec, make_ics, dt, steps,
                                      direct_exact):
    """The acceptance bar, per member: a request served in a batch of N
    is BIT-identical to the same request served ALONE on the daemon
    (member trajectories are invariant to batch composition — vmap lanes
    never mix, membership is a value operand, and solo serving runs the
    same compiled fleet program as a batch of one). Both scheme families
    (the multistep path exercises the cohort ramp), the 2-D flagship
    included; the diffusion cases additionally bit-match a DIRECT
    in-process solve, with zero post-warmup retraces."""
    members = 3
    requests = [dict(spec=spec, ics=make_ics(i), dt=dt,
                     stop_iteration=steps) for i in range(members)]
    # solo-served references: each request alone = a batch of one
    solo = []
    client = ServiceClient(port=daemon.port, timeout=300)
    for kw in requests:
        r = client.run(**kw)
        assert (r.ack or {}).get("batch"), "solo request not fleet-served"
        solo.append({name: arr for name, (_l, arr) in r.fields.items()})
    results = concurrent_runs(daemon, requests)
    batch_ids = set()
    for i, r in enumerate(results):
        assert not isinstance(r, Exception), r
        assert r.result["stopped_by"] == "completed"
        assert r.result["iteration"] == steps
        batch = (r.ack or {}).get("batch")
        assert batch, "request was not served batched"
        batch_ids.add(batch["id"])
        ref = direct_reference(spec, requests[i]["ics"], dt, steps)
        for name, (layout, arr) in r.fields.items():
            assert layout == "c"
            assert np.array_equal(arr, solo[i][name]), \
                ("batched != solo served", spec, i, name,
                 np.max(np.abs(arr - solo[i][name])))
            if direct_exact:
                assert np.array_equal(arr, ref[name]), \
                    (spec, i, name, np.max(np.abs(arr - ref[name])))
            else:
                assert np.allclose(arr, ref[name], atol=1e-10), \
                    (spec, i, name, np.max(np.abs(arr - ref[name])))
        record = r.record
        assert record is not None
        assert record["serving"]["batch"]["seat"] == batch["seat"]
        assert record["retraces_post_warmup"] == 0
    # the three concurrent requests shared at most two batches (the
    # anchor's batch plus, in the worst submission race, one follow-up)
    assert len(batch_ids) <= 2, batch_ids


# -------------------------------------------------------- late join

def test_late_join_at_block_boundary(daemon):
    """A request submitted while a batch is mid-flight joins it at a
    block boundary (ack says late_join) and both members bit-match their
    solo runs — the joiner's multistep ramp replays with the anchor
    frozen."""
    anchor_steps = 600
    in_flight = threading.Event()
    anchor_out = {}

    def anchor():
        client = ServiceClient(port=daemon.port, timeout=300)
        anchor_out["r"] = client.run(
            DIFF, ics=diff_ics(k=3, amp=0.2), dt=DT,
            stop_iteration=anchor_steps, progress_every=5,
            on_progress=lambda f: in_flight.set())

    thread = threading.Thread(target=anchor, daemon=True)
    thread.start()
    assert in_flight.wait(60), "anchor produced no progress frame"
    client = ServiceClient(port=daemon.port, timeout=300)
    joiner = client.run(DIFF, ics=diff_ics(k=5, amp=0.7), dt=DT,
                        stop_iteration=STEPS)
    thread.join(timeout=300)
    anchor_r = anchor_out["r"]
    jbatch = (joiner.ack or {}).get("batch")
    abatch = (anchor_r.ack or {}).get("batch")
    assert jbatch and jbatch["late_join"], jbatch
    assert jbatch["id"] == abatch["id"]
    ref_a = direct_reference(DIFF, diff_ics(k=3, amp=0.2), DT,
                             anchor_steps)
    ref_j = direct_reference(DIFF, diff_ics(k=5, amp=0.7), DT, STEPS)
    assert np.array_equal(anchor_r.fields["u"][1], ref_a["u"])
    assert np.array_equal(joiner.fields["u"][1], ref_j["u"])
    assert joiner.record["retraces_post_warmup"] == 0
    assert anchor_r.result["iteration"] == anchor_steps
    assert joiner.result["iteration"] == STEPS


# ------------------------------------------------- per-member deadlines

def test_member_deadline_stops_at_boundary_with_checkpoint(
        daemon, tmp_path):
    """Deadline skew across one batch: the short-deadline member stops
    gracefully at a block boundary (stopped_by=deadline-exceeded) with a
    durable validated checkpoint, while its batchmate completes
    bit-identically — blast radius zero."""
    ckpt = tmp_path / "member_ckpt"
    survivor_ics = diff_ics(k=4, amp=0.3)
    doomed = dict(spec=DIFF, ics=diff_ics(k=2, amp=0.1), dt=DT,
                  stop_iteration=500000, deadline_sec=1.5,
                  checkpoint=str(ckpt))
    survivor = dict(spec=DIFF, ics=survivor_ics, dt=DT,
                    stop_iteration=STEPS)
    results = concurrent_runs(daemon, [doomed, survivor], stagger=0.02)
    doomed_r, survivor_r = results
    assert not isinstance(doomed_r, Exception), doomed_r
    assert doomed_r.result["stopped_by"] == "deadline-exceeded"
    assert 0 < doomed_r.result["iteration"] < 500000
    assert doomed_r.serving["deadline_sec"] == 1.5
    # the durable per-member checkpoint validates (solo resume format)
    sets = sorted(ckpt.glob("*.h5"))
    assert sets, "deadline stop wrote no durable checkpoint"
    n_valid, reason = res_mod.validate_checkpoint(sets[-1])
    assert n_valid >= 1, reason
    assert not isinstance(survivor_r, Exception), survivor_r
    assert survivor_r.result["stopped_by"] == "completed"
    ref = direct_reference(DIFF, survivor_ics, DT, STEPS)
    assert np.array_equal(survivor_r.fields["u"][1], ref["u"])
    assert daemon.deadline_exceeded >= 1
    assert_healthy(daemon, "member-deadline")


# ---------------------------------------------------- divergent member

def test_nan_member_detaches_blast_radius_zero(daemon):
    """The batch-targeted nan_member: one request's own chaos block
    poisons ITS member mid-batch; the per-member probe detaches it with
    a structured `health` error at the next boundary while the clean
    member's result stays bit-identical."""
    before = daemon.batcher.detached.get("health", 0)
    poisoned = dict(spec=DIFF, ics=diff_ics(k=2, amp=0.1), dt=DT,
                    stop_iteration=400,
                    chaos={"nan_field": "u", "nan_iteration": 16})
    clean_ics = diff_ics(k=5, amp=0.5)
    clean = dict(spec=DIFF, ics=clean_ics, dt=DT, stop_iteration=STEPS)
    results = concurrent_runs(daemon, [poisoned, clean], stagger=0.02)
    poisoned_r, clean_r = results
    assert isinstance(poisoned_r, ServiceError), poisoned_r
    assert poisoned_r.code == "health"
    assert not isinstance(clean_r, Exception), clean_r
    ref = direct_reference(DIFF, clean_ics, DT, STEPS)
    assert np.array_equal(clean_r.fields["u"][1], ref["u"])
    assert clean_r.record["retraces_post_warmup"] == 0
    assert daemon.batcher.detached.get("health", 0) == before + 1
    # a malformed chaos block is a structured bad-spec at admission —
    # never a mid-batch blowup that could take co-tenants down
    with pytest.raises(ServiceError) as err:
        ServiceClient(port=daemon.port, timeout=60).run(
            DIFF, ics=diff_ics(), dt=DT, stop_iteration=STEPS,
            chaos={"hang_iteration": 5})
    assert err.value.code == "bad-spec"
    assert_healthy(daemon, "nan-member")


# ---------------------------------------------------- client vanishes

def test_vanished_client_detaches_member_mid_batch():
    """ON_CLIENT_DROP=abort: a member whose client vanished mid-stream
    detaches at the next boundary; the rest of the batch is
    unperturbed."""
    with batch_service(on_client_drop="abort") as svc:
        anchor_steps = 800
        in_flight = threading.Event()
        anchor_out = {}

        def anchor():
            client = ServiceClient(port=svc.port, timeout=300)
            anchor_out["r"] = client.run(
                DIFF, ics=diff_ics(k=3, amp=0.2), dt=DT,
                stop_iteration=anchor_steps, progress_every=5,
                on_progress=lambda f: in_flight.set())

        thread = threading.Thread(target=anchor, daemon=True)
        thread.start()
        assert in_flight.wait(60), "anchor produced no progress frame"
        # a real socket client that joins the batch, reads its ack, then
        # disappears without a word — mid-batch
        header = {"kind": "run", "spec": DIFF, "dt": DT,
                  "stop_iteration": 400, "progress_every": 5}
        payload = protocol.encode_fields(
            {k: v for k, v in diff_ics(k=5, amp=0.7).items()})
        frames = chaos_mod.vanish_client(svc.port, header,
                                         payload=payload, read_frames=1)
        assert frames and frames[0]["kind"] == "ack"
        assert frames[0]["batch"]["late_join"]
        thread.join(timeout=300)
        anchor_r = anchor_out["r"]
        ref = direct_reference(DIFF, diff_ics(k=3, amp=0.2), DT,
                               anchor_steps)
        assert np.array_equal(anchor_r.fields["u"][1], ref["u"])
        deadline = time.monotonic() + 30
        while daemon_drops(svc) < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert daemon_drops(svc) >= 1
        assert svc.batcher.detached.get("client-drop", 0) >= 1
        assert_healthy(svc, "vanished-client")


def daemon_drops(svc):
    return svc.client_drops


def test_sigkilled_client_mid_batch():
    """The OS-level client vanish: a real `submit` subprocess joins a
    live batch, streams a progress frame, and is SIGKILLed — the daemon
    detaches that member (abort) while the anchor keeps stepping, a
    healthy request joins the STILL-RUNNING batch bit-identically, and
    the drain then stops the anchor gracefully."""
    with batch_service(on_client_drop="abort") as svc:
        in_flight = threading.Event()
        anchor_out = {}

        def anchor():
            client = ServiceClient(port=svc.port, timeout=600)
            try:
                anchor_out["r"] = client.run(
                    DIFF, ics=diff_ics(k=3, amp=0.2), dt=DT,
                    stop_iteration=2_000_000, progress_every=50,
                    on_progress=lambda f: in_flight.set())
            except (ServiceError, OSError) as exc:
                anchor_out["r"] = exc

        thread = threading.Thread(target=anchor, daemon=True)
        thread.start()
        assert in_flight.wait(60), "anchor produced no progress frame"
        proc = chaos_mod.sigkill_client(svc.port, DIFF, DT, 400,
                                        after_progress_frames=1)
        assert proc.returncode is not None
        deadline = time.monotonic() + 60
        while svc.batcher.detached.get("client-drop", 0) < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert svc.batcher.detached.get("client-drop", 0) >= 1
        assert svc.client_drops >= 1
        # the batch survived the kill: a fresh request joins it live and
        # still bit-matches the direct solve
        assert_healthy(svc, "sigkilled-client")
        # stop the anchor through the drain path: a batched member's
        # graceful drain stop, result frame included
        svc.request_drain("test stop")
        thread.join(timeout=120)
        anchor_r = anchor_out["r"]
        assert not isinstance(anchor_r, Exception), anchor_r
        assert anchor_r.result["stopped_by"] == "test stop"
        assert 0 < anchor_r.result["iteration"] < 2_000_000


# ------------------------------------------------- watchdog batch replay

def test_watchdog_abandons_batch_and_replays_survivors(tmp_path):
    """A wedged batch (hang chaos out-sleeping WATCHDOG_SEC at a block
    boundary) is abandoned: postmortem recorded, pool entry + fleet
    quarantined, executor replaced — and every surviving member's
    request is REQUEUED and served to completion by the replacement,
    bit-identical to solo. The clients never see the fault."""
    sink = tmp_path / "watchdog.jsonl"
    # watchdog_sec must out-wait every LEGITIMATE stall in the replay
    # path, not just the prewarmed batch's boundaries: the fire
    # quarantines the pool entry, so the requeued survivors pay a fresh
    # fleet build + compile on the replacement executor — under
    # full-suite load on a small box that rebuild has been observed to
    # outlast a 6 s watchdog, producing a SECOND (spurious) fire and
    # failing the exactly-once asserts below. 10 s rides above the
    # loaded rebuild; hang_sec rides above the whole measured window so
    # `wall < hang_sec` still proves the replacement (not the hang
    # releasing) is what finished the runs.
    hang_sec = 60.0
    with batch_service(watchdog_sec=10.0, sink=str(sink)) as svc:
        # prewarm: the first batched request pays the fleet build +
        # compile under the (generous) watchdog, so the test's hang is
        # the only stall in the measured window
        client = ServiceClient(port=svc.port, timeout=300)
        client.run(DIFF, ics=diff_ics(), dt=DT, stop_iteration=STEPS)
        hang_ics = diff_ics(k=2, amp=0.1)
        mate_ics = diff_ics(k=5, amp=0.6)
        hanging = dict(spec=DIFF, ics=hang_ics, dt=DT,
                       stop_iteration=200,
                       chaos={"hang_iteration": 50,
                              "hang_sec": hang_sec})
        mate = dict(spec=DIFF, ics=mate_ics, dt=DT, stop_iteration=200)
        t0 = time.monotonic()
        results = concurrent_runs(svc, [hanging, mate], stagger=0.02)
        wall = time.monotonic() - t0
        for kw, r in zip((hanging, mate), results):
            assert not isinstance(r, Exception), r
            assert r.result["stopped_by"] == "completed"
            ref = direct_reference(DIFF, kw["ics"], DT, 200)
            assert np.array_equal(r.fields["u"][1], ref["u"]), \
                "replayed member differs from solo"
        # served by the replacement BEFORE the hang released the stale
        # executor: the fire + requeue is what finished the runs
        assert wall < hang_sec, wall
        assert svc.watchdog_fires == 1
        assert svc.batcher.detached.get("watchdog", 0) >= 2
        records = [json.loads(line) for line in
                   sink.read_text().splitlines()]
        posts = [r for r in records
                 if r.get("kind") == "watchdog_postmortem"]
        assert len(posts) == 1 and posts[0]["batch"] is True
        assert len(posts[0]["requeued"]) == 2
        assert_healthy(svc, "batch-watchdog")


# ----------------------------------------------- occupancy + report CLI

def test_occupancy_telemetry_and_report(daemon):
    """The `serving.batching` stats block carries per-batch occupancy
    (members/joins/detaches per batch), and the `report` CLI renders the
    batching lines plus the per-record batch column."""
    stats = ServiceClient(port=daemon.port, timeout=60).stats()
    batching = stats["serving"]["batching"]
    assert batching["enabled"] and batching["batches"] >= 1
    assert batching["members"] >= 2
    assert batching["recent_batches"]
    event = batching["recent_batches"][-1]
    assert {"batch_id", "members", "late_joins", "blocks", "peak_active",
            "detached"} <= set(event)
    # the sink's member records carry the batch column; report renders
    # both them and a service_stats line with the occupancy block
    stats_record = dict(stats, kind="service_stats")
    with open(daemon.sink_path, "a") as f:
        f.write(json.dumps(stats_record) + "\n")
    proc = subprocess.run(
        [sys.executable, "-m", "dedalus_tpu", "report",
         str(daemon.sink_path)],
        capture_output=True, text=True, cwd=REPO,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
             "HOME": str(REPO)}, timeout=300)
    assert proc.returncode == 0, proc.stderr
    assert "batching:" in proc.stdout
    assert "batch=batch-" in proc.stdout


# ------------------------------------------------- end-to-end trace

def test_batched_request_exports_one_linked_trace(tmp_path):
    """The observability acceptance bar: one request served through the
    --batch daemon produces exactly ONE exported trace record linking
    accept -> queue -> pool acquire -> batch seat -> >= 1 batch block ->
    result send, every span sharing the request's trace_id (joined to
    the step record by `serving.trace_id`), the resolved plan stamped on
    the root, and the Chrome export structurally valid trace-event
    JSON."""
    from dedalus_tpu.tools import tracing
    sink = tmp_path / "served.jsonl"
    was_on = tracing.enabled()
    old_sink = tracing.trace_sink()
    try:
        # trace_file="" = bare `serve --trace`: records ride the sink
        with batch_service(sink=str(sink), trace_file="") as svc:
            client = ServiceClient(port=svc.port, timeout=300)
            result = client.run(DIFF, ics=diff_ics(), dt=DT,
                                stop_iteration=STEPS)
        assert result.result["stopped_by"] == "completed"
        trace_id = result.record["serving"]["trace_id"]
        assert trace_id
    finally:
        tracing.disable()
        tracing._sink = old_sink
        if was_on:
            tracing.enable()

    records = tracing.load_trace_records(str(sink))
    mine = [r for r in records if r["trace_id"] == trace_id]
    assert len(mine) == 1, \
        f"expected ONE trace for the request, got {len(mine)}"
    rec = mine[0]
    spans = rec["spans"]
    assert all(s["trace_id"] == trace_id for s in spans)
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    for required in ("request", "accept", "queue", "pool_acquire",
                     "batch/seat", "batch/block", "result_send"):
        assert required in by_name, \
            f"span {required!r} missing from the request trace"
    assert len(by_name["batch/block"]) >= 1
    # lifecycle linkage: every non-root span parents (transitively)
    # under the request root
    root = by_name["request"][0]
    assert root["parent_id"] is None
    ids = {s["span_id"]: s for s in spans}
    for s in spans:
        node = s
        for _ in range(len(spans)):
            if node["parent_id"] is None:
                break
            node = ids[node["parent_id"]]
        assert node["span_id"] == root["span_id"], \
            f"span {s['name']!r} not linked under the request root"
    # provenance rides the root span
    assert root["attrs"]["plan"]["plan_version"] == 1
    # Chrome export validity (loadable in Perfetto / chrome://tracing)
    doc = tracing.chrome_trace_from_records([rec])
    doc = json.loads(json.dumps(doc))
    assert doc["traceEvents"]
    for ev in doc["traceEvents"]:
        assert ev["ph"] == "X"
        assert isinstance(ev["ts"], float) and ev["dur"] >= 0
        assert "trace_id" in ev["args"]
