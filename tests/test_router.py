"""
Replica-fleet router (service/router.py + service/fleet.py): the fault
matrix behind the fleet's robustness claim — every injected replica
fault must be INVISIBLE to clients (one ack, one bit-identical result,
diffusion64 SBDF2) and followed by a healthy bit-identical request
proving the fleet recovered:

  * spec-digest affinity: same-spec traffic lands on the same replica,
    and the consistent-hash ring's membership-change property holds
    (losing a replica only remaps the keys it owned);
  * mid-run replica SIGKILL → failover re-dispatch (same request id,
    next ring replica), then a supervisor restart with backoff;
  * wedged replica (hang chaos): the REPLICA's watchdog abandons the
    run, the router treats `watchdog-timeout` as a replica fault and
    re-dispatches with the chaos block STRIPPED (fire-once);
  * slow replica (SIGSTOP/SIGCONT stall below the wedge threshold):
    the deadline-derived forward timeout fails the run over without a
    restart;
  * rolling drain (SIGTERM): the draining replica leaves the ring
    without dropping in-flight work and returns via the crash path;
  * network partition (endpoint repointed at a dead port): failover on
    connection refusal, full recovery on heal();
  * degradation discipline: whole-fleet saturation aggregates the
    MINIMUM `retry_after_sec` hint into one structured `overloaded`
    error; a fully-faulted fleet answers `fleet-unavailable` (which
    the client treats as retryable);
  * client retry hardening: `retry_after_sec` hints FLOOR the capped
    jittered exponential schedule instead of replacing it, under a
    configurable attempt budget — asserted against a scripted fake
    server with captured sleeps;
  * observability: the `router`/`fleet` stats block, its Prometheus
    exposition under `validate_exposition`, and the `report` CLI
    rendering of router stats + the `router_scaling` bench row.

Scripted fake replicas cover the protocol/degradation matrix cheaply
(tier-1); the spawned-fleet tests (real `serve` subprocesses, real
SIGKILL/SIGSTOP) carry the `slow` marker like the other process-heavy
drills and run in the extended sweep and CI stage that invokes them
explicitly.
"""

import contextlib
import json
import pathlib
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from dedalus_tpu.service import promexport, protocol
from dedalus_tpu.service import client as client_mod
from dedalus_tpu.service.client import ServiceClient
from dedalus_tpu.service.protocol import ServiceError
from dedalus_tpu.service.router import (RouterService, ring_order,
                                        ring_points, route_digest)
from dedalus_tpu.tools import chaos as chaos_mod

REPO = pathlib.Path(__file__).parent.parent

pytestmark = [pytest.mark.service, pytest.mark.chaos]

SIZE = 64
DT = 1e-3
STEPS = 40
SPEC = {"problem": "diffusion", "params": {"size": SIZE,
                                           "scheme": "SBDF2"}}
SPEC_B = {"problem": "diffusion", "params": {"size": 48,
                                             "scheme": "SBDF2"}}


def diff_ics(size=SIZE, k=3, amp=0.2):
    x = np.linspace(0, 2 * np.pi, size, endpoint=False)
    return {"u": ("g", np.sin(k * x)), "a": ("g", amp * np.cos(x))}


_references = {}


def direct_reference(spec, ics, dt, steps):
    """The direct in-process solve a routed run must bit-match (same
    discipline as tests/test_service_batching.py)."""
    from dedalus_tpu.service.server import SolverService
    key = json.dumps([spec, sorted(ics), dt, steps], sort_keys=True,
                     default=str)
    ics_key = (key, tuple(np.asarray(v[1]).tobytes()
                          for v in ics.values()))
    if ics_key not in _references:
        solver = protocol.resolve_builder(spec)()
        SolverService._install_ics(solver, ics)
        for _ in range(steps):
            solver.step(dt)
        _references[ics_key] = {
            v.name: np.asarray(v.coeff_data()).copy()
            for v in solver.state}
    return _references[ics_key]


# ------------------------------------------------------------- hash ring

class TestRing:
    def test_order_is_a_stable_permutation(self):
        points = ring_points(["r0", "r1", "r2", "r3"], vnodes=64)
        order = ring_order(points, "some-digest")
        assert sorted(order) == ["r0", "r1", "r2", "r3"]
        assert order == ring_order(points, "some-digest")

    def test_distribution_is_roughly_balanced(self):
        points = ring_points(["r0", "r1", "r2", "r3"], vnodes=64)
        owners = [ring_order(points, f"digest{i}")[0]
                  for i in range(2000)]
        for name in ("r0", "r1", "r2", "r3"):
            share = owners.count(name) / 2000
            assert 0.10 < share < 0.45, (name, share)

    def test_membership_change_only_remaps_owned_keys(self):
        full = ring_points(["r0", "r1", "r2", "r3"], vnodes=64)
        reduced = ring_points(["r0", "r1", "r2"], vnodes=64)
        moved = owned = 0
        for i in range(2000):
            before = ring_order(full, f"digest{i}")[0]
            owned += before == "r3"
            moved += before != ring_order(reduced, f"digest{i}")[0]
        assert moved == owned   # the consistent-hash property, exactly

    def test_route_digest_is_the_pool_key(self):
        # the router must route by the SAME digest the warm pool keys
        # on, or affinity silently evaporates
        assert route_digest({"spec": SPEC}) == protocol.spec_digest(SPEC)
        # malformed specs still route deterministically (the replica
        # owns the structured bad-spec reply)
        bad = {"spec": {"problem": 7}}
        assert route_digest(bad) == route_digest(bad)


# ---------------------------------------------------------- fake fleet
#
# Scripted replicas speaking just enough protocol to exercise every
# router verdict deterministically, with zero JAX: behaviors are
# per-connection scripts consumed in order (the last repeats).

class FakeReplica:
    def __init__(self, *script):
        self.script = list(script) or ["serve"]
        self.runs = 0
        self.listener = socket.create_server(("127.0.0.1", 0))
        self.port = self.listener.getsockname()[1]
        self._lock = threading.Lock()
        threading.Thread(target=self._loop, daemon=True).start()

    def _next(self):
        with self._lock:
            self.runs += 1
            if len(self.script) > 1:
                return self.script.pop(0)
            return self.script[0]

    def _loop(self):
        while True:
            try:
                conn, _ = self.listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        with conn:
            rfile = conn.makefile("rb")
            wfile = conn.makefile("wb")
            try:
                header = protocol.recv_header(rfile)
                if header is None:
                    return
                protocol.recv_payload(rfile, header)
                kind = header.get("kind")
                if kind == "stats":
                    protocol.send_frame(wfile, {"kind": "stats",
                                                "faults": {}})
                    return
                if kind == "shutdown":
                    protocol.send_frame(wfile, {"kind": "ok"})
                    return
                if kind != "run":
                    return
                step = self._next()
                if step == "die":
                    return             # EOF before any frame
                if step == "die_after_ack":
                    protocol.send_frame(wfile, {"kind": "ack",
                                                "pool_verdict": "hit"})
                    return             # EOF mid-stream
                if step.startswith("refuse:"):
                    _, code, hint = step.split(":")
                    protocol.send_frame(
                        wfile, {"kind": "error", "code": code,
                                "message": f"scripted {code}",
                                "retry_after_sec": float(hint)})
                    return
                if step == "watchdog":
                    protocol.send_frame(wfile, {"kind": "ack",
                                                "pool_verdict": "hit"})
                    protocol.send_frame(
                        wfile, {"kind": "error",
                                "code": "watchdog-timeout",
                                "message": "scripted wedge"})
                    return
                if step == "bad-spec":
                    protocol.send_frame(
                        wfile, {"kind": "error", "code": "bad-spec",
                                "message": "scripted rejection"})
                    return
                # "serve": ack + one result frame echoing the request id
                protocol.send_frame(wfile, {"kind": "ack",
                                            "pool_verdict": "hit"})
                protocol.send_frame(
                    wfile, {"kind": "result", "iteration": 1,
                            "sim_time": DT, "stopped_by": "scripted",
                            "id": header.get("id")})
            except (protocol.ProtocolError, OSError):
                pass

    def close(self):
        try:
            self.listener.close()
        except OSError:
            pass


@contextlib.contextmanager
def fake_router(*scripts, **router_kw):
    """A RouterService fronting one FakeReplica per script tuple."""
    fakes = [FakeReplica(*script) for script in scripts]
    router_kw.setdefault("probe_sec", 0.2)
    router_kw.setdefault("probe_timeout", 1.0)
    router = RouterService(
        attach=[f"127.0.0.1:{f.port}" for f in fakes], **router_kw)
    thread = threading.Thread(target=router.serve_forever, daemon=True)
    thread.start()
    deadline = time.monotonic() + 30
    while router.port == 0 or router._listener is None:
        if time.monotonic() > deadline:
            raise RuntimeError("fake router did not come up")
        time.sleep(0.01)
    try:
        yield router, fakes
    finally:
        router.request_drain("test teardown")
        thread.join(timeout=30)
        assert not thread.is_alive(), "router failed to drain"
        for fake in fakes:
            fake.close()


def fake_named(router, fakes, name):
    """The FakeReplica adopted under fleet name `name`."""
    port = router.fleet.endpoint(name)[1]
    return next(f for f in fakes if f.port == port)


def primary_fake(router, fakes, spec=SPEC):
    """(primary_name, its FakeReplica) for `spec` — script THIS one
    with the fault so the failover target stays healthy."""
    name = router.route_of(spec)
    return name, fake_named(router, fakes, name)


def wait_for(predicate, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.25)
    raise AssertionError(f"timed out waiting for {what}")


class TestFakeFleetRouting:
    def test_failover_suppresses_duplicate_ack(self):
        # primary acks then dies mid-stream; the sibling serves. The
        # client must see ONE ack and one result carrying failover
        # provenance.
        with fake_router(("serve",), ("serve",)) as (router, fakes):
            primary, dying = primary_fake(router, fakes)
            dying.script[:] = ["die_after_ack", "serve"]
            client = ServiceClient(port=router.port, timeout=20)
            result = client.run(SPEC, dt=DT, stop_iteration=1)
            assert result.result["replica"] != primary
            assert result.result["failover"] == 1
            assert result.ack is not None
            wait_for(lambda: router.stats()["router"]["failovers"] == 1,
                     5, "failover accounting")
            stats = router.stats()["router"]
            assert stats["forwarded"] == 1
            assert stats["replica_faults"] == 1
            assert stats["acks_suppressed"] == 1

    def test_request_id_is_pinned_across_failover(self):
        # the id minted by the router on attempt 1 must reach the
        # failover target unchanged — it IS the idempotent replay key
        with fake_router(("serve",), ("serve",)) as (router, fakes):
            _, dying = primary_fake(router, fakes)
            dying.script[:] = ["die_after_ack", "serve"]
            client = ServiceClient(port=router.port, timeout=20)
            result = client.run(SPEC, dt=DT, stop_iteration=1)
            assert result.result["id"]   # echoed by the serving fake
            assert result.result["failover"] == 1

    def test_watchdog_timeout_is_a_replica_fault(self):
        with fake_router(("serve",), ("serve",)) as (router, fakes):
            _, wedged = primary_fake(router, fakes)
            wedged.script[:] = ["watchdog", "serve"]
            client = ServiceClient(port=router.port, timeout=20)
            result = client.run(SPEC, dt=DT, stop_iteration=1)
            assert result.result["failover"] == 1
            wait_for(lambda: router.stats()["router"]["replica_faults"]
                     == 1, 5, "fault accounting")

    def test_refusal_fails_over_without_breaker_penalty(self):
        with fake_router(("serve",), ("serve",)) as (router, fakes):
            _, refusing = primary_fake(router, fakes)
            refusing.script[:] = ["refuse:draining:3.0", "serve"]
            client = ServiceClient(port=router.port, timeout=20)
            result = client.run(SPEC, dt=DT, stop_iteration=1)
            assert result.result["failover"] == 1
            stats = router.stats()["router"]
            assert stats["refusals"] == 1
            assert stats["breaker"]["opens"] == 0

    def test_saturation_aggregates_min_retry_after(self):
        with fake_router(("refuse:overloaded:11.0",),
                         ("refuse:overloaded:7.0",)) as (router, fakes):
            client = ServiceClient(port=router.port, timeout=20)
            with pytest.raises(ServiceError) as err:
                client.run(SPEC, dt=DT, stop_iteration=1)
            assert err.value.code == "overloaded"
            assert err.value.retry_after_sec == 7.0
            assert router.stats()["router"]["shed"] == 1

    def test_fully_faulted_fleet_is_fleet_unavailable(self):
        with fake_router(("die",), ("die",)) as (router, fakes):
            client = ServiceClient(port=router.port, timeout=20)
            with pytest.raises(ServiceError) as err:
                client.run(SPEC, dt=DT, stop_iteration=1)
            assert err.value.code == "fleet-unavailable"
            assert err.value.retry_after_sec > 0
        # the client-side retry machinery must classify it transient:
        # the supervisor is restarting the fleet behind that error
        assert "fleet-unavailable" in client_mod._RETRYABLE_CODES

    def test_deterministic_errors_relay_verbatim(self):
        # bad-spec is the CLIENT's fault: no failover, no breaker
        # penalty, the replica's structured answer passes through
        with fake_router(("bad-spec",), ("bad-spec",)) as (router,
                                                           fakes):
            client = ServiceClient(port=router.port, timeout=20)
            with pytest.raises(ServiceError) as err:
                client.run(SPEC, dt=DT, stop_iteration=1)
            assert err.value.code == "bad-spec"
            stats = router.stats()["router"]
            assert stats["replica_faults"] == 0
            assert sum(f.runs for f in fakes) == 1

    def test_draining_router_refuses_new_runs(self):
        with fake_router(("serve",)) as (router, fakes):
            client = ServiceClient(port=router.port, timeout=20)
            client.run(SPEC, dt=DT, stop_iteration=1)
            router._draining = "test drain"
            with pytest.raises(ServiceError) as err:
                client.run(SPEC, dt=DT, stop_iteration=1)
            assert err.value.code == "draining"
            router._draining = None   # let teardown drain normally


# ------------------------------------------------- client retry backoff

@contextlib.contextmanager
def fake_server_client(script, sleeps, **client_kw):
    """A ServiceClient aimed at ONE FakeReplica, with time.sleep in the
    client module captured instead of slept."""
    fake = FakeReplica(*script)
    real_sleep = client_mod.time.sleep
    client_mod.time.sleep = lambda s: sleeps.append(s)
    try:
        yield ServiceClient(port=fake.port, **client_kw), fake
    finally:
        client_mod.time.sleep = real_sleep
        fake.close()


class TestClientRetryHardening:
    def test_hint_floors_the_exponential_schedule(self):
        # a 5s hint must not be outrun by the young exponential
        # schedule (0.2, 0.4, ...): every delay sits at >= jittered 5s
        sleeps = []
        with fake_server_client(["refuse:overloaded:5.0",
                                 "refuse:overloaded:5.0", "serve"],
                                sleeps, retries=3, retry_base_delay=0.2,
                                retry_max_delay=8.0) as (client, fake):
            result = client.run(SPEC, dt=DT, stop_iteration=1)
            assert result.result is not None
        assert len(sleeps) == 2
        for delay in sleeps:
            assert 5.0 * 0.75 - 1e-9 <= delay <= 8.0 * 1.25

    def test_tiny_hint_keeps_exponential_growth(self):
        # a near-zero hint must NOT collapse backoff growth — that is
        # the retry-storm metronome this hardening removes
        sleeps = []
        with fake_server_client(["refuse:overloaded:0.01",
                                 "refuse:overloaded:0.01", "serve"],
                                sleeps, retries=3, retry_base_delay=0.2,
                                retry_max_delay=8.0) as (client, fake):
            client.run(SPEC, dt=DT, stop_iteration=1)
        assert len(sleeps) == 2
        assert sleeps[0] <= 0.2 * 1.25 + 1e-9
        assert 0.4 * 0.75 - 1e-9 <= sleeps[1] <= 0.4 * 1.25 + 1e-9

    def test_retry_max_delay_caps_the_hint(self):
        sleeps = []
        with fake_server_client(["refuse:overloaded:300.0", "serve"],
                                sleeps, retries=2, retry_base_delay=0.2,
                                retry_max_delay=2.0) as (client, fake):
            client.run(SPEC, dt=DT, stop_iteration=1)
        assert len(sleeps) == 1
        assert sleeps[0] <= 2.0 * 1.25 + 1e-9

    def test_attempt_budget_is_configurable_and_finite(self):
        sleeps = []
        with fake_server_client(["refuse:overloaded:0.1"], sleeps,
                                retries=2,
                                retry_base_delay=0.01) as (client, fake):
            with pytest.raises(ServiceError) as err:
                client.run(SPEC, dt=DT, stop_iteration=1)
            assert err.value.code == "overloaded"
            assert fake.runs == 3       # retries + 1, not one more
        assert len(sleeps) == 2

    def test_deterministic_errors_are_not_retried(self):
        sleeps = []
        with fake_server_client(["bad-spec"], sleeps,
                                retries=5,
                                retry_base_delay=0.01) as (client, fake):
            with pytest.raises(ServiceError) as err:
                client.run(SPEC, dt=DT, stop_iteration=1)
            assert err.value.code == "bad-spec"
            assert fake.runs == 1
        assert sleeps == []

    def test_submit_cli_exposes_retry_max_delay(self):
        parser = client_mod.build_parser()
        args = parser.parse_args(["--port", "1", "--retry", "3",
                                  "--retry-max-delay", "4.5"])
        assert args.retry_max_delay == 4.5


# --------------------------------------------------------- observability

def _router_stats_fixture():
    """A RouterService.stats()-shaped dict (kept in sync by the live
    scrape test below, which validates the real surface end to end)."""
    return {
        "kind": "stats", "role": "router", "port": 9999,
        "uptime_sec": 12.5, "draining": None,
        "router": {
            "forwarded": 7, "failovers": 2, "shed": 1, "refusals": 3,
            "replica_faults": 2, "client_drops": 1,
            "acks_suppressed": 2,
            "error_codes": {"overloaded": 1, "bad-spec": 2},
            "forward": {"p50_ms": 2.0, "p95_ms": 11.0, "count": 7},
            "ring_members": ["r0", "r1"],
            "breaker": {"opens": 1, "closes": 0, "fastfails": 4,
                        "open": ["r2"]},
        },
        "fleet": {
            "restarts": 3, "crashes": 2, "wedges": 1,
            "watchdog_fires": 1,
            "states": {"up": 2, "down": 1},
            "spawned": 3, "attached": 0,
            "replicas": {
                "r0": {"name": "r0", "state": "up", "draining": False,
                       "restarts": 0, "port": 1001, "pid": 11},
                "r1": {"name": "r1", "state": "up", "draining": True,
                       "restarts": 1, "port": 1002, "pid": 12},
                "r2": {"name": "r2", "state": "down", "draining": False,
                       "restarts": 2, "port": 1003, "pid": None},
            },
        },
    }


class TestRouterObservability:
    def test_drain_flushes_router_stats_to_sink(self, tmp_path):
        # the CLI's --sink contract: one `router_stats` record at drain,
        # written AFTER fleet.stop so it carries the final fleet tallies
        sink = tmp_path / "router.jsonl"
        with fake_router(("serve",), ("serve",), sink=str(sink)):
            pass
        records = [json.loads(line)
                   for line in sink.read_text().splitlines()]
        assert len(records) == 1
        rec = records[0]
        assert rec["kind"] == "router_stats"
        assert "ts" in rec
        assert rec["draining"] == "test teardown"
        assert rec["fleet"]["attached"] == 2
        assert set(rec["router"]) >= {"forwarded", "failovers",
                                      "error_codes", "forward"}

    def test_render_router_stats_exposition(self):
        hists = {"router_forward_seconds":
                 ({"counts": {0: 3, 5: 4}, "total": 7, "sum": 0.42},
                  "Wall seconds per routed run.")}
        text = promexport.render_router_stats(_router_stats_fixture(),
                                              hists)
        families = promexport.validate_exposition(text)
        lines = text.splitlines()
        assert "dedalus_router_up 1" in lines
        assert "dedalus_router_forwarded_total 7" in lines
        assert "dedalus_router_failovers_total 2" in lines
        assert "dedalus_router_ring_members 2" in lines
        assert ('dedalus_router_errors_by_code_total{code="overloaded"}'
                " 1") in lines
        assert 'dedalus_fleet_replicas{state="up"} 2' in lines
        assert "dedalus_fleet_restarts_total 3" in lines
        assert 'dedalus_fleet_replica_up{replica="r0"} 1' in lines
        assert 'dedalus_fleet_replica_up{replica="r2"} 0' in lines
        assert ('dedalus_fleet_replica_draining{replica="r1"} 1'
                in lines)
        assert families["dedalus_router_forward_seconds"]["type"] \
            == "histogram"

    def test_live_router_prom_scrape(self):
        with fake_router(("serve",), ("serve",)) as (router, fakes):
            client = ServiceClient(port=router.port, timeout=20)
            client.run(SPEC, dt=DT, stop_iteration=1)
            text = client.stats_prom()
        families = promexport.validate_exposition(text)
        assert "dedalus_router_up" in families
        assert "dedalus_router_forwarded_total" in families
        assert "dedalus_fleet_replica_up" in families
        assert "dedalus_router_forward_seconds" in families

    def test_router_stats_frame_shape(self):
        with fake_router(("serve",), ("serve",)) as (router, fakes):
            client = ServiceClient(port=router.port, timeout=20)
            stats = client.stats()
            assert stats["role"] == "router"
            assert sorted(stats["router"]["ring_members"]) \
                == ["a0", "a1"]
            fleet = stats["fleet"]
            assert fleet["attached"] == 2 and fleet["spawned"] == 0
            assert set(fleet["replicas"]) == {"a0", "a1"}

    def test_report_renders_router_stats_and_scaling_row(self, tmp_path):
        sink = tmp_path / "router.jsonl"
        rows = [
            dict(_router_stats_fixture(), kind="router_stats"),
            {"config": "router_scaling", "benchmark": "router",
             "metric": "router_requests_per_sec_4r", "value": 4.2,
             "unit": "requests/sec", "backend": "cpu", "ts": 1e9,
             "requests_speedup_4v1": 3.1,
             "replica_requests_per_sec": {"1": 1.35, "2": 2.4,
                                          "4": 4.2},
             "specs": 6, "clients": 6, "forward_overhead_p50_ms": 2.2},
        ]
        sink.write_text("".join(json.dumps(r) + "\n" for r in rows))
        out = subprocess.run(
            [sys.executable, "-m", "dedalus_tpu", "report", str(sink)],
            capture_output=True, text=True, cwd=str(REPO), timeout=120)
        assert out.returncode == 0, out.stderr
        assert "(router) 7 forwarded, 2 failovers" in out.stdout
        assert "fleet: 3 restarts, 2 crashes, 1 wedges" in out.stdout
        assert "3.1x at 4 replicas" in out.stdout
        assert "forward overhead p50 2.2 ms" in out.stdout


# ------------------------------------------------- spawned-fleet matrix
#
# Real `serve` subprocess replicas under the supervisor, real signals.
# One module-scoped fleet; faults land sequentially against it (the
# long-lived survival claim), and EVERY fault test ends with a healthy
# bit-identical request through the router.

@pytest.fixture(scope="module")
def fleet_router(tmp_path_factory):
    from conftest import register_daemon
    workdir = str(tmp_path_factory.mktemp("fleet"))
    router = RouterService(
        replicas=2, workdir=workdir,
        replica_args=["--pool-size", "4", "--chaos",
                      "--watchdog-sec", "6", "--queue-depth", "8"],
        probe_sec=0.25, probe_timeout=1.0, wedge_misses=8,
        backoff_base=0.25, breaker_failures=3, breaker_cooloff=2.0)
    router.fleet.on_spawn = register_daemon
    thread = threading.Thread(target=router.serve_forever, daemon=True)
    thread.start()
    deadline = time.monotonic() + 120
    while router.port == 0 or router._listener is None:
        if time.monotonic() > deadline:
            raise RuntimeError("fleet router did not come up")
        time.sleep(0.1)
    wait_for(lambda: len(router.fleet.routable()) == 2, 30,
             "both replicas routable")
    yield router
    router.request_drain("test teardown")
    thread.join(timeout=90)
    assert not thread.is_alive(), "fleet router failed to drain"


def routed_client(router, **kw):
    kw.setdefault("timeout", 300)
    return ServiceClient(port=router.port, **kw)


def assert_healthy(router, tag):
    """The post-fault invariant: a fresh routed run still bit-matches
    the direct in-process solve."""
    result = routed_client(router).run(SPEC, ics=diff_ics(), dt=DT,
                                       stop_iteration=STEPS)
    reference = direct_reference(SPEC, diff_ics(), DT, STEPS)
    for name, expected in reference.items():
        served = result.fields[name][1]
        assert np.array_equal(served, expected), \
            f"{tag}: served {name} diverged from the direct solve"


def prewarm(router, spec, size):
    """Build `spec` warm on EVERY replica (direct, bypassing the ring)
    so failover targets serve from a warm pool deterministically."""
    for name in router.fleet.routable():
        host, port = router.fleet.endpoint(name)
        ServiceClient(host=host, port=port, timeout=300).run(
            spec, ics=diff_ics(size), dt=DT, stop_iteration=2)


@pytest.mark.slow
class TestSpawnedFleet:
    def test_affinity_and_bit_identity(self, fleet_router):
        router = fleet_router
        client = routed_client(router)
        first = client.run(SPEC, ics=diff_ics(), dt=DT,
                           stop_iteration=STEPS)
        again = client.run(SPEC, ics=diff_ics(), dt=DT,
                           stop_iteration=STEPS)
        # same spec -> same replica (the warm-pool affinity claim),
        # and the router's preview agrees with where it actually went
        assert first.result["replica"] == again.result["replica"]
        assert first.result["replica"] == router.route_of(SPEC)
        reference = direct_reference(SPEC, diff_ics(), DT, STEPS)
        for name, expected in reference.items():
            assert np.array_equal(again.fields[name][1], expected)
        other = routed_client(router).run(SPEC_B, ics=diff_ics(48),
                                          dt=DT, stop_iteration=STEPS)
        ref_b = direct_reference(SPEC_B, diff_ics(48), DT, STEPS)
        for name, expected in ref_b.items():
            assert np.array_equal(other.fields[name][1], expected)

    def test_replica_sigkill_mid_run_fails_over(self, fleet_router):
        router = fleet_router
        prewarm(router, SPEC, SIZE)
        primary = router.route_of(SPEC)
        baseline_restarts = {s["name"]: s["restarts"]
                             for s in router.fleet.snapshot()}
        in_flight = threading.Event()
        out = {}

        def go():
            out["result"] = routed_client(router).run(
                SPEC, ics=diff_ics(), dt=DT, stop_iteration=12000,
                progress_every=10,
                on_progress=lambda f: in_flight.set())

        worker = threading.Thread(target=go)
        worker.start()
        assert in_flight.wait(120), "run never streamed progress"
        chaos_mod.kill_replica(router.fleet, primary)
        worker.join(timeout=150)
        assert not worker.is_alive(), "failover never completed"
        result = out["result"]
        assert result.result["replica"] != primary
        assert result.result["failover"] >= 1
        reference = direct_reference(SPEC, diff_ics(), DT, 12000)
        for name, expected in reference.items():
            assert np.array_equal(result.fields[name][1], expected), \
                f"failover result for {name} is not bit-identical"
        wait_for(lambda: any(
            s["name"] == primary and s["state"] == "up"
            and s["restarts"] == baseline_restarts[primary] + 1
            for s in router.fleet.snapshot()), 90,
            "supervisor restart of the killed replica")
        assert_healthy(router, "after SIGKILL failover")

    def test_wedged_run_watchdog_fires_over(self, fleet_router):
        router = fleet_router
        prewarm(router, SPEC, SIZE)
        t0 = time.monotonic()
        result = routed_client(router).run(
            SPEC, ics=diff_ics(), dt=DT, stop_iteration=STEPS,
            chaos={"hang_iteration": 20, "hang_sec": 90})
        wall = time.monotonic() - t0
        # served by FAILOVER (chaos stripped fire-once), not by waiting
        # out the 90s hang on the wedged replica
        assert wall < 60, f"hang released instead of failing over " \
                          f"({wall:.1f}s)"
        assert result.result["failover"] >= 1
        reference = direct_reference(SPEC, diff_ics(), DT, STEPS)
        for name, expected in reference.items():
            assert np.array_equal(result.fields[name][1], expected)
        # the wedged replica healed ITSELF (watchdog postmortem +
        # worker replacement); the supervisor observes, not restarts
        wait_for(lambda: router.fleet.stats()["watchdog_fires"] >= 1,
                 30, "fleet-level watchdog postmortem accounting")
        assert_healthy(router, "after watchdog failover")

    def test_slow_replica_transient_stall_is_waited_out(self, fleet_router):
        # a stall SHORTER than the deadline-derived read timeout is not a
        # fault: the router waits, the primary serves after resuming, and
        # neither a failover hop nor a restart is spent on it
        router = fleet_router
        prewarm(router, SPEC, SIZE)
        primary = router.route_of(SPEC)
        restarts_before = {s["name"]: s["restarts"]
                           for s in router.fleet.snapshot()}
        chaos_mod.slow_replica_sec(router.fleet, primary, 4.0)
        result = routed_client(router).run(
            SPEC, ics=diff_ics(), dt=DT, stop_iteration=STEPS,
            deadline_sec=30.0)
        assert result.result["replica"] == primary
        assert result.result.get("failover", 0) == 0
        reference = direct_reference(SPEC, diff_ics(), DT, STEPS)
        for name, expected in reference.items():
            assert np.array_equal(result.fields[name][1], expected)
        # a stall below the wedge threshold must NOT cost a restart
        wait_for(lambda: any(s["name"] == primary and s["state"] == "up"
                             and s["misses"] == 0
                             for s in router.fleet.snapshot()), 60,
                 "stalled replica shedding its probe misses")
        assert {s["name"]: s["restarts"]
                for s in router.fleet.snapshot()} == restarts_before
        assert_healthy(router, "after transient stall")

    def test_slow_replica_past_deadline_fails_over(self, fleet_router):
        # a stall LONGER than the deadline-derived read timeout
        # (min(forward_timeout, deadline_sec + 2)) is a replica fault:
        # the forward times out, the router re-dispatches to the next
        # ring replica, and the client still sees one bit-exact result
        router = fleet_router
        prewarm(router, SPEC, SIZE)
        primary = router.route_of(SPEC)
        chaos_mod.slow_replica_sec(router.fleet, primary, 30.0)
        t0 = time.monotonic()
        result = routed_client(router).run(
            SPEC, ics=diff_ics(), dt=DT, stop_iteration=STEPS,
            deadline_sec=6.0)
        wall = time.monotonic() - t0
        assert result.result["replica"] != primary
        assert result.result["failover"] >= 1
        # served by the failover target while the primary was still
        # stalled — not by waiting the stall out
        assert wall < 25, wall
        reference = direct_reference(SPEC, diff_ics(), DT, STEPS)
        for name, expected in reference.items():
            assert np.array_equal(result.fields[name][1], expected)
        # a 30 s unresponsive replica IS a wedge by the supervisor's
        # contract — let it restart (or resume) and rejoin before the
        # next test
        wait_for(lambda: any(s["name"] == primary and s["state"] == "up"
                             and s["misses"] == 0
                             for s in router.fleet.snapshot()), 90,
                 "stalled primary rejoining the ring")
        assert_healthy(router, "after slow-replica failover")

    def test_rolling_drain_is_invisible(self, fleet_router):
        router = fleet_router
        prewarm(router, SPEC, SIZE)
        primary = router.route_of(SPEC)
        restarts_before = {s["name"]: s["restarts"]
                           for s in router.fleet.snapshot()}
        import os
        os.kill(router.fleet.pid_of(primary), signal.SIGTERM)
        # the drain (or the exit behind it) must push the primary off
        # the ring; requests keep landing on the sibling meanwhile
        wait_for(lambda: router.route_of(SPEC) != primary, 30,
                 "draining replica leaving the ring")
        result = routed_client(router).run(SPEC, ics=diff_ics(), dt=DT,
                                           stop_iteration=STEPS)
        assert result.result["replica"] != primary
        reference = direct_reference(SPEC, diff_ics(), DT, STEPS)
        for name, expected in reference.items():
            assert np.array_equal(result.fields[name][1], expected)
        # rolling restart: the drained replica exits and comes back
        wait_for(lambda: any(
            s["name"] == primary and s["state"] == "up"
            and s["restarts"] == restarts_before[primary] + 1
            for s in router.fleet.snapshot()), 120,
            "drained replica restarting")
        assert_healthy(router, "after rolling drain")

    def test_partition_heals(self, fleet_router):
        router = fleet_router
        prewarm(router, SPEC, SIZE)
        primary = router.route_of(SPEC)
        heal = chaos_mod.partition(router.fleet, primary)
        try:
            result = routed_client(router).run(
                SPEC, ics=diff_ics(), dt=DT, stop_iteration=STEPS)
            assert result.result["replica"] != primary
            assert result.result["failover"] >= 1
        finally:
            heal()
        wait_for(lambda: any(s["name"] == primary and s["state"] == "up"
                             and s["misses"] == 0
                             for s in router.fleet.snapshot()), 60,
                 "partitioned replica recovering after heal")
        assert_healthy(router, "after partition heal")

    def test_wedge_replica_supervisor_restarts(self, fleet_router):
        router = fleet_router
        victim = router.fleet.routable()[0]
        restarts_before = {s["name"]: s["restarts"]
                           for s in router.fleet.snapshot()}
        chaos_mod.wedge_replica(router.fleet, victim)
        wait_for(lambda: any(
            s["name"] == victim and s["state"] == "up"
            and s["restarts"] == restarts_before[victim] + 1
            for s in router.fleet.snapshot()), 150,
            "supervisor wedge detection + restart")
        assert router.fleet.stats()["wedges"] >= 1
        assert_healthy(router, "after wedge restart")
