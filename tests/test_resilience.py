"""
Resilient solve loop (tools/resilience.py) driven by the chaos harness
(tools/chaos.py): divergence -> rewind -> dt-backoff -> completion,
SIGTERM -> checkpoint -> resume round-trips (bitwise), transient-IO retry,
corrupted-checkpoint fallback, escalation semantics, and the
zero-overhead disabled path. Every recovery branch is exercised by a
deterministic injected fault — tier-1, CPU, no timing dependence.
"""

import json
import os
import pathlib
import signal
import subprocess
import sys

import numpy as np
import pytest

import dedalus_tpu.public as d3
from dedalus_tpu.tools import chaos as chaos_mod
from dedalus_tpu.tools import resilience as res_mod
from dedalus_tpu.tools.exceptions import CheckpointError, SolverHealthError

REPO = pathlib.Path(__file__).parent.parent

pytestmark = pytest.mark.chaos


def build_diffusion_solver(tmp_path, scheme="RK222", **solver_kw):
    """Small stable 1D heat IVP: recovery trivially succeeds once the
    injected fault is rewound past."""
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=32, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    problem = d3.IVP([u], namespace={"u": u, "lap": d3.lap})
    problem.add_equation("dt(u) - lap(u) = 0")
    kw = dict(health_cadence=1, warmup_iterations=2,
              enforce_real_cadence=0,
              postmortem_dir=str(tmp_path / "pm"))
    kw.update(solver_kw)
    solver = problem.build_solver(getattr(d3, scheme), **kw)
    x = dist.local_grid(xb)
    u["g"] = np.sin(3 * x)
    return solver, u


def build_blowup_solver(tmp_path, **solver_kw):
    """dt(s) = s*s, s0 = 2: diverges at ANY dt — rewinds cannot save it,
    so escalation paths are reachable deterministically."""
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=16, bounds=(0, 2 * np.pi))
    s = dist.Field(name="s", bases=xb)
    problem = d3.IVP([s], namespace={})
    problem.add_equation((d3.dt(s), s * s))
    kw = dict(health_cadence=1, warmup_iterations=2,
              postmortem_dir=str(tmp_path / "pm"))
    kw.update(solver_kw)
    solver = problem.build_solver(d3.SBDF1, **kw)
    s["g"] = 2.0
    return solver, s


# ------------------------------------------------------ rewind + backoff

def test_nan_divergence_rewind_recovers(tmp_path):
    """Injected NaN at iteration N: the loop rewinds to the last good
    snapshot, caps dt by the backoff factor, and runs to completion —
    with the recovery visible in the telemetry record."""
    solver, u = build_diffusion_solver(tmp_path)
    solver.stop_iteration = 30
    injector = chaos_mod.ChaosInjector(nan_field="u", nan_iteration=12)
    summary = solver.evolve_resilient(
        dt=1e-3, snapshot_cadence=5, max_retries=3, dt_backoff=0.5,
        retry_base_delay=0.0, chaos=injector)
    assert solver.iteration == 30
    assert np.all(np.isfinite(np.asarray(solver.X)))
    assert summary["stopped_by"] == "completed"
    assert summary["rewinds"] >= 1
    assert summary["retries"] >= 1
    assert [f["kind"] for f in injector.fired] == ["nan"]
    # the rewind went to a snapshot at or before the poisoned iteration
    lineage = summary["lineage"]
    assert lineage[0]["outcome"] == "rewound"
    assert lineage[0]["rewind_iteration"] <= 12
    assert lineage[0]["dt_limit"] == pytest.approx(5e-4)
    # counters + summary ride in the flushed telemetry record
    rec = solver.flush_metrics()
    assert rec["resilience"]["rewinds"] == summary["rewinds"]
    assert rec["counters"]["resilience/rewinds"] >= 1
    assert rec["counters"]["resilience/dt_backoffs"] >= 1
    # the postmortem of the poisoned attempt records the retry lineage
    pm_dirs = sorted((tmp_path / "pm").iterdir())
    assert pm_dirs
    from dedalus_tpu.tools.health import read_postmortem, format_postmortem
    record, _ = read_postmortem(pm_dirs[-1])
    text = "\n".join(format_postmortem(record))
    assert "resilience" in record or "retry" in text


def test_rb_nan_divergence_recovers_and_reports(tmp_path):
    """Acceptance: injected NaN divergence on the RB benchmark problem
    recovers automatically and the rewind/retry counts surface in the
    flushed record and in `python -m dedalus_tpu report`."""
    from dedalus_tpu.extras.bench_problems import build_rb_solver
    solver, b = build_rb_solver(32, 16, np.float32)
    solver.warmup_iterations = 2
    solver.health.cadence = 1
    solver.health.postmortem_dir = str(tmp_path / "pm")
    solver.stop_iteration = 20
    injector = chaos_mod.ChaosInjector(nan_field="b", nan_iteration=8)
    summary = solver.evolve_resilient(
        dt=0.01, snapshot_cadence=4, max_retries=3,
        retry_base_delay=0.0, chaos=injector)
    assert solver.iteration == 20
    assert np.all(np.isfinite(np.asarray(solver.X)))
    assert summary["rewinds"] >= 1
    rec = solver.flush_metrics()
    assert rec["resilience"]["rewinds"] >= 1
    # report CLI shows the resilience columns
    sink = tmp_path / "results.jsonl"
    with open(sink, "w") as f:
        f.write(json.dumps(rec) + "\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "dedalus_tpu", "report", str(sink)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr
    assert "resilience:" in proc.stdout
    assert "rewinds" in proc.stdout


def test_poisoned_snapshot_skipped(tmp_path):
    """A snapshot captured after the true onset but before detection is
    itself poisoned: the ring discards it and rewinds further."""
    solver, u = build_diffusion_solver(tmp_path)
    loop = res_mod.ResilientLoop(solver, dt=1e-3, snapshot_cadence=5,
                                 retry_base_delay=0.0,
                                 install_signal_handlers=False)
    loop._capture()
    good_iter = solver.iteration
    for _ in range(3):
        solver.step(1e-3)
    # capture a poisoned snapshot on top of the good anchor
    chaos_mod.ChaosInjector().poison_field(solver, "u")
    loop._capture()
    assert len(loop.ring) == 2
    solver.health.check()
    assert solver.health_error is not None
    loop._recover(solver.health_error)
    assert solver.iteration == good_iter
    assert np.all(np.isfinite(np.asarray(solver.X)))
    assert loop.rewinds == 1


def test_retry_budget_escalates(tmp_path):
    """max_retries consecutive failures escalate: the original structured
    error propagates and the lineage records the decision."""
    solver, s = build_blowup_solver(tmp_path)
    solver.health.max_abs_limit = 1e6
    solver.stop_iteration = 500
    with pytest.raises(SolverHealthError):
        solver.evolve_resilient(dt=1.0, snapshot_cadence=1000,
                                max_retries=0, retry_base_delay=0.0)
    loop = solver.resilience
    assert loop.lineage[-1]["outcome"] == "escalated: retry budget exhausted"
    rec = solver.flush_metrics()
    assert rec["resilience"]["retries"] == 1


def test_ring_exhaustion_escalates(tmp_path):
    """When every snapshot has been consumed (or poisoned), recovery
    escalates instead of rewinding to nothing."""
    solver, s = build_blowup_solver(tmp_path)
    solver.health.max_abs_limit = 1e6
    solver.stop_iteration = 500
    with pytest.raises(SolverHealthError):
        # one anchor snapshot, cadence too long to capture another:
        # failure 1 consumes the anchor, failure 2 finds an empty ring
        solver.evolve_resilient(dt=1.0, snapshot_cadence=1000,
                                max_retries=5, retry_base_delay=0.0)
    loop = solver.resilience
    assert loop.rewinds == 1
    assert loop.lineage[-1]["outcome"] == "escalated: no finite snapshot"


def test_postmortem_dirs_collision_proof(tmp_path):
    """Repeated dumps at the SAME iteration (a rewind-retry-fail cycle)
    never overwrite an earlier flight recording."""
    solver, s = build_blowup_solver(tmp_path)
    paths = {solver.health.dump_postmortem(f"attempt {i}")
             for i in range(3)}
    assert len(paths) == 3
    for p in paths:
        assert p.is_dir()


# ------------------------------------------- preemption + checkpointing

def test_sigterm_checkpoint_resume_roundtrip(tmp_path):
    """Acceptance: a SIGTERM mid-run produces a valid checkpoint; the
    resumed run restores sim_time/iteration/state exactly and finishes
    bitwise-identical to an uninterrupted reference run."""
    ckpt = tmp_path / "ckpt"
    # reference: 20 uninterrupted steps
    ref, _ = build_diffusion_solver(tmp_path, metrics=False)
    ref.stop_iteration = 20
    for _ in range(20):
        ref.step(1e-3)

    solver, u = build_diffusion_solver(tmp_path, metrics=False)
    solver.stop_iteration = 20
    injector = chaos_mod.ChaosInjector(sigterm_iteration=10)
    summary = solver.evolve_resilient(
        dt=1e-3, checkpoint_dir=ckpt, chaos=injector)
    assert summary["stopped_by"] == "SIGTERM"
    assert solver.iteration == 10
    assert [f["kind"] for f in injector.fired] == ["sigterm"]
    sets = sorted(ckpt.glob("*.h5"))
    assert sets, "no checkpoint written on SIGTERM"
    # the previous SIGTERM disposition was restored on loop exit
    assert signal.getsignal(signal.SIGTERM) is not None

    resumed, u2 = build_diffusion_solver(tmp_path, metrics=False)
    resumed.stop_iteration = 20
    summary2 = resumed.evolve_resilient(
        dt=1e-3, checkpoint_dir=ckpt, resume=True)
    assert summary2["resumed_from"]
    event = resumed.resilience.resume_event
    assert event["iteration"] == 10
    assert event["sim_time"] == solver.sim_time      # exact
    assert summary2["stopped_by"] == "completed"
    assert resumed.iteration == 20
    # bitwise: coefficient-layout checkpoints put no transform in the
    # restore path, so the resumed trajectory is the reference trajectory
    assert np.array_equal(np.asarray(resumed.X), np.asarray(ref.X))
    assert resumed.sim_time == ref.sim_time


def test_sigterm_during_divergence_writes_good_checkpoint(tmp_path):
    """Preemption landing on the same step as (undetected) divergence:
    the graceful stop probes the state, rewinds first, and writes the
    final checkpoint from the last GOOD state — never the poisoned one."""
    ckpt = tmp_path / "ckpt"
    solver, u = build_diffusion_solver(tmp_path)
    solver.stop_iteration = 30
    injector = chaos_mod.ChaosInjector(nan_field="u", nan_iteration=8,
                                       sigterm_iteration=8)
    summary = solver.evolve_resilient(
        dt=1e-3, snapshot_cadence=3, retry_base_delay=0.0,
        checkpoint_dir=ckpt, chaos=injector)
    assert summary["stopped_by"] == "SIGTERM"
    assert summary["rewinds"] == 1
    sets = sorted(ckpt.glob("*.h5"))
    assert sets, "no final checkpoint written"
    resumed, _ = build_diffusion_solver(tmp_path)
    event = res_mod.resume_latest(resumed, ckpt)
    assert event["iteration"] <= 8
    assert np.all(np.isfinite(np.asarray(resumed.X))), \
        "poisoned state leaked into the durable checkpoint"


def test_resume_restores_state_bitwise(tmp_path):
    """The restore itself is exact: X after resume equals X at the write,
    bit for bit, and the clocks match."""
    ckpt = tmp_path / "ckpt"
    solver, u = build_diffusion_solver(tmp_path, metrics=False)
    loop = res_mod.ResilientLoop(solver, dt=1e-3, checkpoint_dir=ckpt,
                                 install_signal_handlers=False)
    for _ in range(7):
        solver.step(1e-3)
    X_at_write = np.asarray(solver.X).copy()
    loop.write_checkpoint()
    solver2, u2 = build_diffusion_solver(tmp_path, metrics=False)
    event = res_mod.resume_latest(solver2, ckpt)
    assert event is not None and not event["fallbacks"]
    assert solver2.iteration == 7
    assert solver2.sim_time == solver.sim_time
    assert solver2.dt == solver.dt
    assert np.array_equal(np.asarray(solver2.X), X_at_write)


def test_corrupted_newest_checkpoint_falls_back(tmp_path):
    """Acceptance: a corrupted newest checkpoint is detected at resume
    and the previous write is used; with every set corrupted the failure
    is structured."""
    ckpt = tmp_path / "ckpt"
    solver, u = build_diffusion_solver(tmp_path, metrics=False)
    loop = res_mod.ResilientLoop(solver, dt=1e-3, checkpoint_dir=ckpt,
                                 install_signal_handlers=False)
    marks = {}
    for k in range(3):
        for _ in range(4):
            solver.step(1e-3)
        loop.write_checkpoint()
        marks[solver.iteration] = np.asarray(solver.X).copy()
    sets = sorted(ckpt.glob("*.h5"),
                  key=lambda p: int(p.stem.rsplit("_s", 1)[1]))
    assert len(sets) == 3
    chaos_mod.corrupt_checkpoint(sets[-1], mode="truncate")
    solver2, _ = build_diffusion_solver(tmp_path, metrics=False)
    event = res_mod.resume_latest(solver2, ckpt)
    assert event["path"] == str(sets[-2])
    assert len(event["fallbacks"]) == 1
    assert "unreadable" in event["fallbacks"][0]["reason"]
    assert solver2.iteration == 8
    assert np.array_equal(np.asarray(solver2.X), marks[8])
    # all sets corrupted: structured escalation naming the directory
    for p in sets[:-1]:
        chaos_mod.corrupt_checkpoint(p, mode="truncate")
    solver3, _ = build_diffusion_solver(tmp_path, metrics=False)
    with pytest.raises(CheckpointError) as excinfo:
        res_mod.resume_latest(solver3, ckpt)
    assert "no loadable checkpoint" in str(excinfo.value)
    # no checkpoints at all: a fresh start, not an error
    assert res_mod.resume_latest(solver3, tmp_path / "nowhere") is None


def test_transient_io_fault_retried(tmp_path):
    """The Nth checkpoint write raises a transient OSError: the retry
    policy absorbs it and the write lands."""
    ckpt = tmp_path / "ckpt"
    solver, u = build_diffusion_solver(tmp_path)
    solver.stop_iteration = 6
    injector = chaos_mod.ChaosInjector(fail_checkpoint_write=1)
    summary = solver.evolve_resilient(
        dt=1e-3, checkpoint_dir=ckpt, chaos=injector)
    assert summary["stopped_by"] == "completed"
    assert [f["kind"] for f in injector.fired] == ["io"]
    sets = sorted(ckpt.glob("*.h5"))
    assert sets, "checkpoint lost despite retry"
    n_valid, reason = res_mod.validate_checkpoint(sets[-1])
    assert n_valid == 1 and reason is None
    rec = solver.flush_metrics()
    assert rec["counters"]["resilience/io_retries"] >= 1
    assert rec["counters"]["resilience/checkpoints_written"] >= 1


# --------------------------------------- sharded + async checkpointing

def test_sharded_sigterm_checkpoint_resume_roundtrip(tmp_path):
    """The PR-4 SIGTERM acceptance, on the sharded format: preemption
    writes a manifest-committed sharded checkpoint, and the resumed run
    (format auto-detected) finishes bitwise-identical to an
    uninterrupted reference."""
    ckpt = tmp_path / "ckpt"
    ref, _ = build_diffusion_solver(tmp_path, metrics=False)
    ref.stop_iteration = 20
    for _ in range(20):
        ref.step(1e-3)

    solver, u = build_diffusion_solver(tmp_path, metrics=False)
    solver.stop_iteration = 20
    injector = chaos_mod.ChaosInjector(sigterm_iteration=10)
    summary = solver.evolve_resilient(
        dt=1e-3, checkpoint_dir=ckpt, checkpoint_format="sharded",
        chaos=injector)
    assert summary["stopped_by"] == "SIGTERM"
    assert summary["checkpoint"]["format"] == "sharded"
    from dedalus_tpu.tools import dcheckpoint as dc
    assert dc.list_checkpoints(ckpt), "no sharded checkpoint on SIGTERM"

    resumed, _ = build_diffusion_solver(tmp_path, metrics=False)
    resumed.stop_iteration = 20
    summary2 = resumed.evolve_resilient(
        dt=1e-3, checkpoint_dir=ckpt, checkpoint_format="sharded",
        resume=True)
    assert summary2["resumed_from"]
    event = resumed.resilience.resume_event
    assert event["format"] == "sharded"
    assert event["iteration"] == 10
    assert summary2["stopped_by"] == "completed"
    assert resumed.iteration == 20
    assert np.array_equal(np.asarray(resumed.X), np.asarray(ref.X))
    assert resumed.sim_time == ref.sim_time
    # the stall accounting and writer stats ride the summary block
    ck = summary2["checkpoint"]
    assert ck["format"] == "sharded" and ck["written"] >= 1
    assert ck["stall_sec"] > 0.0


def test_sharded_multistep_history_resumes_bitwise(tmp_path):
    """Multistep (SBDF2) history arrays ride the sharded checkpoint: a
    resume mid-ramp continues bitwise-identical to uninterrupted."""
    ckpt = tmp_path / "ckpt"
    ref, _ = build_diffusion_solver(tmp_path, scheme="SBDF2",
                                    metrics=False)
    for _ in range(20):
        ref.step(1e-3)
    solver, _ = build_diffusion_solver(tmp_path, scheme="SBDF2",
                                       metrics=False)
    solver.stop_iteration = 20
    injector = chaos_mod.ChaosInjector(sigterm_iteration=9)
    solver.evolve_resilient(dt=1e-3, checkpoint_dir=ckpt,
                            checkpoint_format="sharded", chaos=injector)
    resumed, _ = build_diffusion_solver(tmp_path, scheme="SBDF2",
                                        metrics=False)
    resumed.stop_iteration = 20
    resumed.evolve_resilient(dt=1e-3, checkpoint_dir=ckpt,
                             checkpoint_format="sharded", resume=True)
    assert resumed.iteration == 20
    assert np.array_equal(np.asarray(resumed.X), np.asarray(ref.X))


def test_async_periodic_checkpoints_durable_and_corrupt_fallback(tmp_path):
    """Async periodic sharded checkpoints: the loop's stall is submits
    only, everything lands durably by loop exit, and a silently
    corrupted newest checkpoint falls back to the previous one at
    resume."""
    ckpt = tmp_path / "ckpt"
    solver, u = build_diffusion_solver(tmp_path, metrics=False)
    solver.stop_iteration = 18
    summary = solver.evolve_resilient(
        dt=1e-3, checkpoint_dir=ckpt, checkpoint_format="sharded",
        checkpoint_async=True, checkpoint_iter=5)
    ck = summary["checkpoint"]
    assert ck["async"] is True
    assert ck["errors"] == 0
    assert ck["written"] >= 3      # periodic 5/10/15 + final 18
    from dedalus_tpu.tools import dcheckpoint as dc
    X18 = np.asarray(solver.X).copy()
    # newest (iteration 18) silently corrupted -> quarantine + the
    # retained previous checkpoint (iteration 15) used, steps replayed
    newest = dc.list_checkpoints(ckpt)[-1]
    chaos_mod.corrupt_shard(newest, mode="garbage")
    resumed, _ = build_diffusion_solver(tmp_path, metrics=False)
    resumed.stop_iteration = 18
    summary2 = resumed.evolve_resilient(
        dt=1e-3, checkpoint_dir=ckpt, checkpoint_format="sharded",
        resume=True)
    event = resumed.resilience.resume_event
    assert len(event["fallbacks"]) == 1
    assert event["iteration"] == 15
    assert resumed.iteration == 18
    assert np.array_equal(np.asarray(resumed.X), X18), \
        "resume-after-corruption did not reproduce the reference run"


def test_sharded_rejects_async_hdf5_and_dd(tmp_path):
    """Config validation is explicit: async needs the sharded format."""
    solver, u = build_diffusion_solver(tmp_path, metrics=False)
    with pytest.raises(ValueError, match="sharded"):
        res_mod.ResilientLoop(solver, dt=1e-3,
                              checkpoint_format="hdf5",
                              checkpoint_async=True,
                              install_signal_handlers=False)
    with pytest.raises(ValueError, match="hdf5"):
        res_mod.ResilientLoop(solver, dt=1e-3, checkpoint_format="zip",
                              install_signal_handlers=False)


# --------------------------------------------------------- SDC sentinel

def test_sdc_clean_run_replays_are_invisible(tmp_path):
    """With the sentinel armed and no fault, every check agrees and the
    trajectory is bitwise identical to a plain run — the re-executions
    are genuinely side-effect-free."""
    ref, _ = build_diffusion_solver(tmp_path, metrics=False)
    for _ in range(30):
        ref.step(1e-3)
    solver, u = build_diffusion_solver(tmp_path, metrics=False)
    solver.stop_iteration = 30
    summary = solver.evolve_resilient(dt=1e-3, sdc_cadence=5)
    assert summary["sdc_checks"] >= 5
    assert summary["sdc_detected"] == 0
    assert np.array_equal(np.asarray(solver.X), np.asarray(ref.X))


def test_sdc_detects_flip_bit_and_recovers_bitwise(tmp_path):
    """Acceptance: a chaos-flipped mantissa bit (finite, plausible,
    invisible to the health probe) inside a checked window is detected
    by the redundant re-execution; the loop rewinds to the anchor
    WITHOUT a dt backoff and the finished state bit-matches the
    fault-free reference. The flight recorder holds the postmortem."""
    ref, _ = build_diffusion_solver(tmp_path, metrics=False)
    for _ in range(30):
        ref.step(1e-3)
    solver, u = build_diffusion_solver(tmp_path)
    solver.stop_iteration = 30
    # cadence 5 checks the steps into iterations 5, 10, 15, ...; the
    # flip fires after step 15 — inside the 14 -> 15 checked window
    injector = chaos_mod.ChaosInjector(seed=3, flip_bit_iteration=15)
    summary = solver.evolve_resilient(
        dt=1e-3, sdc_cadence=5, snapshot_cadence=50,
        retry_base_delay=0.0, chaos=injector)
    assert [f["kind"] for f in injector.fired] == ["flip_bit"]
    assert summary["sdc_detected"] == 1
    assert summary["rewinds"] == 1
    assert summary["dt_limit"] is None, "SDC recovery must not back off dt"
    assert solver.iteration == 30
    assert np.array_equal(np.asarray(solver.X), np.asarray(ref.X)), \
        "post-SDC state does not bit-match the fault-free reference"
    # lineage + counters + postmortem
    assert "silent corruption" in summary["lineage"][0]["reason"]
    rec = solver.flush_metrics()
    assert rec["counters"]["resilience/sdc_detected"] == 1
    assert rec["resilience"]["sdc_checks"] == summary["sdc_checks"]
    pm_dirs = sorted((tmp_path / "pm").iterdir())
    assert pm_dirs, "SDC detection left no flight recording"
    from dedalus_tpu.tools.health import read_postmortem
    record, _ = read_postmortem(pm_dirs[-1])
    assert "silent corruption" in record["reason"]


def test_sdc_mismatch_escalates_structured(tmp_path):
    """With the retry budget exhausted the sentinel raises the
    structured SilentCorruptionError (mismatch count + anchor)."""
    from dedalus_tpu.tools.exceptions import SilentCorruptionError
    solver, u = build_diffusion_solver(tmp_path)
    solver.stop_iteration = 30
    injector = chaos_mod.ChaosInjector(seed=3, flip_bit_iteration=15)
    with pytest.raises(SilentCorruptionError) as excinfo:
        solver.evolve_resilient(dt=1e-3, sdc_cadence=5, max_retries=0,
                                retry_base_delay=0.0, chaos=injector)
    err = excinfo.value
    assert err.mismatched >= 1
    assert err.anchor_iteration == 14
    assert isinstance(err, SolverHealthError)   # recovery-machinery compat
    assert err.postmortem_dir


def test_sdc_flip_outside_checked_window_is_absorbed(tmp_path):
    """Honesty check of the documented sampling semantics: a flip
    landing in an UNchecked window is absorbed into the next anchor and
    never detected — the sentinel is coverage-by-cadence, not a proof."""
    solver, u = build_diffusion_solver(tmp_path, metrics=False)
    solver.stop_iteration = 30
    injector = chaos_mod.ChaosInjector(seed=3, flip_bit_iteration=12)
    summary = solver.evolve_resilient(dt=1e-3, sdc_cadence=5,
                                      chaos=injector)
    assert [f["kind"] for f in injector.fired] == ["flip_bit"]
    assert summary["sdc_detected"] == 0


# ------------------------------------------------- load_state hardening

def test_load_state_structured_errors_and_fallback(tmp_path):
    """Truncated files raise CheckpointError naming the file; a torn
    newest write falls back to the previous valid write."""
    ckpt = tmp_path / "ckpt"
    solver, u = build_diffusion_solver(tmp_path, metrics=False)
    handler = solver.evaluator.add_file_handler(ckpt, max_writes=10)
    handler.add_task(u, layout="c", name="u")
    clocks = []
    for _ in range(3):
        solver.step(1e-3)
        handler.process(iteration=solver.iteration,
                        sim_time=solver.sim_time, timestep=solver.dt)
        clocks.append((solver.iteration, solver.sim_time))
    path = handler.current_file
    # tear the newest write: task data shorter than the scales cursor
    import h5py
    with h5py.File(path, "r+") as f:
        ds = f["tasks/u"]
        ds.resize((2,) + ds.shape[1:])
    solver2, _ = build_diffusion_solver(tmp_path, metrics=False)
    with pytest.raises(CheckpointError) as excinfo:
        solver2.load_state(path, index=-1)
    err = excinfo.value
    assert isinstance(err, OSError)            # legacy catch compatibility
    assert str(path) in str(err)
    assert "torn write" in str(err)
    assert err.index == 2
    # fallback walks to the previous valid write
    write, dt = solver2.load_state(path, index=-1, fallback=True)
    assert write == 2
    assert (solver2.iteration, solver2.sim_time) == clocks[1]
    # validate_checkpoint reports the same torn-write diagnosis
    n_valid, reason = res_mod.validate_checkpoint(path)
    assert n_valid == 2 and "torn write" in reason
    # file-level corruption: structured error, file named, no h5py leak
    chaos_mod.corrupt_checkpoint(path, mode="truncate")
    with pytest.raises(CheckpointError) as excinfo:
        solver2.load_state(path)
    assert "unreadable" in str(excinfo.value)
    # missing file is also structured
    with pytest.raises(CheckpointError):
        solver2.load_state(tmp_path / "missing.h5")


# ----------------------------------------------------- retry classifier

def test_retry_policy_classification():
    """Transient OSErrors are retried with exponential backoff;
    structural ones and foreign exceptions escalate immediately."""
    import errno
    policy = res_mod.RetryPolicy(max_attempts=3, base_delay=0.0)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError(errno.EIO, "flaky disk")
        return "ok"

    assert policy.call(flaky) == "ok"
    assert calls["n"] == 3

    def denied():
        calls["n"] += 1
        raise OSError(errno.EACCES, "permission denied")

    calls["n"] = 0
    with pytest.raises(PermissionError):
        policy.call(denied)
    assert calls["n"] == 1                       # no retry on EACCES

    def wrong():
        raise ValueError("not IO at all")

    with pytest.raises(ValueError):
        policy.call(wrong)
    # transient fault past the attempt budget propagates
    calls["n"] = 0
    with pytest.raises(OSError):
        res_mod.RetryPolicy(max_attempts=2, base_delay=0.0).call(
            lambda: (_ for _ in ()).throw(OSError(errno.EIO, "always")))
    # backoff doubles per attempt, capped
    p = res_mod.RetryPolicy(max_attempts=5, base_delay=0.1, max_delay=0.25)
    assert [p.delay(k) for k in (1, 2, 3)] == [0.1, 0.2, 0.25]


# -------------------------------------------------------- zero overhead

def test_disabled_resilience_zero_overhead(tmp_path):
    """A plain run never touches the resilience machinery: no snapshots,
    no counters, no handlers, no `resilience` key in telemetry."""
    solver, u = build_diffusion_solver(tmp_path)
    for _ in range(5):
        solver.step(1e-3)
    assert getattr(solver, "resilience", None) is None
    assert solver.evaluator.handlers == []
    rec = solver.flush_metrics()
    assert "resilience" not in rec
    assert not any(k.startswith("resilience/") for k in rec["counters"])


def test_schedule_state_roundtrip(tmp_path):
    """Evaluator scheduling counters rewind with the solver: an output
    cadence crossed between snapshot and failure re-fires on replay."""
    solver, u = build_diffusion_solver(tmp_path, metrics=False)
    handler = solver.evaluator.add_dictionary_handler(iter=5)
    handler.add_task(u, name="u")
    state0 = handler.schedule_state()
    for _ in range(6):
        solver.step(1e-3)
    assert handler.last_iter_div == 1            # fired at iteration 5
    handler.restore_schedule_state(state0)
    assert handler.last_iter_div == state0["last_iter_div"]
    # replaying past the cadence schedules the handler again
    assert handler.check_schedule(iteration=5) is True
