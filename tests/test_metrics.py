"""
Step-loop metrics (tools/metrics.py): counter/timer/watermark semantics,
sampling-cadence gating (no device sync off-cadence), JSONL flush
round-trip, and an instrumented-solver smoke test on the CPU backend.
"""

import json

import numpy as np
import pytest

from dedalus_tpu.tools import metrics as metrics_mod
from dedalus_tpu.tools.metrics import (PHASES, Counter, Metrics,
                                       MemoryWatermark, PhaseTimer)


def test_counter_semantics():
    c = Counter("steps")
    assert c.value == 0
    assert c.inc() == 1
    assert c.inc(5) == 6
    m = Metrics(sample_cadence=10)
    m.inc("a")
    m.inc("a", 2)
    assert m.counter("a").value == 3
    # disabled metrics: counters are inert
    off = Metrics(enabled=False)
    off.inc("a", 7)
    assert off.counter("a").value == 0


def test_phase_timer_semantics():
    t = PhaseTimer()
    assert set(t.totals) == set(PHASES)
    t.add("transform", 0.5)
    t.add("transform", 1.5)
    t.add("matsolve", 1.0)
    assert t.mean("transform") == pytest.approx(1.0)
    assert t.mean("matsolve") == pytest.approx(1.0)
    assert t.mean("transpose") == 0.0
    assert t.samples == 2


def test_memory_watermark_cpu():
    import jax.numpy as jnp
    w = MemoryWatermark()
    first = w.sample()
    keep = jnp.zeros((1024, 1024), dtype=jnp.float32)  # 4 MB live
    second = w.sample()
    assert second >= first
    assert w.peak_bytes == max(first, second)
    assert w.source in ("memory_stats", "live_arrays")
    del keep


def test_sampling_cadence_gating():
    m = Metrics(sample_cadence=5)
    fired = []
    for i in range(1, 21):
        m.observe_steps(1)
        if m.due():
            fired.append(i)
    assert fired == [5, 10, 15, 20]  # one fire per cadence crossing
    # block-of-steps crossing: fires once, not per crossed multiple
    m2 = Metrics(sample_cadence=5)
    m2.observe_steps(17)
    assert m2.due()
    assert not m2.due()
    # sampling disabled: never due
    m3 = Metrics(sample_cadence=5, sampling=False)
    m3.observe_steps(50)
    assert not m3.due()


def test_time_thunk_warms_once_and_blocks():
    calls = []

    class FakeArray:
        def block_until_ready(self):
            calls.append("block")
            return self

    m = Metrics(sample_cadence=1)
    thunk = lambda: (calls.append("run"), FakeArray())[1]
    m.time_thunk("x", thunk)
    assert calls == ["run", "block", "run", "block"]  # warm + timed
    calls.clear()
    m.time_thunk("x", thunk)
    assert calls == ["run", "block"]  # warmed: single timed run


def test_jsonl_flush_roundtrip(tmp_path):
    sink = tmp_path / "metrics.jsonl"
    m = Metrics(sample_cadence=2, sink=str(sink),
                meta={"config": "unit", "backend": "cpu"})
    m.observe_steps(4)
    m.add_phase_sample({"transform": 0.01, "matsolve": 0.02,
                        "transpose": 0.0, "evaluator": 0.005})
    rec = m.flush(extra={"note": "roundtrip"})
    assert rec is not None
    lines = sink.read_text().splitlines()
    assert len(lines) == 1
    parsed = json.loads(lines[0])
    assert parsed["kind"] == "step_metrics"
    assert parsed["config"] == "unit"
    assert parsed["note"] == "roundtrip"
    assert parsed["iterations"] == 4
    assert set(parsed["phase_total_sec"]) == set(PHASES)
    assert parsed["phase_total_sec"]["matsolve"] == pytest.approx(
        0.02 * 4, rel=1e-3)
    assert parsed["phase_samples"] == 1
    assert parsed["ts"] > 0
    # second flush appends a second record
    m.flush()
    assert len(sink.read_text().splitlines()) == 2
    # disabled metrics flush to nothing
    assert Metrics(enabled=False).flush() is None


def test_resolve_respects_spec_and_config():
    m = Metrics(sample_cadence=7, meta={"backend": "x"})
    same = metrics_mod.resolve(m, meta={"backend": "y", "dtype": "f32"})
    assert same is m
    assert same.meta["backend"] == "x"      # existing keys win
    assert same.meta["dtype"] == "f32"      # new keys merge in
    off = metrics_mod.resolve(False)
    assert not off.enabled
    on = metrics_mod.resolve(True, sink=None, cadence=33)
    assert on.enabled and on.sample_cadence == 33


def _instrumented_rb(tmp_path, nx=64, nz=32, cadence=4):
    from dedalus_tpu.extras.bench_problems import build_rb_solver
    solver, b = build_rb_solver(nx, nz, np.float32)
    solver.warmup_iterations = 2
    solver.metrics = metrics_mod.resolve(
        True, sink=str(tmp_path / "m.jsonl"), cadence=cadence,
        meta={"backend": "cpu", "dtype": "float32", "config": "rb_smoke"})
    return solver


def test_instrumented_step_many_emits_phase_record(tmp_path):
    """CPU smoke: an instrumented step_many run emits a phase-breakdown
    JSONL record whose phase sum is commensurate with the loop wall."""
    solver = _instrumented_rb(tmp_path)
    dt = 1e-4
    for _ in range(3):
        solver.step(dt)   # crosses warmup at iteration 2 -> probes compile
    solver.step_many(9, dt)
    rec = solver.flush_metrics()
    assert rec["iterations"] == 10          # post-warmup window
    assert rec["phase_samples"] >= 2        # warm sample + >=1 cadence fire
    assert set(rec["phase_total_sec"]) == set(PHASES)
    assert rec["phase_total_sec"]["transpose"] == 0.0   # single device
    for phase in ("transform", "matsolve", "evaluator"):
        assert rec["phase_total_sec"][phase] > 0.0
    assert rec["steps_per_sec"] > 0
    # phase attribution is commensurate with the measured loop wall (the
    # tight 20% acceptance bound is asserted at bench scale in the slow
    # test below; tiny problems carry relatively more host overhead)
    assert 0.2 < rec["phase_sum_frac"] < 1.5
    # sink got the same record
    lines = (tmp_path / "m.jsonl").read_text().splitlines()
    assert json.loads(lines[-1])["phase_total_sec"] == rec["phase_total_sec"]
    # state untouched by sampling: still finite
    assert np.all(np.isfinite(np.asarray(solver.X)))


def test_no_sampling_off_cadence(tmp_path):
    """Off-cadence iterations never run phase probes (no block_until_ready
    beyond the step dispatch): with cadence above the iteration count only
    the warmup-boundary sample exists."""
    solver = _instrumented_rb(tmp_path, cadence=1000)
    calls = []
    orig = solver._sample_phases

    def spy():
        calls.append(solver.iteration)
        return orig()

    solver._sample_phases = spy
    dt = 1e-4
    for _ in range(3):
        solver.step(dt)
    solver.step_many(5, dt)
    assert calls == [2]   # the warmup-end compile/sample only
    rec = solver.flush_metrics()
    assert rec["phase_samples"] == 1


def test_step_many_only_driver_defers_warm(tmp_path):
    """A driver that only calls step_many crosses warmup before the LHS is
    factored: the probe warm-up defers past that first (compile-bearing)
    block and the loop window re-anchors after it, so per-step rates never
    include jit compile."""
    solver = _instrumented_rb(tmp_path, cadence=1000)
    solver.warmup_iterations = 2
    solver.step_many(6, 1e-4)    # crosses warmup with no factor yet
    assert not solver._metrics_warm_pending   # warmed after the block
    assert solver.metrics.sampling
    solver.step_many(4, 1e-4)
    rec = solver.flush_metrics()
    assert rec["phase_samples"] == 1          # the deferred warm sample
    assert rec["iterations"] == 4             # window excludes block 1


def test_metrics_disabled_solver(tmp_path):
    """metrics=False solvers keep stepping with zero metrics state."""
    from dedalus_tpu.extras.bench_problems import build_rb_solver
    solver, b = build_rb_solver(32, 16, np.float64)
    solver.metrics = metrics_mod.resolve(False)
    solver.warmup_iterations = 1
    for _ in range(3):
        solver.step(1e-4)
    assert solver.flush_metrics() is None
    assert np.all(np.isfinite(np.asarray(solver.X)))


def test_log_stats_phase_table(tmp_path, caplog):
    import logging
    solver = _instrumented_rb(tmp_path)
    dt = 1e-4
    for _ in range(3):
        solver.step(dt)
    solver.step_many(5, dt)
    with caplog.at_level(logging.INFO, logger="dedalus_tpu"):
        solver.log_stats()
    text = caplog.text
    assert "Per-phase wall time" in text
    # the transpose_exposed/transpose_overlapped split renders only when
    # measured (benchmarks/scaling.py feeds it); the in-loop sampler
    # table always carries the decomposition rows + the fused overlay
    from dedalus_tpu.tools.metrics import SUM_PHASES
    for phase in SUM_PHASES + ("fused",):
        assert phase in text


@pytest.mark.slow
def test_rb256_phase_sum_within_20pct(tmp_path):
    """Acceptance-scale check (RB2D 256x64 f32 CPU): per-phase timings sum
    to within 20% of the measured loop wall time."""
    solver = _instrumented_rb(tmp_path, nx=256, nz=64, cadence=10)
    dt = 1e-4
    for _ in range(3):
        solver.step(dt)
    for _ in range(3):
        solver.step_many(10, dt)   # one cadence fire per block
    rec = solver.flush_metrics()
    assert rec["phase_samples"] >= 3
    assert 0.8 <= rec["phase_sum_frac"] <= 1.2


def test_sigint_chains_abnormal_exit_flush(tmp_path):
    """Ctrl-C (SIGINT) on an unflushed run flushes one telemetry record
    through the chaining signal hook (tools/metrics.py installs it for
    SIGTERM AND SIGINT wherever the default disposition is in place),
    then restores default semantics — the process still dies by
    KeyboardInterrupt."""
    import json
    import os
    import subprocess
    import sys
    sink = tmp_path / "flush.jsonl"
    # a stub stands in for the solver (same register_exit_flush path a
    # real build takes) so the subprocess pays no core import or build —
    # the signal semantics under test are identical
    script = f"""
import os, signal
from dedalus_tpu.tools import metrics as metrics_mod

class Stub:
    metrics = metrics_mod.Metrics(sink={str(sink)!r}, enabled=True)
    def flush_metrics(self, extra=None):
        return self.metrics.flush(extra=extra)

stub = Stub()
metrics_mod.register_exit_flush(stub)
stub.metrics.observe_steps(3)      # unflushed activity: dirty latch set
os.kill(os.getpid(), signal.SIGINT)
print("UNREACHABLE")   # the redelivered SIGINT raises KeyboardInterrupt
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=240)
    # default semantics preserved: died by KeyboardInterrupt, not clean
    assert proc.returncode != 0
    assert "UNREACHABLE" not in proc.stdout
    assert "KeyboardInterrupt" in proc.stderr
    records = [json.loads(line)
               for line in sink.read_text().splitlines() if line.strip()]
    assert len(records) == 1
    assert records[0]["flush_source"] == f"signal:{2}"
    assert records[0]["iterations"] == 3


def test_format_phase_table_zero_samples():
    """A record with no samples (flushed before the first cadence fire)
    renders a complete table of zeros — header, every decomposition row,
    and the sum line — without dividing by the zero wall."""
    from dedalus_tpu.tools.metrics import format_phase_table
    lines = format_phase_table({"phase_samples": 0, "iterations": 0,
                                "loop_wall_sec": 0.0})
    assert lines[0].startswith("Per-phase wall time (0 samples")
    text = "\n".join(lines)
    for phase in ("transform", "matsolve", "transpose", "evaluator"):
        assert phase in text
    assert "0 iterations" in text
    # empty/None records render to nothing rather than raising
    assert format_phase_table({}) == []
    assert format_phase_table(None) == []


def test_format_phase_table_overlap_split_only():
    """A record carrying ONLY the transpose exposed/overlapped split
    (benchmarks/scaling.py feeds it without the in-loop sampler rows)
    renders the split line with its hidden-fraction, excluded from the
    phase sum."""
    from dedalus_tpu.tools.metrics import format_phase_table
    lines = format_phase_table({
        "phase_samples": 0, "iterations": 10, "loop_wall_sec": 1.0,
        "phase_total_sec": {"transpose_exposed": 0.25,
                            "transpose_overlapped": 0.75}})
    text = "\n".join(lines)
    assert "exposed 0.2500 s" in text
    assert "overlapped 0.7500 s" in text
    assert "(75% hidden" in text
    assert "excluded from sum" in text
    # the decomposition sum stays zero: the split rows never enter it
    assert "sum        0.000 s" in text


def test_format_phase_table_percentile_columns():
    """Records carrying phase_pct_sec grow p50/p95/p99 tail columns on
    exactly the phases that have them; pre-percentile records render the
    plain row unchanged."""
    from dedalus_tpu.tools.metrics import format_phase_table
    rec = {
        "phase_samples": 8, "iterations": 40, "loop_wall_sec": 4.0,
        "sample_cadence": 5,
        "phase_mean_sec": {"transform": 0.01, "matsolve": 0.02},
        "phase_total_sec": {"transform": 0.4, "matsolve": 0.8},
        "phase_pct_sec": {"matsolve": {"p50": 0.019, "p95": 0.03,
                                       "p99": 0.05}},
    }
    lines = format_phase_table(rec)
    mat = next(ln for ln in lines if ln.strip().startswith("matsolve"))
    assert "p50/p95/p99" in mat
    assert "0.0190/0.0300/0.0500 s" in mat
    tra = next(ln for ln in lines if ln.strip().startswith("transform"))
    assert "p50" not in tra               # no histogram, no column


def test_phase_timer_feeds_histograms():
    """Every add() lands in the per-phase LogHistogram (always-on,
    independent of tracing) and percentiles() reads back ordered tails;
    phases without samples report None."""
    t = PhaseTimer()
    for sec in (0.01, 0.011, 0.012, 0.1):
        t.add("matsolve", sec)
    pct = t.percentiles("matsolve")
    assert pct["p50"] <= pct["p95"] <= pct["p99"]
    assert 0.005 <= pct["p50"] <= 0.02
    assert t.percentiles("transpose") is None


def test_flush_carries_phase_percentiles(tmp_path):
    """Flushed records carry phase_pct_sec for sampled phases — the
    serving tier's tail telemetry — alongside the means."""
    m = Metrics(sample_cadence=1, sink=str(tmp_path / "m.jsonl"))
    m.observe_steps(3)
    for _ in range(3):
        m.add_phase_sample({"transform": 0.01, "matsolve": 0.02,
                            "transpose": 0.0, "evaluator": 0.005})
    rec = m.flush()
    assert "matsolve" in rec["phase_pct_sec"]
    p = rec["phase_pct_sec"]["matsolve"]
    assert set(p) == {"p50", "p95", "p99"}
    assert p["p50"] <= p["p99"]
    assert p["p50"] == pytest.approx(0.02, rel=0.25)
