"""
Request tracing (tools/tracing.py): log-histogram percentile semantics,
span-tree construction and cross-thread propagation, the disabled-path
zero-cost contract, flush/load round-trip, and Chrome trace-event export
validity. The end-to-end served-request trace structure is asserted in
tests/test_service_batching.py; the compiled-program inertness contract
(DTP107) in tests/test_progcheck.py.
"""

import json
import threading

import pytest

from dedalus_tpu.tools import tracing


@pytest.fixture
def traced(tmp_path):
    """Tracing enabled with a tmp sink, ring cleared; global state
    (enabled flag, sink path) restored afterwards so no other test sees
    this one's spans."""
    was_on = tracing.enabled()
    old_sink = tracing.trace_sink()
    sink = tmp_path / "traces.jsonl"
    tracing.enable(str(sink))
    tracing.recorder().clear()
    yield sink
    tracing.disable()
    tracing._sink = old_sink
    tracing.recorder().clear()
    if was_on:
        tracing.enable()


# ------------------------------------------------------------- histogram

def test_histogram_empty_and_single():
    h = tracing.LogHistogram()
    assert h.percentile(50) == 0.0
    assert h.summary() == {"count": 0, "p50": 0.0, "p95": 0.0, "p99": 0.0}
    h.add(0.25)
    # a one-sample histogram is clamped to its own min/max: every
    # percentile IS the sample
    for q in (1, 50, 99):
        assert h.percentile(q) == pytest.approx(0.25)


def test_histogram_percentiles_ordered_and_bounded():
    h = tracing.LogHistogram()
    values = [0.001] * 90 + [0.1] * 9 + [5.0]
    for v in values:
        h.add(v)
    s = h.summary()
    assert s["count"] == 100
    assert s["p50"] <= s["p95"] <= s["p99"]
    # p50 lands in the bulk, p99 in the tail; bucket midpoint error is
    # bounded by one geometric bucket (~19%)
    assert s["p50"] == pytest.approx(0.001, rel=0.25)
    assert s["p99"] >= 0.05
    assert h.min == 0.001 and h.max == 5.0
    assert h.sum == pytest.approx(sum(values))
    # percentiles never leave the observed range
    assert h.percentile(100) <= 5.0
    assert h.percentile(0) >= 0.001


def test_histogram_degenerate_samples():
    h = tracing.LogHistogram()
    h.add(0.0)
    h.add(-1.0)          # clock skew / subtraction noise: bucket 0
    assert h.total == 2
    assert h.percentile(99) <= 1e-9 or h.percentile(99) == h.max


# ------------------------------------------------------------ span trees

def test_disabled_span_is_shared_noop():
    assert not tracing.enabled()
    s1 = tracing.span("a")
    s2 = tracing.span("b", attrs={"x": 1})
    assert s1 is s2                       # shared singleton: no per-call cost
    with s1 as inner:
        assert inner.set(y=2) is inner    # attrs accepted and dropped
    assert tracing.new_trace("t") is None
    assert tracing.add_span("c", 0.1) is None
    with tracing.resume(None):
        assert tracing.current_context() is None


def test_nested_spans_share_trace_and_parent(traced):
    with tracing.span("outer") as outer:
        with tracing.span("inner", attrs={"k": "v"}):
            pass
    spans = tracing.recorder().spans()
    assert len(spans) == 2
    inner, outer_s = sorted(spans, key=lambda s: s.name != "inner")
    assert inner.trace_id == outer_s.trace_id
    assert inner.parent_id == outer_s.span_id
    assert outer_s.parent_id is None      # orphan root: its own trace
    assert inner.attrs == {"k": "v"}
    assert inner.dur >= 0.0


def test_context_resume_across_threads(traced):
    ctx = tracing.new_trace("request", attrs={"id": "r1"})
    assert ctx is not None
    tracing.add_span("accept", 0.01, parent=ctx)

    def worker():
        with tracing.resume(ctx):
            with tracing.span("run"):
                with tracing.span("phase/matsolve"):
                    pass

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    root = ctx.finish(outcome="ok")
    assert root is not None and root.span_id == ctx.root_id
    spans = tracing.recorder().spans(ctx.trace_id)
    by_name = {s.name: s for s in spans}
    assert set(by_name) == {"request", "accept", "run", "phase/matsolve"}
    assert by_name["accept"].parent_id == ctx.root_id
    assert by_name["run"].parent_id == ctx.root_id
    assert by_name["phase/matsolve"].parent_id == by_name["run"].span_id
    assert len({s.trace_id for s in spans}) == 1
    # finish is idempotent
    assert ctx.finish() is None


def test_ring_bounded(traced):
    rec = tracing.TraceRecorder(capacity=16)
    for i in range(100):
        rec.record(tracing.Span("t", i, None, f"s{i}", 0.0, 0.0))
    spans = rec.spans()
    assert len(spans) == 16
    assert spans[0].name == "s84"         # oldest evicted first


# ------------------------------------------------------- flush and export

def _one_trace(sink):
    ctx = tracing.new_trace("request", attrs={"id": "r1"})
    with tracing.resume(ctx):
        with tracing.span("run"):
            pass
    ctx.finish(outcome="ok")
    return ctx


def test_flush_pops_and_appends(traced):
    ctx = _one_trace(traced)
    rec = tracing.flush_trace(ctx.trace_id, plan={"plan_version": 1})
    assert rec["kind"] == "trace"
    assert rec["trace_id"] == ctx.trace_id
    assert rec["plan"] == {"plan_version": 1}
    assert {s["name"] for s in rec["spans"]} == {"request", "run"}
    # pop semantics: the ring no longer holds the trace, a second flush
    # is a no-op (flush-once for the JSONL sink)
    assert tracing.recorder().spans(ctx.trace_id) == []
    assert tracing.flush_trace(ctx.trace_id) is None
    assert tracing.flush_trace(None) is None
    loaded = tracing.load_trace_records(str(traced))
    assert len(loaded) == 1
    assert loaded[0]["trace_id"] == ctx.trace_id


def test_summarize_and_tree(traced):
    ctx = _one_trace(traced)
    rec = tracing.flush_trace(ctx.trace_id)
    s = tracing.summarize_trace(rec)
    assert s["root"] == "request"
    assert s["spans"] == 2
    assert s["root_attrs"]["outcome"] == "ok"
    assert set(s["by_name"]) == {"request", "run"}
    lines = tracing.format_trace_tree(rec)
    assert ctx.trace_id in lines[0]
    text = "\n".join(lines)
    assert "request" in text and "run" in text
    # the child renders deeper than the root
    req = next(ln for ln in lines if "request" in ln and "trace" not in ln)
    run = next(ln for ln in lines if ln.strip().startswith("run"))
    assert len(run) - len(run.lstrip()) > len(req) - len(req.lstrip())


def _assert_valid_chrome(doc):
    json.loads(json.dumps(doc))          # JSON-serializable throughout
    assert doc["displayTimeUnit"] == "ms"
    assert doc["traceEvents"]
    for ev in doc["traceEvents"]:
        assert ev["ph"] == "X"
        assert isinstance(ev["ts"], float) and isinstance(ev["dur"], float)
        assert ev["ts"] > 0 and ev["dur"] >= 0
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        assert ev["name"] and ev["cat"] == "dedalus"
        assert "trace_id" in ev["args"] and "span_id" in ev["args"]


def test_chrome_export_valid(traced):
    ctx = _one_trace(traced)
    spans = tracing.recorder().spans(ctx.trace_id)
    _assert_valid_chrome(tracing.chrome_trace(spans))
    rec = tracing.flush_trace(ctx.trace_id)
    doc = tracing.chrome_trace_from_records([rec])
    _assert_valid_chrome(doc)
    names = {ev["name"] for ev in doc["traceEvents"]}
    assert names == {"request", "run"}
    # parent linkage survives the round-trip
    child = next(ev for ev in doc["traceEvents"] if ev["name"] == "run")
    assert child["args"]["parent_id"] == ctx.root_id


def test_flush_never_raises_on_bad_sink(traced):
    ctx = _one_trace(traced)
    rec = tracing.flush_trace(ctx.trace_id, sink="/dev/null/not/a/dir/x.jsonl")
    # telemetry must never kill a request: the unwritable sink is
    # swallowed (record may be None or returned ringless, but no raise)
    assert rec is None or rec["trace_id"] == ctx.trace_id
