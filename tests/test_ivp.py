"""
IVP integration tests (reference: dedalus/tests/test_ivp.py — heat equation
vs exact solution for every registered timestepper).
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3
from dedalus_tpu.core.timesteppers import schemes


@pytest.mark.parametrize("scheme", sorted(schemes))
def test_heat_periodic(scheme):
    """Decaying Fourier mode vs exact exponential
    (reference: test_ivp.py:25 test_heat_periodic)."""
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=32, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    problem = d3.IVP([u], namespace={"u": u, "lap": d3.lap})
    problem.add_equation("dt(u) - lap(u) = 0")
    x = dist.local_grid(xb)
    u["g"] = np.sin(3 * x)
    solver = problem.build_solver(scheme)
    for _ in range(100):
        solver.step(1e-3)
    exact = np.exp(-9 * solver.sim_time) * np.sin(3 * x)
    assert np.max(np.abs(u["g"] - exact.ravel())) < 2e-3


@pytest.mark.parametrize("scheme", ["SBDF2", "RK222"])
def test_heat_variable_dt(scheme):
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=32, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    problem = d3.IVP([u], namespace={"u": u, "lap": d3.lap})
    problem.add_equation("dt(u) - lap(u) = 0")
    x = dist.local_grid(xb)
    u["g"] = np.sin(3 * x)
    solver = problem.build_solver(scheme)
    for i in range(100):
        solver.step(1e-3 if i % 2 else 7e-4)
    exact = np.exp(-9 * solver.sim_time) * np.sin(3 * x)
    assert np.max(np.abs(u["g"] - exact.ravel())) < 2e-3


def test_kdv_burgers_mass_conservation():
    """Nonlinear RHS path: conserved integral and stability
    (reference example: examples/ivp_1d_kdv_burgers)."""
    Lx = 10
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=128, bounds=(0, Lx), dealias=3/2)
    u = dist.Field(name="u", bases=xb)
    dx = lambda A: d3.Differentiate(A, xc)
    a, b = 1e-4, 2e-4
    problem = d3.IVP([u], namespace=locals())
    problem.add_equation("dt(u) - a*dx(dx(u)) - b*dx(dx(dx(u))) = - u*dx(u)")
    x = dist.local_grid(xb)
    n = 20
    u["g"] = np.log(1 + np.cosh(n)**2 / np.cosh(n * (x - 0.2 * Lx))**2) / (2 * n)
    mass0 = np.sum(u["g"])
    solver = problem.build_solver(d3.SBDF2)
    for _ in range(200):
        solver.step(2e-3)
    assert np.all(np.isfinite(u["g"]))
    assert np.allclose(np.sum(u["g"]), mass0)


def test_advection_diffusion_exact():
    """IVP with explicit nonlinearity evaluated but solution known:
    traveling decaying wave via complex Fourier."""
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.complex128)
    xb = d3.ComplexFourier(xc, size=32, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    c, nu = 1.5, 0.1
    dx = lambda A: d3.Differentiate(A, xc)
    problem = d3.IVP([u], namespace=locals())
    problem.add_equation("dt(u) + c*dx(u) - nu*lap(u) = 0")
    x = dist.local_grid(xb)
    u["g"] = np.exp(2j * x)
    solver = problem.build_solver(d3.RK443)
    for _ in range(100):
        solver.step(1e-3)
    t = solver.sim_time
    exact = np.exp(2j * (x - c * t)) * np.exp(-nu * 4 * t)
    assert np.max(np.abs(u["g"] - exact.ravel())) < 1e-6


def test_rayleigh_benard_smoke():
    """Full RB stack: taus, NCC, Lift, BCs, gauge
    (reference example: examples/ivp_2d_rayleigh_benard)."""
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 4), dealias=3/2)
    zb = d3.ChebyshevT(coords["z"], size=8, bounds=(0, 1), dealias=3/2)
    p = dist.Field(name="p", bases=(xb, zb))
    b = dist.Field(name="b", bases=(xb, zb))
    u = dist.VectorField(coords, name="u", bases=(xb, zb))
    tau_p = dist.Field(name="tau_p")
    tau_b1 = dist.Field(name="tau_b1", bases=xb)
    tau_b2 = dist.Field(name="tau_b2", bases=xb)
    tau_u1 = dist.VectorField(coords, name="tau_u1", bases=xb)
    tau_u2 = dist.VectorField(coords, name="tau_u2", bases=xb)
    kappa = nu = 2e-3
    x, z = dist.local_grids(xb, zb)
    ex, ez = coords.unit_vector_fields(dist)
    lift_basis = zb.derivative_basis(1)
    lift = lambda A: d3.Lift(A, lift_basis, -1)
    grad_u = d3.grad(u) + ez * lift(tau_u1)
    grad_b = d3.grad(b) + ez * lift(tau_b1)
    problem = d3.IVP([p, b, u, tau_p, tau_b1, tau_b2, tau_u1, tau_u2],
                     namespace=locals())
    problem.add_equation("trace(grad_u) + tau_p = 0")
    problem.add_equation("dt(b) - kappa*div(grad_b) + lift(tau_b2) = - u@grad(b)")
    problem.add_equation("dt(u) - nu*div(grad_u) + grad(p) - b*ez + lift(tau_u2) = - u@grad(u)")
    problem.add_equation("b(z=0) = 1")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("b(z=1) = 0")
    problem.add_equation("u(z=1) = 0")
    problem.add_equation("integ(p) = 0")
    solver = problem.build_solver(d3.RK222)
    b.fill_random("g", seed=42, distribution="normal", scale=1e-3)
    b["g"] *= z * (1 - z)
    b["g"] += 1 - z
    for _ in range(10):
        solver.step(0.02)
    assert np.all(np.isfinite(b["g"]))
    assert np.all(np.isfinite(u["g"]))
    # boundary conditions hold
    assert np.max(np.abs(d3.Interpolate(b, coords["z"], 0.0).evaluate()["g"] - 1)) < 1e-10
    assert np.max(np.abs(d3.Interpolate(b, coords["z"], 1.0).evaluate()["g"])) < 1e-10
    # incompressibility holds
    assert np.max(np.abs(d3.trace(grad_u).evaluate()["g"])) < 1e-12


def test_enforce_real_cadence_projects_invalid_modes():
    """enforce_hermitian_symmetry (reference: core/solvers.py:675-692)
    re-projects the state through a dealiased grid roundtrip, clearing
    drift accumulated in non-representable slots (e.g. the ComplexFourier
    Nyquist mode)."""
    coords = d3.CartesianCoordinates("x")
    dist = d3.Distributor(coords, dtype=np.complex128)
    xb = d3.ComplexFourier(coords["x"], size=16, bounds=(0, 2*np.pi))
    u = dist.Field(name="u", bases=xb)
    problem = d3.IVP([u], namespace={})
    problem.add_equation((d3.dt(u) - d3.lap(u), 0))
    solver = problem.build_solver(d3.SBDF1, enforce_real_cadence=2)
    x, = dist.local_grids(xb)
    u["g"] = np.exp(1j*x) + np.exp(-2j*x)
    # pollute the invalid Nyquist slot
    X = np.asarray(solver.X).copy()
    import jax.numpy as jnp
    solver.X = jnp.asarray(X)
    solver.enforce_hermitian_symmetry()
    X0 = np.asarray(solver.X)
    pol = X0.copy()
    nyq = 8  # ComplexFourier(16) group layout [0..7, nyquist, -7..-1]
    pol[nyq, :] += 10.0
    solver.X = jnp.asarray(pol)
    solver.enforce_hermitian_symmetry()
    X1 = np.asarray(solver.X)
    # valid content preserved, polluted Nyquist slot actually cleared
    others = np.ones(len(X1), dtype=bool)
    others[nyq] = False
    assert np.abs(X1[others] - X0[others]).max() < 1e-12
    assert np.abs(X1[nyq] - X0[nyq]).max() < 1e-12
    # several steps with cadence on stay finite and drift-bounded
    for _ in range(6):
        solver.step(1e-3)
    assert np.isfinite(np.asarray(solver.X)).all()


def test_step_many_matches_single_steps():
    """step_many(n, dt) must reproduce n individual step(dt) calls exactly
    (including the multistep startup ramp)."""
    import dedalus_tpu.public as d3

    def build(scheme):
        coords = d3.CartesianCoordinates("x")
        dist = d3.Distributor(coords, dtype=np.float64)
        xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 2 * np.pi))
        u = dist.Field(name="u", bases=xb)
        problem = d3.IVP([u], namespace=locals())
        problem.add_equation("dt(u) - lap(u) = - u*u")
        solver = problem.build_solver(scheme)
        x, = dist.local_grids(xb)
        u["g"] = np.sin(x) + 0.1 * np.cos(3 * x)
        return solver

    for scheme in ("RK222", "SBDF3"):
        s1 = build(scheme)
        s2 = build(scheme)
        for _ in range(7):
            s1.step(1e-3)
        s2.step_many(7, 1e-3)
        X1 = np.asarray(s1.X)
        X2 = np.asarray(s2.X)
        assert np.allclose(X1, X2, rtol=1e-12, atol=1e-14), scheme
        assert abs(s1.sim_time - s2.sim_time) < 1e-14
        assert s1.iteration == s2.iteration == 7


@pytest.mark.parametrize("ts_name", ["RK222", "SBDF2"])
def test_split_step_matches_fused(ts_name):
    """Split-step mode (per-stage eval/solve device programs, the
    TPU-compiler-friendly path for very large systems) must be bit-exact
    against the fused single-program step."""
    import sys as _sys, os as _os
    _sys.path.insert(0, _os.path.dirname(__file__))
    from test_banded import build_rb
    from dedalus_tpu.tools.config import config
    ts = getattr(d3, ts_name)
    old = config["execution"].get("STEP_PROGRAM", "auto")
    try:
        config["execution"]["STEP_PROGRAM"] = "fused"
        sf = build_rb(16, 32, timestepper=ts)
        config["execution"]["STEP_PROGRAM"] = "split"
        ss = build_rb(16, 32, timestepper=ts)
        assert ss.timestepper._split and not sf.timestepper._split
        for _ in range(5):
            sf.step(0.01)
            ss.step(0.01)
        sf.step_many(4, 0.01)
        ss.step_many(4, 0.01)
    finally:
        config["execution"]["STEP_PROGRAM"] = old
    assert np.abs(np.asarray(sf.X) - np.asarray(ss.X)).max() < 1e-12
