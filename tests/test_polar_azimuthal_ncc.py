"""
Azimuthally-varying polar NCCs (VERDICT round-4 item 6; reference: the
geometry-generic NCC pipeline, dedalus/core/arithmetic.py:359-406 — whose
own polar tests are axisymmetric, dedalus/tests/test_polar_ncc.py).

Oracle: the assembled pencil matrix of an LHS product with an
f(phi, r)-dependent NCC must act on coefficients exactly like the
grid-space pointwise product, over the m-COUPLED pencil the NCC forces.
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3
from dedalus_tpu.core.subsystems import PencilLayout, build_subproblems


def _annulus(dtype, Nphi=12, Nr=8, radii=(0.7, 1.8)):
    # dealias 2: the grid-evaluation oracle must be alias-free for the
    # product of the band-limited test data (the matrix path is exactly
    # dealiased by construction — 2x quadrature)
    coords = d3.PolarCoordinates("phi", "r")
    dist = d3.Distributor(coords, dtype=dtype)
    ann = d3.AnnulusBasis(coords, shape=(Nphi, Nr), dtype=dtype, radii=radii,
                          dealias=2)
    return coords, dist, ann


def _check_expr(dist, expr, operand, tol=2e-10):
    """Assembled matrix action == grid evaluation on the coupled pencil."""
    eq = {"domain": expr.domain, "tensorsig": tuple(expr.tensorsig),
          "L": expr}
    layout = PencilLayout(dist, [operand], [eq])
    az = expr.domain.bases[-1].first_axis
    assert az not in layout.sep_widths, "NCC should have coupled azimuth"
    sps = build_subproblems(layout)
    Xin = np.asarray(layout.gather(operand.coeff_data(), operand.domain,
                                   operand.tensorsig))
    out = expr.evaluate()
    Xout = np.asarray(layout.gather(out.coeff_data(), out.domain,
                                    out.tensorsig))
    scale = max(np.abs(Xout).max(), 1e-12)
    checked = 0
    for sp in sps:
        mats = expr.expression_matrices(sp, [operand])
        y = mats[operand] @ Xin[sp.index]
        valid = layout.valid_mask(expr.domain, tuple(expr.tensorsig),
                                  sp.group).ravel()
        err = np.abs(y - Xout[sp.index])[valid].max(initial=0.0) / scale
        assert err < tol, (sp.group, err)
        checked += 1
    assert checked


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_annulus_scalar_ncc_phi_r(dtype):
    """f(phi, r) * u for scalar u: whole-axis azimuth convolution kron
    radial multiplication."""
    coords, dist, ann = _annulus(dtype)
    phi, r = dist.local_grids(ann)
    f = dist.Field(name="f", bases=ann)
    f["g"] = 2.0 + np.cos(2 * phi) * (1 + 0.3 * r) + 0.4 * np.sin(phi) * r ** 2
    u = dist.Field(name="u", bases=ann)
    u["g"] = np.cos(phi) * r ** 2 + np.sin(3 * phi) + 0.7
    _check_expr(dist, (f * u), u)


def test_annulus_scalar_ncc_times_vector_complex():
    """f(phi, r) * u for VECTOR u (complex dtype: the exp-mode convolution
    acts identically on each spin component's complex coefficients)."""
    coords, dist, ann = _annulus(np.complex128)
    phi, r = dist.local_grids(ann)
    f = dist.Field(name="f", bases=ann)
    f["g"] = 1.5 + 0.5 * np.cos(phi) * r
    u = dist.VectorField(coords, name="u", bases=ann)
    x, y = r * np.cos(phi), r * np.sin(phi)
    ux, uy = x * y, x ** 2 - y ** 2 + 0.5
    u["g"] = np.array([-np.sin(phi) * ux + np.cos(phi) * uy,
                       np.cos(phi) * ux + np.sin(phi) * uy])
    _check_expr(dist, (f * u), u)


def test_annulus_vector_real_dtype():
    """REAL-dtype tensor operands: the spin-pair recombination does not
    commute with the azimuth convolution, so the matrix conjugates the
    coordinate-component convolution by the stored recombination (four
    kron terms per azimuth mode with component-mixing tensor factors);
    oracle-checked against the grid product."""
    coords, dist, ann = _annulus(np.float64)
    phi, r = dist.local_grids(ann)
    f = dist.Field(name="f", bases=ann)
    f["g"] = 1.5 + 0.5 * np.cos(phi) * r
    u = dist.VectorField(coords, name="u", bases=ann)
    x, y = r * np.cos(phi), r * np.sin(phi)
    ux, uy = x * y, x ** 2 - y ** 2 + 0.5
    u["g"] = np.array([-np.sin(phi) * ux + np.cos(phi) * uy,
                       np.cos(phi) * ux + np.sin(phi) * uy])
    _check_expr(dist, (f * u), u)
    # NCC on the right (ncc_index = 1) exercises the mixer composition
    # with the other component placement
    _check_expr(dist, (u * f), u)


def test_annulus_azimuthal_ncc_lbvp():
    """End-to-end: (1 + eps*cos(phi)) u - lap(u) = g solved on the
    m-coupled pencils reproduces a manufactured solution."""
    coords, dist, ann = _annulus(np.float64, Nphi=16, Nr=10)
    phi, r = dist.local_grids(ann)
    u_true = (r - 0.7) * (1.8 - r) * (1 + 0.5 * np.cos(phi))
    w = dist.Field(name="w", bases=ann)
    w["g"] = 1.0 + 0.3 * np.cos(phi) * r
    u = dist.Field(name="u", bases=ann)
    tau1 = dist.Field(name="tau1", bases=ann.edge)
    tau2 = dist.Field(name="tau2", bases=ann.edge)
    lift_basis = ann.derivative_basis(2)
    lift = lambda A, n: d3.Lift(A, lift_basis, n)
    # manufactured RHS evaluated spectrally from u_true
    ut = dist.Field(name="ut", bases=ann)
    ut["g"] = u_true
    g = (w * ut - d3.lap(ut)).evaluate()
    problem = d3.LBVP([u, tau1, tau2], namespace=locals())
    problem.add_equation("w*u - lap(u) + lift(tau1,-1) + lift(tau2,-2) = g")
    problem.add_equation("u(r=0.7) = 0")
    problem.add_equation("u(r=1.8) = 0")
    solver = problem.build_solver()
    solver.solve()
    assert np.abs(u["g"] - u_true).max() < 1e-10


def _disk(dtype, Nphi=12, Nr=8):
    coords = d3.PolarCoordinates("phi", "r")
    dist = d3.Distributor(coords, dtype=dtype)
    disk = d3.DiskBasis(coords, shape=(Nphi, Nr), dtype=dtype, radius=1.0,
                        dealias=2)
    return coords, dist, disk


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_disk_scalar_ncc_phi_r(dtype):
    """f(phi, r) * u on the DISK: per-(m_out, m_in) Zernike radial blocks
    under the whole-axis azimuth convolution."""
    coords, dist, disk = _disk(dtype)
    phi, r = dist.local_grids(disk)
    x, y = r * np.cos(phi), r * np.sin(phi)
    f = dist.Field(name="f", bases=disk)
    f["g"] = 1.0 + 0.5 * x + 0.3 * (x * y - y)
    u = dist.Field(name="u", bases=disk)
    u["g"] = x ** 2 - y ** 2 + y + 0.5
    _check_expr(dist, (f * u), u)


def test_disk_scalar_ncc_times_vector_complex():
    """Disk scalar azimuthal NCC times a vector operand (complex dtype)."""
    coords, dist, disk = _disk(np.complex128)
    phi, r = dist.local_grids(disk)
    x, y = r * np.cos(phi), r * np.sin(phi)
    f = dist.Field(name="f", bases=disk)
    f["g"] = 1.0 + 0.4 * y
    u = dist.VectorField(coords, name="u", bases=disk)
    ux, uy = x * y, x ** 2 - y ** 2 + 0.5
    u["g"] = np.array([-np.sin(phi) * ux + np.cos(phi) * uy,
                       np.cos(phi) * ux + np.sin(phi) * uy])
    _check_expr(dist, (f * u), u)


def test_disk_vector_real_dtype():
    """REAL-dtype tensor operands on the disk: stored-pair conjugation
    with per-(m, spin) Zernike radial blocks (same non-commutation as the
    annulus, m-dependent radial spaces); oracle-checked against the grid
    product."""
    coords, dist, disk = _disk(np.float64)
    phi, r = dist.local_grids(disk)
    x, y = r * np.cos(phi), r * np.sin(phi)
    f = dist.Field(name="f", bases=disk)
    f["g"] = 1.0 + 0.5 * r * np.cos(phi)
    u = dist.VectorField(coords, name="u", bases=disk)
    ux, uy = x * y, x ** 2 - y ** 2 + 0.5
    u["g"] = np.array([-np.sin(phi) * ux + np.cos(phi) * uy,
                       np.cos(phi) * ux + np.sin(phi) * uy])
    _check_expr(dist, (f * u), u)
