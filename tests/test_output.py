"""
Output subsystem tests: FileHandler schema, set splitting, append-mode
continuation, and checkpoint/restart equivalence
(reference: dedalus/tests/test_output.py, core/evaluator.py:369-438).
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3


def build_heat(dtype=np.float64):
    coords = d3.CartesianCoordinates("x")
    dist = d3.Distributor(coords, dtype=dtype)
    xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    problem = d3.IVP([u], namespace={})
    problem.add_equation((d3.dt(u) - 0.1 * d3.lap(u), 0))
    solver = problem.build_solver(d3.SBDF1)
    x, = dist.local_grids(xb)
    u["g"] = np.cos(x) + 0.5 * np.cos(3 * x)
    return solver, u, x


def test_filehandler_schema_and_sets(tmp_path):
    import h5py
    solver, u, x = build_heat()
    out = tmp_path / "snaps"
    handler = solver.evaluator.add_file_handler(out, iter=2, max_writes=2)
    handler.add_task(u, name="u")
    handler.add_task(d3.lap(u), name="lap_u")
    for _ in range(10):
        solver.step(1e-3)
    files = sorted(out.glob("snaps_s*.h5"))
    # 6 writes (first-step initial write at iter 1, then 2,4,6,8,10)
    # at 2 writes/set -> 3 sets
    assert len(files) == 3
    with h5py.File(files[0], "r") as f:
        assert "tasks/u" in f and "tasks/lap_u" in f
        assert f["tasks/u"].shape == (2, 16)
        for key in ("sim_time", "iteration", "write_number", "timestep",
                    "wall_time"):
            assert f"scales/{key}" in f
        assert list(np.asarray(f["scales/write_number"])) == [1, 2]
    with h5py.File(files[-1], "r") as f:
        assert np.asarray(f["scales/write_number"])[-1] == 6


def test_filehandler_append_continues_numbering(tmp_path):
    import h5py
    out = tmp_path / "snaps"
    solver, u, x = build_heat()
    h = solver.evaluator.add_file_handler(out, iter=1, max_writes=3)
    h.add_task(u, name="u")
    for _ in range(3):
        solver.step(1e-3)
    # second run in append mode continues set and write numbering
    solver2, u2, _ = build_heat()
    h2 = solver2.evaluator.add_file_handler(out, iter=1, max_writes=3,
                                            mode="append")
    h2.add_task(u2, name="u")
    for _ in range(2):
        solver2.step(1e-3)
    files = sorted(out.glob("snaps_s*.h5"))
    assert len(files) == 2
    with h5py.File(files[1], "r") as f:
        assert list(np.asarray(f["scales/write_number"])) == [4, 5]


def test_checkpoint_restart_equivalence(tmp_path):
    """load_state restores sim_time/iteration/fields so a restarted run
    reproduces an uninterrupted one (reference: core/solvers.py:632)."""
    out = tmp_path / "ckpt"
    dt = 1e-3
    # uninterrupted run: 10 steps
    s1, u1, x = build_heat()
    for _ in range(10):
        s1.step(dt)
    X_ref = np.asarray(s1.X)
    # checkpointed run: 5 steps, write, restart into a fresh solver, 5 more
    s2, u2, _ = build_heat()
    h = s2.evaluator.add_file_handler(out, iter=5)
    h.add_tasks(s2.state, layout="g")
    for _ in range(5):
        s2.step(dt)
    s2.evaluator.evaluate_handlers([h], iteration=s2.iteration,
                                   sim_time=s2.sim_time, timestep=dt)
    s3, u3, _ = build_heat()
    files = sorted(out.glob("ckpt_s*.h5"))
    write, dt_loaded = s3.load_state(files[-1])
    assert s3.iteration == 5
    assert abs(s3.sim_time - 5 * dt) < 1e-12
    assert dt_loaded == dt
    for _ in range(5):
        s3.step(dt)
    X_restart = np.asarray(s3.X)
    # SBDF1 carries one step of history; restart matches to history-startup
    # accuracy for a single-step scheme: exact here
    assert np.abs(X_restart - X_ref).max() < 1e-12


def test_filehandler_append_resumes_partial_set(tmp_path):
    import h5py
    out = tmp_path / "snaps"
    solver, u, x = build_heat()
    h = solver.evaluator.add_file_handler(out, iter=1, max_writes=5)
    h.add_task(u, name="u")
    for _ in range(2):
        solver.step(1e-3)
    solver2, u2, _ = build_heat()
    h2 = solver2.evaluator.add_file_handler(out, iter=1, max_writes=5,
                                            mode="append")
    h2.add_task(u2, name="u")
    for _ in range(2):
        solver2.step(1e-3)
    files = sorted(out.glob("snaps_s*.h5"))
    assert len(files) == 1   # resumed into the partially-filled set
    with h5py.File(files[0], "r") as f:
        assert list(np.asarray(f["scales/write_number"])) == [1, 2, 3, 4]


def test_filehandler_grid_dimension_scales(tmp_path):
    """Task datasets carry attached grid dimension scales (reference:
    core/evaluator.py:656-728 setup_file scales), so post-processing can
    recover coordinates from the file alone."""
    import h5py
    solver, u, x = build_heat()
    out = tmp_path / "snaps"
    handler = solver.evaluator.add_file_handler(out, iter=1, max_writes=10)
    handler.add_task(u, name="u", layout="g")
    for _ in range(3):
        solver.step(1e-3)
    files = sorted(out.glob("snaps_s*.h5"))
    with h5py.File(files[0], "r") as f:
        ds = f["tasks/u"]
        assert ds.dims[0].label == "write"
        assert ds.dims[1].label == "x"
        grid = np.asarray(ds.dims[1][0])
        assert grid.shape[0] == ds.shape[1]
        assert np.allclose(grid, np.ravel(x))


def test_post_merge_and_xarray(tmp_path):
    """Set merging + xarray loading (reference: tools/post.py:166,363)."""
    pytest.importorskip("xarray")
    from dedalus_tpu.tools import post
    out = tmp_path / "snaps"
    solver, u, x = build_heat()
    h = solver.evaluator.add_file_handler(out, iter=1, max_writes=2)
    h.add_task(u, name="u")
    for _ in range(5):
        solver.step(1e-3)
    joint = post.merge_sets(out)
    import h5py
    with h5py.File(joint, "r") as f:
        assert f["tasks/u"].shape == (5, 16)
        assert list(np.asarray(f["scales/write_number"])) == [1, 2, 3, 4, 5]
    arrays = post.load_tasks_to_xarray(joint)
    assert arrays["u"].shape == (5, 16)
    assert list(arrays["u"].coords["write_number"].values) == [1, 2, 3, 4, 5]


def test_cli_get_config(capsys):
    from dedalus_tpu import __main__ as cli
    cli.get_config()
    out = capsys.readouterr().out
    assert "MATRIX_SOLVER" in out.upper() or "matrix_solver" in out


def test_op_tree_rendering(tmp_path):
    """tools/plot_op formats and draws expression trees
    (reference: tools/plot_op.py)."""
    import dedalus_tpu.public as d3
    from dedalus_tpu.tools.plot_op import format_op_tree, plot_operator_tree
    coords = d3.CartesianCoordinates("x")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=8, bounds=(0, 1))
    u = dist.Field(name="u", bases=xb)
    expr = d3.lap(u) + u * d3.Differentiate(u, coords["x"])
    text = format_op_tree(expr)
    assert "u" in text and "Lap" in str(text) or "Add" in text
    out = plot_operator_tree(expr, filename=str(tmp_path / "tree.png"))
    import os
    assert os.path.exists(out)
