"""
NLBVP tests (reference: dedalus/tests/test_nlbvp.py).
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3


@pytest.mark.parametrize("dealias", [1, 1.5])
def test_sin_jacobi(dealias):
    """Find cos(x) from the nonlinear ODE dx(u)^2 + u^2 = 1, u(0) = 1
    (reference: tests/test_nlbvp.py:14 test_sin_jacobi)."""
    # tolerance matches the reference: the root is degenerate (v = sin x is
    # a null direction of the Jacobian at u = cos x compatible with the BC),
    # so Newton converges linearly at rate 1/2 here, not quadratically
    N = 12
    tolerance = 1e-6
    coords = d3.CartesianCoordinates("x")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.ChebyshevT(coords["x"], size=N, bounds=(0, 1), dealias=dealias)
    x, = dist.local_grids(xb)
    u = dist.Field(name="u", bases=xb)
    tau = dist.Field(name="tau")
    dx = lambda A: d3.Differentiate(A, coords["x"])
    lift = lambda A: d3.Lift(A, xb.derivative_basis(1), -1)
    problem = d3.NLBVP([u, tau], namespace=locals())
    problem.add_equation("dx(u)**2 + u**2 + lift(tau) = 1")
    problem.add_equation("u(x=0) = 1")
    solver = problem.build_solver()
    u["g"] = 1 - x / 2
    error = np.inf
    while error > tolerance:
        solver.newton_iteration()
        error = solver.perturbation_norm()
        assert solver.iteration <= 20
    assert np.allclose(np.asarray(u["g"]), np.cos(x))


def test_lane_emden():
    """Lane-Emden n=3 stellar structure on the ball: lap(f) = -f^3 with
    floating amplitude; the recovered radius R = f(0)^((n-1)/2) matches
    Boyd's reference value (reference: tests/test_nlbvp.py:92
    test_lane_emden_floating_amp, R_ref[3.0] = 6.896848619376960)."""
    n = 3.0
    Nr = 64
    tolerance = 1e-8
    coords = d3.SphericalCoordinates("phi", "theta", "r")
    dist = d3.Distributor(coords, dtype=np.float64)
    ball = d3.BallBasis(coords, shape=(4, 2, Nr), radius=1.0, dealias=2)
    phi, theta, r = dist.local_grids(ball)
    f = dist.Field(name="f", bases=ball)
    tau = dist.Field(name="tau", bases=ball.surface)
    lift = lambda A: d3.Lift(A, ball, -1)
    problem = d3.NLBVP([f, tau], namespace=locals())
    problem.add_equation("lap(f) + lift(tau) = - f**3")
    problem.add_equation("f(r=1) = 0")
    solver = problem.build_solver()
    f["g"] = 5 * np.cos(np.pi / 2 * r) ** 2
    error = np.inf
    iters = 0
    while error > tolerance and iters < 30:
        solver.newton_iteration()
        error = solver.perturbation_norm()
        iters += 1
    assert error < tolerance
    f0 = np.asarray(d3.Interpolate(f, coords["r"], 0.0).evaluate()["g"]).ravel()[0]
    R = f0 ** ((n - 1) / 2)
    assert abs(R - 6.896848619376960) < 1e-5
