"""
Two-process distributed execution test (reference: dedalus runs on any MPI
world, tests_parallel/ under mpiexec; here two REAL jax.distributed
processes on localhost, each owning 4 virtual CPU devices of a global
8-device mesh).
"""

import os
import socket
import subprocess
import sys

import pytest

WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])
from dedalus_tpu.parallel import multihost as mh

pid = int(sys.argv[1])
mh.initialize(coordinator_address=os.environ["COORD"], num_processes=2,
              process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8

import dedalus_tpu.public as d3
from dedalus_tpu.parallel import distribute_solver

mesh = mh.device_mesh()
coords = d3.CartesianCoordinates("x", "z")
dist = d3.Distributor(coords, dtype=np.float64)
xb = d3.RealFourier(coords["x"], size=32, bounds=(0, 4.0), dealias=3/2)
zb = d3.ChebyshevT(coords["z"], size=16, bounds=(0, 1.0), dealias=3/2)
u = dist.Field(name="u", bases=(xb, zb))
t1 = dist.Field(name="t1", bases=xb)
t2 = dist.Field(name="t2", bases=xb)
lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
problem = d3.IVP([u, t1, t2], namespace=locals())
problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = - u*u")
problem.add_equation("u(z=0) = 0")
problem.add_equation("u(z=1) = 0")
solver = problem.build_solver(d3.SBDF2)
x, z = dist.local_grids(xb, zb)
u["g"] = np.sin(np.pi * z) * (1 + 0.3 * np.cos(np.pi * x / 2))
distribute_solver(solver, mesh)
for _ in range(3):
    solver.step(1e-3)
import jax.numpy as jnp
finite = bool(jax.jit(lambda X: jnp.all(jnp.isfinite(X)))(solver.X))
assert finite
norm = float(jax.jit(lambda X: jnp.linalg.norm(X))(solver.X))
Xfull = mh.process_allgather(solver.X)
mh.barrier("done")
print(f"WORKER_OK {pid} norm={norm:.12e} shape={Xfull.shape}", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.skipif(os.environ.get("SKIP_MULTIHOST") == "1",
                    reason="multihost disabled")
def test_two_process_sharded_step(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["COORD"] = f"localhost:{_free_port()}"
    env["REPO"] = repo
    env.pop("JAX_PLATFORMS", None)
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    procs = [subprocess.Popen([sys.executable, str(script), str(i)], env=env,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True,
                              start_new_session=True)
             for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost workers timed out")
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\n{err[-2000:]}"
        assert "WORKER_OK" in out
    # both processes agree on the global norm
    norms = [out.split("norm=")[1].split()[0] for _, out, _ in outs]
    assert norms[0] == norms[1]


OUTPUT_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])
from dedalus_tpu.parallel import multihost as mh

pid = int(sys.argv[1])
out_dir = sys.argv[2]
mh.initialize(coordinator_address=os.environ["COORD"], num_processes=2,
              process_id=pid)

import dedalus_tpu.public as d3
from dedalus_tpu.parallel import distribute_solver

mesh = mh.device_mesh()
coords = d3.CartesianCoordinates("x", "z")
dist = d3.Distributor(coords, dtype=np.float64)
xb = d3.RealFourier(coords["x"], size=32, bounds=(0, 4.0), dealias=3/2)
zb = d3.ChebyshevT(coords["z"], size=16, bounds=(0, 1.0), dealias=3/2)
u = dist.Field(name="u", bases=(xb, zb))
t1 = dist.Field(name="t1", bases=xb)
t2 = dist.Field(name="t2", bases=xb)
lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
problem = d3.IVP([u, t1, t2], namespace=locals())
problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = - u*u")
problem.add_equation("u(z=0) = 0")
problem.add_equation("u(z=1) = 0")
solver = problem.build_solver(d3.SBDF2)
x, z = dist.local_grids(xb, zb)
u["g"] = np.sin(np.pi * z) * (1 + 0.3 * np.cos(np.pi * x / 2))
distribute_solver(solver, mesh)

# analysis file: primary-gated writes backed by collective allgather
# (reference: tests_parallel/test_output_parallel.py:48-59)
snaps = solver.evaluator.add_file_handler(out_dir, iter=2)
snaps.add_task(u, name="u")
snaps.add_task(d3.Differentiate(u, coords["x"]), name="ux")
for _ in range(4):
    solver.step(1e-3)   # writes land after iterations 2 and 4
mh.barrier("writes_done")

# check the file against locally evaluated (gathered) task data
u.change_scales(1)
u_now = np.asarray(u["g"])  # field data is process-locally global
import h5py
with h5py.File(os.path.join(out_dir, os.path.basename(out_dir) + "_s1.h5"),
               "r") as f:
    wn = np.asarray(f["scales/write_number"])
    data = np.asarray(f["tasks/u"])
assert len(wn) == 3, wn          # initial write + iters 2 and 4
err = np.abs(data[-1] - u_now).max()
assert err < 1e-12, err
mh.barrier("checked")
print(f"OUTPUT_OK {pid} writes={len(wn)}", flush=True)
"""


@pytest.mark.skipif(os.environ.get("SKIP_MULTIHOST") == "1",
                    reason="multihost disabled")
def test_two_process_file_output(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["COORD"] = f"localhost:{_free_port()}"
    env["REPO"] = repo
    env.pop("JAX_PLATFORMS", None)
    script = tmp_path / "worker_out.py"
    script.write_text(OUTPUT_WORKER)
    out_dir = tmp_path / "snap_mh"
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), str(out_dir)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost output workers timed out")
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\n{err[-2000:]}"
        assert "OUTPUT_OK" in out
    # exactly one file set, written once (no double-writes from rank 1)
    files = sorted(out_dir.glob("*.h5"))
    assert len(files) == 1, files


RESTART_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])
from dedalus_tpu.parallel import multihost as mh

pid = int(sys.argv[1])
ckpt_dir = sys.argv[2]
mh.initialize(coordinator_address=os.environ["COORD"], num_processes=2,
              process_id=pid)

import dedalus_tpu.public as d3
from dedalus_tpu.parallel import distribute_solver

mesh = mh.device_mesh()

def build():
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=32, bounds=(0, 4.0), dealias=3/2)
    zb = d3.ChebyshevT(coords["z"], size=16, bounds=(0, 1.0), dealias=3/2)
    u = dist.Field(name="u", bases=(xb, zb))
    t1 = dist.Field(name="t1", bases=xb)
    t2 = dist.Field(name="t2", bases=xb)
    lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
    problem = d3.IVP([u, t1, t2], namespace=locals())
    problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = - u*u")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("u(z=1) = 0")
    solver = problem.build_solver(d3.SBDF1)
    x, z = dist.local_grids(xb, zb)
    u["g"] = np.sin(np.pi * z) * (1 + 0.3 * np.cos(np.pi * x / 2))
    distribute_solver(solver, mesh)
    return solver

dt = 1e-3
# uninterrupted sharded run: 10 steps
s1 = build()
for _ in range(10):
    s1.step(dt)
X_ref = mh.process_allgather(s1.X)

# checkpointed sharded run: 5 steps + checkpoint write (primary-gated)
s2 = build()
h = s2.evaluator.add_file_handler(ckpt_dir, iter=5)
h.add_tasks(s2.state, layout="g")
for _ in range(5):
    s2.step(dt)
s2.evaluator.evaluate_handlers([h], iteration=s2.iteration,
                               sim_time=s2.sim_time, timestep=dt)
mh.barrier("ckpt_written")

# restart into a FRESH sharded solver on both processes; 5 more steps
s3 = build()
import glob
files = sorted(glob.glob(os.path.join(ckpt_dir, "*.h5")))
assert files, "no checkpoint written"
write, dt_loaded = s3.load_state(files[-1])
assert s3.iteration == 5
assert dt_loaded == dt
for _ in range(5):
    s3.step(dt)
X_restart = mh.process_allgather(s3.X)
err = np.abs(X_restart - X_ref).max()
assert err < 1e-12, err
norm = float(np.linalg.norm(X_restart))
mh.barrier("restart_checked")
print(f"RESTART_OK {pid} norm={norm:.12e}", flush=True)
"""


@pytest.mark.skipif(os.environ.get("SKIP_MULTIHOST") == "1",
                    reason="multihost disabled")
def test_two_process_checkpoint_restart(tmp_path):
    """Sharded checkpoint write + restart across 2 real processes
    reproduces the uninterrupted sharded trajectory, and both agree with
    a SINGLE-process run of the same problem (reference pattern:
    tests_parallel/test_output_parallel.py + core/solvers.py:632)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["COORD"] = f"localhost:{_free_port()}"
    env["REPO"] = repo
    env.pop("JAX_PLATFORMS", None)
    script = tmp_path / "worker_restart.py"
    script.write_text(RESTART_WORKER)
    ckpt_dir = tmp_path / "ckpt_mh"
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), str(ckpt_dir)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost restart workers timed out")
    if any(rc != 0 for rc, _, _ in outs):
        # report EVERY worker: a rank that dies first takes the others
        # down through the shutdown barrier (rc=-6 abort), so the first
        # failing rc in order is usually the secondary victim and the
        # root cause lives in the other rank's tail
        report = "\n".join(
            f"--- worker {i} rc={rc}\n{err[-2000:]}"
            for i, (rc, _, err) in enumerate(outs))
        pytest.fail(f"multihost restart workers failed\n{report}")
    for rc, out, err in outs:
        assert "RESTART_OK" in out
    norms = [out.split("norm=")[1].split()[0] for _, out, _ in outs]
    assert norms[0] == norms[1]
    # single-process oracle of the same 10-step trajectory
    single = subprocess.run(
        [sys.executable, "-c", SINGLE_ORACLE], env={**env, "REPO": repo},
        capture_output=True, text=True, timeout=600)
    assert single.returncode == 0, single.stderr[-2000:]
    norm_single = single.stdout.split("norm=")[1].split()[0]
    assert abs(float(norm_single) - float(norms[0])) < 1e-10


SINGLE_ORACLE = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])
import dedalus_tpu.public as d3
coords = d3.CartesianCoordinates("x", "z")
dist = d3.Distributor(coords, dtype=np.float64)
xb = d3.RealFourier(coords["x"], size=32, bounds=(0, 4.0), dealias=3/2)
zb = d3.ChebyshevT(coords["z"], size=16, bounds=(0, 1.0), dealias=3/2)
u = dist.Field(name="u", bases=(xb, zb))
t1 = dist.Field(name="t1", bases=xb)
t2 = dist.Field(name="t2", bases=xb)
lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
problem = d3.IVP([u, t1, t2], namespace=locals())
problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = - u*u")
problem.add_equation("u(z=0) = 0")
problem.add_equation("u(z=1) = 0")
solver = problem.build_solver(d3.SBDF1)
x, z = dist.local_grids(xb, zb)
u["g"] = np.sin(np.pi * z) * (1 + 0.3 * np.cos(np.pi * x / 2))
for _ in range(10):
    solver.step(1e-3)
print(f"norm={float(np.linalg.norm(np.asarray(solver.X))):.12e}")
"""
