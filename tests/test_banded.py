"""
Banded + pinned-Woodbury pencil solve vs the dense reference path
(reference test pattern: dual-implementation oracle,
/root/reference/dedalus/tests/test_transforms.py — here the oracle is the
dense (G,S,S) batched solve).
"""

import numpy as np
import pytest
import jax.numpy as jnp

import dedalus_tpu.public as d3


def build_rb(Nx, Nz, matsolver=None, timestepper=None, dtype=np.float64):
    Lx, Lz = 4.0, 1.0
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=dtype)
    xbasis = d3.RealFourier(coords["x"], size=Nx, bounds=(0, Lx), dealias=3/2)
    zbasis = d3.ChebyshevT(coords["z"], size=Nz, bounds=(0, Lz), dealias=3/2)
    p = dist.Field(name="p", bases=(xbasis, zbasis))
    b = dist.Field(name="b", bases=(xbasis, zbasis))
    u = dist.VectorField(coords, name="u", bases=(xbasis, zbasis))
    tau_p = dist.Field(name="tau_p")
    tau_b1 = dist.Field(name="tau_b1", bases=xbasis)
    tau_b2 = dist.Field(name="tau_b2", bases=xbasis)
    tau_u1 = dist.VectorField(coords, name="tau_u1", bases=xbasis)
    tau_u2 = dist.VectorField(coords, name="tau_u2", bases=xbasis)
    kappa = nu = 2.0e-6 ** 0.5
    x, z = dist.local_grids(xbasis, zbasis)
    ex, ez = coords.unit_vector_fields(dist)
    lift_basis = zbasis.derivative_basis(1)
    lift = lambda A: d3.Lift(A, lift_basis, -1)
    grad_u = d3.grad(u) + ez*lift(tau_u1)
    grad_b = d3.grad(b) + ez*lift(tau_b1)
    problem = d3.IVP([p, b, u, tau_p, tau_b1, tau_b2, tau_u1, tau_u2],
                     namespace=locals())
    problem.add_equation("trace(grad_u) + tau_p = 0")
    problem.add_equation("dt(b) - kappa*div(grad_b) + lift(tau_b2) = - u@grad(b)")
    problem.add_equation("dt(u) - nu*div(grad_u) + grad(p) - b*ez + lift(tau_u2) = - u@grad(u)")
    problem.add_equation("b(z=0) = Lz")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("b(z=Lz) = 0")
    problem.add_equation("u(z=Lz) = 0")
    problem.add_equation("integ(p) = 0")
    solver = problem.build_solver(timestepper or d3.RK222, matsolver=matsolver)
    b.fill_random("g", seed=42, distribution="normal", scale=1e-3)
    b["g"] += (Lz - z)
    return solver


@pytest.mark.parametrize("timestepper", [d3.RK222, d3.SBDF2])
def test_rb_banded_matches_dense(timestepper):
    sd = build_rb(16, 64, timestepper=timestepper)
    sb = build_rb(16, 64, matsolver="banded", timestepper=timestepper)
    assert sd.ops.kind == "dense"
    assert sb.ops.kind == "banded"
    for _ in range(5):
        sd.step(0.01)
        sb.step(0.01)
    Xd, Xb = np.asarray(sd.X), np.asarray(sb.X)
    assert np.isfinite(Xd).all()
    assert np.abs(Xd - Xb).max() < 1e-11


def test_rb_banded_structure_scales():
    """Pins and bandwidth must be resolution-independent: storage is
    O(G * S * band), enabling the RB 2048x1024 target (VERDICT item 2)."""
    stats = []
    for Nz in (64, 256):
        s = build_rb(8, Nz, matsolver="banded")
        st = s.structure
        stats.append((st.t_pins, st.kl, st.ku))
    assert stats[0] == stats[1]
    # storage for M+L at Nz=256 stays far below dense G*S^2
    s = build_rb(8, 256, matsolver="banded")
    nbytes = sum(a.nbytes for n in ("M", "L") for a in s._matrices[n].values()
                 if hasattr(a, "nbytes"))
    G, S = s.pencil_shape
    assert nbytes < 0.1 * (2 * G * S * S * 8)


def test_rb_banded_matvec_matches_densified():
    s = build_rb(8, 32, matsolver="banded")
    G, S = s.pencil_shape
    rng = np.random.default_rng(1)
    x = rng.standard_normal((G, S))
    for name, mat in (("M", s.M_mat), ("L", s.L_mat)):
        y = np.asarray(s.ops.matvec(mat, jnp.asarray(x)))
        for g in range(G):
            A = s.ops.densify_host(s._matrices[name], g)
            assert np.abs(y[g] - A @ x[g]).max() < 1e-10


def build_poisson(matsolver=None):
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 2*np.pi))
    zb = d3.ChebyshevT(coords["z"], size=64, bounds=(0, 1))
    u = dist.Field(name="u", bases=(xb, zb))
    tau1 = dist.Field(name="tau1", bases=xb)
    tau2 = dist.Field(name="tau2", bases=xb)
    f = dist.Field(name="f", bases=(xb, zb))
    x, z = dist.local_grids(xb, zb)
    f["g"] = np.sin(2*x)*np.cos(np.pi*z)
    lift_basis = zb.derivative_basis(1)
    lift = lambda A, n: d3.Lift(A, lift_basis, n)
    problem = d3.LBVP([u, tau1, tau2], namespace=locals())
    problem.add_equation("lap(u) + lift(tau1,-1) + lift(tau2,-2) = f")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("u(z=1) = 0")
    solver = problem.build_solver(matsolver=matsolver)
    solver.solve()
    return np.asarray(u["g"])


def test_lbvp_banded_matches_dense():
    """The pure-elliptic LBVP is the hard case: a boundary-row Schur
    complement is exponentially ill-conditioned here; the pinned Woodbury
    form must still solve it to near machine precision."""
    ud = build_poisson()
    ub = build_poisson(matsolver="banded")
    assert np.abs(ud).max() > 1e-3
    assert np.abs(ud - ub).max() < 1e-12


def build_ball(matsolver=None):
    coords = d3.SphericalCoordinates("phi", "theta", "r")
    dist = d3.Distributor(coords, dtype=np.float64)
    ball = d3.BallBasis(coords, shape=(8, 4, 16), radius=1.0)
    u = dist.Field(name="u", bases=ball)
    tau = dist.Field(name="tau", bases=ball.surface)
    lift = lambda A: d3.Lift(A, ball, -1)
    problem = d3.IVP([u, tau], namespace=locals())
    problem.add_equation("dt(u) - lap(u) + lift(tau) = 0")
    problem.add_equation("u(r=1) = 0")
    solver = problem.build_solver(d3.SBDF2, matsolver=matsolver)
    u.fill_random("g", seed=7, scale=1e-2)
    for _ in range(5):
        solver.step(1e-3)
    return np.asarray(solver.X)


def test_ball_banded_matches_dense():
    """Curvilinear (per-ell coupled radial) pencils on the banded path."""
    Xd = build_ball()
    Xb = build_ball(matsolver="banded")
    assert np.isfinite(Xd).all()
    assert np.abs(Xd).max() > 1e-6
    assert np.abs(Xd - Xb).max() < 1e-12


def test_auto_selects_dense_for_small():
    s = build_rb(8, 16)
    assert s.ops.kind == "dense"


@pytest.mark.parametrize("timestepper", [d3.RK222, d3.SBDF2])
def test_rb_banded_chunked_matches_dense(timestepper):
    """G-chunked factorization/solve (lax.map over pencil-batch chunks,
    the HBM-bounding path for RB 2048x1024) must reproduce the dense
    answer exactly like the unchunked banded path."""
    from dedalus_tpu.tools.config import config
    sd = build_rb(16, 64, timestepper=timestepper)
    old = config["linear algebra"].get("BANDED_CHUNK_MB")
    config["linear algebra"]["BANDED_CHUNK_MB"] = "0.01"
    try:
        sb = build_rb(16, 64, matsolver="banded", timestepper=timestepper)
        assert sb.ops.kind == "banded"
        for _ in range(5):
            sd.step(0.01)
            sb.step(0.01)
        assert sb.ops._g_chunks > 1
    finally:
        config["linear algebra"]["BANDED_CHUNK_MB"] = old
    Xd, Xb = np.asarray(sd.X), np.asarray(sb.X)
    assert np.isfinite(Xd).all()
    assert np.abs(Xd - Xb).max() < 1e-11


def test_rb_banded_chunk_padding_matches_dense():
    """Group counts with no convenient divisor edge-pad the chunked batch
    (C*Gc > G) instead of degenerating to size-1 sequential chunks."""
    from dedalus_tpu.tools.config import config
    sd = build_rb(14, 64)
    sb0 = build_rb(14, 64, matsolver="banded")
    ops = sb0.ops
    G = sb0.pencil_shape[0]
    assert G % 2 == 1, "want an odd group count to force padding"
    # target exactly two groups per chunk -> C = ceil(G/2), G_pad = C*2 > G
    per_g = ops.NB * 2 * ops.q * ops.q * 2 * np.dtype(sb0.pencil_dtype).itemsize
    old = config["linear algebra"].get("BANDED_CHUNK_MB")
    # 2.05x margin: the /1e6 str round-trip must not land below 2*per_g
    config["linear algebra"]["BANDED_CHUNK_MB"] = str(2.05 * per_g / 1e6)
    try:
        sb = build_rb(14, 64, matsolver="banded")
        for _ in range(5):
            sd.step(0.01)
            sb.step(0.01)
        C = sb.ops._g_chunks
        assert C > 1 and G % C != 0, f"padding path not engaged (G={G}, C={C})"
    finally:
        config["linear algebra"]["BANDED_CHUNK_MB"] = old
    Xd, Xb = np.asarray(sd.X), np.asarray(sb.X)
    assert np.isfinite(Xd).all()
    assert np.abs(Xd - Xb).max() < 1e-11


@pytest.mark.parametrize("timestepper", [d3.RK222, d3.SBDF2])
def test_rb_banded_incremental_factor_matches_dense(timestepper):
    """Incremental (per-chunk dispatch, donated-store) factorization — the
    HBM-peak-capping mode for RB 2048x1024 — must reproduce the dense
    answer exactly like the fused factor."""
    from dedalus_tpu.tools.config import config
    sd = build_rb(16, 64, timestepper=timestepper)
    la = config["linear algebra"]
    old = (la.get("BANDED_CHUNK_MB"), la.get("BANDED_FACTOR_MODE", "auto"))
    la["BANDED_CHUNK_MB"] = "0.01"
    la["BANDED_FACTOR_MODE"] = "incremental"
    try:
        sb = build_rb(16, 64, matsolver="banded", timestepper=timestepper)
        assert sb.ops.kind == "banded"
        for _ in range(5):
            sd.step(0.01)
            sb.step(0.01)
        assert sb.ops._g_chunks > 1
    finally:
        la["BANDED_CHUNK_MB"] = old[0]
        la["BANDED_FACTOR_MODE"] = old[1]
    Xd, Xb = np.asarray(sd.X), np.asarray(sb.X)
    assert np.isfinite(Xd).all()
    assert np.abs(Xd - Xb).max() < 1e-11


def test_lbvp_banded_chunked_matches_dense():
    """factor()/solve() (LBVP path) under forced chunking."""
    from dedalus_tpu.tools.config import config
    ud = build_poisson()
    old = config["linear algebra"].get("BANDED_CHUNK_MB")
    config["linear algebra"]["BANDED_CHUNK_MB"] = "0.01"
    try:
        ub = build_poisson(matsolver="banded")
    finally:
        config["linear algebra"]["BANDED_CHUNK_MB"] = old
    assert np.abs(ud - ub).max() < 1e-12


def build_rb_dtype(Nz, dtype, matsolver):
    """RB column at a given dtype/matsolver for precision comparisons."""
    return build_rb(16, Nz, matsolver=matsolver, dtype=dtype)


def test_f32_inverse_accuracy_vs_f64_lu():
    """The TPU default solvers (explicit batched inverse; f32) must track
    the f64 LU oracle on a realistic tau-bordered RB pencil system
    (VERDICT weak item 3: the dense-inverse numerics were untested)."""
    s64 = build_rb_dtype(64, np.float64, "BatchedLUFactorized")
    s32 = build_rb_dtype(64, np.float32, "BatchedInverse")
    for _ in range(10):
        s64.step(0.01)
        s32.step(0.01)
    X64 = np.asarray(s64.X)
    X32 = np.asarray(s32.X)
    assert np.isfinite(X32).all()
    scale = np.abs(X64).max()
    assert scale > 1e-6
    # f32 arithmetic + inverse: expect ~1e-5 relative trajectory agreement
    assert np.abs(X64 - X32).max() / scale < 5e-4


def test_refined_inverse_matches_lu_f64():
    """BatchedInverseRefined (f32 inverse + f64 residual polish, the TPU
    path for 64-bit problems) must reach near-f64 accuracy."""
    s_lu = build_rb_dtype(64, np.float64, "BatchedLUFactorized")
    s_ref = build_rb_dtype(64, np.float64, "BatchedInverseRefined")
    for _ in range(10):
        s_lu.step(0.01)
        s_ref.step(0.01)
    Xl = np.asarray(s_lu.X)
    Xr = np.asarray(s_ref.X)
    scale = np.abs(Xl).max()
    assert scale > 1e-6
    assert np.abs(Xl - Xr).max() / scale < 1e-9


def test_banded_min_q_reblocking_equivalence():
    """BANDED_MIN_Q re-blocks the same banded lattice with larger q
    (fewer, fatter scan steps for TPU latency); the solve must agree with
    the structural-q path to rounding."""
    import numpy as np
    from dedalus_tpu.tools.config import config
    from dedalus_tpu.extras.bench_problems import build_rb_solver

    def run(min_q):
        old_s = config["linear algebra"].get("MATRIX_SOLVER", "auto")
        old_q = config["linear algebra"].get("BANDED_MIN_Q", "0")
        config["linear algebra"]["MATRIX_SOLVER"] = "banded"
        config["linear algebra"]["BANDED_MIN_Q"] = str(min_q)
        try:
            solver, b = build_rb_solver(64, 32, np.float64)
            for _ in range(5):
                solver.step(1e-3)
            return np.asarray(solver.X, np.float64), solver.ops
        finally:
            config["linear algebra"]["MATRIX_SOLVER"] = old_s
            config["linear algebra"]["BANDED_MIN_Q"] = old_q

    X0, ops0 = run(0)
    X1, ops1 = run(128)
    assert ops1.q == 128 and ops1.NB < ops0.NB
    assert np.abs(X1 - X0).max() / np.abs(X0).max() < 1e-11
