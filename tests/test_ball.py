"""
Ball basis tests: transforms, regularity calculus, NCCs, LBVPs, diffusion
eigenvalue, and the stress-free boundary-condition machinery
(reference patterns: dedalus/tests/test_transforms.py,
tests/test_spherical_calculus.py, tests/test_ivp.py:56 ball diffusion,
tests/ball_diffusion_analytical_eigenvalues.py).
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3

R = 1.5


def make_ball(dtype, shape=(12, 8, 10), radius=R, dealias=1):
    cs = d3.SphericalCoordinates("phi", "theta", "r")
    dist = d3.Distributor(cs, dtype=dtype)
    ball = d3.BallBasis(cs, shape=shape, dtype=dtype, radius=radius,
                        dealias=dealias)
    return cs, dist, ball


def xyz(phi, theta, r):
    return (r * np.sin(theta) * np.cos(phi),
            r * np.sin(theta) * np.sin(phi),
            r * np.cos(theta))


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_ball_scalar_roundtrip(dtype):
    cs, dist, ball = make_ball(dtype)
    phi, theta, r = dist.local_grids(ball)
    x, y, z = xyz(phi, theta, r)
    f = dist.Field(name="f", bases=ball)
    f["g"] = x * y + z ** 2 + x + 3
    g0 = np.array(f["g"])
    f["c"] = f["c"]
    assert np.abs(f["g"] - g0).max() < 1e-12


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_ball_vector_roundtrip(dtype):
    cs, dist, ball = make_ball(dtype)
    phi, theta, r = dist.local_grids(ball)
    x, y, z = xyz(phi, theta, r)
    f = dist.Field(name="f", bases=ball)
    f["g"] = x * y * z + z ** 3 + x
    u = d3.grad(f).evaluate()
    g0 = np.array(u["g"])
    u["c"] = u["c"]
    assert np.abs(u["g"] - g0).max() < 1e-11


def test_ball_calculus():
    cs, dist, ball = make_ball(np.float64)
    phi, theta, r = dist.local_grids(ball)
    x, y, z = xyz(phi, theta, r)
    f = dist.Field(name="f", bases=ball)
    f["g"] = x * y + z ** 2 + x + 3
    assert np.abs(d3.lap(f).evaluate()["g"] - 2.0).max() < 1e-9
    assert np.abs(d3.div(d3.grad(f)).evaluate()["g"] - 2.0).max() < 1e-9
    assert np.abs(d3.curl(d3.grad(f)).evaluate()["g"]).max() < 1e-9
    # curl of rigid rotation u = z_hat x r is 2 z_hat
    vxc, vyc, vzc = -y, x, 0 * z
    u = dist.VectorField(cs, name="u", bases=ball)
    u["g"] = np.array([
        -np.sin(phi) * vxc + np.cos(phi) * vyc,
        np.cos(theta) * np.cos(phi) * vxc + np.cos(theta) * np.sin(phi) * vyc
        - np.sin(theta) * vzc,
        np.sin(theta) * np.cos(phi) * vxc + np.sin(theta) * np.sin(phi) * vyc
        + np.cos(theta) * vzc])
    c = d3.curl(u).evaluate()["g"]
    expect_theta = -np.sin(theta) * 2 + 0 * x
    expect_r = np.cos(theta) * 2 + 0 * x
    assert np.abs(c[0]).max() < 1e-10
    assert np.abs(c[1] - expect_theta).max() < 1e-10
    assert np.abs(c[2] - expect_r).max() < 1e-10


def test_ball_cross_product_orientation():
    """cross() respects the left-handed (phi, theta, r) component ordering."""
    cs, dist, ball = make_ball(np.float64)
    phi, theta, r = dist.local_grids(ball)
    x, y, z = xyz(phi, theta, r)
    # u = x_hat, v = y_hat -> u x v = z_hat
    zero = 0 * (phi + theta + r)
    u = dist.VectorField(cs, name="u", bases=ball)
    v = dist.VectorField(cs, name="v", bases=ball)
    u["g"] = np.array([-np.sin(phi) + zero,
                       np.cos(theta) * np.cos(phi) + zero,
                       np.sin(theta) * np.cos(phi) + zero])
    v["g"] = np.array([np.cos(phi) + zero,
                       np.cos(theta) * np.sin(phi) + zero,
                       np.sin(theta) * np.sin(phi) + zero])
    w = d3.cross(u, v).evaluate()["g"]
    expect = np.array([zero, -np.sin(theta) + zero, np.cos(theta) + zero])
    assert np.abs(w - expect).max() < 1e-12


def test_ball_interpolation_and_integration():
    cs, dist, ball = make_ball(np.float64)
    phi, theta, r = dist.local_grids(ball)
    x, y, z = xyz(phi, theta, r)
    f = dist.Field(name="f", bases=ball)
    f["g"] = x * y + z ** 2 + x + 3
    phig, thetag = phi[:, :, 0], theta[:, :, 0]
    xo, yo, zo = xyz(phig, thetag, R)
    fo = f(r=R).evaluate()["g"]
    assert np.abs(fo[:, :, 0] - (xo * yo + zo ** 2 + xo + 3)).max() < 1e-11
    total = float(d3.integ(f).evaluate()["g"].ravel()[0])
    exact = 4 * np.pi / 3 * R ** 3 * 3 + 4 * np.pi / 3 * R ** 5 / 5
    assert abs(total - exact) < 1e-11


def test_ball_ncc():
    cs, dist, ball = make_ball(np.float64, shape=(8, 6, 12), dealias=3 / 2)
    phi, theta, r = dist.local_grids(ball)
    x, y, z = xyz(phi, theta, r)
    ncc = dist.Field(name="ncc", bases=ball)
    ncc["g"] = np.asarray(r) ** 2 + 1
    v = dist.Field(name="v", bases=ball)
    w = dist.Field(name="w", bases=ball)
    problem = d3.LBVP([v], namespace=locals())
    problem.add_equation("ncc*v = ncc*w")
    w["g"] = x * z + np.asarray(r) ** 2
    problem.build_solver().solve()
    assert np.abs(np.asarray(v["g"]) - np.asarray(w["g"])).max() < 1e-12


def test_ball_rvec_ncc():
    cs, dist, ball = make_ball(np.float64, shape=(8, 6, 12), dealias=3 / 2)
    phi, theta, r = dist.local_grids(ball)
    x, y, z = xyz(phi, theta, r)
    rvec = dist.VectorField(cs, name="rvec", bases=ball)
    rvec["g"][2] = np.broadcast_to(np.asarray(r),
                                   np.asarray(rvec["g"])[2].shape)
    v = dist.Field(name="v", bases=ball)
    w = dist.VectorField(cs, name="w", bases=ball)
    f = dist.Field(name="f", bases=ball)
    f["g"] = x * z + np.asarray(r) ** 2
    problem = d3.LBVP([v, w], namespace=locals())
    problem.add_equation("w - rvec*v = 0")
    problem.add_equation("v = f")
    problem.build_solver().solve()
    expect = np.zeros_like(np.asarray(w["g"]))
    expect[2] = np.asarray(f["g"]) * np.asarray(r)
    assert np.abs(np.asarray(w["g"]) - expect).max() < 1e-12


def test_ball_scalar_poisson_lbvp():
    cs, dist, ball = make_ball(np.float64, shape=(8, 6, 12))
    phi, theta, r = dist.local_grids(ball)
    u = dist.Field(name="u", bases=ball)
    t1 = dist.Field(name="t1", bases=ball.surface)
    six = dist.Field(name="six", bases=ball)
    six["g"] = 6.0
    lift = lambda A, n: d3.Lift(A, ball.derivative_basis(2), n)
    problem = d3.LBVP([u, t1], namespace={**locals(), "R": R})
    problem.add_equation("lap(u) + lift(t1, -1) = six")
    problem.add_equation("u(r=R) = R**2")
    problem.build_solver().solve()
    assert np.abs(np.asarray(u["g"]) - np.asarray(r) ** 2).max() < 1e-12


def test_ball_diffusion_bessel_rate():
    """Lowest diffusion decay rate in the unit ball is (pi/R)^2 (first zero
    of j_0; reference: tests/ball_diffusion_analytical_eigenvalues.py)."""
    cs, dist, ball = make_ball(np.float64, shape=(4, 4, 16), radius=1.0)
    phi, theta, r = dist.local_grids(ball)
    u = dist.Field(name="u", bases=ball)
    t1 = dist.Field(name="t1", bases=ball.surface)
    lift = lambda A, n: d3.Lift(A, ball.derivative_basis(2), n)
    problem = d3.IVP([u, t1], namespace=locals())
    problem.add_equation("dt(u) - lap(u) + lift(t1, -1) = 0")
    problem.add_equation("u(r=1.0) = 0")
    solver = problem.build_solver(d3.SBDF2)
    u["g"] = np.sinc(np.asarray(r))  # j_0(pi r)
    E0 = float(d3.integ(u * u).evaluate()["g"].ravel()[0])
    n, dt_ = 400, 5e-5
    for _ in range(n):
        solver.step(dt_)
    E1 = float(d3.integ(u * u).evaluate()["g"].ravel()[0])
    rate = -np.log(E1 / E0) / (2 * n * dt_)
    assert abs(rate - np.pi ** 2) < 1e-2


def test_ball_vector_diffusion_smoke():
    """Ball vector diffusion IVP stays finite with exact BCs
    (reference: tests/test_ivp.py:56)."""
    cs, dist, ball = make_ball(np.float64, shape=(8, 6, 10), radius=1.0,
                               dealias=3 / 2)
    phi, theta, r = dist.local_grids(ball)
    x, y, z = xyz(phi, theta, r)
    u = dist.VectorField(cs, name="u", bases=ball)
    t1 = dist.VectorField(cs, name="t1", bases=ball.surface)
    lift = lambda A, n: d3.Lift(A, ball.derivative_basis(2), n)
    problem = d3.IVP([u, t1], namespace=locals())
    problem.add_equation("dt(u) - lap(u) + lift(t1, -1) = - u@grad(u)")
    problem.add_equation("u(r=1.0) = 0")
    solver = problem.build_solver(d3.RK222)
    h = dist.Field(name="h", bases=ball)
    h["g"] = (1 - np.asarray(r) ** 2) ** 2 * (1 + 0.2 * x)
    u["g"] = np.asarray(d3.grad(h).evaluate()["g"])
    for _ in range(20):
        solver.step(1e-3)
    # NOTE: check BCs before reading u['g'] -- a grid read roundtrips through
    # the quadrature-limited transforms, truncating the top nmin(ell) radial
    # modes (reference truncation: core/transforms.py:1408-1417).
    assert np.abs(u(r=1.0).evaluate()["g"]).max() < 1e-10
    assert np.all(np.isfinite(np.asarray(u["g"])))


def test_ball_stress_free_setup():
    """Stress-free BC machinery: transpose, index-1 radial extraction,
    angular extraction on boundary tensors (reference:
    examples/ivp_ball_internally_heated_convection)."""
    cs, dist, ball = make_ball(np.float64, shape=(8, 6, 10), radius=1.0,
                               dealias=3 / 2)
    phi, theta, r = dist.local_grids(ball)
    u = dist.VectorField(cs, name="u", bases=ball)
    p = dist.Field(name="p", bases=ball)
    tau_p = dist.Field(name="tau_p")
    tau_u = dist.VectorField(cs, name="tau_u", bases=ball.surface)
    lift = lambda A: d3.Lift(A, ball, -1)
    strain_rate = d3.grad(u) + d3.trans(d3.grad(u))
    shear_stress = d3.angular(d3.radial(strain_rate(r=1.0), index=1))
    problem = d3.IVP([p, u, tau_p, tau_u], namespace=locals())
    problem.add_equation("div(u) + tau_p = 0")
    problem.add_equation("dt(u) - lap(u) + grad(p) + lift(tau_u) = - u@grad(u)")
    problem.add_equation("shear_stress = 0")
    problem.add_equation("radial(u(r=1.0)) = 0")
    problem.add_equation("integ(p) = 0")
    solver = problem.build_solver(d3.RK222)
    u.fill_random("g", seed=7, distribution="normal", scale=1e-3)
    for _ in range(10):
        solver.step(1e-3)
    # no-penetration holds (check before any lossy grid read)
    ur = d3.radial(u(r=1.0)).evaluate()["g"]
    assert np.abs(ur).max() < 1e-10
    assert np.all(np.isfinite(np.asarray(u["g"])))
