"""
Sharded/async/elastic checkpoints (tools/dcheckpoint.py).

The contract under test is the durability tier of the distributed
resilience PR:
  * per-shard files + blake2b checksums, manifest-written-last commit:
    a write torn at ANY point (no manifest, truncated shard, silently
    corrupted shard bytes) is quarantined at restore and the PREVIOUS
    manifest is used;
  * asynchronous writes with a bounded in-flight budget: the overrun
    barrier blocks the submitter instead of pinning unbounded device
    memory, and everything submitted lands durably, in order;
  * a real SIGTERM killing the process mid-async-write leaves the
    previous checkpoint valid (the torn directory is invisible);
  * ELASTIC restore: an 8-virtual-device fleet checkpoint restores onto
    4 and 1 devices (and 1 -> 8) with member state EXACTLY equal to the
    source — resharding is placement, not data transformation.

All CPU, deterministic, tier-1 (chaos marker: watchdogged).
"""

import os
import pathlib
import signal
import subprocess
import sys
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import dedalus_tpu.public as d3
from dedalus_tpu.tools import chaos as chaos_mod
from dedalus_tpu.tools import dcheckpoint as dc
from dedalus_tpu.tools.exceptions import CheckpointError

REPO = pathlib.Path(__file__).parent.parent

pytestmark = pytest.mark.chaos

N_DEV = len(jax.devices())
needs_devices = pytest.mark.skipif(N_DEV < 8, reason="needs 8 devices")


def sharded(arr, n_devices):
    """Place an array on a 1-D batch mesh over the first n devices
    (n_devices=1: plain single-device placement)."""
    if n_devices <= 1:
        return jnp.asarray(arr)
    mesh = Mesh(np.array(jax.devices()[:n_devices]), ("batch",))
    return jax.device_put(jnp.asarray(arr), NamedSharding(mesh, P("batch")))


# ------------------------------------------------------------ raw format

@needs_devices
def test_write_restore_roundtrip_sharded_array(tmp_path):
    """An 8-way sharded array writes one file per shard (plus checksums
    and global indices in the manifest) and restores bit-identically;
    host arrays and meta ride along."""
    X = np.arange(16 * 6, dtype=np.float64).reshape(16, 6)
    path = dc.write_checkpoint(
        tmp_path, {"X": sharded(X, 8), "host": np.eye(3)},
        {"iteration": 7, "sim_time": 0.125})
    manifest = dc.read_manifest(path)
    assert len(manifest["arrays"]["X"]["shards"]) == 8
    for shard in manifest["arrays"]["X"]["shards"]:
        assert shard["nbytes"] == X.nbytes // 8
        assert (path / shard["file"]).exists()
    assert len(manifest["arrays"]["host"]["shards"]) == 1
    arrays, meta = dc.load_checkpoint(path)
    assert np.array_equal(arrays["X"], X)
    assert np.array_equal(arrays["host"], np.eye(3))
    assert meta == {"iteration": 7, "sim_time": 0.125}


@needs_devices
def test_elastic_placement_bit_identical(tmp_path):
    """Restored global arrays re-place onto 4, 1, and back to 8 devices
    with bytes exactly equal to the 8-device source."""
    rng = np.random.default_rng(11)
    X = rng.normal(size=(16, 5))
    dc.write_checkpoint(tmp_path / "w8", {"X": sharded(X, 8)}, {})
    restored = dc.restore_latest(tmp_path / "w8")["arrays"]["X"]
    for n in (4, 1):
        placed = sharded(restored, n)
        assert np.array_equal(np.asarray(placed), X)
    # and the reverse direction: written on 1 device, restored onto 8
    dc.write_checkpoint(tmp_path / "w1", {"X": sharded(X, 1)}, {})
    ev = dc.restore_latest(tmp_path / "w1")
    placed8 = sharded(ev["arrays"]["X"], 8)
    assert np.array_equal(np.asarray(placed8), X)


def test_replicated_shards_deduplicated(tmp_path):
    """A replicated-on-mesh array writes ONE shard, not one per device."""
    if N_DEV < 4:
        pytest.skip("needs >= 4 devices")
    mesh = Mesh(np.array(jax.devices()[:4]), ("batch",))
    rep = jax.device_put(jnp.arange(12.0), NamedSharding(mesh, P()))
    assert len(rep.addressable_shards) == 4
    path = dc.write_checkpoint(tmp_path, {"rep": rep}, {})
    manifest = dc.read_manifest(path)
    assert len(manifest["arrays"]["rep"]["shards"]) == 1


# --------------------------------------------- torn/corrupt + quarantine

def _write_two(tmp_path):
    X0 = np.arange(32.0).reshape(8, 4)
    dc.write_checkpoint(tmp_path, {"X": X0}, {"iteration": 1})
    dc.write_checkpoint(tmp_path, {"X": X0 + 100}, {"iteration": 2})
    return X0


def test_torn_manifestless_dir_invisible_and_quarantined(tmp_path):
    """A checkpoint directory without a manifest (the writer died before
    the commit point) falls back to the previous manifest and is
    quarantined out of future walks."""
    X0 = _write_two(tmp_path)
    newest = dc.list_checkpoints(tmp_path)[-1]
    (newest / dc.MANIFEST).unlink()          # sever the commit marker
    event = dc.restore_latest(tmp_path)
    assert event["meta"]["iteration"] == 1
    assert np.array_equal(event["arrays"]["X"], X0)
    assert len(event["fallbacks"]) == 1
    assert "manifest" in event["fallbacks"][0]["reason"]
    assert "quarantined" in event["fallbacks"][0]
    # quarantined: a second walk no longer sees the torn directory
    assert len(dc.list_checkpoints(tmp_path)) == 1
    assert list(tmp_path.glob("quarantine_*"))


@pytest.mark.parametrize("mode", ["garbage", "truncate", "delete"])
def test_corrupt_shard_quarantine_fallback(tmp_path, mode):
    """Every shard-level damage mode — silent byte corruption (checksum
    mismatch), truncation, deletion — is detected at restore and falls
    back to the previous manifest."""
    X0 = _write_two(tmp_path)
    newest = dc.list_checkpoints(tmp_path)[-1]
    chaos_mod.corrupt_shard(newest, mode=mode)
    event = dc.restore_latest(tmp_path)
    assert event["meta"]["iteration"] == 1
    assert np.array_equal(event["arrays"]["X"], X0)
    assert len(event["fallbacks"]) == 1
    if mode == "garbage":
        assert "checksum" in event["fallbacks"][0]["reason"]


def test_all_corrupt_raises_structured(tmp_path):
    _write_two(tmp_path)
    for path in dc.list_checkpoints(tmp_path):
        chaos_mod.corrupt_shard(path, mode="truncate")
    with pytest.raises(CheckpointError) as excinfo:
        dc.restore_latest(tmp_path)
    assert "no loadable sharded checkpoint" in str(excinfo.value)
    # an empty/absent directory is a fresh start, not an error
    assert dc.restore_latest(tmp_path / "nowhere") is None


def test_torn_shard_chaos_fault_fires_once(tmp_path):
    """The chaos torn_shard fault kills the Nth write after K shards —
    before the manifest — exactly once. Synchronous callers SEE the
    failure (raised, like the HDF5 path would), and the next write
    commits."""
    ck = dc.ShardedCheckpointer(tmp_path, keep=4)
    injector = chaos_mod.ChaosInjector(torn_shard_write=2,
                                       torn_after_shards=1)
    injector.wire_checkpointer(ck)
    X = np.arange(8.0)
    assert ck.save({"X": X}, {"iteration": 1}) is not None
    with pytest.raises(RuntimeError, match="torn"):
        ck.save({"X": X + 1}, {"iteration": 2})
    assert [f["kind"] for f in injector.fired] == ["torn_shard"]
    assert len(ck.errors) == 1
    assert ck.save({"X": X + 2}, {"iteration": 3}) is not None
    event = dc.restore_latest(tmp_path)
    assert event["meta"]["iteration"] == 3
    assert len(event["fallbacks"]) == 0    # pruned: torn dir older than newest


# ------------------------------------------------------------ async writer

def test_async_overrun_barrier_blocks_and_lands_everything(tmp_path):
    """inflight=1 with a slowed writer: the second submit returns
    immediately, the third blocks at the barrier (recorded stall), and
    after drain every submitted checkpoint is durable, newest last."""
    ck = dc.ShardedCheckpointer(tmp_path, async_write=True, inflight=1,
                                keep=8)
    injector = chaos_mod.ChaosInjector(slow_shard_sec=0.2)
    injector.wire_checkpointer(ck)
    X = np.arange(16.0)
    t0 = time.perf_counter()
    ck.save({"X": X}, {"iteration": 1})
    first_two = time.perf_counter() - t0
    assert first_two < 0.15, "submit should not wait for the slow write"
    ck.save({"X": X + 1}, {"iteration": 2})   # blocks: budget is 1
    assert ck.stall_sec > 0.05, "overrun barrier never engaged"
    errors = ck.drain()
    assert errors == []
    assert ck.written == 2 and ck.max_inflight == 1
    sequence = [dc.read_manifest(p)["meta"]["iteration"]
                for p in dc.list_checkpoints(tmp_path)]
    assert sequence == [1, 2]
    event = dc.restore_latest(tmp_path)
    assert event["meta"]["iteration"] == 2
    assert np.array_equal(event["arrays"]["X"], X + 1)


def test_retention_keeps_newest_k(tmp_path):
    ck = dc.ShardedCheckpointer(tmp_path, keep=2)
    for i in range(5):
        ck.save({"X": np.full(4, float(i))}, {"iteration": i})
    kept = dc.list_checkpoints(tmp_path)
    assert len(kept) == 2
    assert [dc.read_manifest(p)["meta"]["iteration"] for p in kept] == [3, 4]


def test_sigterm_mid_async_write_leaves_previous_valid(tmp_path):
    """A real SIGTERM (default disposition: die now) delivered while the
    async writer is mid-checkpoint: the torn write never commits, and
    restore finds the previous checkpoint intact — the acceptance
    property of the manifest-written-last protocol."""
    script = r"""
import sys, time
import numpy as np
from dedalus_tpu.tools import dcheckpoint as dc

d = sys.argv[1]
ck = dc.ShardedCheckpointer(d, async_write=True, inflight=2, keep=8)
arrays = {k: np.full((64, 64), float(i))
          for i, k in enumerate(("X", "F_hist", "MX_hist"))}
ck.save(arrays, {"iteration": 1})
assert ck.drain() == []                      # checkpoint 1 fully durable
ck.shard_hook = lambda k: time.sleep(0.5)    # ~1.5 s write window
ck.save({k: v + 1 for k, v in arrays.items()}, {"iteration": 2})
time.sleep(0.2)                              # writer is inside the write
print("INFLIGHT", flush=True)
time.sleep(60)                               # SIGTERM lands here
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen([sys.executable, "-c", script, str(tmp_path)],
                            cwd=REPO, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()
        assert line.strip() == "INFLIGHT", proc.stderr.read()
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=30)
    finally:
        proc.kill()
    assert proc.returncode != 0              # died by signal, mid-write
    dirs = dc.list_checkpoints(tmp_path)
    assert len(dirs) == 2                    # committed + torn
    assert not (dirs[-1] / dc.MANIFEST).exists(), \
        "the interrupted write must not have committed"
    event = dc.restore_latest(tmp_path)
    assert event["meta"]["iteration"] == 1
    assert np.array_equal(event["arrays"]["X"], np.full((64, 64), 0.0))


# -------------------------------------------------- elastic fleet restore

AMPS = [0.1, 0.5, 1.0, 2.0, 0.3, 0.7, 1.5, 0.05]
KS = [1, 2, 3, 4, 1, 2, 3, 4]


def build_heat_solver():
    """The ensemble test problem: 1-D forced heat with a parameter field
    riding as an RHS extra operand (so elastic restore covers parameter
    operands too)."""
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=32, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    a = dist.Field(name="a", bases=xb)
    problem = d3.IVP([u], namespace={"u": u, "a": a, "lap": d3.lap})
    problem.add_equation("dt(u) - lap(u) = a*u")
    solver = problem.build_solver(d3.SBDF2, warmup_iterations=2,
                                  enforce_real_cadence=10)
    x = dist.local_grid(xb)

    def member_init(i):
        u["g"] = np.sin(KS[i] * x)
        a["g"] = AMPS[i] * np.cos(x)

    return solver, member_init


@needs_devices
@pytest.mark.ensemble
def test_elastic_fleet_restore_8_to_4_to_1_and_back(tmp_path):
    """Acceptance: an 8-virtual-device fleet checkpoint restores onto 4
    and 1 devices (and a 1-device checkpoint onto 8) with member state
    EXACTLY equal to the source, and the restored fleets step onward
    identically to the source fleet."""
    solver8, member_init = build_heat_solver()
    ens8 = solver8.ensemble(8, mesh="auto")
    ens8.init_members(member_init)
    ens8.evolve(dt=1e-3, stop_iteration=24, block=4,
                checkpoint_dir=tmp_path / "fleet", checkpoint_iter=8,
                log_cadence=0)
    assert ens8.summary()["devices"] == 8
    X8 = np.asarray(ens8.X[:8]).copy()
    T8 = np.asarray(ens8.sim_times[:8]).copy()

    restored = {}
    for n_devices in (4, 1):
        solver, _ = build_heat_solver()
        mesh = (Mesh(np.array(jax.devices()[:n_devices]), ("batch",))
                if n_devices > 1 else None)
        ens = solver.ensemble(8, mesh=mesh)
        event = ens.restore_checkpoint(tmp_path / "fleet")
        assert event["meta"]["iteration"] == 24
        assert ens.iteration == 24
        assert np.array_equal(np.asarray(ens.X[:8]), X8), \
            f"8 -> {n_devices} restore not bit-identical"
        assert np.array_equal(ens.sim_times[:8], T8)
        restored[n_devices] = ens

    # 1 -> 8: write from the single-device fleet, restore onto the mesh
    ens1 = restored[1]
    ens1.init_checkpoints(tmp_path / "fleet1")
    ens1.write_checkpoint()
    solver8b, _ = build_heat_solver()
    ens8b = solver8b.ensemble(8, mesh="auto")
    ens8b.restore_checkpoint(tmp_path / "fleet1")
    assert np.array_equal(np.asarray(ens8b.X[:8]), X8), \
        "1 -> 8 restore not bit-identical"

    # the restored fleets continue the SAME trajectory as the source
    for ens in (ens8, restored[4], ens8b):
        ens.step_many(8, 1e-3)
    for label, ens in (("4dev", restored[4]), ("8dev-from-1", ens8b)):
        err = np.max(np.abs(np.asarray(ens.X[:8])
                            - np.asarray(ens8.X[:8])))
        assert err <= 1e-12, (label, err)


@needs_devices
@pytest.mark.ensemble
def test_fleet_restore_validates_compatibility(tmp_path):
    """Member count / scheme / shape mismatches are structured errors,
    not silent shape corruption."""
    solver, member_init = build_heat_solver()
    ens = solver.ensemble(8, mesh="auto")
    ens.init_members(member_init)
    ens.init_checkpoints(tmp_path / "fleet")
    ens.write_checkpoint()
    other, _ = build_heat_solver()
    with pytest.raises(CheckpointError, match="members"):
        other.ensemble(4, mesh=None).restore_checkpoint(tmp_path / "fleet")
    with pytest.raises(CheckpointError, match="no sharded checkpoint"):
        other.ensemble(8, mesh=None).restore_checkpoint(tmp_path / "empty")
