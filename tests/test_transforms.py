"""
Transform tests (reference: dedalus/tests/test_transforms.py).

The reference's dual-implementation oracle pattern: every fast transform
library is checked against the 'matrix' MMT implementation of the same
basis, plus grid<->coeff roundtrips with random data.
"""

import numpy as np
import pytest
import jax.numpy as jnp

import dedalus_tpu.public as d3
from dedalus_tpu.core.field import transform_to_coeff, transform_to_grid

N_range = [8, 16, 32]
dealias_range = [1, 3/2]


@pytest.mark.parametrize("N", N_range)
@pytest.mark.parametrize("dealias", dealias_range)
@pytest.mark.parametrize("library", ["fft"])
def test_real_fourier_libraries(N, dealias, library, rng):
    """Fast library forward/backward vs matrix MMT
    (reference: test_transforms.py:22)."""
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=N, bounds=(0, 2.3), dealias=dealias)
    gdata = rng.standard_normal(xb.grid_size(dealias))
    c_fast = np.asarray(xb.forward_transform(jnp.asarray(gdata), 0, dealias, library))
    c_mat = np.asarray(xb.forward_transform(jnp.asarray(gdata), 0, dealias, "matrix"))
    assert np.allclose(c_fast, c_mat)
    g_fast = np.asarray(xb.backward_transform(jnp.asarray(c_mat), 0, dealias, library))
    g_mat = np.asarray(xb.backward_transform(jnp.asarray(c_mat), 0, dealias, "matrix"))
    assert np.allclose(g_fast, g_mat)


@pytest.mark.parametrize("N", N_range)
@pytest.mark.parametrize("library", ["fft"])
def test_complex_fourier_libraries(N, library, rng):
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.complex128)
    xb = d3.ComplexFourier(xc, size=N, bounds=(0, 1.7))
    gdata = rng.standard_normal(N) + 1j * rng.standard_normal(N)
    c_fast = np.asarray(xb.forward_transform(jnp.asarray(gdata), 0, 1.0, library))
    c_mat = np.asarray(xb.forward_transform(jnp.asarray(gdata), 0, 1.0, "matrix"))
    assert np.allclose(c_fast, c_mat)
    g_fast = np.asarray(xb.backward_transform(jnp.asarray(c_mat), 0, 1.0, library))
    g_mat = np.asarray(xb.backward_transform(jnp.asarray(c_mat), 0, 1.0, "matrix"))
    assert np.allclose(g_fast, g_mat)


@pytest.mark.parametrize("N", N_range)
@pytest.mark.parametrize("basis_fn", [d3.ChebyshevT, d3.Legendre,
                                      lambda c, **kw: d3.Jacobi(c, a=1.0, b=0.5, **kw)])
def test_jacobi_roundtrip(N, basis_fn, rng):
    """Band-limited roundtrip is exact (reference: test_transforms.py
    roundtrip suites)."""
    zc = d3.Coordinate("z")
    dist = d3.Distributor(zc, dtype=np.float64)
    zb = basis_fn(zc, size=N, bounds=(-0.7, 1.3))
    coeffs = rng.standard_normal(N)
    g = np.asarray(zb.backward_transform(jnp.asarray(coeffs), 0, 1.0))
    c2 = np.asarray(zb.forward_transform(jnp.asarray(g), 0, 1.0))
    assert np.allclose(c2, coeffs)


@pytest.mark.parametrize("N", [16, 32])
@pytest.mark.parametrize("dealias", dealias_range)
def test_2d_field_roundtrip(N, dealias, rng):
    """Full-field grid->coeff->grid roundtrip in 2D."""
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=N, bounds=(0, 2), dealias=dealias)
    zb = d3.ChebyshevT(coords["z"], size=N, bounds=(0, 1), dealias=dealias)
    u = dist.Field(name="u", bases=(xb, zb))
    x, z = dist.local_grids(xb, zb)
    u["g"] = np.sin(np.pi * x) * z**3
    g0 = u["g"].copy()
    _ = u["c"]
    assert np.allclose(u["g"], g0)


def test_scale_change(rng):
    """Dealias pad/truncate through coefficient space."""
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=16, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    x = dist.local_grid(xb)
    u["g"] = np.sin(3 * x)
    u.change_scales(3 / 2)
    x2 = dist.local_grid(xb, scale=3 / 2)
    assert np.allclose(u["g"], np.sin(3 * x2.ravel()))
    u.change_scales(1)
    assert np.allclose(u["g"], np.sin(3 * x.ravel()))


def test_jacobi_derivative_level_transforms(rng):
    """Transforms at derivative levels k>0 (ultraspherical conversion)."""
    zc = d3.Coordinate("z")
    dist = d3.Distributor(zc, dtype=np.float64)
    zb = d3.ChebyshevT(zc, size=24, bounds=(0, 1))
    zb2 = zb.derivative_basis(2)
    z = dist.local_grid(zb).ravel()
    f = z**4 - 2 * z
    c = np.asarray(zb2.forward_transform(jnp.asarray(f), 0, 1.0))
    g = np.asarray(zb2.backward_transform(jnp.asarray(c), 0, 1.0))
    assert np.allclose(g, f)


@pytest.mark.parametrize("N", [8, 64, 256])
@pytest.mark.parametrize("k", [0, 1, 2])
@pytest.mark.parametrize("scale", [1.0, 1.5])
def test_fast_chebyshev_vs_mmt(N, k, scale, rng):
    """DCT fast path vs the MMT oracle (reference pattern:
    tests/test_transforms.py fast-vs-matrix checks; math reference:
    core/transforms.py:801-890 FastChebyshevTransform)."""
    import dedalus_tpu.public as d3
    from dedalus_tpu.core import transforms as tr
    coords = d3.CartesianCoordinates("z")
    d3.Distributor(coords, dtype=np.float64)
    zb = d3.ChebyshevT(coords["z"], size=N, bounds=(0, 1)).derivative_basis(k)
    mmt = tr.get_plan(zb, scale, "matrix")
    fft = tr.get_plan(zb, scale, "fft")
    assert fft._mmt is None  # really the DCT path
    Ng = zb.grid_size(scale)
    g = rng.standard_normal((3, Ng))
    cm = np.asarray(mmt.forward(jnp.asarray(g), 1))
    cf = np.asarray(fft.forward(jnp.asarray(g), 1))
    assert np.abs(cm - cf).max() < 1e-11 * max(1, np.abs(cm).max())
    c = rng.standard_normal((3, N))
    gm = np.asarray(mmt.backward(jnp.asarray(c), 1))
    gf = np.asarray(fft.backward(jnp.asarray(c), 1))
    assert np.abs(gm - gf).max() < 1e-11 * max(1, np.abs(gm).max())


def test_legendre_fft_falls_back_to_mmt(rng):
    """Non-Chebyshev Jacobi grids have no DCT; the fft plan must still be
    correct by falling back to the MMT."""
    import dedalus_tpu.public as d3
    from dedalus_tpu.core import transforms as tr
    coords = d3.CartesianCoordinates("z")
    d3.Distributor(coords, dtype=np.float64)
    zb = d3.Legendre(coords["z"], size=32, bounds=(0, 1))
    fft = tr.get_plan(zb, 1.0, "fft")
    assert fft._mmt is not None
    g = rng.standard_normal(32)
    c = np.asarray(fft.forward(jnp.asarray(g), 0))
    g2 = np.asarray(fft.backward(jnp.asarray(c), 0))
    assert np.abs(g - g2).max() < 1e-12


def test_fast_chebyshev_complex_and_coarse(rng):
    """Complex data must survive the DCT path (real/imag split), and
    coarse scales (Ng < N) must route to the rectangular MMT."""
    import dedalus_tpu.public as d3
    from dedalus_tpu.core import transforms as tr
    coords = d3.CartesianCoordinates("z")
    d3.Distributor(coords, dtype=np.complex128)
    zb = d3.ChebyshevT(coords["z"], size=16, bounds=(0, 1))
    mmt = tr.get_plan(zb, 1.0, "matrix")
    fft = tr.get_plan(zb, 1.0, "fft")
    g = rng.standard_normal(16) + 1j * rng.standard_normal(16)
    cm = np.asarray(mmt.forward(jnp.asarray(g), 0))
    cf = np.asarray(fft.forward(jnp.asarray(g), 0))
    assert np.abs(cm - cf).max() < 1e-13
    gm = np.asarray(mmt.backward(jnp.asarray(cm), 0))
    gf = np.asarray(fft.backward(jnp.asarray(cm), 0))
    assert np.abs(gm - gf).max() < 1e-13
    coarse = tr.get_plan(zb, 0.5, "fft")
    assert coarse._mmt is not None
    c = rng.standard_normal(16)
    out = np.asarray(coarse.backward(jnp.asarray(c), 0))
    assert out.shape == (8,)


# ---------------------------------------------------------------------------
# Extended roundtrip coverage (reference: tests/test_transforms.py parametrizes
# every basis x dtype x dealias x rank against the matrix oracle, 742 LoC)

@pytest.mark.parametrize("basis_fn", [
    lambda c, N, d: d3.ChebyshevU(c, size=N, bounds=(-1, 2), dealias=d),
    lambda c, N, d: d3.ChebyshevV(c, size=N, bounds=(0, 1), dealias=d),
    lambda c, N, d: d3.Ultraspherical(c, size=N, bounds=(0, 3), alpha=1.5,
                                      dealias=d),
    lambda c, N, d: d3.Legendre(c, size=N, bounds=(-2, -1), dealias=d),
])
@pytest.mark.parametrize("dealias", [1, 3 / 2])
def test_jacobi_family_roundtrips(basis_fn, dealias, rng):
    N = 24
    c = d3.Coordinate("x")
    dist = d3.Distributor(c, dtype=np.float64)
    b = basis_fn(c, N, dealias)
    f = dist.Field(name="f", bases=b)
    f["c"] = rng.standard_normal(N)
    c0 = np.asarray(f["c"]).copy()
    f.change_scales(dealias)
    _ = f["g"]
    assert np.allclose(np.asarray(f["c"]), c0, atol=1e-12)


@pytest.mark.parametrize("rank", [1, 2])
def test_tensor_field_roundtrip(rank, rng):
    """Vector/tensor fields roundtrip with tensor axes leading."""
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=12, bounds=(0, 1), dealias=3 / 2)
    zb = d3.ChebyshevT(coords["z"], size=10, bounds=(0, 1), dealias=3 / 2)
    sig = (coords,) * rank
    f = dist.TensorField(sig, name="f", bases=(xb, zb))
    shape = np.asarray(f["c"]).shape
    f["c"] = rng.standard_normal(shape)
    # one roundtrip first: random coefficients include invalid slots
    # (RealFourier -sin0/Nyquist) that project away
    _ = f["g"]
    c0 = np.asarray(f["c"]).copy()
    _ = f["g"]
    assert np.allclose(np.asarray(f["c"]), c0, atol=1e-12)


def test_complex_fourier_matrix_vs_fft_forward(rng):
    """Forward coefficients agree between MMT oracle and FFT library."""
    c = d3.Coordinate("x")
    dist = d3.Distributor(c, dtype=np.complex128)
    N = 16
    g = rng.standard_normal(N) + 1j * rng.standard_normal(N)
    coeffs = {}
    for lib in ("matrix", "fft"):
        b = d3.ComplexFourier(c, size=N, bounds=(0, 2 * np.pi), library=lib)
        f = dist.Field(name="f", bases=b)
        f["g"] = g
        coeffs[lib] = np.asarray(f["c"]).copy()
    assert np.allclose(coeffs["matrix"], coeffs["fft"], atol=1e-12)


def test_real_fourier_matrix_vs_fft_forward(rng):
    c = d3.Coordinate("x")
    dist = d3.Distributor(c, dtype=np.float64)
    N = 16
    g = rng.standard_normal(N)
    coeffs = {}
    for lib in ("matrix", "fft"):
        b = d3.RealFourier(c, size=N, bounds=(0, 2 * np.pi), library=lib)
        f = dist.Field(name="f", bases=b)
        f["g"] = g
        coeffs[lib] = np.asarray(f["c"]).copy()
    assert np.allclose(coeffs["matrix"], coeffs["fft"], atol=1e-12)


@pytest.mark.parametrize("Ng_scale", [1, 2, 3 / 2])
def test_chebyshev_known_function(Ng_scale):
    """T_3(x) has exactly one coefficient in the ChebyshevT expansion."""
    c = d3.Coordinate("x")
    dist = d3.Distributor(c, dtype=np.float64)
    b = d3.ChebyshevT(c, size=8, bounds=(-1, 1), dealias=Ng_scale)
    f = dist.Field(name="f", bases=b)
    f.change_scales(Ng_scale)
    x = b.global_grid(Ng_scale)
    f["g"] = 4 * x ** 3 - 3 * x   # T_3
    coeffs = np.asarray(f["c"])
    # orthonormal normalization: only mode 3 nonzero
    mask = np.zeros(8, dtype=bool)
    mask[3] = True
    assert np.abs(coeffs[~mask]).max() < 1e-13
    assert np.abs(coeffs[3]) > 0.1


def test_degenerate_sizes(rng):
    """Size-1 and size-2 bases roundtrip (reference degenerate-size tests)."""
    c = d3.Coordinate("x")
    dist = d3.Distributor(c, dtype=np.float64)
    for N in (1, 2, 3):
        b = d3.ChebyshevT(c, size=N, bounds=(0, 1))
        f = dist.Field(name="f", bases=b)
        f["c"] = rng.standard_normal(N)
        c0 = np.asarray(f["c"]).copy()
        _ = f["g"]
        assert np.allclose(np.asarray(f["c"]), c0, atol=1e-12), N
