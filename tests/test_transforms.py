"""
Transform tests (reference: dedalus/tests/test_transforms.py).

The reference's dual-implementation oracle pattern: every fast transform
library is checked against the 'matrix' MMT implementation of the same
basis, plus grid<->coeff roundtrips with random data.
"""

import numpy as np
import pytest
import jax.numpy as jnp

import dedalus_tpu.public as d3
from dedalus_tpu.core.field import transform_to_coeff, transform_to_grid

N_range = [8, 16, 32]
dealias_range = [1, 3/2]


@pytest.mark.parametrize("N", N_range)
@pytest.mark.parametrize("dealias", dealias_range)
@pytest.mark.parametrize("library", ["fft"])
def test_real_fourier_libraries(N, dealias, library, rng):
    """Fast library forward/backward vs matrix MMT
    (reference: test_transforms.py:22)."""
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=N, bounds=(0, 2.3), dealias=dealias)
    gdata = rng.standard_normal(xb.grid_size(dealias))
    c_fast = np.asarray(xb.forward_transform(jnp.asarray(gdata), 0, dealias, library))
    c_mat = np.asarray(xb.forward_transform(jnp.asarray(gdata), 0, dealias, "matrix"))
    assert np.allclose(c_fast, c_mat)
    g_fast = np.asarray(xb.backward_transform(jnp.asarray(c_mat), 0, dealias, library))
    g_mat = np.asarray(xb.backward_transform(jnp.asarray(c_mat), 0, dealias, "matrix"))
    assert np.allclose(g_fast, g_mat)


@pytest.mark.parametrize("N", N_range)
@pytest.mark.parametrize("library", ["fft"])
def test_complex_fourier_libraries(N, library, rng):
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.complex128)
    xb = d3.ComplexFourier(xc, size=N, bounds=(0, 1.7))
    gdata = rng.standard_normal(N) + 1j * rng.standard_normal(N)
    c_fast = np.asarray(xb.forward_transform(jnp.asarray(gdata), 0, 1.0, library))
    c_mat = np.asarray(xb.forward_transform(jnp.asarray(gdata), 0, 1.0, "matrix"))
    assert np.allclose(c_fast, c_mat)
    g_fast = np.asarray(xb.backward_transform(jnp.asarray(c_mat), 0, 1.0, library))
    g_mat = np.asarray(xb.backward_transform(jnp.asarray(c_mat), 0, 1.0, "matrix"))
    assert np.allclose(g_fast, g_mat)


@pytest.mark.parametrize("N", N_range)
@pytest.mark.parametrize("basis_fn", [d3.ChebyshevT, d3.Legendre,
                                      lambda c, **kw: d3.Jacobi(c, a=1.0, b=0.5, **kw)])
def test_jacobi_roundtrip(N, basis_fn, rng):
    """Band-limited roundtrip is exact (reference: test_transforms.py
    roundtrip suites)."""
    zc = d3.Coordinate("z")
    dist = d3.Distributor(zc, dtype=np.float64)
    zb = basis_fn(zc, size=N, bounds=(-0.7, 1.3))
    coeffs = rng.standard_normal(N)
    g = np.asarray(zb.backward_transform(jnp.asarray(coeffs), 0, 1.0))
    c2 = np.asarray(zb.forward_transform(jnp.asarray(g), 0, 1.0))
    assert np.allclose(c2, coeffs)


@pytest.mark.parametrize("N", [16, 32])
@pytest.mark.parametrize("dealias", dealias_range)
def test_2d_field_roundtrip(N, dealias, rng):
    """Full-field grid->coeff->grid roundtrip in 2D."""
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=N, bounds=(0, 2), dealias=dealias)
    zb = d3.ChebyshevT(coords["z"], size=N, bounds=(0, 1), dealias=dealias)
    u = dist.Field(name="u", bases=(xb, zb))
    x, z = dist.local_grids(xb, zb)
    u["g"] = np.sin(np.pi * x) * z**3
    g0 = u["g"].copy()
    _ = u["c"]
    assert np.allclose(u["g"], g0)


def test_scale_change(rng):
    """Dealias pad/truncate through coefficient space."""
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=16, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    x = dist.local_grid(xb)
    u["g"] = np.sin(3 * x)
    u.change_scales(3 / 2)
    x2 = dist.local_grid(xb, scale=3 / 2)
    assert np.allclose(u["g"], np.sin(3 * x2.ravel()))
    u.change_scales(1)
    assert np.allclose(u["g"], np.sin(3 * x.ravel()))


def test_jacobi_derivative_level_transforms(rng):
    """Transforms at derivative levels k>0 (ultraspherical conversion)."""
    zc = d3.Coordinate("z")
    dist = d3.Distributor(zc, dtype=np.float64)
    zb = d3.ChebyshevT(zc, size=24, bounds=(0, 1))
    zb2 = zb.derivative_basis(2)
    z = dist.local_grid(zb).ravel()
    f = z**4 - 2 * z
    c = np.asarray(zb2.forward_transform(jnp.asarray(f), 0, 1.0))
    g = np.asarray(zb2.backward_transform(jnp.asarray(c), 0, 1.0))
    assert np.allclose(g, f)
