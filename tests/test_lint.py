"""
Jit-hygiene analyzer (tools/lint) + runtime sentinels (tools/retrace,
jitlift trace probe, leak_check marker).

Self-enforcement lives here: test_package_lints_clean runs the analyzer
over the installed package against the checked-in baseline, so tier-1
fails on any new un-baselined violation. Every rule gets a good/bad
fixture pair plus suppression and baseline coverage, and the retrace
sentinel is asserted to stay at zero across the RB step loop.
"""

import json
import logging
import pathlib
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dedalus_tpu.tools import retrace as retrace_mod
from dedalus_tpu.tools import metrics as metrics_mod
from dedalus_tpu.tools.lint import (all_rules, apply_baseline,
                                    check_baseline_fresh, lint_package,
                                    make_baseline, run_lint, DEFAULT_BASELINE,
                                    PACKAGE_DIR)
from dedalus_tpu.tools.lint.cli import main as lint_main

REPO = pathlib.Path(__file__).parent.parent


def _lint_src(tmp_path, relname, src):
    """Write a fixture module and lint it. relname controls path-scoped
    rules (e.g. 'core/timesteppers.py' opts into the hot-path scope)."""
    path = tmp_path / relname
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(src)
    return run_lint([path])


def _rules_fired(result):
    return sorted({f.rule for f in result.findings})


# ----------------------------------------------------------------- rule set

def test_rule_catalog():
    # the DTC thread-safety rules (tools/lint/threadcheck.py) register
    # in the shared rule set so the default run covers them
    rules = all_rules()
    assert [r.id for r in rules] == ["DTC001", "DTC002", "DTC003",
                                     "DTL001", "DTL002", "DTL003",
                                     "DTL004", "DTL005", "DTL006",
                                     "DTL007", "DTL008", "DTL009"]
    for r in rules:
        assert r.severity in ("error", "warning")
        assert r.title
        assert r.__doc__


def test_dtl001_fires_on_hot_path_syncs(tmp_path):
    result = _lint_src(tmp_path, "core/timesteppers.py", """
import jax
import jax.numpy as jnp

def step(solver, dt):
    err = float(jnp.max(solver.X))
    solver.X.block_until_ready()
    jax.block_until_ready(solver.X)
    return err + solver.X[0, 0].item()
""")
    assert _rules_fired(result) == ["DTL001"]
    assert len(result.findings) == 4


def test_dtl001_quiet_on_host_setup(tmp_path):
    result = _lint_src(tmp_path, "core/timesteppers.py", """
import numpy as np

def coefficients(dt_hist):
    a = np.asarray(dt_hist)   # host-side setup: fine
    return float(a[0])        # float of a host value: fine
""")
    assert result.findings == []


def test_dtl001_state_gather_in_resilience_module(tmp_path):
    """tools/resilience.py is hot-module scoped, and np.asarray of a
    device-state attribute there (the shipped Snapshot.is_finite full
    gather) flags — so the fix stays fixed."""
    bad = _lint_src(tmp_path, "tools/resilience.py", """
import numpy as np

def is_finite(snap):
    # the shipped hazard: full device->host gather per capture validation
    return bool(np.all(np.isfinite(np.asarray(snap.X))))

def fleet_finite(snap):
    return np.asarray(snap.F_hist)
""")
    assert _rules_fired(bad) == ["DTL001"]
    assert len(bad.findings) == 2
    assert "gathers the full state" in bad.findings[0].message


def test_dtl001_fires_in_transposes_module(tmp_path):
    """parallel/transposes.py is hot-module scoped: the overlapped
    chunked walk stages compile into every sharded step, so a stray
    host sync there stalls the whole transpose pipeline. Fixture-pinned
    so the scope can never silently regress."""
    bad = _lint_src(tmp_path, "parallel/transposes.py", """
import jax
import jax.numpy as jnp

def overlapped_stage(data, mesh):
    jax.block_until_ready(data)        # sync between chunk issues
    return float(jnp.max(data))        # host read of the moved block
""")
    assert _rules_fired(bad) == ["DTL001"]
    assert len(bad.findings) == 2


def test_dtl001_quiet_on_transposes_host_setup(tmp_path):
    """Host-side chunk bookkeeping (divisor clamping, spec lists) in the
    transposes module is not a device sync."""
    result = _lint_src(tmp_path, "parallel/transposes.py", """
import numpy as np

def stage_chunks(requested, block):
    c = max(1, min(int(requested), int(block)))   # host chunk math
    while block % c:
        c -= 1
    return c

def specs(layout, ndim):
    return [layout.get(d) for d in range(ndim)]
""")
    assert result.findings == []


def test_dtl001_state_gather_quiet_on_host_conversions(tmp_path):
    """The dtype= convention and non-state attributes stay quiet: host
    bookkeeping in the hot modules is not a device sync."""
    result = _lint_src(tmp_path, "tools/resilience.py", """
import numpy as np

def bookkeeping(snap, times):
    a = np.asarray(times)                       # bare Name: host data
    b = np.asarray(snap.sim_times, dtype=float) # dtype=: deliberate host
    c = np.array(snap.lineage)                  # not a state attribute
    return a, b, c
""")
    assert result.findings == []


def test_dtl001_state_gather_scoped_to_hot_modules(tmp_path):
    """The state-attribute heuristic is hot-module scoped: analysis/
    plotting code reading solver.X to host is legitimate."""
    result = _lint_src(tmp_path, "tools/post.py", """
import numpy as np

def to_host(solver):
    return np.asarray(solver.X)
""")
    assert result.findings == []


def test_dtl001_covers_fusedstep_module(tmp_path):
    """core/fusedstep.py is a declared hot module (its grid_eval/pallas
    bodies compile into the step program through the evaluator call
    graph): a stray sync there fires whole-file, and host-side
    precomposition stays quiet."""
    bad = _lint_src(tmp_path, "core/fusedstep.py", """
import jax

def grid_eval(plan, node, data):
    jax.block_until_ready(data)
    return data
""")
    assert _rules_fired(bad) == ["DTL001"]
    good = _lint_src(tmp_path, "core/fusedstep.py", """
import numpy as np

def composite(backward, term):
    return np.ascontiguousarray(np.asarray(backward) @ term)
""")
    assert good.findings == []


def test_dtl001_covers_solvecomp_module(tmp_path):
    """libraries/solvecomp.py is a declared hot module (the restructured
    substitution programs trace into every fused solve through
    BandedOps/DenseOps): a stray sync there fires whole-file, and the
    pure-jnp prefix/chunk builders stay quiet."""
    bad = _lint_src(tmp_path, "libraries/solvecomp.py", """
import jax

def spike_apply(ops, u, v0):
    jax.block_until_ready(u)
    return u
""")
    assert _rules_fired(bad) == ["DTL001"]
    good = _lint_src(tmp_path, "libraries/solvecomp.py", """
import jax.numpy as jnp

def ascan_combine(prev, nxt):
    A1, b1 = prev
    A2, b2 = nxt
    return A2 @ A1, A2 @ b1 + b2
""")
    assert good.findings == []


def test_dtl001_traced_concretization_any_module(tmp_path):
    bad = _lint_src(tmp_path, "anywhere.py", """
import numpy as np
import jax

def body(x):
    return np.asarray(x) + 1

jitted = jax.jit(body)
""")
    assert _rules_fired(bad) == ["DTL001"]
    good = _lint_src(tmp_path, "anywhere2.py", """
import numpy as np

def body(x):
    return np.asarray(x) + 1   # never traced: host helper
""")
    assert good.findings == []


def test_dtl002_fires_in_traced_context_module(tmp_path):
    result = _lint_src(tmp_path, "core/transforms.py", """
import jax.numpy as jnp

def apply_plan(plan, data):
    return jnp.asarray(plan.matrix) @ data
""")
    assert _rules_fired(result) == ["DTL002"]


def test_dtl002_fires_in_detected_traced_function(tmp_path):
    result = _lint_src(tmp_path, "mymodule.py", """
import jax.numpy as jnp
from dedalus_tpu.tools.jitlift import lifted_jit

def matmul(M, x):
    return jnp.asarray(M) @ x

matmul_j = lifted_jit(matmul)
""")
    assert _rules_fired(result) == ["DTL002"]


def test_dtl002_quiet_on_funnel_and_dtype_forms(tmp_path):
    result = _lint_src(tmp_path, "core/transforms.py", """
import jax.numpy as jnp
from dedalus_tpu.tools.jitlift import device_constant

def apply_plan(plan, data, rd):
    a = jnp.asarray(plan.shift, dtype=rd)      # scalar conversion: fine
    return device_constant(plan.matrix) @ data + a
""")
    assert result.findings == []


def test_dtl003_fires_on_wrapper_in_call_path(tmp_path):
    result = _lint_src(tmp_path, "solver.py", """
import jax

def solve(A, b):
    fn = jax.jit(lambda x: A @ x)
    return fn(b)
""")
    assert _rules_fired(result) == ["DTL003"]


def test_dtl003_exempts_init_self_and_module_scope(tmp_path):
    result = _lint_src(tmp_path, "stepper.py", """
import jax
from dedalus_tpu.tools.jitlift import lifted_jit

topfn = jax.jit(lambda x: x)

class Stepper:
    def __init__(self):
        self._fn = lifted_jit(lambda x: x + 1)
        self._cache = {}

    def rebuild(self, key, fn):
        self._fn = jax.jit(fn)                    # memoized on self
        out = self._cache[key] = jax.jit(fn)      # memoized in a cache
        return out
""")
    assert result.findings == []


def test_dtl004_fires_on_wide_device_dtypes(tmp_path):
    result = _lint_src(tmp_path, "widen.py", """
import numpy as np
import jax.numpy as jnp

def widen(x):
    y = jnp.zeros(4, dtype=np.complex128)
    return y + jnp.asarray(x, jnp.float64)
""")
    assert _rules_fired(result) == ["DTL004"]
    assert len(result.findings) == 2


def test_dtl004_quiet_on_host_numpy(tmp_path):
    result = _lint_src(tmp_path, "host.py", """
import numpy as np

def quadrature(n):
    return np.zeros(n, dtype=np.float64)   # host assembly: house precision
""")
    assert result.findings == []


def test_dtl005_fires_on_private_jax_imports(tmp_path):
    result = _lint_src(tmp_path, "internals.py", """
from jax._src.core import trace_ctx
import jax

def peek():
    return jax._src
""")
    assert _rules_fired(result) == ["DTL005"]
    assert len(result.findings) == 2


def test_dtl005_quiet_on_public_surface(tmp_path):
    result = _lint_src(tmp_path, "public.py", """
from jax.core import trace_state_clean

def clean():
    return trace_state_clean()
""")
    assert result.findings == []


def test_dtl006_fires_on_gradient_breakers_in_step_body(tmp_path):
    result = _lint_src(tmp_path, "core/timesteppers.py", """
import functools
import jax
from jax.experimental import io_callback

def step_body(M, L, X, t):
    Xd = jax.lax.stop_gradient(X)
    io_callback(print, None, t)
    return Xd

@functools.partial(jax.jit, donate_argnums=0)
def write_state(store, X):
    return store.at[0].set(X)
""")
    assert "DTL006" in _rules_fired(result)
    dtl6 = [f for f in result.findings if f.rule == "DTL006"]
    assert len(dtl6) == 3
    messages = " ".join(f.message for f in dtl6)
    assert "stop_gradient" in messages
    assert "host callback" in messages
    assert "donate" in messages


def test_dtl006_quiet_outside_step_bodies_and_without_donation(tmp_path):
    # stop_gradient in a non-step-body module: out of scope
    outside = _lint_src(tmp_path, "core/adjoint_helpers.py", """
import jax

def detach(x):
    return jax.lax.stop_gradient(x)
""")
    assert "DTL006" not in _rules_fired(outside)
    # .at[].set without donation, and on a local (not a donated
    # parameter): fine — functional updates are the jnp idiom
    undonated = _lint_src(tmp_path, "core/ddstep.py", """
import jax
import jax.numpy as jnp

def update(store, X):
    fresh = jnp.zeros_like(store)
    return fresh.at[0].set(X)

update_j = jax.jit(update)
""")
    assert undonated.findings == []


def test_dtl007_fires_on_aliased_host_mirror(tmp_path):
    """The PR-11 race encoded: jnp.asarray zero-copies the host mirror,
    a later in-place mutation rewrites the queued device operand. Both
    the attribute-mirror form (placement and mutation in different
    methods) and the same-function local form flag."""
    result = _lint_src(tmp_path, "core/ensemble.py", """
import jax.numpy as jnp

class Fleet:
    def place(self):
        self._active_dev = jnp.asarray(self.active_host)   # zero-copy

    def detach(self, m):
        self.active_host[m] = False                        # rewrites it

def budgets(steps_left):
    dev = jnp.asarray(steps_left)
    steps_left[0] = 0        # later in-place write, same function
    return dev
""")
    assert _rules_fired(result) == ["DTL007"]
    assert len(result.findings) == 2
    assert "zero-copies" in result.findings[0].message


def test_dtl007_quiet_on_copying_placements(tmp_path):
    """The sanctioned spellings stay quiet: jnp.array copies by default
    (the _put_host fix), build-then-place locals mutate BEFORE the
    placement, and numpy-side asarray is host bookkeeping."""
    result = _lint_src(tmp_path, "core/ensemble.py", """
import numpy as np
import jax.numpy as jnp

class Fleet:
    def place(self):
        self._active_dev = jnp.array(self.active_host)     # copies

    def detach(self, m):
        self.active_host[m] = False

def build_mask(n):
    mask = np.zeros(n, dtype=bool)
    mask[0] = True                 # mutation BEFORE placement: build
    return jnp.asarray(mask)

def host_only(snap):
    snap.lineage[0] = "x"
    return np.asarray(snap.lineage)
""")
    assert result.findings == []


def test_dtl008_fires_on_step_path_config_reads(tmp_path):
    """Config reads on the step/dispatch path of a hot module (and
    inside traced code anywhere) violate the resolved-once-per-build
    invariant the assembly/pool keys depend on."""
    bad = _lint_src(tmp_path, "core/timesteppers.py", """
from ..tools.config import config, cfg_get

class Stepper:
    def step(self, dt):
        mode = config["fusion"].get("FUSED_SOLVE", "auto")   # per step!
        return mode

    def _dispatch(self, n):
        return cfg_get("distributed", "TRANSPOSE_CHUNKS", "auto")
""")
    assert _rules_fired(bad) == ["DTL008"]
    assert len(bad.findings) == 2
    assert "solver-key" in bad.findings[0].message \
        or "pool keys" in bad.findings[0].message
    traced = _lint_src(tmp_path, "anymodule.py", """
import jax
from dedalus_tpu.tools.config import cfg_get

def body(x):
    chunks = int(cfg_get("distributed", "TRANSPOSE_CHUNKS", "2"))
    return x * chunks

jitted = jax.jit(body)
""")
    assert _rules_fired(traced) == ["DTL008"]
    assert "traced" in traced.findings[0].message


def test_dtl008_quiet_on_build_time_reads(tmp_path):
    """Build/factor-time resolution is the sanctioned pattern: reads in
    __init__, module-level helpers, and resolve_* functions stay quiet
    (the resolved value is stored before solver_key seals it)."""
    result = _lint_src(tmp_path, "core/timesteppers.py", """
from ..tools.config import config, cfg_get

def _use_split_step(solver):
    return config["execution"].get("STEP_PROGRAM", "auto") == "split"

def resolve_chunks():
    return cfg_get("distributed", "TRANSPOSE_CHUNKS", "auto")

class Stepper:
    def __init__(self):
        self._mode = config["fusion"].get("FUSED_SOLVE", "auto")

    def step(self, dt):
        return self._mode      # resolved once, read from self
""")
    assert result.findings == []
    # step-path reads OUTSIDE the hot modules are out of scope (tools,
    # analysis code) unless traced
    cold = _lint_src(tmp_path, "tools/post.py", """
from .config import cfg_get

def step(data):
    return cfg_get("analysis", "FORMAT", "h5")
""")
    assert cold.findings == []


def test_dtl009_fires_on_gspmd_fragile_ops(tmp_path):
    """jnp.pad / lax.map restored into a manual-region module — the
    jaxlib SPMD-partitioner crash classes PR 13 fixed — flag whole-file;
    the zeropad funnel and out-of-scope modules stay quiet."""
    bad = _lint_src(tmp_path, "core/transforms.py", """
import jax
import jax.numpy as jnp

def backward(data, n):
    padded = jnp.pad(data, ((0, 0), (0, n)))
    return jax.lax.map(lambda x: x * 2, padded)
""")
    assert _rules_fired(bad) == ["DTL009"]
    assert len(bad.findings) == 2
    messages = " ".join(f.message for f in bad.findings)
    assert "zeropad" in messages and "_shard_chunked" in messages
    good = _lint_src(tmp_path, "core/transforms.py", """
from ..tools.array import zeropad

def backward(data, n):
    return zeropad(data, ((0, 0), (0, n)))
""")
    assert good.findings == []
    # pencilops is deliberately out of scope (documented: its chunk maps
    # route through _shard_chunked; DTP105 guards the lowered programs)
    scoped = _lint_src(tmp_path, "libraries/pencilops.py", """
import jax.numpy as jnp

def pad_groups(arr, n):
    return jnp.pad(arr, ((0, n),), mode="edge")
""")
    assert scoped.findings == []


def test_dtl006_suppression_and_baseline_zero():
    """The shipped step bodies carry ZERO grandfathered DTL006 entries —
    the differentiable path depends on them staying gradient-clean."""
    import json
    data = json.loads(DEFAULT_BASELINE.read_text())
    assert [e for e in data["entries"] if e["rule"] == "DTL006"] == []


# -------------------------------------------- suppressions and the baseline

def test_same_line_suppression(tmp_path):
    result = _lint_src(tmp_path, "core/timesteppers.py", """
import jax

def warm(x):
    jax.block_until_ready(x)  # dedalus-lint: disable=DTL001 (probe warm)
""")
    assert result.findings == []
    assert len(result.suppressed) == 1
    assert result.suppressed[0].rule == "DTL001"


def test_file_level_suppression(tmp_path):
    result = _lint_src(tmp_path, "core/timesteppers.py", """
# dedalus-lint: disable-file=DTL001
import jax

def warm(x):
    jax.block_until_ready(x)

def warm2(x):
    jax.block_until_ready(x)
""")
    assert result.findings == []
    assert len(result.suppressed) == 2


def test_suppression_in_string_literal_is_inert(tmp_path):
    """Suppression syntax QUOTED in a docstring/string (e.g. docs of the
    mechanism itself) must not suppress anything."""
    result = _lint_src(tmp_path, "core/timesteppers.py", '''
"""Docs: add `# dedalus-lint: disable-file=DTL001` to silence a file."""
import jax

HOWTO = "# dedalus-lint: disable-file=DTL001"

def warm(x):
    jax.block_until_ready(x)
''')
    assert _rules_fired(result) == ["DTL001"]
    assert result.suppressed == []


def test_suppression_is_rule_specific(tmp_path):
    result = _lint_src(tmp_path, "core/timesteppers.py", """
import jax

def warm(x):
    jax.block_until_ready(x)  # dedalus-lint: disable=DTL002
""")
    # wrong rule named: the DTL001 finding stays active
    assert _rules_fired(result) == ["DTL001"]


def test_baseline_grandfathers_and_goes_stale(tmp_path):
    path = tmp_path / "core" / "timesteppers.py"
    path.parent.mkdir(parents=True)
    path.write_text("""
import jax

def warm(x):
    jax.block_until_ready(x)

def drain(x):
    jax.block_until_ready(x)
""")
    findings = run_lint([path]).findings
    assert len(findings) == 2
    baseline = {}
    for key, n in ((f.key(), 1) for f in findings):
        baseline[key] = baseline.get(key, 0) + n
    # grandfathered: nothing new, nothing stale
    new, stale = apply_baseline(findings, baseline)
    assert new == [] and stale == []
    # a third occurrence of the same snippet exceeds the baseline count
    path.write_text(path.read_text()
                    + "\n\ndef extra(x):\n    jax.block_until_ready(x)\n")
    new, stale = apply_baseline(run_lint([path]).findings, baseline)
    assert len(new) == 1 and stale == []
    # fixing every occurrence leaves the baseline stale
    path.write_text("import jax\n")
    new, stale = apply_baseline(run_lint([path]).findings, baseline)
    assert new == []
    assert len(stale) == 1 and stale[0]["rule"] == "DTL001"


def test_make_baseline_roundtrip(tmp_path):
    result = _lint_src(tmp_path, "core/timesteppers.py", """
import jax

def warm(x):
    jax.block_until_ready(x)
""")
    data = make_baseline(result.findings)
    assert data["version"] == 1
    assert len(data["entries"]) == 1
    entry = data["entries"][0]
    assert entry["rule"] == "DTL001"
    assert entry["snippet"] == "jax.block_until_ready(x)"


def test_multi_rule_same_line_suppression(tmp_path):
    """One comment can disable several rules on its line; each finding
    is counted separately (whitespace after commas tolerated)."""
    result = _lint_src(tmp_path, "mymod.py", """
import jax
import jax.numpy as jnp

def body(plan, data):
    jax.block_until_ready(data)  # dedalus-lint: disable=DTL001,DTL002
    return jnp.asarray(plan.matrix) @ data  # dedalus-lint: disable=DTL002, DTL001

jitted = jax.jit(body)
""")
    assert result.findings == []
    assert sorted(f.rule for f in result.suppressed) == ["DTL001", "DTL002"]


def test_multi_rule_disable_file(tmp_path):
    """disable-file accepts a rule list too, and leaves unnamed rules
    active."""
    result = _lint_src(tmp_path, "mymod.py", """
# dedalus-lint: disable-file=DTL002,DTL004
import jax
import jax.numpy as jnp
import numpy as np

def body(plan, data):
    a = jnp.asarray(plan.matrix)            # DTL002: file-suppressed
    b = jnp.zeros(4, dtype=np.float64)      # DTL004: file-suppressed
    jax.block_until_ready(data)             # DTL001: still active
    return a @ data + b

jitted = jax.jit(body)
""")
    assert _rules_fired(result) == ["DTL001"]
    assert sorted({f.rule for f in result.suppressed}) == ["DTL002",
                                                           "DTL004"]


def test_traced_detection_partial_jit_decorator(tmp_path):
    """functools.partial(jax.jit, ...) — decorator form AND call form —
    marks the function traced, so in-trace hazards fire without a plain
    jax.jit in sight."""
    result = _lint_src(tmp_path, "mymod.py", """
import functools
import numpy as np
import jax

@functools.partial(jax.jit, static_argnums=0)
def decorated(n, x):
    return np.asarray(x) + n          # DTL001: concretizes a tracer

def plain(x):
    return np.asarray(x) * 2          # DTL001 via the call form below

jitted = functools.partial(jax.jit, donate_argnums=())(plain)
""")
    dtl1 = [f for f in result.findings if f.rule == "DTL001"]
    assert len(dtl1) == 2, [f.format() for f in result.findings]


def test_traced_detection_noncall_contexts_stay_host(tmp_path):
    """A function never handed to a trace wrapper stays host code even
    when it LOOKS jit-adjacent (named like one, called next to one)."""
    result = _lint_src(tmp_path, "mymod2.py", """
import numpy as np
import jax

def jit_helper(x):
    return np.asarray(x)      # host: never traced

def run(x):
    return jax.jit(lambda v: v + 1)(x) + jit_helper(x).sum()
""")
    assert "DTL001" not in _rules_fired(result)


def test_dtl000_syntax_error_carries_location(tmp_path):
    """Unparsable modules surface as DTL000 findings with the parse
    error's line, participate in the baseline like any finding, and do
    not abort the scan of other files."""
    broken = tmp_path / "pkg" / "broken.py"
    broken.parent.mkdir(parents=True)
    broken.write_text("def f(:\n    pass\n")
    fine = broken.parent / "fine.py"
    fine.write_text("x = 1\n")
    result = run_lint([broken.parent])
    assert _rules_fired(result) == ["DTL000"]
    f = result.findings[0]
    assert f.line == 1 and "unparsable" in f.message
    # baseline round-trip: DTL000 grandfathering works like any rule
    new, stale = apply_baseline(result.findings, {f.key(): 1})
    assert new == [] and stale == []


def test_parallel_scan_matches_serial():
    """jobs>1 fans the per-file scan over a process pool; findings and
    suppressions must be IDENTICAL (content and order) to the serial
    pass over the real package tree."""
    serial = run_lint([PACKAGE_DIR])
    parallel = run_lint([PACKAGE_DIR], jobs=2)
    assert [f.to_dict() for f in parallel.findings] \
        == [f.to_dict() for f in serial.findings]
    assert [f.to_dict() for f in parallel.suppressed] \
        == [f.to_dict() for f in serial.suppressed]


def test_rules_filter_cli(tmp_path, capsys):
    """--rules runs the named subset only (and never reports package-
    baseline staleness, which a filtered run cannot judge); unknown ids
    are a usage error."""
    bad = tmp_path / "core" / "timesteppers.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("""
import jax
import jax.numpy as jnp
import numpy as np

def step(x):
    jax.block_until_ready(x)                  # DTL001
    return jnp.zeros(4, dtype=np.float64)     # DTL004
""")
    rc = lint_main([str(bad), "--no-baseline", "--rules", "DTL004"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "DTL004" in out and "DTL001" not in out
    rc = lint_main([str(bad), "--rules", "DTL999"])
    assert rc == 2
    assert "unknown rule id" in capsys.readouterr().err


def test_stale_entries_render_with_fixed_count_by_default(tmp_path,
                                                         capsys):
    """The framework docstring promise, now rendered: a DEFAULT run
    prints stale entries as warnings with the fixed-hazard count, so the
    baseline visibly shrinks without anyone running --update-baseline."""
    bad = tmp_path / "core" / "timesteppers.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("import jax\n\ndef f(x):\n    jax.block_until_ready(x)"
                   "\n\ndef g(x):\n    jax.block_until_ready(x)\n")
    baseline = tmp_path / "baseline.json"
    assert lint_main([str(bad), "--baseline", str(baseline),
                      "--update-baseline"]) == 0
    capsys.readouterr()
    bad.write_text("import jax\n")   # both hazards fixed
    rc = lint_main([str(bad), "--baseline", str(baseline)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "stale baseline entry" in out
    assert "2 grandfathered occurrences no longer found" in out
    assert "1 stale baseline entry" in out


# --------------------------------------------------------- package hygiene

def test_package_lints_clean_against_baseline():
    """Self-enforcement: the shipped package has no un-baselined findings
    and no stale baseline entries. A new hot-path sync / inlined constant /
    nested jit / wide dtype / private import fails tier-1 here."""
    summary = lint_package()
    assert summary["new"] == 0, summary["findings"]
    assert summary["stale"] == []
    # the baseline is a short grandfather list, not a dumping ground
    assert summary["baselined"] <= 10


def test_known_bad_fixture_fails_lint(tmp_path, capsys):
    bad = tmp_path / "core" / "timesteppers.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("import jax\n\ndef f(x):\n    jax.block_until_ready(x)\n")
    rc = lint_main([str(PACKAGE_DIR), str(bad)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "DTL001" in out
    assert "1 new" in out


def test_cli_baseline_workflow(tmp_path, capsys):
    bad = tmp_path / "core" / "timesteppers.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("import jax\n\ndef f(x):\n    jax.block_until_ready(x)\n")
    baseline = tmp_path / "baseline.json"
    assert lint_main([str(bad), "--no-baseline"]) == 1
    assert lint_main([str(bad), "--baseline", str(baseline),
                      "--update-baseline"]) == 0
    assert lint_main([str(bad), "--baseline", str(baseline)]) == 0
    # fixing the finding leaves the baseline stale -> nonzero until refreshed
    bad.write_text("import jax\n")
    assert lint_main([str(bad), "--baseline", str(baseline)]) == 1
    assert "stale" in capsys.readouterr().out


def test_update_baseline_refuses_path_subset(capsys):
    """Regenerating the PACKAGE baseline from a subset of paths would
    silently wipe every grandfathered entry outside them — including when
    the package baseline is spelled as a relative --baseline path."""
    before = DEFAULT_BASELINE.read_text()
    rc = lint_main([str(PACKAGE_DIR / "tools" / "health.py"),
                    "--update-baseline"])
    assert rc == 2
    assert "refusing" in capsys.readouterr().err
    assert DEFAULT_BASELINE.read_text() == before
    import os
    rel = os.path.relpath(DEFAULT_BASELINE)
    rc = lint_main([str(PACKAGE_DIR / "tools" / "health.py"),
                    "--update-baseline", "--baseline", rel])
    assert rc == 2
    assert DEFAULT_BASELINE.read_text() == before


def test_subset_scan_does_not_report_package_baseline_stale(capsys):
    """Linting one clean file against the default baseline must not call
    the out-of-scope grandfathered entries stale (they are unmatched
    because they were not scanned, not because they were fixed)."""
    rc = lint_main([str(PACKAGE_DIR / "tools" / "health.py")])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "stale" not in out or "0 stale" in out


def test_nonexistent_path_is_a_usage_error(tmp_path, capsys):
    rc = lint_main([str(tmp_path / "nope" / "missing.py"), "--no-baseline"])
    assert rc == 2
    assert "no such file" in capsys.readouterr().err


def test_cli_json_format(tmp_path, capsys):
    bad = tmp_path / "core" / "timesteppers.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("import jax\n\ndef f(x):\n    jax.block_until_ready(x)\n")
    rc = lint_main([str(bad), "--no-baseline", "--format", "json"])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert report["summary"]["new"] == 1
    assert report["findings"][0]["rule"] == "DTL001"
    assert report["findings"][0]["line"] == 4


def test_unparsable_file_is_a_finding(tmp_path):
    result = _lint_src(tmp_path, "broken.py", "def f(:\n")
    assert _rules_fired(result) == ["DTL000"]


def test_check_baseline_fresh(tmp_path):
    # shipped baseline: present and fresh
    assert check_baseline_fresh() == []
    assert DEFAULT_BASELINE.exists()
    missing = check_baseline_fresh(tmp_path / "nope.json")
    assert len(missing) == 1 and "missing" in missing[0]
    stale_file = tmp_path / "stale.json"
    stale_file.write_text(json.dumps({"version": 1, "entries": [
        {"rule": "DTL001", "path": "core/timesteppers.py",
         "snippet": "zzz_never_there()", "count": 1}]}))
    problems = check_baseline_fresh(stale_file)
    assert len(problems) == 1 and "stale" in problems[0]


def test_lint_cli_subprocess():
    """`python -m dedalus_tpu lint` is registered and exits 0 on the
    shipped tree (the acceptance-criteria invocation)."""
    proc = subprocess.run(
        [sys.executable, "-m", "dedalus_tpu", "lint", "dedalus_tpu/"],
        cwd=REPO, capture_output=True, text=True, timeout=240,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 new" in proc.stdout


# -------------------------------------------------------- retrace sentinel

@pytest.fixture
def clean_sentinel():
    retrace_mod.sentinel.reset()
    yield retrace_mod.sentinel
    retrace_mod.sentinel.reset()


def test_retrace_counts_and_warns_after_arm(clean_sentinel, caplog):
    from dedalus_tpu.tools.jitlift import lifted_jit
    m = metrics_mod.Metrics(sample_cadence=0, sampling=False)
    clean_sentinel.subscribe(m)
    fn = lifted_jit(lambda x: x * 2)
    fn(jnp.ones(3))
    fn(jnp.ones(3))          # cached signature: no new trace
    assert clean_sentinel.retraces == 0
    clean_sentinel.arm()
    with caplog.at_level(logging.WARNING, logger="dedalus_tpu.tools.retrace"):
        fn(jnp.ones(4))      # new signature after warmup: retrace
    assert clean_sentinel.post_arm_retraces == 1
    assert m.counter("dedalus/retrace").value == 1
    assert clean_sentinel.events[0]["kind"] == "retrace"
    assert any("post-warmup retrace" in r.message for r in caplog.records)


def test_first_trace_after_arm_is_not_a_retrace(clean_sentinel):
    from dedalus_tpu.tools.jitlift import lifted_jit
    clean_sentinel.arm()
    fn = lifted_jit(lambda x: x + 1)
    fn(jnp.ones(2))          # first compile of a fresh program: expected
    assert clean_sentinel.post_arm_retraces == 0
    assert clean_sentinel.total_traces >= 1


def test_noted_wrapper_participates(clean_sentinel):
    wrapped = retrace_mod.noted(lambda x: x + 1, "health/probe")
    j = jax.jit(wrapped)
    j(jnp.ones(2))
    j(jnp.ones(2))
    assert wrapped._retrace_state.count == 1
    clean_sentinel.arm()
    j(jnp.ones(3))
    assert clean_sentinel.post_arm_retraces == 1
    assert clean_sentinel.events[0]["label"] == "health/probe"


def test_retrace_warning_rate_limit_and_bounded_events(clean_sentinel,
                                                       caplog):
    """A retrace storm (the pathology the sentinel exists to catch) is
    fully counted but neither floods the log nor grows memory without
    bound."""
    from dedalus_tpu.tools.jitlift import lifted_jit
    fn = lifted_jit(lambda x: x.sum())
    fn(jnp.ones(1))
    clean_sentinel.arm()
    with caplog.at_level(logging.WARNING, logger="dedalus_tpu.tools.retrace"):
        for n in range(2, 10):          # 8 fresh signatures -> 8 retraces
            fn(jnp.ones(n))
    assert clean_sentinel.post_arm_retraces == 8
    warnings = [r for r in caplog.records
                if "post-warmup retrace" in r.message]
    assert len(warnings) == retrace_mod.WARNINGS_PER_LABEL
    assert "counted but not logged" in warnings[-1].message
    assert clean_sentinel.events.maxlen == retrace_mod.EVENT_RING_SIZE


def test_rb_step_loop_zero_post_warmup_retraces(clean_sentinel):
    """The acceptance-criteria sentinel assertion: the RB step loop —
    single steps and a scanned step_many block — compiles during/at
    warmup and never retraces afterwards; the verdict rides in the
    flushed telemetry record."""
    from dedalus_tpu.extras.bench_problems import build_rb_solver
    solver, b = build_rb_solver(32, 16, np.float64)
    solver.warmup_iterations = 2
    dt = 1e-4
    for _ in range(3):
        solver.step(dt)          # crosses warmup -> sentinel arms
    assert clean_sentinel.armed
    for _ in range(4):
        solver.step(dt)
    solver.step_many(4, dt)      # scan-block compile: first trace, no alarm
    solver.step_many(4, dt)
    assert clean_sentinel.post_arm_retraces == 0
    record = solver.flush_metrics()
    assert record["retraces_post_warmup"] == 0
    assert np.all(np.isfinite(np.asarray(solver.X)))


# ----------------------------------------------------- tracing-state probe

def test_tracing_active_public_path():
    from dedalus_tpu.tools import jitlift
    assert jitlift.tracing_active() is False
    seen = {}

    def f(x):
        seen["tracing"] = jitlift.tracing_active()
        return x

    jax.jit(f)(jnp.ones(2))
    assert seen["tracing"] is True
    assert jitlift.tracing_active() is False


def test_tracing_probe_degrades_with_one_warning(caplog):
    from dedalus_tpu.tools.jitlift import _resolve_tracing_probe

    def broken():
        raise ImportError("simulated jax API drift")

    with caplog.at_level(logging.WARNING, logger="dedalus_tpu.tools.jitlift"):
        probe = _resolve_tracing_probe(candidates=(broken, broken))
    assert probe() is False
    warnings = [r for r in caplog.records
                if "trace-state" in r.message]
    assert len(warnings) == 1


def test_tracing_probe_private_fallback_still_resolves():
    from dedalus_tpu.tools.jitlift import (_probe_private,
                                           _resolve_tracing_probe)

    def broken():
        raise AttributeError("public surface renamed")

    probe = _resolve_tracing_probe(candidates=(broken, _probe_private))
    assert probe() is False   # eager context: not tracing


def test_degraded_probe_does_not_poison_registry(monkeypatch):
    """With the probe degraded to never-tracing, a device_constant
    reached inside a foreign trace must NOT cache the resulting tracer
    in the process-global registry (jnp.asarray of a numpy array under
    a trace IS a tracer)."""
    from dedalus_tpu.tools import jitlift
    monkeypatch.setattr(jitlift, "_tracing_probe", jitlift._degraded_probe)
    assert jitlift.tracing_state_known() is False
    arr = np.arange(8.0)

    def f(x):
        return x + jitlift.device_constant(arr)

    assert np.allclose(np.asarray(jax.jit(f)(jnp.ones(8))), arr + 1)
    # the registry survived the foreign trace: eager use still works
    assert np.allclose(np.asarray(jitlift.device_constant(arr)), arr)
    assert np.allclose(np.asarray(jax.jit(f)(jnp.ones(8))), arr + 1)


def test_degraded_probe_keeps_general_function_callback_path(monkeypatch):
    """operators._tracing_active reports True when the probe degraded:
    an argless impure GeneralFunction has no tracer arguments for the
    call-site scan to catch, so unknown trace state must keep the
    io_callback path."""
    from dedalus_tpu.tools import jitlift
    from dedalus_tpu.core import operators
    assert operators._tracing_active() is False   # healthy probe, eager
    monkeypatch.setattr(jitlift, "_tracing_probe", jitlift._degraded_probe)
    assert operators._tracing_active() is True


# ------------------------------------------------------------ leak sentinel

@pytest.mark.leak_check
def test_lifted_jit_under_leak_check():
    """jitlift's discover/substitute machinery holds no tracers across
    trace boundaries (the registry caches numpy, never tracers); the
    leak_check marker runs this under jax.checking_leaks()."""
    from dedalus_tpu.tools.jitlift import lifted_jit
    fn = lifted_jit(lambda x: x * 3 + 1)
    out = fn(jnp.arange(4.0))
    assert np.allclose(np.asarray(out), np.arange(4.0) * 3 + 1)
