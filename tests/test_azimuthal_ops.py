"""
Azimuthal interpolation on curvilinear bases + Component index > 0
(VERDICT round-4 item 7; reference: dedalus/core/operators.py:1037
Interpolate, :2160-2283 Component family).

Azimuthal interpolation is grid-exact for band-limited data: the result
is a phi-constant field whose values equal the operand evaluated at
phi = position (tensor components in the coordinate frame there).
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3


PHI0 = 0.73


def grid_at_phi(field, phi0, axis):
    """Oracle: spectrally interpolate field['g'] to phi0 along `axis`
    with numpy (complex DFT evaluation — exact for band-limited data)."""
    g = np.asarray(field["g"], dtype=np.complex128)
    Ng = g.shape[axis]
    coeffs = np.fft.fft(g, axis=axis) / Ng
    ms = np.fft.fftfreq(Ng, d=1.0 / Ng)
    phase = np.exp(1j * ms * phi0)
    shape = [1] * g.ndim
    shape[axis] = Ng
    val = (coeffs * phase.reshape(shape)).sum(axis=axis)
    if not np.iscomplexobj(np.asarray(field["g"])):
        val = val.real
    return val


def test_disk_azimuthal_interpolation_scalar():
    cs = d3.PolarCoordinates("phi", "r")
    dist = d3.Distributor(cs, dtype=np.float64)
    disk = d3.DiskBasis(cs, shape=(24, 16), dtype=np.float64, radius=1.5)
    phi, r = dist.local_grids(disk)
    x, y = r * np.cos(phi), r * np.sin(phi)
    f = dist.Field(name="f", bases=disk)
    f["g"] = x ** 2 + 2 * x * y - y ** 2 + 3
    out = d3.Interpolate(f, cs["phi"], PHI0).evaluate()
    expected = grid_at_phi(f, PHI0, axis=0)
    got = np.asarray(out["g"])
    # phi-constant result equal to f(phi0, r) at every phi slot
    assert np.abs(got - expected[None, :]).max() < 1e-12


def test_disk_azimuthal_interpolation_vector():
    cs = d3.PolarCoordinates("phi", "r")
    dist = d3.Distributor(cs, dtype=np.float64)
    disk = d3.DiskBasis(cs, shape=(24, 16), dtype=np.float64, radius=1.5)
    phi, r = dist.local_grids(disk)
    x, y = r * np.cos(phi), r * np.sin(phi)
    u = dist.VectorField(cs, name="u", bases=disk)
    ux, uy = 2 * x * y, x ** 2 - y ** 2 + 1
    u["g"] = np.array([-np.sin(phi) * ux + np.cos(phi) * uy,
                       np.cos(phi) * ux + np.sin(phi) * uy])
    out = d3.Interpolate(u, cs["phi"], PHI0).evaluate()
    expected = grid_at_phi(u, PHI0, axis=1)     # tensor axis leads
    got = np.asarray(out["g"])
    assert np.abs(got - expected[:, None, :]).max() < 1e-12


def test_annulus_azimuthal_interpolation():
    cs = d3.PolarCoordinates("phi", "r")
    dist = d3.Distributor(cs, dtype=np.float64)
    ann = d3.AnnulusBasis(cs, shape=(24, 16), dtype=np.float64,
                          radii=(0.5, 2.0))
    phi, r = dist.local_grids(ann)
    f = dist.Field(name="f", bases=ann)
    f["g"] = np.cos(3 * phi) * r ** 2 + np.sin(phi) / r
    out = d3.Interpolate(f, cs["phi"], PHI0).evaluate()
    expected = grid_at_phi(f, PHI0, axis=0)
    assert np.abs(np.asarray(out["g"]) - expected[None, :]).max() < 1e-12


def test_sphere_azimuthal_interpolation():
    cs = d3.S2Coordinates("phi", "theta")
    dist = d3.Distributor(cs, dtype=np.float64)
    sph = d3.SphereBasis(cs, shape=(24, 12), dtype=np.float64, radius=1.0)
    phi, theta = dist.local_grids(sph)
    f = dist.Field(name="f", bases=sph)
    f["g"] = (1 + np.cos(theta) ** 2) * (1 + 0.3 * np.cos(2 * phi)
                                         + 0.2 * np.sin(phi))
    out = d3.Interpolate(f, cs["phi"], PHI0).evaluate()
    expected = grid_at_phi(f, PHI0, axis=0)
    assert np.abs(np.asarray(out["g"]) - expected[None, :]).max() < 1e-12


def test_shell_azimuthal_interpolation():
    cs = d3.SphericalCoordinates("phi", "theta", "r")
    dist = d3.Distributor(cs, dtype=np.float64)
    shell = d3.ShellBasis(cs, shape=(12, 8, 8), dtype=np.float64,
                          radii=(0.6, 1.4))
    phi, theta, r = dist.local_grids(shell)
    f = dist.Field(name="f", bases=shell)
    f["g"] = (r ** 2 * np.sin(theta) ** 2 * np.cos(2 * phi)
              + r * np.cos(theta) + 1)
    out = d3.Interpolate(f, cs["phi"], PHI0).evaluate()
    expected = grid_at_phi(f, PHI0, axis=0)
    assert np.abs(np.asarray(out["g"]) - expected[None]).max() < 1e-11


def test_azimuthal_interpolation_rejected_on_lhs():
    cs = d3.PolarCoordinates("phi", "r")
    dist = d3.Distributor(cs, dtype=np.float64)
    disk = d3.DiskBasis(cs, shape=(16, 8), dtype=np.float64, radius=1.0)
    f = dist.Field(name="f", bases=disk)
    tau = dist.Field(name="tau")
    problem = d3.LBVP([f, tau], namespace=locals())
    with pytest.raises(Exception):
        problem.add_equation("interp(f, phi=0.5) + tau = 1")
        problem.build_solver()


# ------------------------------------------------- Component index > 0

def test_polar_component_index1_rank2():
    """Extract the SECOND index's components of a rank-2 disk tensor on
    the RHS and compare against direct grid slices (grid storage is
    coordinate components: axis order (phi, r))."""
    cs = d3.PolarCoordinates("phi", "r")
    dist = d3.Distributor(cs, dtype=np.float64)
    disk = d3.DiskBasis(cs, shape=(24, 16), dtype=np.float64, radius=1.5)
    phi, r = dist.local_grids(disk)
    x, y = r * np.cos(phi), r * np.sin(phi)
    T = dist.TensorField(cs, name="T", bases=disk)
    Tc = np.array([[x * y + 0 * r, x ** 2 + 0 * r],
                   [y ** 2 + 0 * r, x + y + 0 * r]])
    R = np.array([[-np.sin(phi) + 0 * r, np.cos(phi) + 0 * r],
                  [np.cos(phi) + 0 * r, np.sin(phi) + 0 * r]])
    T["g"] = np.einsum("ia...,ab...,jb...->ij...", R, Tc, R)
    g = np.array(T["g"])
    # grid-layout oracle (coordinate components of smooth tensors are not
    # regular scalars, so a coeff roundtrip through .evaluate() converges
    # only spectrally — the extraction itself is an exact grid selection)
    from dedalus_tpu.core.future import EvalContext
    rad1 = np.asarray(d3.Radial(T, index=1).ev(EvalContext(), "g"))
    azi1 = np.asarray(d3.Azimuthal(T, index=1).ev(EvalContext(), "g"))
    rad0 = np.asarray(d3.Radial(T, index=0).ev(EvalContext(), "g"))
    assert np.abs(rad1 - g[:, 1]).max() < 1e-12
    assert np.abs(azi1 - g[:, 0]).max() < 1e-12
    assert np.abs(rad0 - g[1]).max() < 1e-12
    # end-to-end .evaluate() additionally projects onto the disk's
    # regular function space; coordinate columns of smooth tensors are
    # generally NOT regular vectors (e.g. a*e_r has m=3 content at r^2),
    # so the projection converges spectrally rather than reproducing the
    # grid selection exactly — same semantics at every index
    out1 = d3.Radial(T, index=1).evaluate()
    out0 = d3.Radial(T, index=0).evaluate()
    assert np.abs(np.asarray(out1["g"]) - g[:, 1]).max() < 0.05
    assert np.abs(np.asarray(out0["g"]) - g[1]).max() < 0.05


def test_spherical_component_index1_rank2():
    """S2 boundary fields (spin storage with a constant selection matrix;
    interiors use regularity storage and are excluded by construction)."""
    cs = d3.SphericalCoordinates("phi", "theta", "r")
    dist = d3.Distributor(cs, dtype=np.float64)
    sphere = d3.SphereBasis(cs.S2coordsys, shape=(12, 8), dtype=np.float64,
                            radius=1.0)
    u = dist.VectorField(cs, name="u", bases=sphere)
    v = dist.VectorField(cs, name="v", bases=sphere)
    phi, theta = dist.local_grids(sphere)
    u["g"][2] = 1 + 0.1 * np.cos(theta) + 0 * phi
    u["g"][1] = np.sin(theta) + 0 * phi
    v["g"][2] = 0.5 + 0 * theta + 0 * phi
    v["g"][0] = np.sin(theta) * np.cos(phi)
    T = (u * v).evaluate()            # rank 2 spherical tensor on S2
    Tg = np.asarray(T["g"])
    rad1 = d3.Radial(T, index=1).evaluate()
    assert np.abs(np.asarray(rad1["g"]) - Tg[:, 2]).max() < 1e-10


def test_sphere_colatitude_interpolation():
    """theta=const interpolation on the sphere (PolarInterpolate over the
    SWSH per-m interpolation stacks): exact at collocation points,
    output on the S1 azimuth basis."""
    cs = d3.S2Coordinates("phi", "theta")
    dist = d3.Distributor(cs, dtype=np.float64)
    sph = d3.SphereBasis(cs, shape=(16, 8), dtype=np.float64, radius=1.0)
    phi, theta = dist.local_grids(sph)
    f = dist.Field(name="f", bases=sph)
    f["g"] = ((1 + np.cos(theta) ** 2) * (1 + 0.3 * np.cos(2 * phi))
              + np.sin(theta) * np.sin(phi))
    th_grid = theta.ravel()
    out = d3.Interpolate(f, cs["theta"], float(th_grid[3])).evaluate()
    assert out.domain.bases[1] is None         # colatitude removed
    fg = np.asarray(f["g"])
    assert np.abs(np.asarray(out["g"]).ravel() - fg[:, 3]).max() < 1e-12


def test_azimuthal_average_sphere_and_annulus():
    """AzimuthalAverage = the m=0 projection (reference:
    core/basis.py:5202): matches the phi-mean of the grid data."""
    cs = d3.S2Coordinates("phi", "theta")
    dist = d3.Distributor(cs, dtype=np.float64)
    sph = d3.SphereBasis(cs, shape=(16, 8), dtype=np.float64, radius=1.0)
    phi, theta = dist.local_grids(sph)
    f = dist.Field(name="f", bases=sph)
    f["g"] = np.cos(theta) ** 2 * (1 + 0.4 * np.cos(3 * phi)) + np.sin(phi)
    out = d3.AzimuthalAverage(f).evaluate()
    mean = np.asarray(f["g"]).mean(axis=0)
    assert np.abs(np.asarray(out["g"]) - mean[None, :]).max() < 1e-12

    csp = d3.PolarCoordinates("phi", "r")
    distp = d3.Distributor(csp, dtype=np.float64)
    ann = d3.AnnulusBasis(csp, shape=(16, 8), dtype=np.float64,
                          radii=(0.5, 2.0))
    phi, r = distp.local_grids(ann)
    g = distp.Field(name="g", bases=ann)
    g["g"] = r ** 2 * (1 + np.sin(2 * phi)) + np.cos(phi) / r
    out = d3.AzimuthalAverage(g).evaluate()
    mean = np.asarray(g["g"]).mean(axis=0)
    assert np.abs(np.asarray(out["g"]) - mean[None, :]).max() < 1e-12


def test_interpolate_convert_aliases():
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=16, bounds=(0, 2 * np.pi))
    f = dist.Field(name="f", bases=xb)
    x = dist.local_grids(xb)[0]
    f["g"] = np.sin(x)
    out = d3.interpolate(f, x=0.5).evaluate()
    assert abs(float(np.asarray(out["g"]).ravel()[0]) - np.sin(0.5)) < 1e-12
    assert d3.Transpose is d3.TransposeComponents
    assert d3.convert is d3.Convert
