"""
Compiled-program contract checker (tools/lint/progcheck.py).

Two layers of proof:

  * the REAL census: the fast subset lowers the shipped step/fleet/grad/
    pool programs on the virtual CPU mesh and must report ZERO new
    findings against the checked-in progcheck_baseline.json — this is
    the tier-1 gate that keeps every future PR's compiled programs
    contract-checked by default;
  * SEEDED regressions: each encoded bug class (a dropped donation, a
    restored jnp.pad in a partial-auto region, a gather-degraded chunk
    stage, a triangular custom call on the fused path, a host callback
    in a step body) is reproduced as a small fixture program and must
    produce its NAMED finding — so a quiet census is evidence the
    contracts look, not that they cannot see.
"""

import hashlib
import re
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dedalus_tpu.tools.compat import shard_map
from dedalus_tpu.tools.lint import progcheck
from dedalus_tpu.tools.lint.cli import main as lint_main
from dedalus_tpu.tools.lint.framework import apply_baseline, make_baseline
from dedalus_tpu.tools.lint.progcheck import (CONTRACTS, ProgramRecord,
                                              check_records,
                                              collective_counts,
                                              donated_alias_count,
                                              gather_buffers,
                                              pads_in_auto_regions,
                                              record_from_jit, run_programs)

pytestmark = pytest.mark.progcheck

N_DEV = len(jax.devices())
needs_devices = pytest.mark.skipif(N_DEV < 4, reason="needs >= 4 devices")
needs_8 = pytest.mark.skipif(N_DEV < 8, reason="needs >= 8 devices")

# the tier-1 subset: every contract exercised on at least one REAL
# program, the expensive banded-RB builds left to the full CLI census
# (tau_step_ascan is the fast DTP106 anchor: a small banded build whose
# lowered step must carry no sequential substitution scan; traced_step
# is the DTP107 anchor: the same step lowered with tracing on must hash
# to the untraced build)
FAST_SUBSET = ["diffusion_step", "sharded_step_1d", "chunked_walk_1d",
               "fleet_2d", "adjoint_grad", "pool_step", "tau_step_ascan",
               "traced_step"]


def _rules_fired(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------- the real census

@pytest.fixture(scope="module")
def fast_report():
    """One fast-subset census per module: the expensive part of every
    real-program assertion below."""
    return run_programs(names=FAST_SUBSET)


@needs_8
def test_census_head_is_clean(fast_report):
    """The acceptance gate: the shipped programs carry zero new contract
    findings and the checked-in baseline is empty and fresh."""
    summary = fast_report["summary"]
    assert summary["new"] == 0, fast_report["findings"]
    assert summary["stale"] == []
    assert summary["skipped"] == []
    # the baseline is empty on a healthy tree — true positives get fixed,
    # not grandfathered
    assert summary["baselined"] == 0


@needs_8
def test_census_breadth(fast_report):
    """The subset really lowers the distinct program shapes the
    contracts claim to cover: a sharded step, a chunked walk (both
    directions), a 2-D batch x pencil fleet, an adjoint grad program and
    a pool-served entry."""
    rows = {row["program"]: row for row in fast_report["programs"]}
    assert set(rows) == {"diffusion_step", "sharded_step_1d",
                         "chunked_walk_to_grid", "chunked_walk_to_coeff",
                         "fleet_2d", "adjoint_grad", "pool_step",
                         "tau_step_ascan", "traced_step"}
    # collective placement facts the weak-scaling/fusion claims rest on
    assert rows["sharded_step_1d"]["collectives"]["all-to-all"] >= 2
    assert rows["sharded_step_1d"]["collectives"]["all-gather"] == 0
    assert rows["fleet_2d"]["collectives"]["all-gather"] == 0
    assert rows["fleet_2d"]["pads_in_auto_regions"] == 0
    assert rows["chunked_walk_to_grid"]["collectives"]["all-to-all"] >= 2
    # donation honored on the donating programs
    assert rows["diffusion_step"]["donated_aliases"] >= 3
    assert rows["pool_step"]["donated_aliases"] >= 3
    # the depth contract's fast anchor: the associative-scan step's
    # longest surviving scan sits under its declared log-depth bound
    ascan = rows["tau_step_ascan"]
    assert ascan["fused_solve"] is True
    assert ascan["while_loops"] == 0
    assert max(ascan["scan_lengths"], default=0) <= ascan["max_scan_length"]
    # the tracing-inert anchor: the census carried the untraced build's
    # hash, and head-clean above means the traced build matched it
    traced = rows["traced_step"]
    assert re.fullmatch(r"[0-9a-f]{64}", traced["untraced_sha256"])
    # per-contract timings recorded for every registered contract
    assert set(fast_report["timings"]["contracts"]) == set(CONTRACTS)


@needs_8
def test_full_census_names_cover_required_shapes():
    """The FULL census registry (the `lint --programs` default) includes
    the fused and unfused RB banded steps on top of the fast subset."""
    names = progcheck.census_names()
    for required in ("rb_step_fused", "rb_step_unfused", "diffusion_step",
                     "sharded_step_1d", "chunked_walk_1d",
                     "chunked_walk_2dmesh", "fleet_2d",
                     "ensemble_fleet_1d", "adjoint_grad", "pool_step",
                     "tau_step_ascan", "rb_step_spike", "rb_step_ladder",
                     "traced_step"):
        assert required in names
    fast = progcheck.census_names(fast_only=True)
    assert "rb_step_fused" not in fast and "rb_step_unfused" not in fast
    assert "rb_step_spike" not in fast and "rb_step_ladder" not in fast
    assert "tau_step_ascan" in fast


# ------------------------------------------------ seeded regressions

def test_seeded_dropped_donation():
    """A program that declares donated buffers but compiles without the
    aliases (the dropped-donation memory regression) produces a named
    DTP104 finding; the same program WITH donation passes."""
    args = (jnp.ones((8, 8)), jnp.ones((8, 8)))

    def body(a, b):
        return a + 1.0, b * 2.0

    dropped = record_from_jit("seed_drop_donation", body, args,
                              meta={"donated": 2})
    findings, _, _ = check_records([dropped])
    assert _rules_fired(findings) == ["DTP104"]
    assert "donation was dropped" in findings[0].message
    honored = record_from_jit("seed_honored_donation", body, args,
                              meta={"donated": 2}, donate_argnums=(0, 1))
    assert donated_alias_count(honored.compiled_text) == 2
    findings, _, _ = check_records([honored])
    assert findings == []


def test_seeded_tracing_divergence():
    """A program whose tracing-enabled build hashes differently from its
    declared untraced build (instrumentation leaked into the lowered
    computation) produces a named DTP107 finding; a matching hash — and
    a record with no declared hash — pass."""
    args = (jnp.ones((8, 8)),)

    def body(a):
        return a * 2.0

    rec = record_from_jit("seed_traced_match", body, args)
    rec.meta["untraced_sha256"] = hashlib.sha256(
        rec.compiled_text.encode()).hexdigest()
    findings, _, _ = check_records([rec])
    assert findings == []

    diverged = record_from_jit("seed_traced_diverged", body, args)
    diverged.meta["untraced_sha256"] = hashlib.sha256(
        (diverged.compiled_text + "x").encode()).hexdigest()
    findings, _, _ = check_records([diverged])
    assert _rules_fired(findings) == ["DTP107"]
    assert "instrumentation has leaked" in findings[0].message

    undeclared = record_from_jit("seed_traced_undeclared", body, args)
    findings, _, _ = check_records([undeclared])
    assert findings == []


@needs_devices
def test_seeded_pad_in_auto_region():
    """jnp.pad restored inside a PARTIAL-AUTO shard_map region (the
    jaxlib SPMD-partitioner crash class) produces DTP105; the identical
    pad inside a FULLY MANUAL region is exempt (explicitly partitioned),
    and the zeropad lowering passes everywhere."""
    from dedalus_tpu.tools.array import zeropad
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("a", "b"))
    x = jnp.ones((8, 8))

    def padded(block):
        return jnp.pad(block, ((0, 0), (1, 1)))[:, 1:-1] * 2.0

    def zeropadded(block):
        return zeropad(block, ((0, 0), (1, 1)))[:, 1:-1] * 2.0

    def wrap(body, auto):
        kw = {"check_rep": False, "auto": frozenset({"b"})} if auto else {}
        return partial(shard_map, mesh=mesh, in_specs=P("a"),
                       out_specs=P("a"), **kw)(body)

    # compile=False: compiling this program ABORTS the process inside
    # the XLA partitioner (the crash is a CHECK failure, not a raisable
    # error) — the contract's value is precisely that it catches the pad
    # at the jaxpr tier, before any compile
    bad = record_from_jit("seed_pad_auto", wrap(padded, auto=True), (x,),
                          compile=False)
    assert pads_in_auto_regions(bad.jaxpr) == 1
    findings, _, _ = check_records([bad])
    assert _rules_fired(findings) == ["DTP105"]
    assert "partial-auto" in findings[0].message
    manual = record_from_jit("seed_pad_manual", wrap(padded, auto=False),
                             (x,))
    fixed = record_from_jit("seed_zeropad_auto", wrap(zeropadded, auto=True),
                            (x,))
    findings, _, _ = check_records([manual, fixed])
    assert findings == []


@needs_devices
def test_seeded_gather_degraded_stage():
    """A stage that gathers the full state instead of exchanging
    all-to-all (the GSPMD fallback) fails BOTH ways: the state-sized
    gather (DTP101) and the missing declared all-to-all (DTP103)."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
    x = jax.device_put(jnp.arange(64.0).reshape(16, 4),
                       NamedSharding(mesh, P("x", None)))

    @partial(shard_map, mesh=mesh, in_specs=P("x"), out_specs=P("x"))
    def degraded(block):
        full = jax.lax.all_gather(block, "x", tiled=True)
        return full[:block.shape[0]] * 2.0

    meta = {"sharded": True, "state_bytes": int(x.nbytes),
            "expected_a2a_min": 1}
    rec = record_from_jit("seed_gather_degraded", degraded, (x,), meta=meta)
    assert gather_buffers(rec.compiled_text)
    findings, _, _ = check_records([rec])
    assert _rules_fired(findings) == ["DTP101", "DTP103"]
    by_rule = {f.rule: f for f in findings}
    assert "full-state all-gather" in by_rule["DTP101"].message
    assert "degraded to a gather" in by_rule["DTP103"].message
    # the size-aware bound: the SAME gather against a much larger
    # declared state is a small bookkeeping gather, not a violation
    small = record_from_jit(
        "seed_small_gather", degraded, (x,),
        meta={"sharded": True, "state_bytes": int(x.nbytes) * 100})
    findings, _, _ = check_records([small])
    assert findings == []


def test_seeded_triangular_on_fused_path():
    """A triangular/pivot solve inside a program declared fused_solve
    (the precomposed-GEMM substitution) produces DTP102; the same
    program NOT declared fused (the legacy path) is legal."""
    A = jnp.eye(6) + jnp.tril(jnp.ones((6, 6))) * 0.1
    b = jnp.ones(6)

    def solve(A, b):
        return jax.scipy.linalg.solve_triangular(A, b, lower=True)

    fused = record_from_jit("seed_fused_triangular", solve, (A, b),
                            meta={"fused_solve": True})
    findings, _, _ = check_records([fused])
    assert _rules_fired(findings) == ["DTP102"]
    assert "triangular" in findings[0].message or \
        "triangular_solve" in findings[0].snippet
    legacy = record_from_jit("seed_legacy_triangular", solve, (A, b))
    findings, _, _ = check_records([legacy])
    assert findings == []


def test_seeded_host_callback_in_step_body():
    """A host callback compiled into any census program body produces
    DTP102 regardless of fusion flags (no transpose rule, serializes
    dispatch)."""
    from jax.experimental import io_callback

    def body(x):
        io_callback(lambda v: None, None, x[0])
        return x * 2.0

    rec = record_from_jit("seed_callback", body, (jnp.ones(4),))
    findings, _, _ = check_records([rec])
    assert "DTP102" in _rules_fired(findings)
    assert any("callback" in f.message for f in findings)


def test_seeded_sequential_scan_regression():
    """A lax.scan longer than the declared substitution depth bound
    produces DTP106 (the depth claim made machine-checkable); the same
    program without the declaration is legal, and a while loop inside a
    depth-bounded program is flagged as unprovable."""

    def seq_sweep(ops, x):
        def body(c, op):
            return op @ c, c
        out, _ = jax.lax.scan(body, x, ops)
        return out

    ops = jnp.stack([jnp.eye(4)] * 64)
    x = jnp.ones(4)
    rec = record_from_jit("seed_seq_scan", seq_sweep, (ops, x),
                          meta={"max_scan_length": 5})
    findings, _, _ = check_records([rec])
    assert _rules_fired(findings) == ["DTP106"]
    assert "64" in findings[0].message
    undeclared = record_from_jit("seed_seq_scan_free", seq_sweep, (ops, x))
    findings, _, _ = check_records([undeclared])
    assert findings == []
    # an in-bound refinement loop passes
    small = record_from_jit(
        "seed_small_scan", seq_sweep, (jnp.stack([jnp.eye(4)] * 3), x),
        meta={"max_scan_length": 5})
    findings, _, _ = check_records([small])
    assert findings == []

    def while_sweep(x):
        return jax.lax.while_loop(lambda v: jnp.sum(v) < 1e3,
                                  lambda v: v * 2.0, x)

    wrec = record_from_jit("seed_while", while_sweep, (jnp.ones(4),),
                           meta={"max_scan_length": 5})
    findings, _, _ = check_records([wrec])
    assert _rules_fired(findings) == ["DTP106"]
    assert "while" in findings[0].message


# -------------------------------------- baseline/waiver discipline

def test_program_findings_baseline_roundtrip():
    """Program findings grandfather exactly like AST findings: stable
    pseudo-path keys, counts absorbed, staleness when fixed."""
    rec = record_from_jit("seed_baseline", lambda a: a + 1.0,
                          (jnp.ones(4),), meta={"donated": 1})
    findings, _, _ = check_records([rec])
    assert _rules_fired(findings) == ["DTP104"]
    key = findings[0].key()
    assert key[1] == "__programs__/seed_baseline.hlo"
    baseline = {k: 1 for k in {f.key() for f in findings}}
    new, stale = apply_baseline(findings, baseline)
    assert new == [] and stale == []
    # fixing the program leaves the entry stale (the baseline shrinks)
    fixed = record_from_jit("seed_baseline", lambda a: a + 1.0,
                            (jnp.ones(4),), meta={"donated": 1},
                            donate_argnums=(0,))
    findings, _, _ = check_records([fixed])
    new, stale = apply_baseline(findings, baseline)
    assert new == [] and len(stale) == 1
    assert stale[0]["rule"] == "DTP104" and stale[0]["missing"] == 1
    # make_baseline round-trips the same keys
    data = make_baseline([])
    assert data["entries"] == []


def test_program_waiver_counts_as_suppressed():
    """A census entry can waive a contract for one program; the finding
    is counted as suppressed, never silently dropped."""
    rec = record_from_jit("seed_waived", lambda a: a + 1.0,
                          (jnp.ones(4),),
                          meta={"donated": 1, "waive": {"DTP104"}})
    findings, suppressed, _ = check_records([rec])
    assert findings == []
    assert _rules_fired(suppressed) == ["DTP104"]


def test_skipped_records_are_reported_not_checked():
    rec = ProgramRecord("needs_more_devices", skipped="needs >= 64 devices")
    findings, _, _ = check_records([rec])
    assert findings == []
    summary = {"skipped": rec.skipped}
    assert "64" in summary["skipped"]


def test_unknown_selection_raises():
    with pytest.raises(KeyError, match="unknown census program"):
        progcheck.run_census(["nope"])
    with pytest.raises(KeyError, match="unknown contract"):
        run_programs(names=[], contracts=["DTPXXX"])


# ------------------------------------------------------------ CLI wiring

@needs_8
def test_cli_programs_json_roundtrip(capsys):
    """`lint --programs --json` (the standalone CI invocation) renders
    the census + per-contract timings and exits 0 on the healthy tree."""
    import json
    rc = lint_main(["--programs", "--select", "diffusion_step",
                    "--contracts", "DTP102,DTP104", "--format", "json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert report["summary"]["new"] == 0
    assert report["programs"][0]["program"] == "diffusion_step"
    assert report["programs"][0]["donated_aliases"] >= 3
    assert set(report["timings"]["contracts"]) == {"DTP102", "DTP104"}
    assert report["timings"]["census"]["diffusion_step"] > 0


def test_cli_programs_exits_nonzero_on_new_finding(capsys, monkeypatch):
    """A seeded census regression drives the CLI to rc 1 with the named
    finding — the property standalone CI relies on."""
    def bad_builder():
        return [record_from_jit(
            "seed_cli_bad", lambda a: a + 1.0, (jnp.ones(4),),
            meta={"donated": 1})]

    monkeypatch.setitem(progcheck.CENSUS, "seed_cli_bad",
                        (bad_builder, True))
    rc = lint_main(["--programs", "--select", "seed_cli_bad"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "DTP104" in out and "1 new" in out


def test_cli_programs_update_baseline_refuses_subset(capsys, tmp_path):
    """Regenerating the PROGRAMS baseline from a census subset would
    drop entries outside the selection — same refusal discipline as the
    AST tier; a scoped --baseline FILE is the sanctioned escape."""
    before = progcheck.PROGRAMS_BASELINE.read_text()
    rc = lint_main(["--programs", "--select", "diffusion_step",
                    "--update-baseline"])
    assert rc == 2
    assert "refusing" in capsys.readouterr().err
    assert progcheck.PROGRAMS_BASELINE.read_text() == before


def test_cli_programs_rejects_paths(capsys):
    rc = lint_main(["--programs", "dedalus_tpu/"])
    assert rc == 2
    assert "--programs" in capsys.readouterr().err


# ----------------------------------------------------- analysis helpers

def test_collective_counts_parser():
    text = """
  %a = f64[4,8]{1,0} all-to-all(f64[4,8]{1,0} %p), replica_groups={}
  %b = f64[16,8]{1,0} all-gather(f64[4,8]{1,0} %p), dimensions={0}
  %c = (f64[16,8]{1,0}, f64[4]{0}) all-gather-start(f64[4,8]{1,0} %p)
  %d = f64[4,8]{1,0} all-reduce(f64[4,8]{1,0} %p)
"""
    counts = collective_counts(text)
    assert counts["all-to-all"] == 1
    assert counts["all-gather"] == 2
    assert counts["all-reduce"] == 1
    sizes = gather_buffers(text)
    assert ("f64", "16,8", 16 * 8 * 8) in sizes


def test_donated_alias_count_parser():
    head = ("HloModule jit_f, is_scheduled=true, input_output_alias={ "
            "{0}: (5, {}, may-alias), {1}: (6, {}, may-alias), "
            "{2}: (7, {}, may-alias) }, entry_computation_layout={...}\n"
            "ENTRY %main ...")
    assert donated_alias_count(head) == 3
    assert donated_alias_count("HloModule jit_f, is_scheduled=true\n") == 0


# ------------------------------------------------------- resource ledger

class _FakeMem:
    argument_size_in_bytes = 100
    output_size_in_bytes = 40
    temp_size_in_bytes = 60
    generated_code_size_in_bytes = 7
    alias_size_in_bytes = 30


class _FakeCompiled:
    def cost_analysis(self):
        return {"flops": 123.0, "transcendentals": 4.0,
                "bytes accessed": 456.0}

    def memory_analysis(self):
        return _FakeMem()


def test_program_ledger_full():
    text = ("  %a = f32[4]{0} add(f32[4]{0} %p, f32[4]{0} %p)\n"
            "  ROOT %b = f32[4]{0} multiply(%a, %a)\n")
    led = progcheck.program_ledger(_FakeCompiled(), hlo_text=text)
    assert led["ledger_version"] == 1
    assert led["flops"] == 123
    assert led["transcendentals"] == 4
    assert led["bytes_accessed"] == 456
    assert led["argument_bytes"] == 100
    assert led["peak_bytes"] == 100 + 40 + 60 - 30   # alias-corrected
    assert led["hlo_instructions"] == 2


def test_program_ledger_guarded_fallbacks():
    """A backend where the analyses are absent or raise yields nulls for
    their fields and never an exception — the census stays green."""
    class Raising:
        def cost_analysis(self):
            raise NotImplementedError("no cost analysis here")

        def memory_analysis(self):
            raise RuntimeError("nor memory analysis")

    led = progcheck.program_ledger(Raising())
    assert led["ledger_version"] == 1
    assert all(led[f] is None for f in progcheck.LEDGER_FIELDS)

    class Missing:
        pass                      # neither method exists at all

    led = progcheck.program_ledger(Missing(), hlo_text="%r = f32[] x()")
    assert led["flops"] is None and led["peak_bytes"] is None
    assert led["hlo_instructions"] == 1

    class OldStyle:               # list-of-dicts cost_analysis (old jax)
        def cost_analysis(self):
            return [{"flops": 9.0}, {"flops": 1.0}]

        def memory_analysis(self):
            raise RuntimeError("unavailable")

    led = progcheck.program_ledger(OldStyle())
    assert led["flops"] == 9                   # main computation first
    assert led["bytes_accessed"] is None
    assert led["argument_bytes"] is None


def test_record_from_jit_carries_ledger():
    rec = record_from_jit("seed_ledgered",
                          lambda a: jnp.sin(a) * 2.0, (jnp.ones(64),))
    assert rec.ledger is not None
    assert rec.ledger["ledger_version"] == 1
    assert rec.ledger["hlo_instructions"] > 0
    assert rec.stats()["ledger"] == rec.ledger
    # jaxpr-only records (the DTP105 tier) carry no ledger — and report
    # none rather than zeros
    uncompiled = record_from_jit("seed_uncompiled", lambda a: a + 1.0,
                                 (jnp.ones(4),), compile=False)
    assert uncompiled.ledger is None
    assert "ledger" not in uncompiled.stats()


def test_ledger_rows_shape_and_scan_depth():
    def scanned(x):
        return jax.lax.scan(lambda c, _: (c + 1.0, None), x, None,
                            length=17)[0]

    rec = record_from_jit("seed_ledger_row", scanned, (jnp.ones(8),))
    skipped = ProgramRecord("too_big", skipped="needs >= 64 devices")
    rows = progcheck.ledger_rows([rec, skipped])
    assert len(rows) == 1                       # skipped yields no row
    row = rows[0]
    assert row["kind"] == "ledger"
    assert row["config"] == "progcheck_census"
    assert row["program"] == "seed_ledger_row"
    assert row["scan_max_length"] == 17
    assert row["while_loops"] == 0
    assert row["plan"] is None                  # fixture has no solver
    assert row["env"]["env_version"] == 1
    assert row["env"]["python"]                 # fingerprint is stamped
    assert row["hlo_instructions"] > 0


def test_append_ledger_rows_appends(tmp_path):
    import json
    rec = record_from_jit("seed_ledger_append", lambda a: a * 2.0,
                          (jnp.ones(8),))
    sink = tmp_path / "results.jsonl"
    rows = progcheck.append_ledger_rows([rec], sink)
    assert len(rows) == 1
    lines = sink.read_text().splitlines()
    assert len(lines) == 1
    row = json.loads(lines[0])
    assert row["program"] == "seed_ledger_append"
    assert row["ts"] > 0
    progcheck.append_ledger_rows([rec], sink)   # append, never truncate
    assert len(sink.read_text().splitlines()) == 2


def test_cli_programs_ledger_flag(capsys, monkeypatch, tmp_path):
    """`lint --programs --ledger PATH` appends trajectory rows and says
    so; without the flag the census writes nothing."""
    import json

    def builder():
        return [record_from_jit("seed_ledger_cli", lambda a: a * 2.0,
                                (jnp.ones(8),))]

    monkeypatch.setitem(progcheck.CENSUS, "seed_ledger_cli",
                        (builder, True))
    sink = tmp_path / "results.jsonl"
    rc = lint_main(["--programs", "--select", "seed_ledger_cli",
                    "--ledger", str(sink)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "ledger: 1 trajectory row(s) appended" in out
    row = json.loads(sink.read_text().splitlines()[0])
    assert row["kind"] == "ledger"
    assert row["program"] == "seed_ledger_cli"
    rc = lint_main(["--programs", "--select", "seed_ledger_cli"])
    capsys.readouterr()
    assert rc == 0
    assert len(sink.read_text().splitlines()) == 1   # opt-in: no growth
