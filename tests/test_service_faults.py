"""
Service-level fault tolerance (dedalus_tpu/service/faults.py + the
server wiring): every degradation path — load shedding, deadlines (in
queue and mid-run with a checkpoint), the hung-dispatch watchdog,
circuit-breaker open/half-open/close, client drops, idempotent replay,
the memory watermark, slow-loris/torn-frame protocol abuse, SIGKILL'd
clients, and rolling daemon restarts — driven deterministically by the
chaos harness (tools/chaos.py service faults), with the daemon
surviving each fault and answering a subsequent healthy request
bit-identically to a direct in-process solve. Tier-1: the degradation
branch that is not exercised does not exist.

Budget discipline: most tests share ONE in-process daemon
(serve_forever on a thread, real sockets, real reader/worker/watchdog
threads — no subprocess JAX import tax, and sequential faults against
one long-lived daemon is exactly the production claim being tested);
counter assertions are deltas. Tests that need incompatible knobs
(watchdog cadence, abort-on-drop, memory watermark) spin their own
service; the rolling-restart test uses real daemon subprocesses
(registered with the conftest watchdog).
"""

import contextlib
import io
import json
import os
import pathlib
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from dedalus_tpu.service import faults, protocol
from dedalus_tpu.service.client import ServiceClient
from dedalus_tpu.service.server import SolverService
from dedalus_tpu.service.protocol import ServiceError
from dedalus_tpu.tools import chaos as chaos_mod
from dedalus_tpu.tools import resilience as res_mod

REPO = pathlib.Path(__file__).parent.parent

pytestmark = [pytest.mark.service, pytest.mark.chaos]

SIZE = 32
DIFF = {"problem": "diffusion", "params": {"size": SIZE}}
DT = 1e-3
STEPS = 10


def _ics():
    x = np.linspace(0, 2 * np.pi, SIZE, endpoint=False)
    return {"u": ("g", np.sin(3 * x)), "a": ("g", 0.2 * np.cos(x))}


_reference = {}


def direct_reference():
    """The direct in-process solve every healthy post-fault request is
    compared against, computed once per session."""
    if not _reference:
        solver = protocol.resolve_builder(DIFF)()
        SolverService._install_ics(solver, _ics())
        for _ in range(STEPS):
            solver.step(DT)
        _reference["u"] = np.asarray(solver.state[0].coeff_data()).copy()
    return _reference["u"]


@contextlib.contextmanager
def local_service(prewarm=False, **kw):
    """In-process daemon: serve_forever on a thread with real sockets,
    reader threads, executor, and watchdog. `prewarm=True` builds the
    DIFF pool entry BEFORE the watchdog starts, so a small watchdog_sec
    can be tested without the build tripping it."""
    svc = SolverService(port=0, **kw)
    if prewarm:
        # build AND compile before the watchdog arms: the first step of
        # a fresh solver pays the step-program compile, which a tight
        # test watchdog_sec would (correctly!) flag as no-progress
        entry, _, _ = svc.pool.acquire(DIFF)
        entry.solver.step(DT)
        # the next acquire() resets the entry to its just-built state
    thread = threading.Thread(target=svc.serve_forever,
                              kwargs={"ready_stream": io.StringIO()},
                              daemon=True)
    thread.start()
    deadline = time.monotonic() + 30
    while svc.started_ts is None:
        if time.monotonic() > deadline:
            raise RuntimeError("in-process daemon did not come up")
        time.sleep(0.01)
    try:
        yield svc
    finally:
        svc.request_drain("test teardown")
        thread.join(timeout=60)
        assert not thread.is_alive(), "in-process daemon failed to drain"


@pytest.fixture(scope="module")
def daemon(tmp_path_factory):
    """The shared long-lived daemon most fault tests aim at: faults are
    delivered sequentially against ONE process — exactly the survival
    claim under test. Knobs chosen so every sharing test works:
    queue_depth=1 (storm shedding; tests are otherwise sequential),
    idle_timeout=0.5 (slow-loris bound), a tight breaker, a telemetry
    sink, and the default complete-on-client-drop (replay needs the
    orphaned run to finish)."""
    sink = str(tmp_path_factory.mktemp("service_faults") / "served.jsonl")
    with local_service(prewarm=True, queue_depth=1, idle_timeout=0.5,
                       breaker_failures=2, breaker_cooloff=0.5,
                       sink=sink) as svc:
        svc.sink_path = sink
        yield svc


def assert_healthy(svc, tag):
    """The acceptance bar after every fault: the daemon answers a fresh
    healthy request bit-identically to a direct in-process solve."""
    client = ServiceClient(port=svc.port, timeout=120)
    result = client.run(DIFF, ics=_ics(), dt=DT, stop_iteration=STEPS)
    layout, u = result.fields["u"]
    assert layout == "c"
    assert np.array_equal(u, direct_reference()), \
        f"post-{tag} served result differs from the direct solve"
    assert result.result["stopped_by"] == "completed"


def _sink_runs(svc, request_id):
    """step_metrics records in the shared sink for one request id
    (empty before the daemon's first flush creates the file)."""
    try:
        text = pathlib.Path(svc.sink_path).read_text()
    except OSError:
        return []
    records = [json.loads(line) for line in text.splitlines()]
    return [r for r in records if r.get("kind") == "step_metrics"
            and (r.get("serving") or {}).get("request_id") == request_id]


# ----------------------------------------------------------- unit: faults

def test_circuit_breaker_state_machine():
    br = faults.CircuitBreaker(failures=2, cooloff_sec=0.2,
                               max_cooloff_sec=1.0)
    key = "spec-a"
    assert br.admit(key) == (True, 0.0, "closed")
    br.record_failure(key)
    assert br.admit(key)[0]                       # one failure: still closed
    br.record_failure(key)                        # second: opens
    allowed, retry_after, state = br.admit(key)
    assert (allowed, state) == (False, "open") and retry_after > 0
    assert br.fastfails == 1 and br.opens == 1
    time.sleep(0.25)                              # cool-off elapses
    allowed, _, state = br.admit(key)
    assert (allowed, state) == (True, "probe")    # half-open probe
    assert br.admit(key)[0] is False              # only ONE probe at a time
    br.record_failure(key)                        # probe fails: re-open,
    entry = br._keys[key]                         # cool-off doubled
    assert entry["state"] == "open" and entry["cooloff"] == 0.4
    time.sleep(0.45)
    allowed, _, state = br.admit(key)
    assert (allowed, state) == (True, "probe")
    br.record_success(key)                        # probe succeeds: closed
    assert br.state(key) == "closed" and br.closes == 1
    assert br.admit(key) == (True, 0.0, "closed")
    # abandoned probe frees the slot without a verdict
    br2 = faults.CircuitBreaker(failures=1, cooloff_sec=0.05)
    br2.record_failure(key)
    time.sleep(0.1)
    assert br2.admit(key)[2] == "probe"
    br2.abandon_probe(key)
    assert br2.admit(key)[2] == "probe"           # next request probes again
    # the key table is LRU-bounded against unique-spec storms
    br3 = faults.CircuitBreaker(failures=1, max_keys=4)
    for i in range(10):
        br3.record_failure(f"k{i}")
    assert len(br3._keys) == 4


def test_result_cache_lru_and_replay_count():
    cache = faults.ResultCache(size=2)
    cache.put("a", {"r": 1}, {"kind": "result"}, b"pa")
    cache.put("b", None, {"kind": "result"}, b"pb")
    assert cache.get("a")[2] == b"pa"            # touch: a is now MRU
    cache.put("c", None, {"kind": "result"}, b"pc")
    assert cache.get("b") is None                # LRU evicted
    assert cache.get("a") is not None and cache.get("c") is not None
    assert cache.replays == 3
    # fingerprint mismatch is a MISS: an id reused with different
    # spec/params must never serve another request's result
    cache.put("f", None, {"kind": "result"}, b"pf", fingerprint="abc")
    assert cache.get("f", fingerprint="abc") is not None
    assert cache.get("f", fingerprint="xyz") is None
    # byte budget: large payloads evict LRU entries past max_bytes, and
    # one oversized payload is refused rather than flushing the cache
    small = faults.ResultCache(size=16, max_bytes=100)
    small.put("x", None, {}, b"a" * 60)
    small.put("y", None, {}, b"b" * 60)          # 120 > 100: x evicted
    assert small.get("x") is None and small.get("y") is not None
    assert small.payload_bytes == 60
    small.put("huge", None, {}, b"c" * 200)      # oversized: refused
    assert small.get("huge") is None and small.get("y") is not None
    off = faults.ResultCache(size=0)
    off.put("a", None, {}, b"")
    assert off.get("a") is None                  # disabled


def test_retry_policy_jitter_bounds():
    pol = res_mod.RetryPolicy(base_delay=1.0, max_delay=10.0, jitter=0.25)
    for attempt in (1, 2, 3):
        base = min(1.0 * 2 ** (attempt - 1), 10.0)
        for _ in range(20):
            d = pol.delay(attempt)
            assert 0.75 * base <= d <= 1.25 * base
    deterministic = res_mod.RetryPolicy(base_delay=1.0)
    assert deterministic.delay(2) == 2.0         # jitter=0: exact


# --------------------------------------------------- admission / shedding

def test_overload_storm_sheds_with_retry_hint(daemon):
    """Over-capacity storm against queue_depth=1: excess requests get
    structured `overloaded` refusals carrying retry_after_sec, at least
    one request is served, and the daemon survives."""
    shed_before = daemon.shed
    header = {"kind": "run", "spec": DIFF, "dt": DT,
              "stop_iteration": 2500}
    payload = protocol.encode_fields(_ics())
    results = chaos_mod.queue_storm(daemon.port, header, payload=payload,
                                    n=5)
    assert all(r is not None for r in results)
    served = [r for r in results if r["ok"]]
    shed = [r for r in results if r["code"] == "overloaded"]
    assert served, "storm starved every request"
    assert shed, "5 concurrent requests against queue_depth=1 " \
                 "produced no overload shed"
    assert all(r["retry_after_sec"] is not None
               and r["retry_after_sec"] > 0 for r in shed)
    # shed replies are FAST (load shedding, not queueing)
    assert all(r["wall_sec"] < 5.0 for r in shed)
    assert daemon.shed - shed_before == len(shed)
    assert_healthy(daemon, "overload storm")


def test_mem_watermark_evicts_pool():
    """A 1 MiB RSS watermark (always exceeded) trims the warm pool to
    one entry before each build instead of letting entries accumulate
    toward an OOM — and requests still succeed."""
    with local_service(mem_watermark_mb=1, pool_size=4) as svc:
        client = ServiceClient(port=svc.port, timeout=120)
        for size in (SIZE, 16):
            spec = {"problem": "diffusion", "params": {"size": size}}
            x = np.linspace(0, 2 * np.pi, size, endpoint=False)
            result = client.run(spec, ics={"u": ("g", np.sin(x))}, dt=DT,
                                stop_iteration=3)
            assert result.result["stopped_by"] == "completed"
        # the third distinct request finds len(pool)==2 over the
        # watermark and must trim to one before building
        assert_healthy(svc, "memory watermark")
        assert len(svc.pool) <= 2
        assert svc.stats()["faults"]["mem_evictions"] >= 1


# --------------------------------------------------------------- deadlines

def test_deadline_expired_in_queue_fails_structurally():
    """A run whose deadline elapsed while it sat in the queue is refused
    at pop with `deadline-exceeded`, before any solver work."""
    svc = SolverService(port=0)
    a, b = socket.socketpair()
    with a:
        item = {"conn": b, "wfile": b.makefile("wb"),
                "header": {"kind": "run", "spec": DIFF, "dt": DT,
                           "stop_iteration": 5, "deadline_sec": 0.01},
                "payload": None, "t_accept": time.perf_counter() - 1.0,
                "deadline_mono": time.monotonic() - 0.5, "probe": False}
        svc._handle_run(item)
        header, _ = protocol.recv_frame(a.makefile("rb"))
    assert header["kind"] == "error"
    assert header["code"] == "deadline-exceeded"
    assert svc.deadline_exceeded == 1
    assert svc.pool.misses == 0                  # no build was attempted


def test_deadline_mid_run_stops_gracefully_with_checkpoint(daemon,
                                                           tmp_path):
    """A mid-run deadline stops the solve at a step boundary through the
    resilient loop: the client still gets telemetry + a result frame
    (`stopped_by: "deadline-exceeded"`), the final durable checkpoint is
    written and restores to the stop iteration, and the daemon serves
    the next request bit-identically."""
    ckpt = tmp_path / "ckpt"
    before = daemon.deadline_exceeded
    client = ServiceClient(port=daemon.port, timeout=120)
    result = client.run(DIFF, ics=_ics(), dt=1e-4, stop_iteration=10**6,
                        deadline_sec=0.6, checkpoint=str(ckpt))
    assert result.result["stopped_by"] == "deadline-exceeded"
    stopped_at = result.result["iteration"]
    assert 0 < stopped_at < 10**6
    assert result.serving["deadline_sec"] == 0.6
    assert result.record is not None             # telemetry still flushed
    assert daemon.deadline_exceeded - before == 1
    # the deadline-stop checkpoint restores the run exactly
    sets = sorted(ckpt.glob("*.h5"))
    assert sets, "no durable checkpoint written at the deadline stop"
    n_valid, reason = res_mod.validate_checkpoint(sets[-1])
    assert n_valid >= 1, reason
    solver = protocol.resolve_builder(DIFF)()
    event = res_mod.resume_latest(solver, ckpt)
    assert event is not None and solver.iteration == stopped_at
    assert_healthy(daemon, "deadline")


# ---------------------------------------------------------------- watchdog

def test_watchdog_fails_hung_step_and_replaces_executor(tmp_path):
    """A chaos-hung step (no step progress past watchdog_sec) fails the
    request with `watchdog-timeout`, emits a watchdog_postmortem record
    (thread stacks) to the sink, replaces the wedged executor thread,
    and the replacement serves the next request bit-identically."""
    sink = tmp_path / "served.jsonl"
    # watchdog_sec rides above the worst observed first-request overhead
    # (an XLA-cache deserialization on a loaded box measured ~0.8s) so
    # the fire deterministically lands inside the chaos hang, not on a
    # slow-but-legitimate first step
    with local_service(prewarm=True, watchdog_sec=1.2, chaos_enabled=True,
                       sink=str(sink)) as svc:
        gen_before = svc._worker_gen
        client = ServiceClient(port=svc.port, timeout=120)
        with pytest.raises(ServiceError) as excinfo:
            client.run(DIFF, ics=_ics(), dt=DT, stop_iteration=10**6,
                       chaos={"hang_iteration": 5, "hang_sec": 3.0})
        assert excinfo.value.code == "watchdog-timeout"
        stats = svc.stats()["faults"]
        assert stats["watchdog_fires"] == 1
        assert svc._worker_gen == gen_before + 1   # executor replaced
        # the suspect pool entry is quarantined: the wedged (stale)
        # executor may still hold its solver, so the replacement must
        # build fresh rather than share it
        assert len(svc.pool) == 0
        # postmortem record: request context + thread stacks
        records = [json.loads(line)
                   for line in sink.read_text().splitlines()]
        post = [r for r in records
                if r.get("kind") == "watchdog_postmortem"]
        assert len(post) == 1
        assert post[0]["stuck_sec"] >= 1.2
        assert any("sleep" in s or "after_step" in s
                   for s in post[0]["stacks"]), \
            "postmortem stacks do not show the hung thread"
        # the replacement executor answers (and the stale one, once its
        # hang ends, unwinds via AbandonedRun without touching the queue)
        assert_healthy(svc, "watchdog")
        # chaos injection is refused on a daemon without --chaos
        svc.chaos_enabled = False
        with pytest.raises(ServiceError) as refused:
            client.run(DIFF, ics=_ics(), dt=DT, stop_iteration=5,
                       chaos={"hang_iteration": 1, "hang_sec": 1.0})
        assert refused.value.code == "bad-spec"


# --------------------------------------------------------- circuit breaker

def test_circuit_breaker_isolates_poisoned_spec(daemon):
    """A spec whose build fails repeatedly trips its circuit: requests
    fast-fail with `circuit-open` (builder NOT invoked) during the
    cool-off, the half-open probe closes the circuit on success, and
    healthy specs are unaffected throughout. (The shared daemon runs
    breaker_failures=2, breaker_cooloff=0.5.)"""
    calls = {"n": 0}

    def flaky_builder(size=24):
        calls["n"] += 1
        if calls["n"] <= 2:
            raise RuntimeError("chaos: poisoned build")
        return protocol.PROBLEMS["diffusion"](size=size)

    protocol.register_problem("flaky_diffusion", flaky_builder)
    flaky = {"problem": "flaky_diffusion", "params": {"size": 24}}
    opens_before = daemon.breaker.opens
    try:
        client = ServiceClient(port=daemon.port, timeout=120)
        x24 = np.linspace(0, 2 * np.pi, 24, endpoint=False)
        ics24 = {"u": ("g", np.sin(x24))}
        for _ in range(2):                        # two consecutive failures
            with pytest.raises(ServiceError) as excinfo:
                client.run(flaky, ics=ics24, dt=DT, stop_iteration=5)
            assert excinfo.value.code == "build-failed"
        assert calls["n"] == 2
        with pytest.raises(ServiceError) as excinfo:  # circuit OPEN
            client.run(flaky, ics=ics24, dt=DT, stop_iteration=5)
        assert excinfo.value.code == "circuit-open"
        assert excinfo.value.retry_after_sec > 0
        assert calls["n"] == 2, "fast-fail still invoked the builder"
        # a healthy spec is served while the poisoned one cools off
        assert_healthy(daemon, "circuit-open")
        time.sleep(0.6)                           # cool-off elapses
        result = client.run(flaky, ics=ics24, dt=DT,
                            stop_iteration=5)     # half-open probe: builds
        assert calls["n"] == 3
        assert result.result["stopped_by"] == "completed"
        breaker = daemon.stats()["faults"]["breaker"]
        assert breaker["opens"] - opens_before == 1
        assert breaker["fastfails"] >= 1
        assert breaker["closes"] >= 1 and breaker["open"] == []
        result = client.run(flaky, ics=ics24, dt=DT,
                            stop_iteration=5)     # closed again: pool hit
        assert result.ack["pool_verdict"] == "hit"
    finally:
        protocol.PROBLEMS.pop("flaky_diffusion", None)


# -------------------------------------------------------- idempotent retry

def test_idempotent_retry_replays_after_dropped_result(daemon):
    """A client that vanishes before reading its result frame retries
    with the same request id and gets the COMPLETED outcome replayed
    from the result cache — bit-identical to the direct solve — instead
    of a re-run."""
    replays_before = daemon.results.replays
    payload = protocol.encode_fields(_ics())
    header = {"kind": "run", "spec": DIFF, "dt": DT,
              "stop_iteration": STEPS, "id": "retry-me-1"}
    # the client vanishes right after the ack — the daemon completes
    # the run (ON_CLIENT_DROP=complete) and caches the result
    frames = chaos_mod.vanish_client(daemon.port, header, payload=payload,
                                     read_frames=1)
    assert frames and frames[0]["kind"] == "ack"
    # the idempotent retry: same id, fresh connection
    client = ServiceClient(port=daemon.port, timeout=120)
    result = client.run(DIFF, ics=_ics(), dt=DT, stop_iteration=STEPS,
                        request_id="retry-me-1")
    assert result.replayed
    assert result.ack["pool_verdict"] == "replayed"
    layout, u = result.fields["u"]
    assert layout == "c"
    assert np.array_equal(u, direct_reference()), \
        "replayed result differs from the direct solve"
    assert daemon.results.replays > replays_before
    # exactly ONE solve ran for the id: one step_metrics record
    assert len(_sink_runs(daemon, "retry-me-1")) == 1, \
        "the retry re-ran the solve"
    # replaying again is also served from cache
    again = client.run(DIFF, dt=DT, stop_iteration=STEPS,
                       request_id="retry-me-1")
    assert again.replayed
    assert np.array_equal(again.fields["u"][1], u)
    # the SAME id with different run params must re-execute, not serve
    # the stale cached outcome
    mismatch = client.run(DIFF, ics=_ics(), dt=DT,
                          stop_iteration=STEPS + 2,
                          request_id="retry-me-1")
    assert not mismatch.replayed, \
        "an id reused with different params replayed a stale result"
    assert mismatch.result["iteration"] == STEPS + 2


# ------------------------------------------------------------- client drop

def test_client_disconnect_mid_stream_abort(tmp_path):
    """ON_CLIENT_DROP=abort: a dead client socket detected on a progress
    send stops the run at the next step boundary; telemetry for the run
    is flushed exactly once; the daemon stays healthy."""
    sink = tmp_path / "served.jsonl"
    with local_service(on_client_drop="abort", sink=str(sink),
                       prewarm=True) as svc:
        svc.sink_path = str(sink)
        payload = protocol.encode_fields(_ics())
        header = {"kind": "run", "spec": DIFF, "dt": 1e-4,
                  "stop_iteration": 10**6, "progress_every": 1,
                  "id": "dropper"}
        chaos_mod.vanish_client(svc.port, header, payload=payload,
                                read_frames=2)   # ack + one progress
        # poll for the SINK RECORD, not intermediate daemon state: the
        # active-run slot clears before the telemetry flush lands
        deadline = time.monotonic() + 60
        runs = []
        while time.monotonic() < deadline:
            runs = _sink_runs(svc, "dropper")
            if runs and svc.client_drops >= 1:
                break
            time.sleep(0.05)
        assert svc.stats()["faults"]["client_drops"] == 1
        assert len(runs) == 1, \
            f"telemetry flushed {len(runs)} times for the dropped run"
        # the abort stopped the run long before its 10^6 iterations
        assert runs[0]["iterations"] < 10**5
        # an ABORTED partial result must never be cached for replay: a
        # retry of the same id re-executes and completes
        client = ServiceClient(port=svc.port, timeout=120)
        retry = client.run(DIFF, ics=_ics(), dt=DT, stop_iteration=STEPS,
                           request_id="dropper")
        assert not retry.replayed, \
            "a client-drop-aborted partial result was replayed as done"
        assert retry.result["stopped_by"] == "completed"
        assert_healthy(svc, "client drop")


# -------------------------------------------------------- protocol abuse

def test_slow_loris_and_torn_frame_bounded_by_idle_timeout(daemon):
    """A slow-loris connection is expired by the ABSOLUTE request-read
    bound (IDLE_TIMEOUT_SEC — a byte-drip cannot reset it) with a
    structured error; a half-written frame (header promising a payload,
    then disconnect) is a structured truncation — and the daemon answers
    a healthy request bit-identically after both."""
    errors_before = daemon.errors
    t0 = time.monotonic()
    reply = chaos_mod.slow_loris(daemon.port, hold_sec=1.2)
    assert time.monotonic() - t0 < 30
    assert reply is None or reply.get("code") == "bad-frame"
    chaos_mod.half_frame(daemon.port, claim_bytes=4096)
    deadline = time.monotonic() + 10
    while daemon.errors < errors_before + 2 \
            and time.monotonic() < deadline:
        time.sleep(0.05)
    assert daemon.errors >= errors_before + 2
    client = ServiceClient(port=daemon.port, timeout=60)
    assert client.ping()["kind"] == "pong"
    assert_healthy(daemon, "slow-loris/torn-frame")


def test_sigkill_client_mid_run(daemon):
    """A real `submit` subprocess SIGKILLed mid-stream (no cooperative
    close): the daemon detects the dead peer on a later send, completes
    per ON_CLIENT_DROP=complete, and stays healthy."""
    served_before = daemon.requests_served
    proc = chaos_mod.sigkill_client(daemon.port, DIFF, dt=1e-4,
                                    stop_iteration=4000,
                                    after_progress_frames=1)
    assert proc.returncode == -signal.SIGKILL
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if daemon._get_active_run() is None and daemon._queued_runs == 0 \
                and daemon.requests_served > served_before:
            break
        time.sleep(0.1)
    assert daemon.requests_served > served_before, \
        "daemon did not complete the orphaned run"
    assert_healthy(daemon, "SIGKILL'd client")


# -------------------------------------------------------- rolling restart

def _spawn_daemon(workdir, port):
    from conftest import register_daemon
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    stderr_path = os.path.join(workdir, f"daemon_{port}.err")
    stderr = open(stderr_path, "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dedalus_tpu", "serve",
         "--port", str(port)],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=stderr,
        text=True)
    register_daemon(proc, stderr_path)
    return proc, stderr


def test_client_retry_survives_rolling_daemon_restart(tmp_path):
    """The satellite acceptance: kill the daemon and relaunch it on the
    same port mid-session; the client's jittered-backoff reconnect
    (`retries=` / `submit --retry`) makes the restart invisible — the
    second request succeeds against the relaunched daemon."""
    with socket.socket() as probe:              # reserve an ephemeral port
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    workdir = str(tmp_path)
    proc1, stderr1 = _spawn_daemon(workdir, port)
    try:
        banner = json.loads(proc1.stdout.readline())
        assert banner["kind"] == "ready" and banner["port"] == port
        client = ServiceClient(port=port, timeout=120, retries=20,
                               retry_base_delay=0.5)
        r1 = client.run(DIFF, ics=_ics(), dt=DT, stop_iteration=STEPS)
        assert r1.result["stopped_by"] == "completed"
        # rolling restart: SIGKILL (no graceful drain) + relaunch
        proc1.kill()
        proc1.wait(timeout=30)
    finally:
        stderr1.close()
    proc2, stderr2 = _spawn_daemon(workdir, port)
    try:
        # no waiting for the ready banner: the CLIENT's reconnect loop
        # must ride out the boot window (connection refused -> retry)
        r2 = client.run(DIFF, ics=_ics(), dt=DT, stop_iteration=STEPS)
        assert r2.result["stopped_by"] == "completed"
        assert np.array_equal(r2.fields["u"][1], direct_reference()), \
            "post-restart served result differs from the direct solve"
        assert r2.attempts > 1, \
            "restart was supposedly invisible but no retry happened"
    finally:
        try:
            ServiceClient(port=port, timeout=30).shutdown()
            proc2.wait(timeout=60)
        except Exception:
            proc2.kill()
        stderr2.close()
