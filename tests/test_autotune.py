"""
Empirical plan-autotuner tests (tools/autotune.py wired through
core/solvers, libraries/solvecomp, and the assembly cache): config
validation fails loud at build, the winner selection is deterministic
under the accuracy bar (a fast-but-wrong cell can never win), decisions
round-trip the content-addressed cache with corrupt-record quarantine,
warm builds perform ZERO microbench probes (`probe_count()` is the
machine-checked witness), a decision change re-keys solver_key, bare-ops
constructions resolve the same tuned plan via the ops registry, and
`plan_provenance()` names its selector (`plan_source: tuned|config|
default`). The in-build microbench itself is monkeypatched to rigged
rates so the selection logic is exercised deterministically and fast.
"""

import json

import numpy as np
import jax
import pytest

from dedalus_tpu.libraries import solvecomp
from dedalus_tpu.tools import assembly_cache, autotune
from dedalus_tpu.tools.config import config

pytestmark = pytest.mark.autotune

# every config key a test may mutate, saved/restored by the fixture
CFG_KEYS = (("autotune", "MODE"), ("autotune", "TUNE_STEPS"),
            ("autotune", "TUNE_BUDGET_SEC"),
            ("fusion", "SOLVE_COMPOSITION"), ("fusion", "SPIKE_CHUNKS"),
            ("fusion", "FUSED_SOLVE"), ("fusion", "PALLAS"),
            ("precision", "SOLVE_DTYPE"), ("precision", "REFINE_SWEEPS"))


@pytest.fixture
def tune_cfg(tmp_path, monkeypatch):
    """Isolated tuner state: config keys restored, in-process memo/ops
    registry cleared, and the assembly cache redirected to a tmp dir so
    tests never read or warm the user's real cache."""
    monkeypatch.setenv("DEDALUS_TPU_ASSEMBLY_CACHE",
                       str(tmp_path / "assembly"))
    for section in {s for s, _ in CFG_KEYS}:
        if not config.has_section(section):
            config.add_section(section)
    saved = {(s, k): config[s].get(k) for s, k in CFG_KEYS}
    autotune.clear_memo()

    def set_cfg(**kw):
        for (s, k) in CFG_KEYS:
            if k in kw:
                config[s][k] = str(kw[k])

    yield set_cfg
    for (s, k), val in saved.items():
        if val is None:
            config[s].pop(k, None)
        else:
            config[s][k] = val
    autotune.clear_memo()


def build_rb(Nx=16, Nz=32):
    from dedalus_tpu.extras.bench_problems import build_rb_solver
    solver, b = build_rb_solver(Nx, Nz, np.float64, matsolver="banded")
    return solver


GOOD_CELL = {"composition": "ascan", "solve_dtype": "f32",
             "refine_sweeps": 2, "spike_chunks": 0, "pallas": False,
             "fused_transforms": None, "transpose_chunks": None}


# --------------------------------------------------- config validation

def test_resolve_autotune_defaults(tune_cfg):
    plan = autotune.resolve_autotune()
    assert plan.mode == "off"
    assert plan.tune_steps >= 1
    assert plan.budget_sec > 0


@pytest.mark.parametrize("key,value,fragment", [
    ("MODE", "always", "MODE"),
    ("MODE", "ON", "not a recognized value"),
    ("TUNE_STEPS", "fast", "TUNE_STEPS"),
    ("TUNE_STEPS", "0", "must be >= 1"),
    ("TUNE_BUDGET_SEC", "forever", "TUNE_BUDGET_SEC"),
    ("TUNE_BUDGET_SEC", "-3", "must be > 0"),
])
def test_bad_autotune_config_fails_loud(tune_cfg, key, value, fragment):
    tune_cfg(**{key: value})
    with pytest.raises(ValueError, match=fragment):
        autotune.resolve_autotune()


def test_bad_mode_fails_the_build_even_when_tuning_off(tune_cfg):
    # [autotune] is validated at EVERY build (core/solvers resolves it
    # unconditionally), so a typo cannot silently disable tuning
    tune_cfg(MODE="bogus")
    with pytest.raises(ValueError, match="MODE"):
        build_rb()


# ----------------------------------------------------- winner selection

def test_candidate_grid_reference_first_and_pallas_gating():
    cells = autotune.candidate_cells(backend="cpu")
    assert cells[0].get("reference") is True
    assert cells[0]["composition"] == "sequential"
    assert cells[0]["solve_dtype"] == "native"
    (pallas,) = [c for c in cells if c.get("pallas")]
    assert "skipped" in pallas          # cpu cannot lower it natively
    (tpu_pallas,) = [c for c in autotune.candidate_cells(backend="tpu")
                     if c.get("pallas")]
    assert "skipped" not in tpu_pallas  # first-class candidate on tpu


def test_pick_winner_accuracy_bar_beats_speed():
    evidence = [
        {"composition": "sequential", "solve_dtype": "native",
         "solves_per_sec": 100.0, "rel_err": 0.0, "finite": True},
        # fastest cell, but inaccurate: can NEVER win
        {"composition": "ascan", "solve_dtype": "f32",
         "solves_per_sec": 1000.0, "rel_err": 1e-3, "finite": True},
        {"composition": "spike", "solve_dtype": "f32",
         "solves_per_sec": 500.0, "rel_err": 1e-12, "finite": True},
        # fast but non-finite / errored / skipped: all ineligible
        {"composition": "spike", "solve_dtype": "native",
         "solves_per_sec": 900.0, "rel_err": 0.0, "finite": False},
        {"composition": "ascan", "solve_dtype": "native",
         "error": "boom"},
        {"composition": "sequential", "solve_dtype": "f32",
         "skipped": "budget"},
    ]
    winner, margin = autotune.pick_winner(evidence, 1e-10,
                                          "solves_per_sec")
    assert (winner["composition"], winner["solve_dtype"]) == \
        ("spike", "f32")
    assert margin == pytest.approx(5.0)     # 500 over the 100 runner-up


def test_pick_winner_degenerate_cases():
    assert autotune.pick_winner([], 1e-10, "solves_per_sec") == \
        (None, None)
    solo = [{"composition": "sequential", "solve_dtype": "native",
             "solves_per_sec": 10.0, "rel_err": 0.0, "finite": True}]
    winner, margin = autotune.pick_winner(solo, 1e-10, "solves_per_sec")
    assert winner is solo[0] and margin is None


# ------------------------------------------------- decision round-trip

def test_decision_record_round_trip():
    d = autotune.Decision("sig" * 10, GOOD_CELL, evidence=[{"a": 1}],
                          backend="cpu", device_kind="cpu",
                          wall_sec=1.5, margin=2.0)
    back = autotune.Decision.from_record(d.to_record(),
                                         signature="sig" * 10)
    assert back is not None
    assert back.cell == GOOD_CELL
    assert back.margin == 2.0
    assert back.evidence == [{"a": 1}]


@pytest.mark.parametrize("mutate", [
    lambda r: r.update(tuning_version=99),
    lambda r: r.update(signature=None),
    lambda r: r["cell"].update(composition="warp"),
    lambda r: r["cell"].update(solve_dtype="f8"),
    lambda r: r["cell"].update(refine_sweeps=True),    # bool is not int
    lambda r: r["cell"].update(refine_sweeps=-1),
    lambda r: r["cell"].update(spike_chunks="two"),
    lambda r: r["cell"].update(pallas="yes"),
    lambda r: r["cell"].update(transpose_chunks=0),
    lambda r: r.update(cells="not-a-list"),
])
def test_decision_rejects_drifted_records(mutate):
    record = autotune.Decision("s" * 40, GOOD_CELL).to_record()
    mutate(record)
    assert autotune.Decision.from_record(record, "s" * 40) is None


def test_decision_rejects_signature_mismatch():
    record = autotune.Decision("s" * 40, GOOD_CELL).to_record()
    assert autotune.Decision.from_record(record, "x" * 40) is None


def test_corrupt_cached_record_is_quarantined(tune_cfg, tmp_path):
    cache = assembly_cache.AssemblyCache(str(tmp_path / "quarantine"))
    sig = "f" * 40
    # structurally valid JSON, semantically drifted (bad version):
    # load_decision must report a miss AND discard the entry
    assert assembly_cache.store_tuning(cache, sig, {"tuning_version": 99})
    assert autotune.load_decision(cache, sig) is None
    assert assembly_cache.load_tuning(cache, sig) is None   # quarantined
    # a valid record survives the round trip
    good = autotune.Decision(sig, GOOD_CELL, backend="cpu")
    assert autotune.store_decision(cache, good)
    loaded = autotune.load_decision(cache, sig)
    assert loaded is not None and loaded.cell == GOOD_CELL


# ------------------------------------- in-build tuning (rigged probes)

RIGGED_RATES = {("sequential", "native"): 100.0,
                ("sequential", "f32"): 50.0,
                ("ascan", "native"): 40.0,
                ("ascan", "f32"): 1000.0,       # fastest but inaccurate
                ("spike", "native"): 30.0,
                ("spike", "f32"): 500.0}        # fastest ACCURATE cell
RIGGED_ERRS = {("ascan", "f32"): 1e-3}


def rigged_probe(structure, stores, dtype, cell, tune_steps, ref_x):
    autotune._count_probe()
    key = (cell["composition"], cell["solve_dtype"])
    return {"solves_per_sec": RIGGED_RATES[key],
            "rel_err": 0.0 if ref_x is None else RIGGED_ERRS.get(key,
                                                                 1e-13),
            "finite": True,
            "refine_sweeps": 2 if cell["solve_dtype"] == "f32" else None,
            "x": np.zeros(4)}


@pytest.mark.skipif(jax.default_backend() != "cpu",
                    reason="rigged grid assumes the cpu candidate set")
def test_cold_tune_warm_hit_and_quarantine_retune(tune_cfg, monkeypatch):
    """The consult life cycle end to end: cold build measures every
    candidate once and the accurate winner (not the fast-but-wrong one)
    lands in the plan; a warm build after a memo wipe loads the decision
    from disk with ZERO probes; corrupting the cached record quarantines
    it and triggers exactly one fresh tune."""
    monkeypatch.setattr(autotune, "_probe_ops_cell", rigged_probe)
    tune_cfg(MODE="cached", TUNE_STEPS="2", TUNE_BUDGET_SEC="600")
    p0 = autotune.probe_count()
    solver = build_rb()
    assert autotune.probe_count() - p0 == 6     # pallas skipped on cpu
    assert solver._plan_source == "tuned"
    plan = solver._solve_plan
    assert (plan.composition, plan.dtype, plan.sweeps) == \
        ("spike", "f32", 2)
    prov = solver.plan_provenance()
    assert prov["plan_source"] == "tuned"
    tuning = prov["tuning"]
    assert tuning["cache"] == "stored"
    assert tuning["evidence_kind"] == "ops_probe"
    assert tuning["margin"] == pytest.approx(5.0)
    assert len(tuning["cells"]) == 7            # 6 measured + 1 skipped
    sig = autotune.solver_signature(solver)
    key_tuned = assembly_cache.solver_key(solver, list(solver.matrices))

    # warm build: decision from DISK (memo wiped), zero probes
    autotune.clear_memo()
    p1 = autotune.probe_count()
    warm = build_rb()
    assert autotune.probe_count() == p1         # the tentpole invariant
    assert warm._plan_source == "tuned"
    assert warm._tuning["cache"] == "hit"
    assert warm._solve_plan.composition == "spike"
    # identical decision -> identical content key as the tuning build
    assert assembly_cache.solver_key(warm, list(warm.matrices)) == \
        key_tuned

    # corrupt the persisted record: next cold build quarantines + re-tunes
    cache = assembly_cache.resolve()
    assert assembly_cache.store_tuning(cache, sig, {"tuning_version": 99})
    autotune.clear_memo()
    p2 = autotune.probe_count()
    retuned = build_rb()
    assert autotune.probe_count() - p2 == 6     # fresh tune, not a crash
    assert retuned._plan_source == "tuned"
    assert retuned._tuning["cache"] == "stored"


@pytest.mark.skipif(jax.default_backend() != "cpu",
                    reason="rigged grid assumes the cpu candidate set")
def test_plan_source_and_rekey(tune_cfg, monkeypatch):
    """plan_source names the selector: `default` untuned, `tuned` with a
    (seeded) decision — which re-keys solver_key/pool_key — and `config`
    when any explicit knob pins the plan (explicit config always wins:
    zero probes even under MODE=force)."""
    # default: tuner off, heuristic plan
    solver = build_rb()
    assert solver._plan_source == "default"
    prov = solver.plan_provenance()
    assert prov["plan_source"] == "default"
    assert "tuning" not in prov
    key_default = assembly_cache.solver_key(solver, list(solver.matrices))
    pool_default = assembly_cache.pool_key(solver)
    sig = autotune.solver_signature(solver)

    # tuned: a seeded ascan/f32 decision flips the whole plan stack and
    # therefore the assembly/pool content keys, with zero probes
    autotune.seed_decision(sig, GOOD_CELL, evidence_kind="seeded")
    tune_cfg(MODE="cached")
    p0 = autotune.probe_count()
    tuned = build_rb()
    assert autotune.probe_count() == p0
    assert tuned._plan_source == "tuned"
    assert (tuned._solve_plan.composition, tuned._solve_plan.dtype,
            tuned._solve_plan.sweeps) == ("ascan", "f32", 2)
    assert assembly_cache.solver_key(tuned, list(tuned.matrices)) != \
        key_default
    assert assembly_cache.pool_key(tuned) != pool_default

    # config: one pinned knob beats the seeded decision, probes stay 0
    monkeypatch.setattr(autotune, "_probe_ops_cell", rigged_probe)
    tune_cfg(MODE="force", SOLVE_COMPOSITION="sequential")
    pinned = build_rb()
    assert autotune.probe_count() == p0
    assert pinned._plan_source == "config"
    assert pinned._solve_plan.composition == "sequential"
    assert pinned.plan_provenance()["plan_source"] == "config"


# ------------------------------------------------- bare-ops consistency

def test_bare_ops_resolve_the_registered_decision(tune_cfg):
    """libraries/pencilops.py fallback paths (BandedOps/DenseOps built
    with no solver threading a plan) must resolve the SAME plan a tuned
    solver build registered for that system size."""
    decision = autotune.Decision("d" * 40, GOOD_CELL)
    autotune._register_ops(decision, [48])
    assert autotune.ops_decision("banded", 48) is decision
    assert autotune.ops_decision("dense", 48) is decision
    assert autotune.ops_decision("banded", 49) is None
    assert autotune.ops_decision("banded", None) is None
    plan = solvecomp.resolve_solve_plan_for_ops("banded", 48)
    assert (plan.composition, plan.dtype, plan.sweeps) == \
        ("ascan", "f32", 2)
    # unregistered size: plain heuristics
    plan = solvecomp.resolve_solve_plan_for_ops("banded", 49)
    assert plan.composition == "sequential"
    # pinned config wins over the registry too
    tune_cfg(SOLVE_COMPOSITION="spike", SPIKE_CHUNKS="4")
    plan = solvecomp.resolve_solve_plan_for_ops("banded", 48)
    assert (plan.composition, plan.spike_chunks) == ("spike", 4)


def test_apply_decision_layers_cell_over_plan():
    base = solvecomp.SolvePlan(composition="sequential", spike_chunks=0,
                               dtype="native", sweeps=None, tol=0.0,
                               mmt_dtype="native")
    plan = solvecomp.apply_decision(base, GOOD_CELL)
    assert (plan.composition, plan.dtype, plan.sweeps) == \
        ("ascan", "f32", 2)
    assert plan.tol == base.tol and plan.mmt_dtype == base.mmt_dtype
    # sweeps fall back to the dtype's auto schedule when the cell is
    # silent, and f64 normalizes to native
    cell = {"composition": "spike", "solve_dtype": "f32",
            "refine_sweeps": None}
    assert solvecomp.apply_decision(base, cell).sweeps == \
        solvecomp._AUTO_SWEEPS["f32"]
    assert solvecomp.apply_decision(
        base, {"solve_dtype": "f64"}).dtype == "native"


def test_solve_knobs_pinned(tune_cfg):
    assert not solvecomp.solve_knobs_pinned()
    tune_cfg(REFINE_SWEEPS="3")
    assert solvecomp.solve_knobs_pinned()
    tune_cfg(REFINE_SWEEPS="auto")
    assert not solvecomp.solve_knobs_pinned()


# --------------------------------------------------------- the tune CLI

def test_run_tune_rejects_bad_inputs(tune_cfg):
    lines = []
    assert autotune.run_tune(problem="nosuch", out=lines.append) == 2
    assert any("unknown tune problem" in ln for ln in lines)
    tune_cfg(MODE="bogus")
    lines.clear()
    assert autotune.run_tune(out=lines.append) == 2
    assert any("MODE" in ln for ln in lines)


def rigged_offline(build, plan=None, label="", n_steps=12, block=20,
                   blocks=5):
    evidence = [
        {"composition": "sequential", "solve_dtype": "native",
         "pallas": False, "steps_per_sec": 8.0, "rel_err": 0.0,
         "finite": True, "refine_sweeps": None, "reference": True},
        {"composition": "sequential", "solve_dtype": "f32",
         "pallas": False, "steps_per_sec": 9.5, "rel_err": 1e-13,
         "finite": True, "refine_sweeps": 2},
        {"composition": "ascan", "solve_dtype": "native", "pallas": False,
         "skipped": "budget"},
    ]
    cell = {"composition": "sequential", "solve_dtype": "f32",
            "refine_sweeps": 2, "spike_chunks": 0, "pallas": False,
            "fused_transforms": None, "transpose_chunks": None}
    decision = autotune.Decision("a" * 40, cell, evidence=evidence,
                                 backend="cpu", device_kind="cpu",
                                 evidence_kind="step_sweep",
                                 wall_sec=4.2, margin=1.188)
    return decision, evidence


def test_run_tune_reports_and_persists(tune_cfg, monkeypatch):
    monkeypatch.setattr(autotune, "tune_offline", rigged_offline)
    lines = []
    rc = autotune.run_tune(problem="rb64x32", quick=True, as_json=True,
                           record=False, out=lines.append)
    assert rc == 0
    row = json.loads("\n".join(lines))
    assert row["kind"] == "autotune"
    assert row["chosen_label"] == "sequential/f32+2sw"
    assert row["evidence_kind"] == "step_sweep"
    assert row["cache"] == "stored"
    assert len(row["cells"]) == 3
    # the decision reached the (tmp) persistent cache AND the memo
    cache = assembly_cache.resolve()
    assert autotune.load_decision(cache, "a" * 40) is not None
    assert autotune._MEMO["a" * 40].cell["solve_dtype"] == "f32"
    # human rendering names the winner and the per-cell evidence
    lines.clear()
    rc = autotune.run_tune(problem="rb64x32", quick=True, record=False,
                           out=lines.append)
    assert rc == 0
    assert "chosen sequential/f32+2sw" in lines[0]
    assert any("(reference)" in ln for ln in lines)
    assert any("skipped" in ln for ln in lines)


def test_run_tune_no_accurate_winner(tune_cfg, monkeypatch):
    def no_winner(build, **kw):
        return None, [{"composition": "ascan", "solve_dtype": "f32",
                       "pallas": False, "error": "Exception('nan')"}]
    monkeypatch.setattr(autotune, "tune_offline", no_winner)
    lines = []
    assert autotune.run_tune(problem="rb64x32", quick=True, record=False,
                             out=lines.append) == 1
    assert any("no accurate candidate" in ln for ln in lines)
