"""
Cylinder (DirectProduct: Fourier z x disk/annulus) calculus tests against
closed-form grid expressions (reference test pattern:
/root/reference/dedalus/tests/test_cylinder_calculus.py).
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3

length = 1.88
radius_disk = 1.5
radii_annulus = (0.5, 3.0)


def build_cylinder(Nz, Nphi, Nr, dealias, dtype, shape="disk"):
    cz = d3.Coordinate("z")
    cp = d3.PolarCoordinates("phi", "r")
    c = d3.DirectProduct(cz, cp)
    dist = d3.Distributor(c, dtype=dtype)
    if np.dtype(dtype).kind == "c":
        bz = d3.ComplexFourier(cz, size=Nz, bounds=(0, length), dealias=dealias)
    else:
        bz = d3.RealFourier(cz, size=Nz, bounds=(0, length), dealias=dealias)
    if shape == "disk":
        bp = d3.DiskBasis(cp, (Nphi, Nr), dtype=dtype, radius=radius_disk,
                          dealias=dealias)
    else:
        bp = d3.AnnulusBasis(cp, (Nphi, Nr), dtype=dtype,
                             radii=radii_annulus, dealias=dealias)
    z, phi, r = dist.local_grids(bz, bp)
    x, y = r * np.cos(phi), r * np.sin(phi)
    return c, dist, (bz, bp), z, phi, r, x, y


kz = 4 * np.pi / length
params = pytest.mark.parametrize("shape,dealias,dtype", [
    ("disk", 1, np.float64),
    ("disk", 3 / 2, np.float64),
    ("disk", 1, np.complex128),
    ("annulus", 1, np.float64),
    ("annulus", 3 / 2, np.complex128),
])


def polar_comps(fx, fy, phi):
    """Cartesian (fx, fy) -> cylinder (phi, r) components."""
    return (-fx * np.sin(phi) + fy * np.cos(phi),
            fx * np.cos(phi) + fy * np.sin(phi))


def assert_comps(data, expected, atol=1e-8):
    for i, e in enumerate(expected):
        got = np.asarray(data[i])
        err = np.abs(got - np.broadcast_to(e, got.shape)).max()
        assert err < atol, f"component {i}: max err {err}"


@params
def test_gradient_scalar(shape, dealias, dtype):
    c, dist, b, z, phi, r, x, y = build_cylinder(8, 16, 8, dealias, dtype,
                                                 shape)
    f = dist.Field(bases=b, dtype=dtype)
    f["g"] = 3 * x ** 2 + 2 * y + np.sin(kz * z) * x
    u = d3.grad(f).evaluate()
    u.change_scales(1)
    fx = 6 * x + np.sin(kz * z)
    fy = 2 + 0 * x + 0 * z
    fz = kz * np.cos(kz * z) * x
    gphi, gr = polar_comps(fx, fy, phi)
    assert_comps(u["g"], (fz + 0 * phi, gphi + 0 * z, gr + 0 * z))


@params
def test_gradient_vector(shape, dealias, dtype):
    """grad(grad(f)): rank-2 tensor over the product."""
    c, dist, b, z, phi, r, x, y = build_cylinder(8, 16, 10, dealias, dtype,
                                                 shape)
    f = dist.Field(bases=b, dtype=dtype)
    f["g"] = 3 * x ** 4 + 2 * y ** 3 + np.sin(kz * z) * x * y
    T = d3.grad(d3.grad(f)).evaluate()
    T.change_scales(1)
    s = np.sin(kz * z)
    cz_ = np.cos(kz * z)
    # cartesian second derivatives
    fxx = 36 * x ** 2
    fyy = 12 * y + 0 * x
    fxy = s + 0 * x
    fzz = -kz ** 2 * s * x * y
    fzx = kz * cz_ * y
    fzy = kz * cz_ * x
    # rotate to cylinder components (z, phi, r) for both indices
    def rot(vx, vy):
        return polar_comps(vx, vy, phi)
    # first index z row: (fzz, (fzx, fzy)->polar)
    zphi, zr = rot(fzx, fzy)
    # hessian in (phi, r) x (phi, r): H_polar = R H R^T with R the
    # cartesian->polar rotation; rotate columns, then rows
    phix, rx = rot(fxx, fxy)
    phiy, ry = rot(fxy, fyy)
    pp, pr = rot(phix, phiy)
    rp, rr = rot(rx, ry)
    expected = np.empty((3, 3) + np.broadcast_shapes(x.shape, z.shape),
                        dtype=np.result_type(dtype, float))
    expected[0, 0] = fzz + 0 * x
    expected[0, 1] = zphi + 0 * z
    expected[0, 2] = zr + 0 * z
    expected[1, 0] = zphi + 0 * z
    expected[1, 1] = pp + 0 * z
    expected[1, 2] = pr + 0 * z
    expected[2, 0] = zr + 0 * z
    expected[2, 1] = rp + 0 * z
    expected[2, 2] = rr + 0 * z
    got = np.asarray(T["g"])
    err = np.abs(got - expected).max()
    assert err < 1e-7, f"max err {err}"


@params
def test_divergence_vector(shape, dealias, dtype):
    c, dist, b, z, phi, r, x, y = build_cylinder(8, 16, 8, dealias, dtype,
                                                 shape)
    f = dist.Field(bases=b, dtype=dtype)
    f["g"] = 3 * x ** 2 + 2 * y ** 2 + np.sin(kz * z) * x
    h = d3.div(d3.grad(f)).evaluate()
    h.change_scales(1)
    expected = 10 - kz ** 2 * np.sin(kz * z) * x + 0 * y
    got = np.asarray(h["g"])
    err = np.abs(got - np.broadcast_to(expected, got.shape)).max()
    assert err < 1e-8, f"max err {err}"


@params
def test_divergence_tensor(shape, dealias, dtype):
    """div(grad(grad(f))) = grad(lap(f)) componentwise."""
    c, dist, b, z, phi, r, x, y = build_cylinder(8, 16, 10, dealias, dtype,
                                                 shape)
    f = dist.Field(bases=b, dtype=dtype)
    f["g"] = x ** 4 + y ** 4 + np.sin(kz * z) * x * y
    v = d3.div(d3.grad(d3.grad(f))).evaluate()
    v.change_scales(1)
    # lap f = 12x^2 + 12y^2 - kz^2 sin x y
    s = np.sin(kz * z)
    gx = 24 * x - kz ** 2 * s * y
    gy = 24 * y - kz ** 2 * s * x
    gz = -kz ** 3 * np.cos(kz * z) * x * y
    gphi, gr = polar_comps(gx, gy, phi)
    assert_comps(v["g"], (gz + 0 * phi, gphi + 0 * z, gr + 0 * z), 1e-7)


@params
def test_curl_vector(shape, dealias, dtype):
    c, dist, b, z, phi, r, x, y = build_cylinder(8, 16, 8, dealias, dtype,
                                                 shape)
    v = dist.VectorField(c, bases=b, dtype=dtype)
    # v = (4x^3 + 3y^2) e_y + x y sin(kz z) e_z
    vy = 4 * x ** 3 + 3 * y ** 2 + 0 * z
    vz = x * y * np.sin(kz * z)
    vphi, vr = polar_comps(0 * vy, vy, phi)
    vg = np.empty((3,) + np.broadcast_shapes(x.shape, z.shape),
                  dtype=np.result_type(dtype, float))
    vg[0] = vz
    vg[1] = vphi + 0 * z
    vg[2] = vr + 0 * z
    v["g"] = vg
    u = d3.curl(v).evaluate()
    u.change_scales(1)
    s = np.sin(kz * z)
    # curl = (d_y v_z - d_z v_y, d_z v_x - d_x v_z, d_x v_y - d_y v_x)
    ux = x * s - 0 * y
    uy = -y * s + 0 * x
    uz = 12 * x ** 2 + 0 * y + 0 * z
    uphi, ur = polar_comps(ux, uy, phi)
    assert_comps(u["g"], (uz + 0 * phi + 0 * z, uphi, ur), 1e-8)


@params
def test_laplacian_scalar(shape, dealias, dtype):
    c, dist, b, z, phi, r, x, y = build_cylinder(8, 16, 8, dealias, dtype,
                                                 shape)
    f = dist.Field(bases=b, dtype=dtype)
    f["g"] = x ** 4 + 2 * y ** 4 + np.sin(kz * z) * x
    h = d3.lap(f).evaluate()
    h.change_scales(1)
    expected = 12 * x ** 2 + 24 * y ** 2 - kz ** 2 * np.sin(kz * z) * x
    got = np.asarray(h["g"])
    err = np.abs(got - np.broadcast_to(expected, got.shape)).max()
    assert err < 1e-7, f"max err {err}"


@pytest.mark.parametrize("shape", ["disk", "annulus"])
def test_ncc_scalar_lhs_vs_rhs(shape):
    """LHS NCC matrices on the cylinder match explicit grid multiplication
    (reference: tests/test_cylinder_ncc.py)."""
    # annulus needs radial resolution for the 1/r profile (geometric
    # convergence: ~1e-5 at Nr=12, ~3e-10 at Nr=24)
    Nr = 24 if shape == "annulus" else 12
    c, dist, b, z, phi, r, x, y = build_cylinder(8, 8, Nr, 1, np.float64,
                                                 shape)
    ncc = dist.Field(name="ncc", bases=b[1])
    ncc["g"] = r ** 2 + (1 / r if shape == "annulus" else 0)
    u = dist.Field(name="u", bases=b)
    v = dist.Field(name="v", bases=b)
    problem = d3.LBVP([u], namespace=locals())
    problem.add_equation("ncc*u = ncc*v")
    v["g"] = (x * y + 3 * y + r) * (1 + 0.5 * np.sin(kz * z))
    problem.build_solver().solve()
    u.change_scales(1)
    v.change_scales(1)
    assert np.abs(np.asarray(u["g"]) - np.asarray(v["g"])).max() < 1e-9


def test_ncc_vector_operand_lhs_vs_rhs():
    """Scalar radial NCC times a product-vector operand."""
    c, dist, b, z, phi, r, x, y = build_cylinder(8, 8, 12, 1, np.float64)
    ncc = dist.Field(name="ncc", bases=b[1])
    ncc["g"] = 1 + r ** 2
    u = dist.VectorField(c, name="u", bases=b)
    v = dist.VectorField(c, name="v", bases=b)
    problem = d3.LBVP([u], namespace=locals())
    problem.add_equation("ncc*u = ncc*v")
    vg = np.zeros((3,) + np.broadcast_shapes(x.shape, z.shape))
    vg[0] = x * y * np.sin(kz * z)
    vg[1], vg[2] = polar_comps(3 * x ** 2 + y, x + 2 * y, phi)
    vg[1] = vg[1] + 0 * z
    vg[2] = vg[2] + 0 * z
    v["g"] = vg
    problem.build_solver().solve()
    u.change_scales(1)
    v.change_scales(1)
    assert np.abs(np.asarray(u["g"]) - np.asarray(v["g"])).max() < 1e-9


def test_poisson_lbvp():
    """lap(u) = f in the periodic cylinder with u(r=R)=0; manufactured
    u = (R^2 - r^2) x sin(kz z) type solution via RHS evaluation."""
    c, dist, b, z, phi, r, x, y = build_cylinder(8, 8, 16, 1, np.float64)
    bz, bp = b
    R = radius_disk
    u = dist.Field(name="u", bases=b)
    tau = dist.Field(name="tau", bases=(bz, bp.edge))
    f = dist.Field(name="f", bases=b)
    # u_exact = (R^2 - r^2) * x * sin(kz z) (vanishes at r=R; x = r cos phi)
    # lap u_exact: compute in cartesian: u = (R^2 - x^2 - y^2) x sin
    # d2x: -6x sin; d2y: -2x sin; d2z: -kz^2 (R^2-r^2) x sin
    s = np.sin(kz * z)
    f["g"] = (-6 * x - 2 * x - kz ** 2 * (R ** 2 - r ** 2) * x) * s
    lift = lambda A: d3.Lift(A, bp.derivative_basis(2), -1)
    problem = d3.LBVP([u, tau], namespace=locals())
    problem.add_equation("lap(u) + lift(tau) = f")
    problem.add_equation("u(r=1.5) = 0")
    problem.build_solver().solve()
    u.change_scales(1)
    expected = (R ** 2 - r ** 2) * x * s
    err = np.abs(np.asarray(u["g"]) - expected).max()
    assert err < 1e-10, f"max err {err}"


def test_heat_ivp_decay():
    """Periodic-cylinder heat equation: the (kz, m=0) Bessel mode decays at
    rate kz^2 + j01^2/R^2 (j01 = first zero of J0)."""
    from scipy.special import jn_zeros, j0
    c, dist, b, z, phi, r, x, y = build_cylinder(8, 8, 32, 1, np.float64)
    bz, bp = b
    R = radius_disk
    u = dist.Field(name="u", bases=b)
    tau = dist.Field(name="tau", bases=(bz, bp.edge))
    lift = lambda A: d3.Lift(A, bp, -1)
    problem = d3.IVP([u, tau], namespace=locals())
    problem.add_equation("dt(u) - lap(u) + lift(tau) = 0")
    problem.add_equation("u(r=1.5) = 0")
    solver = problem.build_solver(d3.RK443)
    j01 = jn_zeros(0, 1)[0]
    u["g"] = j0(j01 * r / R) * np.cos(kz * z) + 0 * phi
    u0 = np.asarray(u["g"]).copy()
    dt, n = 2e-4, 50
    for _ in range(n):
        solver.step(dt)
    rate = kz ** 2 + (j01 / R) ** 2
    expected = u0 * np.exp(-rate * n * dt)
    err = np.abs(np.asarray(u["g"]) - expected).max()
    assert err < 1e-6 * np.abs(u0).max(), f"max err {err}"


def test_volume_integral_and_interpolation():
    """Volume integral over the product (Fourier x disk measure r dr dphi)
    and interpolation along the straight axis."""
    R = radius_disk
    c, dist, b, z, phi, r, x, y = build_cylinder(8, 8, 16, 1, np.float64)
    f = dist.Field(name="f", bases=b)
    f["g"] = (1 + np.cos(2 * np.pi * z / length)) * (R ** 2 - r ** 2)
    exact = length * np.pi * R ** 4 / 2
    got = float(np.asarray(d3.Integrate(f, c).evaluate()["g"]).ravel()[0])
    assert np.isclose(got, exact)
    nested = float(np.asarray(
        d3.Integrate(d3.Integrate(f, c.coordsystems[0]),
                     c.coordsystems[1]).evaluate()["g"]).ravel()[0])
    assert np.isclose(nested, exact)
    g = d3.Interpolate(f, c["z"], 0.5).evaluate()
    expect = (1 + np.cos(2 * np.pi * 0.5 / length)) * (R ** 2 - r ** 2)
    assert np.abs(np.asarray(g["g"])[0] - expect).max() < 1e-12


def test_pipe_flow_ivp_structure():
    """Incompressible flow in a periodic pipe: vector IVP with pressure
    gauge, divergence constraint, and no-slip walls — the full cylinder
    fluid stack (reference geometry: tests/test_cylinder_*.py; no
    reference pipe IVP exists, the disk EVP covers the physics)."""
    R = radius_disk
    c, dist, b, z, phi, r, x, y = build_cylinder(8, 8, 12, 3 / 2, np.float64)
    bz, bp = b
    cp = c.coordsystems[1]
    u = dist.VectorField(c, name="u", bases=(bz, bp))
    p = dist.Field(name="p", bases=(bz, bp))
    tau_u = dist.VectorField(c, name="tau_u", bases=(bz, bp.edge))
    tau_p = dist.Field(name="tau_p")
    Fz = dist.VectorField(c, name="Fz")
    Fz["g"] = np.array([1.0, 0, 0]).reshape((3, 1, 1, 1))
    nu = 1.0
    lift = lambda A: d3.Lift(A, bp, -1)
    problem = d3.IVP([u, p, tau_u, tau_p], namespace=locals())
    problem.add_equation(
        "dt(u) - nu*lap(u) + grad(p) + lift(tau_u) = - u@grad(u) + Fz")
    problem.add_equation("div(u) + tau_p = 0")
    problem.add_equation(f"u(r={R}) = 0")
    problem.add_equation("integ(p) = 0")
    solver = problem.build_solver(d3.RK222)
    for _ in range(20):
        solver.step(2e-3)
    X = np.asarray(solver.X)
    assert np.isfinite(X).all()
    # walls: no slip
    wall = np.asarray(d3.Interpolate(u, cp["r"], R).evaluate()["g"])
    assert np.abs(wall).max() < 1e-10
    # incompressibility (constraint residual includes tau_p)
    resid = np.asarray((d3.div(u) + tau_p).evaluate()["g"])
    assert np.abs(resid).max() < 1e-10
    # gauge
    pint = float(np.asarray(d3.Integrate(p, c).evaluate()["g"]).ravel()[0])
    assert abs(pint) < 1e-10
    # flow accelerates along +z under the axial force
    uz_mean = float(np.asarray(
        d3.Integrate(u @ Fz, c).evaluate()["g"]).ravel()[0])
    assert uz_mean > 0


@params
def test_laplacian_vector(shape, dealias, dtype):
    """lap(grad f) = grad(lap f)."""
    c, dist, b, z, phi, r, x, y = build_cylinder(8, 16, 10, dealias, dtype,
                                                 shape)
    f = dist.Field(bases=b, dtype=dtype)
    f["g"] = x ** 4 + y ** 4 + np.sin(kz * z) * x * y
    u = d3.lap(d3.grad(f)).evaluate()
    u.change_scales(1)
    s = np.sin(kz * z)
    gx = 24 * x - kz ** 2 * s * y
    gy = 24 * y - kz ** 2 * s * x
    gz = -kz ** 3 * np.cos(kz * z) * x * y
    gphi, gr = polar_comps(gx, gy, phi)
    assert_comps(u["g"], (gz + 0 * phi, gphi + 0 * z, gr + 0 * z), 1e-7)
