"""
Sphere (S2/SWSH) basis tests: transforms, calculus vs closed forms, EVP
eigenvalues, and a shallow-water IVP with mass conservation
(reference patterns: dedalus/tests/test_transforms.py:358
test_sphere_roundtrip_noise, tests/test_sphere_calculus.py,
examples/ivp_sphere_shallow_water/shallow_water.py).
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3


def make_sphere(dtype, shape=(16, 8), radius=1.0, dealias=(1, 1)):
    cs = d3.S2Coordinates("phi", "theta")
    dist = d3.Distributor(cs, dtype=dtype)
    basis = d3.SphereBasis(cs, shape=shape, dtype=dtype, radius=radius,
                           dealias=dealias)
    return cs, dist, basis


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_sphere_scalar_roundtrip(dtype):
    cs, dist, basis = make_sphere(dtype, radius=2.0)
    phi, theta = dist.local_grids(basis)
    x = np.sin(theta) * np.cos(phi)
    y = np.sin(theta) * np.sin(phi)
    z = np.cos(theta) + 0 * phi
    f = dist.Field(name="f", bases=basis)
    f["g"] = x ** 2 + 2 * x * y - y * z + 3
    g0 = np.array(f["g"])
    f["c"] = f["c"]
    assert np.abs(f["g"] - g0).max() < 1e-12


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_sphere_vector_roundtrip(dtype):
    cs, dist, basis = make_sphere(dtype)
    phi, theta = dist.local_grids(basis)
    u = dist.VectorField(cs, name="u", bases=basis)
    # grad of smooth scalars -> smooth (spin-regular) vector fields
    # r*grad(sin(theta)cos(phi) - cos(theta)): components (u_phi, u_theta)
    u["g"] = np.array([-np.sin(phi) + 0 * theta,
                       np.cos(theta) * np.cos(phi) + np.sin(theta)])
    g0 = np.array(u["g"])
    u["c"] = u["c"]
    assert np.abs(u["g"] - g0).max() < 1e-12


def test_sphere_tensor_roundtrip():
    cs, dist, basis = make_sphere(np.float64)
    phi, theta = dist.local_grids(basis)
    f = dist.Field(name="f", bases=basis)
    f["g"] = np.cos(theta) * np.sin(theta) * np.cos(phi)
    T = d3.grad(d3.grad(f)).evaluate()
    g0 = np.array(T["g"])
    T["c"] = T["c"]
    assert np.abs(T["g"] - g0).max() < 1e-11


def test_sphere_coeff_roundtrip_random():
    """Valid random coefficients survive a grid roundtrip."""
    cs, dist, basis = make_sphere(np.float64, shape=(16, 8))
    f = dist.Field(name="f", bases=basis)
    rng = np.random.default_rng(0)
    c = rng.standard_normal(f["c"].shape)
    # zero invalid slots: l < m, and the m=0 minus-sin slot
    for g in range(8):
        c[2 * g:2 * g + 2, :g] = 0
    c[1, :] = 0
    f["c"] = c
    f["g"] = f["g"]
    assert np.abs(f["c"] - c).max() < 1e-11


def test_sphere_gradient():
    """grad(cos theta) = -(sin theta)/r e_theta."""
    cs, dist, basis = make_sphere(np.float64, radius=2.0)
    phi, theta = dist.local_grids(basis)
    f = dist.Field(name="f", bases=basis)
    f["g"] = np.cos(theta) + 0 * phi
    g = d3.grad(f).evaluate()
    exact = np.array([0 * phi * theta, -np.sin(theta) / 2.0 + 0 * phi])
    assert np.abs(g["g"] - exact).max() < 1e-13


def test_sphere_laplacian_eigenfunctions():
    """lap(Y_lm) = -l(l+1)/r^2 Y_lm for several (l, m)."""
    cs, dist, basis = make_sphere(np.float64, shape=(24, 12), radius=1.5)
    phi, theta = dist.local_grids(basis)
    # Y_3^2 ~ sin^2(theta) cos(theta) cos(2 phi)
    f = dist.Field(name="f", bases=basis)
    f["g"] = np.sin(theta) ** 2 * np.cos(theta) * np.cos(2 * phi)
    l = d3.lap(f).evaluate()
    assert np.abs(l["g"] - (-12 / 1.5 ** 2) * np.array(f["g"])).max() < 1e-12
    # div(grad(f)) == lap(f)
    dg = d3.div(d3.grad(f)).evaluate()
    assert np.abs(dg["g"] - l["g"]).max() < 1e-12


def test_sphere_vector_laplacian():
    """Spin-weighted vector Laplacian: on the spin +-1 components of
    grad(Y_l), lap has eigenvalue -(l(l+1) - 1)/r^2."""
    cs, dist, basis = make_sphere(np.float64, shape=(16, 8))
    phi, theta = dist.local_grids(basis)
    f = dist.Field(name="f", bases=basis)
    f["g"] = np.cos(theta)
    u = d3.grad(f)
    lu = d3.lap(u).evaluate()
    gu = np.array(u.evaluate()["g"])
    assert np.abs(lu["g"] - (-1.0) * gu).max() < 1e-12


def test_sphere_skew_and_mulcos():
    """skew(u) = (u_theta, -u_phi) in (phi, theta) components;
    MulCosine multiplies by cos(theta)."""
    cs, dist, basis = make_sphere(np.float64)
    phi, theta = dist.local_grids(basis)
    f = dist.Field(name="f", bases=basis)
    f["g"] = np.sin(theta) * np.cos(theta) * np.sin(phi)
    u = d3.grad(f).evaluate()
    ug = np.array(u["g"])
    s = d3.Skew(u).evaluate()
    exact = np.array([ug[1], -ug[0]])
    assert np.abs(s["g"] - exact).max() < 1e-12
    m = d3.MulCosine(u).evaluate()
    assert np.abs(m["g"] - np.cos(theta) * ug).max() < 1e-12


def test_sphere_interpolation_integration():
    cs, dist, basis = make_sphere(np.float64, shape=(16, 8), radius=3.0)
    phi, theta = dist.local_grids(basis)
    f = dist.Field(name="f", bases=basis)
    f["g"] = np.cos(theta) ** 2 + np.sin(theta) * np.cos(phi)
    # interpolate onto colatitude ring
    th0 = 1.1
    ring = f(theta=th0).evaluate()
    phis = basis.azimuth_grid(1.0)
    exact = np.cos(th0) ** 2 + np.sin(th0) * np.cos(phis)
    assert np.abs(np.asarray(ring["g"]).ravel() - exact).max() < 1e-12
    # integral: cos^2 integrates to 4 pi r^2 / 3; the cos(phi) term drops
    I = d3.integ(f).evaluate()
    exact_I = 4 * np.pi * 9.0 / 3
    assert abs(float(np.asarray(I["g"]).ravel()[0]) - exact_I) < 1e-10
    A = d3.ave(f).evaluate()
    assert abs(float(np.asarray(A["g"]).ravel()[0]) - 1 / 3) < 1e-12


def test_integrate_coords_exclusion():
    """Integrate/Average with explicit coords must not reduce over an
    unselected curvilinear system (mixed disk x Jacobi domain)."""
    pcs = d3.PolarCoordinates("phi", "r")
    zc = d3.Coordinate("z")
    dist = d3.Distributor((pcs, zc), dtype=np.float64)
    disk = d3.DiskBasis(pcs, shape=(8, 6), dtype=np.float64, radius=1.0)
    zbasis = d3.ChebyshevT(zc, size=8, bounds=(0, 2))
    f = dist.Field(name="f", bases=(disk, zbasis))
    f["g"] = 1.0
    Iz = d3.Integrate(f, zc).evaluate()
    # still defined on the disk, value = 2 everywhere
    assert Iz.domain.get_basis(pcs.coords[0]) is not None
    assert np.abs(np.asarray(Iz["g"]) - 2.0).max() < 1e-12
    Az = d3.Average(f, zc).evaluate()
    assert np.abs(np.asarray(Az["g"]) - 1.0).max() < 1e-12
    Ifull = d3.Integrate(f).evaluate()
    assert abs(float(np.asarray(Ifull["g"]).ravel()[0]) - 2 * np.pi) < 1e-12


def test_sphere_laplacian_evp():
    """EVP: lap(f) + lam/r^2 f = 0 -> lam = l(l+1) at each m group."""
    cs, dist, basis = make_sphere(np.float64, shape=(8, 6), radius=2.0)
    f = dist.Field(name="f", bases=basis)
    lam = dist.Field(name="lam")
    problem = d3.EVP([f], eigenvalue=lam, namespace=locals())
    problem.add_equation("lap(f) + lam*f/4.0 = 0")
    solver = problem.build_solver()
    sp = solver.subproblems[1]  # m = 1
    evals = np.sort(np.asarray(solver.solve_dense(sp)).real)
    ells = np.arange(1, 6)
    expected = np.sort(np.concatenate([ells * (ells + 1)] * 2))  # cos+sin pairs
    assert np.abs(evals[:len(expected)] - expected).max() < 1e-8


def test_sphere_shallow_water_ivp():
    """Rotating shallow water: finite fields + mass conservation
    (reference: examples/ivp_sphere_shallow_water/shallow_water.py)."""
    Nphi, Ntheta = 32, 16
    R, Omega, nu, g, H = 2.0, 0.5, 1e-4, 1.0, 1.0
    cs = d3.S2Coordinates("phi", "theta")
    dist = d3.Distributor(cs, dtype=np.float64)
    basis = d3.SphereBasis(cs, shape=(Nphi, Ntheta), dtype=np.float64,
                           radius=R, dealias=(3 / 2, 3 / 2))
    u = dist.VectorField(cs, name="u", bases=basis)
    h = dist.Field(name="h", bases=basis)
    zcross = lambda A: d3.MulCosine(d3.Skew(A))
    problem = d3.IVP([u, h], namespace=locals())
    problem.add_equation(
        "dt(u) + nu*lap(lap(u)) + g*grad(h) + 2*Omega*zcross(u) = - u@grad(u)")
    problem.add_equation(
        "dt(h) + nu*lap(lap(h)) + H*div(u) = - div(u*h)")
    solver = problem.build_solver(d3.RK222)
    h.fill_random("g", seed=7, scale=1e-2)
    u.fill_random("g", seed=8, scale=1e-3)
    mass0 = float(np.asarray(d3.integ(h).evaluate()["g"]).ravel()[0])
    for _ in range(10):
        solver.step(0.05)
    assert np.isfinite(np.asarray(h["g"])).all()
    assert np.isfinite(np.asarray(u["g"])).all()
    mass1 = float(np.asarray(d3.integ(h).evaluate()["g"]).ravel()[0])
    assert abs(mass1 - mass0) < 1e-10


def test_shallow_water_f32_finite():
    """The nondimensionalized Galewsky config must stay finite in f32
    (regression: round-3 sw_ell255 NaN came from raw-SI units putting
    hyperdiffusion entries below the f32 normal range; BENCHMARKS.md)."""
    import sys
    import pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "benchmarks"))
    import progression
    solver, dt = progression.build_shallow_water(64, 32, np.float32)
    for _ in range(5):
        solver.step(dt)
    X = np.asarray(solver.X)
    assert np.isfinite(X).all()
    # hyperdiffusion entries must be representable in f32 (not denormal)
    L = solver._matrices["L"]
    vals = np.abs(np.asarray(L)[np.asarray(L) != 0])
    assert vals.min() > 1e-30


def test_spherical_ell_product():
    """SphericalEllProduct(u, cs, f): ell-diagonal multiplication; with
    f = ell(ell+1) it must equal -lap on the unit sphere (reference:
    core/operators.py:4119)."""
    cs = d3.S2Coordinates("phi", "theta")
    dist = d3.Distributor(cs, dtype=np.float64)
    b = d3.SphereBasis(cs, shape=(8, 8), dtype=np.float64, radius=1.0)
    phi, theta = dist.local_grids(b)
    u = dist.Field(name="u", bases=b)
    u["g"] = np.cos(theta) + np.sin(theta) * np.cos(phi)
    out = d3.SphericalEllProduct(u, cs, lambda l: l * (l + 1)).evaluate()
    lap = d3.lap(u).evaluate()
    assert np.abs(np.asarray(out["g"]) + np.asarray(lap["g"])).max() < 1e-12
