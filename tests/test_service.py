"""
Warm-pool solver service (dedalus_tpu/service/): protocol codecs, pool
hit/miss/eviction + reset bit-identity in-process, and the live daemon
over a real socket in a subprocess — sequential clients bit-identical to
a direct in-process solve, structured malformed-spec errors with the
daemon surviving, SIGTERM-during-request graceful drain with a valid
durable checkpoint, and `report` rendering of served records. Tier-1:
the serving path that is not exercised does not exist.
"""

import io
import json
import os
import pathlib
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

from dedalus_tpu.service import protocol
from dedalus_tpu.service.client import ServiceClient
from dedalus_tpu.service.pool import SolverPool
from dedalus_tpu.service.protocol import ServiceError, SpecError
from dedalus_tpu.tools import assembly_cache

REPO = pathlib.Path(__file__).parent.parent

pytestmark = pytest.mark.service

DIFF48 = {"problem": "diffusion", "params": {"size": 48}}


# ------------------------------------------------------------- protocol

def test_frame_roundtrip():
    buf = io.BytesIO()
    payload = b"\x00\x01binary\nframe"
    protocol.send_frame(buf, {"kind": "x", "n": 3}, payload=payload)
    protocol.send_frame(buf, {"kind": "y"})
    buf.seek(0)
    h1, p1 = protocol.recv_frame(buf)
    assert h1["kind"] == "x" and h1["n"] == 3 and p1 == payload
    h2, p2 = protocol.recv_frame(buf)
    assert h2["kind"] == "y" and p2 is None
    assert protocol.recv_frame(buf) == (None, None)       # clean EOF
    # garbage header
    with pytest.raises(protocol.ProtocolError):
        protocol.recv_frame(io.BytesIO(b"not json\n"))
    # truncated payload
    trunc = io.BytesIO(b'{"kind": "x", "payload_bytes": 10}\nabc')
    with pytest.raises(protocol.ProtocolError):
        protocol.recv_frame(trunc)


def test_field_payload_roundtrip():
    rng = np.random.default_rng(7)
    fields = {"u": ("c", rng.standard_normal(33)),
              "b": ("g", rng.standard_normal((4, 5)).astype(np.float32))}
    out = protocol.decode_fields(protocol.encode_fields(fields))
    for name, (layout, arr) in fields.items():
        got_layout, got = out[name]
        assert got_layout == layout
        assert got.dtype == arr.dtype
        assert np.array_equal(got, arr)                    # bit-exact
    with pytest.raises(SpecError):
        protocol.encode_fields({"u": ("q", np.zeros(3))})
    with pytest.raises(SpecError):
        protocol.decode_fields(b"junk that is not an npz archive")


def test_spec_validation_and_digest():
    with pytest.raises(SpecError):
        protocol.normalize_spec("not a dict")
    with pytest.raises(SpecError):
        protocol.normalize_spec({})                        # neither key
    with pytest.raises(SpecError):
        protocol.normalize_spec({"problem": "diffusion",
                                 "builder": "m:f"})        # both keys
    with pytest.raises(SpecError):
        protocol.normalize_spec({"problem": "no_such_problem"})
    # client-side structural normalization skips the registry test
    protocol.normalize_spec({"problem": "no_such_problem"},
                            check_registry=False)
    # digest is canonical under param ordering
    d1 = protocol.spec_digest({"problem": "diffusion",
                               "params": {"size": 48, "scheme": "SBDF2"}})
    d2 = protocol.spec_digest({"problem": "diffusion",
                               "params": {"scheme": "SBDF2", "size": 48}})
    assert d1 == d2
    assert d1 != protocol.spec_digest(DIFF48)
    # dotted builders are gated server-side
    with pytest.raises(SpecError):
        protocol.resolve_builder({"builder": "os:getcwd"},
                                 allow_imports=False)
    with pytest.raises(SpecError):
        protocol.resolve_builder({"builder": "no.such.module:fn"},
                                 allow_imports=True)()
    # bad builder params are spec errors, not internal ones
    with pytest.raises(SpecError):
        protocol.resolve_builder({"problem": "diffusion",
                                  "params": {"bogus_kw": 1}})()


# ----------------------------------------------------------------- pool

def test_pool_hit_miss_eviction():
    pool = SolverPool(size=2)
    e1, v1, b1 = pool.acquire(DIFF48)
    assert v1 in ("cold", "warm-cache") and b1 > 0
    e2, v2, b2 = pool.acquire(DIFF48)
    assert e2 is e1 and v2 == "hit" and b2 == 0.0
    assert (pool.hits, pool.misses, pool.evictions) == (1, 1, 0)
    # distinct shapes fill the pool, then evict LRU
    pool.acquire({"problem": "diffusion", "params": {"size": 16}})
    pool.acquire({"problem": "diffusion", "params": {"size": 24}})
    assert len(pool) == 2
    assert pool.evictions == 1
    assert pool.peek(DIFF48) is None              # the LRU entry is gone
    assert pool.peek({"problem": "diffusion",
                      "params": {"size": 24}}) is not None
    # a re-request of the evicted spec is a fresh miss, not a stale alias
    e4, v4, _ = pool.acquire(DIFF48)
    assert v4 in ("cold", "warm-cache") and e4 is not e1
    stats = pool.stats()
    assert stats["hits"] == 1 and stats["misses"] == 4
    assert len(stats["entries"]) == 2


def test_pool_reset_bit_identity():
    """A warm entry re-run with the same ICs reproduces a fresh build's
    trajectory bit for bit — including zeroing the RHS parameter field a
    previous request set."""
    pool = SolverPool(size=2)
    entry, _, _ = pool.acquire(DIFF48)
    solver = entry.solver
    u = solver.state[0]
    a = solver.eval_F.extra_fields[0]
    x = np.linspace(0, 2 * np.pi, 48, endpoint=False)
    # request 1: forced run (a nonzero) — this must NOT leak into run 2
    u["g"] = np.sin(3 * x)
    a["g"] = 0.3 * np.cos(x)
    for _ in range(12):
        solver.step(1e-3)
    X_forced = np.asarray(solver.X).copy()
    # request 2: warm hit, unforced ICs
    entry2, verdict, _ = pool.acquire(DIFF48)
    assert entry2 is entry and verdict == "hit"
    u["g"] = np.sin(3 * x)
    for _ in range(12):
        solver.step(1e-3)
    X_warm = np.asarray(solver.X).copy()
    assert not np.array_equal(X_warm, X_forced), \
        "request-1 forcing leaked through the pool reset"
    # reference: a fresh build stepping the same unforced ICs
    fresh = protocol.resolve_builder(DIFF48)()
    fresh.state[0]["g"] = np.sin(3 * x)
    for _ in range(12):
        fresh.step(1e-3)
    assert np.array_equal(X_warm, np.asarray(fresh.X)), \
        "warm pooled run is not bit-identical to a fresh solve"
    # clocks and per-run accounting were rewound
    entry3, _, _ = pool.acquire(DIFF48)
    s = entry3.solver
    assert s.iteration == 0 and s.sim_time == 0.0 and s.dt is None
    assert s.timestepper.iteration == 0
    assert s.metrics.iterations == 0
    assert s.health.checks == 0


def test_pool_key_separates_schemes():
    """Same equations, different timestepper: the assembly-cache content
    key matches (matrices are scheme-independent) but the POOL key must
    not — a pooled solver carries scheme-specific compiled programs."""
    s1 = protocol.resolve_builder(
        {"problem": "diffusion", "params": {"size": 32}})()
    s2 = protocol.resolve_builder(
        {"problem": "diffusion",
         "params": {"size": 32, "scheme": "RK222"}})()
    assert s1.assembly_key == s2.assembly_key \
        or None in (s1.assembly_key, s2.assembly_key)
    k1, k2 = assembly_cache.pool_key(s1), assembly_cache.pool_key(s2)
    assert k1 is not None and k2 is not None
    assert k1 != k2


# ----------------------------------------------------------- live daemon

def _start_daemon(stderr_path, *extra):
    from conftest import register_daemon
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    stderr = open(stderr_path, "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dedalus_tpu", "serve", *extra],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=stderr,
        text=True)
    register_daemon(proc, stderr_path)
    try:
        banner = json.loads(proc.stdout.readline())
    except ValueError:
        proc.kill()
        stderr.close()
        raise RuntimeError(
            f"daemon died before ready banner: "
            f"{pathlib.Path(stderr_path).read_text()[-2000:]}")
    assert banner["kind"] == "ready"
    return proc, banner["port"], stderr


@pytest.fixture(scope="module")
def daemon():
    """One shared daemon for the request-path tests (the drain test
    starts its own, since it kills it)."""
    workdir = tempfile.mkdtemp(prefix="dedalus_service_test_")
    sink = os.path.join(workdir, "served.jsonl")
    proc, port, stderr = _start_daemon(
        os.path.join(workdir, "daemon.err"), "--sink", sink)
    yield {"port": port, "sink": sink, "proc": proc, "workdir": workdir}
    try:
        ServiceClient(port=port, timeout=30).shutdown()
        proc.wait(timeout=60)
    except Exception:
        proc.kill()
    finally:
        stderr.close()


def test_served_bit_identical_to_direct(daemon):
    """Acceptance: two sequential clients get bit-identical results, and
    they match a direct in-process solve of the same spec + ICs."""
    client = ServiceClient(port=daemon["port"], timeout=300)
    x = np.linspace(0, 2 * np.pi, 48, endpoint=False)
    ics = {"u": ("g", np.sin(3 * x)), "a": ("g", 0.2 * np.cos(x))}
    r1 = client.run(DIFF48, ics=ics, dt=1e-3, stop_iteration=10)
    assert r1.ack["pool_verdict"] in ("cold", "warm-cache")
    # outputs may name state AND RHS-parameter fields explicitly
    r2 = client.run(DIFF48, ics=ics, dt=1e-3, stop_iteration=10,
                    outputs=["u", "a"])
    assert r2.ack["pool_verdict"] == "hit"
    assert "a" in r2.fields
    layout1, u1 = r1.fields["u"]
    layout2, u2 = r2.fields["u"]
    assert layout1 == layout2 == "c"
    assert np.array_equal(u1, u2)
    # direct in-process reference
    solver = protocol.resolve_builder(DIFF48)()
    solver.state[0]["g"] = np.sin(3 * x)
    solver.eval_F.extra_fields[0]["g"] = 0.2 * np.cos(x)
    for _ in range(10):
        solver.step(1e-3)
    direct = np.asarray(solver.state[0].coeff_data())
    assert u1.dtype == direct.dtype
    assert np.array_equal(u1, direct), \
        "served result differs from the direct in-process solve"
    # served-latency fields ride the telemetry record and the result
    serving = r2.serving
    assert serving["pool_verdict"] == "hit"
    assert serving["queue_sec"] >= 0
    assert serving["time_to_first_step_sec"] > 0
    # warm-hit time-to-first-step must be far below the cold build's
    assert serving["time_to_first_step_sec"] \
        < r1.serving["time_to_first_step_sec"]
    assert r2.record is not None
    assert r2.record["serving"]["pool_verdict"] == "hit"
    assert r2.result["stopped_by"] == "completed"
    assert r2.result["iteration"] == 10


def test_malformed_spec_structured_error(daemon):
    """Bad specs and bad run parameters produce structured error replies
    — and the daemon survives to serve the next request."""
    client = ServiceClient(port=daemon["port"], timeout=120)
    with pytest.raises(ServiceError) as excinfo:
        client.run({"problem": "no_such_problem"}, dt=1e-3,
                   stop_iteration=5)
    assert excinfo.value.code == "bad-spec"
    assert "no_such_problem" in excinfo.value.message
    with pytest.raises(ServiceError) as excinfo:
        client.run(DIFF48, dt=-1.0, stop_iteration=5)
    assert excinfo.value.code == "bad-spec"
    with pytest.raises(ServiceError) as excinfo:
        client.run(DIFF48, dt=1e-3, stop_iteration=5,
                   ics={"nope": ("g", np.zeros(48))})
    assert excinfo.value.code == "bad-spec"
    assert "nope" in excinfo.value.message
    with pytest.raises(ServiceError) as excinfo:
        # a typo'd output name must fail loudly, not return empty fields
        client.run(DIFF48, dt=1e-3, stop_iteration=5, outputs=["nope"])
    assert excinfo.value.code == "bad-spec"
    assert "nope" in excinfo.value.message
    with pytest.raises(ServiceError) as excinfo:
        # dotted builder specs are refused without --import-builders
        client.run({"builder": "os:getcwd"}, dt=1e-3, stop_iteration=5)
    assert excinfo.value.code == "bad-spec"
    # raw protocol garbage is also structured
    import socket as socket_mod
    conn = socket_mod.create_connection(("127.0.0.1", daemon["port"]),
                                        timeout=60)
    with conn:
        conn.sendall(b"this is not a frame\n")
        reply = json.loads(conn.makefile("rb").readline())
    assert reply["kind"] == "error" and reply["code"] == "bad-frame"
    # daemon alive and well
    assert client.ping()["kind"] == "pong"
    stats = client.stats()
    assert stats["pool"]["hits"] >= 1


def test_draining_daemon_refuses_new_runs():
    """Runs arriving during a drain get a structured 'draining' error —
    on BOTH refusal sites: the reader thread (request read after drain
    began) and the worker (run already queued when drain began).
    Exercised deterministically against the handler internals over
    socketpairs; the live daemon's end-to-end drain is covered by the
    SIGTERM test."""
    import socket as socket_mod
    from dedalus_tpu.service.server import SolverService
    svc = SolverService(port=0, pool_size=1)
    svc._draining = "test drain"
    run_header = {"kind": "run", "spec": DIFF48, "dt": 1e-3,
                  "stop_iteration": 5}
    # reader-side refusal
    a, b = socket_mod.socketpair()
    with a:
        protocol.send_frame(a.makefile("wb"), run_header)
        svc._receive(b, time.perf_counter())
        header, _ = protocol.recv_frame(a.makefile("rb"))
    assert header["kind"] == "error" and header["code"] == "draining"
    # worker-side refusal: the run was queued BEFORE the drain began
    a2, b2 = socket_mod.socketpair()
    with a2:
        svc._queue.put({"conn": b2, "wfile": b2.makefile("wb"),
                        "header": run_header, "payload": None,
                        "t_accept": time.perf_counter(),
                        "deadline_mono": None, "probe": False})
        svc._queued_runs += 1
        svc._queue.put(None)               # stop sentinel
        svc._worker()
        header, _ = protocol.recv_frame(a2.makefile("rb"))
    assert header["kind"] == "error" and header["code"] == "draining"
    # control requests stay answerable while draining (reader-side)
    a3, b3 = socket_mod.socketpair()
    with a3:
        protocol.send_frame(a3.makefile("wb"), {"kind": "stats"})
        svc._receive(b3, time.perf_counter())
        header, _ = protocol.recv_frame(a3.makefile("rb"))
    assert header["kind"] == "stats"
    assert header["draining"] == "test drain"
    assert svc.errors == 2


# ------------------------------------------------------------ report CLI

def test_report_renders_served_records(daemon, tmp_path):
    """The daemon's sink records (serving fields, service_stats) and the
    serving benchmark row render through `python -m dedalus_tpu report`."""
    # real served records exist in the module daemon's sink by now; add a
    # synthetic service_stats + serving benchmark row alongside
    sink = tmp_path / "served.jsonl"
    lines = pathlib.Path(daemon["sink"]).read_text().strip().splitlines()
    assert lines, "daemon sink is empty despite served requests"
    extra = [
        {"kind": "service_stats", "ts": 2.0, "requests_served": 3,
         "errors": 1, "uptime_sec": 9.5,
         "pool": {"hits": 2, "misses": 1, "evictions": 0,
                  "entries": [{"key": "abc", "spec": "diffusion"}]},
         "faults": {"queue_depth": 8, "queued": 0, "shed": 4,
                    "deadline_exceeded": 2, "watchdog_fires": 1,
                    "client_drops": 1, "mem_evictions": 0, "replays": 3,
                    "result_cache": 2,
                    "breaker": {"opens": 1, "closes": 1, "fastfails": 5,
                                "open": []}}},
        {"kind": "watchdog_postmortem", "ts": 2.5, "request_id": "r9",
         "stuck_sec": 12.3, "watchdog_sec": 5.0, "iteration": 41,
         "stacks": ["thread service-worker-1:\n  ..."]},
        {"config": "rb256x64_serving", "backend": "cpu", "ts": 3.0,
         "ttfs_cold_sec": 12.5, "ttfs_warm_sec": 0.31,
         "ttfs_speedup": 40.3, "throughput_requests_per_sec": 2.5},
        {"config": "diffusion64_overload", "backend": "cpu", "ts": 4.0,
         "queue_depth": 4, "storm_rate_x": 2.0, "shed_rate": 0.3,
         "accepted_p50_sec": 0.61, "accepted_p95_sec": 1.1,
         "latency_bound_sec": 1.8, "daemon_restarts": 0},
    ]
    sink.write_text("\n".join(lines + [json.dumps(r) for r in extra])
                    + "\n")
    # in-process (the subprocess CLI plumbing is covered by the other
    # daemon tests and tests/test_cli.py; this one is about rendering)
    import argparse
    from dedalus_tpu import __main__ as cli
    import contextlib
    stream = io.StringIO()
    with contextlib.redirect_stdout(stream):
        cli.report(argparse.Namespace(jsonl=str(sink), last=None))
    out = stream.getvalue()
    assert "serving: pool=hit" in out
    assert "queue=" in out and "ttfs=" in out
    assert "(service) 3 requests" in out
    assert "2 hits / 1 misses" in out
    # fault-tolerance counters render on the service_stats line
    assert "faults: 4 shed, 2 deadline-exceeded, 1 watchdog" in out
    assert "breaker 1 opens / 5 fast-fails" in out
    assert "3 replays" in out
    # watchdog postmortems get their own line
    assert "(watchdog) request=r9 stuck 12.3s" in out
    assert "1 thread stack(s)" in out
    assert "rb256x64_serving" in out
    assert "ttfs cold 12.5s -> warm 0.31s (40.3x)" in out
    # overload benchmark rows render the shed/bounded-latency story
    assert "2.0x capacity storm, 30.0% shed" in out
    assert "p50 0.61s / p95 1.1s" in out
    assert "0 daemon restarts" in out


def test_sigterm_drain_checkpoints_inflight_run(daemon, tmp_path):
    """Acceptance: SIGTERM mid-request drains gracefully — the in-flight
    run stops at a step boundary, writes its durable checkpoint, the
    client still receives telemetry + result frames, and the daemon
    exits 0. The checkpoint restores into a fresh solver.

    NOTE: this test consumes (kills) the shared module daemon, so it
    must stay the LAST daemon-using test in this file — the fixture
    teardown tolerates the already-dead process."""
    from dedalus_tpu.tools import resilience as res_mod
    ckpt = tmp_path / "ckpt"
    proc = daemon["proc"]
    client = ServiceClient(port=daemon["port"], timeout=300)
    x = np.linspace(0, 2 * np.pi, 48, endpoint=False)
    fired = []

    def on_progress(frame):
        if not fired:
            fired.append(frame)
            proc.send_signal(signal.SIGTERM)

    result = client.run(
        DIFF48, ics={"u": ("g", np.sin(3 * x))}, dt=1e-4,
        stop_iteration=500000, progress_every=20,
        checkpoint=str(ckpt), on_progress=on_progress)
    assert fired, "run finished before any progress frame"
    assert result.result["stopped_by"] == "SIGTERM"
    stopped_at = result.result["iteration"]
    assert 0 < stopped_at < 500000
    # telemetry still streamed, stamped with the serving fields
    assert result.record is not None
    assert result.record["serving"]["pool_verdict"] in (
        "cold", "warm-cache", "hit")
    assert proc.wait(timeout=120) == 0
    # the drain-time checkpoint is valid and restores the run exactly
    sets = sorted(ckpt.glob("*.h5"))
    assert sets, "no durable checkpoint written during drain"
    n_valid, reason = res_mod.validate_checkpoint(sets[-1])
    assert n_valid >= 1, reason
    solver = protocol.resolve_builder(DIFF48)()
    event = res_mod.resume_latest(solver, ckpt)
    assert event is not None and not event["fallbacks"]
    assert solver.iteration == stopped_at
    assert np.all(np.isfinite(np.asarray(solver.X)))
