"""
Jointly-varying (multi-axis) Cartesian NCCs (reference:
tests/test_cartesian_ncc.py:89 test_eval_fourier_jacobi_ncc): a 2-D
background state f(x, z) on the LHS expands modally along its first
varying axis; each mode contributes one kron term — exact by linearity
of the multiplication matrices.
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3
from dedalus_tpu.core.subsystems import PencilLayout, build_subproblems


def _check(dist, expr, operand):
    eq = {"domain": expr.domain, "tensorsig": tuple(expr.tensorsig),
          "L": expr}
    layout = PencilLayout(dist, [operand], [eq])
    sps = build_subproblems(layout)
    Xin = np.asarray(layout.gather(operand.coeff_data(), operand.domain,
                                   operand.tensorsig))
    out = expr.evaluate()
    Xout = np.asarray(layout.gather(out.coeff_data(), out.domain,
                                    out.tensorsig))
    scale = max(np.abs(Xout).max(), 1e-12)
    for sp in sps:
        mats = expr.expression_matrices(sp, [operand])
        y = mats[operand] @ Xin[sp.index]
        valid = layout.valid_mask(expr.domain, tuple(expr.tensorsig),
                                  sp.group).ravel()
        err = np.abs(y - Xout[sp.index])[valid].max(initial=0.0) / scale
        assert err < 2e-10, (sp.group, err)
    return layout


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_joint_fourier_jacobi_ncc(dtype):
    """f(x, z) * u with RealFourier x Chebyshev: the x axis is forced
    coupled and the joint structure expands over x modes."""
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=dtype)
    xb = (d3.RealFourier if dtype == np.float64 else d3.ComplexFourier)(
        coords["x"], size=12, bounds=(0, 2 * np.pi), dealias=2)
    zb = d3.ChebyshevT(coords["z"], size=10, bounds=(0, 1), dealias=2)
    x, z = dist.local_grids(xb, zb)
    f = dist.Field(name="f", bases=(xb, zb))
    f["g"] = 2.0 + np.sin(x) * z ** 2 + 0.3 * np.cos(2 * x) * z
    u = dist.Field(name="u", bases=(xb, zb))
    u["g"] = np.cos(x) * (1 - z) + 0.5 * np.sin(2 * x) * z ** 2
    layout = _check(dist, (f * u), u)
    assert 0 not in layout.sep_widths  # x axis forced coupled


def test_joint_jacobi_jacobi_ncc():
    """f(x, z) * u with Chebyshev x Chebyshev (two genuinely coupled
    axes) — matrix equals grid product."""
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.ChebyshevT(coords["x"], size=12, bounds=(0, 1), dealias=2)
    zb = d3.ChebyshevT(coords["z"], size=10, bounds=(0, 1), dealias=2)
    x, z = dist.local_grids(xb, zb)
    f = dist.Field(name="f", bases=(xb, zb))
    f["g"] = 1.0 + 0.5 * x * z + 0.2 * x ** 2 * z ** 2
    u = dist.Field(name="u", bases=(xb, zb))
    u["g"] = np.sin(2 * x) * (1 - z ** 2)
    _check(dist, (f * u), u)


def test_joint_ncc_lbvp_roundtrip():
    """Solve (2 + 0.5 sin(x) z) u = F for a known u (2-D variable
    coefficient on the LHS — the linearized-background problem class)."""
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=12, bounds=(0, 2 * np.pi),
                        dealias=2)
    zb = d3.ChebyshevT(coords["z"], size=10, bounds=(0, 1), dealias=2)
    x, z = dist.local_grids(xb, zb)
    f = dist.Field(name="f", bases=(xb, zb))
    f["g"] = 2.0 + 0.5 * np.sin(x) * z
    u = dist.Field(name="u", bases=(xb, zb))
    u_target = dist.Field(name="u_target", bases=(xb, zb))
    u_target["g"] = np.cos(x) * z + 0.3 * np.sin(2 * x) * (1 - z)
    F = (f * u_target).evaluate()
    problem = d3.LBVP([u], namespace=locals())
    problem.add_equation("f*u = F")
    solver = problem.build_solver()
    solver.solve()
    err = np.abs(np.asarray(u["g"]) - np.asarray(u_target["g"])).max()
    assert err < 1e-10
