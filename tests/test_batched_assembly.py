"""
Batched group assembly vs the per-group scipy walk: the shared-pattern COO
result scattered dense must match subsystems.build_matrices exactly
(oracle pattern mirroring the reference's fast-vs-matrix transform tests,
reference: tests/test_transforms.py:22).
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3
from dedalus_tpu.core.batched_assembly import batched_system_coos
from dedalus_tpu.core.subsystems import build_matrices


def assert_batched_matches(solver, names):
    layout, eqs, variables = solver.layout, solver.equations, solver.variables
    pr, pc, vals, row_valid, col_valid = batched_system_coos(
        layout, eqs, variables, names)
    ref = build_matrices(solver.subproblems, eqs, variables, names=names)
    G, S = solver.pencil_shape
    for name in names:
        dense = np.zeros((G, S, S), dtype=vals[name].dtype)
        dense[:, pr, pc] = vals[name]
        if name == names[-1]:
            for g in range(G):
                inv_r = np.flatnonzero(~row_valid[g])
                inv_c = np.flatnonzero(~col_valid[g])
                dense[g, inv_r, inv_c] = 1.0
        assert np.abs(dense - ref[name]).max() < 1e-11, name


def test_rayleigh_benard():
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from __graft_entry__ import _build_rb_solver
    solver, b = _build_rb_solver(16, 8, np.float64)
    assert solver._batched is not None
    assert_batched_matches(solver, ("M", "L"))


def test_fourier_2d():
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=8, bounds=(0, 1))
    zb = d3.RealFourier(coords["z"], size=8, bounds=(-1, 1))
    p = dist.Field(name="p", bases=(xb, zb))
    u = dist.VectorField(coords, name="u", bases=(xb, zb))
    tau_p = dist.Field(name="tau_p")
    nu = 1e-2
    problem = d3.IVP([u, p, tau_p], namespace=locals())
    problem.add_equation("dt(u) + grad(p) - nu*lap(u) = - u@grad(u)")
    problem.add_equation("div(u) + tau_p = 0")
    problem.add_equation("integ(p) = 0")
    solver = problem.build_solver(d3.RK222)
    assert solver._batched is not None
    assert_batched_matches(solver, ("M", "L"))


def test_complex_fourier():
    coords = d3.CartesianCoordinates("x")
    dist = d3.Distributor(coords, dtype=np.complex128)
    xb = d3.ComplexFourier(coords["x"], size=16, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    problem = d3.IVP([u], namespace=locals())
    problem.add_equation("dt(u) - lap(u) = 0")
    solver = problem.build_solver("SBDF1")
    assert solver._batched is not None
    assert_batched_matches(solver, ("M", "L"))


def test_disk_lbvp():
    coords = d3.PolarCoordinates("phi", "r")
    dist = d3.Distributor(coords, dtype=np.float64)
    disk = d3.DiskBasis(coords, shape=(8, 8), radius=1.0, dtype=np.float64)
    f = dist.Field(name="f", bases=disk)
    tau = dist.Field(name="tau", bases=disk.edge)
    g = dist.Field(name="g", bases=disk)
    problem = d3.LBVP([f, tau], namespace=locals())
    problem.add_equation("lap(f) + Lift(tau, disk, -1) = g")
    problem.add_equation("f(r=1) = 0")
    solver = problem.build_solver()
    assert_batched_matches(solver, ("L",))


def test_chebyshev_ncc():
    # z-dependent NCC multiplying a variable (coupled-axis NCC matrices)
    coords = d3.CartesianCoordinates("z")
    dist = d3.Distributor(coords, dtype=np.float64)
    zb = d3.ChebyshevT(coords["z"], size=16, bounds=(0, 1))
    u = dist.Field(name="u", bases=zb)
    t1 = dist.Field(name="t1")
    t2 = dist.Field(name="t2")
    ncc = dist.Field(name="ncc", bases=zb)
    z, = dist.local_grids(zb)
    ncc["g"] = 1 + z ** 2
    lift_b = zb.derivative_basis(2)
    problem = d3.LBVP([u, t1, t2], namespace=locals())
    problem.add_equation(
        "lap(u) + ncc*u + Lift(t1, lift_b, -1) + Lift(t2, lift_b, -2) = ncc")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("u(z=1) = 0")
    solver = problem.build_solver()
    assert solver._batched is not None
    assert_batched_matches(solver, ("L",))


def test_valid_masks_all_matches_per_group():
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=8, bounds=(0, 1))
    zb = d3.ChebyshevT(coords["z"], size=8, bounds=(0, 1))
    u = dist.VectorField(coords, name="u", bases=(xb, zb))
    tau = dist.Field(name="tau", bases=xb)
    from dedalus_tpu.core.subsystems import PencilLayout
    layout = PencilLayout(dist, [u, tau], [])
    for operand in (u, tau):
        batched = layout.valid_masks_all(operand.domain, operand.tensorsig)
        for g_i, group in enumerate(layout.groups()):
            per_group = layout.valid_mask(operand.domain, operand.tensorsig,
                                          group).ravel()
            assert np.array_equal(batched[g_i], per_group)


def test_ball_radial_ncc():
    """Spherical radial NCC (T*r_vec) batches via per-ell stacks and
    matches the per-group path."""
    coords = d3.SphericalCoordinates("phi", "theta", "r")
    dist = d3.Distributor(coords, dtype=np.float64)
    ball = d3.BallBasis(coords, shape=(8, 4, 8), radius=1.0, dealias=3 / 2)
    u = dist.VectorField(coords, name="u", bases=ball)
    p = dist.Field(name="p", bases=ball)
    T = dist.Field(name="T", bases=ball)
    tau_p = dist.Field(name="tau_p")
    tau_u = dist.VectorField(coords, name="tau_u", bases=ball.surface)
    tau_T = dist.Field(name="tau_T", bases=ball.surface)
    r_vec = dist.VectorField(coords, name="r_vec", bases=ball)
    phi, theta, r = dist.local_grids(ball)
    r_vec["g"][2] = np.broadcast_to(np.asarray(r),
                                    np.asarray(r_vec["g"])[2].shape)
    nu = kappa = 1e-2
    lift = lambda A: d3.Lift(A, ball, -1)
    problem = d3.IVP([p, u, T, tau_p, tau_u, tau_T], namespace=locals())
    problem.add_equation("div(u) + tau_p = 0")
    problem.add_equation(
        "dt(u) - nu*lap(u) + grad(p) - T*r_vec + lift(tau_u) = - u@grad(u)")
    problem.add_equation(
        "dt(T) - kappa*lap(T) + lift(tau_T) = - u@grad(T) + 1")
    problem.add_equation("u(r=1) = 0")
    problem.add_equation("T(r=1) = 0")
    problem.add_equation("integ(p) = 0")
    solver = problem.build_solver(d3.RK222)
    assert solver._batched is not None, "spherical NCC did not batch"
    assert_batched_matches(solver, ("M", "L"))
