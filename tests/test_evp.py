"""
EVP tests against analytic spectra (reference: dedalus/tests/test_evp.py).
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3


def build_waves(N=32, L=1.0):
    """u_xx = -lam*u with Dirichlet BCs: lam_k = (k pi / L)^2."""
    coords = d3.CartesianCoordinates("x")
    dist = d3.Distributor(coords, dtype=np.complex128)
    xb = d3.ChebyshevT(coords["x"], size=N, bounds=(0, L))
    u = dist.Field(name="u", bases=xb)
    t1 = dist.Field(name="t1")
    t2 = dist.Field(name="t2")
    lam = dist.Field(name="lam")
    lift = lambda A, n: d3.Lift(A, xb.derivative_basis(1), n)
    problem = d3.EVP([u, t1, t2], eigenvalue=lam, namespace=locals())
    problem.add_equation("lap(u) + lam*u + lift(t1,-1) + lift(t2,-2) = 0")
    problem.add_equation("u(x=0) = 0")
    problem.add_equation(f"u(x={L}) = 0")
    return problem.build_solver(), L


def test_waves_dense_eigenvalues():
    """Dense solve recovers the Dirichlet Laplacian spectrum
    (reference: tests/test_evp.py waves tests)."""
    solver, L = build_waves()
    evals = solver.solve_dense(solver.subproblems[0])
    evals = np.sort(evals.real)
    exact = ((np.arange(1, 9) * np.pi / L) ** 2)
    # low eigenvalues resolved to high accuracy
    assert np.allclose(evals[:8], exact, rtol=1e-8)


def test_waves_dense_left_biorthonormality():
    """Left eigenvectors normalized against -M (reference:
    core/solvers.py:180 solve_dense(left=True) biorthonormalization)."""
    solver, L = build_waves(N=24)
    sp = solver.subproblems[0]
    solver.solve_dense(sp, left=True)
    M = solver.ops.densify_host(solver._matrices["M"], sp.index)
    right = solver.eigenvectors
    left = solver.left_eigenvectors
    B = np.conj(left).T @ (-M) @ right
    # modes with distinct eigenvalues are biorthonormal
    n = min(8, B.shape[0])
    assert np.allclose(B[:n, :n], np.eye(n), atol=1e-8)


def test_waves_sparse_target():
    """Sparse shift-invert finds eigenvalues near the target
    (reference: core/solvers.py:225 solve_sparse)."""
    solver, L = build_waves()
    target = (3 * np.pi / L) ** 2
    evals = solver.solve_sparse(solver.subproblems[0], N=3, target=target + 1.0)
    found = np.sort(np.abs(evals.real))
    assert np.any(np.abs(found - target) < 1e-6 * target)


def test_evp_set_state():
    """set_state loads an eigenmode into the state fields
    (reference: core/solvers.py:296 set_state)."""
    solver, L = build_waves()
    solver.solve_dense(solver.subproblems[0])
    order = np.argsort(solver.eigenvalues.real)
    solver.set_state(int(order[0]))
    u = solver.problem.variables[0]
    x = np.linspace(0, L, 64)[1:-1]
    # mode shape ~ sin(pi x / L) up to complex scale
    from dedalus_tpu.core.operators import Interpolate
    g = np.asarray(u["g"]).ravel()
    grid = u.domain.bases[0].global_grid(1.0)
    ref = np.sin(np.pi * grid / L)
    scale = g[np.argmax(np.abs(g))] / ref[np.argmax(np.abs(g))]
    assert np.allclose(g, scale * ref, atol=1e-8 * abs(scale))


def test_ivp_build_evp():
    """IVP -> EVP conversion (reference: core/problems.py:364 build_EVP):
    dt(u) = lap(u) with Dirichlet BCs gives lam_k = -(k pi / L)^2."""
    L = 1.0
    coords = d3.CartesianCoordinates("x")
    dist = d3.Distributor(coords, dtype=np.complex128)
    xb = d3.ChebyshevT(coords["x"], size=32, bounds=(0, L))
    u = dist.Field(name="u", bases=xb)
    t1 = dist.Field(name="t1")
    t2 = dist.Field(name="t2")
    lift = lambda A, n: d3.Lift(A, xb.derivative_basis(1), n)
    problem = d3.IVP([u, t1, t2], namespace=locals())
    problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = 0")
    problem.add_equation("u(x=0) = 0")
    problem.add_equation(f"u(x={L}) = 0")
    evp = problem.build_EVP()
    solver = evp.build_solver()
    evals = solver.solve_dense(solver.subproblems[0])
    evals = np.sort(evals.real)[::-1]
    exact = -((np.arange(1, 7) * np.pi / L) ** 2)
    assert np.allclose(evals[:6], exact, rtol=1e-8)


def test_mathieu_fourier_ncc():
    """Periodic EVP with a Fourier-varying LHS NCC (reference:
    examples/evp_1d_mathieu): the cos(2x) coefficient couples Fourier
    modes, forcing the layout to treat the axis as coupled (G=1) and the
    NCC to assemble a whole-axis convolution matrix. Characteristic
    values at q=5 from Abramowitz & Stegun 20.
    """
    N = 32
    xcoord = d3.Coordinate('x')
    dist = d3.Distributor(xcoord, dtype=np.complex128)
    xbasis = d3.ComplexFourier(xcoord, size=N, bounds=(0, 2 * np.pi))
    x = dist.local_grids(xbasis)[0]
    y = dist.Field(name='y', bases=xbasis)
    a = dist.Field(name='a')
    q = dist.Field(name='q')
    cos_2x = dist.Field(name='cos_2x', bases=xbasis)
    cos_2x['g'] = np.cos(2 * x)
    dx = lambda A: d3.Differentiate(A, xcoord)
    problem = d3.EVP([y], eigenvalue=a, namespace=locals())
    problem.add_equation("dx(dx(y)) + (a - 2*q*cos_2x)*y = 0")
    solver = problem.build_solver()
    assert solver.pencil_shape[0] == 1  # NCC coupling -> single pencil
    # q=0: plain Fourier eigenvalues n^2 (doubly degenerate for n>0)
    solver.solve_dense(solver.subproblems[0])
    got0 = np.sort(solver.eigenvalues.real)[:5]
    assert np.allclose(got0, [0, 1, 1, 4, 4], atol=1e-10)
    # q=5: interleaved even/odd characteristic values a0 < b1 < a1 < b2
    q['g'] = 5.0
    solver.solve_dense(solver.subproblems[0], rebuild_matrices=True)
    got5 = np.sort(solver.eigenvalues.real)[:4]
    expect5 = [-5.80004602, -5.79008060, 1.85818754, 2.09946045]
    assert np.allclose(got5, expect5, atol=1e-6), got5
