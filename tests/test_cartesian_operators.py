"""
Cartesian operator tests vs closed-form grid expressions
(reference: dedalus/tests/test_cartesian_operators.py).
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3


@pytest.fixture
def setup_2d():
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=32, bounds=(0, 2), dealias=3/2)
    zb = d3.ChebyshevT(coords["z"], size=24, bounds=(0, 1), dealias=3/2)
    x, z = dist.local_grids(xb, zb)
    return coords, dist, xb, zb, x, z


def test_gradient_scalar(setup_2d):
    coords, dist, xb, zb, x, z = setup_2d
    f = dist.Field(name="f", bases=(xb, zb))
    f["g"] = np.sin(np.pi * x) * np.cos(3 * z)
    g = d3.grad(f).evaluate()["g"]
    assert np.allclose(g[0], np.pi * np.cos(np.pi * x) * np.cos(3 * z))
    assert np.allclose(g[1], -3 * np.sin(np.pi * x) * np.sin(3 * z))


def test_divergence_vector(setup_2d):
    coords, dist, xb, zb, x, z = setup_2d
    u = dist.VectorField(coords, name="u", bases=(xb, zb))
    ug = np.zeros((2, 32, 24))
    ug[0] = np.sin(np.pi * x) * np.cos(z)
    ug[1] = np.cos(np.pi * x) * z**2
    u["g"] = ug
    div = d3.div(u).evaluate()
    div.change_scales(1)
    exact = np.pi * np.cos(np.pi * x) * np.cos(z) + 2 * np.cos(np.pi * x) * z
    assert np.allclose(div["g"], exact)


def test_laplacian(setup_2d):
    coords, dist, xb, zb, x, z = setup_2d
    f = dist.Field(name="f", bases=(xb, zb))
    f["g"] = np.sin(np.pi * x) * np.exp(z)
    lap = d3.lap(f).evaluate()["g"]
    exact = (1 - np.pi**2) * np.sin(np.pi * x) * np.exp(z)
    assert np.allclose(lap, exact, atol=1e-8)


def test_curl_2d(setup_2d):
    coords, dist, xb, zb, x, z = setup_2d
    u = dist.VectorField(coords, name="u", bases=(xb, zb))
    ug = np.zeros((2, 32, 24))
    ug[0] = np.sin(np.pi * x) * z
    ug[1] = np.cos(np.pi * x) * z**2
    u["g"] = ug
    curl = d3.curl(u).evaluate()["g"]
    exact = -np.pi * np.sin(np.pi * x) * z**2 - np.sin(np.pi * x)
    assert np.allclose(curl, exact)


def test_trace_transpose_skew(setup_2d):
    coords, dist, xb, zb, x, z = setup_2d
    u = dist.VectorField(coords, name="u", bases=(xb, zb))
    ug = np.zeros((2, 32, 24))
    ug[0] = np.sin(np.pi * x) * z
    ug[1] = np.cos(np.pi * x) * z**2
    u["g"] = ug
    T = d3.grad(u)
    tr = d3.trace(T).evaluate()["g"]
    exact_tr = np.pi * np.cos(np.pi * x) * z + 2 * np.cos(np.pi * x) * z
    assert np.allclose(tr, exact_tr)
    Tt = d3.transpose(T).evaluate()["g"]
    Tg = T.evaluate()["g"]
    assert np.allclose(Tt, np.swapaxes(Tg, 0, 1))
    sk = d3.skew(u).evaluate()["g"]
    u1 = u.copy()
    u1.change_scales(1)
    assert np.allclose(sk[0], -np.asarray(u1["g"])[1])
    assert np.allclose(sk[1], np.asarray(u1["g"])[0])


def test_integrate_average_interpolate(setup_2d):
    coords, dist, xb, zb, x, z = setup_2d
    f = dist.Field(name="f", bases=(xb, zb))
    f["g"] = (1 + np.cos(np.pi * x)) * z**2
    # integral over x in [0,2] of (1+cos(pi x)) = 2; integral of z^2 = 1/3
    total = np.asarray(d3.integ(f).evaluate()["g"]).ravel()[0]
    assert np.allclose(total, 2 / 3)
    avg = np.asarray(d3.ave(f).evaluate()["g"]).ravel()[0]
    assert np.allclose(avg, 1 / 3)
    fz = d3.Interpolate(f, coords["z"], 0.5).evaluate()["g"]
    assert np.allclose(fz.ravel(), ((1 + np.cos(np.pi * x)) * 0.25).ravel())
    fx = d3.Interpolate(f, coords["x"], 0.5).evaluate()["g"]
    assert np.allclose(fx.ravel(), ((1 + np.cos(np.pi * 0.5)) * z**2).ravel())


def test_dot_cross_products(setup_2d):
    coords, dist, xb, zb, x, z = setup_2d
    u = dist.VectorField(coords, name="u", bases=(xb, zb))
    v = dist.VectorField(coords, name="v", bases=(xb, zb))
    ug = np.zeros((2, 32, 24)); vg = np.zeros((2, 32, 24))
    ug[0] = np.sin(np.pi * x) * np.ones_like(z); ug[1] = z * np.ones_like(x)
    vg[0] = np.cos(np.pi * x) * np.ones_like(z); vg[1] = z**2 * np.ones_like(x)
    u["g"] = ug; v["g"] = vg
    dp = (u @ v).evaluate()["g"]
    exact = np.sin(np.pi * x) * np.cos(np.pi * x) + z**3
    assert np.allclose(dp, exact)


def test_ufunc(setup_2d):
    coords, dist, xb, zb, x, z = setup_2d
    f = dist.Field(name="f", bases=(xb, zb))
    f["g"] = 1 + 0.5 * np.sin(np.pi * x) * z
    out = np.exp(f).evaluate()["g"]
    assert np.allclose(out, np.exp(1 + 0.5 * np.sin(np.pi * x) * z))


def test_power(setup_2d):
    coords, dist, xb, zb, x, z = setup_2d
    f = dist.Field(name="f", bases=(xb, zb))
    f["g"] = 1 + 0.3 * np.cos(np.pi * x) * z
    out = (f**2).evaluate()["g"]
    assert np.allclose(out, (1 + 0.3 * np.cos(np.pi * x) * z) ** 2)


def test_fourier_differentiate_1d():
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=64, bounds=(0, 3))
    u = dist.Field(name="u", bases=xb)
    x = dist.local_grid(xb)
    k = 2 * np.pi / 3
    u["g"] = np.sin(4 * k * x) + np.cos(7 * k * x)
    du = d3.Differentiate(u, xc).evaluate()["g"]
    exact = 4 * k * np.cos(4 * k * x) - 7 * k * np.sin(7 * k * x)
    assert np.allclose(du, exact.ravel())


def test_complex_fourier_differentiate():
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.complex128)
    xb = d3.ComplexFourier(xc, size=32, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    x = dist.local_grid(xb)
    u["g"] = np.exp(3j * x)
    du = d3.Differentiate(u, xc).evaluate()["g"]
    assert np.allclose(du, 3j * np.exp(3j * x).ravel())


def test_string_coordinate_specs(setup_2d):
    """Coordinate NAMES must resolve to the same operators as coordinate
    objects (a string used to silently no-op Interpolate/Integrate)."""
    coords, dist, xb, zb, x, z = setup_2d
    f = dist.Field(name="f", bases=(xb, zb))
    f["g"] = 0 * x + z ** 2
    vi = np.asarray(d3.Interpolate(f, "z", 0.25).evaluate()["g"]).ravel()
    assert np.allclose(vi, 0.0625)
    vq = np.asarray(d3.Integrate(f, "z").evaluate()["g"]).ravel()
    assert np.allclose(vq, 1 / 3)
    va = np.asarray(d3.Average(f, ("x", "z")).evaluate()["g"]).ravel()
    assert np.allclose(va, 1 / 3)
    vd = np.asarray(d3.Differentiate(f, "z").evaluate()["g"])
    assert np.allclose(vd, 2 * z + 0 * x)
    with pytest.raises(ValueError, match="Unknown coordinate"):
        d3.Interpolate(f, "w", 0.0)


def test_string_coordinate_specs_curvilinear():
    """String coords must take the curvilinear reduction path in
    Integrate/Average (resolution happens before _curv_selected)."""
    coords = d3.PolarCoordinates("phi", "r")
    dist = d3.Distributor(coords, dtype=np.float64)
    disk = d3.DiskBasis(coords, shape=(16, 16), radius=2.0)
    f = dist.Field(name="f", bases=disk)
    phi, r = dist.local_grids(disk)
    f["g"] = np.broadcast_to(r ** 2, np.broadcast_shapes(phi.shape, r.shape))
    v = float(np.asarray(d3.Integrate(f, ("phi", "r")).evaluate()["g"]).ravel()[0])
    assert abs(v - 8 * np.pi) < 1e-10
    va = float(np.asarray(d3.Average(f, ("phi", "r")).evaluate()["g"]).ravel()[0])
    assert abs(va - 2.0) < 1e-10
