"""
Solve compositions + precision ladder (libraries/solvecomp.py wired
through pencilops/matsolvers/solvers): every [fusion] SOLVE_COMPOSITION
and [precision] SOLVE_DTYPE cell must agree with the sequential f64
path — tolerance-bounded on the banded restructurings (the refinement
polish holds them at the fused tolerance class), bitwise on the dense
path where the compositions are inert — and compose with the adjoint
funnel, EnsembleSolver vmap, the 2-D batch x pencil mesh, the retrace
sentinel, and the assembly/pool key discipline.

Tolerance contract under test (docs/performance.md "Solve depth and the
precision ladder"): ascan/spike trajectories track sequential within
~1e-11 relative (observed ~1e-14 on the small RB); the f32+refinement
ladder holds state error <= 1e-10 vs f64 (observed ~1e-13) with its
sweep count resolved from [precision] REFINE_SWEEPS.
"""

import pathlib
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import dedalus_tpu.public as d3
from dedalus_tpu.libraries import solvecomp
from dedalus_tpu.libraries.matsolvers import (BatchedInverseRefined,
                                              get_solver)
from dedalus_tpu.tools import retrace as retrace_mod
from dedalus_tpu.tools.config import config
from dedalus_tpu.tools.lint.progcheck import scan_lengths

sys.path.insert(0, str(pathlib.Path(__file__).parent))
from test_banded import build_rb  # noqa: E402

pytestmark = pytest.mark.solvecomp

SOLVE_KEYS = (("fusion", "SOLVE_COMPOSITION"), ("fusion", "SPIKE_CHUNKS"),
              ("precision", "SOLVE_DTYPE"), ("precision", "REFINE_SWEEPS"),
              ("precision", "REFINE_TOL"), ("precision", "MMT_DTYPE"),
              ("fusion", "FUSED_SOLVE"), ("fusion", "PALLAS"))


@pytest.fixture
def solve_cfg():
    """Mutate the solve-plan keys inside a test, restored afterwards."""
    for section in {s for s, _ in SOLVE_KEYS}:
        if not config.has_section(section):
            config.add_section(section)
    saved = {(s, k): config[s].get(k) for s, k in SOLVE_KEYS}

    def set_cfg(composition="auto", solve_dtype="auto", sweeps="auto",
                tol="auto", spike_chunks="auto", mmt="auto",
                fused_solve="auto", pallas="off"):
        config["fusion"]["SOLVE_COMPOSITION"] = composition
        config["fusion"]["SPIKE_CHUNKS"] = spike_chunks
        config["fusion"]["FUSED_SOLVE"] = fused_solve
        config["fusion"]["PALLAS"] = pallas
        config["precision"]["SOLVE_DTYPE"] = solve_dtype
        config["precision"]["REFINE_SWEEPS"] = sweeps
        config["precision"]["REFINE_TOL"] = tol
        config["precision"]["MMT_DTYPE"] = mmt

    set_cfg()
    yield set_cfg
    for (s, k), val in saved.items():
        if val is None:
            config[s].pop(k, None)
        else:
            config[s][k] = val


def rb_trajectory(scheme, n=8, **build_kw):
    solver = build_rb(8, 32, matsolver="banded", timestepper=scheme,
                      **build_kw)
    for _ in range(n):
        solver.step(0.01)
    return np.asarray(solver.X), solver


# sequential-f64 baselines shared across the comparison tests (one build
# per scheme instead of one per test; computed under the solve_cfg
# fixture's default reset, which every caller applies first)
_SEQ_BASELINES = {}


def seq_baseline(scheme):
    key = scheme.__name__
    if key not in _SEQ_BASELINES:
        _SEQ_BASELINES[key], _ = rb_trajectory(scheme)
    return _SEQ_BASELINES[key]


def build_diffusion(scheme=d3.SBDF2, size=48):
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=size, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    dx = lambda A: d3.Differentiate(A, xc)  # noqa: E731
    problem = d3.IVP([u], namespace={"u": u, "lap": d3.lap, "dx": dx})
    problem.add_equation("dt(u) - lap(u) = - u*dx(u)")
    x = dist.local_grid(xb)
    u["g"] = np.sin(3 * x) + 0.2 * np.cos(x)
    return problem.build_solver(scheme, warmup_iterations=2,
                                enforce_real_cadence=0)


# ------------------------------------------------- unit-level recurrences

def test_ascan_apply_matches_reference():
    """ascan_apply == the sequential affine recurrence, for general
    state/input/output widths and multiple RHS columns."""
    rng = np.random.default_rng(0)
    m, G, s, kin, o, k = 7, 3, 4, 2, 5, 2
    A = rng.standard_normal((m, G, s, s)) * 0.3
    B = rng.standard_normal((m, G, s, kin))
    C = rng.standard_normal((m, G, o, s))
    D = rng.standard_normal((m, G, o, kin))
    u = rng.standard_normal((m, G, kin, k))
    v0 = rng.standard_normal((G, s, k))
    outs, v_end = solvecomp.ascan_apply(*map(jnp.asarray, (A, B, C, D, u,
                                                           v0)))
    v = v0
    for j in range(m):
        ref = C[j] @ v + D[j] @ u[j]
        assert np.allclose(np.asarray(outs[j]), ref, atol=1e-12)
        v = A[j] @ v + B[j] @ u[j]
    assert np.allclose(np.asarray(v_end), v, atol=1e-12)


@pytest.mark.parametrize("chunks", [2, 3, 7])
def test_spike_apply_matches_reference(chunks):
    """spike_precompose + spike_apply == the sequential recurrence for
    every chunk count, including non-dividing ones (identity padding)."""
    rng = np.random.default_rng(1)
    m, G, s, kin, o, k = 7, 2, 3, 3, 3, 1
    A = rng.standard_normal((m, G, s, s)) * 0.3
    B = rng.standard_normal((m, G, s, kin))
    C = rng.standard_normal((m, G, o, s))
    D = rng.standard_normal((m, G, o, kin))
    u = rng.standard_normal((m, G, kin, k))
    v0 = rng.standard_normal((G, s, k))
    ops = solvecomp.spike_precompose(*map(jnp.asarray, (A, B, C, D)),
                                     chunks)
    outs, v_end = solvecomp.spike_apply(ops, jnp.asarray(u),
                                        jnp.asarray(v0))
    v = v0
    for j in range(m):
        ref = C[j] @ v + D[j] @ u[j]
        assert np.allclose(np.asarray(outs[j]), ref, atol=1e-12), (chunks, j)
        v = A[j] @ v + B[j] @ u[j]
    assert np.allclose(np.asarray(v_end), v, atol=1e-12)


def test_spike_chunk_count():
    assert solvecomp.spike_chunk_count(3, 0) == 1      # too short to chunk
    assert solvecomp.spike_chunk_count(16, 0) == 4     # auto ~ sqrt
    assert solvecomp.spike_chunk_count(16, 6) == 6
    assert solvecomp.spike_chunk_count(16, 99) == 16   # clamped


# ------------------------------------------ trajectory agreement (banded)

@pytest.mark.parametrize("scheme", [d3.SBDF2, d3.RK222])
@pytest.mark.parametrize("composition", ["ascan", "spike"])
def test_composition_matches_sequential_banded(scheme, composition,
                                               solve_cfg):
    """Every restructured composition tracks the sequential f64 banded
    trajectory within the fused tolerance class; the aux carries the
    structure the composition claims (spike chunk operators / retained
    step operators for ascan)."""
    solve_cfg(composition="sequential")
    x_seq = seq_baseline(scheme)
    solve_cfg(composition=composition)
    x_new, solver = rb_trajectory(scheme)
    assert solver.ops._composition == composition
    aux = solver.timestepper._lhs_aux
    aux0 = (aux[0] if isinstance(aux, list) else aux)["fsub"]
    if composition == "spike":
        assert "spikeF" in aux0 and "spikeB" in aux0
        assert "FwdOp" not in aux0     # dropped: spike consumes chunk ops
        # adjoint contract, directly on the funnel: <A^-1 r, s> must
        # equal <r, A^-T s> against the SAME restructured factors
        ops = solver.ops
        aux_full = aux[0] if isinstance(aux, list) else aux
        mats = (solver.M_mat, solver.L_mat)
        rng = np.random.default_rng(9)
        r = jnp.asarray(rng.standard_normal(solver.pencil_shape))
        s = jnp.asarray(rng.standard_normal(solver.pencil_shape))
        lhs = float(jnp.vdot(ops.solve(aux_full, r, mats=mats), s))
        rhs = float(jnp.vdot(r, ops.solve_transpose(aux_full, s, mats=mats)))
        assert abs(lhs - rhs) <= 1e-10 * max(abs(lhs), 1.0)
    else:
        assert "FwdOp" in aux0
    assert np.isfinite(x_new).all()
    scale = np.max(np.abs(x_seq))
    assert np.max(np.abs(x_new - x_seq)) <= 1e-11 * scale


@pytest.mark.parametrize("composition", ["ascan", "spike"])
def test_composition_inert_on_dense(composition, solve_cfg):
    """The scan compositions are no-ops on the dense pencil path (there
    is no substitution scan): trajectories are BITWISE identical to the
    sequential build under the same config."""
    solve_cfg(composition="sequential")
    s_seq = build_diffusion()
    for _ in range(10):
        s_seq.step(1e-3)
    solve_cfg(composition=composition)
    s_new = build_diffusion()
    for _ in range(10):
        s_new.step(1e-3)
    assert np.array_equal(np.asarray(s_seq.X), np.asarray(s_new.X))


# --------------------------------------------------- the precision ladder

def test_ladder_f32_banded_accuracy(solve_cfg):
    """The f32 ladder stores the fused factors in float32 (halving the
    factor store) and the f64 refinement polish contracts the error by
    ~cond*eps32 per sweep: the auto schedule (2 sweeps, the measured
    rb256x64 speed/accuracy knee) holds this stiffer small RB at the
    1e-9 class (observed 1.2e-10), one more sweep lands the <=1e-10
    ladder bar with orders to spare (observed 4e-15); the telemetry
    block records the resolved plan + achieved residual."""
    solve_cfg()
    x_f64 = seq_baseline(d3.RK222)
    solve_cfg(solve_dtype="f32")
    x_auto, solver = rb_trajectory(d3.RK222)
    aux = solver.timestepper._lhs_aux[0]
    assert aux["fsub"]["lastOp"].dtype == np.float32
    assert solver._solve_plan.sweeps == 2    # auto scales to the gap
    scale = np.max(np.abs(x_f64))
    assert np.max(np.abs(x_auto - x_f64)) <= 1e-9 * scale
    block = solver._precision_summary()
    assert block["solve_dtype"] == "f32"
    assert block["refine_sweeps"] == 2
    assert block["achieved_residual"] <= 1e-8
    solve_cfg(solve_dtype="f32", sweeps="3")
    x_deep, _ = rb_trajectory(d3.RK222)
    assert np.max(np.abs(x_deep - x_f64)) <= 1e-10 * scale


def test_ladder_f32_composes_with_spike(solve_cfg):
    """Ladder x composition: the spike chunk operators cast low too,
    the refined trajectory stays in the 1e-10 class, and the whole
    restructured+laddered program compiles once — zero post-warmup
    retraces across repeated step_many blocks (composition resolved at
    build, never read in traced code)."""
    solve_cfg()
    x_f64 = seq_baseline(d3.RK222)
    solve_cfg(composition="spike", solve_dtype="f32", sweeps="3")
    retrace_mod.sentinel.reset()
    x_new, solver = rb_trajectory(d3.RK222)
    aux = solver.timestepper._lhs_aux[0]
    assert aux["fsub"]["spikeF"]["Y"].dtype == np.float32
    scale = np.max(np.abs(x_f64))
    assert np.max(np.abs(x_new - x_f64)) <= 1e-10 * scale
    solver.step_many(4, 0.01)
    solver.step_many(4, 0.01)
    assert retrace_mod.sentinel.post_arm_retraces == 0


def test_ladder_f32_dense(solve_cfg):
    """Dense arm of the ladder: DenseOps routes through the refined
    low-dtype inverse (matsolvers.refined_ladder) and holds 1e-10."""
    solve_cfg()
    s_f64 = build_diffusion()
    for _ in range(10):
        s_f64.step(1e-3)
    solve_cfg(solve_dtype="f32")
    s_f32 = build_diffusion()
    assert issubclass(s_f32.ops.solver_cls, BatchedInverseRefined)
    assert s_f32.ops.solver_cls.iterations == 2
    for _ in range(10):
        s_f32.step(1e-3)
    scale = np.max(np.abs(np.asarray(s_f64.X)))
    assert np.max(np.abs(np.asarray(s_f32.X) - np.asarray(s_f64.X))) \
        <= 1e-10 * scale


def test_refined_matsolver_schedule_and_depth(solve_cfg):
    """The BatchedInverseRefined sweep count is config-driven (was a
    hardcoded class attribute), the refinement lowers as a fixed-length
    loop (no while — the DTP106-checkable shape), tolerance termination
    freezes converged systems, and residual() reports achieved
    accuracy."""
    solve_cfg(sweeps="5")
    cls = get_solver("batchedinverserefined")
    assert cls.iterations == 5
    rng = np.random.default_rng(3)
    A = jnp.asarray(rng.standard_normal((4, 6, 6)) + 6 * np.eye(6))
    b = jnp.asarray(rng.standard_normal((4, 6)))
    aux = cls.factor(A)
    x = cls.solve(aux, b)
    res = np.asarray(cls.residual(aux, np.asarray(x), b))
    assert res.shape == (4,) and res.max() < 1e-12
    lengths, whiles = scan_lengths(jax.make_jaxpr(cls.solve)(aux, b))
    assert whiles == 0 and max(lengths, default=0) <= 5
    # a saturated tolerance freezes every update: the masked fixed-trip
    # loop returns the unrefined first solve bitwise
    solve_cfg(sweeps="5", tol="1e9")
    frozen_cls = get_solver("batchedinverserefined")
    assert frozen_cls.tol == 1e9
    x_frozen = frozen_cls.solve(aux, b)
    x0 = jnp.einsum("gij,gj->gi", aux[1],
                    b.astype(np.float32)).astype(b.dtype)
    assert np.array_equal(np.asarray(x_frozen), np.asarray(x0))


# ------------------------------------------------ adjoint + fleet + mesh

def test_adjoint_fd_through_composition(solve_cfg):
    """DifferentiableIVP gradients FD-validate through the restructured
    solve: the custom_vjp funnel transposes the same associative-scan
    linear algebra (jax.vjp over the restructured _solve_impl). SPIKE's
    adjoint is pinned by the transpose dot-identity inside
    test_composition_matches_sequential_banded (same funnel, no second
    DifferentiableIVP build)."""
    composition = "ascan"
    solve_cfg(composition=composition)
    solver = build_rb(8, 32, matsolver="banded", timestepper=d3.RK222)
    assert solver.ops._composition == composition
    div = solver.differentiable(wrt=("initial_state",),
                                loss=lambda X: jnp.sum(X ** 2))
    n, dt = 6, 0.01
    X0 = np.asarray(solver.gather_fields()).copy()
    _, grads = div.value_and_grad(n, dt, initial_state=X0)
    g = np.asarray(grads["initial_state"])
    assert np.isfinite(g).all()
    v = np.random.default_rng(0).standard_normal(X0.shape)
    eps = 1e-6
    fd = (div.value(n, dt, initial_state=X0 + eps * v)
          - div.value(n, dt, initial_state=X0 - eps * v)) / (2 * eps)
    an = float(np.sum(g * v))
    assert abs(fd - an) <= 1e-5 * max(abs(fd), 1e-12)


def test_ensemble_vmap_composes_with_spike(solve_cfg):
    """EnsembleSolver vmaps the step bodies over the restructured ops
    (including the vmapped spike factorization): fleet members match
    their serial runs with the composition on."""
    solve_cfg(composition="spike")
    seeds = [21, 22]
    serial = []
    for seed in seeds:
        solver = build_rb(8, 32, matsolver="banded", timestepper=d3.RK222)
        solver.problem.variables[1].fill_random(
            "g", seed=seed, distribution="normal", scale=1e-3)
        solver.step_many(6, 0.01)
        serial.append(np.asarray(solver.X))
    solver = build_rb(8, 32, matsolver="banded", timestepper=d3.RK222)
    assert solver.ops._composition == "spike"
    ens = solver.ensemble(len(seeds), mesh=None)

    def member_init(i):
        solver.problem.variables[1].fill_random(
            "g", seed=seeds[i], distribution="normal", scale=1e-3)

    ens.init_members(member_init)
    ens.step_many(6, 0.01)
    for i in range(len(seeds)):
        err = np.max(np.abs(np.asarray(ens.X[i]) - serial[i]))
        assert err <= 1e-12, (i, err)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs >= 8 devices")
def test_2d_mesh_fleet_composes_with_ascan(solve_cfg):
    """The 2-D batch x pencil fleet steps through the restructured solve
    (manual batch shard_map over GSPMD-auto pencils) and matches the 1-D
    fleet at roundoff — the composition the north-star run uses. (The
    sequential composition's bitwise 2-D-vs-1-D claim lives in
    tests/test_distributed.py; the associative-scan combine is a tree
    reduction whose fp order GSPMD may legally re-associate across mesh
    layouts, so the contract here is the roundoff class, observed
    ~1e-17.)"""
    from jax.sharding import Mesh
    from dedalus_tpu.extras.bench_problems import build_tau_ivp
    solve_cfg(composition="ascan")
    states = {}
    for label, mesh in (
            ("1d", Mesh(np.array(jax.devices()[:2]), ("batch",))),
            ("2d", Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                        ("batch", "pencil")))):
        solver, u, x, z = build_tau_ivp(8, 32, matsolver="banded")
        assert solver.ops._composition == "ascan"
        fleet = solver.ensemble(2, mesh=mesh)

        def ics(i):
            u["g"] = np.sin(np.pi * z) * (1 + 0.1 * (i + 1)
                                          * np.cos(np.pi * x / 2))

        fleet.init_members(ics)
        fleet.step_many(6, 1e-3)
        states[label] = np.asarray(fleet.X).copy()
    scale = np.max(np.abs(states["1d"]))
    assert np.max(np.abs(states["1d"] - states["2d"])) <= 1e-13 * scale


# -------------------------------------------------- hygiene + key discipline

def test_solver_and_pool_keys_rekey(solve_cfg):
    """solver_key and pool_key re-key across compositions AND solve
    dtypes: pooled compiled programs can never alias across the plan."""
    from dedalus_tpu.tools import assembly_cache
    keys = []
    for kw in ({"composition": "sequential"}, {"composition": "ascan"},
               {"composition": "spike"}, {"solve_dtype": "f32"},
               {"composition": "spike", "spike_chunks": "3"}):
        solve_cfg(**kw)
        solver = build_diffusion()
        keys.append((assembly_cache.solver_key(solver, solver.matrices),
                     assembly_cache.pool_key(solver)))
    assert all(k[0] is not None and k[1] is not None for k in keys)
    assert len({k[0] for k in keys}) == len(keys)
    assert len({k[1] for k in keys}) == len(keys)


def test_config_validation(solve_cfg):
    """Unknown [fusion]/[precision] values raise ValueError (never
    silent auto) — every knob at the per-build resolve, and the resolve
    really runs at build time (one build-level probe); incompatible
    combinations fail loudly at ops construction."""
    for bad, match in ((dict(composition="logdepth"), "SOLVE_COMPOSITION"),
                       (dict(solve_dtype="f16"), "SOLVE_DTYPE"),
                       (dict(sweeps="-1"), "REFINE_SWEEPS"),
                       (dict(spike_chunks="1"), "SPIKE_CHUNKS"),
                       (dict(tol="many"), "REFINE_TOL"),
                       (dict(mmt="f8"), "MMT_DTYPE")):
        solve_cfg(**bad)
        with pytest.raises(ValueError, match=match):
            solvecomp.resolve_solve_plan()
    solve_cfg(composition="logdepth")
    with pytest.raises(ValueError, match="SOLVE_COMPOSITION"):
        build_diffusion()   # the resolve runs inside every solver build
    # composition without the fused operators it restructures
    solve_cfg(composition="ascan", fused_solve="off")
    with pytest.raises(ValueError, match="FUSED_SOLVE"):
        build_rb(8, 32, matsolver="banded")
    # the Pallas kernel covers the sequential substitution only
    solve_cfg(composition="spike", pallas="on")
    with pytest.raises(ValueError, match="PALLAS"):
        build_rb(8, 32, matsolver="banded")
