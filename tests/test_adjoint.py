"""
Differentiable solves (core/adjoint.py + libraries/pencilops adjoint
funnel): finite-difference validation of adjoint gradients through the
step loop (ICs, parameter fields, forcing; SBDF2 + RK222; diffusion and
KdV-Burgers), checkpoint-segment invariance, forward fidelity against
the stepping loop, the solve_transpose identity on both pencil-ops
kinds, linear-transpose round-trip of the transform chain, the
zero-retrace assertion on the compiled grad program, and the structured
health error for a NaN backward pass.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import dedalus_tpu.public as d3
from dedalus_tpu.tools import retrace as retrace_mod
from dedalus_tpu.tools.exceptions import SolverHealthError

RNG = np.random.default_rng(7)

_RB_CACHE = {}


def rb_solver(matsolver):
    """One shared RB 8x32 build per matsolver kind (these builds dominate
    this file's runtime; the tests using them are read-only on the
    solver: explicit initial_state everywhere, no stepping)."""
    if matsolver not in _RB_CACHE:
        import sys
        import pathlib
        sys.path.insert(0, str(pathlib.Path(__file__).parent))
        from test_banded import build_rb
        _RB_CACHE[matsolver] = build_rb(8, 32, matsolver=matsolver,
                                        timestepper=d3.RK222)
    return _RB_CACHE[matsolver]


def build_diffusion(scheme, size=64):
    """1-D forced heat IVP with a parameter field `a` and a forcing
    field `f` as distinct RHS operands (the three differentiable operand
    classes: IC / parameter / forcing)."""
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=size, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    a = dist.Field(name="a", bases=xb)
    f = dist.Field(name="f", bases=xb)
    problem = d3.IVP([u], namespace={"u": u, "a": a, "f": f, "lap": d3.lap})
    problem.add_equation("dt(u) - lap(u) = a*u + f")
    x = dist.local_grid(xb)
    u["g"] = np.sin(3 * x) + 0.2 * np.cos(x)
    a["g"] = 0.1 * np.cos(x)
    f["g"] = 0.05 * np.sin(2 * x)
    solver = problem.build_solver(scheme, warmup_iterations=2,
                                  enforce_real_cadence=0)
    return solver


def build_kdv(scheme, size=128):
    """KdV-Burgers (reference example): nonlinear RHS through the
    dealiased transform chain."""
    Lx = 10
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=size, bounds=(0, Lx), dealias=3 / 2)
    u = dist.Field(name="u", bases=xb)
    dx = lambda A: d3.Differentiate(A, xc)
    a, b = 1e-4, 2e-4
    problem = d3.IVP([u], namespace=locals())
    problem.add_equation("dt(u) - a*dx(dx(u)) - b*dx(dx(dx(u))) = - u*dx(u)")
    x = dist.local_grid(xb)
    n = 20
    u["g"] = np.log(1 + np.cosh(n) ** 2
                    / np.cosh(n * (x - 0.2 * Lx)) ** 2) / (2 * n)
    return problem.build_solver(scheme, warmup_iterations=2,
                                enforce_real_cadence=0)


def fd_directional(div, n, dt, base, v, eps, operand):
    """Central finite difference of the loss along direction v."""
    if operand == "initial_state":
        plus = div.value(n, dt, initial_state=base + eps * v)
        minus = div.value(n, dt, initial_state=base - eps * v)
    else:
        plus = div.value(n, dt, fields={operand: base + eps * v})
        minus = div.value(n, dt, fields={operand: base - eps * v})
    return (plus - minus) / (2 * eps)


# ------------------------------------------------- gradient validation

@pytest.mark.parametrize("scheme", ["SBDF2", "RK222"])
def test_diffusion_gradients_match_fd(scheme):
    """jax.grad of a scalar loss through >=100 steps matches central
    finite differences (rtol ~1e-5, f64) for initial-condition,
    parameter-field, and forcing operands (acceptance criteria)."""
    solver = build_diffusion(getattr(d3, scheme))
    div = solver.differentiable(
        wrt=("initial_state", "a", "f"),
        loss=lambda X: jnp.sum(X ** 2), checkpoint_segments=8)
    n, dt = 120, 1e-3
    X0 = np.asarray(solver.gather_fields()).copy()
    val, grads = div.value_and_grad(n, dt, initial_state=X0)
    assert np.isfinite(val)
    assert sorted(grads) == ["a", "f", "initial_state"]
    bases = {"initial_state": X0,
             "a": np.asarray(solver.eval_F.extra_fields[0].coeff_data()),
             "f": np.asarray(solver.eval_F.extra_fields[1].coeff_data())}
    for operand, g in grads.items():
        g = np.asarray(g)
        assert np.isfinite(g).all(), operand
        base = bases[operand]
        v = RNG.standard_normal(base.shape)
        fd = fd_directional(div, n, dt, base, v, 1e-6, operand)
        an = float(np.sum(g * v))
        assert fd == pytest.approx(an, rel=1e-5), (scheme, operand)


@pytest.mark.parametrize("scheme", ["SBDF2", "RK222"])
def test_kdv_burgers_ic_gradient_matches_fd(scheme):
    """Nonlinear dealiased RHS: IC gradient through >=100 KdV-Burgers
    steps matches finite differences."""
    solver = build_kdv(getattr(d3, scheme))
    div = solver.differentiable(
        wrt=("initial_state",), loss=lambda X: jnp.sum(X ** 2))
    n, dt = 100, 2e-3
    X0 = np.asarray(solver.gather_fields()).copy()
    val, grads = div.value_and_grad(n, dt, initial_state=X0)
    g = np.asarray(grads["initial_state"])
    assert np.isfinite(g).all()
    v = RNG.standard_normal(X0.shape)
    fd = fd_directional(div, n, dt, X0, v, 1e-6, "initial_state")
    an = float(np.sum(g * v))
    assert fd == pytest.approx(an, rel=1e-5), scheme


def test_banded_path_gradient_matches_fd():
    """The banded (blocked pivoted-LU + Woodbury) solve differentiates
    through the custom VJP: RB gradient vs finite differences."""
    solver = rb_solver("banded")
    assert solver.ops.kind == "banded"
    X0 = np.asarray(solver.gather_fields()).copy()
    div = solver.differentiable(
        wrt=("initial_state",), loss=lambda X: jnp.sum(X ** 2),
        checkpoint_segments=2)
    _, grads = div.value_and_grad(5, 0.01, initial_state=X0)
    g = np.asarray(grads["initial_state"])
    assert np.isfinite(g).all()
    v = RNG.standard_normal(X0.shape)
    fd = fd_directional(div, 5, 0.01, X0, v, 1e-6, "initial_state")
    assert fd == pytest.approx(float(np.sum(g * v)), rel=1e-5)


# ------------------------------------------- forward + segment identity

def test_forward_matches_step_loop():
    """The differentiable forward pass is bit-identical to n solver.step
    calls (multistep ramp included)."""
    for scheme in (d3.SBDF2, d3.RK222):
        ref = build_diffusion(scheme)
        for _ in range(9):
            ref.step(1e-3)
        div_solver = build_diffusion(scheme)
        div = div_solver.differentiable(
            wrt=("initial_state",), loss=lambda X: jnp.sum(X ** 2))
        _, XT = div.forward(9, 1e-3)
        assert np.array_equal(np.asarray(XT), np.asarray(ref.X)), \
            scheme.__name__


def test_checkpoint_segments_do_not_change_gradients():
    """Remat segmentation is a memory policy, not a numerics knob: K=1,
    K=4, and an n-indivisible K produce identical losses and gradients."""
    results = []
    for K in (1, 4, 7):
        solver = build_diffusion(d3.SBDF2)
        div = solver.differentiable(
            wrt=("initial_state",), loss=lambda X: jnp.sum(X ** 2),
            checkpoint_segments=K)
        val, grads = div.value_and_grad(30, 1e-3)
        results.append((val, np.asarray(grads["initial_state"])))
        assert div.summary()["checkpoint_segments"] == min(K, 28)
    v0, g0 = results[0]
    for val, g in results[1:]:
        assert val == pytest.approx(v0, rel=1e-14)
        np.testing.assert_allclose(g, g0, rtol=1e-12, atol=1e-14)


# --------------------------------------------------- adjoint solve unit

def test_solve_transpose_identity_dense_and_banded():
    """ops.solve_transpose solves A^T x = b against the forward
    factorization: <x, A y> == <b, y> for random b, y on both pencil-ops
    kinds (including the banded Woodbury pin correction)."""
    for ms in (None, "banded"):
        solver = rb_solver(ms)
        ops = solver.ops
        ts = solver.timestepper
        dt = 0.01
        aux = ts._factor(solver.M_mat, solver.L_mat,
                         jnp.asarray(dt, dtype=solver.real_dtype))[0]
        h = ts.uniq_H_diag[ts.stage_slot[0]]
        rng = np.random.default_rng(11)
        b = jnp.asarray(rng.standard_normal(solver.pencil_shape))
        y = jnp.asarray(rng.standard_normal(solver.pencil_shape))
        x = ops.solve_transpose(aux, b, mats=(solver.M_mat, solver.L_mat))
        Ay = ops.matvec(solver.M_mat, y) + dt * h * ops.matvec(
            solver.L_mat, y)
        lhs = float(jnp.sum(x * Ay))
        rhs = float(jnp.sum(b * y))
        assert lhs == pytest.approx(rhs, rel=1e-10), ops.kind


def test_transform_chain_linear_transposes():
    """The Chebyshev/Jacobi MMT + dealiasing chain round-trips under
    jax.linear_transpose: the dealiased projection P (coeff -> grid ->
    coeff) of a Fourier x Chebyshev state satisfies <P x, y> ==
    <x, P^T y>, and P^T traces without error — the property the adjoint
    step relies on."""
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=8, bounds=(0, 4), dealias=3 / 2)
    zb = d3.ChebyshevT(coords["z"], size=16, bounds=(0, 1), dealias=3 / 2)
    b = dist.Field(name="b", bases=(xb, zb))
    tau = dist.Field(name="tau", bases=xb)
    lift = lambda A: d3.Lift(A, zb.derivative_basis(1), -1)
    problem = d3.IVP([b, tau], namespace=locals())
    problem.add_equation("dt(b) - lap(b) + lift(tau) = 0")
    problem.add_equation("b(z=0) = 0")
    solver = problem.build_solver(d3.RK222, warmup_iterations=2,
                                  enforce_real_cadence=0)
    solver._ensure_project()
    project = solver._project_body
    rng = np.random.default_rng(13)
    x = jnp.asarray(rng.standard_normal(solver.pencil_shape))
    y = jnp.asarray(rng.standard_normal(solver.pencil_shape))
    Px = project(x)
    (PTy,) = jax.linear_transpose(project, x)(y)
    assert float(jnp.sum(Px * y)) == pytest.approx(
        float(jnp.sum(x * PTy)), rel=1e-10)


# ------------------------------------------------ hygiene + health

def test_grad_program_zero_post_warmup_retraces():
    """The compiled grad program traces once: repeated value_and_grad
    calls after the sentinel arms are retrace-free (the PR-3 lint/
    sentinel contract extended to the adjoint path)."""
    sentinel = retrace_mod.sentinel
    sentinel.reset()
    try:
        solver = build_diffusion(d3.SBDF2)
        div = solver.differentiable(
            wrt=("initial_state", "a"), loss=lambda X: jnp.sum(X ** 2),
            checkpoint_segments=4)
        div.value_and_grad(20, 1e-3)   # compile
        sentinel.arm()
        for _ in range(3):
            div.value_and_grad(20, 1e-3)
        assert sentinel.post_arm_retraces == 0
        record = div.flush_metrics()
        assert record["retraces_post_warmup"] == 0
        assert record["adjoint"]["grad_calls"] == 4
    finally:
        sentinel.reset()


def test_nan_backward_raises_structured_health_error():
    """A NaN produced in the loss/backward pass raises a
    SolverHealthError naming the adjoint phase (routed through
    HealthMonitor.check_values) instead of silently reaching an
    optimizer."""
    solver = build_diffusion(d3.SBDF2)
    div = solver.differentiable(
        wrt=("initial_state",),
        loss=lambda X: jnp.log(-jnp.sum(X ** 2)))   # log of negative: nan
    with pytest.raises(SolverHealthError) as excinfo:
        div.value_and_grad(10, 1e-3)
    assert "adjoint" in str(excinfo.value)
    # check_health=False opts out: the caller gets raw values
    val, grads = div.value_and_grad(10, 1e-3, check_health=False)
    assert np.isnan(val)


def test_wrt_validation_and_summary():
    solver = build_diffusion(d3.SBDF2)
    with pytest.raises(ValueError, match="wrt"):
        solver.differentiable(wrt=("nope",), loss=lambda X: jnp.sum(X))
    with pytest.raises(ValueError, match="loss"):
        solver.differentiable(wrt=("initial_state",))
    div = solver.differentiable(wrt=("parameters",),
                                loss=lambda X: jnp.sum(X ** 2))
    assert set(div.wrt) == {"a", "f"}
    div.value_and_grad(5, 1e-3)
    summary = div.summary()
    assert summary["grad_calls"] == 1
    assert summary["wrt"] == ["a", "f"]
