"""
Two-coupled-axis (Chebyshev x Chebyshev) structured solves
(reference: dedalus/core/subsystems.py:493-598 — arbitrary coupled sets
via sparse SuperLU; here the two coupled axes flatten into one banded
super-axis whose occupied diagonals stay sparse under kron structure,
solved by the same blocked windowed-pivoting LU as single-axis problems).
"""

import numpy as np
import pytest

import dedalus_tpu.public as d3
from dedalus_tpu.libraries.pencilops import BandedOps

# NOTE: a tau-less "u + dxx(u) = F" operator problem is NOT a usable test:
# the conversion diagonals decay like n^-2 while the strictly-upper D^2
# entries grow like n^3, so the triangular system's condition number is
# astronomical. All tests below use proper tau formulations.


def _build_poisson_rect(Nx, Nz, matsolver):
    """Rectangle Poisson with tau lines on both axes (the corner modes
    close through the lifted tau columns)."""
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.ChebyshevT(coords["x"], size=Nx, bounds=(0, 1))
    zb = d3.ChebyshevT(coords["z"], size=Nz, bounds=(0, 1))
    x, z = dist.local_grids(xb, zb)
    u = dist.Field(name="u", bases=(xb, zb))
    tx1 = dist.Field(name="tx1", bases=zb)
    tx2 = dist.Field(name="tx2", bases=zb)
    tz1 = dist.Field(name="tz1", bases=xb)
    tz2 = dist.Field(name="tz2", bases=xb)
    # exact solution vanishing on the boundary
    u_ex = np.sin(np.pi * x) * np.sin(np.pi * z) * np.exp(x)
    rhs = dist.Field(name="rhs", bases=(xb, zb))
    lap_ex = (np.exp(x) * np.sin(np.pi * z)
              * ((1 - np.pi ** 2) * np.sin(np.pi * x)
                 + 2 * np.pi * np.cos(np.pi * x))
              - np.pi ** 2 * np.sin(np.pi * x) * np.sin(np.pi * z)
              * np.exp(x))
    rhs["g"] = lap_ex
    liftx = lambda A, n: d3.Lift(A, xb.derivative_basis(2), n)
    liftz = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
    problem = d3.LBVP([u, tx1, tx2, tz1, tz2], namespace=locals())
    problem.add_equation("lap(u) + liftx(tx1,-1) + liftx(tx2,-2)"
                         " + liftz(tz1,-1) + liftz(tz2,-2) = rhs")
    problem.add_equation("u(x=0) = 0")
    problem.add_equation("u(x=1) = 0")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("u(z=1) = 0")
    solver = problem.build_solver(matsolver=matsolver)
    return solver, u, u_ex


def test_poisson_rectangle_dense():
    solver, u, u_ex = _build_poisson_rect(24, 24, "dense")
    solver.solve()
    assert np.abs(np.asarray(u["g"]) - u_ex).max() < 1e-8


def test_poisson_rectangle_banded_matches_dense():
    # large enough that the flattened band beats dense (q << S)
    solver_d, u_d, u_ex = _build_poisson_rect(48, 48, "dense")
    solver_d.solve()
    ref = np.asarray(u_d["g"]).copy()
    solver_b, u_b, _ = _build_poisson_rect(48, 48, "banded")
    assert isinstance(solver_b.ops, BandedOps), solver_b._banded_reason
    solver_b.solve()
    sol = np.asarray(u_b["g"])
    assert np.abs(sol - u_ex).max() < 1e-7
    assert np.abs(sol - ref).max() < 1e-8


def test_shell_theta_ncc_ivp_banded_matches_dense():
    """Well-posed 2-coupled-axis IVP: shell diffusion with a
    theta-dependent conductivity NCC (ell x r coupled pencils, the
    rotating-convection-class structure; no rectangle corner modes)."""
    def build(matsolver):
        coords = d3.SphericalCoordinates("phi", "theta", "r")
        dist = d3.Distributor(coords, dtype=np.float64)
        shell = d3.ShellBasis(coords, shape=(8, 40, 24), radii=(0.5, 1.5),
                              dtype=np.float64)
        phi, theta, r = dist.local_grids(shell)
        T = dist.Field(name="T", bases=shell)
        tau1 = dist.Field(name="tau1", bases=shell.outer_surface)
        tau2 = dist.Field(name="tau2", bases=shell.outer_surface)
        kap = dist.Field(name="kap", bases=shell.meridional_basis)
        kap["g"] = 1.0 + 0.4 * np.cos(theta) + 0.2 * r * np.cos(theta) ** 2
        lift_basis = shell.derivative_basis(1)
        lift = lambda A: d3.Lift(A, lift_basis, -1)
        rvec = dist.VectorField(coords, bases=shell.meridional_basis)
        rvec["g"][2] = np.broadcast_to(r, rvec["g"][2].shape)
        grad_T = d3.grad(T) + rvec * lift(tau1)
        problem = d3.IVP([T, tau1, tau2], namespace=locals())
        problem.add_equation(
            "dt(T) - div(kap*grad_T) + lift(tau2) = 0")
        problem.add_equation("T(r=0.5) = 0")
        problem.add_equation("T(r=1.5) = 0")
        solver = problem.build_solver(d3.SBDF2, matsolver=matsolver)
        T["g"] = (np.sin(np.pi * (r - 0.5))
                  * (1 + 0.3 * np.cos(theta)
                     + 0.2 * np.sin(theta) * np.cos(phi)))
        return solver, T

    s_d, T_d = build("dense")
    for _ in range(5):
        s_d.step(2e-3)
    ref = np.asarray(T_d["g"]).copy()
    assert np.isfinite(ref).all()
    s_b, T_b = build("banded")
    assert isinstance(s_b.ops, BandedOps), s_b._banded_reason
    for _ in range(5):
        s_b.step(2e-3)
    sol = np.asarray(T_b["g"])
    assert np.isfinite(sol).all()
    assert np.abs(sol - ref).max() < 1e-11 * max(np.abs(ref).max(), 1.0)


def test_poisson_rectangle_banded_at_scale():
    """128^2 two-Chebyshev Poisson: the AUTO path must pick the banded
    representation (dense would be (G,S,S) ~ 2.2 GB) and solve to
    spectral accuracy — the memory-order-below-dense demonstration."""
    from dedalus_tpu.tools.config import config
    old = config["linear algebra"].get("BANDED_MAX_DIAGS", "384")
    config["linear algebra"]["BANDED_MAX_DIAGS"] = "768"
    try:
        solver, u, u_ex = _build_poisson_rect(128, 128, "auto")
    finally:
        config["linear algebra"]["BANDED_MAX_DIAGS"] = old
    assert isinstance(solver.ops, BandedOps), solver._banded_reason
    st = solver.structure
    band_bytes = sum(v["bands"].nbytes for v in solver._matrices.values())
    dense_bytes = 1 * st.S * st.S * 8
    assert band_bytes < dense_bytes / 20
    solver.solve()
    assert np.abs(np.asarray(u["g"]) - u_ex).max() < 1e-8


@pytest.mark.parametrize("dtype", [np.float64, np.float32])
def test_shell_coriolis_ivp_banded_matches_dense(dtype):
    """Coriolis-dominant regime (1/Ekman >> radial operator magnitudes):
    the alignment must stay on the radial principal regardless of entry
    magnitudes (regression: a magnitude-gated matching aligned on the
    1/Ekman-scaled dl=+-1 Coriolis couplings and diverged). The f32
    variant additionally locks in the dtype-aware NCC cutoffs + row-
    relative band detection (f32 data noise must not widen the band or
    force the dense path)."""
    def build(matsolver):
        coords = d3.SphericalCoordinates("phi", "theta", "r")
        dist = d3.Distributor(coords, dtype=dtype)
        shell = d3.ShellBasis(coords, shape=(8, 40, 16), radii=(0.35, 1.0),
                              dtype=dtype)
        sphere = shell.outer_surface
        phi, theta, r = dist.local_grids(shell)
        u = dist.VectorField(coords, name="u", bases=shell)
        p = dist.Field(name="p", bases=shell)
        tau_u1 = dist.VectorField(coords, name="tau_u1", bases=sphere)
        tau_u2 = dist.VectorField(coords, name="tau_u2", bases=sphere)
        tau_p = dist.Field(name="tau_p")
        Ekman = 1e-3
        rvec = dist.VectorField(coords, name="rvec",
                                bases=shell.meridional_basis)
        rvec["g"][2] = np.broadcast_to(r, rvec["g"][2].shape)
        ez = dist.VectorField(coords, name="ez",
                              bases=shell.meridional_basis)
        ez["g"][1] = -np.sin(theta)
        ez["g"][2] = np.cos(theta)
        lift_basis = shell.derivative_basis(1)
        lift = lambda A: d3.Lift(A, lift_basis, -1)
        grad_u = d3.grad(u) + rvec * lift(tau_u1)
        problem = d3.IVP([p, u, tau_u1, tau_u2, tau_p], namespace=locals())
        problem.add_equation("trace(grad_u) + tau_p = 0")
        problem.add_equation(
            "dt(u) + (1/Ekman)*cross(ez, u) + grad(p) - div(grad_u)"
            " + lift(tau_u2) = 0")
        problem.add_equation("u(r=0.35) = 0")
        problem.add_equation("u(r=1.0) = 0")
        problem.add_equation("integ(p) = 0")
        solver = problem.build_solver(d3.RK222, matsolver=matsolver)
        u.fill_random("g", seed=11, scale=1e-3)
        return solver, u

    s_d, u_d = build("dense")
    for _ in range(4):
        s_d.step(1e-4)
    ref = np.asarray(u_d["g"]).copy()
    assert np.isfinite(ref).all()
    s_b, u_b = build("banded")
    assert isinstance(s_b.ops, BandedOps), s_b._banded_reason
    for _ in range(4):
        s_b.step(1e-4)
    sol = np.asarray(u_b["g"])
    assert np.isfinite(sol).all()
    # f64 pins representation agreement; the f32 bound only guards
    # against gross blowup — at 1/Ekman = 1e3 the Coriolis-scaled system
    # amplifies f32 assembly roundoff, and the partial-batched assembly's
    # summation order legitimately moves the error with thread count and
    # reduction order (measured 2.0e-4 per-group vs 3.5e-4
    # partial-batched originally, 7.6e-4 on the round-13 2-core host AT
    # UNMODIFIED HEAD — the old 5e-4 bar sat inside the environmental
    # band; f64 5.7e-13)
    rtol = 1e-10 if dtype == np.float64 else 2e-3
    assert np.abs(sol - ref).max() < rtol * max(np.abs(ref).max(), 1.0)


def test_matrix_coupling_forced_disk():
    """Reference-parity matrix_coupling kwarg: the disk Poisson solved
    with a FORCED azimuthal coupling (one flattened (m x r) pencil)
    matches the separable per-m solve (reference: tests parametrize
    azimuth_coupling on polar LBVPs)."""
    def build(**kw):
        coords = d3.PolarCoordinates("phi", "r")
        dist = d3.Distributor(coords, dtype=np.float64)
        disk = d3.DiskBasis(coords, shape=(8, 16), dtype=np.float64,
                            radius=1.0)
        phi, r = dist.local_grids(disk)
        u = dist.Field(name="u", bases=disk)
        tau = dist.Field(name="tau", bases=disk.edge)
        rhs = dist.Field(name="rhs", bases=disk)
        x = r * np.cos(phi)
        y = r * np.sin(phi)
        u_ex = (1 - r ** 2) * (1 + 0.5 * x + 0.3 * y)
        # lap((1-r^2) v) = -4 v + 2 grad(1-r^2).grad(v), v harmonic
        rhs["g"] = -4.0 - 4.0 * x - 2.4 * y
        lift = lambda A: d3.Lift(A, disk, -1)
        problem = d3.LBVP([u, tau], namespace=locals())
        problem.add_equation("lap(u) + lift(tau) = rhs")
        problem.add_equation("u(r=1) = 0")
        solver = problem.build_solver(**kw)
        return solver, u, u_ex

    s_sep, u_sep, u_ex = build()
    s_sep.solve()
    assert np.abs(np.asarray(u_sep["g"]) - u_ex).max() < 1e-10
    s_cpl, u_cpl, _ = build(matrix_coupling=[True, True])
    assert s_cpl.pencil_shape[0] == 1  # one flattened pencil
    s_cpl.solve()
    assert np.abs(np.asarray(u_cpl["g"])
                  - np.asarray(u_sep["g"])).max() < 1e-11
