"""
Test configuration: force the CPU backend (the axon TPU platform is forced
via env in this environment and rejects complex128) and expose a virtual
8-device mesh for sharding tests.
"""

import os

# Must be set before the backend initializes.
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _leak_sentinel(request):
    """Opt-in tracer-leak sentinel: tests marked `leak_check` run under
    jax.checking_leaks(), so a jitted path that captures tracers in
    module/global state (the classic lifted_jit-registry hazard class)
    fails the marked test instead of surfacing as a cryptic error in some
    later trace. Opt-in because the check globally disables trace caching
    (every call retraces) — too slow for the whole suite."""
    if request.node.get_closest_marker("leak_check") is None:
        yield
        return
    with jax.checking_leaks():
        yield
